"""Fleet observability plane: cross-host trace merge, sync-point skew
attribution, coordinator rollup (DESIGN.md §6.5).

The telemetry spine (spans/registry/goodput) and the live plane stop at
the process boundary: per-host files, per-host registries, a per-host
``/statz``.  Every pod-scale question is a FLEET question — "which host
gated this step", "what did its lateness cost", "is the fleet's goodput
acceptable" (the MLPerf-pods and pjit/TPUv4 papers both attribute
pod-scale step time to per-host skew at collective boundaries).  This
module is that layer, in three coordinated pieces:

**1. Cross-host trace merge with clock alignment.**  Every host emits a
``fleet/sync`` span per fleet-wide barrier (the trainer's logging-sync
allgather and checkpoint boundaries; barrier id = ``<kind>_<step>``):
``ts`` is the host's barrier ARRIVAL on its own wall clock, ``dur`` the
time it waited inside the barrier, so ``ts + dur`` is the barrier
RELEASE.  A real collective releases every host at (nearly) the same
true instant — the last arrival frees everyone — so release-stamp deltas
between hosts are pure clock offset plus network jitter, and the median
over many barriers (:func:`estimate_offsets`) recovers each host's
offset without any clock protocol.  ``report --export-trace`` re-bases
every host's span stream by its offset (``spans.export_chrome_trace``'s
``offsets_s``) and emits one Perfetto track-group per host, so a fleet
step reads as a single picture.

**2. Sync-point skew attribution.**  At every barrier the per-host
arrival deltas are ranked: the LAST arrival is the host that gated the
fleet, its margin over the second-latest is the wall-clock it cost
everyone, and the spread is the barrier's skew.  Booked live as
``fleet/skew_ms`` / ``fleet/blame_p*`` / ``fleet/lateness_s_p*`` and
judged post-hoc by :func:`attribute`, which also fits each host's
arrival DRIFT (ms of lateness per step — a persistent straggler's
injected delay reads straight off the slope).  In a real multi-host job
the arrival stamps ride the SAME allgather that already powers
``flag_stragglers`` (no new collectives); without cross-process
collectives (the CPU-sim rig) the file mesh below carries them.

**3. Coordinator fleet rollup.**  Each host publishes its registry
snapshot + goodput books into a fleet mesh (``--fleet_dir``: a shared
directory, or ``tcp://host:port`` — the same dual transport as
``resilience/health.py``); the coordinator folds them into ONE
consistent fleet cut (per-host docs are written atomically, aggregates
computed from one read pass), served live at ``/fleetz`` on the admin
endpoint and written to ``<logdir>/fleet.json`` for ``report --fleet``,
whose gates (``--max_skew_ms``, ``--min_fleet_goodput``,
``--max_blame_frac``) ride the ordinary ``check_gates``.

Jax-free, stdlib + numpy only: importable from the report CLI and unit
tests without a backend.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from dtf_tpu.resilience.health import atomic_write
from dtf_tpu.telemetry import spans as _spans

#: Rollup file name (written into the fleet logdir by the coordinator).
FLEET_FILE = "fleet.json"
#: Live skew samples kept for the /fleetz distribution (bounded).
_SKEW_KEEP = 1024
#: Sync events kept per host by the TCP mesh server (bounded).
_TCP_SYNC_KEEP = 1024
#: Live-plane bounds: booked-barrier ids remembered for dedup, pending
#: (incomplete) barriers held for a lagging host, and release-delta
#: samples per host feeding the live clock-offset estimate.  All sized
#: far above any real window so a week-long run stays O(1) per sync
#: point without ever forgetting a barrier it could still book.
_BOOKED_KEEP = 4096
_PENDING_KEEP = 1024
_DELTA_KEEP = 64


def barrier_id(kind: str, step: int) -> str:
    """``("log", 40) -> "log_00000040"`` — zero-padded so lexical order
    within a kind is step order."""
    return f"{kind}_{int(step):08d}"


def split_unix(t: float) -> "tuple[float, float]":
    """Epoch seconds as a float32-survivable (hi, lo) pair.

    The trainer rides arrival stamps on the straggler allgather, but
    jax's default x64-off config canonicalizes any f64 payload to f32
    on the multi-process device_put path — and f32 spacing at epoch
    ~1.7e9 s is 128-256 s, which would quantize every host's stamp to
    the same value and fabricate the blame.  The classic double-single
    split survives: hi carries the f32-rounded seconds (identical
    rounding on every host is irrelevant — each host rounds its OWN
    stamp), lo the f64 remainder (|lo| <= 256 s, so its f32 resolution
    is ~15 µs); :func:`merge_unix` reconstructs to microsecond-level
    precision.  Pinned by a round-trip test at current epoch."""
    hi = float(np.float32(t))
    lo = float(np.float32(t - hi))
    return hi, lo


def merge_unix(hi: float, lo: float) -> float:
    """Reconstruct :func:`split_unix`'s pair (after an f32 wire)."""
    return float(np.float64(hi) + np.float64(lo))


# ---------------------------------------------------------------------------
# Pure attribution math (shared by the live plane and report --fleet)
# ---------------------------------------------------------------------------


def sync_events(records: List[dict]) -> List[dict]:
    """``fleet/sync`` span records out of an already-parsed span stream,
    as flat events: {pid, barrier, kind, step, arrive_s, wait_s}."""
    out = []
    for rec in records:
        if rec.get("name") != "fleet/sync" or rec.get("ph") != "X":
            continue
        args = rec.get("args", {})
        if "barrier" not in args:
            continue
        out.append({
            "pid": int(args.get("host", rec.get("pid", 0))),
            "barrier": args["barrier"],
            "kind": args.get("kind", ""),
            "step": int(args.get("step", 0)),
            "arrive_s": float(rec.get("ts", 0.0)) / 1e6,
            "wait_s": float(rec.get("dur", 0.0)) / 1e6,
        })
    return out


def estimate_offsets(events: List[dict],
                     reference: Optional[int] = None) -> Dict[int, float]:
    """Per-host clock offsets (seconds, relative to ``reference`` — the
    lowest pid by default) from shared barrier RELEASE stamps.

    Only release-bearing events (``wait_s > 0``, i.e. the host measurably
    waited inside a real barrier) feed the estimate: a collective's
    release is simultaneous across hosts in true time, so
    ``release_i - release_ref`` per shared barrier is that host's clock
    offset plus jitter, and the median over barriers suppresses the
    jitter.  Arrival stamps must NOT be used — arrivals differ by real
    skew (that is the signal :func:`attribute` measures), and folding
    them into the offset would cancel a persistent straggler's lateness.
    A host with no release-bearing events shares no estimable clock edge
    and gets offset 0.0 (correct on a single machine, flagged in the
    report by ``offset_estimated=False``)."""
    pids = sorted({e["pid"] for e in events})
    if not pids:
        return {}
    releases: Dict[str, Dict[int, float]] = {}
    for e in events:
        if e["wait_s"] > 0:
            releases.setdefault(e["barrier"], {})[e["pid"]] = (
                e["arrive_s"] + e["wait_s"])
    ref = pids[0] if reference is None else reference
    offsets = {ref: 0.0}
    for p in pids:
        if p == ref:
            continue
        deltas = [rel[p] - rel[ref] for rel in releases.values()
                  if p in rel and ref in rel]
        offsets[p] = float(np.median(deltas)) if deltas else 0.0
    return offsets


def _rank_arrivals(arrivals: Dict[int, float]):
    """``(last_pid, skew_s, margin_s)`` for one barrier's corrected
    arrivals: the LAST host gated the fleet; its margin over the
    second-latest is the wall-clock its lateness cost every other host
    (the fleet critical-path contribution)."""
    srt = sorted(arrivals.items(), key=lambda kv: (kv[1], kv[0]))
    last_pid, last_t = srt[-1]
    return last_pid, last_t - srt[0][1], last_t - srt[-2][1]


def attribute(events: List[dict],
              offsets: Optional[Dict[int, float]] = None) -> Optional[dict]:
    """Post-hoc sync-point skew attribution over ``fleet/sync`` events.

    Arrivals are corrected by ``offsets`` (see :func:`estimate_offsets`)
    before ranking, so cross-host clock offset never masquerades as — or
    masks — real skew.  Returns None when no barrier saw >= 2 hosts.

    Per host, besides blame counts and accumulated cost ("lateness"),
    the DRIFT is fitted: each host's arrival lateness relative to the
    earliest arrival of the same barrier, regressed against the step —
    a persistent per-step straggler shows its injected delay as the
    slope (ms/step), which is the measurement the sharding planner's
    A/B and the chaos tests key on.

    Cost accounting distinguishes the two barrier shapes.  A RESYNCING
    barrier (some host measurably waited inside it — a real collective)
    realigns the fleet, so the last host's margin over the second-latest
    is wall-clock paid afresh every window and sums directly.  An
    OBSERVATIONAL barrier (file-mesh marks, nobody waits) carries the
    straggler's ACCUMULATED lag, so only the INCREMENT of its relative
    lateness since the previous barrier is new cost — summing raw
    margins there would count the same lag once per barrier."""
    offsets = offsets or {}
    by_barrier: Dict[str, Dict[int, dict]] = {}
    meta: Dict[str, tuple] = {}
    for e in events:
        by_barrier.setdefault(e["barrier"], {}).setdefault(e["pid"], e)
        meta[e["barrier"]] = (e["step"], e["kind"])
    pids = sorted({e["pid"] for e in events})
    rows: List[dict] = []
    blame: Dict[int, int] = {p: 0 for p in pids}
    lateness: Dict[int, float] = {p: 0.0 for p in pids}
    rel_by_pid: Dict[int, List[tuple]] = {p: [] for p in pids}
    prev_rel: Dict[int, float] = {}
    t_min, t_max = float("inf"), float("-inf")
    for b in sorted(by_barrier, key=lambda b: (meta[b], b)):
        evs = by_barrier[b]
        if len(evs) < 2:
            continue
        arr = {p: ev["arrive_s"] - offsets.get(p, 0.0)
               for p, ev in evs.items()}
        last, skew, margin = _rank_arrivals(arr)
        resync = any(ev.get("wait_s", 0.0) > 0 for ev in evs.values())
        first_t = min(arr.values())
        cost = (margin if resync
                else max(arr[last] - first_t - prev_rel.get(last, 0.0),
                         0.0))
        blame[last] += 1
        lateness[last] += cost
        for p, t in arr.items():
            rel_by_pid[p].append((meta[b][0], t - first_t))
            prev_rel[p] = 0.0 if resync else t - first_t
        rows.append({"barrier": b, "step": meta[b][0], "kind": meta[b][1],
                     "hosts": len(arr), "last": last, "resync": resync,
                     "skew_ms": skew * 1e3, "margin_ms": margin * 1e3,
                     "cost_ms": cost * 1e3})
        t_min = min(t_min, first_t)
        t_max = max(t_max, max(arr.values()))
    if not rows:
        return None
    n = len(rows)
    skews = sorted(r["skew_ms"] for r in rows)
    window = t_max - t_min
    per_host = {}
    for p in pids:
        pts = rel_by_pid[p]
        drift = None
        steps = sorted({s for s, _ in pts})
        if len(steps) >= 2:
            xs = np.asarray([s for s, _ in pts], np.float64)
            ys = np.asarray([r for _, r in pts], np.float64)
            drift = float(np.polyfit(xs, ys, 1)[0]) * 1e3
        per_host[p] = {
            "last_arrivals": blame[p],
            "blame_frac": round(blame[p] / n, 6),
            "lateness_s": round(lateness[p], 6),
            "cost_pct": (round(lateness[p] / window * 100.0, 4)
                         if window > 0 else None),
            "drift_ms_per_step": (None if drift is None
                                  else round(drift, 4)),
        }
    return {
        "barriers": n,
        "hosts": pids,
        "skew_ms_p50": round(skews[n // 2], 4),
        "skew_ms_mean": round(sum(skews) / n, 4),
        "skew_ms_max": round(skews[-1], 4),
        "window_s": round(window, 6) if window > 0 else 0.0,
        "per_host": {str(p): d for p, d in per_host.items()},
        "recent_barriers": rows[-16:],
    }


def fleet_report(records: Optional[List[dict]] = None,
                 rollup_doc: Optional[dict] = None) -> Optional[dict]:
    """The report CLI's ``fleet`` section: span-based, offset-corrected
    attribution (the post-hoc truth) plus the coordinator rollup's fleet
    goodput cut.  None when neither source has fleet data.

    When the span streams are NOT co-located (node-local logdirs, or
    the tcp:// mesh — only the judged logdir's own spans are visible),
    the coordinator's LIVE attribution persisted in ``fleet.json``
    stands in, so the skew/blame gates still judge real measurements
    instead of failing on absence; ``attribution_source`` names which
    fed the section."""
    out: dict = {}
    if records:
        events = sync_events(records)
        if events:
            offsets = estimate_offsets(events)
            release_bearing = {e["pid"] for e in events if e["wait_s"] > 0}
            out["hosts"] = sorted({e["pid"] for e in events})
            out["offsets_s"] = {str(p): round(o, 6)
                                for p, o in sorted(offsets.items())}
            out["offset_estimated"] = {
                str(p): p in release_bearing or p == min(offsets, default=0)
                for p in sorted(offsets)}
            att = attribute(events, offsets)
            if att:
                out["attribution"] = att
                out["attribution_source"] = "spans"
    if rollup_doc:
        out["rollup"] = {
            "nproc": rollup_doc.get("nproc"),
            "written_unix": rollup_doc.get("written_unix"),
            "hosts_reporting": sorted(rollup_doc.get("hosts", {})),
            "goodput": rollup_doc.get("goodput"),
        }
        live = rollup_doc.get("attribution") or {}
        if "attribution" not in out and live.get("barriers"):
            blame = {p: int(c) for p, c in (live.get("blame") or {}).items()}
            lateness = live.get("lateness_s") or {}
            n = live["barriers"]
            hosts = sorted(set(blame) | set(lateness), key=str)
            out["attribution"] = {
                "barriers": n,
                "hosts": hosts,
                "skew_ms_p50": live.get("skew_ms_p50"),
                "skew_ms_mean": None,
                "skew_ms_max": live.get("skew_ms_max"),
                "window_s": None,
                "per_host": {
                    str(p): {
                        "last_arrivals": blame.get(str(p), blame.get(p, 0)),
                        "blame_frac": round(
                            blame.get(str(p), blame.get(p, 0)) / n, 6),
                        "lateness_s": lateness.get(
                            str(p), lateness.get(p, 0.0)),
                        "cost_pct": None,
                        "drift_ms_per_step": None,
                    } for p in hosts},
            }
            out["attribution_source"] = "rollup_live"
    return out or None


# ---------------------------------------------------------------------------
# Fleet mesh transports (same dual shape as resilience/health.py)
# ---------------------------------------------------------------------------


class FileFleetMesh:
    """Shared-directory transport: per-host sync streams as append-only
    JSONL (single writer per file; readers drop a torn tail), per-host
    book snapshots as atomically-replaced JSON docs (a reader can never
    observe a torn per-host snapshot), plus ready-markers for the
    startup rendezvous the 2-process rig uses."""

    observes_peers = True

    def __init__(self, directory: str, process: int):
        self.directory = directory
        self.process = process
        os.makedirs(directory, exist_ok=True)
        self._sync_path = os.path.join(directory,
                                       f"fleet_sync_p{process}.jsonl")
        # drain cursors: path -> byte offset.  The coordinator drains
        # the sync streams at every sync point of a potentially
        # week-long run; re-parsing whole files every poll would be
        # O(run length) per poll, and RETAINING every parsed event
        # would grow without bound — drain_syncs() parses only bytes
        # past the cursor and hands the events to the caller (the
        # plane's bounded pending-barrier ledger) without keeping them.
        self._cursors: Dict[str, int] = {}

    def append_sync(self, event: dict) -> None:
        with open(self._sync_path, "a") as f:
            f.write(json.dumps(event, separators=(",", ":")) + "\n")

    def publish_host(self, doc: dict) -> None:
        atomic_write(os.path.join(self.directory,
                                  f"host_{self.process}.json"),
                     json.dumps(doc, sort_keys=True))

    def mark_ready(self) -> None:
        atomic_write(os.path.join(self.directory,
                                  f"ready_{self.process}"), "1")

    def ready_count(self) -> int:
        return sum(1 for name in os.listdir(self.directory)
                   if name.startswith("ready_"))

    def _sync_files(self):
        for name in sorted(os.listdir(self.directory)):
            if not (name.startswith("fleet_sync_p")
                    and name.endswith(".jsonl")):
                continue
            try:
                pid = int(name[len("fleet_sync_p"):-len(".jsonl")])
            except ValueError:
                continue
            yield pid, os.path.join(self.directory, name)

    def drain_syncs(self) -> Dict[int, List[dict]]:
        """NEW sync events per host since the last drain — nothing is
        retained here.  Only COMPLETE lines are consumed: a partial
        tail (a writer mid-append) stays for the next poll, and one a
        dead writer left behind is dropped forever — the same torn-tail
        rule as the span readers."""
        out: Dict[int, List[dict]] = {}
        for pid, path in self._sync_files():
            offset = self._cursors.get(path, 0)
            try:
                if os.path.getsize(path) <= offset:
                    continue
                with open(path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read()
            except OSError:
                continue
            end = chunk.rfind(b"\n") + 1
            events = []
            for line in chunk[:end].splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue
            self._cursors[path] = offset + end
            if events:
                out[pid] = events
        return out

    def read_syncs(self) -> Dict[int, List[dict]]:
        """FULL per-host sync streams (a fresh whole-file parse — the
        debug/test view; the coordinator's hot path is
        :meth:`drain_syncs`)."""
        out: Dict[int, List[dict]] = {}
        for pid, path in self._sync_files():
            events = []
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            events.append(json.loads(line))
                        except ValueError:
                            continue       # torn tail from a hard kill
            except OSError:
                continue
            out[pid] = events
        return out

    def read_hosts(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for name in sorted(os.listdir(self.directory)):
            if not (name.startswith("host_") and name.endswith(".json")):
                continue
            try:
                pid = int(name[len("host_"):-len(".json")])
                with open(os.path.join(self.directory, name)) as f:
                    out[pid] = json.load(f)
            except (OSError, ValueError):
                continue          # mid-replace or foreign file: skip
        return out

    def close(self) -> None:
        pass


class TcpFleetServer:
    """Coordinator-side fleet sink for meshes with no shared filesystem
    (same line-protocol shape as health's TcpHeartbeatServer):

        sync <proc> <json>     ->  "ok"
        host <proc> <json>     ->  "ok"
        ready <proc>           ->  "ok <count>"
        snapshot               ->  one JSON line {hosts, syncs-per-host}
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._sock.settimeout(0.25)
        self.address = self._sock.getsockname()
        self._lock = threading.Lock()
        self._syncs: Dict[int, deque] = {}
        self._fresh: deque = deque(maxlen=_TCP_SYNC_KEEP * 4)
        self._hosts: Dict[int, dict] = {}
        self._ready: set = set()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="dtf_tpu-fleet-server")
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                with conn:
                    conn.settimeout(2.0)
                    line = conn.makefile("r").readline().strip()
                    try:
                        reply = self._handle(line)
                    except Exception as exc:
                        # Same rule as the beat sink: a malformed request
                        # must never kill the serve thread.
                        reply = f"err {type(exc).__name__}"
                    conn.sendall((reply + "\n").encode())
            except OSError:
                continue

    def _handle(self, line: str) -> str:
        parts = line.split(" ", 2)
        with self._lock:
            if parts[0] == "sync" and len(parts) == 3:
                pid = int(parts[1])
                event = json.loads(parts[2])
                self._syncs.setdefault(
                    pid, deque(maxlen=_TCP_SYNC_KEEP)).append(event)
                self._fresh.append((pid, event))
                return "ok"
            if parts[0] == "host" and len(parts) == 3:
                self._hosts[int(parts[1])] = json.loads(parts[2])
                return "ok"
            if parts[0] == "ready" and len(parts) >= 2:
                self._ready.add(int(parts[1]))
                return f"ok {len(self._ready)}"
            if parts[0] == "snapshot":
                return json.dumps({
                    "hosts": {str(k): v for k, v in self._hosts.items()},
                    "syncs": {str(k): list(v)
                              for k, v in self._syncs.items()}})
            return "err unknown command"

    # -- coordinator-local accessors ----------------------------------------

    def drain_syncs(self) -> Dict[int, List[dict]]:
        """NEW sync events since the last drain (bounded buffer — a
        coordinator that never drains cannot grow without bound)."""
        with self._lock:
            fresh = list(self._fresh)
            self._fresh.clear()
        out: Dict[int, List[dict]] = {}
        for pid, event in fresh:
            out.setdefault(pid, []).append(event)
        return out

    def read_syncs(self) -> Dict[int, List[dict]]:
        with self._lock:
            return {k: list(v) for k, v in self._syncs.items()}

    def read_hosts(self) -> Dict[int, dict]:
        with self._lock:
            return dict(self._hosts)

    def ready_count(self) -> int:
        with self._lock:
            return len(self._ready)

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2)


class TcpFleetMesh:
    """Client/coordinator facade over :class:`TcpFleetServer` — the
    coordinator hosts the sink in-process (full observer); other hosts
    push their sync events and book snapshots over TCP.  Sends are
    best-effort: fleet observability must never wedge training on a
    coordinator hiccup."""

    def __init__(self, address: str, process: int, is_coordinator: bool):
        host, _, port = address.partition(":")
        self.process = process
        self._server: Optional[TcpFleetServer] = None
        if is_coordinator:
            self._server = TcpFleetServer(host or "127.0.0.1", int(port))
            self._addr = self._server.address
        else:
            self._addr = (host or "127.0.0.1", int(port))
        self.observes_peers = is_coordinator
        self._ready_seen = 0

    def _request(self, line: str) -> Optional[str]:
        try:
            with socket.create_connection(self._addr, timeout=2.0) as conn:
                conn.sendall((line + "\n").encode())
                return conn.makefile("r").readline().strip()
        except OSError:
            return None

    def append_sync(self, event: dict) -> None:
        if self._server is not None:
            self._server._handle(
                f"sync {self.process} "
                + json.dumps(event, separators=(',', ':')))
        else:
            self._request(f"sync {self.process} "
                          + json.dumps(event, separators=(',', ':')))

    def drain_syncs(self) -> Dict[int, List[dict]]:
        return self._server.drain_syncs() if self._server else {}

    def publish_host(self, doc: dict) -> None:
        payload = json.dumps(doc, sort_keys=True)
        if self._server is not None:
            self._server._handle(f"host {self.process} {payload}")
        else:
            self._request(f"host {self.process} {payload}")

    def mark_ready(self) -> None:
        if self._server is not None:
            self._server._handle(f"ready {self.process}")
        else:
            reply = self._request(f"ready {self.process}")
            if reply and reply.startswith("ok "):
                self._ready_seen = int(reply.split()[1])

    def ready_count(self) -> int:
        if self._server is not None:
            return self._server.ready_count()
        # a client learns the count from its own (re-sent) ready line
        self.mark_ready()
        return self._ready_seen

    def read_syncs(self) -> Dict[int, List[dict]]:
        return self._server.read_syncs() if self._server else {}

    def read_hosts(self) -> Dict[int, dict]:
        return self._server.read_hosts() if self._server else {}

    def close(self) -> None:
        if self._server is not None:
            self._server.close()


def make_fleet_mesh(fleet_dir: str, process: int, is_coordinator: bool):
    """``tcp://host:port`` selects the socket transport (no shared FS);
    anything else is a shared rendezvous directory — the same rule as
    :func:`dtf_tpu.resilience.health.make_transport`."""
    if fleet_dir.startswith("tcp://"):
        return TcpFleetMesh(fleet_dir[len("tcp://"):], process,
                            is_coordinator)
    return FileFleetMesh(fleet_dir, process)


# ---------------------------------------------------------------------------
# The per-process plane
# ---------------------------------------------------------------------------


class FleetPlane:
    """One process's handle on the fleet plane (see module docstring).

    Every host: :meth:`note_sync` at each fleet barrier (emits the
    ``fleet/sync`` span and ships the arrival into the mesh),
    :meth:`publish_books` at telemetry sync points.  The coordinator
    additionally ingests completed barriers from the mesh into the live
    ``fleet/*`` instruments and serves/writes the rollup
    (:meth:`fleetz` / :meth:`write_rollup`).

    Thread-safety: the lock covers the live attribution state, so a
    concurrent ``/fleetz`` scrape reads one consistent cut of the skew
    books; per-host docs are atomic at the mesh layer."""

    def __init__(self, mesh, process: int, nproc: int,
                 spans_dir: Optional[str] = None):
        self.mesh = mesh
        self.process = int(process)
        self.nproc = int(nproc)
        self.spans_dir = spans_dir
        self.is_coordinator = self.process == 0
        self._lock = threading.RLock()
        # dedup ledger, bounded: a deque evicts the oldest remembered
        # barrier id once _BOOKED_KEEP are held (barriers arrive in
        # step order; a duplicate older than thousands of barriers
        # cannot occur)
        self._booked: set = set()
        self._booked_order: deque = deque()
        # incomplete barriers awaiting a lagging host's arrival:
        # barrier -> {"arr": {pid: (t, w)}, "step": int, "kind": str}
        self._pending: Dict[str, dict] = {}
        # live clock-offset estimate vs THIS coordinator, from release
        # stamps (t + w where w > 0) of shared barriers — the same
        # math as estimate_offsets, kept as a bounded running median so
        # the live blame ranking is offset-corrected too (a peer's NTP
        # drift must not masquerade as lateness on /fleetz).  Until a
        # release-bearing barrier has been seen for a peer its offset
        # is 0 — exact on a single machine, converging within a few
        # barriers on a real fleet; the post-hoc attribute() pass
        # remains the precise source.
        self._release_deltas: Dict[int, deque] = {}
        self._offsets: Dict[int, float] = {}
        self._barriers = 0
        self._skews_ms: deque = deque(maxlen=_SKEW_KEEP)
        self._blame: Dict[int, int] = {}
        self._lateness: Dict[int, float] = {}
        self._prev_rel: Dict[int, float] = {}
        self._rev = 0

    # -- feeding (every host) -----------------------------------------------

    def note_sync(self, kind: str, step: int, *,
                  arrival_unix: Optional[float] = None,
                  wait_s: float = 0.0) -> None:
        """This host reached fleet barrier ``<kind>_<step>``: emit the
        ``fleet/sync`` span (arrival = ``ts``, in-barrier wait = ``dur``)
        and ship the arrival into the mesh.  The coordinator then sweeps
        the mesh for newly-completed barriers."""
        t = time.time() if arrival_unix is None else float(arrival_unix)
        b = barrier_id(kind, step)
        _spans.get_tracer().emit_complete(
            "fleet/sync", t * 1e6, wait_s * 1e6,
            {"barrier": b, "kind": kind, "step": int(step),
             "host": self.process})
        try:
            self.mesh.append_sync({"barrier": b, "kind": kind,
                                   "step": int(step), "p": self.process,
                                   "t": t, "w": wait_s})
        except OSError:
            pass              # observability must never kill the job
        if self.is_coordinator:
            self._ingest_mesh()

    def note_barrier(self, kind: str, step: int,
                     arrivals: Dict[int, float]) -> None:
        """Direct booking from an in-band exchange: the trainer's
        straggler allgather already moves one float per host per sync
        point, and riding the arrival stamp on it costs no new
        collective — every host sees the whole fleet's arrivals the
        instant the barrier releases.  A collective RESYNCS the fleet,
        so the last host's margin is fresh cost (see
        :func:`attribute`)."""
        self._book(barrier_id(kind, step), arrivals, resync=True)

    def rendezvous(self, timeout_s: float = 120.0,
                   poll_s: float = 0.05) -> bool:
        """Startup alignment for the attribution rig: mark this host
        ready and wait (bounded) until every host has — so compile-time
        skew between hosts doesn't pollute the first barriers' blame.
        Observational only; a production fleet's real collectives align
        it anyway."""
        try:
            self.mesh.mark_ready()
        except OSError:
            return False
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                if self.mesh.ready_count() >= self.nproc:
                    return True
            except OSError:
                pass
            time.sleep(poll_s)
        return False

    def publish_books(self) -> None:
        """Publish THIS host's registry snapshot + goodput books into the
        mesh (atomically: a rollup can never read a torn per-host cut).
        ``rev``/``rev_echo`` bracket the doc so consistency is checkable
        from the outside."""
        from dtf_tpu.telemetry import goodput as _goodput
        from dtf_tpu.telemetry import registry as _registry
        with self._lock:
            self._rev += 1
            rev = self._rev
        doc = {"process": self.process, "nproc": self.nproc,
               "rev": rev, "written_unix": time.time(),
               "goodput": _goodput.get_tracker().snapshot(),
               "metrics": _registry.get_registry().snapshot(),
               "rev_echo": rev}
        try:
            self.mesh.publish_host(doc)
        except OSError:
            pass

    # -- coordinator --------------------------------------------------------

    def _ingest_mesh(self) -> None:
        """Drain NEW mesh events into the bounded pending-barrier
        ledger, fold release stamps into the live clock-offset
        estimate, and book each barrier every host has reached exactly
        once.  Work per sync point is O(new events + pending), not
        O(run length)."""
        try:
            drained = self.mesh.drain_syncs()
        except OSError:
            return
        with self._lock:
            for pid, events in drained.items():
                for e in events:
                    try:
                        b = e["barrier"]
                        p = int(e.get("p", pid))
                        t = float(e["t"])
                        w = float(e.get("w", 0.0))
                    except (KeyError, TypeError, ValueError):
                        continue
                    # NOTE: a barrier already booked in-band (the
                    # allgather ride) still accumulates here — its
                    # release stamps must reach the offset fold below;
                    # _book itself dedups.
                    doc = self._pending.setdefault(
                        b, {"arr": {}, "step": int(e.get("step", 0)),
                            "kind": e.get("kind", "")})
                    doc["arr"].setdefault(p, (t, w))
            ready = [b for b, doc in self._pending.items()
                     if len(doc["arr"]) >= self.nproc]
            ready.sort(key=lambda b: (self._pending[b]["step"],
                                      self._pending[b]["kind"], b))
            docs = [(b, self._pending.pop(b)) for b in ready]
            for _, doc in docs:
                self._fold_offsets_locked(doc["arr"])
            # prune: a dead host's incomplete barriers must not pile up
            if len(self._pending) > _PENDING_KEEP:
                for b in sorted(
                        self._pending,
                        key=lambda b: (self._pending[b]["step"],
                                       self._pending[b]["kind"], b)
                )[:len(self._pending) - _PENDING_KEEP]:
                    del self._pending[b]
        # book in step order so the incremental (no-resync) cost math
        # sees barriers in the order the fleet passed them
        for b, doc in docs:
            self._book(b, {p: t for p, (t, w) in doc["arr"].items()},
                       resync=any(w > 0 for _, w in doc["arr"].values()))

    def _fold_offsets_locked(self, arr: Dict[int, tuple]) -> None:
        """Fold one completed barrier's release stamps (t + w, w > 0)
        into the per-peer running clock-offset medians — the live twin
        of :func:`estimate_offsets`, referenced to THIS coordinator.
        Each barrier contributes each peer pair exactly once (folded
        only at booking time)."""
        ref = arr.get(self.process)
        if ref is None or ref[1] <= 0:
            return
        ref_release = ref[0] + ref[1]
        for p, (t, w) in arr.items():
            if p == self.process or w <= 0:
                continue
            dq = self._release_deltas.setdefault(
                p, deque(maxlen=_DELTA_KEEP))
            dq.append((t + w) - ref_release)
            self._offsets[p] = float(np.median(dq))

    def _book(self, b: str, arrivals: Dict[int, float],
              resync: bool) -> None:
        if len(arrivals) < 2:
            return
        with self._lock:
            if b in self._booked:
                return
            self._booked.add(b)
            self._booked_order.append(b)
            while len(self._booked_order) > _BOOKED_KEEP:
                self._booked.discard(self._booked_order.popleft())
            # rank offset-CORRECTED arrivals: a peer's clock offset
            # (already estimated from release stamps) must not read as
            # lateness — the /fleetz verdict and the post-hoc
            # attribute() apply the same rule
            arrivals = {p: t - self._offsets.get(p, 0.0)
                        for p, t in arrivals.items()}
            last, skew, margin = _rank_arrivals(arrivals)
            first_t = min(arrivals.values())
            # resync barriers pay the margin fresh each window; purely
            # observational marks carry accumulated lag, so only the
            # increment since the last barrier is new cost (same rule
            # as attribute())
            cost = (margin if resync
                    else max(arrivals[last] - first_t
                             - self._prev_rel.get(last, 0.0), 0.0))
            for p, t in arrivals.items():
                self._prev_rel[p] = 0.0 if resync else t - first_t
            self._barriers += 1
            self._skews_ms.append(skew * 1e3)
            self._blame[last] = self._blame.get(last, 0) + 1
            self._lateness[last] = self._lateness.get(last, 0.0) + cost
        from dtf_tpu.telemetry import registry as _registry
        reg = _registry.get_registry()
        with reg.locked():
            reg.counter("fleet/barriers_total").inc()
            reg.histogram("fleet/skew_ms").observe(skew * 1e3)
            reg.counter(f"fleet/blame_p{last}").inc()
            reg.gauge(f"fleet/lateness_s_p{last}").add(cost)
            reg.gauge("fleet/hosts").set(len(arrivals))
        # incident plane: per-barrier skew into the changepoint detector
        # — a straggler ONSET (not a steady straggler) fires here
        from dtf_tpu.telemetry import anomaly as _anomaly
        _anomaly.observe("fleet/skew_ms", skew * 1e3)

    def fleetz(self) -> dict:
        """ONE consistent fleet cut for ``/fleetz`` / ``fleet.json``:
        live skew books under the plane lock, per-host docs read
        atomically from the mesh, fleet goodput aggregated from exactly
        the docs in this payload (sum of productive over sum of wall —
        the fleet's joint fraction — plus the weakest host's own)."""
        try:
            hosts = self.mesh.read_hosts()
        except OSError:
            hosts = {}
        with self._lock:
            skews = sorted(self._skews_ms)
            n = len(skews)
            att = {
                "barriers": self._barriers,
                "skew_ms_p50": round(skews[n // 2], 4) if n else None,
                "skew_ms_max": round(skews[-1], 4) if n else None,
                "blame": {str(p): c
                          for p, c in sorted(self._blame.items())},
                "lateness_s": {str(p): round(s, 6)
                               for p, s in sorted(self._lateness.items())},
                # live clock-offset estimate vs this coordinator (0 =
                # none measured yet; arrivals are ranked corrected)
                "offsets_s": {str(p): round(o, 6) for p, o
                              in sorted(self._offsets.items())},
            }
        prod = wall = 0.0
        per_host = {}
        for p, doc in sorted(hosts.items()):
            g = doc.get("goodput", {}) if isinstance(doc, dict) else {}
            prod += float(g.get("productive_s", 0.0))
            wall += float(g.get("wall_s", 0.0))
            per_host[str(p)] = g.get("productive_fraction")
        fractions = [f for f in per_host.values() if f is not None]
        return {
            "written_unix": time.time(),
            "coordinator": self.process,
            "nproc": self.nproc,
            "hosts_reporting": sorted(hosts),
            "attribution": att,
            "goodput": {
                "productive_s_total": round(prod, 6),
                "wall_s_total": round(wall, 6),
                "productive_fraction": (round(prod / wall, 6)
                                        if wall > 0 else None),
                "per_host_fraction": per_host,
                "min_host_fraction": (min(fractions)
                                      if fractions else None),
            },
            "hosts": {str(p): doc for p, doc in sorted(hosts.items())},
        }

    def write_rollup(self) -> Optional[str]:
        """Coordinator: fold the current fleet cut into
        ``<spans_dir>/fleet.json`` (atomic) — the artifact ``report
        --fleet`` judges."""
        if not self.is_coordinator:
            return None
        out_dir = self.spans_dir or getattr(self.mesh, "directory", None)
        if not out_dir:
            return None
        path = os.path.join(out_dir, FLEET_FILE)
        try:
            os.makedirs(out_dir, exist_ok=True)
            atomic_write(path, json.dumps(self.fleetz(), sort_keys=True))
        except OSError:
            return None
        return path

    def close(self) -> None:
        try:
            self.mesh.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Process-wide plane (the --fleet_dir entry)
# ---------------------------------------------------------------------------

_PLANE: Optional[FleetPlane] = None


def configure(fleet_dir: Optional[str], process: int = 0, nproc: int = 1,
              spans_dir: Optional[str] = None) -> Optional[FleetPlane]:
    """Install the process-wide fleet plane (``fleet_dir`` = shared
    directory or ``tcp://host:port``; ``spans_dir`` = the SHARED logdir
    every host's span stream and the coordinator's ``fleet.json`` land
    in).  ``fleet_dir=None`` uninstalls.  The multi-process rigs call
    this BEFORE constructing the Trainer with their explicit identity
    (the same pattern as their explicit HealthMonitor); the trainer
    falls back to jax's process identity when only ``--fleet_dir`` is
    set."""
    global _PLANE
    if _PLANE is not None:
        _PLANE.close()
        _PLANE = None
    if fleet_dir:
        _PLANE = FleetPlane(
            make_fleet_mesh(fleet_dir, process, process == 0),
            process, nproc, spans_dir=spans_dir)
    return _PLANE


def get_plane() -> Optional[FleetPlane]:
    return _PLANE


def reset() -> None:
    configure(None)
