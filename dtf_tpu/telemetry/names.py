"""The instrument/span naming scheme — ONE canonical table.

Every metric and span name in the codebase is ``snake_case`` segments
joined by ``/`` (scope separator): ``checkpoint/save``,
``health/step_ms_p3``, ``goodput/rollback_s``.  Dynamic suffixes (a
process index, an event kind) are declared here with a trailing ``*``
wildcard.  Two consumers:

* :func:`validate` — runtime guard: the registry and the tracer reject a
  malformed name at creation time, so a typo'd scope never ships a run's
  worth of garbage rows;
* :func:`check_source_names` — the lint lane
  (``scripts/check_telemetry_names.py`` and the tier-1 test): scans the
  package source for name literals passed to ``span(``/``counter(``/
  ``gauge(``/``histogram(``/``scalar(``/``instant(`` and fails on any
  that is unregistered here or not scheme-shaped.  Registration is the
  point: the report CLI and dashboards key on these strings, and an
  undeclared name is a dashboard hole nobody notices until the
  post-mortem needs it.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Tuple

# snake_case segments, slash-scoped: "cost", "train/step", "health/step_ms_p0"
NAME_RE = re.compile(r"^[a-z0-9_]+(/[a-z0-9_]+)*$")
# declaration patterns may end a segment with '*' (dynamic suffix)
_DECL_RE = re.compile(r"^[a-z0-9_*]+(/[a-z0-9_*]+)*$")

# -- the registered names ----------------------------------------------------
# metrics (registry instruments / MetricLogger scalars)
METRICS = (
    "cost",
    "avg_ms",
    "test_accuracy",
    "bad_steps_total",
    "model_tflops_per_chip",
    "health/step_ms_p*",          # per-host step-time overlay
    "health/stragglers",
    "event/*",                    # lifecycle events (rollback, preempted, ...)
    "train/steps_total",
    "train/bad_streak",
    "throughput/examples_per_s",
    "throughput/tokens_per_s",
    "throughput/step_ms",
    "mfu/model_tflops_per_chip",
    "mfu/pct_peak",
    "goodput/*",                  # per-category seconds + fraction
    "compile/first_step_s",
    "compile/aot_s",
    "compile/cache_hit",
    "compile/cache_miss",
    "data/prefetch_depth",
    "data/prefetch_stall_s",
    # gradient sync / weight-update sharding (parallel/grad_sync.py)
    "comm/strategy_idx",          # index into grad_sync.STRATEGIES
    "comm/wire_dtype_idx",        # index into grad_sync.WIRE_DTYPES
    "comm/data_axis_size",
    "comm/grad_sync_bytes",       # full sync payload per device per step
    "comm/wire_bytes",            # gradient-wire payload (dtype-scaled)
    "comm/quant_error",           # int8 wire: measured relative-RMS error
    "comm/bucket_count",
    "comm/optimizer_state_bytes", # measured per-device opt-state HBM
    "comm/grad_sync_s",           # isolated sync+update time (bench A/B)
    "comm/hops",                  # RS hops per round (int8_ring: n-1)
    # sharding planner (parallel/planner.py): predicted-vs-measured audit
    "plan/active",                # 1 iff a --plan auto plan drove the run
    "plan/predicted_hbm_bytes",   # planner's per-device peak-HBM claim
    "plan/predicted_step_ms",     # planner's step-time claim (0 = no card)
    "plan/source_idx",            # index into planner.PLAN_SOURCES
    "plan/hbm_budget_bytes",      # the budget the plan was solved against
    "checkpoint/save_ms",
    "checkpoint/saves_total",
    "checkpoint/restores_total",
    "checkpoint/rollbacks_total",
    "supervisor/restarts_total",
    "chaos/faults_fired_total",
    "data/fetch_retries_total",
    # serving engine (dtf_tpu/serve): request lifecycle + SLO latency.
    # submissions_total counts SUBMIT calls — a supervisor restart's
    # replay re-counts its unfinished requests here, so it can exceed
    # completed+rejected; those two reconcile per unique request.
    "serve/submissions_total",
    "serve/requests_completed",
    "serve/requests_rejected",
    "serve/tokens_generated_total",
    "serve/prefill_tokens_total",
    "serve/decode_iterations_total",
    "serve/queue_depth",
    "serve/active_requests",
    "serve/slots",
    "serve/kv_blocks_total",
    "serve/kv_blocks_peak",
    "serve/ttft_ms",              # per-request time-to-first-token
    "serve/tpot_ms",              # per-request time-per-output-token
    # fast decode data path (ISSUE 14): batched multi-request prefill +
    # speculative decoding.  prefill_batch_size is a histogram of
    # requests per prefill dispatch (mean > 1 = coalescing is paying);
    # acceptance = spec_accepted_total / spec_proposed_total, surfaced
    # in summary() and the report's Serving section.
    "serve/prefill_batch_size",
    "serve/spec_proposed_total",
    "serve/spec_accepted_total",
    # overload control / resilience (PR 10): sheds happen BEFORE prefill
    # (deadline feasibility or brownout level), evictions tear out
    # in-flight requests (client disconnect / detected KV corruption),
    # drains checkpoint accepted-but-unfinished work for replay.
    "serve/shed_total",
    "serve/shed_*",               # per-reason: deadline_expired,
                                  # deadline_unmeetable,
                                  # brownout_low_priority,
                                  # brownout_admissions
    "serve/degraded_total",       # brownout max_new_tokens clamps
    "serve/brownout_level",       # 0..3 (serve/brownout.py LEVELS)
    "serve/cancelled_total",      # client disconnects / caller cancels
    "serve/kv_evictions_total",   # non-finite-logits evictions
    "serve/drained_total",        # unfinished requests checkpointed by
                                  # a graceful drain (each replays)
    "serve/conn_total",           # TCP front end: connections accepted
    "serve/conn_errors_total",    # malformed requests + timeouts + drops
    # SLO burn-rate monitor (telemetry/slo.py): windowed error-budget
    # burn per objective (ttft/tpot/deadline) at the fast and slow
    # lookback windows, plus edge-triggered alert counters — the
    # operator's early warning, surfaced live on /slo and in the report.
    "serve/slo_burn_*",           # gauges: slo_burn_<objective>_<speed>
    "serve/slo_alert_*",          # counters: slo_alert_<speed>_total and
                                  # slo_alert_<objective>_<speed>
    # live introspection endpoint (telemetry/live.py)
    "live/requests_total",        # admin HTTP requests served
    "live/errors_total",          # admin HTTP 4xx/5xx responses
    # device cost observatory (telemetry/costobs.py): per-compile XLA
    # cost/memory attribution.  cost/* book at COMPILE time only;
    # hbm/* gauges update at existing sync points (write_telemetry_json)
    # and from the engine's per-iteration KV arithmetic — zero hot-path
    # device work, zero new collectives.
    "cost/compiles_total",        # compiles captured as CostCards
    "cost/cards",                 # distinct (site, geometry) cards
    "cost/flops_total",           # summed cost_analysis flops (known only)
    "cost/bytes_total",           # summed cost_analysis bytes accessed
    "hbm/live_bytes",             # sum of jax.live_arrays() bytes
    "hbm/live_bytes_peak",        # high-water of the above
    "hbm/frac",                   # live peak / chip HBM capacity (roofline)
    "hbm/peak_card_bytes",        # max per-executable HBM claim over cards
    "hbm/kv_pool_bytes",          # paged-KV blocks-in-use x block bytes
    # KV-pool observability (serve/paged_kv.py pool via engine.step):
    # pool pressure visible BEFORE admission starts rejecting
    "serve/kv_blocks_in_use",
    "serve/kv_pool_frac",
    "serve/kv_hot_prefix_blocks",
    # prefix/prompt KV cache (serve/paged_kv.py sharing index, strict —
    # no wildcard): lookup/hit counters book as a pair under the
    # registry lock at submit-time match; kv_cached_blocks gauges the
    # refcount-0 blocks parked in the LRU cached tier (matchable until
    # allocation pressure reclaims them)
    "serve/prefix_lookup_total",
    "serve/prefix_hit_blocks_total",
    "serve/kv_cached_blocks",
    # fleet plane (telemetry/fleet.py): sync-point skew attribution,
    # booked by the coordinator as fleet barriers complete.  blame_p<k>
    # counts the barriers host k arrived LAST at (it gated the fleet);
    # lateness_s_p<k> accumulates its margin over the second-latest
    # arrival (the wall-clock its lateness cost every other host).
    "fleet/barriers_total",
    "fleet/skew_ms",              # per-barrier arrival spread (histogram)
    "fleet/blame_p*",             # last-arrival counters per host
    "fleet/lateness_s_p*",        # accumulated critical-path margin
    "fleet/hosts",                # hosts seen at the latest barrier
    # serving fleet (serve/fleet.py): the acceptor's view of its replica
    # failure domains.  Two-tier shed accounting is deliberate — an
    # acceptor-level shed (fleet brownout / no replicas) is an operator
    # page, a replica-level shed is that replica's own admission policy
    # doing its job.
    "fleet/replicas",             # gauge: fleet size
    "fleet/replicas_up",          # gauge: replicas in rotation
    "fleet/accepted_total",       # requests past acceptor admission
    "fleet/completed_total",      # terminal=completed at the front door
    "fleet/detached_total",       # replicas marked down (any reason)
    "fleet/rejoined_total",       # beat-resumption rejoins (wedge healed)
    "fleet/failovers_total",      # leg deaths that triggered re-dispatch
    "fleet/replayed_total",       # resubmit legs launched on survivors
    "fleet/replay_mismatch_total",  # replayed prefix diverged (bug!)
    "fleet/hedged_total",         # duplicate legs launched past the delay
    "fleet/hedge_wins_total",     # hedge leg beat the primary
    "fleet/hedge_cancelled_total",  # losing legs cancelled (KV freed)
    "fleet/conn_retries_total",   # transient connect errors retried
    "fleet/conn_flakes_total",    # chaos-severed acceptor<->replica socks
    "fleet/replica_wedged_total",  # chaos wedges injected
    "fleet/shed_acceptor_total",  # tier 1: fleet brownout / no replicas
    "fleet/shed_replica_total",   # tier 2: replica admission shed/reject
    "fleet/drains_total",         # rolling-restart drains completed
    # self-tuning control plane (dtf_tpu/control): the runtime knob
    # registry + SLO-driven controller.  Every knob mutation flows
    # through ONE audited path (KnobRegistry.set), so these totals plus
    # the control/set instants ARE the complete mutation history; the
    # per-knob gauges mirror current values for /statz and /controlz.
    "control/decisions_total",    # controller policy evaluations
    "control/sets_total",         # accepted knob mutations
    "control/clamped_total",      # proposals clamped by bounds/max_step
    "control/cooldown_skips_total",  # proposals refused on cooldown
    "control/rollback_total",     # safety-rail snap-backs to defaults
    "control/knob_*",             # gauges: knob_<name> current value
    # incident plane (telemetry/anomaly.py + telemetry/diagnose.py):
    # online changepoint detection over already-booked signals, plus
    # the cross-plane root-cause correlator.  detected_total is
    # registered EAGERLY when the monitor arms (absent = never armed =
    # FAIL, the torn-pair discipline); recorded/attributed reconcile
    # against it — every fire becomes an incident, and an incident
    # without a suspect is report --diagnose's exit-1 condition.
    "anomaly/detected_total",     # detector onsets (edge-triggered)
    "incident/recorded_total",    # incidents pushed into the live ring
    "incident/attributed_total",  # incidents with >= 1 ranked suspect
)
# spans (host-side tracer)
SPANS = (
    "train/fit",
    "train/fetch",
    "train/put",
    "train/step",
    "train/log",
    "train/eval",
    "checkpoint/save",
    "checkpoint/restore",
    "supervisor/backoff",
    "data/next_batch",
    "data/fast_forward",
    "data/prefetch_stall",
    "compile/aot_warmup",
    "comm/grad_sync",
    "serve/prefill",
    "serve/decode",
    "trainer/init",
    # per-request distributed tracing (telemetry/reqtrace.py): one
    # lifecycle event stream per request, keyed by trace_id — submit /
    # shed / rejected / admitted / prefill / first_token / completed /
    # cancelled / failed / drained / lifetime
    "reqtrace/*",
    # fleet barrier marks (telemetry/fleet.py): one complete-span per
    # host per fleet-wide barrier; ts = local arrival, dur = in-barrier
    # wait, so ts+dur is the release edge the clock-offset estimator
    # aligns hosts on
    "fleet/sync",
    # instants
    "chaos/*",                    # chaos/<fault kind> firing marks
    "health/*",                   # peer_stale / abort / poison marks
    "event/*",
    # control-plane audit trail (dtf_tpu/control): one instant per
    # accepted knob mutation (knob/old/new/reason/actor) and one per
    # safety-rail snap-back (reason + knobs restored) — report's
    # "Control plane" section and /controlz render these verbatim
    "control/set",
    "control/rollback",
    # incident plane: one instant per detector ONSET —
    # anomaly/<signal_slug> (slashes in the signal name flatten to '_',
    # e.g. anomaly/serve_ttft_ms) with value/median/mad/z args; these
    # are the SYMPTOM marks the diagnose correlator explains, and are
    # never themselves evidence
    "anomaly/*",
)

DECLARED: Tuple[str, ...] = tuple(sorted(set(METRICS) | set(SPANS)))


def validate(name: str) -> str:
    """Runtime shape check (scheme only, not registration).  Returns the
    name so call sites can inline it."""
    if not NAME_RE.match(name):
        raise ValueError(
            f"telemetry name {name!r} violates the naming scheme: "
            f"snake_case segments joined by '/' (see telemetry/names.py)")
    return name


def require_declared(name: str) -> str:
    """Runtime REGISTRATION guard (the reverse of the source lint): an
    instrument created at runtime whose name is not declared here —
    e.g. assembled from variables the AST lint collapsed to a pattern
    that matches nothing — is rejected at creation, not discovered as a
    dashboard hole at post-mortem time.  Returns the name."""
    validate(name)
    if not is_declared(name):
        raise ValueError(
            f"telemetry instrument {name!r} is not declared in "
            f"dtf_tpu/telemetry/names.py — declare it (or a '*' pattern "
            f"covering it) before registering")
    return name


def is_declared(name: str, declared: Iterable[str] = DECLARED) -> bool:
    """True when ``name`` matches a declaration (exact, or a ``*``-suffixed
    pattern where ``*`` absorbs the rest of its segment and any further
    segments)."""
    for pat in declared:
        if pat == name:
            return True
        if pat.endswith("*") and name.startswith(pat[:-1]):
            return True
    return False


_NAME_FUNCS = frozenset(
    ("span", "instant", "counter", "gauge", "histogram", "scalar"))


def _string_literal(node) -> "str | None":
    """A string constant or f-string (placeholders collapse to ``*`` so
    ``f"health/step_ms_p{k}"`` lints against ``health/step_ms_p*``)."""
    import ast
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return "".join(v.value if isinstance(v, ast.Constant)
                       and isinstance(v.value, str) else "*"
                       for v in node.values)
    return None


def extract_source_names(text: str) -> List[str]:
    """Name literals passed to the telemetry call sites in ``text``.

    AST-based (not a regex), so a complex first argument —
    ``scalar(int(state["step"]), "name", v)`` — cannot smuggle a name
    literal past the lint: for every call to a ``_NAME_FUNCS`` function
    the first string literal among its first two positional arguments is
    extracted."""
    import ast
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = (func.attr if isinstance(func, ast.Attribute)
                 else getattr(func, "id", None))
        if fname not in _NAME_FUNCS:
            continue
        for arg in node.args[:2]:
            name = _string_literal(arg)
            if name is not None:
                out.append(name)
                break
    return out


def check_source_names(paths: Iterable[str]) -> List[str]:
    """Lint: every telemetry name literal under ``paths`` must be scheme-
    shaped and declared.  Returns a list of human-readable violations
    (empty == clean)."""
    problems = []
    for path in paths:
        with open(path) as f:
            text = f.read()
        for name in extract_source_names(text):
            shape = name.replace("*", "x")      # '*' only from f-string holes
            if not NAME_RE.match(shape):
                problems.append(f"{path}: {name!r} is not snake_case/slash")
            elif not is_declared(name):
                problems.append(
                    f"{path}: {name!r} is not declared in "
                    f"dtf_tpu/telemetry/names.py")
    return problems
