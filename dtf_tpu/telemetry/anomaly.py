"""Online anomaly detection over the signals the run already books.

The five evidence planes (spans/goodput, request traces, SLO burn,
fleet skew, CostCards/HBM, control audit) record what happened; none of
them says *something just changed*.  This module is that trigger: a
rolling robust-statistics detector per signal — windowed median/MAD
with a changepoint EDGE trigger — fed inline from ``engine.step()`` and
the trainer's sync points, emitting one ``anomaly/<signal>`` instant
per onset plus the eagerly-registered ``anomaly/detected_total``
counter (absent counter = the plane never armed = a gate FAIL, never a
silent zero — the torn-pair discipline).

Detector math (DESIGN.md "Incident plane"):

* maintain a bounded window of recent observations; never fire until
  ``min_samples`` have been seen (cold start is silence, not noise);
* robust z-score ``z = |x - median| / D`` with
  ``D = max(1.4826 * MAD, rel_floor * |median|, abs_floor)`` — the MAD
  term adapts to the signal's own spread, the two floors keep an
  all-constant signal (MAD = 0) from dividing by zero or firing on
  float noise;
* EDGE trigger: a detector in the anomalous state does not re-fire; it
  re-arms only after z falls below ``threshold / 2`` (hysteresis).  A
  step function therefore fires exactly once; a recurring fault (every
  Nth checkpoint stalled) fires once per onset.

Everything is values-only arithmetic — no clock reads, no jax — so a
VirtualClock run and a WallClock run fed the same observation sequence
fire identically (tested), and the hot-path cost is one deque append
plus a sort of a <=64-element window per observation.

Detection and attribution are deliberately split: this module only
*notices*; :mod:`dtf_tpu.telemetry.diagnose` explains, by correlating
each fire against every plane's instant stream.
"""

from __future__ import annotations

import collections
import threading
from typing import Deque, Dict, Optional

# -- per-signal detector configuration ---------------------------------------
# Conservative by design: a false anomaly poisons the attribution gate
# far more than a missed one (every fire must find its cause).  Floors
# are in the signal's own units.  A steadily RAMPING signal (overload
# queue growth, TTFT creep) keeps z near 1 because the MAD grows with
# the ramp — only discontinuities fire, which is exactly the changepoint
# semantic the correlator needs.
SIGNALS: Dict[str, dict] = {
    "serve/ttft_ms":      dict(window=48, min_samples=16, threshold=8.0,
                               rel_floor=0.25, abs_floor=5.0),
    "serve/tpot_ms":      dict(window=48, min_samples=16, threshold=8.0,
                               rel_floor=0.25, abs_floor=2.0),
    "serve/queue_depth":  dict(window=64, min_samples=24, threshold=10.0,
                               rel_floor=0.50, abs_floor=2.0),
    "train/step_ms":      dict(window=32, min_samples=12, threshold=8.0,
                               rel_floor=0.20, abs_floor=5.0),
    "checkpoint/save_ms": dict(window=16, min_samples=3, threshold=4.0,
                               rel_floor=0.50, abs_floor=15.0),
    "goodput/fraction":   dict(window=16, min_samples=8, threshold=6.0,
                               rel_floor=0.20, abs_floor=0.05),
    "hbm/frac":           dict(window=16, min_samples=8, threshold=6.0,
                               rel_floor=0.20, abs_floor=0.02),
    "fleet/skew_ms":      dict(window=32, min_samples=12, threshold=8.0,
                               rel_floor=0.50, abs_floor=5.0),
    # serve-fleet membership: a count, not a latency.  One replica
    # dropping out of a small fleet must fire (|Δ|=1 against abs_floor
    # 0.25 gives z=4 even when the default rel_floor would swallow it),
    # and a warm survivor can absorb the load with NO client-visible
    # latency shift — membership is the only plane that sees the fault.
    "serve/fleet_up_replicas": dict(window=48, min_samples=8,
                                    threshold=4.0, rel_floor=0.05,
                                    abs_floor=0.25),
}
DEFAULT_CONFIG = dict(window=48, min_samples=16, threshold=8.0,
                      rel_floor=0.25, abs_floor=1e-9)

_MAD_SCALE = 1.4826            # MAD -> sigma for a normal distribution


def _median(xs) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class RollingDetector:
    """One signal's changepoint detector (see module docstring)."""

    def __init__(self, signal: str, window: int = 48, min_samples: int = 16,
                 threshold: float = 8.0, rel_floor: float = 0.25,
                 abs_floor: float = 1e-9):
        self.signal = signal
        self.min_samples = max(2, min_samples)
        self.threshold = threshold
        self.rel_floor = rel_floor
        self.abs_floor = abs_floor
        self.window: Deque[float] = collections.deque(maxlen=window)
        self.in_anomaly = False
        self.fired_total = 0
        self._n_seen = 0

    def score(self, value: float) -> Optional[dict]:
        """Robust z of ``value`` against the current window, or None
        while the window is still cold."""
        if len(self.window) < self.min_samples:
            return None
        med = _median(self.window)
        mad = _median([abs(x - med) for x in self.window])
        denom = max(_MAD_SCALE * mad, self.rel_floor * abs(med),
                    self.abs_floor)
        return {"median": med, "mad": mad,
                "z": abs(value - med) / denom}

    def observe(self, value: float, tick=None) -> Optional[dict]:
        """Feed one observation; returns a fire-doc on an anomaly ONSET
        (edge), None otherwise.  ``tick`` is annotation only (step /
        iteration number) — the math never reads a clock."""
        value = float(value)
        self._n_seen += 1
        sc = self.score(value)
        fired = None
        if sc is not None:
            z = sc["z"]
            if z >= self.threshold and not self.in_anomaly:
                self.in_anomaly = True
                self.fired_total += 1
                fired = {"signal": self.signal, "value": value,
                         "median": sc["median"], "mad": sc["mad"],
                         "z": z, "n": self._n_seen}
                if tick is not None:
                    fired["tick"] = tick
            elif self.in_anomaly and z < self.threshold / 2.0:
                self.in_anomaly = False
        # the window always absorbs the observation — after a level
        # shift the baseline migrates, z decays below the hysteresis
        # exit, and the detector re-arms for the NEXT edge
        self.window.append(value)
        return fired


class AnomalyMonitor:
    """Process-wide detector bank: one :class:`RollingDetector` per
    signal, lazily created from :data:`SIGNALS`.  On a fire it books the
    ``anomaly/detected_total`` counter, emits the ``anomaly/<signal>``
    instant (the post-hoc evidence), and hands the fire-doc to the live
    correlator (:func:`dtf_tpu.telemetry.diagnose.record_anomaly`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._detectors: Dict[str, RollingDetector] = {}
        self._armed = False

    def arm(self) -> "AnomalyMonitor":
        """Eagerly register the detection counter (absence from a run's
        books must mean 'never armed', not zero).  Idempotent."""
        if not self._armed:
            from dtf_tpu.telemetry import counter
            counter("anomaly/detected_total")
            self._armed = True
        return self

    def _detector(self, signal: str) -> RollingDetector:
        det = self._detectors.get(signal)
        if det is None:
            cfg = SIGNALS.get(signal, DEFAULT_CONFIG)
            det = self._detectors[signal] = RollingDetector(signal, **cfg)
        return det

    def observe(self, signal: str, value, tick=None) -> Optional[dict]:
        """Feed one observation of ``signal``; returns the fire-doc on
        an onset (after booking + emitting it), else None."""
        with self._lock:
            fired = self._detector(signal).observe(value, tick=tick)
        if fired is None:
            return None
        self.arm()
        from dtf_tpu.telemetry import counter, instant
        counter("anomaly/detected_total").inc()
        # slash-scoped signal -> one flat anomaly/* segment, so every
        # anomaly instant lints against the single declared pattern
        slug = signal.replace("/", "_")
        instant(f"anomaly/{slug}", **fired)
        from dtf_tpu.telemetry import diagnose
        diagnose.record_anomaly(f"anomaly/{slug}", fired)
        return fired

    def reset_baselines(self) -> None:
        """Drop every detector's window/state (keeps the armed counter).
        Used after a warmup phase whose traffic shape is deliberately
        unlike steady state (the fleet cell's pre-chaos barrage)."""
        with self._lock:
            self._detectors.clear()


# -- process-wide monitor ----------------------------------------------------

_MONITOR = AnomalyMonitor()


def get_monitor() -> AnomalyMonitor:
    return _MONITOR


def observe(signal: str, value, tick=None) -> Optional[dict]:
    """Module-level convenience: feed the process-wide monitor."""
    return _MONITOR.observe(signal, value, tick=tick)


def reset() -> None:
    """Forget all detector state AND the armed flag (telemetry.reset()
    companion — a new run re-arms on first feed)."""
    global _MONITOR
    _MONITOR = AnomalyMonitor()
