"""Device cost observatory: XLA cost/memory attribution per compile.

The telemetry spine stops at the host boundary: spans, goodput books and
the fleet rollup say *when* a step was slow, and the perf ledger says
*that* a rig regressed — nothing says *why*.  This module closes the
loop at the only place XLA will tell us: **compile time**.  Every
``.lower().compile()`` site the repo has (the trainer's AOT warmup, the
serving engine's cached prefill/decode/verify builds, the bench
drivers) captures ``compiled.cost_analysis()`` +
``compiled.memory_analysis()`` into a per-geometry :class:`CostCard`
and books the ``cost/*`` + ``hbm/*`` instrument family — so a run's
FLOP/byte/HBM accounting is on disk (``<logdir>/costcards.jsonl``),
live (the ``/memz`` admin endpoint), and diffable
(``telemetry.report --explain <a> <b>``).

Honesty rules, pinned by tests/test_costobs.py:

* a backend that reports nothing (or partial dicts) yields a
  well-formed card with ``None`` fields — never a fake zero a gate
  could pass on;
* capture happens at compile time only, and the live-memory gauges
  update at existing sync points (``write_telemetry_json``) — the hot
  path pays nothing and no collective is added;
* classification (compute- vs memory-bound) is against a per-chip
  roofline table (``utils/profiling.chip_roofline``); the CPU sim gets
  a pinned synthetic entry so tests are deterministic.

Pure stdlib at import time (jax is imported lazily inside the capture
helpers), same rule as the rest of the telemetry spine.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from dtf_tpu.telemetry import registry as _registry

#: On-disk card stream under a run's logdir (one JSON object per line,
#: rewritten atomically at every sync point — cards are cumulative).
COSTCARDS_FILE = "costcards.jsonl"


def _deep_tuple(v):
    """Lists/tuples -> nested tuples (hashable, JSON-round-trip-stable
    geometry keys); everything else passes through."""
    if isinstance(v, (list, tuple)):
        return tuple(_deep_tuple(x) for x in v)
    return v


# -- the card ----------------------------------------------------------------

@dataclasses.dataclass
class CostCard:
    """One compiled executable's cost/memory accounting, keyed by
    ``(site, geometry)`` — the same static-geometry key the compile
    caches use, so "one card per executable the process warmed" holds
    by construction.  A recompile of the same geometry (e.g. the paged
    pool's hot prefix crossing a bucket) folds into the card:
    ``n_compiles`` increments, the latest per-compile numbers replace
    the headline fields, and the ``*_total`` accumulators sum every
    capture whose backend reported a value (``None`` = never reported,
    distinct from a measured zero)."""

    site: str                  # "train/step", "serve/decode", "bench/matmul"
    geometry: Tuple            # static shape key (slots, window, bucket, ...)
    flops: Optional[float] = None           # latest compile
    bytes_accessed: Optional[float] = None  # latest compile
    flops_total: Optional[float] = None     # summed over captures
    bytes_total: Optional[float] = None
    peak_hbm_bytes: Optional[float] = None  # max over captures (see below)
    argument_bytes: Optional[float] = None
    output_bytes: Optional[float] = None
    temp_bytes: Optional[float] = None
    generated_code_bytes: Optional[float] = None
    oi: Optional[float] = None              # operational intensity, flops/byte
    bound: str = "unknown"                  # "compute" | "memory" | "unknown"
    n_compiles: int = 0
    seq: int = 0                            # capture order (stable sort key)

    def key(self) -> Tuple[str, Tuple]:
        return (self.site, _deep_tuple(self.geometry))

    def to_doc(self) -> dict:
        d = dataclasses.asdict(self)
        d["geometry"] = list(self.geometry)
        return d

    @classmethod
    def from_doc(cls, doc: dict) -> "CostCard":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in doc.items() if k in known}
        # recursive list->tuple: JSON turns NESTED geometry tuples (e.g.
        # bench/breakdown's operand-shape element) into lists, and the
        # key must round-trip hashable AND equal to the in-process key —
        # explain pairs A/B cards by it
        kw["geometry"] = _deep_tuple(kw.get("geometry") or ())
        return cls(**kw)


def _fnum(v) -> Optional[float]:
    """A usable float or None: non-numeric, NaN and negative sentinels
    (XLA reports -1 for "unknown") all degrade to None — absence, never
    a fake value a gate could pass on."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    if f != f or f < 0:
        return None
    return f


def _cost_dict(compiled) -> dict:
    """``compiled.cost_analysis()`` with every backend quirk absorbed:
    None, a raise, a list-of-dicts (one per computation — first wins),
    or a plain dict all normalize to a (possibly empty) dict."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else {}


def _mem_fields(compiled) -> dict:
    """``compiled.memory_analysis()`` -> the four device-side byte
    fields (None where the backend reports nothing).  ``peak_hbm_bytes``
    is arguments + outputs + temps − aliased: XLA exposes no single
    "peak" number, and that sum is the executable's device-memory claim
    while it runs (generated code is reported separately)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    out = {"argument_bytes": None, "output_bytes": None,
           "temp_bytes": None, "generated_code_bytes": None,
           "peak_hbm_bytes": None}
    if ma is None:
        return out
    out["argument_bytes"] = _fnum(getattr(ma, "argument_size_in_bytes", None))
    out["output_bytes"] = _fnum(getattr(ma, "output_size_in_bytes", None))
    out["temp_bytes"] = _fnum(getattr(ma, "temp_size_in_bytes", None))
    out["generated_code_bytes"] = _fnum(
        getattr(ma, "generated_code_size_in_bytes", None))
    parts = [out["argument_bytes"], out["output_bytes"], out["temp_bytes"]]
    if any(p is not None for p in parts):
        alias = _fnum(getattr(ma, "alias_size_in_bytes", None)) or 0.0
        out["peak_hbm_bytes"] = max(
            sum(p for p in parts if p is not None) - alias, 0.0)
    return out


def classify(flops: Optional[float], bytes_accessed: Optional[float],
             roofline) -> Tuple[Optional[float], str]:
    """``(operational intensity, bound)`` against a
    :class:`~dtf_tpu.utils.profiling.ChipRoofline`.  Any missing input
    (no flops, no bytes, unknown chip) is "unknown" — a gate must see
    absence, not a guessed verdict."""
    if not flops or not bytes_accessed:
        return None, "unknown"
    oi = flops / bytes_accessed
    if roofline is None:
        return oi, "unknown"
    return oi, ("compute" if oi >= roofline.ridge_flops_per_byte
                else "memory")


# -- the observatory ---------------------------------------------------------

class CostObservatory:
    """Process-wide card store + the ``hbm/*`` live-memory plane.

    Thread-safe (one lock over the card dict; instrument updates group
    under the registry lock, same ``/statz`` discipline) — the admin
    ``/memz`` handler reads while the engine/trainer thread records.
    Lock order is observatory -> registry everywhere, so the two can
    never deadlock.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._cards: Dict[Tuple[str, Tuple], CostCard] = {}
        self._seq = 0
        self._compiles = 0
        self._live_peak: Optional[float] = None
        self._roofline = None
        self._roofline_tried = False

    # -- roofline (lazy: jax must not load at telemetry import time) --------

    def _resolve_roofline(self):
        if not self._roofline_tried:
            self._roofline_tried = True
            try:
                import jax

                from dtf_tpu.utils.profiling import chip_roofline
                self._roofline = chip_roofline(jax.devices()[0])
            except Exception:
                self._roofline = None
        return self._roofline

    # -- capture ------------------------------------------------------------

    def observe(self, site: str, geometry, compiled) -> CostCard:
        """Capture one compile.  Called at compile time only (the AOT
        warmup, a jit-wrapper's per-signature lower+compile) — never on
        the hot path."""
        ca = _cost_dict(compiled)
        mem = _mem_fields(compiled)
        flops = _fnum(ca.get("flops"))
        bytes_accessed = _fnum(ca.get("bytes accessed"))
        oi, bound = classify(flops, bytes_accessed,
                             self._resolve_roofline())
        new_geometry = False
        with self._lock:
            geometry = _deep_tuple(geometry)
            key = (site, geometry)
            card = self._cards.get(key)
            if card is None:
                card = CostCard(site=site, geometry=geometry,
                                seq=self._seq)
                self._seq += 1
                self._cards[key] = card
                new_geometry = True
            card.n_compiles += 1
            self._compiles += 1
            card.flops = flops
            card.bytes_accessed = bytes_accessed
            if flops is not None:
                card.flops_total = (card.flops_total or 0.0) + flops
            if bytes_accessed is not None:
                card.bytes_total = (card.bytes_total or 0.0) + bytes_accessed
            for f in ("argument_bytes", "output_bytes", "temp_bytes",
                      "generated_code_bytes"):
                if mem[f] is not None:
                    setattr(card, f, mem[f])
            if mem["peak_hbm_bytes"] is not None:
                card.peak_hbm_bytes = max(card.peak_hbm_bytes or 0.0,
                                          mem["peak_hbm_bytes"])
            card.oi, card.bound = oi, bound
            n_cards = len(self._cards)
            peak_card = max((c.peak_hbm_bytes for c in self._cards.values()
                             if c.peak_hbm_bytes is not None), default=None)
            # instruments update INSIDE the observatory lock (nested
            # obs -> registry, the established order): a /memz scrape —
            # cards under the obs lock, instruments under the registry
            # lock — can then never see a card whose cost/cards or
            # cost/compiles_total hasn't landed yet
            with _registry.get_registry().locked():
                _registry.counter("cost/compiles_total").inc()
                _registry.gauge("cost/cards").set(n_cards)
                if flops is not None:
                    _registry.gauge("cost/flops_total").add(flops)
                if bytes_accessed is not None:
                    _registry.gauge("cost/bytes_total").add(bytes_accessed)
                if peak_card is not None:
                    _registry.gauge("hbm/peak_card_bytes").set(peak_card)
        if new_geometry:
            # evidence instant for the incident correlator: a compile
            # against a geometry this process has never seen is exactly
            # the kind of event that explains a step-time spike.
            # Emitted OUTSIDE the observatory lock (the tracer flushes
            # to disk; lock order stays obs -> registry only).
            from dtf_tpu.telemetry import spans as _spans
            _spans.instant("event/compile_new_geometry", site=site,
                           seq=card.seq)
        return card

    # -- live device memory (sync points only) ------------------------------

    def update_live_memory(self) -> Optional[float]:
        """High-water gauge over ``jax.live_arrays()`` — the measured
        device-memory claim, booked at existing sync points (every
        ``write_telemetry_json``).  Returns the current live bytes, or
        None when jax is absent/uninitialized (a jax-free tool writing
        telemetry must not crash)."""
        try:
            import jax
            live = float(sum(getattr(a, "nbytes", 0)
                             for a in jax.live_arrays()))
        except Exception:
            return None
        with self._lock:
            self._live_peak = max(self._live_peak or 0.0, live)
            peak = self._live_peak
        rl = self._resolve_roofline()
        # hbm/frac denominator is the PROCESS's capacity: live_arrays()
        # sums every local device's shards, so a single-chip capacity
        # would overstate the fraction n_devices-fold on a pod slice
        try:
            n_dev = max(len(jax.local_devices()), 1)
        except Exception:
            n_dev = 1
        with _registry.get_registry().locked():
            _registry.gauge("hbm/live_bytes").set(live)
            _registry.gauge("hbm/live_bytes_peak").set(peak)
            if rl is not None and rl.hbm_capacity_bytes:
                _registry.gauge("hbm/frac").set(
                    peak / (rl.hbm_capacity_bytes * n_dev))
        return live

    # -- reading ------------------------------------------------------------

    def cards(self) -> List[CostCard]:
        with self._lock:
            return sorted(self._cards.values(), key=lambda c: c.seq)

    def total_compiles(self) -> int:
        with self._lock:
            return self._compiles

    def live_peak_bytes(self) -> Optional[float]:
        with self._lock:
            return self._live_peak

    def summary(self) -> dict:
        """Deterministic aggregate for telemetry.json's ``cost`` section
        (sorted keys, value types only — the report renders it and the
        ``--max_hbm_frac`` arithmetic reads it post-hoc)."""
        rl = self._resolve_roofline()
        with self._lock:
            sites: Dict[str, dict] = {}
            for c in sorted(self._cards.values(), key=lambda c: c.seq):
                s = sites.setdefault(c.site, {
                    "cards": 0, "compiles": 0, "flops_total": None,
                    "bytes_total": None, "peak_hbm_bytes": None,
                    "compute_bound": 0, "memory_bound": 0})
                s["cards"] += 1
                s["compiles"] += c.n_compiles
                if c.flops_total is not None:
                    s["flops_total"] = ((s["flops_total"] or 0.0)
                                        + c.flops_total)
                if c.bytes_total is not None:
                    s["bytes_total"] = ((s["bytes_total"] or 0.0)
                                        + c.bytes_total)
                if c.peak_hbm_bytes is not None:
                    s["peak_hbm_bytes"] = max(s["peak_hbm_bytes"] or 0.0,
                                              c.peak_hbm_bytes)
                if c.bound in ("compute", "memory"):
                    s[c.bound + "_bound"] += 1
            out = {"cards": len(self._cards), "compiles": self._compiles,
                   "live_bytes_peak": self._live_peak,
                   "sites": {k: sites[k] for k in sorted(sites)}}
        if rl is not None:
            out["roofline"] = {
                "kind": rl.kind, "peak_flops": rl.peak_flops,
                "hbm_bytes_per_s": rl.hbm_bytes_per_s,
                "hbm_capacity_bytes": rl.hbm_capacity_bytes,
                "ridge_flops_per_byte": rl.ridge_flops_per_byte,
                "synthetic": rl.synthetic}
        else:
            out["roofline"] = None
        return out

    def memz(self) -> dict:
        """The ``/memz`` payload: one consistent cut — the observatory
        lock is held across the cards read, the registry snapshot AND
        the summary (observe() updates its instruments nested inside
        the same lock), so a scrape can never see a card without its
        ``cost/*`` bookings or vice versa (same torn-pair discipline
        as ``/statz``)."""
        with self._lock:
            cards = [c.to_doc()
                     for c in sorted(self._cards.values(),
                                     key=lambda c: c.seq)]
            metrics = _registry.get_registry().snapshot()
            summary = self.summary()
        fam = {n: m for n, m in metrics.items()
               if n.startswith(("hbm/", "cost/", "serve/kv_",
                                "serve/prefix_"))}
        return {"cards": cards, "metrics": fam, "summary": summary}

    # -- persistence --------------------------------------------------------

    def write_jsonl(self, logdir: str) -> Optional[str]:
        """Atomic rewrite of ``<logdir>/costcards.jsonl`` (cards are
        cumulative; the whole stream is rewritten each sync point, so a
        SIGKILL leaves a recent consistent file).  No-op when no card
        was ever captured."""
        cards = self.cards()
        if not cards:
            return None
        path = os.path.join(logdir, COSTCARDS_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for c in cards:
                f.write(json.dumps(c.to_doc(), sort_keys=True) + "\n")
        os.replace(tmp, path)
        return path

    def reset(self) -> None:
        with self._lock:
            self._cards.clear()
            self._seq = 0
            self._compiles = 0
            self._live_peak = None
            self._roofline = None
            self._roofline_tried = False


_OBSERVATORY = CostObservatory()


def get_observatory() -> CostObservatory:
    return _OBSERVATORY


def observe(site: str, geometry, compiled) -> CostCard:
    return _OBSERVATORY.observe(site, geometry, compiled)


def read_costcards(logdir: str) -> List[CostCard]:
    """Cards back off a run's ``costcards.jsonl`` (torn tail lines from
    a hard kill are skipped, same rule as every other reader)."""
    path = os.path.join(logdir, COSTCARDS_FILE)
    out: List[CostCard] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(CostCard.from_doc(json.loads(line)))
            except (ValueError, TypeError):
                continue
    return out


# -- the jit wrapper (the serving/bench compile sites) -----------------------

class InstrumentedJit:
    """AOT-capturing wrapper around a jitted callable: per input
    signature it runs ``jfn.lower(*args).compile()`` ONCE, captures the
    CostCard, and dispatches every later call straight to the compiled
    executable — the identical program jit would have built (the parity
    tests that pin token-bitwise behavior run through this wrapper).

    Hot-path contract: the steady state pays ONE identity check and a
    try-frame, nothing else — the last-used Compiled is called
    directly, and ITS OWN C-level argument validation (shape/dtype/
    sharding, run before execution or donation — the same pre-execution
    contract the trainer's AOT dispatch leans on) doubles as the cache
    probe.  Only a mismatch (a new shape bucket, a resharded input)
    raises TypeError/ValueError and falls into the slow path, which
    computes the full pytree signature, compiles+captures if new, and
    promotes the entry.  Shape buckets in the engine are sticky, so the
    exception path is O(distinct geometries) per process, not per step.

    Failure is always graceful and PER SIGNATURE: a lowering quirk (or
    a first-call input rejection) routes that signature to the plain
    jit path while other geometries keep capturing — so
    ``cost/compiles_total`` never silently undercounts a run with real
    geometry churn just because one shape misbehaved.  Fallback
    signatures pay the sig-keyed slow path per call (they are the
    rare, already-broken case); an execution failure propagates.
    """

    def __init__(self, jfn, site: str, geometry):
        self._jfn = jfn
        self.site = site
        self.geometry = _deep_tuple(geometry)
        self._by_sig: Dict[Tuple, Any] = {}
        self._last: Any = None         # last-used entry (fast path)

    @staticmethod
    def _sig(args) -> Tuple:
        # (aval, sharding) per leaf: a Compiled pins its input
        # shardings, so the same shapes on a different mesh (e.g. the
        # TP-sharded params of a later engine over the same model) must
        # map to a fresh compile, exactly as jit's own cache would.
        # avals and sharding objects are hashable.
        import jax
        import numpy as np
        out = []
        for x in jax.tree_util.tree_leaves(args):
            aval = getattr(x, "aval", None)
            if aval is not None:
                out.append((aval, getattr(x, "sharding", None)))
            else:
                out.append((tuple(np.shape(x)),
                            str(getattr(x, "dtype", type(x).__name__))))
        return tuple(out)

    def __call__(self, *args):
        entry = self._last             # only ever a Compiled, never jfn
        if entry is not None:
            try:
                # the Compiled's own pre-execution argument check IS
                # the cache probe: zero extra hot-path work
                return entry(*args)
            except (TypeError, ValueError):
                pass                   # new geometry: re-route below
        sig = self._sig(args)
        entry = self._by_sig.get(sig)
        if entry is None:
            try:
                entry = self._jfn.lower(*args).compile()
                observe(self.site, self.geometry, entry)
            except Exception:
                entry = self._jfn      # capture must never break serving
            self._by_sig[sig] = entry
        if entry is self._jfn:
            return self._jfn(*args)
        try:
            out = entry(*args)
        except (TypeError, ValueError):
            # first-call input rejection (raised before execution or
            # donation): jit fallback for THIS signature only
            self._by_sig[sig] = self._jfn
            return self._jfn(*args)
        self._last = entry
        return out


def instrument(jfn, site: str, geometry) -> InstrumentedJit:
    """Wrap a jitted callable so every compile it pays is captured as a
    CostCard under ``(site, geometry)``."""
    return InstrumentedJit(jfn, site, geometry)


# -- the explainer (report --explain A B) ------------------------------------

def _card_totals(card: CostCard) -> dict:
    return {"bytes": card.bytes_total, "flops": card.flops_total,
            "compiles": card.n_compiles,
            "peak_hbm_bytes": card.peak_hbm_bytes, "bound": card.bound}


def _rel(b: Optional[float], a: Optional[float]) -> Optional[float]:
    """Relative growth, or None when undefined — including a zero base
    (no Infinity: the ``--json`` document must stay RFC-parseable by
    non-Python consumers, and the absolute deltas carry the signal)."""
    if a is None or b is None or a == 0:
        return None
    return (b - a) / a


def _growth_verdict(bf: Optional[float], ff: Optional[float]) -> str:
    """bytes-growth-fraction, flops-growth-fraction -> a one-word cause."""
    if bf is None and ff is None:
        return "unmeasured"
    bf = bf if bf is not None else 0.0
    ff = ff if ff is not None else 0.0
    if bf > 2 * max(ff, 0.0) + 0.05:
        return "memory-bound growth"
    if ff > 2 * max(bf, 0.0) + 0.05:
        return "compute-bound growth"
    if max(bf, ff) > 0.05:
        return "proportional growth"
    if min(bf, ff) < -0.05:
        return "shrink"
    return "flat"


def diff_cards(cards_a: List[CostCard],
               cards_b: List[CostCard]) -> List[dict]:
    """Card-by-card diff, RANKED by share of byte growth (run A's total
    bytes is the normalizer, so "which executable grew the run" reads
    directly off the order).  A geometry present only in B — the usual
    shape of a widened decode bucket — counts its full cost as growth.
    Ties (no bytes on either side) fall back to flops growth, then to
    compile-count growth."""
    ix_a = {c.key(): c for c in cards_a}
    ix_b = {c.key(): c for c in cards_b}
    total_bytes_a = sum(c.bytes_total or 0.0 for c in cards_a) or 1.0
    total_flops_a = sum(c.flops_total or 0.0 for c in cards_a) or 1.0
    rows = []
    for key in sorted(set(ix_a) | set(ix_b), key=str):
        a, b = ix_a.get(key), ix_b.get(key)
        ta = _card_totals(a) if a else {"bytes": None, "flops": None,
                                        "compiles": 0,
                                        "peak_hbm_bytes": None,
                                        "bound": "unknown"}
        tb = _card_totals(b) if b else {"bytes": None, "flops": None,
                                        "compiles": 0,
                                        "peak_hbm_bytes": None,
                                        "bound": "unknown"}
        d_bytes = (tb["bytes"] or 0.0) - (ta["bytes"] or 0.0)
        d_flops = (tb["flops"] or 0.0) - (ta["flops"] or 0.0)
        score = (abs(d_bytes) / total_bytes_a
                 + 0.1 * abs(d_flops) / total_flops_a
                 + 1e-6 * abs(tb["compiles"] - ta["compiles"]))
        rows.append({
            "site": key[0], "geometry": list(key[1]),
            "in_a": a is not None, "in_b": b is not None,
            "bytes_a": ta["bytes"], "bytes_b": tb["bytes"],
            "flops_a": ta["flops"], "flops_b": tb["flops"],
            "compiles_a": ta["compiles"], "compiles_b": tb["compiles"],
            "peak_hbm_a": ta["peak_hbm_bytes"],
            "peak_hbm_b": tb["peak_hbm_bytes"],
            "bytes_frac": _rel(tb["bytes"], ta["bytes"]),
            "flops_frac": _rel(tb["flops"], ta["flops"]),
            "bound": tb["bound"] if b else ta["bound"],
            "bytes_delta": d_bytes, "flops_delta": d_flops,
            "score": score})
    rows.sort(key=lambda r: (-r["score"], r["site"], str(r["geometry"])))
    return rows


def diff_sites(cards_a: List[CostCard],
               cards_b: List[CostCard]) -> List[dict]:
    """Per-site rollup of :func:`diff_cards` — the headline attribution
    ("decode: bytes +112%, flops flat -> memory-bound growth; compiles
    3 -> 9"), ranked the same way."""
    def fold(cards):
        agg: Dict[str, dict] = {}
        for c in cards:
            s = agg.setdefault(c.site, {"bytes": None, "flops": None,
                                        "compiles": 0})
            s["compiles"] += c.n_compiles
            if c.bytes_total is not None:
                s["bytes"] = (s["bytes"] or 0.0) + c.bytes_total
            if c.flops_total is not None:
                s["flops"] = (s["flops"] or 0.0) + c.flops_total
        return agg

    agg_a, agg_b = fold(cards_a), fold(cards_b)
    total_bytes_a = sum(c.bytes_total or 0.0 for c in cards_a) or 1.0
    total_flops_a = sum(c.flops_total or 0.0 for c in cards_a) or 1.0
    rows = []
    for site in sorted(set(agg_a) | set(agg_b)):
        a = agg_a.get(site, {"bytes": None, "flops": None, "compiles": 0})
        b = agg_b.get(site, {"bytes": None, "flops": None, "compiles": 0})
        bf, ff = _rel(b["bytes"], a["bytes"]), _rel(b["flops"], a["flops"])
        d_bytes = (b["bytes"] or 0.0) - (a["bytes"] or 0.0)
        d_flops = (b["flops"] or 0.0) - (a["flops"] or 0.0)
        # same weights as diff_cards: bytes growth leads, flops growth
        # keeps a compute-bound regression (flat bytes, doubled flops)
        # from ranking at ~zero, compile churn breaks ties
        rows.append({
            "site": site, "bytes_a": a["bytes"], "bytes_b": b["bytes"],
            "flops_a": a["flops"], "flops_b": b["flops"],
            "compiles_a": a["compiles"], "compiles_b": b["compiles"],
            "bytes_frac": bf, "flops_frac": ff,
            "verdict": _growth_verdict(bf, ff),
            "score": abs(d_bytes) / total_bytes_a
            + 0.1 * abs(d_flops) / total_flops_a
            + 1e-6 * abs(b["compiles"] - a["compiles"])})
    rows.sort(key=lambda r: (-r["score"], r["site"]))
    return rows


def _load_telemetry(logdir: str) -> dict:
    path = os.path.join(logdir, "telemetry.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def explain(logdir_a: str, logdir_b: str) -> dict:
    """The ``report --explain`` payload: phase-by-phase (goodput bucket
    deltas off each run's telemetry.json) and card-by-card (ranked site
    + geometry attribution off each run's costcards.jsonl).  Raises
    FileNotFoundError when either side has no cards — an explain
    against a run that never captured is a configuration error, not an
    empty diff."""
    cards_a = read_costcards(logdir_a)
    cards_b = read_costcards(logdir_b)
    for name, cards in (("A", cards_a), ("B", cards_b)):
        if not cards:
            raise FileNotFoundError(
                f"run {name} has no {COSTCARDS_FILE} — was it produced "
                f"by a costobs-instrumented run?")
    tel_a, tel_b = _load_telemetry(logdir_a), _load_telemetry(logdir_b)
    phases = {}
    ga = tel_a.get("goodput") or {}
    gb = tel_b.get("goodput") or {}
    for k in sorted(set(ga) | set(gb)):
        if not k.endswith("_s") and k != "productive_fraction":
            continue
        va, vb = ga.get(k), gb.get(k)
        if va is None and vb is None:
            continue
        phases[k] = {"a": va, "b": vb,
                     "delta": (vb or 0.0) - (va or 0.0)}
    ranked = diff_sites(cards_a, cards_b)
    return {"logdir_a": os.path.abspath(logdir_a),
            "logdir_b": os.path.abspath(logdir_b),
            "phases": phases,
            "ranked": ranked,
            "cards": diff_cards(cards_a, cards_b)}


def _fmt(v: Optional[float]) -> str:
    return "n/a" if v is None else f"{v:.4g}"


def _fmt_frac(v: Optional[float]) -> str:
    return "n/a" if v is None else f"{v:+.0%}"


def render_explain(doc: dict, top: int = 10) -> List[str]:
    """Human-readable explain lines (the ``--json`` twin is the raw
    dict).  The first ranked line IS the attribution — the lane greps
    it."""
    lines = [f"== cost explain: {doc['logdir_a']} -> {doc['logdir_b']} =="]
    if doc["phases"]:
        lines.append("Phase deltas (goodput seconds, B - A)")
        for k, p in sorted(doc["phases"].items(),
                           key=lambda kv: -abs(kv[1]["delta"])):
            if abs(p["delta"]) < 1e-9:
                continue
            lines.append(f"  {k:<24} {_fmt(p['a']):>10} -> "
                         f"{_fmt(p['b']):>10}  ({p['delta']:+.3f})")
    lines.append("Ranked attribution (share of byte growth, largest first)")
    for i, r in enumerate(doc["ranked"][:top], start=1):
        lines.append(
            f"  {i}. {r['site']}: bytes {_fmt_frac(r['bytes_frac'])} "
            f"({_fmt(r['bytes_a'])} -> {_fmt(r['bytes_b'])}), "
            f"flops {_fmt_frac(r['flops_frac'])} -> {r['verdict']}; "
            f"compiles {r['compiles_a']} -> {r['compiles_b']}")
        for c in [c for c in doc["cards"] if c["site"] == r["site"]][:3]:
            tag = ("NEW in B" if not c["in_a"]
                   else "gone in B" if not c["in_b"]
                   else f"bytes {_fmt_frac(c['bytes_frac'])}")
            lines.append(
                f"       geometry {tuple(c['geometry'])}: {tag}, "
                f"bytes {_fmt(c['bytes_a'])} -> {_fmt(c['bytes_b'])}, "
                f"compiles {c['compiles_a']} -> {c['compiles_b']} "
                f"[{c['bound']}]")
    return lines
