"""Per-request distributed tracing: one causally-ordered timeline per
serving request.

The span tracer (:mod:`.spans`) answers "where did the PROCESS's
wall-clock go"; this module answers the per-request question a serving
operator actually asks — *what happened to request 17431* — by stamping
every lifecycle transition of a request with one **trace id**:

* minted once, at the TCP front end (:func:`mint_trace_id` in
  ``serve/frontend.py``) or at ``ServingEngine.submit``;
* propagated through admission, shed/brownout decisions, prefill, first
  token, per-iteration decode, and completion / eviction / drain;
* carried ACROSS a graceful drain: ``drain.jsonl`` replay docs include
  it, so a supervisor-replayed request links to its pre-SIGTERM events
  and ``telemetry.report --request <rid>`` shows one continuous story.

Events ride the EXISTING span-file format (``reqtrace/<phase>`` instant
records in ``spans.p<k>.jsonl``, args carrying ``trace_id``/``rid``/
``t`` = the engine-clock instant), so the Perfetto export interleaves
request timelines with the engine's ``serve/prefill``/``serve/decode``
iteration spans for free; each request additionally closes with one
``reqtrace/lifetime`` "X" span on its own lane (``tid`` derived from the
rid) so a trace viewer shows requests as parallel tracks.

Causal ordering uses the ENGINE clock (``t``), not the wall ``ts``: the
engine may run on the deterministic VirtualClock, and even on the wall
clock a monotonic per-request ordering must not depend on NTP steps.

The **flight recorder** (:class:`TraceRing`) keeps the last-N completed
request traces in memory for the live ``/tracez`` endpoint — it survives
exactly the case the files don't: a process dying before a sync-point
flush still served its recent history to the scrape that noticed it
dying.

Jax-free, stdlib-only: importable from the front end before any backend
exists.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from dtf_tpu.telemetry import spans as _spans

#: Lifecycle phases, in causal order.  ``submit`` opens a segment (a
#: replay opens a second segment under the SAME trace id); the chain a
#: COMPLETED request must show in its final segment:
CHAIN = ("submit", "admitted", "prefill", "first_token", "completed")
#: Terminal phases (a trace lands in the ring when one of these fires).
TERMINAL = ("completed", "rejected", "shed", "cancelled", "failed",
            "drained")


def mint_trace_id() -> str:
    """16-hex-char trace id.  Random, not derived: two engines replaying
    the same rid (an A/B's two arms) must not collide in a shared
    logdir; continuity across drain/replay comes from *carrying* the id
    in the replay doc, never from re-derivation."""
    return os.urandom(8).hex()


def _lane(rid: int) -> int:
    """Stable per-request Perfetto lane, clear of thread-id lanes."""
    return 0x40000 + (int(rid) & 0xFFFF)


class TraceRing:
    """Bounded flight recorder of the last-N *terminal* request traces
    (``/tracez``).  Insertion order == terminal order; the oldest
    completed trace is evicted first.  Thread-safe: the engine thread
    appends, admin handler threads snapshot."""

    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._live: Dict[str, dict] = {}            # trace_id -> doc
        self._done: "OrderedDict[str, dict]" = OrderedDict()

    def event(self, trace_id: str, rid: int, phase: str,
              t: float, **attrs) -> None:
        ev = {"phase": phase, "t": round(float(t), 6), **attrs}
        with self._lock:
            doc = self._live.get(trace_id)
            if doc is None:
                # a replay under the same trace id RE-OPENS its
                # terminal doc: the ring keeps one continuous story
                doc = self._done.pop(trace_id, None)
            if doc is None:
                doc = {"trace_id": trace_id, "rid": int(rid), "events": []}
            self._live[trace_id] = doc
            doc.pop("status", None)
            doc["events"].append(ev)
            if phase in TERMINAL:
                doc["status"] = phase
                self._live.pop(trace_id, None)
                # a replayed trace re-terminates: move it to the back
                self._done.pop(trace_id, None)
                self._done[trace_id] = doc
                while len(self._done) > self.capacity:
                    self._done.popitem(last=False)

    def snapshot(self, n: Optional[int] = None) -> List[dict]:
        """Terminal traces, oldest first (``n`` keeps the newest n;
        0 is genuinely empty — a count probe, not a full dump)."""
        with self._lock:
            docs = [dict(d, events=list(d["events"]))
                    for d in self._done.values()]
        if n is None:
            return docs
        n = int(n)
        return docs[-n:] if n > 0 else []

    def __len__(self) -> int:
        with self._lock:
            return len(self._done)


class RequestTracer:
    """The engine-side emitter: every lifecycle event goes to BOTH the
    process span file (post-hoc plane) and the flight-recorder ring
    (live plane).  One instance per engine; all calls from the engine
    thread."""

    def __init__(self, ring_capacity: int = 64):
        self.ring = TraceRing(ring_capacity)
        self._wall0: Dict[str, float] = {}   # trace_id -> first wall ts(us)

    def event(self, req, phase: str, t: float, **attrs) -> None:
        """``req`` is a serve Request (needs ``.trace_id``/``.rid``);
        ``t`` is the engine-clock instant."""
        import time
        trace_id = req.trace_id
        rid = int(req.rid)
        self.ring.event(trace_id, rid, phase, t, **attrs)
        tracer = _spans.get_tracer()
        now_us = time.time() * 1e6
        self._wall0.setdefault(trace_id, now_us)
        tracer.emit_instant(
            f"reqtrace/{phase}",
            {"trace_id": trace_id, "rid": rid, "t": round(float(t), 6),
             **attrs},
            ts_us=now_us, tid=_lane(rid))
        if phase in TERMINAL:
            wall0 = self._wall0.pop(trace_id, now_us)
            tracer.emit_complete(
                "reqtrace/lifetime", wall0, now_us - wall0,
                {"trace_id": trace_id, "rid": rid, "status": phase},
                tid=_lane(rid))
            tracer.flush()       # terminal events are what post-mortems need


# ---------------------------------------------------------------------------
# Readers (report CLI, completeness gate)
# ---------------------------------------------------------------------------


def events_from_records(records) -> List[dict]:
    """``reqtrace/*`` instants out of already-parsed span records (in
    read order), as flat event dicts.  Each event carries ``seq`` — its
    position in the chronological record stream — which is the CAUSAL
    order key: span files are appended in emit order and
    ``find_span_files`` walks rotated generations oldest-first, so read
    order is emit order without depending on wall-clock stamps (an NTP
    step between two events must not reorder a timeline)."""
    out = []
    for seq, rec in enumerate(records):
        name = rec.get("name", "")
        if rec.get("ph") != "i" or not name.startswith("reqtrace/"):
            continue
        args = rec.get("args", {})
        if "trace_id" not in args:
            continue
        out.append({"phase": name[len("reqtrace/"):],
                    "trace_id": args["trace_id"],
                    "rid": args.get("rid"),
                    "t": args.get("t", 0.0),
                    "ts": rec.get("ts"), "pid": rec.get("pid"),
                    "seq": seq,
                    **{k: v for k, v in args.items()
                       if k not in ("trace_id", "rid", "t")}})
    return out


def read_all_records(logdir: str) -> List[dict]:
    """Every span record under ``logdir``, one chronological stream
    (rotated generations first, active tail last) — parse ONCE and feed
    both :func:`events_from_records` and any span summarizer."""
    return [rec for path in _spans.find_span_files(logdir)
            for rec in _spans.read_spans(path)]


def load_request_events(logdir: str) -> List[dict]:
    """Every ``reqtrace/*`` instant from every span file (rotated
    generations included), as flat event dicts."""
    return events_from_records(read_all_records(logdir))


def group_traces(events: List[dict]) -> Dict[str, List[dict]]:
    """trace_id -> events, causally ordered by ``seq`` (file read
    order == emit order; see :func:`events_from_records`).  Across a
    drain/replay boundary both segments append to the same per-process
    span file, so the replay's events read later — one trace id reads
    as one ordered story even though the engine clock restarts per
    process and the wall clock may step."""
    by_id: Dict[str, List[dict]] = {}
    for ev in events:
        by_id.setdefault(ev["trace_id"], []).append(ev)
    for evs in by_id.values():
        evs.sort(key=lambda e: e.get("seq", 0))
    return by_id


def chain_gaps(events: List[dict]) -> List[str]:
    """Missing lifecycle phases for one trace's FINAL segment (after its
    last ``submit``).  Empty == gap-free.  Only completed traces are
    held to the full chain; a shed/rejected trace is complete with just
    its submit + verdict, and a drained segment is complete by being
    re-opened (the replay segment is the one judged)."""
    if not events:
        return ["no events"]
    last_submit = max((i for i, e in enumerate(events)
                       if e["phase"] == "submit"), default=0)
    seg = [e["phase"] for e in events[last_submit:]]
    status = next((p for p in reversed(seg) if p in TERMINAL), None)
    if status is None:
        return ["no terminal event"]
    if status != "completed":
        # verdict-only chains: submit -> terminal is the whole story
        return [] if "submit" in seg else ["missing submit"]
    return [f"missing {p}" for p in CHAIN if p not in seg]


def completeness(traces: Dict[str, List[dict]]) -> dict:
    """The scenario gate's quantity: of traces that COMPLETED, what
    fraction reconstructs the full admission->prefill->first_token->
    completion chain (drain/replay folded in by trace-id continuity)."""
    completed, complete, incomplete = 0, 0, []
    for tid, evs in sorted(traces.items()):
        if not any(e["phase"] == "completed" for e in evs):
            continue
        completed += 1
        gaps = chain_gaps(evs)
        if gaps:
            incomplete.append({"trace_id": tid,
                               "rid": evs[0].get("rid"), "gaps": gaps})
        else:
            complete += 1
    return {"completed": completed, "complete": complete,
            "complete_frac": (complete / completed) if completed else None,
            "incomplete": incomplete[:16]}


def request_timeline(logdir: str, rid: int,
                     records: Optional[List[dict]] = None,
                     pid: Optional[int] = None) -> List[dict]:
    """Every event of every trace carrying ``rid``, plus the engine
    iteration spans (``serve/prefill``/``serve/decode``) that touched
    it — the ``report --request`` view's data.  ONE parse pass: pass
    pre-parsed ``records`` (from :func:`read_all_records`) to reuse a
    report's.

    Fleet streams: rids are minted per ENGINE, so a merged multi-host
    logdir can carry the same rid on several hosts — those are
    *different requests*.  Ordering is therefore (pid, seq): within one
    host, read order is emit order (the causal rule of
    :func:`group_traces`); across hosts each segment renders contiguous
    with its pid, never interleaved by wall-clock.  Pass ``pid`` to
    restrict the view to one host's stream."""
    if records is None:
        records = read_all_records(logdir)
    # lifecycle instants via the ONE reqtrace parser (seq indexes into
    # `records`, the same space the span extraction below enumerates)
    events = [e for e in events_from_records(records)
              if e.get("rid") == rid]
    for seq, rec in enumerate(records):
        if rec.get("ph") != "X":
            continue
        args = rec.get("args", {})
        if rec.get("name") == "serve/decode" and rid in (
                args.get("rids") or []):
            events.append({"phase": "engine_decode",
                           "trace_id": None, "rid": rid,
                           "t": args.get("t", 0.0), "ts": rec.get("ts"),
                           "pid": rec.get("pid"),
                           "seq": seq, "batch": args.get("batch"),
                           "iteration": args.get("iteration")})
        elif (rec.get("name") == "serve/prefill"
              and args.get("rid") == rid):
            events.append({"phase": "engine_prefill",
                           "trace_id": None, "rid": rid,
                           "t": args.get("t", 0.0), "ts": rec.get("ts"),
                           "pid": rec.get("pid"),
                           "seq": seq, "tokens": args.get("tokens")})
    if pid is not None:
        events = [e for e in events if e.get("pid") == pid]
    events.sort(key=lambda e: (e.get("pid") or 0, e.get("seq", 0)))
    return events


def render_timeline(events: List[dict]) -> List[str]:
    """Human-readable lines for one request's timeline.  When the merged
    stream carries the rid on more than one host (per-engine rid spaces),
    every line is prefixed with its host so the segments read as the
    distinct requests they are."""
    if not events:
        return ["(no trace events for this request)"]
    lines = []
    tids = sorted({e["trace_id"] for e in events if e.get("trace_id")})
    lines.append(f"trace id(s): {', '.join(tids) or '(none)'}")
    pids = {e.get("pid") for e in events}
    multi_host = len(pids) > 1
    if multi_host:
        lines.append(f"hosts: {sorted(p for p in pids if p is not None)} "
                     f"(rids are per-engine — same rid on different "
                     f"hosts is a different request; --pid narrows)")
    for e in events:
        detail = " ".join(
            f"{k}={v}" for k, v in sorted(e.items())
            if k not in ("phase", "trace_id", "rid", "t", "ts", "pid",
                         "seq")
            and v is not None)
        host = f"p{e.get('pid', 0)}  " if multi_host else ""
        lines.append(f"  {host}t={e.get('t', 0.0):10.4f}s  "
                     f"{e['phase']:<16}" + (f" {detail}" if detail else ""))
    return lines
