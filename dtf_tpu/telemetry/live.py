"""Live introspection endpoint: a stdlib admin HTTP server for running
jobs.

The telemetry spine was strictly post-hoc — ``telemetry.json`` and the
span files are written at sync points and judged after exit.  A serving
fleet (or a week-long training run) is operated LIVE: an operator needs
to see the metric registry, the health of the loop, the last-N request
traces, and the SLO burn state *while the process runs* — and most
especially while it is wedged, since a wedged process never reaches its
next sync-point flush.

Endpoints (all JSON, GET only):

* ``/statz``  — one CONSISTENT snapshot of the metric registry (the
  registry lock is held across the whole read — no torn counter pairs)
  plus the goodput books;
* ``/healthz`` — liveness: the :class:`LivenessProbe` the driving loop
  beats every iteration/step, merged with any extra source (e.g. the
  ``resilience/health.py`` coordinator's published ``health.json``);
  HTTP 200 when live, 503 when the beat is stale — curl-able by a k8s
  probe as-is;
* ``/tracez`` — the request-trace flight recorder
  (:class:`~dtf_tpu.telemetry.reqtrace.TraceRing`): last-N completed
  request timelines, even when the process dies before any file flush;
* ``/slo``    — the :class:`~dtf_tpu.telemetry.slo.BurnRateMonitor`
  state (budgets, burn rates, alert history);
* ``/fleetz`` — the fleet plane's coordinator rollup
  (:meth:`~dtf_tpu.telemetry.fleet.FleetPlane.fleetz`): per-host books,
  sync-point skew/blame attribution, fleet goodput — one consistent
  fleet cut (per-host docs are atomic, the skew books read under the
  plane lock);
* ``/controlz`` — the self-tuning control plane
  (:meth:`~dtf_tpu.control.controller.KnobController.state`): every
  knob's value/default/bounds, the bounded mutation audit trail, and
  the controller loop's decision/rollback state — one consistent cut
  under the knob-registry lock;
* ``/memz``   — the device cost observatory
  (:meth:`~dtf_tpu.telemetry.costobs.CostObservatory.memz`): every
  captured CostCard (per-compile FLOP/byte/HBM attribution) plus the
  ``hbm/*`` + ``cost/*`` + KV-pool instruments as one consistent cut
  (cards under the observatory lock, instruments from one locked
  registry snapshot — same torn-pair discipline as ``/statz``).

Threading model — the same discipline as ``serve/frontend.py``: handler
threads NEVER touch the engine or trainer; every endpoint reads a
thread-safe structure (locked registry snapshot, ring snapshot, monitor
state, probe timestamps).  The server binds 127.0.0.1 by default and
runs on daemon threads; :func:`start_admin` is the idempotent
process-wide entry the CLIs use for ``--admin_port``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional

from dtf_tpu.telemetry import goodput as _goodput
from dtf_tpu.telemetry import registry as _registry


class LivenessProbe:
    """The loop's heartbeat into ``/healthz``: the driving thread calls
    :meth:`beat` once per iteration/step; the endpoint judges liveness
    by beat AGE on this process's monotonic clock (same observed-change
    discipline as resilience/health.py — a wall-clock step cannot fake
    or hide a wedge)."""

    def __init__(self, stale_after_s: float = 60.0):
        self.stale_after_s = float(stale_after_s)
        self._lock = threading.Lock()
        self._count: Optional[int] = None
        self._last: Optional[float] = None

    def beat(self, count: int) -> None:
        with self._lock:
            self._count = int(count)
            self._last = time.monotonic()

    def status(self) -> dict:
        with self._lock:
            count, last = self._count, self._last
        if last is None:
            # never beaten: the loop hasn't started — alive-but-booting
            return {"ok": True, "phase": "booting", "beats": None,
                    "age_s": None}
        age = time.monotonic() - last
        return {"ok": age <= self.stale_after_s, "phase": "running",
                "beats": count, "age_s": round(age, 3),
                "stale_after_s": self.stale_after_s}


def health_file_fn(health_dir: str) -> Callable[[], Optional[dict]]:
    """Extra-health source reading the ``resilience/health.py``
    coordinator's published ``health.json`` under ``health_dir`` (the
    file transport's snapshot) — wires multi-host liveness into
    ``/healthz`` without touching the monitor thread."""
    path = os.path.join(health_dir, "health.json")

    def read() -> Optional[dict]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    return read


class AdminServer:
    """See module docstring.  ``port=0`` binds an ephemeral port (tests);
    the bound port is ``self.port`` after :meth:`start`."""

    def __init__(self, port: int, *, host: str = "127.0.0.1",
                 probe: Optional[LivenessProbe] = None,
                 trace_ring=None, slo=None,
                 health_fn: Optional[Callable[[], Optional[dict]]] = None,
                 fleet_fn: Optional[Callable[[], dict]] = None,
                 control_fn: Optional[Callable[[], dict]] = None,
                 logdir: Optional[str] = None):
        self.host = host
        self._requested_port = int(port)
        self.probe = probe or LivenessProbe()
        self.trace_ring = trace_ring
        self.slo = slo
        self.health_fn = health_fn
        self.fleet_fn = fleet_fn
        self.control_fn = control_fn
        #: run logdir, when known — lets /incidentz fold in standing
        #: incidents found near the run (bench-ledger stall)
        self.logdir = logdir
        self._server = None
        self._thread = None

    # sources can be rebound between supervisor attempts (a fresh engine
    # per attempt, one server per process)
    def bind(self, *, probe=None, trace_ring=None, slo=None,
             health_fn=None, fleet_fn=None,
             control_fn=None, logdir=None) -> "AdminServer":
        if probe is not None:
            self.probe = probe
        if trace_ring is not None:
            self.trace_ring = trace_ring
        if slo is not None:
            self.slo = slo
        if health_fn is not None:
            self.health_fn = health_fn
        if fleet_fn is not None:
            self.fleet_fn = fleet_fn
        if control_fn is not None:
            self.control_fn = control_fn
        if logdir is not None:
            self.logdir = logdir
        return self

    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    # -- endpoint payloads (each reads only thread-safe state) --------------

    def _statz(self) -> tuple:
        # goodput first: snapshot() mirrors the buckets into registry
        # gauges, and the registry snapshot after it is ONE locked cut
        good = _goodput.get_tracker().snapshot()
        return 200, {"metrics": _registry.get_registry().snapshot(),
                     "goodput": good,
                     "written_unix": time.time()}

    def _healthz(self) -> tuple:
        doc = self.probe.status()
        if self.health_fn is not None:
            extra = self.health_fn()
            if extra is not None:
                doc["cluster"] = extra
        return (200 if doc.get("ok") else 503), doc

    def _tracez(self, query: Dict[str, str]) -> tuple:
        if self.trace_ring is None:
            return 200, {"traces": [], "note": "no request tracing armed"}
        n = None
        if query.get("n", "").isdigit():
            n = int(query["n"])
        traces = self.trace_ring.snapshot(n)
        return 200, {"capacity": self.trace_ring.capacity,
                     "count": len(traces), "traces": traces}

    def _slo(self) -> tuple:
        if self.slo is None:
            return 200, {"slo": None, "note": "no SLO monitor armed"}
        return 200, self.slo.state()

    def _fleetz(self) -> tuple:
        # fleet_fn is FleetPlane.fleetz: the skew books are read under
        # the plane lock and per-host docs are atomic at the mesh layer,
        # so this is one consistent fleet cut, never a torn mix.
        if self.fleet_fn is None:
            return 200, {"fleet": None, "note": "no fleet plane armed"}
        return 200, self.fleet_fn()

    def _controlz(self) -> tuple:
        # control_fn is KnobController.state: the knob map + audit
        # trail snapshot under the knob-registry lock — one consistent
        # cut, same torn-pair discipline as /statz.
        if self.control_fn is None:
            return 200, {"control": None,
                         "note": "no knob controller armed"}
        return 200, self.control_fn()

    def _memz(self) -> tuple:
        # the process-wide observatory is always present (cards may be
        # empty before the first compile — that IS the honest payload);
        # memz() reads cards under the observatory lock and instruments
        # from one locked registry snapshot.
        from dtf_tpu.telemetry import costobs
        return 200, costobs.get_observatory().memz()

    def _incidentz(self) -> tuple:
        # the process-wide incident ring (telemetry/diagnose.py): one
        # consistent cut built under the ring lock — live incidents with
        # their ranked suspects, plus any standing incidents (bench-
        # ledger stall) in scope of this run's logdir.
        from dtf_tpu.telemetry import diagnose
        return 200, diagnose.incidentz(self.logdir)

    def _endpoints(self) -> dict:
        """The root index: EVERY endpoint — the always-mounted ones and
        the conditionally-armed ones — with an armed/unarmed marker, so
        an operator sees what exists, not just what answers today."""
        return {
            "/statz": "armed",
            "/healthz": "armed",
            "/tracez": ("armed" if self.trace_ring is not None
                        else "unarmed"),
            "/slo": "armed" if self.slo is not None else "unarmed",
            "/fleetz": ("armed" if self.fleet_fn is not None
                        else "unarmed"),
            "/controlz": ("armed" if self.control_fn is not None
                          else "unarmed"),
            "/memz": "armed",
            "/incidentz": "armed",
        }

    # -- server -------------------------------------------------------------

    def start(self) -> "AdminServer":
        if self._server is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        admin = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):     # quiet by design
                pass

            def do_GET(self):
                from dtf_tpu import telemetry as tel
                from urllib.parse import parse_qsl, urlparse
                tel.counter("live/requests_total").inc()
                url = urlparse(self.path)
                query = dict(parse_qsl(url.query))
                try:
                    if url.path in ("/statz", "/statz/"):
                        code, doc = admin._statz()
                    elif url.path in ("/healthz", "/healthz/"):
                        code, doc = admin._healthz()
                    elif url.path in ("/tracez", "/tracez/"):
                        code, doc = admin._tracez(query)
                    elif url.path in ("/slo", "/slo/"):
                        code, doc = admin._slo()
                    elif url.path in ("/fleetz", "/fleetz/"):
                        code, doc = admin._fleetz()
                    elif url.path in ("/controlz", "/controlz/"):
                        code, doc = admin._controlz()
                    elif url.path in ("/memz", "/memz/"):
                        code, doc = admin._memz()
                    elif url.path in ("/incidentz", "/incidentz/"):
                        code, doc = admin._incidentz()
                    elif url.path == "/":
                        code, doc = 200, {"endpoints": admin._endpoints()}
                    else:
                        # 404-with-hint: name the nearest real endpoint —
                        # a typo'd scrape should cost one glance, not a
                        # source dive
                        import difflib
                        known = sorted(admin._endpoints())
                        near = difflib.get_close_matches(
                            url.path.rstrip("/"), known, n=1, cutoff=0.0)
                        code, doc = 404, {
                            "error": f"no such endpoint {url.path!r}",
                            "hint": (f"did you mean {near[0]!r}?"
                                     if near else None),
                            "endpoints": known}
                except Exception as exc:   # an endpoint must never crash
                    code, doc = 500, {"error": f"{type(exc).__name__}: "
                                               f"{exc}"}
                if code != 200:
                    tel.counter("live/errors_total").inc()
                body = json.dumps(doc, indent=1, sort_keys=True,
                                  default=str).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((self.host, self._requested_port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True, name="dtf-admin")
        self._thread.start()
        return self

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None


# -- process-wide singleton (the --admin_port entry) ------------------------

_ADMIN: Optional[AdminServer] = None


def start_admin(port: int, **sources) -> AdminServer:
    """Idempotent per process: the first call starts the server, later
    calls (a supervisor's next attempt constructing a fresh engine)
    rebind the data sources onto the SAME server — one admin window per
    process for its whole life."""
    global _ADMIN
    if _ADMIN is None:
        _ADMIN = AdminServer(port, **sources).start()
    else:
        _ADMIN.bind(**sources)
    return _ADMIN


def get_admin() -> Optional[AdminServer]:
    return _ADMIN


def stop_admin() -> None:
    global _ADMIN
    if _ADMIN is not None:
        _ADMIN.close()
        _ADMIN = None
