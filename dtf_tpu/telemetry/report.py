"""Run-report CLI: one post-mortem from everything a run left on disk.

    python -m dtf_tpu.telemetry.report <logdir> [--top N] [--json]
        [--profile_dir DIR] [--export-trace OUT.json] [--check [--tol PCT]]
    python -m dtf_tpu.telemetry.report --explain <logdir_a> <logdir_b>
        # step-time regression explainer: phase-by-phase + card-by-card
        # diff of two runs' cost observatories (telemetry/costobs.py),
        # ranked attribution of byte/flop growth per compile site
    python -m dtf_tpu.telemetry.report <logdir> --explain
        # single-logdir form: just the sharding-plan audit — the
        # recorded plan.json's predicted peak HBM vs the peak the cost
        # observatory measured (parallel/planner.py)

Merges ``telemetry.json`` (goodput books + instrument snapshot),
``metrics.csv`` (attempt-deduplicated), ``spans.p*.jsonl``,
``health.json`` and — when an XLA profile is present — the device-op
summary, into sections: goodput breakdown, throughput/MFU, event
timeline, per-host step-time overlay, top spans, top XLA ops.

``--check`` is the CI gate: exit non-zero unless the report renders and
the goodput components sum to measured wall-clock within ``--tol``
percent (default 10) — the acceptance contract for the telemetry lane.
``--export-trace`` additionally writes the merged Chrome-trace JSON for
Perfetto; on a fleet logdir (telemetry/fleet.py) every host's stream is
re-based onto the reference clock first.  ``--fleet`` requires the
fleet section, and ``--max_skew_ms`` / ``--min_fleet_goodput`` /
``--max_blame_frac`` gate the cross-host skew attribution.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from dtf_tpu.telemetry.goodput import CATEGORIES
from dtf_tpu.telemetry.spans import find_span_files, read_spans


def load_metrics_csv(path: str) -> List[Tuple[int, int, str, float]]:
    """``[(step, attempt, metric, value)]``; legacy 3-column rows (written
    before the attempt column existed) read as attempt 0."""
    rows = []
    with open(path, newline="") as f:
        for rec in csv.reader(f):
            if not rec or rec[0] == "step":
                continue
            try:
                step, metric, value = int(rec[0]), rec[1], float(rec[2])
                attempt = int(rec[3]) if len(rec) > 3 else 0
            except (ValueError, IndexError):
                continue               # torn tail from a hard kill
            rows.append((step, attempt, metric, value))
    return rows


def dedupe_latest_attempt(rows) -> List[Tuple[int, int, str, float]]:
    """A restart resumes from the last checkpoint, so attempts overlap in
    step range; for each (step, metric) the LATEST attempt's row is the
    one that fed the surviving trajectory."""
    best: Dict[Tuple[int, str], Tuple[int, float]] = {}
    for step, attempt, metric, value in rows:
        key = (step, metric)
        if key not in best or attempt >= best[key][0]:
            best[key] = (attempt, value)
    return sorted((s, a, m, v) for (s, m), (a, v) in best.items())


def summarize_spans(paths: List[str]) -> Tuple[List[dict], List[dict]]:
    """(per-name aggregate rows sorted by total time, instant events)."""
    return summarize_span_records(
        [rec for path in paths for rec in read_spans(path)])


def summarize_span_records(records: List[dict]
                           ) -> Tuple[List[dict], List[dict]]:
    """:func:`summarize_spans` over already-parsed records — the report
    parses every span file exactly once and shares the stream with the
    request-trace reader."""
    agg = defaultdict(lambda: [0, 0.0])     # name -> [count, total_us]
    instants = []
    for rec in records:
        if rec.get("ph") == "X":
            a = agg[rec["name"]]
            a[0] += 1
            a[1] += rec.get("dur", 0.0)
        elif rec.get("ph") == "i":
            instants.append(rec)
    rows = [{"name": n, "count": c, "total_s": t / 1e6,
             "mean_ms": t / 1e3 / c if c else 0.0}
            for n, (c, t) in agg.items()]
    rows.sort(key=lambda r: -r["total_s"])
    instants.sort(key=lambda r: r.get("ts", 0.0))
    return rows, instants


def build_report(logdir: str, profile_dir: Optional[str] = None,
                 top: int = 10) -> dict:
    """Everything the printer / --json / --check consume, as one dict."""
    out: dict = {"logdir": os.path.abspath(logdir)}

    tpath = os.path.join(logdir, "telemetry.json")
    if os.path.exists(tpath):
        try:
            with open(tpath) as f:
                out["telemetry"] = json.load(f)
        except ValueError as exc:
            out["telemetry_error"] = str(exc)

    cpath = os.path.join(logdir, "metrics.csv")
    if os.path.exists(cpath):
        raw = load_metrics_csv(cpath)
        rows = dedupe_latest_attempt(raw)
        out["attempts"] = sorted({a for _, a, _, _ in raw})
        out["metrics_rows"] = len(rows)
        out["duplicate_rows_dropped"] = len(raw) - len(rows)
        steps = [s for s, _, m, _ in rows if m == "cost"]
        costs = [v for _, _, m, v in rows if m == "cost"]
        if steps:
            out["steps"] = {"first": steps[0], "last": steps[-1],
                            "final_cost": costs[-1]}
        out["events"] = [(s, m[len("event/"):], v) for s, _, m, v in rows
                         if m.startswith("event/")]
        hosts = defaultdict(list)
        for s, _, m, v in rows:
            if m.startswith("health/step_ms_p"):
                hosts[int(m.rsplit("p", 1)[1])].append(v)
        out["per_host_step_ms"] = {
            k: {"mean": sum(v) / len(v), "last": v[-1], "n": len(v)}
            for k, v in sorted(hosts.items())}

    span_files = find_span_files(logdir)
    records: List[dict] = []
    if span_files:
        from dtf_tpu.telemetry import reqtrace
        records = [rec for p in span_files for rec in read_spans(p)]
        rows, instants = summarize_span_records(records)
        out["span_files"] = [os.path.basename(p) for p in span_files]
        out["spans"] = rows[:top]
        out["instants"] = [
            {"name": r["name"], "ts": r.get("ts"), "pid": r.get("pid"),
             "args": r.get("args", {})} for r in instants
            # request lifecycle events have their own section/gate; the
            # shared instant timeline would drown in them
            if not r["name"].startswith("reqtrace/")]
        events = reqtrace.events_from_records(records)
        if events:
            traces = reqtrace.group_traces(events)
            comp = reqtrace.completeness(traces)
            out["request_traces"] = {"total": len(traces), **comp}

    # Fleet plane (telemetry/fleet.py): span-based, offset-corrected
    # skew attribution + the coordinator's rollup cut.  Shares the one
    # parsed record stream with the span summary above.
    fleet_rollup = None
    fpath = os.path.join(logdir, "fleet.json")
    if os.path.exists(fpath):
        try:
            with open(fpath) as f:
                fleet_rollup = json.load(f)
        except ValueError:
            pass
    if span_files or fleet_rollup:
        from dtf_tpu.telemetry import fleet as _fleet
        section = _fleet.fleet_report(records=records,
                                      rollup_doc=fleet_rollup)
        if section:
            out["fleet"] = section

    # Incident plane (telemetry/anomaly.py + telemetry/diagnose.py):
    # every anomaly/* instant in the shared record stream is correlated
    # against the other planes' evidence instants — the SAME rule the
    # live /incidentz ring applies, re-run post-hoc so the two verdicts
    # cannot drift.  Standing incidents (bench-ledger stall) attach even
    # when the run itself left no spans.
    from dtf_tpu.telemetry import diagnose as _diagnose
    if records:
        out["incidents"] = _diagnose.diagnose_records(records)
    standing = _diagnose.ledger_standing_incidents(logdir)
    if standing:
        out.setdefault("incidents", {})["standing"] = standing

    hpath = os.path.join(logdir, "health.json")
    if os.path.exists(hpath):
        try:
            with open(hpath) as f:
                out["health"] = json.load(f)
        except ValueError:
            pass

    pdir = profile_dir or logdir
    if os.path.isdir(os.path.join(pdir, "plugins", "profile")):
        from dtf_tpu.utils.profiling import summarize_trace
        try:
            out["xla_ops"] = [{"name": n, "total_s": s}
                              for n, s in summarize_trace(pdir, top=top)]
        except Exception as exc:       # a summary must never fail a report
            out["xla_error"] = str(exc)
    return out


def _metric_value(report: dict, name: str, default=None):
    m = report.get("telemetry", {}).get("metrics", {}).get(name)
    if m is None or m.get("value") is None:
        return default
    return float(m["value"])


def check_gates(report: dict, *, min_goodput: Optional[float] = None,
                min_mfu: Optional[float] = None,
                max_rollbacks: Optional[int] = None,
                min_examples_per_s: Optional[float] = None,
                min_tokens_per_s: Optional[float] = None,
                max_final_cost: Optional[float] = None,
                min_goodput_qps: Optional[float] = None,
                max_ttft_p99_ms: Optional[float] = None,
                max_tpot_p99_ms: Optional[float] = None,
                min_trace_complete_frac: Optional[float] = None,
                max_control_rollbacks: Optional[int] = None,
                max_skew_ms: Optional[float] = None,
                min_fleet_goodput: Optional[float] = None,
                max_blame_frac: Optional[float] = None,
                max_hbm_frac: Optional[float] = None,
                max_compiles: Optional[float] = None,
                min_attribution_frac: Optional[float] = None,
                max_wire_bytes_per_step: Optional[float] = None,
                min_prefix_hit_rate: Optional[float] = None,
                ) -> Tuple[bool, List[str]]:
    """Threshold gates over a built report — THE gate implementation the
    ``report --check`` CLI flags, the scenario matrix runner, and the
    full-suite lanes share.  Every threshold is optional (None = not
    gated); returns ``(all_ok, verdict lines)``, one line per active
    gate.  A gated quantity that is MISSING from the report fails its
    gate (absence of evidence is a failure, not a pass):

    * ``min_goodput`` — goodput fraction floor (``productive_fraction``
      from the goodput books, 0..1);
    * ``min_mfu`` — MFU floor in percent of chip peak (``mfu/pct_peak``;
      unknown-peak backends like the CPU sim should gate on the
      throughput floors instead);
    * ``max_rollbacks`` — ceiling on ``checkpoint/rollbacks_total``
      (absent counter = 0: a run that never rolled back passes);
    * ``min_examples_per_s`` / ``min_tokens_per_s`` — throughput floors
      (``throughput/*`` gauges);
    * ``max_final_cost`` — convergence: the metrics.csv final cost
      (latest attempt) must be at or under the pinned target;
    * ``min_goodput_qps`` / ``max_ttft_p99_ms`` / ``max_tpot_p99_ms``
      — the SERVING gates (telemetry.json's ``serving`` section,
      written by the engine): goodput-QPS floor (completed requests
      that met the SLO TTFT budget per second of makespan), p99 TTFT
      ceiling, and p99 TPOT ceiling (the streaming-cadence gate the
      speculative-decoding lane arms) — the scenario matrix's serve
      cell gates on these, so serving robustness is CI-judged exactly
      like training;
    * ``min_trace_complete_frac`` — observability gate: of requests
      that COMPLETED, the fraction whose per-request trace reconstructs
      the full admission->prefill->first_token->completion chain from
      the span files (telemetry/reqtrace.py; drain/replay folded in by
      trace-id continuity).  No reqtrace events on disk = not measured
      = FAIL, same absence rule as every other gate;
    * ``max_control_rollbacks`` — ceiling on the self-tuning control
      plane's snap-backs (``control/rollback_total``, dtf_tpu/control).
      NO absent-counter default on purpose: the controller registers
      the counter eagerly when armed, so an absent counter means the
      run this gate was pinned for never armed its controller — a
      config regression, not a calm run, and it FAILS.  (Contrast
      ``max_rollbacks`` above, where absent legitimately means zero);
    * ``max_skew_ms`` / ``min_fleet_goodput`` / ``max_blame_frac`` — the
      FLEET gates (telemetry/fleet.py; report section ``fleet``):
      ceiling on the median per-barrier arrival skew (offset-corrected),
      floor on the fleet's joint productive fraction (sum of productive
      over sum of wall across every reporting host, from the
      coordinator rollup), and ceiling on any single host's share of
      last-arrivals (a fleet where one host eats the blame budget is a
      straggler diagnosis, not noise);
    * ``max_hbm_frac`` / ``max_compiles`` — the DEVICE COST gates
      (telemetry/costobs.py): ceiling on the run's live-HBM high-water
      as a fraction of chip capacity (``hbm/frac``, measured off
      ``jax.live_arrays()`` against the roofline table's capacity —
      the CPU sim's pinned synthetic 4 GiB keeps it deterministic),
      and ceiling on captured compiles (``cost/compiles_total`` — a
      geometry churn that recompiles every iteration is a perf bug the
      wall clock alone misattributes).  A run that never captured (no
      observatory wired) FAILS both: absence is falsifiable.
    * ``min_attribution_frac`` — the INCIDENT gate (telemetry/anomaly.py
      + telemetry/diagnose.py; report section ``incidents``): floor on
      the fraction of detected anomalies that are correctly attributed.
      With chaos evidence in the stream the bar is strict — only an
      incident whose TOP-ranked suspect is the injected fault counts
      (a correlator that blames an innocent plane fails).  Chaos fired
      but ZERO anomalies detected leaves the fraction None =
      not-measured = FAIL: injected-but-undetected is the detector's
      falsifiability failure, not a calm run.  Without chaos, attributed
      means 'has at least one suspect', and zero anomalies passes
      vacuously (frac 1.0) — the chaos-off twin's contract;
    * ``max_wire_bytes_per_step`` — the GRADIENT-WIRE gate (ISSUE 19):
      ceiling on the ``comm/wire_bytes`` gauge (per-device scatter-leg
      payload per step).  The int8_ring scenario cell pins it between
      the ring wire and the one-shot int8 wire, so a run that silently
      fell back to a fatter wire (one-shot int8, bf16, f32) fails even
      if it converges.  No absent-gauge default: a run that never
      recorded its wire (no grad-sync path armed) FAILS.
    * ``min_prefix_hit_rate`` — the PREFIX-CACHE gate (ISSUE 20): floor
      on the serving summary's ``prefix_hit_rate`` (matched prefix
      blocks over probed blocks at admission).  No absent-key default:
      the engine only writes the key when its prefix cache is armed, so
      an absent rate means the run this gate was pinned for served
      cold — a config regression, and it FAILS (same falsifiability
      rule as ``max_control_rollbacks``).
    """
    lines: List[str] = []
    ok = True

    def gate(name, value, bound, at_most: bool):
        nonlocal ok
        if value is None:
            ok = False
            lines.append(f"gate {name}: FAIL — not measured "
                         f"(bound {bound:g})")
            return
        passed = value <= bound if at_most else value >= bound
        ok = ok and passed
        op = "<=" if at_most else ">="
        lines.append(f"gate {name}: {'OK' if passed else 'FAIL'} — "
                     f"{value:g} {op} {bound:g}")

    if min_goodput is not None:
        frac = report.get("telemetry", {}).get("goodput", {}) \
            .get("productive_fraction")
        gate("min_goodput", None if frac is None else float(frac),
             min_goodput, at_most=False)
    if min_mfu is not None:
        gate("min_mfu", _metric_value(report, "mfu/pct_peak"), min_mfu,
             at_most=False)
    if max_rollbacks is not None:
        gate("max_rollbacks",
             _metric_value(report, "checkpoint/rollbacks_total", 0.0),
             float(max_rollbacks), at_most=True)
    if min_examples_per_s is not None:
        gate("min_examples_per_s",
             _metric_value(report, "throughput/examples_per_s"),
             min_examples_per_s, at_most=False)
    if min_tokens_per_s is not None:
        gate("min_tokens_per_s",
             _metric_value(report, "throughput/tokens_per_s"),
             min_tokens_per_s, at_most=False)
    if max_final_cost is not None:
        cost = report.get("steps", {}).get("final_cost")
        gate("max_final_cost", None if cost is None else float(cost),
             max_final_cost, at_most=True)
    serving = report.get("telemetry", {}).get("serving", {})
    if min_goodput_qps is not None:
        v = serving.get("goodput_qps")
        gate("min_goodput_qps", None if v is None else float(v),
             min_goodput_qps, at_most=False)
    if max_ttft_p99_ms is not None:
        v = serving.get("ttft_ms_p99")
        gate("max_ttft_p99_ms", None if v is None else float(v),
             max_ttft_p99_ms, at_most=True)
    if max_tpot_p99_ms is not None:
        v = serving.get("tpot_ms_p99")
        gate("max_tpot_p99_ms", None if v is None else float(v),
             max_tpot_p99_ms, at_most=True)
    if min_prefix_hit_rate is not None:
        # absent = prefix cache never armed on this run = FAIL
        v = serving.get("prefix_hit_rate")
        gate("min_prefix_hit_rate", None if v is None else float(v),
             min_prefix_hit_rate, at_most=False)
    if min_trace_complete_frac is not None:
        v = report.get("request_traces", {}).get("complete_frac")
        gate("min_trace_complete_frac", None if v is None else float(v),
             min_trace_complete_frac, at_most=False)
    if max_control_rollbacks is not None:
        # no default: an absent counter = controller never armed = FAIL
        gate("max_control_rollbacks",
             _metric_value(report, "control/rollback_total"),
             float(max_control_rollbacks), at_most=True)
    fleet = report.get("fleet", {})
    att = fleet.get("attribution", {})
    if max_skew_ms is not None:
        v = att.get("skew_ms_p50")
        gate("max_skew_ms", None if v is None else float(v),
             max_skew_ms, at_most=True)
    if min_fleet_goodput is not None:
        v = fleet.get("rollup", {}).get("goodput", {}) \
            .get("productive_fraction")
        gate("min_fleet_goodput", None if v is None else float(v),
             min_fleet_goodput, at_most=False)
    if max_blame_frac is not None:
        shares = [h.get("blame_frac")
                  for h in att.get("per_host", {}).values()
                  if h.get("blame_frac") is not None]
        gate("max_blame_frac", max(shares) if shares else None,
             max_blame_frac, at_most=True)
    if max_hbm_frac is not None:
        gate("max_hbm_frac", _metric_value(report, "hbm/frac"),
             max_hbm_frac, at_most=True)
    if max_compiles is not None:
        gate("max_compiles",
             _metric_value(report, "cost/compiles_total"),
             float(max_compiles), at_most=True)
    if min_attribution_frac is not None:
        # None here covers BOTH no-incidents-section (detector never
        # armed) and chaos-fired-zero-anomalies (injected-but-
        # undetected); the shared not-measured rule fails either way
        v = report.get("incidents", {}).get("attribution_frac")
        gate("min_attribution_frac", None if v is None else float(v),
             min_attribution_frac, at_most=False)
    if max_wire_bytes_per_step is not None:
        # no default: an absent gauge = no gradient wire measured = FAIL
        gate("max_wire_bytes_per_step",
             _metric_value(report, "comm/wire_bytes"),
             max_wire_bytes_per_step, at_most=True)
    return ok, lines


def check_goodput(report: dict, tol_pct: float = 10.0
                  ) -> Tuple[bool, str]:
    """The acceptance arithmetic: accounted categories sum to measured
    wall-clock within the tolerance."""
    good = report.get("telemetry", {}).get("goodput")
    if not good:
        return False, "no goodput section in telemetry.json"
    wall = float(good.get("wall_s", 0.0))
    if wall <= 0:
        return False, f"non-positive wall_s ({wall})"
    total = sum(float(good.get(f"{c}_s", 0.0)) for c in CATEGORIES)
    gap_pct = abs(wall - total) / wall * 100.0
    verdict = (f"accounted {total:.2f}s of {wall:.2f}s wall "
               f"({100 - gap_pct:.1f}% covered, tol {tol_pct:g}%)")
    return gap_pct <= tol_pct, verdict


def _fmt_goodput(good: dict, lines: List[str]) -> None:
    wall = float(good.get("wall_s", 0.0)) or 1.0
    lines.append("Goodput breakdown")
    for c in CATEGORIES:
        s = float(good.get(f"{c}_s", 0.0))
        if s <= 0 and c not in ("productive",):
            continue
        bar = "#" * min(int(round(40 * s / wall)), 40)
        lines.append(f"  {c:<11} {s:9.2f}s  {s / wall * 100:5.1f}%  {bar}")
    lines.append(f"  {'wall_clock':<11} {float(good.get('wall_s', 0)):9.2f}s")
    frac = good.get("productive_fraction")
    if frac is not None:
        lines.append(f"  goodput (productive/wall): "
                     f"{float(frac) * 100:.1f}%")


def render(report: dict, top: int = 10) -> str:
    lines = [f"== dtf_tpu run report: {report['logdir']} =="]
    tel = report.get("telemetry", {})
    if tel.get("goodput"):
        _fmt_goodput(tel["goodput"], lines)
    metrics = tel.get("metrics", {})
    thr = {n: m.get("value") for n, m in metrics.items()
           if n.startswith(("throughput/", "mfu/")) and m.get("value")}
    if thr:
        lines.append("Throughput / MFU")
        for n in sorted(thr):
            lines.append(f"  {n:<28} {thr[n]:12.5g}")
    # Input pipeline + compile reuse: how much host data time the device
    # prefetcher left on the hot path (0 stall = fully overlapped) and
    # whether the persistent compile cache actually saved this attempt a
    # rebuild.  Values may legitimately be 0 — that IS the good reading —
    # so presence is keyed on the instrument, not on a nonzero value.
    pipe = {n: m.get("value") for n, m in metrics.items()
            if n in ("data/prefetch_depth", "data/prefetch_stall_s",
                     "compile/cache_hit", "compile/cache_miss",
                     "compile/aot_s")
            and m.get("value") is not None}
    if pipe:
        lines.append("Input pipeline / compile")
        for n in sorted(pipe):
            lines.append(f"  {n:<28} {pipe[n]:12.5g}")
    # Gradient sync (comm/* from parallel/grad_sync.py): which weight-
    # update strategy ran, its wire payload, and the MEASURED per-device
    # optimizer-state bytes — the zero1 (N-1)/N memory claim, readable off
    # the report.  The strategy gauge is an index into
    # grad_sync.STRATEGIES; the literal below mirrors it so this module
    # stays jax-free (pinned by tests/test_grad_sync.py).
    comm = {n: m.get("value") for n, m in metrics.items()
            if n.startswith("comm/") and m.get("value") is not None}
    if comm:
        lines.append("Gradient sync")
        strategies = ("dense", "zero1", "zero1_overlap")
        idx = comm.pop("comm/strategy_idx", None)
        if idx is not None and 0 <= int(idx) < len(strategies):
            lines.append(f"  {'strategy':<28} {strategies[int(idx)]:>12}")
        # mirror of grad_sync.WIRE_DTYPES (same jax-free pinning rule)
        wire_dtypes = ("f32", "bf16", "int8", "int8_ring")
        widx = comm.pop("comm/wire_dtype_idx", None)
        if widx is not None and 0 <= int(widx) < len(wire_dtypes):
            lines.append(f"  {'wire dtype':<28} "
                         f"{wire_dtypes[int(widx)]:>12}")
        for n in sorted(comm):
            lines.append(f"  {n:<28} {comm[n]:12.5g}")
    # Serving (dtf_tpu/serve): the SLO/goodput section — per-request
    # TTFT/TPOT percentiles and goodput QPS come from the engine's
    # summary (telemetry.json "serving"); the serve/* instruments below
    # it are the raw lifecycle counters.  Keyed on presence, not on
    # nonzero values (0 rejected IS the good reading).
    serving = tel.get("serving")
    srv = {}
    for n, m in metrics.items():
        if not n.startswith("serve/"):
            continue
        if m.get("type") == "histogram":
            # never print a bare count under an ms-suffixed name — it
            # reads as a latency; show the mean and the sample count
            if m.get("count"):
                srv[n + "_mean"] = m["sum"] / m["count"]
                srv[n + "_count"] = m["count"]
        elif m.get("value") is not None:
            srv[n] = m["value"]
    if serving or srv:
        lines.append("Serving (SLO / goodput)")
        if serving:
            order = ("mode", "completed", "rejected", "shed", "cancelled",
                     "failed", "drained_unfinished", "degraded",
                     "deadline_requests_completed", "deadline_violations",
                     "completed_qps",
                     "goodput_qps", "slo_ttft_ms", "slo_attainment",
                     "ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50",
                     "tpot_ms_p99", "makespan_s", "tokens_out",
                     "prefill_calls", "spec_k", "spec_proposed",
                     "spec_accepted", "spec_acceptance",
                     "kv_blocks_peak", "kv_blocks_total",
                     "kv_blocks_in_use", "kv_pool_frac_peak",
                     "kv_hot_prefix_blocks", "kv_cached_blocks",
                     "prefix_cache", "prefix_lookups",
                     "prefix_probed_blocks", "prefix_hit_blocks",
                     "prefix_hit_rate")
            for k in order:
                if k in serving and serving[k] is not None:
                    v = serving[k]
                    lines.append(f"  {k:<28} "
                                 + (f"{v:>12}" if isinstance(v, str)
                                    else f"{v:12.5g}"))
            reasons = serving.get("shed_reasons")
            if reasons:
                detail = " ".join(f"{k}={v}"
                                  for k, v in sorted(reasons.items()))
                lines.append(f"  {'shed_reasons':<28} {detail}")
            bo = serving.get("brownout")
            if bo:
                lines.append(
                    f"  {'brownout':<28} level {bo.get('level')} "
                    f"({bo.get('level_name')}), p99 ewma "
                    f"{bo.get('p99_ttft_ewma_ms'):g} ms, "
                    f"{bo.get('transitions')} transition(s)")
            slo = serving.get("slo")
            if slo:
                for oname, o in sorted(
                        slo.get("objectives", {}).items()):
                    bad = o.get("bad_frac")
                    lines.append(
                        f"  {'slo/' + oname:<28} target {o.get('target')}"
                        f"  bad_frac "
                        + ("n/a" if bad is None else f"{bad:.4f}")
                        + f"  alerts fast={o.get('alerts_fast')} "
                          f"slow={o.get('alerts_slow')}")
            # Control plane (dtf_tpu/control): final knob positions vs
            # their pinned defaults + the loop's decision/rollback books
            ctl = serving.get("control")
            if ctl:
                lines.append(
                    f"  {'control':<28} {ctl.get('decisions', 0)} "
                    f"decision(s), {ctl.get('sets', 0)} knob set(s), "
                    f"{ctl.get('rollbacks', 0)} rollback(s)"
                    + (f" {ctl.get('rollback_reasons')}"
                       if ctl.get("rollback_reasons") else "")
                    + ("" if ctl.get("at_defaults")
                       else "  [knobs OFF defaults]"))
                defaults = ctl.get("knob_defaults") or {}
                for kname, v in sorted((ctl.get("knobs") or {}).items()):
                    d = defaults.get(kname)
                    mark = ("" if d is None or v == d
                            else f"  (default {d:g})")
                    lines.append(f"  {'control/' + kname:<28} "
                                 f"{v:12.5g}{mark}")
        for n in sorted(srv):
            lines.append(f"  {n:<28} {srv[n]:12.5g}")
    # Device cost plane (telemetry/costobs.py): the per-site compile
    # FLOP/byte/HBM rollup plus the roofline the cards were classified
    # against.  None values print as n/a — a backend that reported
    # nothing must read as "not measured", never as zero.
    cost = tel.get("cost")
    if cost:
        lines.append("Device cost (telemetry/costobs.py)")
        rl = cost.get("roofline")
        if rl:
            lines.append(
                f"  {'roofline':<28} {rl.get('kind')}"
                f"  ridge {rl.get('ridge_flops_per_byte'):.3g} flops/B"
                f"  capacity {rl.get('hbm_capacity_bytes'):.3g} B"
                + ("  (synthetic)" if rl.get("synthetic") else ""))
        _na = lambda v, fmt="{:.4g}": ("n/a" if v is None
                                       else fmt.format(v))
        lines.append(f"  {'cards / compiles':<28} "
                     f"{cost.get('cards', 0)} / {cost.get('compiles', 0)}")
        if cost.get("live_bytes_peak") is not None:
            lines.append(f"  {'live_bytes_peak':<28} "
                         f"{_na(cost['live_bytes_peak'])}")
        for site, s in sorted((cost.get("sites") or {}).items()):
            lines.append(
                f"  {site:<28} cards {s['cards']:>3}  compiles "
                f"{s['compiles']:>4}  flops {_na(s['flops_total']):>9}  "
                f"bytes {_na(s['bytes_total']):>9}  peak_hbm "
                f"{_na(s['peak_hbm_bytes']):>9}  "
                f"(compute {s['compute_bound']}/memory "
                f"{s['memory_bound']})")
    rt = report.get("request_traces")
    if rt:
        frac = rt.get("complete_frac")
        lines.append("Request traces (telemetry/reqtrace.py)")
        lines.append(f"  {'traces':<28} {rt.get('total', 0):12d}")
        lines.append(f"  {'completed':<28} {rt.get('completed', 0):12d}")
        lines.append(f"  {'chain_complete':<28} {rt.get('complete', 0):12d}")
        lines.append(f"  {'complete_frac':<28} "
                     + ("         n/a" if frac is None else f"{frac:12.4f}"))
        for inc in rt.get("incomplete", [])[:5]:
            lines.append(f"  incomplete rid={inc.get('rid')} "
                         f"trace={inc.get('trace_id')}: "
                         f"{', '.join(inc.get('gaps', []))}")
    inc = report.get("incidents")
    if inc and (inc.get("anomalies") or inc.get("standing")
                or inc.get("chaos_fired")):
        lines.append("Incidents (telemetry/anomaly.py + diagnose.py)")
        frac = inc.get("attribution_frac")
        lines.append(
            f"  {'anomalies':<28} {inc.get('anomalies', 0):12d}")
        lines.append(
            f"  {'attributed':<28} {inc.get('attributed', 0):12d}"
            + (f"  (frac {frac:.4f})" if frac is not None else
               "  (frac n/a — chaos fired, nothing detected)"))
        planes = inc.get("top_plane_counts") or {}
        if planes:
            detail = " ".join(f"{k}={v}"
                              for k, v in sorted(planes.items()))
            lines.append(f"  {'top suspect planes':<28} {detail}")
        if inc.get("unattributed"):
            lines.append(f"  {'UNATTRIBUTED':<28} "
                         f"{inc['unattributed']:12d}  "
                         f"(--diagnose exits 1 on these)")
        for st in inc.get("standing", []):
            lines.append(f"  standing: {st.get('summary')}")
        lines.append("  (full ranked suspects: report --diagnose)")
    fleet = report.get("fleet")
    if fleet:
        lines.append("Fleet (telemetry/fleet.py)")
        att = fleet.get("attribution")
        offs = fleet.get("offsets_s", {})
        if offs:
            est = fleet.get("offset_estimated", {})
            detail = " ".join(
                f"p{p}={float(o) * 1e3:+.3f}ms"
                + ("" if est.get(str(p), est.get(p, True)) else "(assumed)")
                for p, o in sorted(offs.items(), key=lambda kv: str(kv[0])))
            lines.append(f"  {'clock offsets':<28} {detail}")
        if att:
            src = fleet.get("attribution_source")
            lines.append(f"  {'barriers':<28} {att['barriers']:12d}"
                         f"   hosts {att.get('hosts')}"
                         + (f"   (source: {src})" if src else ""))

            def _ms(v):
                return "       n/a" if v is None else f"{v:10.3f}"

            lines.append(f"  {'skew_ms p50/mean/max':<28} "
                         f"{_ms(att.get('skew_ms_p50'))} /"
                         f"{_ms(att.get('skew_ms_mean'))} /"
                         f"{_ms(att.get('skew_ms_max'))}")
            for p, h in sorted(att.get("per_host", {}).items(),
                               key=lambda kv: -kv[1]["blame_frac"]):
                drift = h.get("drift_ms_per_step")
                cost = h.get("cost_pct")
                lines.append(
                    f"  p{p}: last-arrival {h['last_arrivals']:>4}x "
                    f"({h['blame_frac'] * 100:5.1f}%)  "
                    f"cost {h['lateness_s']:8.3f}s"
                    + (f" ({cost:.2f}% of fleet window)"
                       if cost is not None else "")
                    + (f"  drift {drift:+.2f} ms/step"
                       if drift is not None else ""))
        roll = fleet.get("rollup")
        if roll:
            g = roll.get("goodput") or {}
            frac = g.get("productive_fraction")
            lines.append(
                f"  rollup: {len(roll.get('hosts_reporting', []))} host(s) "
                f"reporting, fleet goodput "
                + ("n/a" if frac is None else f"{float(frac) * 100:.1f}%")
                + (f" (weakest host "
                   f"{float(g['min_host_fraction']) * 100:.1f}%)"
                   if g.get("min_host_fraction") is not None else ""))
    if "steps" in report:
        s = report["steps"]
        lines.append(f"Steps: {s['first']}..{s['last']}  "
                     f"final cost {s['final_cost']:.4f}  "
                     f"(attempts: {report.get('attempts', [0])}, "
                     f"{report.get('duplicate_rows_dropped', 0)} overlapping "
                     f"rows superseded by the latest attempt)")
    if report.get("events") or report.get("instants"):
        lines.append("Event timeline")
        for step, name, value in report.get("events", []):
            lines.append(f"  step {step:>6}  event/{name} (count {value:g})")
        for rec in report.get("instants", []):
            args = rec.get("args") or {}
            detail = " ".join(f"{k}={v}" for k, v in sorted(args.items()))
            lines.append(f"  p{rec.get('pid', 0)}  {rec['name']}"
                         + (f"  {detail}" if detail else ""))
    if report.get("per_host_step_ms"):
        lines.append("Per-host step time (ms, from health/step_ms_p*)")
        for k, st in report["per_host_step_ms"].items():
            lines.append(f"  p{k}: mean {st['mean']:8.2f}  "
                         f"last {st['last']:8.2f}  ({st['n']} samples)")
    if report.get("health"):
        h = report["health"]
        lines.append(f"Health snapshot: {json.dumps(h, sort_keys=True)[:200]}")
    if report.get("spans"):
        lines.append(f"Top spans (host-side, by total time; "
                     f"{', '.join(report.get('span_files', []))})")
        for r in report["spans"][:top]:
            lines.append(f"  {r['total_s']:9.3f}s  {r['count']:>6}x  "
                         f"mean {r['mean_ms']:8.3f}ms  {r['name']}")
    if report.get("xla_ops"):
        lines.append("Top XLA device ops (from the profiler trace)")
        for r in report["xla_ops"][:top]:
            lines.append(f"  {r['total_s']:9.3f}s  {r['name']}")
    elif report.get("xla_error"):
        lines.append(f"XLA trace summary unavailable: {report['xla_error']}")
    if len(lines) == 1:
        lines.append("(nothing found: no telemetry.json / metrics.csv / "
                     "spans under this logdir)")
    return "\n".join(lines)


def render_diagnose(doc: dict, logdir: str) -> List[str]:
    """Text for ``report --diagnose``: the attribution summary, any
    standing incidents, then one merged timeline per anomaly — every
    qualifying suspect at its offset before the fire, top-ranked marked.
    The exit-1 rule (an anomaly with NO suspect) is the caller's."""
    lines = [f"== incident diagnosis: {os.path.abspath(logdir)} =="]
    frac = doc.get("attribution_frac")
    lines.append(
        f"anomalies {doc.get('anomalies', 0)}  "
        f"attributed {doc.get('attributed', 0)}  "
        + ("attribution_frac n/a (chaos fired, NOTHING detected — "
           "injected-but-undetected)" if frac is None
           else f"attribution_frac {frac:.4f}")
        + f"  chaos_evidence={'yes' if doc.get('chaos_fired') else 'no'}")
    planes = doc.get("top_plane_counts") or {}
    if planes:
        lines.append("top suspect planes: "
                     + " ".join(f"{k}={v}"
                                for k, v in sorted(planes.items())))
    for st in doc.get("standing", []):
        lines.append(f"STANDING [{st.get('plane')}] {st.get('kind')}: "
                     f"{st.get('summary')}")
    incidents = doc.get("incidents") or []
    if not incidents:
        lines.append("no anomalies detected"
                     + (" — but chaos evidence is present: the detector "
                        "MISSED the injected fault"
                        if doc.get("chaos_fired") else
                        " (and no chaos evidence: a calm run)"))
        return lines
    for i, incident in enumerate(incidents):
        a = incident.get("anomaly") or {}
        detail = " ".join(
            f"{k}={a[k]:.4g}" if isinstance(a[k], float) else
            f"{k}={a[k]}"
            for k in ("value", "median", "z", "tick") if a.get(k)
            is not None)
        lines.append(f"incident #{i}  {a.get('name')}  {detail}")
        suspects = incident.get("suspects") or []
        if not suspects:
            lines.append("  UNATTRIBUTED — no evidence instant precedes "
                         "this anomaly inside the causality window")
            continue
        top = incident.get("top")
        for s in sorted(suspects, key=lambda s: s["ts_us"]):
            ev = s.get("evidence") or {}
            evtxt = " ".join(f"{k}={v}" for k, v in sorted(ev.items()))
            lines.append(
                f"  -{s['dt_s']:9.3f}s  [{s['plane']:<8}] "
                f"{s['name']:<28} score {s['score']:.3f} "
                f"(prior {s['prior']:g}, x{s['count']})"
                + ("  << TOP" if top is not None
                   and s["name"] == top["name"]
                   and s["ts_us"] == top["ts_us"] else "")
                + (f"  {evtxt}" if evtxt else ""))
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dtf_tpu.telemetry.report",
        description="Merge a run's telemetry into one post-mortem.")
    p.add_argument("logdir")
    p.add_argument("logdir_b", nargs="?", default=None,
                   help="second logdir (the B run) for --explain")
    p.add_argument("--explain", action="store_true",
                   help="step-time regression explainer: diff TWO runs "
                        "phase-by-phase (goodput buckets) and card-by-"
                        "card (costcards.jsonl) and print a ranked "
                        "attribution — which site/geometry grew, in "
                        "bytes or flops, and whether the growth is "
                        "memory- or compute-bound; with ONE logdir, "
                        "print just its sharding-plan audit (plan.json "
                        "predicted vs measured peak HBM)")
    p.add_argument("--diagnose", action="store_true",
                   help="incident post-mortem (telemetry/diagnose.py): "
                        "correlate every anomaly/* instant against the "
                        "other planes' evidence instants and print the "
                        "ranked suspects + a merged timeline per "
                        "anomaly, plus any standing incidents "
                        "(bench-ledger stall).  Exits 1 when ANY "
                        "anomaly has no suspect — silence is a "
                        "failure, not a pass")
    p.add_argument("--top", type=int, default=10)
    p.add_argument("--json", action="store_true",
                   help="emit the merged report as JSON instead of text")
    p.add_argument("--profile_dir", default=None,
                   help="XLA profile dir when not under <logdir>")
    p.add_argument("--export-trace", default=None, metavar="OUT.json",
                   help="also write the merged Chrome-trace for Perfetto")
    p.add_argument("--check", action="store_true",
                   help="CI gate: fail unless goodput components sum to "
                        "wall-clock within --tol percent (implied by any "
                        "threshold flag below)")
    p.add_argument("--tol", type=float, default=10.0)
    # Threshold gates (check_gates) — the ONE gate implementation the
    # scenario matrix runner and the full-suite lanes share; each flag
    # arms its gate, and any of them implies --check.
    p.add_argument("--min_goodput", type=float, default=None,
                   help="goodput-fraction floor (productive/wall, 0..1)")
    p.add_argument("--min_mfu", type=float, default=None,
                   help="MFU floor in percent of chip peak (mfu/pct_peak)")
    p.add_argument("--max_rollbacks", type=int, default=None,
                   help="ceiling on checkpoint/rollbacks_total")
    p.add_argument("--min_examples_per_s", type=float, default=None,
                   help="throughput floor (throughput/examples_per_s)")
    p.add_argument("--min_tokens_per_s", type=float, default=None,
                   help="throughput floor (throughput/tokens_per_s)")
    p.add_argument("--max_final_cost", type=float, default=None,
                   help="convergence gate: metrics.csv final cost ceiling")
    p.add_argument("--min_goodput_qps", type=float, default=None,
                   help="serving gate: goodput-QPS floor (telemetry "
                        "'serving' section)")
    p.add_argument("--max_ttft_p99_ms", type=float, default=None,
                   help="serving gate: p99 TTFT ceiling in ms")
    p.add_argument("--max_tpot_p99_ms", type=float, default=None,
                   help="serving gate: p99 TPOT ceiling in ms (the "
                        "streaming-cadence gate the spec-decode lane "
                        "arms)")
    p.add_argument("--min_prefix_hit_rate", type=float, default=None,
                   help="prefix-cache gate: floor on the serving "
                        "summary's prefix_hit_rate (matched/probed "
                        "blocks at admission; the key ABSENT = prefix "
                        "cache never armed = FAIL)")
    p.add_argument("--min_trace_complete_frac", type=float, default=None,
                   help="observability gate: floor on the fraction of "
                        "completed requests with a gap-free "
                        "admission->completion trace chain")
    p.add_argument("--max_control_rollbacks", type=int, default=None,
                   help="control-plane gate: ceiling on the self-tuning "
                        "knob controller's snap-backs "
                        "(control/rollback_total; the counter ABSENT = "
                        "controller never armed = FAIL)")
    p.add_argument("--fleet", action="store_true",
                   help="require the fleet section (telemetry/fleet.py): "
                        "fail when the logdir holds no fleet/sync spans "
                        "and no fleet.json rollup; --export-trace then "
                        "re-bases every host onto one clock")
    p.add_argument("--max_skew_ms", type=float, default=None,
                   help="fleet gate: ceiling on the median per-barrier "
                        "arrival skew (offset-corrected)")
    p.add_argument("--min_fleet_goodput", type=float, default=None,
                   help="fleet gate: floor on the fleet's joint "
                        "productive fraction (coordinator rollup)")
    p.add_argument("--max_blame_frac", type=float, default=None,
                   help="fleet gate: ceiling on any single host's share "
                        "of last-arrivals (0..1)")
    p.add_argument("--max_hbm_frac", type=float, default=None,
                   help="device-cost gate: ceiling on the live-HBM "
                        "high-water as a fraction of chip capacity "
                        "(hbm/frac; not measured = FAIL)")
    p.add_argument("--max_compiles", type=float, default=None,
                   help="device-cost gate: ceiling on captured compiles "
                        "(cost/compiles_total; not measured = FAIL)")
    p.add_argument("--min_attribution_frac", type=float, default=None,
                   help="incident gate: floor on the fraction of "
                        "detected anomalies correctly attributed — with "
                        "chaos evidence only a TOP-ranked chaos suspect "
                        "counts; chaos fired with zero anomalies = not "
                        "measured = FAIL (injected-but-undetected)")
    p.add_argument("--max_wire_bytes_per_step", type=float, default=None,
                   help="gradient-wire gate: ceiling on the per-step "
                        "scatter-leg wire payload (comm/wire_bytes; not "
                        "measured = FAIL) — pins a quantized-ring run to "
                        "its thin wire so a silent fallback to a fatter "
                        "dtype fails loud")
    p.add_argument("--request", type=int, default=None, metavar="RID",
                   help="print ONE request's causally-ordered timeline "
                        "(reqtrace events + the engine iterations that "
                        "touched it) instead of the full report")
    p.add_argument("--pid", type=int, default=None,
                   help="with --request: restrict the timeline to one "
                        "host's span stream (rids are per-engine, so a "
                        "merged fleet stream can carry the same rid on "
                        "several hosts)")
    ns = p.parse_args(argv)
    if not os.path.isdir(ns.logdir):
        print(f"error: {ns.logdir} is not a directory", file=sys.stderr)
        return 2
    if ns.explain:
        from dtf_tpu.telemetry import costobs
        if ns.logdir_b is None:
            # Single-logdir --explain: just the sharding-plan audit
            # (parallel/planner.py) — predicted peak HBM vs the peak the
            # cost observatory measured.  The A/B cost explainer still
            # takes two runs.
            from dtf_tpu.parallel import planner as _planner
            audit = _planner.audit_lines(ns.logdir)
            if not audit:
                print("error: --explain with one logdir needs a recorded "
                      "plan.json (run with --plan auto); the A/B cost "
                      "explainer takes TWO logdirs "
                      "(report --explain <logdir_a> <logdir_b>)",
                      file=sys.stderr)
                return 2
            for line in audit:
                print(line)
            return 0
        if not os.path.isdir(ns.logdir_b):
            print("error: --explain takes TWO logdirs "
                  "(report --explain <logdir_a> <logdir_b>)",
                  file=sys.stderr)
            return 2
        try:
            doc = costobs.explain(ns.logdir, ns.logdir_b)
        except FileNotFoundError as exc:
            # absence is loud: an explain against a run that never
            # captured cards is a configuration error, not an empty diff
            print(f"error: {exc}", file=sys.stderr)
            return 1
        if ns.json:
            print(json.dumps(doc, indent=1, sort_keys=True, default=str))
        else:
            for line in costobs.render_explain(doc, top=ns.top):
                print(line)
            # Sharding-plan audit (parallel/planner.py): when either run
            # recorded a plan.json, show its predicted peak HBM against
            # the peak the cost observatory measured — the planner's
            # predictions are auditable, not write-only.
            from dtf_tpu.parallel import planner as _planner
            for d in (ns.logdir, ns.logdir_b):
                audit = _planner.audit_lines(d)
                if audit:
                    print()
                    for line in audit:
                        print(line)
        return 0
    if ns.logdir_b is not None:
        print("error: a second logdir only makes sense with --explain",
              file=sys.stderr)
        return 2
    if ns.diagnose:
        from dtf_tpu.telemetry import diagnose as _diagnose
        doc = _diagnose.diagnose_logdir(ns.logdir)
        if ns.json:
            print(json.dumps(doc, indent=1, sort_keys=True, default=str))
        else:
            for line in render_diagnose(doc, ns.logdir):
                print(line)
        # the falsifiability exit rule: an anomaly nobody can explain is
        # a correlator failure, and chaos-with-zero-anomalies (frac
        # None) is a detector failure — both are exit 1
        bad = (doc.get("unattributed", 0) > 0
               or (doc.get("chaos_fired")
                   and doc.get("attribution_frac") is None))
        return 1 if bad else 0
    if ns.request is not None:
        from dtf_tpu.telemetry import reqtrace
        events = reqtrace.request_timeline(ns.logdir, ns.request,
                                           pid=ns.pid)
        print(f"== request {ns.request} timeline: "
              f"{os.path.abspath(ns.logdir)} ==")
        for line in reqtrace.render_timeline(events):
            print(line)
        return 0 if events else 1
    report = build_report(ns.logdir, profile_dir=ns.profile_dir, top=ns.top)
    if ns.fleet and not report.get("fleet"):
        print("error: --fleet requested but the logdir holds no "
              "fleet/sync spans and no fleet.json rollup "
              "(is this a fleet run's shared logdir?)", file=sys.stderr)
        return 1
    if ns.export_trace:
        from dtf_tpu.telemetry.spans import export_chrome_trace
        offsets = None
        if report.get("fleet", {}).get("offsets_s"):
            # fleet run: re-base every host's stream onto the reference
            # clock so the exported trace is ONE timeline
            offsets = {int(p): float(o) for p, o in
                       report["fleet"]["offsets_s"].items()}
        n = export_chrome_trace(ns.logdir, ns.export_trace,
                                offsets_s=offsets)
        report["exported_trace_events"] = n
    if ns.json:
        print(json.dumps(report, indent=1, sort_keys=True, default=str))
    else:
        print(render(report, top=ns.top))
        if ns.export_trace:
            print(f"Chrome trace: {ns.export_trace} "
                  f"({report['exported_trace_events']} events)")
    thresholds = {"min_goodput": ns.min_goodput, "min_mfu": ns.min_mfu,
                  "max_rollbacks": ns.max_rollbacks,
                  "min_examples_per_s": ns.min_examples_per_s,
                  "min_tokens_per_s": ns.min_tokens_per_s,
                  "max_final_cost": ns.max_final_cost,
                  "min_goodput_qps": ns.min_goodput_qps,
                  "max_ttft_p99_ms": ns.max_ttft_p99_ms,
                  "max_tpot_p99_ms": ns.max_tpot_p99_ms,
                  "min_trace_complete_frac": ns.min_trace_complete_frac,
                  "max_control_rollbacks": ns.max_control_rollbacks,
                  "max_skew_ms": ns.max_skew_ms,
                  "min_fleet_goodput": ns.min_fleet_goodput,
                  "max_blame_frac": ns.max_blame_frac,
                  "max_hbm_frac": ns.max_hbm_frac,
                  "max_compiles": ns.max_compiles,
                  "min_attribution_frac": ns.min_attribution_frac,
                  "max_wire_bytes_per_step": ns.max_wire_bytes_per_step,
                  "min_prefix_hit_rate": ns.min_prefix_hit_rate}
    armed = {k: v for k, v in thresholds.items() if v is not None}
    if ns.check or armed:
        # check_goodput already fails on a missing/empty telemetry.json
        # (no goodput section -> (False, ...)).  With --json the verdict
        # goes to stderr so stdout stays parseable.
        out = sys.stderr if ns.json else sys.stdout
        ok, verdict = check_goodput(report, ns.tol)
        print(f"goodput check: {'OK' if ok else 'FAIL'} — {verdict}",
              file=out)
        if armed:
            gates_ok, lines = check_gates(report, **armed)
            for line in lines:
                print(line, file=out)
            ok = ok and gates_ok
        if not ok:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
