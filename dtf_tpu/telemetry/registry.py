"""Process-wide metric registry: counters, gauges, histograms.

Replaces the ad-hoc "write a magic scalar string and hope the reader
greps for it" pattern: an instrument is REGISTERED once (name validated
against telemetry/names.py, type fixed), updated from anywhere in the
process, and read back as one deterministic snapshot — the payload of
``<logdir>/telemetry.json`` and the periodic feed into the existing
MetricLogger CSV/TB stream.

Determinism contract: :meth:`MetricRegistry.snapshot` is a pure function
of the update history — sorted keys, plain Python floats/ints, no
timestamps — so tests can golden it and two processes applying the same
updates produce identical JSON.

Thread-safe (one reentrant lock per registry; instruments share it).
Not cross-process: each process owns its registry, and only the
coordinator serializes (same rule as MetricLogger).

Snapshot consistency (the live plane's contract): :meth:`MetricRegistry.
snapshot` holds the registry lock across EVERY instrument read, and
:meth:`MetricRegistry.locked` lets a writer update a *group* of
instruments atomically (e.g. ``serve/shed_total`` plus its per-reason
counter) — so a concurrent ``/statz`` scrape can never observe a torn
pair.  The lock is reentrant precisely so instrument updates nest inside
``locked()``.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from typing import Dict, Iterator, List, Optional

from dtf_tpu.telemetry.names import require_declared, validate


class Counter:
    """Monotonic count (events, retries, saves)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self._value}


class Gauge:
    """Last-written value (throughput, MFU, fractions)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value: Optional[float] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, v: float) -> None:
        with self._lock:
            self._value = (self._value or 0.0) + float(v)

    @property
    def value(self) -> Optional[float]:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._value}


class Histogram:
    """Streaming summary (count/sum/min/max + mean) — enough for step-time
    and save-latency distributions without a bucket-boundary bikeshed; the
    full distribution lives in the span file anyway."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None

    def snapshot(self) -> dict:
        return {"type": "histogram", "count": self.count, "sum": self.total,
                "min": self.min, "max": self.max, "mean": self.mean}


class MetricRegistry:
    """``strict=True`` (the process-wide registry's mode) additionally
    requires every registered name to be DECLARED in telemetry/names.py
    — the runtime half of the naming lint: a name assembled at runtime
    that no declaration covers fails at creation, not at dashboard
    time.  Scratch registries (tests, tools) default to shape-only."""

    def __init__(self, strict: bool = False):
        self._lock = threading.RLock()
        self.strict = strict
        self._instruments: Dict[str, object] = {}

    def _get(self, name: str, cls):
        if self.strict:
            require_declared(name)
        else:
            validate(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, self._lock)
        if not isinstance(inst, cls):
            raise TypeError(
                f"instrument {name!r} already registered as "
                f"{type(inst).__name__}, requested {cls.__name__}")
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._instruments)

    @contextlib.contextmanager
    def locked(self) -> Iterator[None]:
        """Atomic multi-instrument update: hold the registry lock over a
        GROUP of updates so a concurrent :meth:`snapshot` (the ``/statz``
        scrape) sees either none or all of them.  Reentrant — the
        individual ``inc``/``set``/``observe`` calls inside re-acquire
        the same lock."""
        with self._lock:
            yield

    def snapshot(self) -> dict:
        """Deterministic: sorted by name, value types only.  The lock is
        held across EVERY instrument read — one consistent cut of the
        registry, never a mix of before/after a concurrent ``locked()``
        update group."""
        with self._lock:
            return {name: inst.snapshot()
                    for name, inst in sorted(self._instruments.items())}

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()

    def load_counters(self, metrics_doc: dict) -> None:
        """Seed lifetime counters from a previous process's
        ``telemetry.json`` metrics section (resume path): counters are
        cumulative by contract, so a relaunch must carry them forward.
        Gauges/histograms stay fresh — they are point-in-time
        observations of THIS process."""
        for name, snap in metrics_doc.items():
            if (isinstance(snap, dict) and snap.get("type") == "counter"
                    and isinstance(snap.get("value"), int)):
                try:
                    self.counter(name).inc(snap["value"])
                except (ValueError, TypeError):
                    continue           # foreign/renamed instrument

    def write_json(self, path: str, extra: Optional[dict] = None) -> None:
        """Atomic ``telemetry.json`` write: {"metrics": snapshot, **extra}.
        Called at logging sync points and on exit, so even an abrupt
        SIGKILL leaves a recent machine-readable state on disk."""
        doc = {"metrics": self.snapshot()}
        if extra:
            doc.update(extra)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)


# -- the process-wide registry ----------------------------------------------
# Strict: every instrument the process registers must be declared in
# names.py (the report CLI and dashboards key on those strings).

_REGISTRY = MetricRegistry(strict=True)


def get_registry() -> MetricRegistry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return _REGISTRY.histogram(name)
