"""SLO burn-rate monitoring: windowed error-budget accounting with
Google-SRE-style fast+slow burn alerts.

An SLO like "99% of requests get first token within the TTFT budget"
grants an **error budget** (1% of requests may miss).  The *burn rate*
over a lookback window is::

    burn = (bad events / total events in window) / (1 - target)

burn 1.0 = spending the budget exactly at the sustainable rate; burn 14.4
= the classic "page now" fast-burn threshold (a 30-day budget gone in ~2
days).  Two windows per objective — a short **fast** window that reacts
in seconds and a long **slow** window that filters blips — each with its
own threshold, so a single outlier cannot page but a sustained
regression pages early.

This is the operator's early warning: under the pinned ``slow_decode``
spike the FAST alert fires after a handful of over-budget completions,
strictly before the brownout controller walks its dwell-hysteresis
ladder to ``reject_all`` — alert-leads-control, gated in CI by
``bench.serve_load --chaos --check``.

Objectives are fed by the serving engine (TTFT / TPOT / deadline
violations, on the engine's own wall-or-virtual clock so CI runs are
deterministic) and read by three consumers: the ``serve/slo_*``
instrument family, the live ``/slo`` endpoint, and the report CLI's
Serving section.

Jax-free; thread-safe (the engine thread records, admin handler threads
snapshot).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One objective: attainment ``target`` (0..1, exclusive) and the
    two lookback windows with their burn thresholds.  ``min_events``
    guards both alerts — a burn computed from one sample is noise."""

    name: str
    target: float
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    fast_burn: float = 14.4
    slow_burn: float = 6.0
    min_events: int = 4

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), got "
                             f"{self.target} for {self.name!r}")
        if self.fast_window_s >= self.slow_window_s:
            raise ValueError(
                f"{self.name!r}: fast window ({self.fast_window_s}s) must "
                f"be shorter than slow window ({self.slow_window_s}s)")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


class _Objective:
    """Per-window rolling (bad, total) counts with amortized-O(1)
    updates: two deques of (t, bad) — one per window — each trimmed
    from the front as its horizon advances, counts adjusted on
    append/expire.  ``update`` runs in the engine's per-iteration hot
    loop, so burn evaluation must not rescan the retained events."""

    __slots__ = ("spec", "slow", "fast", "counts", "bad_total", "total",
                 "alerts", "firing", "first_alert")

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        self.slow: Deque[Tuple[float, bool]] = deque()   # (t, bad)
        self.fast: Deque[Tuple[float, bool]] = deque()
        # {"fast"/"slow": [bad_in_window, total_in_window]}
        self.counts = {"fast": [0, 0], "slow": [0, 0]}
        self.bad_total = 0
        self.total = 0
        self.alerts = {"fast": 0, "slow": 0}
        self.firing = {"fast": False, "slow": False}
        self.first_alert: Dict[str, Tuple[float, int]] = {}

    def record(self, bad: bool, t: float) -> None:
        ev = (float(t), bool(bad))
        for speed, q in (("fast", self.fast), ("slow", self.slow)):
            q.append(ev)
            c = self.counts[speed]
            c[0] += int(bad)
            c[1] += 1
        self.total += 1
        self.bad_total += int(bad)

    def _trim(self, now: float) -> None:
        for speed, q, window in (("fast", self.fast,
                                  self.spec.fast_window_s),
                                 ("slow", self.slow,
                                  self.spec.slow_window_s)):
            horizon = now - window
            c = self.counts[speed]
            while q and q[0][0] < horizon:
                _, bad = q.popleft()
                c[0] -= int(bad)
                c[1] -= 1

    def burns(self, now: float) -> Dict[str, Tuple[float, int]]:
        """{"fast"/"slow": (burn rate, events in window)} from the
        rolling counts (caller trims first).  Burn is 0 until
        min_events samples exist in the window — never alert off
        noise."""
        out = {}
        for speed in ("fast", "slow"):
            bad, total = self.counts[speed]
            if total < self.spec.min_events:
                out[speed] = (0.0, total)
            else:
                out[speed] = ((bad / total) / self.spec.budget, total)
        return out


class BurnRateMonitor:
    """The monitor the engine feeds and the live plane reads.

    * :meth:`record` — one good/bad event per objective, stamped with
      the engine clock;
    * :meth:`update` — once per engine iteration: recompute both
      windows' burn per objective, edge-trigger alerts into the
      ``serve/slo_alert_*`` counters and the ``serve/slo_burn_*``
      gauges, remember the FIRST alert instant (the alert-leads-control
      gate's timestamp);
    * :meth:`state` — the ``/slo`` endpoint / report payload.
    """

    def __init__(self, objectives: List[SLOSpec]):
        if not objectives:
            raise ValueError("BurnRateMonitor needs >= 1 objective")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self._lock = threading.Lock()
        self._objs = {o.name: _Objective(o) for o in objectives}

    @classmethod
    def for_serving(cls, slo_ttft_ms: float,
                    slo_tpot_ms: Optional[float] = None, *,
                    ttft_target: float = 0.99,
                    tpot_target: float = 0.99,
                    deadline_target: float = 0.999,
                    **spec_overrides) -> "BurnRateMonitor":
        """The serving trio: TTFT attainment, TPOT attainment (only when
        a TPOT budget exists), and deadline violations (budgeted much
        tighter — a blown deadline is a broken promise, not a slow
        one).  The engine stores the ms budgets for its own good/bad
        classification."""
        objs = [SLOSpec("ttft", ttft_target, **spec_overrides)]
        if slo_tpot_ms is not None:
            objs.append(SLOSpec("tpot", tpot_target, **spec_overrides))
        objs.append(SLOSpec("deadline", deadline_target, **spec_overrides))
        mon = cls(objs)
        mon.slo_ttft_ms = float(slo_ttft_ms)
        mon.slo_tpot_ms = (None if slo_tpot_ms is None
                           else float(slo_tpot_ms))
        return mon

    # engine-facing budgets (set by for_serving; None when hand-built)
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None

    def has(self, name: str) -> bool:
        return name in self._objs

    def record(self, name: str, bad: bool, t: float) -> None:
        with self._lock:
            obj = self._objs.get(name)
            if obj is None:
                raise ValueError(f"unknown SLO objective {name!r}; one of "
                                 f"{sorted(self._objs)}")
            obj.record(bad, t)

    def update(self, now: float, iteration: int) -> Dict[str, dict]:
        """One evaluation pass; returns {objective: {fast/slow burn,
        firing flags}} and feeds the instrument family."""
        from dtf_tpu import telemetry as tel
        out: Dict[str, dict] = {}
        with self._lock:
            for name, obj in self._objs.items():
                obj._trim(now)
                spec = obj.spec
                res = {}
                burns = obj.burns(now)
                for speed, thresh in (("fast", spec.fast_burn),
                                      ("slow", spec.slow_burn)):
                    burn, n = burns[speed]
                    firing = burn >= thresh
                    if firing and not obj.firing[speed]:
                        # edge-triggered: one alert per excursion
                        obj.alerts[speed] += 1
                        obj.first_alert.setdefault(
                            speed, (float(now), int(iteration)))
                        tel.counter(f"serve/slo_alert_{speed}_total").inc()
                        tel.counter(
                            f"serve/slo_alert_{name}_{speed}").inc()
                        tel.instant(f"event/slo_alert_{name}_{speed}",
                                    burn=round(burn, 3),
                                    iteration=int(iteration))
                    obj.firing[speed] = firing
                    tel.gauge(f"serve/slo_burn_{name}_{speed}").set(burn)
                    res[f"{speed}_burn"] = round(burn, 4)
                    res[f"{speed}_window_events"] = n
                    res[f"{speed}_firing"] = firing
                out[name] = res
        return out

    def first_alert(self, name: str, speed: str = "fast"
                    ) -> Optional[Tuple[float, int]]:
        """(engine-clock t, iteration) of the objective's first alert,
        or None — the alert-leads-control gate compares this against the
        brownout controller's reject_all transition."""
        with self._lock:
            return self._objs[name].first_alert.get(speed)

    def state(self) -> dict:
        """The ``/slo`` payload / report section: per-objective budgets,
        burn alert counts, lifetime bad fractions, first-alert marks."""
        with self._lock:
            objectives = {}
            for name, obj in self._objs.items():
                spec = obj.spec
                objectives[name] = {
                    "target": spec.target,
                    "budget": round(spec.budget, 6),
                    "fast_window_s": spec.fast_window_s,
                    "slow_window_s": spec.slow_window_s,
                    "fast_burn_threshold": spec.fast_burn,
                    "slow_burn_threshold": spec.slow_burn,
                    "events_total": obj.total,
                    "bad_total": obj.bad_total,
                    "bad_frac": (round(obj.bad_total / obj.total, 6)
                                 if obj.total else None),
                    "alerts_fast": obj.alerts["fast"],
                    "alerts_slow": obj.alerts["slow"],
                    "firing_fast": obj.firing["fast"],
                    "firing_slow": obj.firing["slow"],
                    "first_alert": {
                        speed: {"t": t, "iteration": it}
                        for speed, (t, it) in
                        sorted(obj.first_alert.items())},
                }
            doc = {"objectives": objectives}
            if self.slo_ttft_ms is not None:
                doc["slo_ttft_ms"] = self.slo_ttft_ms
            if self.slo_tpot_ms is not None:
                doc["slo_tpot_ms"] = self.slo_tpot_ms
            return doc
