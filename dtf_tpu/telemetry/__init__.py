"""Unified telemetry spine (DESIGN.md §6 "Observability").

Three coordinated pieces, every layer reports into them:

* **Spans** (:mod:`.spans`) — host-side structured tracer; JSON-lines on
  disk, exportable to Chrome-trace/Perfetto so it overlays with the XLA
  profiler window.  ``with telemetry.span("checkpoint/save"): ...``
* **Metric registry** (:mod:`.registry`) — process-wide counters /
  gauges / histograms with deterministic snapshots; serialized to
  ``<logdir>/telemetry.json`` and fed through the MetricLogger CSV/TB
  stream at logging sync points.
* **Goodput accounting** (:mod:`.goodput`) — productive vs. rollback /
  restart / stall / checkpoint / compile wall-clock, plus the shared
  MFU / tokens-per-sec formulas.

The LIVE plane (DESIGN.md §6.4) rides on top of the same three pieces:

* **Per-request tracing** (:mod:`.reqtrace`) — trace ids minted at the
  serving front door and propagated through every lifecycle decision,
  written into the ordinary span files and a bounded in-memory flight
  recorder;
* **Admin endpoint** (:mod:`.live`) — ``/statz`` (consistent registry
  snapshot), ``/healthz``, ``/tracez``, ``/slo`` over stdlib HTTP,
  mounted by ``--admin_port``;
* **SLO burn-rate monitor** (:mod:`.slo`) — windowed error-budget
  accounting with fast+slow burn alerts (the operator's early warning,
  CI-gated to fire before brownout ``reject_all``).

``python -m dtf_tpu.telemetry.report <logdir>`` merges all of it (plus
metrics.csv, health.json, and any XLA trace summary) into one run
post-mortem.  Instrument and span names are registered in
:mod:`.names` — ``scripts/check_telemetry_names.py`` lints the source
against that table.

Pure stdlib (no jax import at module load): safe to import from every
layer, including ones that must work before devices exist.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from dtf_tpu.telemetry import names  # noqa: F401  (re-export)
from dtf_tpu.telemetry.goodput import GoodputTracker, get_tracker
from dtf_tpu.telemetry.registry import (MetricRegistry, counter, gauge,
                                        get_registry, histogram)
from dtf_tpu.telemetry.spans import (Tracer, configure, export_chrome_trace,
                                     get_tracer, instant, span)

TELEMETRY_FILE = "telemetry.json"

__all__ = [
    "GoodputTracker", "MetricRegistry", "Tracer", "TELEMETRY_FILE",
    "configure", "counter", "export_chrome_trace", "gauge", "get_registry",
    "get_tracer", "get_tracker", "histogram", "instant", "names",
    "reset", "span", "write_telemetry_json",
]
# live-plane modules are imported lazily by their consumers (reqtrace /
# live / slo are stdlib-only but not needed at telemetry import time)


def write_telemetry_json(logdir: str, extra: Optional[dict] = None) -> str:
    """Serialize the registry snapshot + goodput books to
    ``<logdir>/telemetry.json`` (atomic replace).  Cheap enough for every
    logging sync point, so even a SIGKILL'd host leaves a recent file.

    This IS the cost observatory's sync point too (telemetry/costobs.py):
    the live-HBM gauges update here — never on the hot path — and any
    captured CostCards persist as ``<logdir>/costcards.jsonl`` plus a
    ``cost`` summary section in the JSON (what ``report --explain`` and
    the ``--max_hbm_frac`` / ``--max_compiles`` gates read)."""
    path = os.path.join(logdir, TELEMETRY_FILE)
    from dtf_tpu.telemetry import costobs as _costobs
    obs = _costobs.get_observatory()
    obs.update_live_memory()
    doc = {"goodput": get_tracker().snapshot(),
           "written_unix": time.time()}
    # incident plane: the sync-point signals (goodput fraction, HBM
    # roofline fraction) feed the changepoint detectors here — once per
    # logging boundary, never on the hot path
    from dtf_tpu.telemetry import anomaly as _anomaly
    mon = _anomaly.get_monitor()
    if doc["goodput"].get("wall_s"):
        mon.observe("goodput/fraction",
                    doc["goodput"].get("productive_fraction", 0.0))
    _hbm = get_registry().snapshot().get("hbm/frac")
    if _hbm is not None and _hbm.get("value") is not None:
        mon.observe("hbm/frac", _hbm["value"])
    if obs.total_compiles() or obs.live_peak_bytes() is not None:
        doc["cost"] = obs.summary()
        obs.write_jsonl(logdir)
    if extra:
        doc.update(extra)
    get_registry().write_json(path, extra=doc)
    return path


def reset() -> None:
    """Forget all process-wide telemetry state (registry, goodput books,
    tracer binding).  For tests and for a genuinely NEW run starting in a
    process that already ran one — never called on the supervisor's
    restart path, whose books must span attempts."""
    get_registry().reset()
    get_tracker().reset()
    configure(None)
    from dtf_tpu.telemetry import live as _live
    _live.stop_admin()
    from dtf_tpu.telemetry import fleet as _fleet
    _fleet.reset()
    from dtf_tpu.telemetry import costobs as _costobs
    _costobs.get_observatory().reset()
    from dtf_tpu.telemetry import anomaly as _anomaly
    _anomaly.reset()
    from dtf_tpu.telemetry import diagnose as _diagnose
    _diagnose.reset()
