"""Cross-plane root-cause attribution: every anomaly explains itself.

:mod:`dtf_tpu.telemetry.anomaly` notices that a signal changed; this
module says WHY, by correlating each fire against the instant streams
every other plane already emits — chaos fault marks, ``control/set`` /
``control/rollback`` audit entries, brownout transitions, SLO
first-alert marks, fleet detach/failover, new-geometry compile events,
drain marks, supervisor restarts and health aborts.  One deterministic
rule, two consumers:

* LIVE — a tap on :func:`dtf_tpu.telemetry.spans.Tracer.instant` keeps
  a bounded in-process event log; each anomaly fire is correlated
  immediately and the resulting incident lands in a bounded ring served
  by the ``/incidentz`` admin endpoint as one consistent cut;
* POST-HOC — ``report --diagnose <logdir>`` re-runs the SAME
  :func:`correlate` over the instants parsed back from the span files,
  so the live and post-mortem verdicts cannot drift apart.

Attribution rule (DESIGN.md "Incident plane"): a candidate suspect is
any evidence instant with ``ts <= anomaly.ts`` (temporal PRECEDENCE —
an effect never explains its cause) within the causality window
(default 60 s of tracer wall-clock); its score is
``prior(plane) * exp(-dt / tau)``.  On a VirtualClock run all the
wall-clock gaps compress toward zero, so precedence + priors decide —
which is what makes the scenario-matrix attribution gate deterministic.
Anomaly instants themselves are never evidence (a symptom cannot
explain a symptom), and SLO alerts carry the lowest prior for the same
reason: they are detectors, not causes.

Falsifiability is the contract: an anomaly with NO suspect is an exit-1
failure of ``report --diagnose`` (silence is a failure, not a pass),
and the scenario gate ``min_attribution_frac`` demands the injected
fault kind be TOP-ranked — a correlator that blames an innocent plane
demonstrably fails it (tested with an inverted-priors variant).
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

# -- plane priors -------------------------------------------------------------
# Ordered matchers: first hit wins.  Priors encode how *causal* a plane
# is when it precedes an anomaly — injected faults are ground truth
# (1.0); fleet membership changes and control rollbacks are strong
# causes; an SLO alert is another detector looking at the same symptom
# (0.3, kept only so an otherwise-unexplained anomaly still shows its
# context).  A matcher is (prefix | exact, plane, prior).
PLANE_PRIORS: Tuple[Tuple[str, str, float], ...] = (
    ("chaos/",                    "chaos",    1.00),
    ("event/fleet_detach",        "fleet",    0.90),
    ("event/fleet_failover",      "fleet",    0.90),
    ("control/rollback",          "control",  0.80),
    ("event/supervisor_restart",  "health",   0.70),
    ("health/",                   "health",   0.70),
    ("control/set",               "control",  0.60),
    ("event/brownout_transition", "brownout", 0.50),
    ("event/serve_drain",         "drain",    0.45),
    ("event/compile_new_geometry", "compile", 0.40),
    ("event/slo_alert_",          "slo",      0.30),
)

#: causality window: evidence older than this cannot explain an anomaly
WINDOW_S = 60.0
#: recency decay constant inside the window
TAU_S = 20.0
#: bounded live stores
EVENT_LOG_MAX = 4096
INCIDENT_RING_MAX = 256


def classify(name: str) -> Optional[Tuple[str, float]]:
    """(plane, prior) for an evidence instant name; None when the name
    is not evidence (anomaly/* and reqtrace/* included)."""
    if name.startswith("anomaly/"):
        return None
    for pat, plane, prior in PLANE_PRIORS:
        if name == pat or (pat.endswith(("/", "_")) and
                           name.startswith(pat)):
            return plane, prior
    return None


def _kind(name: str) -> str:
    """Suspect kind: the fault kind for chaos marks, else the full
    instant name — what the gate compares against the injected plan."""
    if name.startswith("chaos/"):
        return name.split("/", 1)[1]
    return name


def correlate(anomaly_ts_us: float, events: Iterable[dict],
              window_s: float = WINDOW_S, tau_s: float = TAU_S,
              priors=None) -> List[dict]:
    """Rank suspects for one anomaly at ``anomaly_ts_us`` against
    ``events`` (dicts with ``name``/``ts``/``args``).  Deterministic:
    score = prior * exp(-dt/tau); ties break by prior then recency.
    ``priors`` overrides :data:`PLANE_PRIORS` (the falsifiability tests
    invert them to prove the gate catches an innocent-blaming ranker).

    One suspect per (plane, kind): the LATEST qualifying instant of that
    kind carries the evidence; ``count`` says how many preceded."""
    table = PLANE_PRIORS if priors is None else priors
    best: Dict[Tuple[str, str], dict] = {}
    for ev in events:
        name = ev.get("name", "")
        hit = None
        for pat, plane, prior in table:
            if name == pat or (pat.endswith(("/", "_")) and
                               name.startswith(pat)):
                hit = (plane, prior)
                break
        if hit is None or name.startswith("anomaly/"):
            continue
        ts = float(ev.get("ts", 0.0))
        dt_s = (anomaly_ts_us - ts) / 1e6
        if dt_s < 0 or dt_s > window_s:
            continue               # precedence + causality window
        plane, prior = hit
        score = prior * math.exp(-dt_s / tau_s)
        key = (plane, _kind(name))
        cur = best.get(key)
        if cur is None or score > cur["score"]:
            best[key] = {"plane": plane, "kind": key[1], "name": name,
                         "ts_us": ts, "dt_s": round(dt_s, 6),
                         "prior": prior, "score": score,
                         "evidence": dict(ev.get("args") or {}),
                         "count": (cur["count"] if cur else 0)}
        best[key]["count"] += 1
    return sorted(best.values(),
                  key=lambda s: (-s["score"], -s["prior"], s["dt_s"]))


# -- live plane ---------------------------------------------------------------

class IncidentRing:
    """Bounded FIFO of incidents with a consistent snapshot (the same
    rev/rev_echo torn-read discipline is unnecessary here because the
    whole cut is built under one lock)."""

    def __init__(self, maxlen: int = INCIDENT_RING_MAX):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=maxlen)
        self._total = 0

    def push(self, incident: dict) -> None:
        with self._lock:
            incident = dict(incident)
            incident["seq"] = self._total
            self._ring.append(incident)
            self._total += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"total": self._total,
                    "evicted": self._total - len(self._ring),
                    "incidents": [dict(i) for i in self._ring]}


class _LiveState:
    """Process-wide tap + ring (reset() swaps the whole object)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.events: collections.deque = collections.deque(
            maxlen=EVENT_LOG_MAX)
        self.ring = IncidentRing()
        self.tapped = False

    def tap(self, name: str, ts_us: float, args: dict, process: int
            ) -> None:
        if classify(name) is None:
            return
        with self.lock:
            self.events.append({"name": name, "ts": ts_us,
                                "args": dict(args), "pid": process})


_STATE = _LiveState()


def _ensure_tap() -> None:
    if not _STATE.tapped:
        from dtf_tpu.telemetry import spans
        spans.add_instant_tap(_STATE.tap)
        _STATE.tapped = True


def install() -> None:
    """Arm the live evidence tap + the incident instruments (idempotent;
    called by the anomaly monitor's consumers at startup so even a
    zero-incident run leaves 'armed, zero' books, never silence)."""
    _ensure_tap()
    from dtf_tpu.telemetry import counter
    counter("incident/recorded_total")
    counter("incident/attributed_total")


def record_anomaly(name: str, fired: dict) -> dict:
    """Live path, called by the anomaly monitor on each fire: correlate
    NOW against the tapped event log, book the incident counters, and
    push the incident into the ring.  Returns the incident."""
    install()
    now_us = time.time() * 1e6
    with _STATE.lock:
        events = list(_STATE.events)
    suspects = correlate(now_us, events)
    incident = {"anomaly": dict(fired, name=name), "ts_us": now_us,
                "suspects": suspects,
                "top": suspects[0] if suspects else None}
    from dtf_tpu.telemetry import counter
    counter("incident/recorded_total").inc()
    if suspects:
        counter("incident/attributed_total").inc()
    _STATE.ring.push(incident)
    return incident


def get_ring() -> IncidentRing:
    return _STATE.ring


def incidentz(logdir: Optional[str] = None) -> dict:
    """The ``/incidentz`` payload: one consistent cut of the live ring
    plus any standing incidents (bench-ledger stall) for ``logdir``."""
    doc = _STATE.ring.snapshot()
    doc["generated_unix"] = time.time()
    standing = ledger_standing_incidents(logdir) if logdir else []
    doc["standing"] = standing
    return doc


def reset() -> None:
    """Forget the live event log + ring (telemetry.reset() companion)."""
    global _STATE
    old, _STATE = _STATE, _LiveState()
    if old.tapped:
        from dtf_tpu.telemetry import spans
        spans.remove_instant_tap(old.tap)


# -- post-hoc plane -----------------------------------------------------------

def diagnose_records(records: Iterable[dict], window_s: float = WINDOW_S,
                     priors=None) -> dict:
    """Re-run the live rule over span records parsed from disk: every
    ``anomaly/*`` instant is correlated against every evidence instant.
    Returns the report's ``incidents`` section (see
    :func:`attribution_summary` for the gate quantity)."""
    instants = [r for r in records if r.get("ph") == "i"]
    anomalies = [r for r in instants
                 if str(r.get("name", "")).startswith("anomaly/")]
    evidence = [r for r in instants
                if classify(str(r.get("name", ""))) is not None]
    incidents = []
    for a in sorted(anomalies, key=lambda r: float(r.get("ts", 0.0))):
        ts = float(a.get("ts", 0.0))
        suspects = correlate(ts, evidence, window_s=window_s,
                             priors=priors)
        incidents.append({
            "anomaly": {"name": a.get("name"), "ts_us": ts,
                        **dict(a.get("args") or {})},
            "ts_us": ts,
            "suspects": suspects,
            "top": suspects[0] if suspects else None,
        })
    return attribution_summary(incidents, evidence)


def attribution_summary(incidents: List[dict], evidence: List[dict]
                        ) -> dict:
    """Fold incidents into the gate's quantities.

    ``attribution_frac`` is the fraction the ``min_attribution_frac``
    gate reads; its meaning is deliberately strict when chaos is in
    play: with injected-fault evidence present, ONLY an incident whose
    TOP suspect is the chaos plane counts as attributed (top-ranked
    innocent = unattributed = gate-visible).  With chaos fired but ZERO
    anomalies detected, the fraction is None — gated-but-unmeasured
    fails, which is exactly the injected-but-undetected case.  Without
    chaos evidence, attributed simply means 'has at least one suspect'
    (the report --diagnose exit-1 rule)."""
    chaos_fired = any(str(e.get("name", "")).startswith("chaos/")
                      for e in evidence)
    n = len(incidents)
    if chaos_fired:
        attributed = sum(1 for i in incidents
                         if i["top"] and i["top"]["plane"] == "chaos")
        frac = (attributed / n) if n else None
    else:
        attributed = sum(1 for i in incidents if i["suspects"])
        frac = (attributed / n) if n else 1.0
    planes = collections.Counter(
        i["top"]["plane"] for i in incidents if i["top"])
    return {"anomalies": n, "attributed": attributed,
            "attribution_frac": frac, "chaos_fired": chaos_fired,
            "unattributed": sum(1 for i in incidents
                                if not i["suspects"]),
            "top_plane_counts": dict(planes),
            "incidents": incidents}


def diagnose_logdir(logdir: str, window_s: float = WINDOW_S,
                    priors=None) -> dict:
    """Parse ``logdir``'s span files and diagnose them; also attaches
    any standing incidents (bench-ledger stall) found near the logdir."""
    from dtf_tpu.telemetry import spans
    records: List[dict] = []
    for path in spans.find_span_files(logdir):
        records.extend(spans.read_spans(path))
    doc = diagnose_records(records, window_s=window_s, priors=priors)
    doc["standing"] = ledger_standing_incidents(logdir)
    return doc


# -- standing incidents (bench-ledger stall) ---------------------------------

#: trailing error rows (same kind) before the trajectory counts as
#: stalled — matches the r03-r05 shape bench.py --check-ledger warns on
LEDGER_STALL_STREAK = 3


def ledger_standing_incidents(logdir: Optional[str]) -> List[dict]:
    """The bench-ledger STALLED streak as a standing incident: walk up
    from ``logdir`` looking for ``LEDGER.jsonl``; a trailing streak of
    >= LEDGER_STALL_STREAK error rows of one kind becomes one incident
    with the preflight stage/reason as evidence.  Empty list when no
    ledger is in scope (the common case) — never an error."""
    path = _find_ledger(logdir)
    if path is None:
        return []
    try:
        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
    except (OSError, ValueError):
        return []
    out = []
    for kind in sorted({r.get("kind") for r in rows if r.get("kind")}):
        kind_rows = sorted((r for r in rows if r.get("kind") == kind),
                           key=lambda r: r.get("n") or 0)
        streak = []
        for r in reversed(kind_rows):
            if r.get("error"):
                streak.append(r)
            else:
                break
        if len(streak) < LEDGER_STALL_STREAK:
            continue
        streak.reverse()
        reasons = sorted({f"{r.get('error')}@{r.get('stage')}"
                          for r in streak})
        out.append({
            "kind": "bench_ledger_stalled",
            "plane": "bench",
            "ledger": path,
            "bench_kind": kind,
            "streak": len(streak),
            "runs": f"{streak[0].get('run')}..{streak[-1].get('run')}",
            "reasons": reasons,
            "summary": (f"last {len(streak)} {kind} run(s) errored "
                        f"({', '.join(reasons)}) — perf trajectory "
                        f"STALLED, fresh numbers needed"),
        })
    return out


def _find_ledger(logdir: Optional[str]) -> Optional[str]:
    if not logdir:
        return None
    d = os.path.abspath(logdir)
    for _ in range(4):             # logdir, run dir, results dir, repo
        cand = os.path.join(d, "LEDGER.jsonl")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None
