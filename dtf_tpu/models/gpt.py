"""Decoder-only (GPT-style) causal language model with KV-cache generation.

Not in the reference (no sequence models, SURVEY.md §5.7) — part of this
framework's first-class long-context support.  TPU-first:

* pre-LN decoder blocks scanned over stacked per-layer params (one compiled
  body, 'stage' leading axis ready for pipeline sharding);
* causal attention defaults to the Pallas flash kernel on TPU
  (ops/flash_attention.py, O(T) memory) and the XLA path elsewhere;
* generation is a ``lax.scan`` over positions with a static-shape KV cache
  — per-step attention masks positions beyond the current index instead of
  dynamic shapes, so decode compiles once;
* logits tied to the token embedding; LayerNorm stats and loss in fp32.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dtf_tpu.nn.attention import (MultiHeadAttention, causal_mask,
                                  dot_product_attention)
from dtf_tpu.nn.core import Module, remat
from dtf_tpu.nn.layers import Dense, Embedding, LayerNorm

NEG_BIG = -1e30


# ONE int8 quantizer shared with the fused decode kernel, so fused and
# unfused --decode_int8 stay bit-compatible.
from dtf_tpu.ops.decode_kernel import quantize_cols as _quantize_cols  # noqa: E402


def _dequant_matmul(x, w8, scale, dtype):
    """y = (x @ dequant(w8)): the int8 operand streams from HBM at half
    the bf16 bytes and widens in-register (int8 values are exact in
    bf16); the per-channel scale folds into the fp32 output."""
    y = jnp.einsum("btd,dp->btp", x, w8.astype(x.dtype),
                   preferred_element_type=jnp.float32)
    return (y * scale).astype(dtype)


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50257
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 1024
    dtype: Any = jnp.float32
    use_flash: Optional[bool] = None   # None = flash on TPU, XLA elsewhere
    # "scan" | "unroll" layer loop — see models/bert.py BertConfig.
    layer_loop: str = "scan"
    remat: bool = False
    # LLaMA-family options (beyond-parity model breadth):
    rope: bool = False                 # rotary positions instead of a table
    num_kv_heads: Optional[int] = None # GQA: KV cache shrinks by H/KVH
    mlp_act: str = "gelu"              # "gelu" | "swiglu"
    label_smoothing: float = 0.0       # eps of uniform mass in the CE loss
    # Checkpoint policy when remat is on: "full" | "dots" (nn/core.remat).
    remat_policy: str = "full"
    # >0: compute the CE loss in sequence chunks of this size under
    # jax.checkpoint, so the (B, T, V) fp32 logits tensor — at GPT-2 scale
    # the single largest activation (B=32, T=1024: 6.6 GB) — is never
    # materialized; backward recomputes each chunk's logits from its
    # (B, C, D) hidden slice.  0 = one dense head pass.
    loss_chunk: int = 0
    # Pipeline parallelism: a Mesh with a 'pipe' axis runs the decoder
    # stack as layer-group stages (parallel/pipeline.py) instead of
    # lax.scan.  "gpipe": forward pipeline + AD backward; "1f1b":
    # interleaved fwd/bwd via GPT.pipeline_loss_and_grads (O(stages)
    # activation memory).
    pipeline_mesh: Optional[Any] = None
    pipeline_microbatches: int = 2
    pipeline_schedule: str = "gpipe"
    # Fused TRAIN-step block kernels (ops/block_kernel.py): pre-LN
    # attention and MLP half-blocks each as one Pallas kernel; covers
    # the LLaMA options too (RoPE in-kernel, GQA packed k/v, SwiGLU via
    # a packed up|gate matmul).  Decode/prefill keep their own paths
    # (the fused decode stack kernel serves generation).
    fused_block: bool = False
    # Training-forward matmul compute format (nn/lowp.py): "fp32" |
    # "bf16" | "int8" | "fp8".  Applies to the block's projections
    # (qkv/o/fc1/fc_gate/fc2) with per-channel scaling and a straight-
    # through backward; the inner attention, norms, loss, and the tied
    # LM head keep full precision.  Quality-gated by
    # bench.int8_quality --trajectory (pinned loss envelope).
    matmul_dtype: str = "fp32"

    @classmethod
    def gpt2_small(cls, **kw):
        return cls(**kw)

    @classmethod
    def llama_style(cls, **kw):
        """LLaMA-family block wiring at GPT-2-small scale: RoPE + GQA(4) +
        SwiGLU (mlp_dim scaled by 2/3 to hold the param count)."""
        d = dict(rope=True, num_kv_heads=4, mlp_act="swiglu", mlp_dim=2048)
        d.update(kw)
        return cls(**d)

    @classmethod
    def tiny(cls, **kw):
        d = dict(vocab_size=128, dim=32, num_layers=2, num_heads=4,
                 mlp_dim=64, max_len=64)
        d.update(kw)
        return cls(**d)

    # The ONE preset-name -> constructor mapping for every CLI/benchmark
    # (lm workload, int8_quality, decode_ladder); "llama" is the CLI
    # spelling of llama_style.
    @classmethod
    def from_preset(cls, name: str, **kw) -> "GPTConfig":
        ctors = {"gpt2_small": cls.gpt2_small, "llama": cls.llama_style,
                 "tiny": cls.tiny}
        if name not in ctors:
            raise ValueError(f"unknown GPT preset {name!r}; "
                             f"choose from {sorted(ctors)}")
        return ctors[name](**kw)

    def flash_enabled(self) -> bool:
        if self.use_flash is None:
            return jax.default_backend() == "tpu"
        return self.use_flash


def _xla_causal_impl(q, k, v, mask=None):
    """Causal XLA attention as a MultiHeadAttention ``attn_impl``."""
    return dot_product_attention(q, k, v, mask=causal_mask(q.shape[1]))


class GPTBlock(Module):
    """Pre-LN decoder block: x + attn(ln(x)); x + mlp(ln(x)).

    Causal attention goes through the MultiHeadAttention ``attn_impl`` seam:
    the Pallas flash kernel on TPU, the XLA softmax path elsewhere.
    """

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        from dtf_tpu.nn.lowp import check_matmul_dtype
        check_matmul_dtype(cfg.matmul_dtype)
        if cfg.fused_block and cfg.matmul_dtype not in ("fp32", "int8"):
            raise ValueError(
                f"--matmul_dtype {cfg.matmul_dtype} and fused_block are "
                f"exclusive: the fused Pallas block kernels take fp32 or "
                f"int8 operands (bf16 compute comes from the model dtype; "
                f"fp8 has no fused path) — drop one of the two")
        if cfg.fused_block:
            from dtf_tpu.ops.block_kernel import _check_block_args
            # fail at construction, not first apply: T checked per-call
            _check_block_args(8, cfg.dim, cfg.num_heads, cfg.num_kv_heads,
                              rope=cfg.rope, mlp_act=cfg.mlp_act)
        if cfg.flash_enabled():
            from dtf_tpu.ops.flash_attention import flash_attention_impl
            impl = flash_attention_impl(causal=True)
        else:
            impl = _xla_causal_impl
        self.ln1 = LayerNorm(cfg.dim)
        self.ln2 = LayerNorm(cfg.dim)
        self.attn = MultiHeadAttention(cfg.dim, cfg.num_heads, cfg.dtype,
                                       attn_impl=impl,
                                       num_kv_heads=cfg.num_kv_heads,
                                       matmul_dtype=cfg.matmul_dtype)
        # SwiGLU: gate and up are SEPARATE column-parallel projections, not
        # one packed matmul split at the midpoint — under the "mlp"->tensor
        # sharding rule a midpoint split would land gate and up on different
        # shards and force a reshard before silu(gate)*up; two projections
        # keep the elementwise product local on every tensor shard.
        self.fc1 = Dense(cfg.dim, cfg.mlp_dim, dtype=cfg.dtype,
                         axes_in="embed", axes_out="mlp",
                         matmul_dtype=cfg.matmul_dtype)
        self.fc_gate = (Dense(cfg.dim, cfg.mlp_dim, dtype=cfg.dtype,
                              axes_in="embed", axes_out="mlp",
                              matmul_dtype=cfg.matmul_dtype)
                        if cfg.mlp_act == "swiglu" else None)
        self.fc2 = Dense(cfg.mlp_dim, cfg.dim, dtype=cfg.dtype,
                         axes_in="mlp", axes_out="embed",
                         matmul_dtype=cfg.matmul_dtype)

    def init(self, key):
        k1, k2, ka, kf1, kf2, kg = jax.random.split(key, 6)
        out = {"ln1": self.ln1.init(k1), "ln2": self.ln2.init(k2),
               "attn": self.attn.init(ka), "fc1": self.fc1.init(kf1),
               "fc2": self.fc2.init(kf2)}
        if self.fc_gate is not None:
            out["fc_gate"] = self.fc_gate.init(kg)
        return out

    def _mlp_residual(self, params, x):
        """x + MLP(ln2(x)) — shared by the train/prefill/decode paths."""
        h = self.ln2.apply(params["ln2"], x)
        u = self.fc1.apply(params["fc1"], h)
        if self.fc_gate is not None:
            u = jax.nn.silu(self.fc_gate.apply(params["fc_gate"], h)) * u
        else:
            u = jax.nn.gelu(u)
        return x + self.fc2.apply(params["fc2"], u)

    def prefill(self, params, x):
        """Full-sequence forward that also returns this block's K/V for the
        cache (one MXU-batched pass); apply() is this minus the K/V.
        x: (B, T, D) -> (y, k, v) with k,v (B, T, KVH, Dh) — k rotated when
        RoPE is on (the cache stores post-rotation keys)."""
        p = params["attn"]
        h = self.ln1.apply(params["ln1"], x)
        q, k, v = self.attn.qkv(p, h)
        if self.cfg.rope:
            from dtf_tpu.nn.rope import apply_rope
            positions = jnp.arange(x.shape[1])
            q = apply_rope(q, positions)
            k = apply_rope(k, positions)
        impl = self.attn.attn_impl or _xla_causal_impl
        out = impl(q, self.attn.expand_kv(k), self.attn.expand_kv(v), None)
        x = x + self.attn.out_proj(p, out)
        return self._mlp_residual(params, x), k, v

    def apply(self, params, x, *, train=False, rng=None):
        if self.cfg.fused_block:
            from dtf_tpu.ops.block_kernel import (fused_attn_block,
                                                  fused_mlp_block)
            x = fused_attn_block(x, params["attn"], params["ln1"],
                                 num_heads=self.cfg.num_heads,
                                 num_kv_heads=self.cfg.num_kv_heads,
                                 causal=True, prenorm=True,
                                 rope=self.cfg.rope,
                                 matmul_dtype=self.cfg.matmul_dtype)
            return fused_mlp_block(x, params["fc1"], params["fc2"],
                                   params["ln2"],
                                   fc_gate_params=params.get("fc_gate"),
                                   prenorm=True,
                                   matmul_dtype=self.cfg.matmul_dtype)
        y, _, _ = self.prefill(params, x)
        return y

    def decode_step(self, params, x_t, cache, pos, packed=None,
                    visible_bias=None):
        """One token through the block with a KV cache.

        x_t: (B, 1, D); cache: {"k","v"}: (B, T_cache, KVH, Dh); pos: scalar
        index of this token.  Returns (y_t, new_cache).  Grouped-query
        attention runs on the grouped cache directly (no head broadcast of
        the cache in the hot decode loop), and the cache stays in its
        storage dtype end to end — the MXU accumulates in fp32 via
        ``preferred_element_type``, so there is no fp32 materialization of
        the whole cache per token (that copy was ~3x the cache's HBM
        traffic).  Decode is HBM-bound: the caller bounds T_cache to the
        actual generation length (init_cache ``length=``), not max_len.

        ``packed``: this layer's slice of GPT._decode_pack's container —
        {"qkv": {"wq", "bq", "wkv", "bkv"}} at minimum (q plus the k/v
        pair stacked into one matmul operand; decode at B~1 is
        op-latency-bound, so fewer, wider matmuls win), or the int8 form
        {"qkv": {"wq", "sq", "bq", "wkv", "skv", "bkv"}} (same layout,
        int8 operands + per-column scales), plus optional
        int8-quantized "o"/"fc1"/"fc_gate"/"fc2" entries ({"w" int8,
        "scale"}) that halve the per-token HBM weight traffic.
        """
        p = params["attn"]
        h = self.ln1.apply(params["ln1"], x_t)
        if packed is not None:
            pq = packed["qkv"]
            if "sq" in pq:
                # int8 pack: same q + stacked-kv layout as the f32 pack,
                # int8 operands with per-output-column scales.
                hd = self.cfg.dim // self.cfg.num_heads
                nh, kvh = self.cfg.num_heads, self.attn.kv_heads
                bsz = x_t.shape[0]
                q = (_dequant_matmul(h, pq["wq"], pq["sq"], h.dtype)
                     + pq["bq"]).reshape(bsz, 1, nh, hd)
                kv = ((jnp.einsum("btd,sdp->sbtp", h,
                                  pq["wkv"].astype(h.dtype),
                                  preferred_element_type=jnp.float32)
                       * pq["skv"][:, None]).astype(h.dtype)
                      + pq["bkv"][:, None, None])
                k_t = kv[0].reshape(bsz, 1, kvh, hd)
                v_t = kv[1].reshape(bsz, 1, kvh, hd)
            else:
                # f32 pack: q plus the k/v pair as ONE stacked matmul
                # operand (see GPT._packed_qkv for why stack, not concat).
                q = jnp.einsum("btd,dhk->bthk", h, pq["wq"]) + pq["bq"]
                kv = (jnp.einsum("btd,sdhk->sbthk", h, pq["wkv"])
                      + pq["bkv"][:, None, None])
                k_t, v_t = kv[0], kv[1]
        else:
            q, k_t, v_t = self.attn.qkv(p, h)
        if self.cfg.rope:
            from dtf_tpu.nn.rope import apply_rope
            q = apply_rope(q, pos[None])
            k_t = apply_rope(k_t, pos[None])
        cache_k = lax.dynamic_update_slice_in_dim(cache["k"],
                                                  k_t.astype(cache["k"].dtype),
                                                  pos, axis=1)
        cache_v = lax.dynamic_update_slice_in_dim(cache["v"],
                                                  v_t.astype(cache["v"].dtype),
                                                  pos, axis=1)
        b, _, h_all, hd = q.shape
        kvh = cache_k.shape[2]
        g = h_all // kvh
        qg = q.reshape(b, kvh, g, hd).astype(cache_k.dtype)  # T=1 folded away
        scale = hd ** -0.5
        s = jnp.einsum("bkgd,btkd->bkgt", qg, cache_k,
                       preferred_element_type=jnp.float32)
        s = s * scale                                 # (B, KVH, G, T_cache)
        if visible_bias is None:                      # hoistable: pos-only
            t_cache = cache_k.shape[1]
            visible_bias = jnp.where(
                jnp.arange(t_cache)[None, None, None, :] <= pos, 0.0,
                NEG_BIG)
        s = s + visible_bias
        w = jax.nn.softmax(s, axis=-1)                # fp32 stats
        out = jnp.einsum("bkgt,btkd->bkgd", w.astype(cache_v.dtype), cache_v,
                         preferred_element_type=jnp.float32)
        out = out.reshape(b, 1, h_all, hd).astype(x_t.dtype)
        if packed is not None and "o" in packed:
            flat = out.reshape(b, 1, h_all * hd)
            x_t = x_t + _dequant_matmul(flat, packed["o"]["w"],
                                        packed["o"]["scale"],
                                        x_t.dtype) + p["o"]["b"]
        else:
            x_t = x_t + self.attn.out_proj(p, out)
        if packed is not None and "fc1" in packed:
            return (self._mlp_residual_q(params, x_t, packed),
                    {"k": cache_k, "v": cache_v})
        return self._mlp_residual(params, x_t), {"k": cache_k, "v": cache_v}

    def _mlp_residual_q(self, params, x, packed):
        """x + MLP(ln2(x)) on int8-quantized decode weights."""
        h = self.ln2.apply(params["ln2"], x)
        u = _dequant_matmul(h, packed["fc1"]["w"], packed["fc1"]["scale"],
                            h.dtype) + params["fc1"]["b"]
        if self.fc_gate is not None:
            g = _dequant_matmul(h, packed["fc_gate"]["w"],
                                packed["fc_gate"]["scale"],
                                h.dtype) + params["fc_gate"]["b"]
            u = jax.nn.silu(g) * u
        else:
            u = jax.nn.gelu(u)
        y = _dequant_matmul(u, packed["fc2"]["w"], packed["fc2"]["scale"],
                            x.dtype) + params["fc2"]["b"]
        return x + y

    def axes(self):
        out = {"ln1": self.ln1.axes(), "ln2": self.ln2.axes(),
               "attn": self.attn.axes(), "fc1": self.fc1.axes(),
               "fc2": self.fc2.axes()}
        if self.fc_gate is not None:
            out["fc_gate"] = self.fc_gate.axes()
        return out


@dataclasses.dataclass
class GPT(Module):
    """Token+position embeddings -> scanned decoder stack -> tied LM head."""

    cfg: GPTConfig

    def __post_init__(self):
        cfg = self.cfg
        if cfg.pipeline_schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"pipeline_schedule must be 'gpipe' or "
                             f"'1f1b', got {cfg.pipeline_schedule!r}")
        if cfg.layer_loop not in ("scan", "unroll"):
            raise ValueError(f"layer_loop must be 'scan' or 'unroll', "
                             f"got {cfg.layer_loop!r}")
        self.tok = Embedding(cfg.vocab_size, cfg.dim, cfg.dtype)
        # RoPE rotates q/k inside the blocks; no position table then.
        self.pos = None if cfg.rope else Embedding(cfg.max_len, cfg.dim,
                                                   cfg.dtype)
        self.block = GPTBlock(cfg)
        self.ln_f = LayerNorm(cfg.dim)

    def init(self, key):
        kt, kp, ks, kl = jax.random.split(key, 4)
        stacked = jax.vmap(self.block.init)(
            jax.random.split(ks, self.cfg.num_layers))
        out = {"tok": self.tok.init(kt), "layers": stacked,
               "ln_f": self.ln_f.init(kl)}
        if self.pos is not None:
            out["pos"] = self.pos.init(kp)
        return out

    def _embed(self, params, tokens, positions):
        """Token embedding (+ position table unless RoPE)."""
        x = self.tok.apply(params["tok"], tokens)
        if self.pos is not None:
            x = x + self.pos.apply(params["pos"], positions)
        return x

    def _hidden(self, params, tokens, *, train=False):
        """tokens (B, T) -> final hidden states (B, T, D) (pre-head)."""
        t = tokens.shape[1]
        x = self._embed(params, tokens, jnp.arange(t))

        block_fn = self.block.apply
        if self.cfg.remat:
            block_fn = remat(block_fn, self.cfg.remat_policy)

        if self.cfg.pipeline_mesh is not None:
            from dtf_tpu.parallel.pipeline import pipeline_apply
            x, _ = pipeline_apply(
                self._stage_fn(), self._grouped_layers(params), x,
                self.cfg.pipeline_mesh,
                num_microbatches=self.cfg.pipeline_microbatches)
            return self.ln_f.apply(params["ln_f"], x)

        if self.cfg.layer_loop == "unroll":
            # see models/bert.py encode: plain buffers beat scan-stacked
            # remat saves at large shapes
            for l in range(self.cfg.num_layers):
                lp = jax.tree_util.tree_map(lambda a: a[l],
                                            params["layers"])
                x = block_fn(lp, x)
            return self.ln_f.apply(params["ln_f"], x)

        def body(carry, lp):
            return block_fn(lp, carry), None

        x, _ = lax.scan(body, x, params["layers"])
        return self.ln_f.apply(params["ln_f"], x)

    def apply(self, params, tokens, *, train=False, rng=None):
        """tokens (B, T) -> logits (B, T, V)."""
        h = self._hidden(params, tokens, train=train)
        return self.tok.attend(params["tok"], h).astype(jnp.float32)

    def axes(self):
        # leading (stacked-layer) dim: the pipeline "stage" logical axis
        # when pipelined, replicated for the scan path (cf. models/bert.py)
        lead = "stage" if self.cfg.pipeline_mesh is not None else None
        layer_axes = jax.tree_util.tree_map(
            lambda ax: (lead, *ax), self.block.axes(),
            is_leaf=lambda x: isinstance(x, tuple) and all(
                a is None or isinstance(a, str) for a in x))
        out = {"tok": self.tok.axes(), "layers": layer_axes,
               "ln_f": self.ln_f.axes()}
        if self.pos is not None:
            out["pos"] = {"table": (None, "embed")}
        return out

    # --- 1F1B pipelined training (loss + grads in one schedule) --------

    @property
    def custom_grads_fn(self):
        """Trainer seam for self-gradient models (cf. models/bert.py):
        1F1B cannot be expressed as jax.grad of a forward pass."""
        cfg = self.cfg
        if cfg.pipeline_mesh is None or cfg.pipeline_schedule != "1f1b":
            return None
        return self.pipeline_loss_and_grads

    def _grouped_layers(self, params):
        """(L, ...) stacked block params -> (S, L/S, ...) pipeline stages."""
        s = self.cfg.pipeline_mesh.shape["pipe"]
        n_layers = self.cfg.num_layers
        if n_layers % s:
            raise ValueError(f"{n_layers} layers not divisible by pipe={s}")
        return jax.tree_util.tree_map(
            lambda p: p.reshape(s, n_layers // s, *p.shape[1:]),
            params["layers"])

    def _stage_fn(self):
        """Pipeline stage: a block group under the schedule contract
        ``(stage_params, h, ctx) -> (h, aux)``."""
        block_fn = self.block.apply
        if self.cfg.remat:
            block_fn = remat(block_fn, self.cfg.remat_policy)

        def stage(stage_params, h, ctx):
            def body(carry, lp):
                return block_fn(lp, carry), None
            h, _ = lax.scan(body, h, stage_params)
            return h, jnp.zeros((), jnp.float32)

        return stage

    def _head_loss_mb(self, head_params, y_mb, ctx_mb):
        """Per-microbatch next-token CE on the pre-ln_f hidden states —
        the ``loss_fn`` the 1F1B schedule runs inside the last stage.
        Every position weighs equally, so the mean of per-microbatch means
        equals the dense path's global mean."""
        from dtf_tpu.nn.losses import smooth_token_logp

        h = self.ln_f.apply(head_params["ln_f"], y_mb)[:, :-1]
        logits = self.tok.attend(head_params["tok"], h).astype(jnp.float32)
        targets = ctx_mb["tokens"][:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_logp = jnp.take_along_axis(logp, targets[..., None],
                                       axis=-1)[..., 0]
        sl = smooth_token_logp(logp, tok_logp, self.cfg.label_smoothing)
        return -jnp.mean(sl)

    def pipeline_loss_and_grads(self, params, batch, rng=None):
        """1F1B training pass (loss, metrics, grads) — embeddings under an
        outer jax.vjp, decoder stages on the tick schedule, ln_f + tied
        head inside the last stage; the token table sums gradient from
        both its embedding and head uses."""
        from dtf_tpu.parallel.pipeline import pipeline_train_1f1b

        cfg = self.cfg
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        emb_params = {"tok": params["tok"]}
        if self.pos is not None:
            emb_params["pos"] = params["pos"]

        def embed(ep):
            x = self.tok.apply(ep["tok"], tokens)
            if self.pos is not None:
                x = x + self.pos.apply(ep["pos"],
                                       jnp.arange(tokens.shape[1]))
            return x

        x0, embed_vjp = jax.vjp(embed, emb_params)
        head_params = {"ln_f": params["ln_f"], "tok": params["tok"]}

        loss, sgrads, hgrads, dx0 = pipeline_train_1f1b(
            self._stage_fn(), self._head_loss_mb,
            self._grouped_layers(params), head_params,
            x0, {"tokens": tokens}, cfg.pipeline_mesh,
            num_microbatches=cfg.pipeline_microbatches)
        (demb,) = embed_vjp(dx0.astype(x0.dtype))

        layer_grads = jax.tree_util.tree_map(
            lambda g: g.reshape(cfg.num_layers, *g.shape[2:]), sgrads)
        grads = {"tok": jax.tree_util.tree_map(jnp.add, demb["tok"],
                                               hgrads["tok"]),
                 "layers": layer_grads, "ln_f": hgrads["ln_f"]}
        if self.pos is not None:
            grads["pos"] = demb["pos"]
        grads = jax.tree_util.tree_map(
            lambda g, p: g.astype(p.dtype), grads, params)
        # accuracy/perplexity are not computed inside the 1F1B schedule
        # (the last stage only reduces the loss); omit the keys rather
        # than emit NaN sentinels a CSV consumer could read as divergence.
        return loss, {}, grads

    # --- training objective -------------------------------------------

    def _loss_chunked(self, params, tokens, train):
        """CE over T-chunks via nn.losses.chunked_token_ce (the shared
        GPT/T5 memory lever, cfg.loss_chunk): backward recomputes each
        chunk's logits from its (B, C, D) hidden slice instead of saving
        the (B, T, V) fp32 logits."""
        from dtf_tpu.nn.losses import chunked_token_ce

        cfg = self.cfg
        h = self._hidden(params, tokens, train=train)[:, :-1]
        targets = tokens[:, 1:]
        b, t1, _ = h.shape
        weights = jnp.ones((b, t1), jnp.float32)
        nll, sm, acc, wsum = chunked_token_ce(
            lambda hc: self.tok.attend(params["tok"], hc), h, targets,
            weights, cfg.label_smoothing, cfg.loss_chunk)
        nll = nll / wsum             # wsum == b * t1 (every position real)
        return sm / wsum, {"accuracy": acc / wsum,
                           "perplexity": jnp.exp(jnp.minimum(nll, 20.0))}

    def loss(self, params, batch, rng=None, train=True):
        """Next-token cross-entropy (optionally label-smoothed, see
        GPTConfig.label_smoothing).  batch: tokens (B, T) int32.

        The forward runs on the FULL sequence and the logits are shifted
        (not the tokens): T stays a flash-kernel-friendly power-of-two
        instead of T-1.
        """
        from dtf_tpu.nn.losses import smooth_token_logp

        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        if self.cfg.loss_chunk > 0:
            return self._loss_chunked(params, tokens, train)
        logits = self.apply(params, tokens, train=train)[:, :-1]
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_logp = jnp.take_along_axis(logp, targets[..., None],
                                       axis=-1)[..., 0]
        # perplexity stays exp(true NLL), comparable across smoothing
        # settings; only the optimized loss is smoothed.
        nll = -jnp.mean(tok_logp)
        loss = -jnp.mean(smooth_token_logp(logp, tok_logp,
                                           self.cfg.label_smoothing))
        acc = jnp.mean((jnp.argmax(logits, -1) == targets)
                       .astype(jnp.float32))
        return loss, {"accuracy": acc,
                      "perplexity": jnp.exp(jnp.minimum(nll, 20.0))}

    def eval_metrics(self, params, batch):
        loss, aux = self.loss(params, batch, train=False)
        return {"loss": loss, **aux}

    # --- autoregressive generation ------------------------------------

    def _cache_len(self, total: int) -> int:
        """Lane-aligned live cache length for a prompt+new total: decode
        HBM traffic scales with the cache, so both decode entry points size
        it to the generation actually requested, not max_len.  128 beats
        finer alignments in measurement (64-multiples gave XLA worse
        layouts: ~900 vs ~960 tok/s single-stream).  When max_len clamps
        below the 128-round-up, keep at least 8-alignment if the window
        allows — the fused path's cache chunking needs an 8-aligned
        divisor of T (sublane tiling), and an odd T would otherwise lock
        long-context runs out of it.  With a non-8-aligned max_len there
        is no aligned choice when total lands in (floor8(max_len),
        max_len]; fused decode then fails fast in _check_fused_decode —
        keep max_len 8-aligned if you want fused decode at every
        length."""
        t = min(-(-total // 128) * 128, self.cfg.max_len)
        if t % 8 and -(-total // 8) * 8 <= self.cfg.max_len:
            t = max(t - t % 8, -(-total // 8) * 8)
        return t

    def init_cache(self, batch: int, length: int | None = None):
        """KV cache sized to ``length`` (default cfg.max_len).  Decode HBM
        traffic scales with the cache length, so generate() sizes it to the
        actual prompt+new total instead of always paying for max_len."""
        cfg = self.cfg
        hd = cfg.dim // cfg.num_heads
        kvh = cfg.num_kv_heads or cfg.num_heads    # GQA: H/KVH smaller cache
        shape = (cfg.num_layers, batch, length or cfg.max_len, kvh, hd)
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype)}

    def _prefill_cache(self, params, prompt, cache_len=None):
        """One batched forward over the prompt -> (filled cache, logits at
        the last prompt position).  The prompt is padded to a multiple of 8
        so the flash kernel always has a valid block size (causal
        attention: real positions never see the zero-padded tail, whose
        K/V and outputs are discarded)."""
        b, p_len = prompt.shape
        p_pad = -(-p_len // 8) * 8
        padded = (prompt if p_pad == p_len else jnp.pad(
            prompt, ((0, 0), (0, p_pad - p_len))))
        x = self._embed(params, padded, jnp.arange(p_pad))

        def prefill_layer(carry_x, lp):
            y, k, v = self.block.prefill(lp, carry_x)
            return y, (k, v)

        x, (ks, vs) = lax.scan(prefill_layer, x, params["layers"])
        cache = self.init_cache(b, cache_len)  # (L, B, T_cache, KVH, Dh)
        cache = {"k": cache["k"].at[:, :, :p_len].set(
                     ks[:, :, :p_len].astype(cache["k"].dtype)),
                 "v": cache["v"].at[:, :, :p_len].set(
                     vs[:, :, :p_len].astype(cache["v"].dtype))}
        x = self.ln_f.apply(params["ln_f"], x)
        return cache, self.tok.attend(params["tok"], x)[:, p_len - 1, :]

    def _packed_qkv(self, params, int8: bool = False):
        """Pack every layer's q/k/v projection weights for the decode hot
        loop (see GPTBlock.decode_step).  Computed once per generate call,
        outside the decode scan.

        f32 layout: ``{"wq" (L, D, H, Dh), "bq", "wkv" (L, 2, D, KVH, Dh),
        "bkv"}`` — k and v are STACKED on a fresh axis, never concatenated
        along the head dim.  The head dim is ``'tensor'``-sharded under
        the TP serving mesh, and GSPMD (jax 0.4.37) miscompiles a
        concatenate whose concat dim is sharded: every value comes back
        multiplied by the product of the OTHER mesh axes' sizes (the
        resharding all-gather is summed over them too).
        ``tests/test_gpt.py::test_generate_tp_mesh_matches_single``
        caught it; ``jnp.stack`` introduces an unsharded axis and stays
        exact under every sharding.

        ``int8``: symmetric per-output-channel weight quantization —
        decode streams every weight from HBM each token, so int8 halves
        the dominant traffic; the matmul runs on dequantized tiles
        (y = (x @ w8) * scale), exact up to the ~0.4% per-channel
        rounding.  Same concat-free q + stacked-kv layout as f32, so the
        miscompile above is unreachable from this path too."""
        attn = params["layers"]["attn"]
        n_layers, d = self.cfg.num_layers, self.cfg.dim
        if int8:
            # Same concat-free shape discipline as the f32 pack below —
            # q on its own, k/v STACKED on a fresh axis — so the int8
            # path can never hit the concat-along-sharded-dim miscompile
            # either.  quantize_cols is per-output-column (axis=-2 is the
            # contraction dim), so quantizing the stack == quantizing
            # k and v separately.
            flat_w = lambda t: t["w"].reshape(n_layers, d, -1)
            flat_b = lambda t: t["b"].reshape(n_layers, -1)
            wq, sq = _quantize_cols(flat_w(attn["q"]))
            wkv, skv = _quantize_cols(jnp.stack(
                [flat_w(attn["k"]), flat_w(attn["v"])], axis=1))
            return {"wq": wq, "sq": sq, "bq": flat_b(attn["q"]),
                    "wkv": wkv, "skv": skv,
                    "bkv": jnp.stack([flat_b(attn["k"]),
                                      flat_b(attn["v"])], axis=1)}
        return {"wq": attn["q"]["w"], "bq": attn["q"]["b"],
                "wkv": jnp.stack([attn["k"]["w"], attn["v"]["w"]], axis=1),
                "bkv": jnp.stack([attn["k"]["b"], attn["v"]["b"]], axis=1)}

    def _decode_pack(self, params, int8: bool = False):
        """The decode loop's weight container: packed q/k/v always; with
        ``int8`` every decode matmul operand (qkv, out proj, MLP, tied
        head) is int8-quantized per output channel — decode streams all
        weights from HBM each token, so this halves the dominant traffic
        for ~0.4%-per-channel rounding error."""
        cfg = self.cfg
        layers = {"qkv": self._packed_qkv(params, int8=int8)}
        head = None
        if int8:
            lay = params["layers"]
            n_layers, d = cfg.num_layers, cfg.dim
            ow = lay["attn"]["o"]["w"].reshape(n_layers, -1, d)
            q8 = lambda w: dict(zip(("w", "scale"), _quantize_cols(w)))
            layers["o"] = q8(ow)
            layers["fc1"] = q8(lay["fc1"]["w"])
            layers["fc2"] = q8(lay["fc2"]["w"])
            if self.block.fc_gate is not None:
                layers["fc_gate"] = q8(lay["fc_gate"]["w"])
            head = q8(params["tok"]["table"].T)      # (D, V) per-vocab
        return {"layers": layers, "head": head}

    def _decode_logits(self, params, cache, tok, pos, packed=None):
        """One decode step: token (B', 1) at position ``pos`` through the
        layer stack with the KV cache -> (logits (B', V), new cache).

        The layer scan is fully unrolled: decode is HBM-latency-bound
        (every op is tiny at B~1), and unrolling lets XLA overlap one
        layer's weight streaming with the previous layer's compute instead
        of serializing 12 scan iterations."""
        x = self._embed(params, tok, pos[None])
        xs = (params["layers"], cache["k"], cache["v"])
        if packed is not None:
            xs = xs + (packed["layers"],)
        # the attention visibility bias depends only on pos: one compute
        # for all layers instead of one per layer
        t_cache = cache["k"].shape[2]
        visible_bias = jnp.where(
            jnp.arange(t_cache)[None, None, None, :] <= pos, 0.0, NEG_BIG)

        def layer_scan(carry_x, inputs):
            lp, ck, cv = inputs[:3]
            pk = inputs[3] if packed is not None else None
            y, nc = self.block.decode_step(lp, carry_x,
                                           {"k": ck, "v": cv}, pos,
                                           packed=pk,
                                           visible_bias=visible_bias)
            return y, (nc["k"], nc["v"])

        x, (new_k, new_v) = lax.scan(layer_scan, x, xs, unroll=True)
        x = self.ln_f.apply(params["ln_f"], x)
        if packed is not None and packed.get("head") is not None:
            hq = packed["head"]
            logits = _dequant_matmul(x, hq["w"], hq["scale"],
                                     jnp.float32)[:, 0, :]
        else:
            logits = self.tok.attend(params["tok"], x)[:, 0, :]
        return logits, {"k": new_k, "v": new_v}

    def generate(self, params, prompt, max_new_tokens: int, *,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 1.0, eos_id: Optional[int] = None,
                 rng=None, int8_weights: bool = False,
                 fused: bool = False, kv_int8: bool = False,
                 cache_chunk: Optional[int] = None):
        """Sample continuations.  prompt (B, P) int32 -> (B, P+max_new).

        Two phases, one compiled program:

        * **prefill**: the whole prompt runs through ONE full forward pass
          (large batched matmuls on the MXU, flash attention) that fills
          the KV cache for all P positions at once — not P sequential
          decode steps;
        * **decode**: a ``lax.scan`` over the new positions with the
          static-shape cache; per-step attention masks positions beyond
          the current index so decode compiles once.

        temperature=0 -> greedy; top_k/top_p filter the distribution
        (nn/sampling.py).  With ``eos_id``, every position after a
        sequence's first EOS is forced to ``eos_id`` (static shapes mean
        no early exit — finished rows keep stepping but their output is
        pinned).

        ``fused=True`` routes each decode token through the single-
        ``pallas_call`` stack kernel (ops/decode_kernel.py) instead of the
        op-per-op layer scan — up to 32 streams (in sublane tiles of 8
        on an inner grid dim, so layer weights stream once per layer
        regardless of stream count); composes with ``int8_weights``.
        """
        from dtf_tpu.nn.sampling import sample_token

        cfg = self.cfg
        b, p_len = prompt.shape
        total = p_len + max_new_tokens
        if total > cfg.max_len:
            raise ValueError(f"prompt+new = {total} exceeds max_len "
                             f"{cfg.max_len}")
        if max_new_tokens == 0:
            return prompt
        if rng is None:
            rng = jax.random.key(0)
        if fused:
            return self._generate_fused(
                params, prompt, max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_id=eos_id, rng=rng,
                int8_weights=int8_weights, kv_int8=kv_int8,
                cache_chunk=cache_chunk)
        if kv_int8:
            raise ValueError("kv_int8 is a fused-decode feature; pass "
                             "fused=True (the op-per-op loop keeps the "
                             "fp cache)")
        if cache_chunk is not None:
            raise ValueError("cache_chunk is a fused-decode feature; "
                             "pass fused=True")

        # Cache bounded to the live total (lane-aligned), not max_len.
        cache, logits = self._prefill_cache(params, prompt,
                                            self._cache_len(total))
        rng, sub = jax.random.split(rng)
        first = sample_token(sub, logits, temperature=temperature,
                             top_k=top_k, top_p=top_p)

        out = jnp.zeros((b, total), jnp.int32)
        out = lax.dynamic_update_slice(out, prompt, (0, 0))
        out = out.at[:, p_len].set(first)
        done = (first == eos_id) if eos_id is not None else None

        packed = self._decode_pack(params, int8=int8_weights)

        # ---- decode: scan positions p_len..total-2, each reading the token
        # it just wrote and emitting the next one.
        def step(carry, pos):
            out, cache, rng, done = carry
            tok = lax.dynamic_slice(out, (0, pos), (b, 1))      # (B, 1)
            logits, cache = self._decode_logits(params, cache, tok, pos,
                                                packed)
            rng, sub = jax.random.split(rng)
            nxt = sample_token(sub, logits, temperature=temperature,
                               top_k=top_k, top_p=top_p)
            if eos_id is not None:
                nxt = jnp.where(done, eos_id, nxt)   # pin finished rows
                done = done | (nxt == eos_id)
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, pos + 1))
            return (out, cache, rng, done), None

        (out, _, _, _), _ = lax.scan(step, (out, cache, rng, done),
                                     jnp.arange(p_len, total - 1))
        return out

    def _generate_fused(self, params, prompt, max_new_tokens: int, *,
                        temperature, top_k, top_p, eos_id, rng,
                        int8_weights, kv_int8=False, cache_chunk=None):
        """generate()'s decode loop with the whole layer stack fused into
        ONE Pallas kernel per token (ops/decode_kernel.py) — the per-token
        op count drops from ~170 to ~12, attacking the measured
        op-latency floor of the unfused loop (BASELINE.md round 2).
        Up to 32 streams (tiles of 8 beyond the first sublane tile);
        the cache runs row-major (L, B, T, KVH·Dh) and
        the kernel's k/v outputs are written back with one
        ``dynamic_update_slice`` per token."""
        from dtf_tpu.nn.sampling import sample_token

        cfg = self.cfg
        b, p_len = prompt.shape
        total = p_len + max_new_tokens
        self._check_fused_decode(b, total)

        cache, logits = self._prefill_cache(params, prompt,
                                            self._cache_len(total))
        pack, head_q, kv = self._fused_decode_setup(
            params, cache, int8_weights, kv_int8)

        rng, sub = jax.random.split(rng)
        first = sample_token(sub, logits, temperature=temperature,
                             top_k=top_k, top_p=top_p)
        out = jnp.zeros((b, total), jnp.int32)
        out = lax.dynamic_update_slice(out, prompt, (0, 0))
        out = out.at[:, p_len].set(first)
        done = (first == eos_id) if eos_id is not None else None

        def step(carry, pos):
            out, kv, rng, done = carry
            tok = lax.dynamic_slice(out, (0, pos), (b, 1))
            logits, kv = self._fused_token_logits(
                params, pack, head_q, kv, tok, pos,
                cache_chunk=cache_chunk)
            rng, sub = jax.random.split(rng)
            nxt = sample_token(sub, logits, temperature=temperature,
                               top_k=top_k, top_p=top_p)
            if eos_id is not None:
                nxt = jnp.where(done, eos_id, nxt)
                done = done | (nxt == eos_id)
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, pos + 1))
            return (out, kv, rng, done), None

        (out, _, _, _), _ = lax.scan(step, (out, kv, rng, done),
                                     jnp.arange(p_len, total - 1))
        return out

    def _check_fused_decode(self, n_streams: int,
                            total: Optional[int] = None) -> None:
        """The fused stack kernel's preconditions, shared by generate and
        beam (ONE place so the two paths cannot drift): the kernel's
        stream-count rule (``validate_stream_count`` — up to
        MAX_FUSED_STREAMS, in sublane tiles of 8 beyond the first), no
        pipeline parallelism, and — given the prompt+new ``total`` — an
        8-aligned cache length (checked from ints alone, BEFORE any
        prefill compute is spent)."""
        from dtf_tpu.ops.decode_kernel import validate_stream_count

        validate_stream_count(n_streams)
        if self.cfg.pipeline_mesh is not None:
            raise ValueError("fused decode does not compose with pipeline "
                             "parallelism")
        if total is not None and self._cache_len(total) % 8:
            # _cache_len keeps T 8-aligned whenever an aligned length fits
            # inside max_len; it cannot when total lands in
            # (floor8(max_len), max_len] with a non-8-aligned max_len.
            raise ValueError(
                f"fused decode needs an 8-aligned cache length, got "
                f"T={self._cache_len(total)}: no 8-aligned length >= "
                f"prompt+new = {total} fits under max_len="
                f"{self.cfg.max_len}. Use an 8-aligned max_len (or "
                f"request fewer tokens).")

    def _fused_decode_setup(self, params, cache, int8_weights: bool,
                            kv_int8: bool = False):
        """Shared fused-decode prologue: kernel weight pack, optional int8
        head quantization, and the (L, B, T, KVH, Dh) -> row-major
        (L, B, T, KVH·Dh) cache reshape.  The stream count (B for
        generate, B·W for beam) is the cache's own batch dim — derived,
        not passed, so a wrong caller value cannot silently scramble the
        reshape.

        Returns (pack, head_q, kv) where ``kv`` is the cache tuple the
        fused token step threads through the scan: (ck, cv) in fp, or
        (ck, cv, k_scales, v_scales) when ``kv_int8`` quantizes the
        cache rows (halved cache DMA per token; ``quantize_rows``)."""
        from dtf_tpu.ops.decode_kernel import (fused_decode_pack,
                                               quantize_rows)

        pack = fused_decode_pack(params, self.cfg, int8=int8_weights)
        head_q = (_quantize_cols(params["tok"]["table"].T)
                  if int8_weights else None)
        n_l, n_streams, t_c = cache["k"].shape[:3]
        ck = cache["k"].reshape(n_l, n_streams, t_c, -1)
        cv = cache["v"].reshape(n_l, n_streams, t_c, -1)
        if not kv_int8:
            return pack, head_q, (ck, cv)
        ck, ksc = quantize_rows(ck)
        cv, vsc = quantize_rows(cv)
        return pack, head_q, (ck, cv, ksc, vsc)

    def _fused_token_logits(self, params, pack, head_q, kv, tok, pos,
                            cache_chunk=None):
        """One token for all streams through the fused stack kernel: embed
        ``tok`` (B, 1), run ``fused_decode_step``, write the returned k/v
        rows into the row-major caches at ``pos`` (quantizing them when
        the cache tuple carries int8 scales), project to logits.  Shared
        by :meth:`_generate_fused` and the fused beam path so the two
        decode modes cannot drift."""
        from dtf_tpu.ops.decode_kernel import (fused_decode_step,
                                               quantize_rows)

        cfg = self.cfg
        kv_int8 = len(kv) == 4
        ck, cv = kv[0], kv[1]
        x = self._embed(params, tok, pos[None])[:, 0, :]         # (B, D)
        rope_kw = {}
        if cfg.rope:
            from dtf_tpu.nn.rope import rope_angles
            cos, sin = rope_angles(pos, cfg.dim // cfg.num_heads)
            rope_kw = {"rope_cos": cos, "rope_sin": sin}
        if kv_int8:
            rope_kw.update(cache_k_scale=kv[2], cache_v_scale=kv[3])
        x, k_new, v_new = fused_decode_step(pack, ck, cv, x, pos, cfg,
                                            cache_chunk=cache_chunk,
                                            **rope_kw)
        if kv_int8:
            k_new, ksc_new = quantize_rows(k_new)
            v_new, vsc_new = quantize_rows(v_new)
            ksc = lax.dynamic_update_slice(
                kv[2], ksc_new[:, :, None, :], (0, 0, pos, 0))
            vsc = lax.dynamic_update_slice(
                kv[3], vsc_new[:, :, None, :], (0, 0, pos, 0))
        ck = lax.dynamic_update_slice(ck, k_new[:, :, None, :],
                                      (0, 0, pos, 0))
        cv = lax.dynamic_update_slice(cv, v_new[:, :, None, :],
                                      (0, 0, pos, 0))
        kv = (ck, cv, ksc, vsc) if kv_int8 else (ck, cv)
        h = self.ln_f.apply(params["ln_f"], x[:, None, :])
        if head_q is not None:
            logits = _dequant_matmul(h, head_q[0], head_q[1],
                                     jnp.float32)[:, 0, :]
        else:
            logits = self.tok.attend(params["tok"], h)[:, 0, :]
        return logits, kv

    def beam_search(self, params, prompt, max_new_tokens: int, *,
                    beam_size: int = 4, eos_id: Optional[int] = None,
                    length_penalty: float = 0.0,
                    int8_weights: bool = False, fused: bool = False,
                    kv_int8: bool = False,
                    cache_chunk: Optional[int] = None):
        """Deterministic beam decoding.  prompt (B, P) int32 ->
        (sequences (B, W, P+max_new), scores (B, W)), beams sorted best
        first.

        Same two-phase structure as :meth:`generate` (batched MXU prefill,
        then a ``lax.scan`` decode) with W beams folded into the batch dim;
        between steps the top-W of the W·V continuations are kept and the
        KV cache rows are reordered to follow their beams.  With ``eos_id``
        a finished beam is frozen (its only zero-cost continuation is
        ``eos_id``, so its score stops changing); ``length_penalty`` > 0
        applies the GNMT ``((5+len)/6)^alpha`` normalization to the final
        ranking.

        ``fused=True`` runs each decode token through the single-
        ``pallas_call`` stack kernel (ops/decode_kernel.py): the W beams
        are exactly W decode streams (B·W within the kernel's stream
        rule — up to 32, multiples of 8 beyond the first tile),
        the beam bookkeeping — top-W over W·V, cache-row reordering —
        stays outside the kernel where XLA already handles it well.
        Composes with ``int8_weights``.
        """
        cfg = self.cfg
        b, p_len = prompt.shape
        w = beam_size
        total = p_len + max_new_tokens
        if total > cfg.max_len:
            raise ValueError(f"prompt+new = {total} exceeds max_len "
                             f"{cfg.max_len}")
        if max_new_tokens == 0:
            # mirror generate(): the zero-token edge returns before any
            # fused-path validation (no decode step ever runs)
            return (jnp.repeat(prompt[:, None], w, axis=1),
                    jnp.zeros((b, w), jnp.float32))
        if fused:
            self._check_fused_decode(b * w, total)
        elif kv_int8:
            raise ValueError("kv_int8 is a fused-decode feature; pass "
                             "fused=True")
        elif cache_chunk is not None:
            raise ValueError("cache_chunk is a fused-decode feature; "
                             "pass fused=True")
        v_size = cfg.vocab_size

        cache, logits = self._prefill_cache(params, prompt,
                                            self._cache_len(total))
        logp0 = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        scores, first = lax.top_k(logp0, w)                  # (B, W)

        out = jnp.zeros((b, w, total), jnp.int32)
        out = out.at[:, :, :p_len].set(prompt[:, None])
        out = out.at[:, :, p_len].set(first)
        alive = (first != eos_id) if eos_id is not None else \
            jnp.ones((b, w), bool)

        # all W beams share the prompt: tile the cache into the batch dim
        def tile(c):
            return jnp.repeat(c[:, :, None], w, axis=2).reshape(
                c.shape[0], b * w, *c.shape[2:])
        cache = jax.tree_util.tree_map(tile, cache)

        def reorder_cache(c, beam_idx):
            """Gather cache rows (L, B*W, ...) to follow the chosen beams."""
            cv = c.reshape(c.shape[0], b, w, *c.shape[2:])
            idx = beam_idx.reshape(1, b, w, *([1] * (cv.ndim - 3)))
            return jnp.take_along_axis(cv, idx, axis=2).reshape(c.shape)

        if fused:
            pack, head_q, cache = self._fused_decode_setup(
                params, cache, int8_weights, kv_int8)

            def decode_logits(cache, tok, pos):
                return self._fused_token_logits(
                    params, pack, head_q, cache, tok, pos,
                    cache_chunk=cache_chunk)
        else:
            packed = self._decode_pack(params, int8=int8_weights)

            def decode_logits(cache, tok, pos):
                return self._decode_logits(params, cache, tok, pos, packed)

        def step(carry, pos):
            out, cache, scores, alive = carry
            tok = lax.dynamic_slice(out, (0, 0, pos),
                                    (b, w, 1)).reshape(b * w, 1)
            logits, cache = decode_logits(cache, tok, pos)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            logp = logp.reshape(b, w, v_size)
            if eos_id is not None:
                # finished beams continue only with eos at zero cost
                frozen = jnp.full((v_size,), -1e30,
                                  jnp.float32).at[eos_id].set(0.0)
                logp = jnp.where(alive[..., None], logp, frozen)
            flat = (scores[..., None] + logp).reshape(b, w * v_size)
            scores, idx = lax.top_k(flat, w)                 # (B, W)
            beam_idx, tok_idx = idx // v_size, idx % v_size
            out = jnp.take_along_axis(out, beam_idx[:, :, None], axis=1)
            out = lax.dynamic_update_slice(
                out, tok_idx[:, :, None].astype(jnp.int32), (0, 0, pos + 1))
            alive = jnp.take_along_axis(alive, beam_idx, axis=1)
            if eos_id is not None:
                alive = alive & (tok_idx != eos_id)
            cache = jax.tree_util.tree_map(
                lambda c: reorder_cache(c, beam_idx), cache)
            return (out, cache, scores, alive), None

        (out, _, scores, _), _ = lax.scan(
            step, (out, cache, scores, alive), jnp.arange(p_len, total - 1))

        if eos_id is not None and length_penalty > 0:
            gen = out[:, :, p_len:]
            has_eos = jnp.any(gen == eos_id, axis=-1)
            first_eos = jnp.argmax(gen == eos_id, axis=-1)
            lengths = jnp.where(has_eos, first_eos + 1,
                                max_new_tokens).astype(jnp.float32)
            norm = ((5.0 + lengths) / 6.0) ** length_penalty
            ranked = scores / norm
        else:
            ranked = scores
        order = jnp.argsort(-ranked, axis=-1)
        out = jnp.take_along_axis(out, order[:, :, None], axis=1)
        return out, jnp.take_along_axis(ranked, order, axis=1)
