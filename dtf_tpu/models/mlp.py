"""The reference's MNIST MLP, as a pure function.

Architecture parity with tf_distributed.py:39-81: 784 -> 100 sigmoid -> 10,
weights ~ N(0,1) (tf.random_normal default stddev, :50-53), biases zero
(:55-57), seed 1 (:49).  Two documented numerics deltas (SURVEY.md §7):

* loss: trained with the stable logits-space cross-entropy instead of the
  reference's ``-sum(y_*log(softmax))`` (:68-70), which can produce
  log(0)=-inf;  ``naive_loss`` reproduces the reference formula (a *sum*
  over the batch, not a mean) for comparison/observability parity.
* the reference applied gradients asynchronously per worker; here gradients
  are psum-averaged across the data axis each step (sync DP).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from dtf_tpu.nn.core import Module
from dtf_tpu.nn.layers import Dense
from dtf_tpu.nn.losses import accuracy, naive_cross_entropy, softmax_cross_entropy


@dataclasses.dataclass
class MnistMLP(Module):
    in_dim: int = 784           # tf_distributed.py:43
    hidden: int = 100           # tf_distributed.py:51
    num_classes: int = 10       # tf_distributed.py:46
    init_scale: "float | str" = "reference"   # N(0,1) weights like tf.random_normal

    def __post_init__(self):
        self.l1 = Dense(self.in_dim, self.hidden, init_scale=self.init_scale,
                        axes_in="embed", axes_out="mlp")
        self.l2 = Dense(self.hidden, self.num_classes, init_scale=self.init_scale,
                        axes_in="mlp", axes_out="embed")

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"l1": self.l1.init(k1), "l2": self.l2.init(k2)}

    def apply(self, params, x, *, train=False, rng=None):
        """Returns logits (softmax applied inside the loss, unlike the
        reference's explicit softmax output at tf_distributed.py:65)."""
        h = jax.nn.sigmoid(self.l1.apply(params["l1"], x))   # :61-62
        return self.l2.apply(params["l2"], h)                # :64-65

    def axes(self):
        return {"l1": self.l1.axes(), "l2": self.l2.axes()}

    # --- losses/metrics (the graph ops the reference built, :68-81) ---

    def loss(self, params, batch, rng=None, train=True):
        x, y = batch
        logits = self.apply(params, x, train=train, rng=rng)
        loss = softmax_cross_entropy(logits, y)
        return loss, {"accuracy": accuracy(logits, y),
                      "naive_cost": naive_cross_entropy(jax.nn.softmax(logits), y)}

    def eval_metrics(self, params, batch):
        x, y = batch
        logits = self.apply(params, x, train=False)
        return {"accuracy": accuracy(logits, y),
                "loss": softmax_cross_entropy(logits, y)}
