"""Encoder-decoder (T5-style) sequence-to-sequence transformer.

Third transformer family beside BERT (encoder-only) and GPT (decoder-only);
not in the reference (no sequence models at all, SURVEY.md §5.7).  The
decoder adds the one genuinely new mechanism: **cross-attention** over the
encoder output (q from the decoder stream, k/v from the context — the
``kv_input`` seam on :class:`dtf_tpu.nn.attention.MultiHeadAttention`).

TPU-first structure mirrors models/gpt.py: pre-LN blocks scanned over
stacked per-layer params, static shapes, KV-cache greedy/sampled decoding
where the encoder runs ONCE and each decoder layer's cross K/V are
projected ONCE (generation cost is decoder-side only).  The family's
signature mechanisms are in: **RMSNorm** (``norm``, default) and **bucketed
relative position biases** (``positions="relative"``, default — one shared
bidirectional table for the encoder, one unidirectional for the decoder,
none on cross-attention, nn/relpos.py); learned absolute positions and
LayerNorm remain as config options.  Remaining documented delta from
published T5: gelu FFN instead of relu.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dtf_tpu.nn.attention import (MultiHeadAttention, causal_mask,
                                  dot_product_attention)
from dtf_tpu.nn.core import Module
from dtf_tpu.nn.layers import Dense, Embedding, LayerNorm, RMSNorm
from dtf_tpu.nn.relpos import RelativePositionBias

NEG_BIG = -1e30


@dataclasses.dataclass
class T5Config:
    vocab_size: int = 32000
    dim: int = 512
    enc_layers: int = 6
    dec_layers: int = 6
    num_heads: int = 8
    mlp_dim: int = 2048
    max_src_len: int = 512
    max_tgt_len: int = 512
    dtype: Any = jnp.float32
    remat: bool = False
    pad_id: int = 0           # also the loss mask
    bos_id: int = 1           # decoder start token
    label_smoothing: float = 0.0   # eps of uniform mass in the CE loss
    # Position mechanism: "relative" (T5's bucketed relative position
    # biases, the default) or "absolute" (learned position tables).
    positions: str = "relative"
    relpos_buckets: int = 32
    relpos_max_distance: int = 128
    # Normalization: "rmsnorm" (T5's, the default) or "layernorm".
    norm: str = "rmsnorm"
    # Pipeline parallelism (GPipe): a Mesh with a 'pipe' axis runs BOTH
    # stacks as layer-group stages — encoder pipeline, then decoder
    # pipeline (cross-attention context rides the per-microbatch ctx).
    # The shared relative-position table is tiled into every stage's
    # params and the bias recomputed per stage (it cannot ride ctx: its
    # leading dim is 1, not B).
    pipeline_mesh: Optional[Any] = None
    pipeline_microbatches: int = 2
    # "gpipe": forward pipelines + AD backward for both stacks.  "1f1b":
    # the DECODER stack runs the interleaved 1F1B schedule (O(stages)
    # activation memory; the encoder output rides the schedule's
    # differentiable ctx) while the encoder keeps GPipe-by-AD — see
    # T5.pipeline_loss_and_grads.
    pipeline_schedule: str = "gpipe"
    # >0: compute the CE loss in decoder-T chunks of this size under
    # jax.checkpoint, so the (B, T, V) fp32 logits — at T5-small scale
    # B=16, T=512, V=32k ≈ 1 GB, the single largest activation — are
    # never materialized (same lever as GPTConfig.loss_chunk).
    loss_chunk: int = 0
    # Fused TRAIN-step block kernels (ops/block_kernel.py): encoder
    # self-attn + FFN and decoder self-attn + cross-attn + FFN
    # half-blocks each run as one Pallas kernel (RMSNorm and the learned
    # relpos bias in-kernel; the rel bias and cross-attention use the
    # XLA-reference-vjp backward).
    fused_block: bool = False

    @classmethod
    def small(cls, **kw):
        return cls(**kw)      # T5-small dims are the defaults above

    @classmethod
    def tiny(cls, **kw):
        d = dict(vocab_size=64, dim=32, enc_layers=2, dec_layers=2,
                 num_heads=4, mlp_dim=64, max_src_len=32, max_tgt_len=32)
        d.update(kw)
        return cls(**d)

    def make_norm(self):
        if self.norm == "rmsnorm":
            return RMSNorm(self.dim)
        if self.norm == "layernorm":
            return LayerNorm(self.dim)
        raise ValueError(f"norm must be 'rmsnorm' or 'layernorm', "
                         f"got {self.norm!r}")


class _FFN(Module):
    def __init__(self, cfg: T5Config):
        self.cfg = cfg
        self.ln = cfg.make_norm()
        self.fc1 = Dense(cfg.dim, cfg.mlp_dim, dtype=cfg.dtype,
                         axes_in="embed", axes_out="mlp")
        self.fc2 = Dense(cfg.mlp_dim, cfg.dim, dtype=cfg.dtype,
                         axes_in="mlp", axes_out="embed")

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {"ln": self.ln.init(k1), "fc1": self.fc1.init(k2),
                "fc2": self.fc2.init(k3)}

    def apply(self, params, x, *, train=False, rng=None):
        if self.cfg.fused_block:
            from dtf_tpu.ops.block_kernel import fused_mlp_block
            return fused_mlp_block(x, params["fc1"], params["fc2"],
                                   params["ln"], prenorm=True,
                                   norm=self.cfg.norm)
        h = self.ln.apply(params["ln"], x)
        return x + self.fc2.apply(params["fc2"],
                                  jax.nn.gelu(self.fc1.apply(params["fc1"],
                                                             h)))

    def axes(self):
        return {"ln": self.ln.axes(), "fc1": self.fc1.axes(),
                "fc2": self.fc2.axes()}


class T5EncoderLayer(Module):
    """Pre-LN bidirectional block: x + selfattn(ln(x)); FFN.

    ``bias`` is the stack-shared relative-position bias (1, H, T, T),
    added to the attention logits (None under absolute positions)."""

    def __init__(self, cfg: T5Config):
        self.cfg = cfg
        self.ln = cfg.make_norm()
        self.attn = MultiHeadAttention(cfg.dim, cfg.num_heads, cfg.dtype)
        self.ffn = _FFN(cfg)

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {"ln": self.ln.init(k1), "attn": self.attn.init(k2),
                "ffn": self.ffn.init(k3)}

    def apply(self, params, x, *, pad_mask=None, bias=None, train=False,
              rng=None):
        if self.cfg.fused_block:
            from dtf_tpu.ops.block_kernel import fused_attn_block
            from dtf_tpu.ops.flash_attention import require_kv_mask
            kv_mask = (None if pad_mask is None else
                       require_kv_mask(pad_mask, x, x, "fused_block"))
            x = fused_attn_block(x, params["attn"], params["ln"],
                                 num_heads=self.cfg.num_heads,
                                 prenorm=True, norm=self.cfg.norm,
                                 kv_mask=kv_mask, rel_bias=bias)
            return self.ffn.apply(params["ffn"], x)
        h = self.ln.apply(params["ln"], x)
        p = params["attn"]
        q, k, v = self.attn.qkv(p, h)
        o = dot_product_attention(q, k, v, mask=pad_mask, bias=bias)
        x = x + self.attn.out_proj(p, o)
        return self.ffn.apply(params["ffn"], x)

    def axes(self):
        return {"ln": self.ln.axes(), "attn": self.attn.axes(),
                "ffn": self.ffn.axes()}


class T5DecoderLayer(Module):
    """Pre-LN causal self-attention -> cross-attention -> FFN.

    ``self_bias`` is the decoder stack's shared unidirectional relative-
    position bias; cross-attention carries no position bias (as in T5)."""

    def __init__(self, cfg: T5Config):
        self.cfg = cfg
        self.ln_self = cfg.make_norm()
        self.ln_cross = cfg.make_norm()
        self.self_attn = MultiHeadAttention(cfg.dim, cfg.num_heads, cfg.dtype)
        self.cross_attn = MultiHeadAttention(cfg.dim, cfg.num_heads,
                                             cfg.dtype)
        self.ffn = _FFN(cfg)

    def init(self, key):
        ks = jax.random.split(key, 5)
        return {"ln_self": self.ln_self.init(ks[0]),
                "self_attn": self.self_attn.init(ks[1]),
                "ln_cross": self.ln_cross.init(ks[2]),
                "cross_attn": self.cross_attn.init(ks[3]),
                "ffn": self.ffn.init(ks[4])}

    def apply(self, params, x, ctx, *, ctx_mask=None, self_bias=None,
              train=False, rng=None):
        t = x.shape[1]
        if self.cfg.fused_block:
            from dtf_tpu.ops.block_kernel import (fused_attn_block,
                                                  fused_cross_attn_block)
            from dtf_tpu.ops.flash_attention import require_kv_mask
            x = fused_attn_block(x, params["self_attn"],
                                 params["ln_self"],
                                 num_heads=self.cfg.num_heads,
                                 causal=True, prenorm=True,
                                 norm=self.cfg.norm, rel_bias=self_bias)
            ctx_kv = (None if ctx_mask is None else
                      require_kv_mask(ctx_mask, x, ctx, "fused_block"))
            x = fused_cross_attn_block(x, ctx, params["cross_attn"],
                                       params["ln_cross"],
                                       num_heads=self.cfg.num_heads,
                                       ctx_kv_mask=ctx_kv,
                                       norm=self.cfg.norm)
        else:
            h = self.ln_self.apply(params["ln_self"], x)
            p = params["self_attn"]
            q, k, v = self.self_attn.qkv(p, h)
            o = dot_product_attention(q, k, v, mask=causal_mask(t),
                                      bias=self_bias)
            x = x + self.self_attn.out_proj(p, o)
            h = self.ln_cross.apply(params["ln_cross"], x)
            x = x + self.cross_attn.apply(params["cross_attn"], h,
                                          kv_input=ctx, mask=ctx_mask)
        return self.ffn.apply(params["ffn"], x)

    def decode_step(self, params, x_t, cache, cross_k, cross_v, pos,
                    ctx_mask=None, self_bias=None):
        """One token: causal self-attn over the KV cache + cross-attn over
        the PRE-PROJECTED encoder K/V (computed once per generate call).
        x_t (B, 1, D); cache {"k","v"} (B, Tmax, H, Dh); cross_k/v
        (B, S, H, Dh); self_bias (1, H, 1, Tmax) — this position's row of
        the decoder relative-position bias."""
        p = params["self_attn"]
        h = self.ln_self.apply(params["ln_self"], x_t)
        q, k_t, v_t = self.self_attn.qkv(p, h)
        cache_k = lax.dynamic_update_slice_in_dim(
            cache["k"], k_t.astype(cache["k"].dtype), pos, axis=1)
        cache_v = lax.dynamic_update_slice_in_dim(
            cache["v"], v_t.astype(cache["v"].dtype), pos, axis=1)
        scale = q.shape[-1] ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       cache_k.astype(jnp.float32)) * scale
        if self_bias is not None:
            s = s + self_bias
        visible = jnp.arange(cache_k.shape[1])[None, None, None, :] <= pos
        s = jnp.where(visible, s, NEG_BIG)
        out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1),
                         cache_v.astype(jnp.float32)).astype(x_t.dtype)
        x_t = x_t + self.self_attn.out_proj(p, out)

        pc = params["cross_attn"]
        h = self.ln_cross.apply(params["ln_cross"], x_t)
        qc = self.cross_attn.q_proj(pc, h)
        sc = jnp.einsum("bqhd,bkhd->bhqk", qc.astype(jnp.float32),
                        cross_k.astype(jnp.float32)) * scale
        if ctx_mask is not None:
            sc = jnp.where(ctx_mask, sc, NEG_BIG)
        outc = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(sc, axis=-1),
                          cross_v.astype(jnp.float32)).astype(x_t.dtype)
        x_t = x_t + self.cross_attn.out_proj(pc, outc)
        return self.ffn.apply(params["ffn"], x_t), {"k": cache_k,
                                                    "v": cache_v}

    def axes(self):
        return {"ln_self": self.ln_self.axes(),
                "self_attn": self.self_attn.axes(),
                "ln_cross": self.ln_cross.axes(),
                "cross_attn": self.cross_attn.axes(),
                "ffn": self.ffn.axes()}


@dataclasses.dataclass
class T5(Module):
    """Shared token embedding -> encoder stack -> decoder stack (causal +
    cross) -> tied LM head."""

    cfg: T5Config

    def __post_init__(self):
        cfg = self.cfg
        if cfg.pipeline_schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"pipeline_schedule must be 'gpipe' or "
                             f"'1f1b', got {cfg.pipeline_schedule!r}")
        if cfg.positions not in ("relative", "absolute"):
            raise ValueError(f"positions must be 'relative' or 'absolute', "
                             f"got {cfg.positions!r}")
        self.relative = cfg.positions == "relative"
        self.tok = Embedding(cfg.vocab_size, cfg.dim, cfg.dtype)
        if self.relative:
            # One table per stack, shared across its layers (T5): encoder
            # bidirectional, decoder unidirectional; none on cross-attn.
            self.relpos_enc = RelativePositionBias(
                cfg.num_heads, cfg.relpos_buckets, cfg.relpos_max_distance,
                bidirectional=True, dtype=cfg.dtype)
            self.relpos_dec = RelativePositionBias(
                cfg.num_heads, cfg.relpos_buckets, cfg.relpos_max_distance,
                bidirectional=False, dtype=cfg.dtype)
        else:
            self.pos_enc = Embedding(cfg.max_src_len, cfg.dim, cfg.dtype)
            self.pos_dec = Embedding(cfg.max_tgt_len, cfg.dim, cfg.dtype)
        self.enc_layer = T5EncoderLayer(cfg)
        self.dec_layer = T5DecoderLayer(cfg)
        self.ln_enc = cfg.make_norm()
        self.ln_dec = cfg.make_norm()

    def init(self, key):
        ks = jax.random.split(key, 7)
        enc = jax.vmap(self.enc_layer.init)(
            jax.random.split(ks[0], self.cfg.enc_layers))
        dec = jax.vmap(self.dec_layer.init)(
            jax.random.split(ks[1], self.cfg.dec_layers))
        out = {"tok": self.tok.init(ks[2]),
               "enc_layers": enc, "dec_layers": dec,
               "ln_enc": self.ln_enc.init(ks[5]),
               "ln_dec": self.ln_dec.init(ks[6])}
        if self.relative:
            out["relpos_enc"] = self.relpos_enc.init(ks[3])
            out["relpos_dec"] = self.relpos_dec.init(ks[4])
        else:
            out["pos_enc"] = self.pos_enc.init(ks[3])
            out["pos_dec"] = self.pos_dec.init(ks[4])
        return out

    def axes(self):
        # leading (stacked-layer) dim: the pipeline "stage" logical axis
        # when pipelined, replicated for the scan path
        lead = "stage" if self.cfg.pipeline_mesh is not None else None
        wrap = lambda ax_tree: jax.tree_util.tree_map(
            lambda ax: (lead, *ax), ax_tree,
            is_leaf=lambda x: isinstance(x, tuple) and all(
                a is None or isinstance(a, str) for a in x))
        out = {"tok": self.tok.axes(),
               "enc_layers": wrap(self.enc_layer.axes()),
               "dec_layers": wrap(self.dec_layer.axes()),
               "ln_enc": self.ln_enc.axes(),
               "ln_dec": self.ln_dec.axes()}
        if self.relative:
            out["relpos_enc"] = self.relpos_enc.axes()
            out["relpos_dec"] = self.relpos_dec.axes()
        else:
            out["pos_enc"] = {"table": (None, "embed")}
            out["pos_dec"] = {"table": (None, "embed")}
        return out

    # --- forward ------------------------------------------------------

    def _pad_mask(self, src):
        """(B, S) -> broadcastable (B, 1, 1, S), True = attend."""
        return (src != self.cfg.pad_id)[:, None, None, :]

    def _grouped_stack(self, layer_params, table):
        """(L, ...) stacked layer params -> {"layers": (S, L/S, ...)}
        pipeline stages, with the shared relpos ``table`` tiled per stage
        (None under absolute positions)."""
        sp = self.cfg.pipeline_mesh.shape["pipe"]
        n = jax.tree_util.tree_leaves(layer_params)[0].shape[0]
        if n % sp:
            raise ValueError(f"{n} layers not divisible by pipe={sp}")
        grouped = {"layers": jax.tree_util.tree_map(
            lambda p: p.reshape(sp, n // sp, *p.shape[1:]), layer_params)}
        if table is not None:
            grouped["table"] = jnp.broadcast_to(table[None],
                                                (sp, *table.shape))
        return grouped

    def _stage_bias(self, stage_params, t, bidirectional):
        """Recompute the stack-shared relpos bias inside a pipeline stage
        from the tiled table (ctx can't carry it: leading dim 1, not B)."""
        if "table" not in stage_params:
            return None
        from dtf_tpu.nn.relpos import relpos_bias
        pos = jnp.arange(t)
        return relpos_bias(stage_params["table"], pos, pos,
                           bidirectional=bidirectional,
                           num_buckets=self.cfg.relpos_buckets,
                           max_distance=self.cfg.relpos_max_distance)

    def encode(self, params, src):
        """src (B, S) int32 -> (hidden (B, S, D), attend-mask)."""
        mask = self._pad_mask(src)
        s = src.shape[1]
        x = self.tok.apply(params["tok"], src)
        bias = None
        if self.relative:
            pos = jnp.arange(s)
            bias = self.relpos_enc.apply(params["relpos_enc"], pos, pos)
        else:
            x = x + self.pos_enc.apply(params["pos_enc"], jnp.arange(s))

        fn = self.enc_layer.apply
        if self.cfg.remat:
            fn = jax.checkpoint(fn)

        if self.cfg.pipeline_mesh is not None:
            from dtf_tpu.parallel.pipeline import pipeline_apply
            grouped = self._grouped_stack(
                params["enc_layers"],
                params["relpos_enc"]["table"] if self.relative else None)

            def stage(sp_params, h, c):
                b = self._stage_bias(sp_params, h.shape[1],
                                     bidirectional=True)
                m4 = c["pad"][:, None, None, :]

                def body(carry, lp):
                    return fn(lp, carry, pad_mask=m4, bias=b), None

                h, _ = lax.scan(body, h, sp_params["layers"])
                return h, jnp.zeros((), jnp.float32)

            x, _ = pipeline_apply(
                stage, grouped, x, self.cfg.pipeline_mesh,
                num_microbatches=self.cfg.pipeline_microbatches,
                ctx={"pad": src != self.cfg.pad_id})
            return self.ln_enc.apply(params["ln_enc"], x), mask

        def body(carry, lp):
            return fn(lp, carry, pad_mask=mask, bias=bias), None

        x, _ = lax.scan(body, x, params["enc_layers"])
        return self.ln_enc.apply(params["ln_enc"], x), mask

    def decode(self, params, tgt_in, ctx, ctx_mask):
        """Teacher-forced decoder pass: tgt_in (B, T) -> logits (B, T, V)."""
        h = self.decode_hidden(params, tgt_in, ctx, ctx_mask)
        return self.tok.attend(params["tok"], h).astype(jnp.float32)

    def decode_hidden(self, params, tgt_in, ctx, ctx_mask):
        """The decoder stack WITHOUT the vocab head: tgt_in (B, T) ->
        post-final-norm hidden states (B, T, D).  Split out so the
        chunked CE loss can run the head per chunk (the (B, T, V) fp32
        logits are the largest activation at T5-small scale: ~1 GB at
        B=16, T=512, V=32k)."""
        t = tgt_in.shape[1]
        x = self.tok.apply(params["tok"], tgt_in)
        bias = None
        if self.relative:
            pos = jnp.arange(t)
            bias = self.relpos_dec.apply(params["relpos_dec"], pos, pos)
        else:
            x = x + self.pos_dec.apply(params["pos_dec"], jnp.arange(t))

        fn = self.dec_layer.apply
        if self.cfg.remat:
            fn = jax.checkpoint(fn)

        if self.cfg.pipeline_mesh is not None:
            from dtf_tpu.parallel.pipeline import pipeline_apply
            grouped = self._grouped_stack(
                params["dec_layers"],
                params["relpos_dec"]["table"] if self.relative else None)

            def stage(sp_params, h, c):
                b = self._stage_bias(sp_params, h.shape[1],
                                     bidirectional=False)
                m4 = c["ctx_valid"][:, None, None, :]

                def body(carry, lp):
                    return fn(lp, carry, c["ctx"], ctx_mask=m4,
                              self_bias=b), None

                h, _ = lax.scan(body, h, sp_params["layers"])
                return h, jnp.zeros((), jnp.float32)

            x, _ = pipeline_apply(
                stage, grouped, x, self.cfg.pipeline_mesh,
                num_microbatches=self.cfg.pipeline_microbatches,
                ctx={"ctx": ctx, "ctx_valid": ctx_mask[:, 0, 0, :]})
            return self.ln_dec.apply(params["ln_dec"], x)

        def body(carry, lp):
            return fn(lp, carry, ctx, ctx_mask=ctx_mask, self_bias=bias), None

        x, _ = lax.scan(body, x, params["dec_layers"])
        return self.ln_dec.apply(params["ln_dec"], x)

    def apply(self, params, batch, *, train=False, rng=None):
        src, tgt_in = batch
        ctx, mask = self.encode(params, src)
        return self.decode(params, tgt_in, ctx, mask)

    def _shift_right(self, tgt):
        return jnp.concatenate(
            [jnp.full((tgt.shape[0], 1), self.cfg.bos_id, tgt.dtype),
             tgt[:, :-1]], axis=1)

    def _loss_chunked(self, params, src, tgt, train):
        """CE over decoder-T chunks via nn.losses.chunked_token_ce (the
        shared GPT/T5 memory lever): the (B, T, V) fp32 logits never
        materialize; pad-position weights are 0, so the injected chunk
        pad rows drop out."""
        from dtf_tpu.nn.losses import chunked_token_ce

        cfg = self.cfg
        ctx, mask = self.encode(params, src)
        h = self.decode_hidden(params, self._shift_right(tgt), ctx, mask)
        weights = (tgt != cfg.pad_id).astype(jnp.float32)
        _, sm, acc, wsum = chunked_token_ce(
            lambda hc: self.tok.attend(params["tok"], hc), h, tgt,
            weights, cfg.label_smoothing, cfg.loss_chunk)
        denom = jnp.maximum(wsum, 1.0)
        return sm / denom, {"accuracy": acc / denom}

    def loss(self, params, batch, rng=None, train=True):
        """batch: {"src": (B, S), "tgt": (B, T)} int32.  Cross-entropy on
        the decoder's next-token predictions, pad positions masked out.
        With cfg.loss_chunk > 0 the head runs per T-chunk under
        jax.checkpoint (see _loss_chunked)."""
        src, tgt = batch["src"], batch["tgt"]
        if self.cfg.loss_chunk > 0:
            return self._loss_chunked(params, src, tgt, train)
        logits = self.apply(params, (src, self._shift_right(tgt)),
                            train=train, rng=rng)
        from dtf_tpu.nn.losses import smooth_token_logp

        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_logp = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        tok_logp = smooth_token_logp(logp, tok_logp,
                                     self.cfg.label_smoothing)
        weight = (tgt != self.cfg.pad_id).astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(weight), 1.0)
        loss = -jnp.sum(tok_logp * weight) / denom
        acc = jnp.sum((jnp.argmax(logits, -1) == tgt) * weight) / denom
        return loss, {"accuracy": acc}

    def eval_metrics(self, params, batch):
        loss, aux = self.loss(params, batch, train=False)
        return {"loss": loss, **aux}

    def train_flops_per_example(self, params) -> float:
        """Honest 6·P·tokens for an encoder-decoder: each stack's params
        only process THEIR side's tokens (6·P_total·(S+T) would roughly
        double-count).  Split: encoder params × S, decoder params × T —
        except the cross-attention K/V projections, which run on the S
        encoder positions — plus the tied vocab head × T.  Uses the
        configured max lengths (the benchmark drives full-length
        batches)."""
        from dtf_tpu.nn.core import count_params
        cfg = self.cfg
        s_len, t_len = cfg.max_src_len, cfg.max_tgt_len
        p_enc = count_params(params["enc_layers"]) + count_params(
            params["ln_enc"])
        dec = params["dec_layers"]
        p_cross_kv = count_params(dec["cross_attn"]["k"]) + count_params(
            dec["cross_attn"]["v"])
        p_dec = (count_params(dec) - p_cross_kv
                 + count_params(params["ln_dec"]))
        p_head = cfg.dim * cfg.vocab_size        # tied table as the head
        return 6.0 * (p_enc * s_len + p_cross_kv * s_len
                      + (p_dec + p_head) * t_len)

    # --- 1F1B pipelined training --------------------------------------

    @property
    def custom_grads_fn(self):
        """Trainer seam for models that produce their own gradients (cf.
        models/bert.py): non-None when configured for the 1F1B decoder
        schedule."""
        if (self.cfg.pipeline_mesh is None
                or self.cfg.pipeline_schedule != "1f1b"):
            return None
        return self.pipeline_loss_and_grads

    def pipeline_loss_and_grads(self, params, batch, rng=None):
        """Two-stack pipelined training pass: (loss, metrics, grads).

        The DECODER stack runs the interleaved 1F1B schedule — its
        activation footprint is O(stages), and every decoder stage's
        cross-attention reads the encoder output through the schedule's
        *differentiable ctx*, whose summed cotangent comes back as
        ``d_ctx``.  The ENCODER (plus both embeddings) runs under an
        outer ``jax.vjp`` with its own GPipe forward pipeline
        (pipeline_apply is AD-differentiable), consuming ``d_ctx`` and
        the schedule's ``dx``.  The tied token table gets gradient from
        all three uses (source embedding, target embedding, logits
        head); the decoder relpos table is tiled per stage and the stage
        grads summed back.

        Loss semantics: the schedule averages per-microbatch losses, and
        each microbatch's CE is weighted by ITS OWN pad count — equal to
        the dense path's global weighted mean only when every microbatch
        carries the same number of non-pad targets (always true for the
        benchmark's full-length batches).  Padded targets still train
        correctly, just under a per-microbatch reweighting.
        """
        from dtf_tpu.parallel.pipeline import pipeline_train_1f1b
        from dtf_tpu.nn.losses import smooth_token_logp

        cfg = self.cfg
        src, tgt = batch["src"], batch["tgt"]
        tgt_in = self._shift_right(tgt)
        t = tgt_in.shape[1]

        outer_keys = ["tok", "enc_layers", "ln_enc"]
        outer_keys += (["relpos_enc"] if self.relative
                       else ["pos_enc", "pos_dec"])
        outer = {k: params[k] for k in outer_keys}

        def embed_and_encode(op):
            ctx, _ = self.encode({**params, **op}, src)
            x = self.tok.apply(op["tok"], tgt_in)
            if not self.relative:
                x = x + self.pos_dec.apply(op["pos_dec"], jnp.arange(t))
            return x, ctx

        (x0, enc_out), outer_vjp = jax.vjp(embed_and_encode, outer)

        grouped = self._grouped_stack(
            params["dec_layers"],
            params["relpos_dec"]["table"] if self.relative else None)
        head_params = {"ln_dec": params["ln_dec"], "tok": params["tok"]}

        fn = self.dec_layer.apply
        if cfg.remat:
            fn = jax.checkpoint(fn)

        def stage(sp_params, h, c):
            b = self._stage_bias(sp_params, h.shape[1],
                                 bidirectional=False)
            m4 = c["ctx_valid"][:, None, None, :]

            def body(carry, lp):
                return fn(lp, carry, c["ctx"], ctx_mask=m4,
                          self_bias=b), None

            h, _ = lax.scan(body, h, sp_params["layers"])
            return h, jnp.zeros((), jnp.float32)

        def head_loss(hp, y, c):
            x = self.ln_dec.apply(hp["ln_dec"], y)
            logits = self.tok.attend(hp["tok"], x).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            tok_logp = jnp.take_along_axis(
                logp, c["tgt"][..., None], axis=-1)[..., 0]
            tok_logp = smooth_token_logp(logp, tok_logp,
                                         cfg.label_smoothing)
            weight = (c["tgt"] != cfg.pad_id).astype(jnp.float32)
            denom = jnp.maximum(jnp.sum(weight), 1.0)
            return -jnp.sum(tok_logp * weight) / denom

        ctx_valid = (src != cfg.pad_id)
        loss, sgrads, hgrads, dx0, ddctx = pipeline_train_1f1b(
            stage, head_loss, grouped, head_params, x0,
            {"ctx_valid": ctx_valid, "tgt": tgt}, cfg.pipeline_mesh,
            num_microbatches=cfg.pipeline_microbatches,
            diff_ctx={"ctx": enc_out})

        (douter,) = outer_vjp((dx0.astype(x0.dtype),
                               ddctx["ctx"].astype(enc_out.dtype)))

        n_dec = cfg.dec_layers
        dec_grads = jax.tree_util.tree_map(
            lambda g: g.reshape(n_dec, *g.shape[2:]), sgrads["layers"])
        grads = {k: douter[k] for k in outer_keys if k != "tok"}
        grads["tok"] = jax.tree_util.tree_map(jnp.add, douter["tok"],
                                              hgrads["tok"])
        grads["dec_layers"] = dec_grads
        grads["ln_dec"] = hgrads["ln_dec"]
        if self.relative:
            grads["relpos_dec"] = {"table": jnp.sum(sgrads["table"],
                                                    axis=0)}
        missing = set(params) - set(grads)
        assert not missing, f"grads missing for params: {missing}"
        grads = jax.tree_util.tree_map(
            lambda g, p: g.astype(p.dtype), grads, params)
        # accuracy is not computed inside the 1F1B schedule (the last
        # stage only reduces the loss); the key is omitted (cf. bert.py).
        return loss, {}, grads

    # --- generation ---------------------------------------------------

    def generate(self, params, src, max_new_tokens: int, *,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, rng=None):
        """src (B, S) -> generated target (B, max_new_tokens), starting
        from BOS.  The encoder runs once; each decoder layer's cross K/V
        are projected once; decode is a ``lax.scan`` with a self KV cache.
        """
        from dtf_tpu.nn.sampling import sample_token

        cfg = self.cfg
        if max_new_tokens > cfg.max_tgt_len:
            raise ValueError(f"{max_new_tokens} exceeds max_tgt_len "
                             f"{cfg.max_tgt_len}")
        b = src.shape[0]
        if rng is None:
            rng = jax.random.key(0)
        ctx, ctx_mask = self.encode(params, src)

        # pre-project every decoder layer's cross K/V from the context
        def cross_kv(lp):
            return self.dec_layer.cross_attn.kv_proj(lp["cross_attn"], ctx)
        cross_k, cross_v = jax.vmap(cross_kv, in_axes=0)(params["dec_layers"])

        hd = cfg.dim // cfg.num_heads
        cache = {"k": jnp.zeros((cfg.dec_layers, b, cfg.max_tgt_len,
                                 cfg.num_heads, hd), cfg.dtype),
                 "v": jnp.zeros((cfg.dec_layers, b, cfg.max_tgt_len,
                                 cfg.num_heads, hd), cfg.dtype)}
        out = jnp.zeros((b, max_new_tokens + 1), jnp.int32)
        out = out.at[:, 0].set(cfg.bos_id)

        def step(carry, pos):
            out, cache, rng = carry
            tok = lax.dynamic_slice(out, (0, pos), (b, 1))
            x = self.tok.apply(params["tok"], tok)
            self_bias = None
            if self.relative:
                self_bias = self.relpos_dec.apply(
                    params["relpos_dec"], pos[None],
                    jnp.arange(cfg.max_tgt_len))      # (1, H, 1, Tmax)
            else:
                x = x + self.pos_dec.apply(params["pos_dec"], pos[None])

            def layer_scan(carry_x, inputs):
                lp, ck, cv, xk, xv = inputs
                y, nc = self.dec_layer.decode_step(
                    lp, carry_x, {"k": ck, "v": cv}, xk, xv, pos,
                    ctx_mask=ctx_mask, self_bias=self_bias)
                return y, (nc["k"], nc["v"])

            x, (nk, nv) = lax.scan(
                layer_scan, x,
                (params["dec_layers"], cache["k"], cache["v"],
                 cross_k, cross_v))
            cache = {"k": nk, "v": nv}
            x = self.ln_dec.apply(params["ln_dec"], x)
            logits = self.tok.attend(params["tok"], x)[:, 0, :]
            rng, sub = jax.random.split(rng)
            nxt = sample_token(sub, logits, temperature=temperature,
                               top_k=top_k, top_p=top_p)
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, pos + 1))
            return (out, cache, rng), None

        (out, _, _), _ = lax.scan(step, (out, cache, rng),
                                  jnp.arange(max_new_tokens))
        return out[:, 1:]     # drop BOS
