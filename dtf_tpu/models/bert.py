"""BERT-base encoder with masked-LM pretraining objective.

North-star workload "BERT-base data-parallel pretrain" (BASELINE.md; the
reference itself has no sequence models, SURVEY.md §5.7).  TPU-first design:

* one encoder-layer function scanned over stacked per-layer params
  (``lax.scan``) — one compiled layer body instead of 12 inlined copies
  (faster compiles, and the stacked leading axis is the natural pipeline
  ("stage") axis for pipeline parallelism);
* logical-axis annotations give megatron tensor parallelism for free via
  the rule table (QKV column-parallel, output row-parallel, MLP in/out
  pair) — no model changes per mesh shape;
* dynamic masking is computed inside the jitted step from the step rng
  (static shapes: a boolean mask + weighted loss, no gathers of dynamic
  size);
* activations bf16-friendly: LayerNorm stats in fp32, loss in fp32.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from dtf_tpu.nn.attention import MultiHeadAttention
from dtf_tpu.nn.core import Module, remat
from dtf_tpu.nn.layers import Dense, Embedding, LayerNorm


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dtype: Any = jnp.float32
    mask_token: int = 103            # [MASK] in the standard vocab
    mask_rate: float = 0.15
    # >0: predict a FIXED number of masked positions per sequence (the
    # standard BERT max_predictions_per_seq recipe).  The MLM head + vocab
    # projection then run on K gathered positions instead of all T — at
    # T=512, K=80 that removes ~85% of the head FLOPs and the (B, T, V)
    # fp32 logits tensor, the single largest activation.  0 = dense head
    # over every position (binomial ~mask_rate masking).
    mlm_predictions: int = 0
    # "scan": lax.scan over stacked layer params (fast compile).
    # "unroll": python loop — XLA keeps each layer's remat saves as plain
    # buffers instead of scan-stacked dynamic-update-slices; measured
    # ~15% faster steps at BERT-base on v5e for slower compiles.
    layer_loop: str = "scan"
    attn_impl: Optional[Any] = None  # pluggable (ring attention etc.)
    # Inner attention when attn_impl is None: the Pallas flash kernel
    # (mask-capable: BERT's key-padding masks run on the kernel) on TPU,
    # the XLA softmax path elsewhere; use_flash forces either.
    use_flash: Optional[bool] = None
    # Pipeline parallelism: set to a Mesh with a 'pipe' axis to run the
    # encoder stack as num_layers/pipe_size-layer stages
    # (parallel/pipeline.py) instead of lax.scan.
    pipeline_mesh: Optional[Any] = None
    pipeline_microbatches: int = 2
    # "gpipe": forward pipeline + AD backward (composes with any loss, all
    # M microbatch activations live).  "1f1b": interleaved fwd/bwd
    # (PipeDream-flush) via BertMLM.pipeline_loss_and_grads — O(S)
    # activations; requires mlm_predictions > 0 (per-microbatch losses
    # must average exactly).
    pipeline_schedule: str = "gpipe"
    # Rematerialization: recompute encoder-layer activations in the backward
    # pass instead of storing them (jax.checkpoint) — trades ~30% more FLOPs
    # for O(num_layers x B x T x D) less HBM, the standard TPU memory lever.
    remat: bool = False
    # Checkpoint policy when remat is on: "full" recomputes everything
    # (max memory savings, ~30% extra FLOPs); "dots" saves matmul outputs
    # and recomputes only elementwise work (most of the memory win at a
    # few % recompute — matmuls are the FLOPs, elementwise is the bulk of
    # the activation bytes).
    remat_policy: str = "full"
    # Mixture-of-Experts: >0 replaces every layer's dense FFN with a MoE of
    # that many experts (nn/moe.py; expert-parallel over the 'expert' mesh
    # axis).  The router's load-balance aux loss is added to the MLM loss
    # with weight moe_aux_weight.
    moe_experts: int = 0
    moe_top_k: int = 1
    moe_aux_weight: float = 0.01
    # Activation sharding constraint: a NamedSharding pinned onto the
    # (B, T, D) hidden stream after the embedding and at every layer
    # boundary (jax.lax.with_sharding_constraint).  Without it GSPMD has
    # to infer the activation layout between the batch-sharded input and
    # the tensor-sharded weights and can pick transition points that
    # force an "involuntary full rematerialization" of the tensor (the
    # spmd_partitioner warning the multichip dryrun used to print 8x).
    # The sharding planner (parallel/planner.py) sets this to
    # batch-over-data-axes automatically under --plan auto; implicit
    # (jit/GSPMD) step only — inside shard_map the data axes are Manual
    # and the hidden stream is already per-shard.
    act_sharding: Optional[Any] = None
    # Fused block kernels (ops/block_kernel.py): the whole attention
    # half-block (LN/qkv/attention/out-proj/residual) and MLP half-block
    # each run as ONE Pallas kernel, keeping the (B,T,3D) qkv and (B,T,F)
    # hidden activations out of HBM.  Dense MHA blocks only (no MoE, no
    # attn_impl override); backward reuses the flash dq/dk/dv kernel.
    fused_block: bool = False

    @classmethod
    def tiny(cls, **kw):
        """Test-size config (CPU-mesh friendly)."""
        d = dict(vocab_size=128, dim=32, num_layers=2, num_heads=4,
                 mlp_dim=64, max_len=32, mask_token=3)
        d.update(kw)
        return cls(**d)


class BertEncoderLayer(Module):
    """Post-LN transformer block (attention -> add&norm -> FFN -> add&norm).

    The FFN is dense by default; with cfg.moe_experts > 0 it is a
    token-choice MoE and ``apply`` additionally returns the router's
    load-balance aux loss (0.0 for the dense FFN) — callers that scan the
    stack accumulate it.
    """

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg
        if cfg.fused_block:
            if cfg.moe_experts > 0:
                raise ValueError("fused_block supports dense FFN blocks "
                                 "only (moe_experts must be 0)")
            if cfg.attn_impl is not None:
                raise ValueError("fused_block replaces the attention impl "
                                 "seam; it does not compose with "
                                 "attn_impl (ring/ulysses)")
        impl = cfg.attn_impl
        if impl is None:
            use_flash = (jax.default_backend() == "tpu"
                         if cfg.use_flash is None else cfg.use_flash)
            if use_flash:
                from dtf_tpu.ops.flash_attention import flash_attention_impl
                impl = flash_attention_impl(causal=False)
        self.attn = MultiHeadAttention(cfg.dim, cfg.num_heads, cfg.dtype,
                                       attn_impl=impl)
        self.ln1 = LayerNorm(cfg.dim)
        self.ln2 = LayerNorm(cfg.dim)
        self.moe = None
        if cfg.moe_experts > 0:
            from dtf_tpu.nn.moe import MoE
            self.moe = MoE(cfg.dim, cfg.mlp_dim, cfg.moe_experts,
                           top_k=cfg.moe_top_k, dtype=cfg.dtype)
        else:
            self.fc1 = Dense(cfg.dim, cfg.mlp_dim, dtype=cfg.dtype,
                             axes_in="embed", axes_out="mlp")
            self.fc2 = Dense(cfg.mlp_dim, cfg.dim, dtype=cfg.dtype,
                             axes_in="mlp", axes_out="embed")

    def _ffn_units(self):
        if self.moe is not None:
            return [("moe", self.moe)]
        return [("fc1", self.fc1), ("fc2", self.fc2)]

    def init(self, key):
        units = [("attn", self.attn), ("ln1", self.ln1),
                 ("ln2", self.ln2)] + self._ffn_units()
        keys = jax.random.split(key, len(units))
        return {name: m.init(k) for (name, m), k in zip(units, keys)}

    def apply(self, params, x, *, mask=None, train=False, rng=None):
        if self.cfg.fused_block:
            return self._apply_fused(params, x, mask)
        a = self.attn.apply(params["attn"], x, mask=mask)
        x = self.ln1.apply(params["ln1"], x + a)
        if self.moe is not None:
            h, aux = self.moe.apply(params["moe"], x)
        else:
            h = self.fc2.apply(params["fc2"],
                               jax.nn.gelu(self.fc1.apply(params["fc1"], x)))
            aux = jnp.zeros((), jnp.float32)
        return self.ln2.apply(params["ln2"], x + h), aux

    def _apply_fused(self, params, x, mask):
        """Post-LN block through the two fused megakernels
        (ops/block_kernel.py); the padding mask rides the same (B, Tk)
        key-padding contract as the flash kernel."""
        from dtf_tpu.ops.block_kernel import (fused_attn_block,
                                              fused_mlp_block)
        kv_mask = None
        if mask is not None:
            from dtf_tpu.ops.flash_attention import require_kv_mask
            kv_mask = require_kv_mask(mask, x, x, "fused_block")
        x1 = fused_attn_block(x, params["attn"], params["ln1"],
                              num_heads=self.cfg.num_heads,
                              kv_mask=kv_mask)
        y = fused_mlp_block(x1, params["fc1"], params["fc2"],
                            params["ln2"])
        return y, jnp.zeros((), jnp.float32)

    def axes(self):
        units = [("attn", self.attn), ("ln1", self.ln1),
                 ("ln2", self.ln2)] + self._ffn_units()
        return {name: m.axes() for name, m in units}


@dataclasses.dataclass
class BertMLM(Module):
    """Embeddings + scanned encoder stack + tied MLM head."""

    cfg: BertConfig

    def __post_init__(self):
        cfg = self.cfg
        if cfg.pipeline_schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"pipeline_schedule must be 'gpipe' or "
                             f"'1f1b', got {cfg.pipeline_schedule!r}")
        if cfg.layer_loop not in ("scan", "unroll"):
            raise ValueError(f"layer_loop must be 'scan' or 'unroll', "
                             f"got {cfg.layer_loop!r}")
        self.tok = Embedding(cfg.vocab_size, cfg.dim, cfg.dtype)
        self.pos = Embedding(cfg.max_len, cfg.dim, cfg.dtype)
        self.ln_emb = LayerNorm(cfg.dim)
        self.layer = BertEncoderLayer(cfg)
        self.head_fc = Dense(cfg.dim, cfg.dim, dtype=cfg.dtype,
                             axes_in="embed", axes_out="embed")
        self.head_ln = LayerNorm(cfg.dim)

    def init(self, key):
        kt, kp, kl, ks, kh = jax.random.split(key, 5)
        layer_keys = jax.random.split(ks, self.cfg.num_layers)
        stacked = jax.vmap(self.layer.init)(layer_keys)
        return {
            "tok": self.tok.init(kt),
            "pos": self.pos.init(kp),
            "ln_emb": self.ln_emb.init(kl),
            "layers": stacked,                       # leading dim: num_layers
            "head_fc": self.head_fc.init(kh),
            "head_ln": self.head_ln.init(jax.random.fold_in(kh, 1)),
            "head_bias": jnp.zeros((self.cfg.vocab_size,), jnp.float32),
        }

    def active_param_count(self, params) -> int:
        """Params doing FLOPs per token, for MFU accounting
        (workloads/_driver.py): with MoE, each token runs top_k of the E
        experts, so only that fraction of the expert FFN weights counts
        (the always-on router counts fully)."""
        from dtf_tpu.nn.core import count_params
        total = int(count_params(params))
        if self.cfg.moe_experts == 0:
            return total
        expert = sum(
            int(leaf.size)
            for name, sub in params["layers"]["moe"].items()
            if name != "router"
            for leaf in jax.tree_util.tree_leaves(sub))
        frac = min(self.cfg.moe_top_k, self.cfg.moe_experts) / self.cfg.moe_experts
        return total - int(expert * (1.0 - frac))

    def _grouped_layers(self, params):
        """(L, ...) stacked layer params -> (S, L/S, ...) pipeline stages."""
        s = self.cfg.pipeline_mesh.shape["pipe"]
        n_layers = self.cfg.num_layers
        if n_layers % s:
            raise ValueError(f"{n_layers} layers not divisible by pipe={s}")
        return jax.tree_util.tree_map(
            lambda p: p.reshape(s, n_layers // s, *p.shape[1:]),
            params["layers"])

    def _stage_fn(self):
        """Pipeline stage: a block of encoder layers under the schedule
        contract ``(stage_params, h, ctx) -> (h, aux)``.  ``ctx`` may carry
        a per-row key-padding mask (``"pad"``); MoE router aux accumulates
        across the stage's layers.  Expert weights are replicated within a
        stage here (all mesh axes are Manual inside the pipeline's
        shard_map, so the ``expert``-axis GSPMD sharding does not apply)."""

        def stage(stage_params, h, ctx):
            mask = None
            if "pad" in ctx:
                mask = ctx["pad"][:, None, None, :]
            lf = lambda lp, c: self.layer.apply(lp, c, mask=mask)
            if self.cfg.remat:   # honor remat inside pipeline stages too
                lf = remat(lf, self.cfg.remat_policy)

            def body(carry, lp):
                hh, aux = carry
                y, a = lf(lp, hh)
                return (y, aux + a), None

            (h, aux), _ = jax.lax.scan(
                body, (h, jnp.zeros((), jnp.float32)), stage_params)
            return h, aux

        return stage

    def _constrain(self, x):
        """Pin the (B, T, D) hidden stream to cfg.act_sharding (no-op when
        unset): the planner's activation policy, and the annotation that
        keeps GSPMD from involuntarily rematerializing the tensor at
        sharding transitions (BertConfig.act_sharding)."""
        if self.cfg.act_sharding is None:
            return x
        return jax.lax.with_sharding_constraint(x, self.cfg.act_sharding)

    def encode(self, params, tokens, *, pad_mask=None):
        """tokens (B, T) int32 -> hidden (B, T, D)."""
        t = tokens.shape[1]
        x = (self.tok.apply(params["tok"], tokens)
             + self.pos.apply(params["pos"], jnp.arange(t)))
        x = self._constrain(x)
        x = self.ln_emb.apply(params["ln_emb"], x)
        attn_mask = None
        if pad_mask is not None:
            attn_mask = pad_mask[:, None, None, :]   # (B,1,1,Tk)

        if self.cfg.pipeline_mesh is not None:
            if self.cfg.attn_impl is not None:
                raise ValueError(
                    "pipelined encoder requires the default attention: a "
                    "shard_map-based attn_impl (ring attention) cannot nest "
                    "inside the pipeline's shard_map (all mesh axes are "
                    "Manual there); use PP x DP or SP x DP, not PP x SP")
            from dtf_tpu.parallel.pipeline import pipeline_apply
            mesh = self.cfg.pipeline_mesh
            grouped = self._grouped_layers(params)
            ctx = {} if pad_mask is None else {"pad": pad_mask}
            out, moe_aux = pipeline_apply(
                self._stage_fn(), grouped, x, mesh,
                num_microbatches=self.cfg.pipeline_microbatches, ctx=ctx)
            # aux_sum is summed over microbatches (each a per-mb mean);
            # divide by M to match the non-pipelined per-batch mean.
            return out, moe_aux / self.cfg.pipeline_microbatches

        def layer_fn(lp, h):
            y, a = self.layer.apply(lp, h, mask=attn_mask)
            return self._constrain(y), a
        if self.cfg.remat:
            layer_fn = remat(layer_fn, self.cfg.remat_policy)

        if self.cfg.layer_loop == "unroll":
            # Python-unrolled layer loop: XLA manages each layer's saved
            # residuals as plain buffers.  The scanned form stacks them
            # through dynamic-update-slice fusions that run far below HBM
            # peak — measured ~15% whole-step win at BERT-base shapes
            # (BASELINE.md round 3) for a compile-time cost.
            moe_aux = jnp.zeros((), jnp.float32)
            for l in range(self.cfg.num_layers):
                lp = jax.tree_util.tree_map(lambda a: a[l],
                                            params["layers"])
                x, a = layer_fn(lp, x)
                moe_aux = moe_aux + a
            return x, moe_aux

        def body(carry, layer_params):
            h, aux = carry
            y, a = layer_fn(layer_params, h)
            return (y, aux + a), None

        (x, moe_aux), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), params["layers"])
        return x, moe_aux

    def apply(self, params, tokens, *, pad_mask=None, train=False, rng=None,
              return_aux: bool = False):
        """Returns MLM logits (B, T, V) — tied to the token embedding.
        ``return_aux=True`` additionally returns the summed MoE router aux
        loss (0.0 for dense FFNs)."""
        x, moe_aux = self.encode(params, tokens, pad_mask=pad_mask)
        h = jax.nn.gelu(self.head_fc.apply(params["head_fc"], x))
        h = self.head_ln.apply(params["head_ln"], h)
        logits = self.tok.attend(params["tok"], h)
        logits = logits.astype(jnp.float32) + params["head_bias"]
        return (logits, moe_aux) if return_aux else logits

    def axes(self):
        # leading (stacked-layer) dim: the pipeline "stage" logical axis when
        # pipelined (rule ("stage", "pipe")), replicated for the scan path
        lead = "stage" if self.cfg.pipeline_mesh is not None else None
        layer_axes = jax.tree_util.tree_map(
            lambda ax: (lead, *ax), self.layer.axes(),
            is_leaf=lambda x: isinstance(x, tuple) and all(
                a is None or isinstance(a, str) for a in x))
        return {
            "tok": self.tok.axes(), "pos": {"table": (None, "embed")},
            "ln_emb": self.ln_emb.axes(), "layers": layer_axes,
            "head_fc": self.head_fc.axes(), "head_ln": self.head_ln.axes(),
            "head_bias": ("vocab",),
        }

    # --- masked-LM objective -------------------------------------------

    def mask_tokens(self, rng, tokens, pad_mask=None):
        """BERT dynamic masking, static shapes: select ~15% positions; of
        those 80% -> [MASK], 10% -> random token, 10% -> unchanged.
        ``pad_mask`` (B, T) bool True=real: padded positions are never
        selected for prediction."""
        cfg = self.cfg
        r_sel, r_kind, r_rand = jax.random.split(rng, 3)
        selected = jax.random.uniform(r_sel, tokens.shape) < cfg.mask_rate
        if pad_mask is not None:
            selected = selected & pad_mask
        kind = jax.random.uniform(r_kind, tokens.shape)
        random_toks = jax.random.randint(r_rand, tokens.shape, 0, cfg.vocab_size)
        masked = jnp.where(kind < 0.8, cfg.mask_token,
                           jnp.where(kind < 0.9, random_toks, tokens))
        inputs = jnp.where(selected, masked, tokens)
        return inputs, selected

    def mask_tokens_fixed(self, rng, tokens, pad_mask=None):
        """Fixed-K masking: select exactly cfg.mlm_predictions positions
        per sequence (top-K of per-position uniform scores — distinct by
        construction), 80/10/10 mask/random/keep.  Returns (inputs,
        idx (B, K), targets (B, K)).  ``pad_mask`` (B, T) bool True=real:
        padded positions score -1 so they are never selected (requires at
        least K real positions per row)."""
        cfg = self.cfg
        k = cfg.mlm_predictions
        r_sel, r_kind, r_rand = jax.random.split(rng, 3)
        scores = jax.random.uniform(r_sel, tokens.shape)
        if pad_mask is not None:
            scores = jnp.where(pad_mask, scores, -1.0)
        _, idx = jax.lax.top_k(scores, k)                    # (B, K)
        targets = jnp.take_along_axis(tokens, idx, axis=1)
        kind = jax.random.uniform(r_kind, idx.shape)
        random_toks = jax.random.randint(r_rand, idx.shape, 0,
                                         cfg.vocab_size)
        masked = jnp.where(kind < 0.8, cfg.mask_token,
                           jnp.where(kind < 0.9, random_toks, targets))
        inputs = tokens.at[jnp.arange(tokens.shape[0])[:, None], idx].set(
            masked)
        return inputs, idx, targets

    def _loss_fixed_k(self, params, tokens, rng, train, pad_mask=None):
        """MLM loss with the K-position head: encoder over all T, head +
        vocab projection over the K gathered positions only."""
        inputs, idx, targets = self.mask_tokens_fixed(rng, tokens, pad_mask)
        x, moe_aux = self.encode(params, inputs, pad_mask=pad_mask)
        h = jnp.take_along_axis(x, idx[..., None], axis=1)   # (B, K, D)
        h = jax.nn.gelu(self.head_fc.apply(params["head_fc"], h))
        h = self.head_ln.apply(params["head_ln"], h)
        logits = self.tok.attend(params["tok"], h)
        logits = logits.astype(jnp.float32) + params["head_bias"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_logp = jnp.take_along_axis(logp, targets[..., None],
                                       axis=-1)[..., 0]
        loss = -jnp.mean(tok_logp)
        acc = jnp.mean((jnp.argmax(logits, -1) == targets)
                       .astype(jnp.float32))
        metrics = {"accuracy": acc,
                   "masked_frac": jnp.float32(self.cfg.mlm_predictions
                                              / tokens.shape[1])}
        if self.cfg.moe_experts > 0:
            loss = loss + self.cfg.moe_aux_weight * moe_aux
            metrics["moe_aux"] = moe_aux
        return loss, metrics

    def train_flops_per_example(self, params) -> float:
        """Actual per-example train FLOPs under the 6·P·T convention: the
        encoder runs on all T positions, the MLM head (head_fc D^2 + tied
        vocab projection D·V) only on the K predicted positions.  Keeps
        the benchmark's MFU honest when mlm_predictions shrinks the head
        instead of silently inflating it with FLOPs that never ran."""
        cfg = self.cfg
        p_active = self.active_param_count(params)
        p_head = cfg.dim * cfg.vocab_size + cfg.dim * cfg.dim
        t = cfg.max_len
        k = cfg.mlm_predictions or t
        return 6.0 * ((p_active - p_head) * t + p_head * k)

    # --- 1F1B pipelined training (loss + grads in one schedule) --------

    @property
    def custom_grads_fn(self):
        """The trainer's seam for models that must produce their own
        gradients: 1F1B interleaves forward and backward microbatches
        inside one schedule, so ``jax.grad`` over a forward pass cannot
        express it.  None unless configured for 1F1B."""
        cfg = self.cfg
        if cfg.pipeline_mesh is None or cfg.pipeline_schedule != "1f1b":
            return None
        if cfg.mlm_predictions <= 0:
            raise ValueError(
                "1f1b needs mlm_predictions > 0: its loss is the mean of "
                "per-microbatch means, which equals the dense path's "
                "weighted mean only when every row predicts the same "
                "fixed K positions")
        return self.pipeline_loss_and_grads

    def _head_loss_mb(self, head_params, y_mb, ctx_mb):
        """Per-microbatch MLM loss on the K gathered positions — the
        ``loss_fn`` the 1F1B schedule runs inside the last stage."""
        h = jnp.take_along_axis(y_mb, ctx_mb["idx"][..., None], axis=1)
        h = jax.nn.gelu(self.head_fc.apply(head_params["head_fc"], h))
        h = self.head_ln.apply(head_params["head_ln"], h)
        logits = self.tok.attend(head_params["tok"], h)
        logits = logits.astype(jnp.float32) + head_params["head_bias"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_logp = jnp.take_along_axis(
            logp, ctx_mb["targets"][..., None], axis=-1)[..., 0]
        return -jnp.mean(tok_logp)

    def pipeline_loss_and_grads(self, params, batch, rng):
        """1F1B training pass: (loss, metrics, grads) in one interleaved
        pipeline schedule (parallel/pipeline.py::pipeline_train_1f1b).

        The embedding layers run outside the pipeline under ``jax.vjp``
        (their cotangent is the schedule's dx output); the MLM head runs
        inside the last stage.  The tied token table gets gradient from
        BOTH paths (input embedding + head projection) — summed here.
        """
        from dtf_tpu.parallel.pipeline import pipeline_train_1f1b

        cfg = self.cfg
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        if isinstance(batch, dict) and batch.get("pad_mask") is not None:
            raise NotImplementedError(
                "pad_mask is not threaded through the 1F1B schedule yet; "
                "use the GPipe schedule (which carries it as stage ctx) "
                "or full-length batches")
        if rng is None:
            rng = jax.random.key(0)
        inputs, idx, targets = self.mask_tokens_fixed(rng, tokens)

        emb_params = {"tok": params["tok"], "pos": params["pos"],
                      "ln_emb": params["ln_emb"]}

        def embed(ep):
            t = inputs.shape[1]
            x = (self.tok.apply(ep["tok"], inputs)
                 + self.pos.apply(ep["pos"], jnp.arange(t)))
            return self.ln_emb.apply(ep["ln_emb"], x)

        x0, embed_vjp = jax.vjp(embed, emb_params)

        head_params = {"head_fc": params["head_fc"],
                       "head_ln": params["head_ln"],
                       "head_bias": params["head_bias"],
                       "tok": params["tok"]}
        ctx = {"idx": idx, "targets": targets}
        aux_w = cfg.moe_aux_weight if cfg.moe_experts > 0 else 0.0
        loss, sgrads, hgrads, dx0 = pipeline_train_1f1b(
            self._stage_fn(), self._head_loss_mb, self._grouped_layers(params),
            head_params, x0, ctx, cfg.pipeline_mesh,
            num_microbatches=cfg.pipeline_microbatches, aux_weight=aux_w)
        (demb,) = embed_vjp(dx0.astype(x0.dtype))

        n_layers = cfg.num_layers
        layer_grads = jax.tree_util.tree_map(
            lambda g: g.reshape(n_layers, *g.shape[2:]), sgrads)
        grads = {
            "tok": jax.tree_util.tree_map(jnp.add, demb["tok"],
                                          hgrads["tok"]),
            "pos": demb["pos"],
            "ln_emb": demb["ln_emb"],
            "layers": layer_grads,
            "head_fc": hgrads["head_fc"],
            "head_ln": hgrads["head_ln"],
            "head_bias": hgrads["head_bias"],
        }
        # grads in param dtype (value_and_grad convention the optimizer
        # states were built around)
        grads = jax.tree_util.tree_map(
            lambda g, p: g.astype(p.dtype), grads, params)
        # accuracy is not computed inside the 1F1B schedule (the last
        # stage only reduces the loss); omit the key rather than emit a
        # NaN sentinel a CSV consumer could read as divergence.
        metrics = {"masked_frac": jnp.float32(cfg.mlm_predictions
                                              / tokens.shape[1])}
        return loss, metrics, grads

    def loss(self, params, batch, rng=None, train=True):
        """batch: tokens (B, T) int32 (labels are the tokens themselves)."""
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        pad_mask = batch.get("pad_mask") if isinstance(batch, dict) else None
        if rng is None:
            rng = jax.random.key(0)
        if self.cfg.mlm_predictions > 0:
            return self._loss_fixed_k(params, tokens, rng, train, pad_mask)
        inputs, selected = self.mask_tokens(rng, tokens, pad_mask)
        logits, moe_aux = self.apply(params, inputs, pad_mask=pad_mask,
                                     train=train, return_aux=True)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_logp = jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]
        w = selected.astype(jnp.float32)
        loss = -jnp.sum(tok_logp * w) / jnp.maximum(jnp.sum(w), 1.0)
        acc = (jnp.sum((jnp.argmax(logits, -1) == tokens) * w)
               / jnp.maximum(jnp.sum(w), 1.0))
        metrics = {"accuracy": acc, "masked_frac": jnp.mean(w)}
        if self.cfg.moe_experts > 0:
            loss = loss + self.cfg.moe_aux_weight * moe_aux
            metrics["moe_aux"] = moe_aux
        return loss, metrics

    def eval_metrics(self, params, batch):
        loss, aux = self.loss(params, batch, rng=jax.random.key(123),
                              train=False)
        return {"loss": loss, "accuracy": aux["accuracy"]}
