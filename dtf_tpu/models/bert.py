"""BERT-base encoder with masked-LM pretraining objective.

North-star workload "BERT-base data-parallel pretrain" (BASELINE.md; the
reference itself has no sequence models, SURVEY.md §5.7).  TPU-first design:

* one encoder-layer function scanned over stacked per-layer params
  (``lax.scan``) — one compiled layer body instead of 12 inlined copies
  (faster compiles, and the stacked leading axis is the natural pipeline
  ("stage") axis for pipeline parallelism);
* logical-axis annotations give megatron tensor parallelism for free via
  the rule table (QKV column-parallel, output row-parallel, MLP in/out
  pair) — no model changes per mesh shape;
* dynamic masking is computed inside the jitted step from the step rng
  (static shapes: a boolean mask + weighted loss, no gathers of dynamic
  size);
* activations bf16-friendly: LayerNorm stats in fp32, loss in fp32.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from dtf_tpu.nn.attention import MultiHeadAttention
from dtf_tpu.nn.core import Module
from dtf_tpu.nn.layers import Dense, Embedding, LayerNorm


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dtype: Any = jnp.float32
    mask_token: int = 103            # [MASK] in the standard vocab
    mask_rate: float = 0.15
    attn_impl: Optional[Any] = None  # pluggable (ring attention etc.)
    # Pipeline parallelism: set to a Mesh with a 'pipe' axis to run the
    # encoder stack as num_layers/pipe_size-layer stages under the GPipe
    # schedule (parallel/pipeline.py) instead of lax.scan.
    pipeline_mesh: Optional[Any] = None
    pipeline_microbatches: int = 2
    # Rematerialization: recompute encoder-layer activations in the backward
    # pass instead of storing them (jax.checkpoint) — trades ~30% more FLOPs
    # for O(num_layers x B x T x D) less HBM, the standard TPU memory lever.
    remat: bool = False

    @classmethod
    def tiny(cls, **kw):
        """Test-size config (CPU-mesh friendly)."""
        d = dict(vocab_size=128, dim=32, num_layers=2, num_heads=4,
                 mlp_dim=64, max_len=32, mask_token=3)
        d.update(kw)
        return cls(**d)


class BertEncoderLayer(Module):
    """Post-LN transformer block (attention -> add&norm -> MLP -> add&norm)."""

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg
        self.attn = MultiHeadAttention(cfg.dim, cfg.num_heads, cfg.dtype,
                                       attn_impl=cfg.attn_impl)
        self.ln1 = LayerNorm(cfg.dim)
        self.ln2 = LayerNorm(cfg.dim)
        self.fc1 = Dense(cfg.dim, cfg.mlp_dim, dtype=cfg.dtype,
                         axes_in="embed", axes_out="mlp")
        self.fc2 = Dense(cfg.mlp_dim, cfg.dim, dtype=cfg.dtype,
                         axes_in="mlp", axes_out="embed")

    def init(self, key):
        ka, k1, k2, kf1, kf2 = jax.random.split(key, 5)
        return {"attn": self.attn.init(ka), "ln1": self.ln1.init(k1),
                "ln2": self.ln2.init(k2), "fc1": self.fc1.init(kf1),
                "fc2": self.fc2.init(kf2)}

    def apply(self, params, x, *, mask=None, train=False, rng=None):
        a = self.attn.apply(params["attn"], x, mask=mask)
        x = self.ln1.apply(params["ln1"], x + a)
        h = self.fc2.apply(params["fc2"],
                           jax.nn.gelu(self.fc1.apply(params["fc1"], x)))
        return self.ln2.apply(params["ln2"], x + h)

    def axes(self):
        return {"attn": self.attn.axes(), "ln1": self.ln1.axes(),
                "ln2": self.ln2.axes(), "fc1": self.fc1.axes(),
                "fc2": self.fc2.axes()}


@dataclasses.dataclass
class BertMLM(Module):
    """Embeddings + scanned encoder stack + tied MLM head."""

    cfg: BertConfig

    def __post_init__(self):
        cfg = self.cfg
        self.tok = Embedding(cfg.vocab_size, cfg.dim, cfg.dtype)
        self.pos = Embedding(cfg.max_len, cfg.dim, cfg.dtype)
        self.ln_emb = LayerNorm(cfg.dim)
        self.layer = BertEncoderLayer(cfg)
        self.head_fc = Dense(cfg.dim, cfg.dim, dtype=cfg.dtype,
                             axes_in="embed", axes_out="embed")
        self.head_ln = LayerNorm(cfg.dim)

    def init(self, key):
        kt, kp, kl, ks, kh = jax.random.split(key, 5)
        layer_keys = jax.random.split(ks, self.cfg.num_layers)
        stacked = jax.vmap(self.layer.init)(layer_keys)
        return {
            "tok": self.tok.init(kt),
            "pos": self.pos.init(kp),
            "ln_emb": self.ln_emb.init(kl),
            "layers": stacked,                       # leading dim: num_layers
            "head_fc": self.head_fc.init(kh),
            "head_ln": self.head_ln.init(jax.random.fold_in(kh, 1)),
            "head_bias": jnp.zeros((self.cfg.vocab_size,), jnp.float32),
        }

    def encode(self, params, tokens, *, pad_mask=None):
        """tokens (B, T) int32 -> hidden (B, T, D)."""
        t = tokens.shape[1]
        x = (self.tok.apply(params["tok"], tokens)
             + self.pos.apply(params["pos"], jnp.arange(t)))
        x = self.ln_emb.apply(params["ln_emb"], x)
        attn_mask = None
        if pad_mask is not None:
            attn_mask = pad_mask[:, None, None, :]   # (B,1,1,Tk)

        if self.cfg.pipeline_mesh is not None:
            if pad_mask is not None:
                raise ValueError("pipelined encoder does not support "
                                 "pad_mask (microbatching would split it)")
            if self.cfg.attn_impl is not None:
                raise ValueError(
                    "pipelined encoder requires the default attention: a "
                    "shard_map-based attn_impl (ring attention) cannot nest "
                    "inside the pipeline's shard_map (all mesh axes are "
                    "Manual there); use PP x DP or SP x DP, not PP x SP")
            from dtf_tpu.parallel.pipeline import pipeline_apply
            mesh = self.cfg.pipeline_mesh
            s = mesh.shape["pipe"]
            n_layers = self.cfg.num_layers
            if n_layers % s:
                raise ValueError(f"{n_layers} layers not divisible by "
                                 f"pipe={s}")
            grouped = jax.tree_util.tree_map(
                lambda p: p.reshape(s, n_layers // s, *p.shape[1:]),
                params["layers"])

            def stage(stage_params, h):
                def body(carry, lp):
                    return self.layer.apply(lp, carry), None
                h, _ = jax.lax.scan(body, h, stage_params)
                return h

            return pipeline_apply(
                stage, grouped, x, mesh,
                num_microbatches=self.cfg.pipeline_microbatches)

        layer_fn = lambda lp, h: self.layer.apply(lp, h, mask=attn_mask)
        if self.cfg.remat:
            layer_fn = jax.checkpoint(layer_fn)

        def body(carry, layer_params):
            return layer_fn(layer_params, carry), None

        x, _ = jax.lax.scan(body, x, params["layers"])
        return x

    def apply(self, params, tokens, *, pad_mask=None, train=False, rng=None):
        """Returns MLM logits (B, T, V) — tied to the token embedding."""
        x = self.encode(params, tokens, pad_mask=pad_mask)
        h = jax.nn.gelu(self.head_fc.apply(params["head_fc"], x))
        h = self.head_ln.apply(params["head_ln"], h)
        logits = self.tok.attend(params["tok"], h)
        return logits.astype(jnp.float32) + params["head_bias"]

    def axes(self):
        # leading (stacked-layer) dim: the pipeline "stage" logical axis when
        # pipelined (rule ("stage", "pipe")), replicated for the scan path
        lead = "stage" if self.cfg.pipeline_mesh is not None else None
        layer_axes = jax.tree_util.tree_map(
            lambda ax: (lead, *ax), self.layer.axes(),
            is_leaf=lambda x: isinstance(x, tuple) and all(
                a is None or isinstance(a, str) for a in x))
        return {
            "tok": self.tok.axes(), "pos": {"table": (None, "embed")},
            "ln_emb": self.ln_emb.axes(), "layers": layer_axes,
            "head_fc": self.head_fc.axes(), "head_ln": self.head_ln.axes(),
            "head_bias": ("vocab",),
        }

    # --- masked-LM objective -------------------------------------------

    def mask_tokens(self, rng, tokens):
        """BERT dynamic masking, static shapes: select ~15% positions; of
        those 80% -> [MASK], 10% -> random token, 10% -> unchanged."""
        cfg = self.cfg
        r_sel, r_kind, r_rand = jax.random.split(rng, 3)
        selected = jax.random.uniform(r_sel, tokens.shape) < cfg.mask_rate
        kind = jax.random.uniform(r_kind, tokens.shape)
        random_toks = jax.random.randint(r_rand, tokens.shape, 0, cfg.vocab_size)
        masked = jnp.where(kind < 0.8, cfg.mask_token,
                           jnp.where(kind < 0.9, random_toks, tokens))
        inputs = jnp.where(selected, masked, tokens)
        return inputs, selected

    def loss(self, params, batch, rng=None, train=True):
        """batch: tokens (B, T) int32 (labels are the tokens themselves)."""
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        if rng is None:
            rng = jax.random.key(0)
        inputs, selected = self.mask_tokens(rng, tokens)
        logits = self.apply(params, inputs, train=train)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_logp = jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]
        w = selected.astype(jnp.float32)
        loss = -jnp.sum(tok_logp * w) / jnp.maximum(jnp.sum(w), 1.0)
        acc = (jnp.sum((jnp.argmax(logits, -1) == tokens) * w)
               / jnp.maximum(jnp.sum(w), 1.0))
        return loss, {"accuracy": acc, "masked_frac": jnp.mean(w)}

    def eval_metrics(self, params, batch):
        loss, aux = self.loss(params, batch, rng=jax.random.key(123),
                              train=False)
        return {"loss": loss, "accuracy": aux["accuracy"]}
