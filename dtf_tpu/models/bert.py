"""BERT-base encoder with masked-LM pretraining objective.

North-star workload "BERT-base data-parallel pretrain" (BASELINE.md; the
reference itself has no sequence models, SURVEY.md §5.7).  TPU-first design:

* one encoder-layer function scanned over stacked per-layer params
  (``lax.scan``) — one compiled layer body instead of 12 inlined copies
  (faster compiles, and the stacked leading axis is the natural pipeline
  ("stage") axis for pipeline parallelism);
* logical-axis annotations give megatron tensor parallelism for free via
  the rule table (QKV column-parallel, output row-parallel, MLP in/out
  pair) — no model changes per mesh shape;
* dynamic masking is computed inside the jitted step from the step rng
  (static shapes: a boolean mask + weighted loss, no gathers of dynamic
  size);
* activations bf16-friendly: LayerNorm stats in fp32, loss in fp32.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from dtf_tpu.nn.attention import MultiHeadAttention
from dtf_tpu.nn.core import Module
from dtf_tpu.nn.layers import Dense, Embedding, LayerNorm


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dtype: Any = jnp.float32
    mask_token: int = 103            # [MASK] in the standard vocab
    mask_rate: float = 0.15
    attn_impl: Optional[Any] = None  # pluggable (ring attention etc.)
    # Pipeline parallelism: set to a Mesh with a 'pipe' axis to run the
    # encoder stack as num_layers/pipe_size-layer stages under the GPipe
    # schedule (parallel/pipeline.py) instead of lax.scan.
    pipeline_mesh: Optional[Any] = None
    pipeline_microbatches: int = 2
    # Rematerialization: recompute encoder-layer activations in the backward
    # pass instead of storing them (jax.checkpoint) — trades ~30% more FLOPs
    # for O(num_layers x B x T x D) less HBM, the standard TPU memory lever.
    remat: bool = False
    # Mixture-of-Experts: >0 replaces every layer's dense FFN with a MoE of
    # that many experts (nn/moe.py; expert-parallel over the 'expert' mesh
    # axis).  The router's load-balance aux loss is added to the MLM loss
    # with weight moe_aux_weight.
    moe_experts: int = 0
    moe_top_k: int = 1
    moe_aux_weight: float = 0.01

    @classmethod
    def tiny(cls, **kw):
        """Test-size config (CPU-mesh friendly)."""
        d = dict(vocab_size=128, dim=32, num_layers=2, num_heads=4,
                 mlp_dim=64, max_len=32, mask_token=3)
        d.update(kw)
        return cls(**d)


class BertEncoderLayer(Module):
    """Post-LN transformer block (attention -> add&norm -> FFN -> add&norm).

    The FFN is dense by default; with cfg.moe_experts > 0 it is a
    token-choice MoE and ``apply`` additionally returns the router's
    load-balance aux loss (0.0 for the dense FFN) — callers that scan the
    stack accumulate it.
    """

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg
        self.attn = MultiHeadAttention(cfg.dim, cfg.num_heads, cfg.dtype,
                                       attn_impl=cfg.attn_impl)
        self.ln1 = LayerNorm(cfg.dim)
        self.ln2 = LayerNorm(cfg.dim)
        self.moe = None
        if cfg.moe_experts > 0:
            from dtf_tpu.nn.moe import MoE
            self.moe = MoE(cfg.dim, cfg.mlp_dim, cfg.moe_experts,
                           top_k=cfg.moe_top_k, dtype=cfg.dtype)
        else:
            self.fc1 = Dense(cfg.dim, cfg.mlp_dim, dtype=cfg.dtype,
                             axes_in="embed", axes_out="mlp")
            self.fc2 = Dense(cfg.mlp_dim, cfg.dim, dtype=cfg.dtype,
                             axes_in="mlp", axes_out="embed")

    def _ffn_units(self):
        if self.moe is not None:
            return [("moe", self.moe)]
        return [("fc1", self.fc1), ("fc2", self.fc2)]

    def init(self, key):
        units = [("attn", self.attn), ("ln1", self.ln1),
                 ("ln2", self.ln2)] + self._ffn_units()
        keys = jax.random.split(key, len(units))
        return {name: m.init(k) for (name, m), k in zip(units, keys)}

    def apply(self, params, x, *, mask=None, train=False, rng=None):
        a = self.attn.apply(params["attn"], x, mask=mask)
        x = self.ln1.apply(params["ln1"], x + a)
        if self.moe is not None:
            h, aux = self.moe.apply(params["moe"], x)
        else:
            h = self.fc2.apply(params["fc2"],
                               jax.nn.gelu(self.fc1.apply(params["fc1"], x)))
            aux = jnp.zeros((), jnp.float32)
        return self.ln2.apply(params["ln2"], x + h), aux

    def axes(self):
        units = [("attn", self.attn), ("ln1", self.ln1),
                 ("ln2", self.ln2)] + self._ffn_units()
        return {name: m.axes() for name, m in units}


@dataclasses.dataclass
class BertMLM(Module):
    """Embeddings + scanned encoder stack + tied MLM head."""

    cfg: BertConfig

    def __post_init__(self):
        cfg = self.cfg
        self.tok = Embedding(cfg.vocab_size, cfg.dim, cfg.dtype)
        self.pos = Embedding(cfg.max_len, cfg.dim, cfg.dtype)
        self.ln_emb = LayerNorm(cfg.dim)
        self.layer = BertEncoderLayer(cfg)
        self.head_fc = Dense(cfg.dim, cfg.dim, dtype=cfg.dtype,
                             axes_in="embed", axes_out="embed")
        self.head_ln = LayerNorm(cfg.dim)

    def init(self, key):
        kt, kp, kl, ks, kh = jax.random.split(key, 5)
        layer_keys = jax.random.split(ks, self.cfg.num_layers)
        stacked = jax.vmap(self.layer.init)(layer_keys)
        return {
            "tok": self.tok.init(kt),
            "pos": self.pos.init(kp),
            "ln_emb": self.ln_emb.init(kl),
            "layers": stacked,                       # leading dim: num_layers
            "head_fc": self.head_fc.init(kh),
            "head_ln": self.head_ln.init(jax.random.fold_in(kh, 1)),
            "head_bias": jnp.zeros((self.cfg.vocab_size,), jnp.float32),
        }

    def active_param_count(self, params) -> int:
        """Params doing FLOPs per token, for MFU accounting
        (workloads/_driver.py): with MoE, each token runs top_k of the E
        experts, so only that fraction of the expert FFN weights counts
        (the always-on router counts fully)."""
        from dtf_tpu.nn.core import count_params
        total = int(count_params(params))
        if self.cfg.moe_experts == 0:
            return total
        expert = sum(
            int(leaf.size)
            for name, sub in params["layers"]["moe"].items()
            if name != "router"
            for leaf in jax.tree_util.tree_leaves(sub))
        frac = min(self.cfg.moe_top_k, self.cfg.moe_experts) / self.cfg.moe_experts
        return total - int(expert * (1.0 - frac))

    def encode(self, params, tokens, *, pad_mask=None):
        """tokens (B, T) int32 -> hidden (B, T, D)."""
        t = tokens.shape[1]
        x = (self.tok.apply(params["tok"], tokens)
             + self.pos.apply(params["pos"], jnp.arange(t)))
        x = self.ln_emb.apply(params["ln_emb"], x)
        attn_mask = None
        if pad_mask is not None:
            attn_mask = pad_mask[:, None, None, :]   # (B,1,1,Tk)

        if self.cfg.pipeline_mesh is not None:
            if pad_mask is not None:
                raise ValueError("pipelined encoder does not support "
                                 "pad_mask (microbatching would split it)")
            if self.cfg.attn_impl is not None:
                raise ValueError(
                    "pipelined encoder requires the default attention: a "
                    "shard_map-based attn_impl (ring attention) cannot nest "
                    "inside the pipeline's shard_map (all mesh axes are "
                    "Manual there); use PP x DP or SP x DP, not PP x SP")
            if self.cfg.moe_experts > 0:
                raise ValueError("pipelined encoder does not support MoE "
                                 "(stage outputs carry activations only, "
                                 "the router aux loss would be dropped)")
            from dtf_tpu.parallel.pipeline import pipeline_apply
            mesh = self.cfg.pipeline_mesh
            s = mesh.shape["pipe"]
            n_layers = self.cfg.num_layers
            if n_layers % s:
                raise ValueError(f"{n_layers} layers not divisible by "
                                 f"pipe={s}")
            grouped = jax.tree_util.tree_map(
                lambda p: p.reshape(s, n_layers // s, *p.shape[1:]),
                params["layers"])

            def stage(stage_params, h):
                lf = lambda lp, c: self.layer.apply(lp, c)[0]
                if self.cfg.remat:   # honor remat inside pipeline stages too
                    lf = jax.checkpoint(lf)

                def body(carry, lp):
                    return lf(lp, carry), None
                h, _ = jax.lax.scan(body, h, stage_params)
                return h

            out = pipeline_apply(
                stage, grouped, x, mesh,
                num_microbatches=self.cfg.pipeline_microbatches)
            return out, jnp.zeros((), jnp.float32)

        layer_fn = lambda lp, h: self.layer.apply(lp, h, mask=attn_mask)
        if self.cfg.remat:
            layer_fn = jax.checkpoint(layer_fn)

        def body(carry, layer_params):
            h, aux = carry
            y, a = layer_fn(layer_params, h)
            return (y, aux + a), None

        (x, moe_aux), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), params["layers"])
        return x, moe_aux

    def apply(self, params, tokens, *, pad_mask=None, train=False, rng=None,
              return_aux: bool = False):
        """Returns MLM logits (B, T, V) — tied to the token embedding.
        ``return_aux=True`` additionally returns the summed MoE router aux
        loss (0.0 for dense FFNs)."""
        x, moe_aux = self.encode(params, tokens, pad_mask=pad_mask)
        h = jax.nn.gelu(self.head_fc.apply(params["head_fc"], x))
        h = self.head_ln.apply(params["head_ln"], h)
        logits = self.tok.attend(params["tok"], h)
        logits = logits.astype(jnp.float32) + params["head_bias"]
        return (logits, moe_aux) if return_aux else logits

    def axes(self):
        # leading (stacked-layer) dim: the pipeline "stage" logical axis when
        # pipelined (rule ("stage", "pipe")), replicated for the scan path
        lead = "stage" if self.cfg.pipeline_mesh is not None else None
        layer_axes = jax.tree_util.tree_map(
            lambda ax: (lead, *ax), self.layer.axes(),
            is_leaf=lambda x: isinstance(x, tuple) and all(
                a is None or isinstance(a, str) for a in x))
        return {
            "tok": self.tok.axes(), "pos": {"table": (None, "embed")},
            "ln_emb": self.ln_emb.axes(), "layers": layer_axes,
            "head_fc": self.head_fc.axes(), "head_ln": self.head_ln.axes(),
            "head_bias": ("vocab",),
        }

    # --- masked-LM objective -------------------------------------------

    def mask_tokens(self, rng, tokens):
        """BERT dynamic masking, static shapes: select ~15% positions; of
        those 80% -> [MASK], 10% -> random token, 10% -> unchanged."""
        cfg = self.cfg
        r_sel, r_kind, r_rand = jax.random.split(rng, 3)
        selected = jax.random.uniform(r_sel, tokens.shape) < cfg.mask_rate
        kind = jax.random.uniform(r_kind, tokens.shape)
        random_toks = jax.random.randint(r_rand, tokens.shape, 0, cfg.vocab_size)
        masked = jnp.where(kind < 0.8, cfg.mask_token,
                           jnp.where(kind < 0.9, random_toks, tokens))
        inputs = jnp.where(selected, masked, tokens)
        return inputs, selected

    def loss(self, params, batch, rng=None, train=True):
        """batch: tokens (B, T) int32 (labels are the tokens themselves)."""
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        if rng is None:
            rng = jax.random.key(0)
        inputs, selected = self.mask_tokens(rng, tokens)
        logits, moe_aux = self.apply(params, inputs, train=train,
                                     return_aux=True)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_logp = jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]
        w = selected.astype(jnp.float32)
        loss = -jnp.sum(tok_logp * w) / jnp.maximum(jnp.sum(w), 1.0)
        acc = (jnp.sum((jnp.argmax(logits, -1) == tokens) * w)
               / jnp.maximum(jnp.sum(w), 1.0))
        metrics = {"accuracy": acc, "masked_frac": jnp.mean(w)}
        if self.cfg.moe_experts > 0:
            loss = loss + self.cfg.moe_aux_weight * moe_aux
            metrics["moe_aux"] = moe_aux
        return loss, metrics

    def eval_metrics(self, params, batch):
        loss, aux = self.loss(params, batch, rng=jax.random.key(123),
                              train=False)
        return {"loss": loss, "accuracy": aux["accuracy"]}
