"""ResNet-50 image classifier (CIFAR-10 / ImageNet stems).

North-star workload "ResNet-50 / CIFAR-10 sync all-reduce" (BASELINE.md; the
reference itself has no conv models — its only model is the 2-layer MNIST MLP,
tf_distributed.py:50-65).  TPU-first design:

* NHWC layout throughout — XLA's preferred conv layout on TPU (lowers to MXU
  convolutions without transposes);
* within each stage, the first (striding/projecting) block is inlined and the
  remaining *identical-shape* blocks are executed by one ``lax.scan`` over
  stacked per-block params — one compiled block body per stage instead of 16
  inlined bottlenecks (compile time scales with 4 stages, not 16 blocks);
* BatchNorm running statistics live in a separate ``model_state`` pytree
  threaded functionally through ``apply_stateful`` — no mutation, jit-safe.
  Under pjit the batch mean over the ``data``-sharded batch axis is a global
  mean (GSPMD inserts the all-reduce), i.e. synchronized/cross-replica BN for
  free, riding ICI;
* BN statistics accumulate in fp32 even when activations are bf16.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from dtf_tpu.nn.core import Module
from dtf_tpu.nn.layers import BatchNorm, Conv2D, Dense


def max_pool(x, window: int, stride: int, padding: str = "SAME"):
    """NHWC max pool via reduce_window."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1), padding=padding)


@dataclasses.dataclass
class ResNetConfig:
    num_classes: int = 10
    stage_sizes: tuple = (3, 4, 6, 3)          # ResNet-50
    widths: tuple = (64, 128, 256, 512)
    expansion: int = 4
    cifar_stem: bool = True                    # 3x3/s1 stem, no maxpool
    dtype: Any = jnp.float32

    @classmethod
    def resnet50(cls, num_classes: int = 10, cifar_stem: bool = True, **kw):
        return cls(num_classes=num_classes, cifar_stem=cifar_stem, **kw)

    @classmethod
    def tiny(cls, **kw):
        """Test-size config (CPU-mesh friendly): 2 stages, 1+2 blocks."""
        d = dict(stage_sizes=(2, 3), widths=(8, 16), expansion=2)
        d.update(kw)
        return cls(**d)


class Bottleneck(Module):
    """1x1 reduce -> 3x3 (stride) -> 1x1 expand, BN after each conv,
    projection shortcut when shape changes."""

    def __init__(self, in_ch: int, width: int, stride: int, expansion: int,
                 dtype=jnp.float32):
        out_ch = width * expansion
        self.conv1 = Conv2D(in_ch, width, (1, 1), use_bias=False, dtype=dtype)
        self.bn1 = BatchNorm(width)
        self.conv2 = Conv2D(width, width, (3, 3), strides=(stride, stride),
                            use_bias=False, dtype=dtype)
        self.bn2 = BatchNorm(width)
        self.conv3 = Conv2D(width, out_ch, (1, 1), use_bias=False, dtype=dtype)
        self.bn3 = BatchNorm(out_ch)
        self.needs_proj = stride != 1 or in_ch != out_ch
        if self.needs_proj:
            self.proj = Conv2D(in_ch, out_ch, (1, 1),
                               strides=(stride, stride), use_bias=False,
                               dtype=dtype)
            self.bn_proj = BatchNorm(out_ch)

    def _units(self):
        units = [("conv1", self.conv1), ("bn1", self.bn1),
                 ("conv2", self.conv2), ("bn2", self.bn2),
                 ("conv3", self.conv3), ("bn3", self.bn3)]
        if self.needs_proj:
            units += [("proj", self.proj), ("bn_proj", self.bn_proj)]
        return units

    def init(self, key):
        units = self._units()
        keys = jax.random.split(key, len(units))
        return {name: m.init(k) for (name, m), k in zip(units, keys)}

    def init_model_state(self):
        return {name: m.init_state() for name, m in self._units()
                if isinstance(m, BatchNorm)}

    def apply_stateful(self, params, state, x, *, train: bool):
        ns = {}
        h = self.conv1.apply(params["conv1"], x)
        h, ns["bn1"] = self.bn1.apply_stateful(params["bn1"], state["bn1"], h,
                                               train=train)
        h = jax.nn.relu(h)
        h = self.conv2.apply(params["conv2"], h)
        h, ns["bn2"] = self.bn2.apply_stateful(params["bn2"], state["bn2"], h,
                                               train=train)
        h = jax.nn.relu(h)
        h = self.conv3.apply(params["conv3"], h)
        h, ns["bn3"] = self.bn3.apply_stateful(params["bn3"], state["bn3"], h,
                                               train=train)
        shortcut = x
        if self.needs_proj:
            shortcut = self.proj.apply(params["proj"], x)
            shortcut, ns["bn_proj"] = self.bn_proj.apply_stateful(
                params["bn_proj"], state["bn_proj"], shortcut, train=train)
        return jax.nn.relu(h + shortcut), ns

    def axes(self):
        return {name: m.axes() for name, m in self._units()}


@dataclasses.dataclass
class ResNet(Module):
    """Stem -> 4 bottleneck stages (first block inlined, rest scanned) ->
    global average pool -> linear classifier."""

    cfg: ResNetConfig

    def __post_init__(self):
        cfg = self.cfg
        stem_in = 3
        if cfg.cifar_stem:
            self.stem = Conv2D(stem_in, cfg.widths[0], (3, 3),
                               use_bias=False, dtype=cfg.dtype)
        else:
            self.stem = Conv2D(stem_in, cfg.widths[0], (7, 7),
                               strides=(2, 2), use_bias=False, dtype=cfg.dtype)
        self.stem_bn = BatchNorm(cfg.widths[0])
        self.stages = []
        in_ch = cfg.widths[0]
        for i, (n, w) in enumerate(zip(cfg.stage_sizes, cfg.widths)):
            stride = 1 if i == 0 else 2
            first = Bottleneck(in_ch, w, stride, cfg.expansion, cfg.dtype)
            out_ch = w * cfg.expansion
            rest = Bottleneck(out_ch, w, 1, cfg.expansion, cfg.dtype)
            self.stages.append((first, rest, n - 1))
            in_ch = out_ch
        self.fc = Dense(in_ch, cfg.num_classes, dtype=cfg.dtype,
                        axes_in="embed", axes_out=None)

    def init(self, key):
        ks, kbn, kfc, *stage_keys = jax.random.split(key, 3 + len(self.stages))
        params = {"stem": self.stem.init(ks), "stem_bn": self.stem_bn.init(kbn),
                  "fc": self.fc.init(kfc)}
        for i, ((first, rest, n_rest), sk) in enumerate(
                zip(self.stages, stage_keys)):
            kf, kr = jax.random.split(sk)
            params[f"s{i}_first"] = first.init(kf)
            if n_rest:
                rest_keys = jax.random.split(kr, n_rest)
                params[f"s{i}_rest"] = jax.vmap(rest.init)(rest_keys)
        return params

    def init_model_state(self):
        state = {"stem_bn": self.stem_bn.init_state()}
        for i, (first, rest, n_rest) in enumerate(self.stages):
            state[f"s{i}_first"] = first.init_model_state()
            if n_rest:
                one = rest.init_model_state()
                state[f"s{i}_rest"] = jax.tree_util.tree_map(
                    lambda x: jnp.broadcast_to(x, (n_rest, *x.shape)), one)
        return state

    def apply_stateful(self, params, state, x, *, train: bool):
        """x (B, H, W, 3) -> logits (B, num_classes), new model_state."""
        ns = {}
        h = self.stem.apply(params["stem"], x)
        h, ns["stem_bn"] = self.stem_bn.apply_stateful(
            params["stem_bn"], state["stem_bn"], h, train=train)
        h = jax.nn.relu(h)
        if not self.cfg.cifar_stem:
            h = max_pool(h, 3, 2)
        for i, (first, rest, n_rest) in enumerate(self.stages):
            h, ns[f"s{i}_first"] = first.apply_stateful(
                params[f"s{i}_first"], state[f"s{i}_first"], h, train=train)
            if n_rest:
                def body(carry, ps, _rest=rest):
                    p, s = ps
                    y, s_new = _rest.apply_stateful(p, s, carry, train=train)
                    return y, s_new
                h, ns[f"s{i}_rest"] = lax.scan(
                    body, h, (params[f"s{i}_rest"], state[f"s{i}_rest"]))
        h = jnp.mean(h, axis=(1, 2))                   # global average pool
        logits = self.fc.apply(params["fc"], h)
        return logits.astype(jnp.float32), ns

    def apply(self, params, x, *, train=False, rng=None, model_state=None):
        if model_state is None:
            raise TypeError("ResNet is stateful; pass model_state or use "
                            "apply_stateful")
        logits, _ = self.apply_stateful(params, model_state, x, train=train)
        return logits

    def axes(self):
        axes = {"stem": self.stem.axes(), "stem_bn": self.stem_bn.axes(),
                "fc": self.fc.axes()}
        for i, (first, rest, n_rest) in enumerate(self.stages):
            axes[f"s{i}_first"] = first.axes()
            if n_rest:
                axes[f"s{i}_rest"] = jax.tree_util.tree_map(
                    lambda ax: (None, *ax), rest.axes(),
                    is_leaf=lambda x: isinstance(x, tuple) and all(
                        a is None or isinstance(a, str) for a in x))
        return axes

    # --- training objective (stateful protocol) -------------------------

    def loss(self, params, model_state, batch, rng=None, train=True):
        """batch: (images NHWC float32, labels one-hot float32) — the same
        (x, y_) contract as the MNIST workload (tf_distributed.py:42-46)."""
        images, labels = batch
        logits, new_state = self.apply_stateful(params, model_state, images,
                                                train=train)
        logp = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.mean(jnp.sum(labels * logp, axis=-1))
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == jnp.argmax(labels, -1)
             ).astype(jnp.float32))
        return loss, ({"accuracy": acc}, new_state)

    def eval_metrics(self, params, model_state, batch):
        loss, (aux, _) = self.loss(params, model_state, batch, train=False)
        return {"loss": loss, "accuracy": aux["accuracy"]}
