from dtf_tpu.models.mlp import MnistMLP  # noqa: F401
from dtf_tpu.models.resnet import ResNet, ResNetConfig  # noqa: F401
