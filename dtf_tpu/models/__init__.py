from dtf_tpu.models.mlp import MnistMLP  # noqa: F401
