from dtf_tpu.models.mlp import MnistMLP  # noqa: F401
from dtf_tpu.models.resnet import ResNet, ResNetConfig  # noqa: F401
from dtf_tpu.models.bert import BertConfig, BertMLM  # noqa: F401
from dtf_tpu.models.gpt import GPT, GPTConfig  # noqa: F401
from dtf_tpu.models.t5 import T5, T5Config  # noqa: F401
