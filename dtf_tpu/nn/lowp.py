"""Low-precision matmul compute paths for TRAINING forward passes.

The serving stack already runs int8 weights (ops/decode_kernel.py,
``--decode_int8``); this module pushes reduced precision into the
*training* forward per "Scalable Training of Language Models using JAX
pjit and TPUv4" (PAPERS.md, arxiv 2204.06514) and the EQuARX
low-precision direction: a ``--matmul_dtype`` knob on the dense layers
and the GPT blocks.

Formats:

``fp32``
    the default — plain ``x @ w``, nothing changes.
``bf16``
    both operands cast to bf16, MXU accumulates in f32
    (``preferred_element_type``).  Gradients flow through the casts
    naturally (d(astype)/dx == astype).
``int8``
    symmetric quantization, **per output channel** for the weight (one
    f32 scale per column — training grows outlier channels, and
    per-channel scales are exactly the serving path's defense) and per
    row (token) for the activation; the product runs int8 x int8 -> i32
    on the MXU and the two scales fold into the f32 output.  Exact
    integer arithmetic: |q| <= 127 so row sums stay far inside i32.
``fp8``
    operands scaled per channel/row into float8_e4m3fn range (max 448)
    and rounded through the f8 lattice; the contraction runs in f32 on
    CPU (numerically identical to an f8-operand MXU matmul with f32
    accumulation, since f8 values are exact in f32) — the TPU kernel
    swap is a lowering detail, not a semantics change.

Backward: quantization rounds, and ``round`` has zero gradient — so the
int8/fp8 paths use the **straight-through estimator** (the standard QAT
move): the forward computes the quantized product, the backward
differentiates as if the matmul had run on the full-precision operands.
The quality harness (``bench.int8_quality --trajectory``) measures the
end-to-end loss-trajectory cost of exactly this approximation.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

#: The ``--matmul_dtype`` spellings, canonical order.
MATMUL_DTYPES: Tuple[str, ...] = ("fp32", "bf16", "int8", "fp8")

_TINY = 1e-30


def check_matmul_dtype(name: str) -> str:
    if name not in MATMUL_DTYPES:
        raise ValueError(f"--matmul_dtype must be one of {MATMUL_DTYPES}, "
                         f"got {name!r}")
    return name


def _int8_pair(v: jax.Array, axis: int):
    """Symmetric int8 quantization of ``v`` with one f32 scale per slice
    along every axis EXCEPT ``axis`` (the contraction axis the scale
    must not span)."""
    # The division stays in f32 (like quantize.encode): dividing by a
    # scale downcast to a bf16 operand dtype can land on 127.5 -> 128 ->
    # clip, biasing exactly the outlier channel the scale protects.
    amax = jnp.max(jnp.abs(v.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = amax / 127.0
    q = jnp.clip(jnp.round(v.astype(jnp.float32)
                           / jnp.maximum(scale, _TINY)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _fp8_cast(v: jax.Array, axis: int):
    """Scale per non-contraction slice into e4m3 range, round through the
    f8 lattice, return (f8-valued f32 tensor, f32 scale)."""
    f8max = float(jnp.finfo(jnp.float8_e4m3fn).max)          # 448
    amax = jnp.max(jnp.abs(v), axis=axis, keepdims=True)
    scale = (amax.astype(jnp.float32) / f8max)
    safe = jnp.maximum(scale, _TINY)
    q = (v.astype(jnp.float32) / safe).astype(jnp.float8_e4m3fn)
    return q.astype(jnp.float32), scale


def _matmul_2d_int8(x2, w):
    xq, sx = _int8_pair(x2, axis=1)               # per-row (token) scale
    wq, sw = _int8_pair(w, axis=0)                # per-output-channel
    y = lax.dot_general(xq, wq, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * sx * sw


def _matmul_2d_fp8(x2, w):
    xq, sx = _fp8_cast(x2, axis=1)
    wq, sw = _fp8_cast(w, axis=0)
    y = lax.dot_general(xq, wq, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    return y * sx * sw


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ste_matmul(x2, w, dtype: str):
    """(m, k) @ (k, n) through the quantized format ``dtype`` with a
    straight-through backward (gradients as if fp32)."""
    return (_matmul_2d_int8 if dtype == "int8" else _matmul_2d_fp8)(x2, w)


def _ste_fwd(x2, w, dtype):
    return _ste_matmul(x2, w, dtype), (x2, w)


def _ste_bwd(dtype, res, g):
    x2, w = res
    g = g.astype(jnp.float32)
    dx = (g @ w.astype(jnp.float32).T).astype(x2.dtype)
    dw = (x2.astype(jnp.float32).T @ g).astype(w.dtype)
    return dx, dw


_ste_matmul.defvjp(_ste_fwd, _ste_bwd)


def lowp_matmul(x: jax.Array, w: jax.Array, dtype: str) -> jax.Array:
    """``x (..., k) @ w (k, n)`` through the compute format ``dtype``;
    output in the fp32-matmul's result dtype.  The seam every
    ``--matmul_dtype`` consumer (nn.Dense, MultiHeadAttention
    projections) routes through, so the formats live in one place."""
    check_matmul_dtype(dtype)
    out_dtype = jnp.promote_types(x.dtype, w.dtype)
    if dtype == "fp32":
        return jnp.matmul(x, w)
    if dtype == "bf16":
        return jnp.matmul(
            x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32).astype(out_dtype)
    lead = x.shape[:-1]
    y = _ste_matmul(x.reshape(-1, x.shape[-1]), w, dtype)
    return y.reshape(*lead, w.shape[-1]).astype(out_dtype)
