"""Losses and metrics.

The reference's hand-written cross-entropy ``-sum(y_ * log(softmax(y)))``
(tf_distributed.py:68-70) is numerically unstable — log of a softmax that can
underflow to 0.  :func:`softmax_cross_entropy` is the stable logits-space
form (logsumexp); :func:`naive_cross_entropy` reproduces the reference's
exact math for parity testing, documenting the numerics delta (SURVEY.md §7
step 4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels_onehot: jax.Array,
                          reduction: str = "mean") -> jax.Array:
    """Stable cross-entropy from logits; labels one-hot (reference feeds
    one-hot labels, tf_distributed.py:27 ``one_hot=True``)."""
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    per_example = -jnp.sum(labels_onehot * log_probs, axis=-1)
    if reduction == "mean":
        return jnp.mean(per_example)
    if reduction == "sum":
        return jnp.sum(per_example)
    return per_example


def naive_cross_entropy(probs: jax.Array, labels_onehot: jax.Array) -> jax.Array:
    """The reference's exact (unstable) formula, tf_distributed.py:70:
    ``-reduce_sum(y_ * log(y))`` over the batch — note: *sum*, not mean."""
    return -jnp.sum(labels_onehot * jnp.log(probs))


def accuracy(logits_or_probs: jax.Array, labels_onehot: jax.Array) -> jax.Array:
    """Argmax-equality accuracy (tf_distributed.py:78-81)."""
    pred = jnp.argmax(logits_or_probs, axis=-1)
    true = jnp.argmax(labels_onehot, axis=-1)
    return jnp.mean((pred == true).astype(jnp.float32))


def mse(pred: jax.Array, target: jax.Array) -> jax.Array:
    return jnp.mean((pred - target) ** 2)


def smooth_token_logp(logp: jax.Array, tok_logp: jax.Array,
                      eps: float) -> jax.Array:
    """Label-smoothed target log-likelihood: mix ``eps`` of uniform mass
    into the one-hot target — ``(1-eps)·logp[target] + eps·mean(logp)``.
    The ONE definition used by every LM loss (gpt.py, t5.py); validates
    ``0 <= eps < 1`` (eps >= 1 would flip the objective's sign on the true
    target — a typo like 1.5-for-0.15 must error, not train wrong)."""
    if not 0.0 <= eps < 1.0:
        raise ValueError(f"label_smoothing must be in [0, 1), got {eps}")
    if eps == 0.0:
        return tok_logp
    return (1.0 - eps) * tok_logp + eps * jnp.mean(logp, axis=-1)


def chunked_token_ce(attend_fn, h, targets, weights, label_smoothing: float,
                     chunk: int):
    """Token cross-entropy scanned over T-chunks of the hidden states —
    the ONE chunked-CE definition used by GPT and T5 (``cfg.loss_chunk``).

    Per chunk, ``attend_fn(hc) -> (B, C, V)`` logits, log-softmax, target
    gather and label smoothing run under ``jax.checkpoint``, so the full
    (B, T, V) fp32 logits are never materialized and the backward
    recomputes each chunk's logits from its (B, C, D) hidden slice.

    h (B, T, D); targets (B, T) int32; weights (B, T) fp32 (a position
    whose weight is 0 contributes nothing).  T is padded to a multiple of
    ``chunk`` with zero-weight rows.  Returns fp32 scalar sums
    ``(nll, smooth_nll, correct, weight)`` — callers normalize.
    """
    from jax import lax

    b, t, d = h.shape
    c = min(chunk, t)
    pad = (-t) % c
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        weights = jnp.pad(weights, ((0, 0), (0, pad)))
    n = (t + pad) // c
    hs = h.reshape(b, n, c, d).swapaxes(0, 1)              # (n, B, C, D)
    ts = targets.reshape(b, n, c).swapaxes(0, 1)           # (n, B, C)
    ws = weights.reshape(b, n, c).swapaxes(0, 1)

    def step(carry, inp):
        hc, tc, wc = inp
        nll_s, sm_s, acc_s, w_s = carry
        logits = attend_fn(hc).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tl = jnp.take_along_axis(logp, tc[..., None], -1)[..., 0]
        sl = smooth_token_logp(logp, tl, label_smoothing)
        nll_s = nll_s - jnp.sum(tl * wc)
        sm_s = sm_s - jnp.sum(sl * wc)
        acc_s = acc_s + jnp.sum((jnp.argmax(logits, -1) == tc) * wc)
        return (nll_s, sm_s, acc_s, w_s + jnp.sum(wc)), None

    zero = jnp.zeros((), jnp.float32)
    (nll, sm, acc, wsum), _ = lax.scan(jax.checkpoint(step),
                                       (zero, zero, zero, zero),
                                       (hs, ts, ws))
    return nll, sm, acc, wsum
