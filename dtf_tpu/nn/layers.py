"""Standard layers with logical-axis annotations.

Initializers default to fan-in scaling; the reference's MNIST MLP used
``tf.random_normal`` with stddev 1.0 (tf_distributed.py:50-53), reproducible
here via ``init_scale="reference"`` on Dense (models/mlp.py uses it for
parity; the numerics delta is documented there).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from dtf_tpu.nn.core import Module


def _fan_in_normal(key, shape, dtype, fan_in):
    return jax.random.normal(key, shape, dtype) / jnp.sqrt(jnp.asarray(fan_in, dtype))


@dataclasses.dataclass
class Dense(Module):
    """y = x @ W + b.

    ``axes_in``/``axes_out`` are the logical axis names of the weight's two
    dims (default ``("embed", "mlp")``); pass e.g. ``("mlp", "embed")`` for a
    projection back, so tensor-parallel rules shard the pair correctly
    (megatron-style column-then-row).
    """

    in_dim: int
    out_dim: int
    use_bias: bool = True
    dtype: Any = jnp.float32
    init_scale: "float | str" = "fan_in"   # "fan_in" | "reference" | float stddev
    axes_in: Optional[str] = "embed"
    axes_out: Optional[str] = "mlp"
    # Forward-pass compute format (nn/lowp.py): "fp32" (default) |
    # "bf16" | "int8" | "fp8".  int8/fp8 quantize per output channel
    # (weight) and per token (activation) with a straight-through
    # backward — the --matmul_dtype training compute path.
    matmul_dtype: str = "fp32"

    def init(self, key):
        kw, _ = jax.random.split(key)
        if self.init_scale == "fan_in":
            w = _fan_in_normal(kw, (self.in_dim, self.out_dim), self.dtype, self.in_dim)
        elif self.init_scale == "reference":
            # tf.random_normal default stddev=1.0 (tf_distributed.py:50-53)
            w = jax.random.normal(kw, (self.in_dim, self.out_dim), self.dtype)
        else:
            w = jax.random.normal(kw, (self.in_dim, self.out_dim), self.dtype) * self.init_scale
        p = {"w": w}
        if self.use_bias:
            # biases zero, as the reference (tf_distributed.py:55-57)
            p["b"] = jnp.zeros((self.out_dim,), self.dtype)
        return p

    def apply(self, params, x, *, train=False, rng=None):
        if self.matmul_dtype != "fp32":
            from dtf_tpu.nn.lowp import lowp_matmul
            y = lowp_matmul(x, params["w"], self.matmul_dtype)
        else:
            y = x @ params["w"]
        if self.use_bias:
            y = y + params["b"]
        return y

    def axes(self):
        p = {"w": (self.axes_in, self.axes_out)}
        if self.use_bias:
            p["b"] = (self.axes_out,)
        return p


@dataclasses.dataclass
class Embedding(Module):
    vocab_size: int
    dim: int
    dtype: Any = jnp.float32

    def init(self, key):
        return {"table": jax.random.normal(key, (self.vocab_size, self.dim),
                                           self.dtype) * 0.02}

    def apply(self, params, ids, *, train=False, rng=None):
        return jnp.take(params["table"], ids, axis=0)

    def attend(self, params, x):
        """Tied-embedding logits (x @ table.T)."""
        return x @ params["table"].T

    def axes(self):
        return {"table": ("vocab", "embed")}


@dataclasses.dataclass
class LayerNorm(Module):
    dim: int
    eps: float = 1e-6
    dtype: Any = jnp.float32

    def init(self, key):
        return {"scale": jnp.ones((self.dim,), self.dtype),
                "bias": jnp.zeros((self.dim,), self.dtype)}

    def apply(self, params, x, *, train=False, rng=None):
        # Compute statistics in fp32 regardless of activation dtype.
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        return (y * params["scale"] + params["bias"]).astype(x.dtype)

    def axes(self):
        return {"scale": ("embed",), "bias": ("embed",)}


@dataclasses.dataclass
class RMSNorm(Module):
    """Root-mean-square norm — no mean subtraction, no bias (T5/LLaMA's
    normalization; cheaper than LayerNorm by one reduction and one
    subtract).  Statistics in fp32 regardless of activation dtype."""

    dim: int
    eps: float = 1e-6
    dtype: Any = jnp.float32

    def init(self, key):
        return {"scale": jnp.ones((self.dim,), self.dtype)}

    def apply(self, params, x, *, train=False, rng=None):
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (y * params["scale"]).astype(x.dtype)

    def axes(self):
        return {"scale": ("embed",)}


@dataclasses.dataclass
class BatchNorm(Module):
    """Batch normalization with functional running stats.

    Under pjit the batch dim is sharded over ``data``, but ``jnp.mean`` over
    a sharded axis is a *global* mean — GSPMD inserts the cross-replica
    all-reduce automatically, so this is synchronized BatchNorm for free (the
    collective rides ICI).  Running stats are part of a separate ``state``
    pytree threaded through apply: ``y, new_state = bn.apply_stateful(...)``.
    """

    dim: int
    momentum: float = 0.9
    eps: float = 1e-5
    dtype: Any = jnp.float32

    def init(self, key):
        return {"scale": jnp.ones((self.dim,), self.dtype),
                "bias": jnp.zeros((self.dim,), self.dtype)}

    def init_state(self):
        return {"mean": jnp.zeros((self.dim,), jnp.float32),
                "var": jnp.ones((self.dim,), jnp.float32)}

    def apply_stateful(self, params, state, x, *, train: bool):
        reduce_axes = tuple(range(x.ndim - 1))
        if train:
            x32 = x.astype(jnp.float32)
            mean = jnp.mean(x32, axis=reduce_axes)
            var = jnp.var(x32, axis=reduce_axes)
            new_state = {
                "mean": self.momentum * state["mean"] + (1 - self.momentum) * mean,
                "var": self.momentum * state["var"] + (1 - self.momentum) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        y = (x - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["scale"] + params["bias"]
        return y.astype(x.dtype), new_state

    def apply(self, params, x, *, train=False, rng=None):
        raise TypeError("BatchNorm is stateful; use apply_stateful")

    def axes(self):
        return {"scale": ("embed",), "bias": ("embed",)}


@dataclasses.dataclass
class Conv2D(Module):
    """NHWC conv; lowers to XLA conv -> MXU."""

    in_ch: int
    out_ch: int
    kernel: tuple = (3, 3)
    strides: tuple = (1, 1)
    padding: str = "SAME"
    use_bias: bool = True
    dtype: Any = jnp.float32

    def init(self, key):
        kh, kw = self.kernel
        fan_in = kh * kw * self.in_ch
        w = _fan_in_normal(key, (kh, kw, self.in_ch, self.out_ch),
                           self.dtype, fan_in)
        p = {"w": w}
        if self.use_bias:
            p["b"] = jnp.zeros((self.out_ch,), self.dtype)
        return p

    def apply(self, params, x, *, train=False, rng=None):
        y = jax.lax.conv_general_dilated(
            x, params["w"], window_strides=self.strides, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["b"]
        return y

    def axes(self):
        p = {"w": (None, None, "conv_in", "conv_out")}
        if self.use_bias:
            p["b"] = ("conv_out",)
        return p


@dataclasses.dataclass
class Dropout(Module):
    rate: float

    def init(self, key):
        return {}

    def apply(self, params, x, *, train=False, rng=None):
        if not train or self.rate == 0.0:
            return x
        if rng is None:
            raise ValueError("Dropout needs rng when train=True")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)

    def axes(self):
        return {}
