"""Mixture-of-Experts layer with expert parallelism (Switch-style top-1 and
GShard-style top-2 routing).

Not in the reference (no MoE anywhere in its 390 lines, SURVEY.md §2.14);
built because expert parallelism is a first-class mesh axis of this
framework (``expert`` in parallel/mesh.py AXES, rule ("expert", "expert")).

TPU-first design:

* static shapes end to end: capacity-based dispatch via one-hot einsums
  (the GShard/Switch pattern) — no dynamic gathers, no data-dependent
  shapes, everything lands on the MXU;
* grouped routing: each batch row is a routing group with its own capacity,
  so the position cumsum runs over the (local) sequence axis only — routing
  is entirely local to a data shard, exactly as GShard prescribes; only the
  dispatch/combine einsums cross shards;
* expert weights are stacked on a leading ``expert`` logical axis; under a
  mesh with an ``expert`` axis GSPMD turns the dispatch/combine einsums into
  all-to-alls over ICI (batch sharded on data x experts sharded on expert);
* tokens over capacity are dropped (their combine weight is zero), the
  residual connection around the layer carries them through unchanged —
  the standard Switch behavior;
* auxiliary load-balancing loss (Switch eq. 4): E * sum_e f_e * p_e, with
  f_e computed from the PRE-capacity assignments so the balancing gradient
  does not vanish when an overloaded expert truncates.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from dtf_tpu.nn.core import Module
from dtf_tpu.nn.layers import _fan_in_normal


@dataclasses.dataclass
class MoE(Module):
    """Token-choice MoE MLP block: router -> dispatch -> expert FFN ->
    combine.  Apply returns (y, aux_loss)."""

    dim: int
    mlp_dim: int
    num_experts: int
    top_k: int = 1                  # 1 = Switch, 2 = GShard
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32

    def init(self, key):
        kr, k1, k2 = jax.random.split(key, 3)
        e, d, m = self.num_experts, self.dim, self.mlp_dim
        return {
            "router": {"w": _fan_in_normal(kr, (d, e), jnp.float32, d)},
            "fc1": {"w": jax.vmap(lambda k: _fan_in_normal(k, (d, m),
                                                           self.dtype, d))(
                        jax.random.split(k1, e)),
                    "b": jnp.zeros((e, m), self.dtype)},
            "fc2": {"w": jax.vmap(lambda k: _fan_in_normal(k, (m, d),
                                                           self.dtype, m))(
                        jax.random.split(k2, e)),
                    "b": jnp.zeros((e, d), self.dtype)},
        }

    def axes(self):
        return {
            "router": {"w": ("embed", None)},
            "fc1": {"w": ("expert", "embed", "mlp"), "b": ("expert", "mlp")},
            "fc2": {"w": ("expert", "mlp", "embed"), "b": ("expert", "embed")},
        }

    def capacity(self, tokens_per_group: int) -> int:
        """Per-group (per batch row) expert buffer size."""
        return max(1, int(tokens_per_group * self.capacity_factor
                          * self.top_k / self.num_experts))

    def apply(self, params, x, *, train=False, rng=None):
        """x (B, T, D) -> (y (B, T, D), aux_loss scalar).

        Each batch row is a routing group: positions come from a cumsum over
        the T axis only, so with B sharded over data the routing math is
        local to the shard.
        """
        b, t, d = x.shape
        e = self.num_experts
        c = self.capacity(t)

        # --- routing (fp32, per group) ---------------------------------
        logits = jnp.einsum("btd,de->bte", x.astype(jnp.float32),
                            params["router"]["w"])
        probs = jax.nn.softmax(logits, axis=-1)                     # (B,T,E)

        remaining = probs
        fill = jnp.zeros((b, e), jnp.int32)   # per-group expert fill count
        gates, dispatch_masks, positions, assign_masks = [], [], [], []
        for _ in range(self.top_k):
            gate = jnp.max(remaining, axis=-1)                      # (B,T)
            idx = jnp.argmax(remaining, axis=-1)                    # (B,T)
            onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)        # (B,T,E)
            assign_masks.append(onehot)       # PRE-capacity, for aux loss
            # position of each token within its expert's per-group buffer
            pos_in_expert = (jnp.cumsum(onehot, axis=1) - 1
                             + fill[:, None, :])                    # (B,T,E)
            pos = jnp.sum(pos_in_expert * onehot, axis=-1)          # (B,T)
            keep = pos < c
            gates.append(jnp.where(keep, gate, 0.0))
            dispatch_masks.append(onehot * keep[..., None].astype(jnp.int32))
            positions.append(jnp.where(keep, pos, 0))
            fill = fill + jnp.sum(dispatch_masks[-1], axis=1)
            remaining = remaining * (1.0 - onehot.astype(jnp.float32))

        # top-1 (Switch): raw router prob as the gate; top-k (GShard):
        # renormalize the chosen gates to sum to 1
        if self.top_k > 1:
            denom = jnp.maximum(sum(gates), 1e-9)
            gates = [g / denom for g in gates]

        combine = jnp.zeros((b, t, e, c), jnp.float32)
        for gate, mask, pos in zip(gates, dispatch_masks, positions):
            oh_pos = jax.nn.one_hot(pos, c, dtype=jnp.float32)      # (B,T,C)
            combine = combine + (gate[..., None, None]
                                 * mask[..., None].astype(jnp.float32)
                                 * oh_pos[..., None, :])

        dispatch = (combine > 0).astype(x.dtype)                    # (B,T,E,C)

        # --- expert computation (all-to-all under expert sharding) -----
        expert_in = jnp.einsum("btec,btd->ebcd", dispatch,
                               x.astype(x.dtype))                   # (E,B,C,D)
        h = jnp.einsum("ebcd,edm->ebcm", expert_in, params["fc1"]["w"])
        h = jax.nn.gelu(h + params["fc1"]["b"][:, None, None, :])
        out = jnp.einsum("ebcm,emd->ebcd", h, params["fc2"]["w"])
        out = out + params["fc2"]["b"][:, None, None, :]            # (E,B,C,D)

        y = jnp.einsum("btec,ebcd->btd", combine.astype(x.dtype), out)

        # --- load-balancing aux loss (Switch eq. 4), pre-capacity f_e --
        frac_tokens = jnp.mean(
            sum(m.astype(jnp.float32) for m in assign_masks), axis=(0, 1))
        frac_probs = jnp.mean(probs, axis=(0, 1))
        aux = e * jnp.sum(frac_tokens * frac_probs) / self.top_k

        return y, aux
