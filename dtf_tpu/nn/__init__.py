"""Pure-functional neural-net layer library.

The reference defined its model as TF1 graph ops with variables placed on a
parameter server (tf_distributed.py:50-65).  Here models are pure functions:
a module is a static-config object with ``init(key) -> params`` and
``apply(params, x) -> y``; params are plain pytrees, so every JAX transform
(jit/grad/shard_map) and every sharding rule applies uniformly.  Each module
also exposes ``axes() -> pytree`` of logical axis names mirroring its params,
which :func:`dtf_tpu.parallel.sharding.apply_rules` maps to mesh shardings —
the declarative replacement for ``replica_device_setter``.
"""

from dtf_tpu.nn.core import Module, Sequential
from dtf_tpu.nn.layers import (
    Dense, Embedding, LayerNorm, BatchNorm, Conv2D, Dropout,
)
from dtf_tpu.nn.losses import (
    softmax_cross_entropy, naive_cross_entropy, accuracy, mse,
    smooth_token_logp,
)

__all__ = [
    "Module", "Sequential", "Dense", "Embedding", "LayerNorm", "BatchNorm",
    "Conv2D", "Dropout", "softmax_cross_entropy", "naive_cross_entropy",
    "accuracy", "mse", "smooth_token_logp",
]
