"""Token sampling strategies for autoregressive decoding.

Not present in the reference (no sequence models, SURVEY.md §5.7); completes
the framework's inference story alongside the KV-cache decode loop in
:meth:`dtf_tpu.models.gpt.GPT.generate`.

All transforms are jit-compatible (static shapes, no data-dependent Python
control flow — the filters are where/sort masks, not gathers of dynamic
size), composable, and operate on a (B, V) logits batch:

    temperature -> top-k filter -> top-p (nucleus) filter -> categorical

``temperature=0`` short-circuits to greedy argmax.  fp32 throughout —
sampling in bf16 visibly distorts the tail of the distribution.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = jnp.finfo(jnp.float32).min


def top_k_filter(logits: jax.Array, k: int) -> jax.Array:
    """Keep the k highest logits per row; set the rest to -inf.
    ``k <= 0`` or ``k >= V`` is a no-op."""
    v = logits.shape[-1]
    if k <= 0 or k >= v:
        return logits
    kth = jnp.sort(logits, axis=-1)[..., v - k][..., None]   # kth largest
    return jnp.where(logits < kth, NEG_INF, logits)


def _nucleus_cutoff(sorted_desc: jax.Array, p: float) -> jax.Array:
    """Smallest kept logit for nucleus mass ``p``, given descending-sorted
    logits.  The argmax is always kept; the token that crosses the
    threshold is included."""
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    # exclusive cumulative mass: token i stays while the mass *before* it
    # is < p, so the crossing token is included too.
    keep = (jnp.cumsum(probs, axis=-1) - probs) < p
    keep = keep.at[..., 0].set(True)     # argmax survives even p <= 0
    return jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                   keepdims=True)


def top_p_filter(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filter: keep the smallest set of tokens whose probability
    mass reaches ``p`` (always at least the argmax — ``p <= 0`` degrades
    to greedy, not to an all-masked row).  ``p >= 1`` no-op."""
    if p >= 1.0:
        return logits
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    return jnp.where(logits < _nucleus_cutoff(sorted_desc, p), NEG_INF,
                     logits)


def sample_token(rng: jax.Array, logits: jax.Array, *,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 1.0) -> jax.Array:
    """Sample next-token ids (B,) int32 from (B, V) logits.

    temperature=0 -> greedy argmax (top_k/top_p then irrelevant); otherwise
    logits/temperature -> top-k -> top-p -> categorical.  When both filters
    are active they share one descending sort (this runs inside the
    KV-cache decode scan — the full-vocab sort is the dominant sampling
    cost).
    """
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = filter_logits(logits / temperature, top_k=top_k, top_p=top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sample_token_batched(keys: jax.Array, logits: jax.Array, *,
                         temperature: jax.Array, top_k: int = 0,
                         top_p: float = 1.0) -> jax.Array:
    """Per-row sampling for the serving engine: each row of a continuous
    batch carries its OWN request's temperature and rng key.

    keys: (B,) typed key array (one independent stream per request, so a
    request's draws do not depend on which batch composition it rode);
    temperature: (B,) fp32 — 0 selects greedy for that row; top_k/top_p
    are static engine-wide filters (shared sort, same composition
    semantics as :func:`filter_logits`).
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temperature = jnp.asarray(temperature, jnp.float32)
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    filtered = filter_logits(logits / safe_t, top_k=top_k, top_p=top_p)
    drawn = jax.vmap(
        lambda k, row: jax.random.categorical(k, row))(keys, filtered)
    return jnp.where(temperature == 0.0, greedy,
                     drawn.astype(jnp.int32))


def sample_token_window(keys: jax.Array, logits: jax.Array, *,
                        temperature: jax.Array, top_k: int = 0,
                        top_p: float = 1.0) -> jax.Array:
    """Per-(row, position) sampling for the speculative verify step:
    ``logits`` (B, S, V) with keys (B, S) — each window position draws
    with its own stream key (the request key folded with the token
    count that position would have in sequential decode) and its row's
    temperature, so the emitted token at every position is EXACTLY the
    one :func:`sample_token_batched` would draw in the sequential
    engine.  Implemented as the batched sampler over the flattened
    (B·S, V) view — same per-row math, pinned by the spec-decode
    token-identity tests."""
    b, s, _ = logits.shape
    temperature = jnp.asarray(temperature, jnp.float32)
    flat = sample_token_batched(
        keys.reshape(b * s), logits.reshape(b * s, logits.shape[-1]),
        temperature=jnp.repeat(temperature, s), top_k=top_k, top_p=top_p)
    return flat.reshape(b, s)


def filter_logits(logits: jax.Array, *, top_k: int = 0,
                  top_p: float = 1.0) -> jax.Array:
    """``top_p_filter(top_k_filter(x, k), p)`` with ONE descending sort.

    Exactly the sequential semantics (the standard composition): the
    nucleus is measured on the distribution *renormalized within the
    top-k*.  That renormalization is recovered from the unfiltered sort —
    mass(top-k) is the inclusive cumulative probability at position k-1,
    and a position survives the nucleus iff its exclusive cumulative mass
    is below ``p * mass(top-k)`` (positions past k are already cut, so
    their exclusive mass within-k equals the raw one).
    """
    v = logits.shape[-1]
    k_active = 0 < top_k < v
    p_active = top_p < 1.0
    if not (k_active or p_active):
        return logits
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    if not p_active:
        cutoff = sorted_desc[..., top_k - 1:top_k]       # kth largest
        return jnp.where(logits < cutoff, NEG_INF, logits)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    if k_active:
        # top_k_filter keeps value-ties with the kth logit, so the survivor
        # count can exceed k; the nucleus renormalizer must be the mass of
        # ALL survivors or ties at the boundary diverge from the sequential
        # composition.
        kth = sorted_desc[..., top_k - 1:top_k]
        n_kept = jnp.sum(sorted_desc >= kth, axis=-1, keepdims=True)
        mass = jnp.take_along_axis(cum, n_kept - 1, axis=-1)
        in_k = jnp.arange(v) < n_kept                    # first n_kept slots
    else:
        mass, in_k = 1.0, True
    keep = ((cum - probs) < top_p * mass) & in_k
    keep = keep.at[..., 0].set(True)          # argmax always survives
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits < cutoff, NEG_INF, logits)
