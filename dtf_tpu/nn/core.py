"""Module protocol: static config, pure init/apply, logical param axes."""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax


class Module:
    """Base class for pure-functional modules.

    Subclasses implement:

    * ``init(key) -> params``: build a params pytree from a PRNG key;
    * ``apply(params, x, *, train=False, rng=None) -> y``: pure forward;
    * ``axes() -> pytree``: logical axis names (tuples of str/None) mirroring
      the params pytree, consumed by ``parallel.sharding.apply_rules``.

    Modules hold only static Python configuration — never arrays — so they
    are safe to close over inside ``jit``.
    """

    def init(self, key: jax.Array) -> Any:
        raise NotImplementedError

    def apply(self, params: Any, x: Any, *, train: bool = False,
              rng: Optional[jax.Array] = None) -> Any:
        raise NotImplementedError

    def axes(self) -> Any:
        raise NotImplementedError

    def __call__(self, params: Any, x: Any, **kw: Any) -> Any:
        return self.apply(params, x, **kw)


class Sequential(Module):
    """Compose modules; params/axes are dicts keyed ``"0", "1", ...``.

    Layers that are plain callables (e.g. activation functions) take no
    params and appear in neither params nor axes.
    """

    def __init__(self, layers: Sequence["Module | Callable"]):
        self.layers = list(layers)

    def _param_layers(self):
        return [(str(i), l) for i, l in enumerate(self.layers)
                if isinstance(l, Module)]

    def init(self, key: jax.Array) -> dict:
        named = self._param_layers()
        keys = jax.random.split(key, max(len(named), 1))
        return {name: l.init(k) for (name, l), k in zip(named, keys)}

    def apply(self, params: dict, x: Any, *, train: bool = False,
              rng: Optional[jax.Array] = None) -> Any:
        i_param = 0
        named = self._param_layers()
        for layer in self.layers:
            if isinstance(layer, Module):
                name = named[i_param][0]
                i_param += 1
                sub_rng = None
                if rng is not None:
                    rng, sub_rng = jax.random.split(rng)
                x = layer.apply(params[name], x, train=train, rng=sub_rng)
            else:
                x = layer(x)
        return x

    def axes(self) -> dict:
        return {name: l.axes() for name, l in self._param_layers()}


def count_params(params: Any) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def remat(fn: Callable, policy: str = "full") -> Callable:
    """jax.checkpoint with the framework's named policies.

    "full": recompute everything in the backward pass — maximum memory
    savings at ~30% extra FLOPs (one extra forward).  "dots": save matmul
    outputs, recompute only elementwise chains — matmuls are where the
    FLOPs are but elementwise intermediates are most of the activation
    bytes, so this keeps most of the memory win at a few % recompute and
    correspondingly higher MFU.
    """
    if policy == "full":
        return jax.checkpoint(fn)
    if policy == "dots":
        # Matmul outputs + the flash kernel's named outputs (out, lse):
        # without the names, the backward pass recomputes the whole flash
        # forward just to rebuild its residuals (ops/flash_attention.py).
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    "flash_out", "flash_lse")))
    if policy == "attn":
        # Save ONLY the flash kernel's outputs; recompute every matmul in
        # the backward pass.  Counter-intuitively this is the FASTEST
        # measured policy at BERT-base shapes on v5e (BASELINE.md round
        # 3): attention is the one op whose recompute is expensive
        # relative to its save (the fwd kernel runs at ~60 TF/s vs ~165
        # for the MLP matmuls), while "dots" pays more in saved-residual
        # HBM traffic than the matmul recompute costs.  Also the
        # memory-lightest option after "full" (~100 MB/layer saved at
        # BERT-base mb64 vs ~480 MB for "dots").
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"))
    raise ValueError(
        f"remat policy must be 'full', 'dots', or 'attn', got {policy!r}")
