"""Rotary position embeddings (RoPE).

Not in the reference (no sequence models, SURVEY.md §5.7); extends the GPT
family to LLaMA-style architectures (RoPE + GQA + SwiGLU, models/gpt.py).

Split-half convention (rotate the first half of the head dim against the
second): out = [x1*cos - x2*sin, x1*sin + x2*cos].  Angles in fp32
regardless of activation dtype — bf16 position angles visibly degrade long
sequences.  TPU note: this is pure elementwise work that XLA fuses into the
surrounding projections; no custom kernel is warranted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(positions: jax.Array, head_dim: int,
                theta: float = 10000.0) -> tuple:
    """cos/sin tables for ``positions`` (any shape) -> each
    ``positions.shape + (head_dim // 2,)``, fp32."""
    half = head_dim // 2
    inv_freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float = 10000.0) -> jax.Array:
    """Rotate q or k.  x: (B, T, H, D) with D even; positions: (T,) token
    indices shared across the batch, or (B, T) PER-ROW indices (the
    serving engine's continuous batches sit at different sequence
    positions per slot).  Returns x's dtype."""
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"RoPE needs an even head dim, got {d}")
    cos, sin = rope_angles(positions, d, theta)   # (T, D/2) or (B, T, D/2)
    if positions.ndim == 1:
        cos = cos[None, :, None, :]                   # (1, T, 1, D/2)
        sin = sin[None, :, None, :]
    elif positions.ndim == 2:
        cos = cos[:, :, None, :]                      # (B, T, 1, D/2)
        sin = sin[:, :, None, :]
    else:
        raise ValueError(
            f"positions must be (T,) or (B, T), got shape "
            f"{positions.shape}")
    x1 = x[..., : d // 2].astype(jnp.float32)
    x2 = x[..., d // 2:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)
