"""T5 relative position biases.

The T5 family's signature position mechanism (used instead of absolute
position embeddings; the reference has no sequence models at all,
SURVEY.md §5.7): every attention logit gets a learned per-head scalar bias
indexed by a BUCKETED relative position ``key_pos - query_pos``.  Half the
buckets hold exact small distances; the other half are log-spaced out to
``max_distance``, beyond which all distances share the last bucket — so
arbitrarily long sequences reuse a tiny (buckets x heads) table.

TPU notes: the bucket computation is pure integer/VPU work on a (Tq, Tk)
iota — no gathers of dynamic size — and the resulting (1, H, Tq, Tk) bias
adds onto the attention logits before softmax, which XLA fuses into the
existing attention elementwise chain.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from dtf_tpu.nn.core import Module


def relative_position_bucket(rel, *, bidirectional: bool = True,
                             num_buckets: int = 32,
                             max_distance: int = 128):
    """Bucket relative positions ``rel = key_pos - query_pos`` (int array).

    Bidirectional (encoder): buckets [0, n/2) cover key<=query, [n/2, n)
    cover key>query, each half split exact/log as below.  Unidirectional
    (decoder): future keys (rel > 0) all map to bucket 0 (they are masked
    anyway); past distances use all ``num_buckets``.  Within a direction,
    distances < n_dir/2 get exact buckets; larger ones are log-spaced up to
    ``max_distance`` and clamp to the last bucket beyond it.
    """
    rel = jnp.asarray(rel, jnp.int32)
    n = num_buckets
    if bidirectional:
        n = n // 2
        offset = jnp.where(rel > 0, n, 0)
        dist = jnp.abs(rel)
    else:
        offset = jnp.zeros_like(rel)
        dist = jnp.maximum(-rel, 0)
    max_exact = n // 2
    is_small = dist < max_exact
    # log-spaced branch; clamp the argument so the unused small-branch
    # lanes never hit log(0)
    d = jnp.maximum(dist, max_exact).astype(jnp.float32)
    log_bucket = max_exact + (
        jnp.log(d / max_exact)
        / jnp.log(max_distance / max_exact)
        * (n - max_exact)).astype(jnp.int32)
    log_bucket = jnp.minimum(log_bucket, n - 1)
    return offset + jnp.where(is_small, dist, log_bucket)


def relpos_bias(table, q_positions, k_positions, *, bidirectional: bool,
                num_buckets: int = 32, max_distance: int = 128):
    """Pure-function form: (buckets, H) table -> (1, H, Tq, Tk) fp32 bias.
    Used directly by pipelined stacks, where the shared table is tiled
    into stage params and the bias recomputed per stage."""
    rel = k_positions[None, :] - q_positions[:, None]
    bucket = relative_position_bucket(
        rel, bidirectional=bidirectional, num_buckets=num_buckets,
        max_distance=max_distance)
    bias = table[bucket]                             # (Tq, Tk, H)
    return bias.transpose(2, 0, 1)[None].astype(jnp.float32)


@dataclasses.dataclass
class RelativePositionBias(Module):
    """Learned (num_buckets, num_heads) table -> (1, H, Tq, Tk) fp32 bias.

    One instance per stack (shared across layers, as in T5): the encoder's
    is bidirectional, the decoder's unidirectional; cross-attention carries
    no position bias.
    """

    num_heads: int
    num_buckets: int = 32
    max_distance: int = 128
    bidirectional: bool = True
    dtype: Any = jnp.float32

    def init(self, key):
        scale = self.num_buckets ** -0.5
        return {"table": jax.random.normal(
            key, (self.num_buckets, self.num_heads), self.dtype) * scale}

    def apply(self, params, q_positions, k_positions, *, train=False,
              rng=None):
        """q_positions (Tq,), k_positions (Tk,) int32 -> (1, H, Tq, Tk)."""
        return relpos_bias(params["table"], q_positions, k_positions,
                           bidirectional=self.bidirectional,
                           num_buckets=self.num_buckets,
                           max_distance=self.max_distance)

    def axes(self):
        return {"table": (None, "heads")}
