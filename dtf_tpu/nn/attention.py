"""Multi-head attention with tensor-parallel logical axes.

Not present in the reference (no attention/sequence models anywhere in its
390 lines — SURVEY.md §5.7); built because the framework's north-star
workloads include BERT-base (BASELINE.md) and long-context support is a
first-class design axis (ring attention over the ``seq`` mesh axis lives in
:mod:`dtf_tpu.ops.ring_attention` and plugs in via ``attn_impl``).

Tensor parallelism follows the megatron pattern expressed as logical axes:
QKV projections are column-parallel (("embed", "joined_kv") -> sharded over
``tensor``), the output projection is row-parallel (("joined_kv", "embed")),
so one all-reduce per attention block is inserted by GSPMD.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from dtf_tpu.nn.core import Module
from dtf_tpu.nn.layers import _fan_in_normal


def dot_product_attention(q, k, v, mask=None, scale=None, bias=None):
    """Plain softmax attention.  q,k,v: (B, T, H, D); mask broadcastable to
    (B, H, Tq, Tk), True = attend; ``bias`` an additive fp32 logit term of
    the same broadcast shape (e.g. T5 relative position biases)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def causal_mask(t: int) -> jax.Array:
    return jnp.tril(jnp.ones((t, t), bool))[None, None, :, :]


@dataclasses.dataclass
class MultiHeadAttention(Module):
    dim: int
    num_heads: int
    dtype: Any = jnp.float32
    # Pluggable inner attention: f(q, k, v, mask) -> out.  Defaults to plain
    # softmax attention; ring/flash implementations swap in here.
    attn_impl: Optional[Callable] = None
    # Grouped-query attention: K/V get this many heads (must divide
    # num_heads); queries share each KV head in groups.  None = classic MHA.
    # Shrinks the KV cache (and its HBM traffic) by num_heads/num_kv_heads.
    num_kv_heads: Optional[int] = None
    # Forward compute format for the q/k/v/o PROJECTIONS (nn/lowp.py):
    # "fp32" | "bf16" | "int8" | "fp8".  The inner attention (scores,
    # softmax, values) keeps full precision — its fp32 statistics are a
    # correctness anchor, and the projections hold the matmul FLOPs.
    matmul_dtype: str = "fp32"

    @property
    def head_dim(self) -> int:
        assert self.dim % self.num_heads == 0
        return self.dim // self.num_heads

    @property
    def kv_heads(self) -> int:
        kvh = self.num_kv_heads or self.num_heads
        assert self.num_heads % kvh == 0, (
            f"num_kv_heads {kvh} must divide num_heads {self.num_heads}")
        return kvh

    def init(self, key):
        kq, kk, kv, ko = jax.random.split(key, 4)
        d, h, hd = self.dim, self.num_heads, self.head_dim
        kvh = self.kv_heads
        mk = lambda k, nh: _fan_in_normal(k, (d, nh, hd), self.dtype, d)
        return {
            "q": {"w": mk(kq, h), "b": jnp.zeros((h, hd), self.dtype)},
            "k": {"w": mk(kk, kvh), "b": jnp.zeros((kvh, hd), self.dtype)},
            "v": {"w": mk(kv, kvh), "b": jnp.zeros((kvh, hd), self.dtype)},
            "o": {"w": _fan_in_normal(ko, (h, hd, d), self.dtype, d),
                  "b": jnp.zeros((d,), self.dtype)},
        }

    def qkv(self, params, x, kv_input=None):
        """Project q from ``x`` (B, Tq, D) and k/v from ``kv_input`` (B,
        Tkv, D; defaults to ``x`` — self-attention).  Returns q (B, Tq, H,
        Dh), k/v (B, Tkv, KVH, Dh).  The single definition of the input
        projections — apply(), cross-attention, and the GPT block's
        prefill/decode paths all route through here."""
        q = self.q_proj(params, x)
        k, v = self.kv_proj(params, x if kv_input is None else kv_input)
        return q, k, v

    def _proj_in(self, x, entry):
        """x (B, T, D) @ w (D, NH, Dh) + b -> (B, T, NH, Dh), through the
        low-precision seam when ``matmul_dtype`` asks for it (the weight
        flattens to (D, NH*Dh) so the per-output-channel scales cover
        every (head, lane) column)."""
        w = entry["w"]
        if self.matmul_dtype != "fp32":
            from dtf_tpu.nn.lowp import lowp_matmul
            y = lowp_matmul(x, w.reshape(w.shape[0], -1), self.matmul_dtype)
            return y.reshape(*x.shape[:-1], *w.shape[1:]) + entry["b"]
        return jnp.einsum("btd,dhk->bthk", x, w) + entry["b"]

    def q_proj(self, params, x):
        """Project only q from ``x`` (B, T, D) — for cross-attention decode
        where k/v come from a precomputed cache."""
        return self._proj_in(x, params["q"])

    def kv_proj(self, params, s):
        """Project only k/v from ``s`` (B, T, D) — for cross-attention
        caches where q is not needed."""
        k = self._proj_in(s, params["k"])
        v = self._proj_in(s, params["v"])
        return k, v

    def expand_kv(self, kv):
        """Broadcast grouped KV heads up to num_heads for an inner attention
        that expects equal head counts (flash/ring/ulysses/XLA)."""
        reps = self.num_heads // kv.shape[2]
        return kv if reps == 1 else jnp.repeat(kv, reps, axis=2)

    def out_proj(self, params, out):
        """(B, T, H, Dh) attention output -> (B, T, D)."""
        w = params["o"]["w"]
        if self.matmul_dtype != "fp32":
            from dtf_tpu.nn.lowp import lowp_matmul
            flat = out.reshape(*out.shape[:-2], -1)      # (B, T, H*Dh)
            return (lowp_matmul(flat, w.reshape(-1, w.shape[-1]),
                                self.matmul_dtype) + params["o"]["b"])
        return (jnp.einsum("bthk,hkd->btd", out, w)
                + params["o"]["b"])

    def apply(self, params, x, *, kv_input=None, mask=None, train=False,
              rng=None):
        """Self-attention over ``x``, or cross-attention when ``kv_input``
        (the encoder context) is given."""
        q, k, v = self.qkv(params, x, kv_input)
        impl = self.attn_impl or dot_product_attention
        return self.out_proj(params, impl(q, self.expand_kv(k),
                                          self.expand_kv(v), mask))

    def axes(self):
        proj = {"w": ("embed", "heads", "kv"), "b": ("heads", "kv")}
        return {"q": dict(proj), "k": dict(proj), "v": dict(proj),
                "o": {"w": ("heads", "kv", "embed"), "b": ("embed",)}}
