"""Per-host scenario-cell driver (spawned by scenarios/runner.py).

One process = one "host" of a cell.  Two shapes, chosen by the spec:

* ``spec.hosts == 1`` — the SUPERVISED shape: the whole cell lives in
  this process under :func:`~dtf_tpu.resilience.supervisor.
  run_supervised_fit` (one chaos plan across attempts, fresh trainer +
  data stream per attempt, bounded restarts), exactly like the
  ``--max_restarts`` workload CLIs.
* ``spec.hosts > 1`` — the ELASTIC shape (the tests/_mp_health.py
  pattern): this process is host ``task`` of an N-host round driven by
  ``run_elastic_hosts``.  The hosts form the health mesh EXPLICITLY
  (process_index/nproc passed in, heartbeats over a shared dir) rather
  than via jax.distributed — liveness detection must not depend on the
  collective runtime a dead peer just wedged, and this keeps the cell
  runnable on jaxlib builds whose CPU backend lacks multiprocess
  collectives.  Host 0 owns the shared logdir/checkpoints (the survivor
  the relaunch resumes); other hosts train a decoy replica in a scratch
  logdir — their role is to heartbeat, straggle, and die on cue.  A
  relaunch round passes the SURVIVOR count (possibly 1) and a shrunken
  device count; ``resume=True`` reshards the last intact checkpoint onto
  the smaller mesh.

Usage::

    _host.py <spec_json> <task> <nproc> <shared_dir> <devices> [chaos]

``chaos`` comes from argv, not the spec: the runner arms it on round 0
and strips it from relaunch rounds (the fault already fired; replaying
it would kill the recovery the cell exists to prove).

Exits 0 on completion, 71/72 through the coordinated abort, or dies
outright under ``host_down``.  Host 0 prints
``SCENARIO_DONE steps=<n> final_cost=<loss> rollbacks=<k> skipped=<s>``.
"""

from __future__ import annotations

import os
import sys


def _serve_fleet_cell(spec, logdir: str, chaos: str) -> int:
    """The fleet serving cell: a multi-replica acceptor fronting N
    in-process engines over real sockets (wall clock -- failover needs
    live stream timeouts), a seeded open-loop trace driven through the
    TCP client, and replica-grade chaos (``replica_down@S:P`` kills a
    replica mid-trace so the gate measures goodput *across* the
    failover).  Telemetry (goodput books + the acceptor's ``serving``
    summary) lands in the judged logdir; replica reqtrace spans flush
    there too so ``min_trace_complete_frac`` sees the failed-over
    chains.  Knobs on ``spec.extra``: ``replicas`` / ``qps`` /
    ``requests`` / ``slo_ttft_ms`` / ``slots``."""
    import jax

    from dtf_tpu import telemetry as tel
    from dtf_tpu.bench.serve_load import poisson_trace
    from dtf_tpu.models.gpt import GPT, GPTConfig
    from dtf_tpu.resilience.chaos import FaultPlan
    from dtf_tpu.serve.fleet import (FleetConfig, build_local_fleet,
                                     client_summary, drive_trace)

    ex = spec.extra_dict
    replicas = int(ex["replicas"])
    qps = float(ex.get("qps", 20.0))
    n_requests = int(ex.get("requests", 36))
    slo_ttft_ms = float(ex.get("slo_ttft_ms", 2000.0))
    slots = int(ex.get("slots", 2))

    os.makedirs(logdir, exist_ok=True)
    tel.configure(logdir)
    cfg = GPTConfig.tiny()
    model = GPT(cfg)
    params = model.init(jax.random.key(spec.seed))
    acc = build_local_fleet(
        model, params, replicas, seed=spec.seed,
        config=FleetConfig(stream_timeout_s=10.0, beat_stale_s=3.0,
                           monitor_interval_s=0.1, connect_timeout_s=2.0),
        logdir=logdir,
        engine_kwargs=dict(num_slots=slots, max_queue=256))
    acc.start()
    try:
        # warm every replica through BOTH prompt-shape buckets (each
        # bucket jit-compiles its own prefill) before arming chaos, so
        # the fault's dispatch sequence counts measured requests only.
        # Decode-length geometries are deliberately NOT warmed: a
        # failover shifts the measured trace's long decodes onto the
        # survivor cold, and the resulting compile-plus-replay TTFT
        # spike is the fault's client-visible signature — exactly what
        # the attribution gate judges the incident plane on
        warm = poisson_trace(seed=spec.seed + 1,
                             n_requests=2 * replicas * slots, qps=1000.0,
                             prompt_lens=[4, 8], output_lens=[2],
                             vocab_size=cfg.vocab_size, temperature=0.0)
        drive_trace(acc.address, warm, request_timeout_s=120.0)
        # the warmup barrage's compile-dominated latencies would poison
        # the anomaly detectors' baselines (a compile looks exactly like
        # a fault); restart them so the measured trace builds its
        # baseline from steady-state serving only
        from dtf_tpu.telemetry import anomaly as _anomaly
        _anomaly.get_monitor().reset_baselines()
        if chaos:
            acc.arm_chaos(FaultPlan.parse(chaos, process_index=0))
        trace = poisson_trace(
            seed=spec.seed, n_requests=n_requests, qps=qps,
            prompt_lens=[4, 8], output_lens=[16, 32],
            vocab_size=cfg.vocab_size, temperature=0.0,
            priorities=[0, 0, 1])
        res = drive_trace(acc.address, trace, request_timeout_s=120.0)
    finally:
        acc.shutdown()
    cs = client_summary(res, slo_ttft_ms=slo_ttft_ms)
    t = acc.totals()
    # the judged serving keys reflect the MEASURED trace as the client
    # saw it — the warmup barrage exists only to pay the jit compile and
    # would otherwise dilute goodput_qps / inflate ttft_p99
    acc.write_telemetry(
        logdir, slo_ttft_ms=slo_ttft_ms,
        extra={"goodput_qps": cs["goodput_qps"],
               "completed_qps": cs["completed_qps"],
               "ttft_ms_p50": cs["ttft_ms_p50"],
               "ttft_ms_p99": cs["ttft_ms_p99"],
               "makespan_s": cs["makespan_s"],
               "measured_requests": n_requests,
               "measured_lost": cs["lost"]})
    tel.get_tracer().flush()
    print(f"SCENARIO_DONE completed={cs['completed']} "
          f"lost={cs['lost']} failovers={t['failovers']} "
          f"replayed={t['replayed']} "
          f"goodput_qps={cs.get('goodput_qps', 0.0):.3f} "
          f"ttft_p99={cs.get('ttft_ms_p99', 0.0):.1f}ms", flush=True)
    return 0 if cs["lost"] == 0 else 1


def _serve_cell(spec, logdir: str, chaos: str) -> int:
    """The serving cell: a chaos'd closed-loop load run through the
    continuous-batching engine on the deterministic virtual clock, with
    deadlines + the brownout controller armed, telemetry (goodput books
    + the ``serving`` summary) written to the logdir the runner judges.
    Scale knobs ride ``spec.extra``: ``qps`` / ``requests`` /
    ``slo_ttft_ms`` / ``deadline_ms`` / ``slots`` / ``qps_profile``
    (arrival-rate shape, bench/serve_load.py) / ``trace_vocab`` (prompt
    alphabet cap — small alphabets give the n-gram drafter material).
    Cells that carry a ``replicas`` knob route to the fleet cell.

    ``controller=1`` arms the self-tuning knob controller
    (dtf_tpu/control) — and turns the cell into a SAME-TRACE A/B: a
    pinned-knob baseline pass runs first (fresh engine, identical trace
    and fault plan), then the controller pass, and the cell FAILS
    unless the controller strictly beats the baseline on goodput QPS
    with p99 TTFT / p99 TPOT / deadline violations no worse.  The
    judged telemetry is the controller pass's; the baseline's numbers
    ride the summary under ``control_ab`` so the margin is on disk.
    Engine summaries are engine-local (per-run results), so the two
    in-process passes cannot pollute each other's judged numbers."""
    import jax

    if "replicas" in spec.extra_dict:
        return _serve_fleet_cell(spec, logdir, chaos)

    from dtf_tpu import telemetry as tel
    from dtf_tpu.bench.serve_load import poisson_trace
    from dtf_tpu.models.gpt import GPT, GPTConfig
    from dtf_tpu.resilience.chaos import FaultPlan
    from dtf_tpu.serve import (BrownoutController, ServingEngine,
                               VirtualClock)
    from dtf_tpu.telemetry.slo import BurnRateMonitor

    ex = spec.extra_dict
    qps = float(ex.get("qps", 10.0))
    n_requests = int(ex.get("requests", 60))
    slo_ttft_ms = float(ex.get("slo_ttft_ms", 400.0))
    deadline_ms = float(ex.get("deadline_ms", 2500.0))
    slots = int(ex.get("slots", 4))
    block_size = int(ex.get("block_size", 16))
    qps_profile = str(ex.get("qps_profile", "constant"))
    controller = bool(ex.get("controller", 0))
    prefix_cache = bool(ex.get("prefix_cache", 0))

    # span tracer into the judged logdir: the cell's
    # min_trace_complete_frac gate reads the per-request trace chains
    # back off these files (runner judges out-of-band, from disk)
    os.makedirs(logdir, exist_ok=True)
    tel.configure(logdir)
    cfg = GPTConfig.tiny()
    model = GPT(cfg)
    params = model.init(jax.random.key(spec.seed))
    vocab = int(ex.get("trace_vocab", cfg.vocab_size))
    if prefix_cache:
        # the shared-prefix chatbot trace (bench/serve_load.py): a small
        # pool of long shared system prompts, short fresh suffixes,
        # greedy/sampled alternating — the workload the prefix cache's
        # hit-rate gate is judged on
        from dtf_tpu.bench.serve_load import shared_prefix_trace
        trace = shared_prefix_trace(
            seed=spec.seed, n_requests=n_requests, qps=qps,
            n_prefixes=int(ex.get("n_prefixes", 3)),
            prefix_len=int(ex.get("prefix_len", 5 * block_size)),
            suffix_lens=[1, 4, 7], output_lens=[2, 4, 8],
            vocab_size=min(vocab, cfg.vocab_size))
    else:
        trace = poisson_trace(
            seed=spec.seed, n_requests=n_requests, qps=qps,
            prompt_lens=[4, 8, 16], output_lens=[2, 8, 16],
            vocab_size=min(vocab, cfg.vocab_size),
            deadline_ms=deadline_ms,
            priorities=[0, 0, 1], qps_profile=qps_profile)

    def run_pass(arm_knobs: bool):
        # fresh engine + clock + fault plan per pass (fired chaos
        # latches are per-plan state) — the ONLY arm difference is the
        # knob controller
        plan = (FaultPlan.parse(chaos, process_index=0) if chaos
                else None)
        engine = ServingEngine(
            model, params, num_slots=slots, block_size=block_size,
            seed=spec.seed, clock=VirtualClock(), max_queue=256,
            brownout=BrownoutController(slo_ttft_ms), chaos=plan,
            slo=BurnRateMonitor.for_serving(slo_ttft_ms),
            prefix_cache=prefix_cache)
        if arm_knobs:
            from dtf_tpu.control import arm_controller
            arm_controller(engine)
        engine.run(trace)
        return engine, engine.summary(slo_ttft_ms=slo_ttft_ms)

    extra = None
    if controller:
        _, base = run_pass(False)
    engine, s = run_pass(controller)
    if controller:
        # the strict-improvement contract, judged in-cell (the gate
        # thresholds on disk are the controller arm's absolutes; the
        # RELATIVE claim needs both arms' numbers)
        deltas = {
            "goodput_qps": (s.get("goodput_qps", 0.0),
                            base.get("goodput_qps", 0.0)),
            "ttft_ms_p99": (s.get("ttft_ms_p99"), base.get("ttft_ms_p99")),
            "tpot_ms_p99": (s.get("tpot_ms_p99"), base.get("tpot_ms_p99")),
            "deadline_violations": (s.get("deadline_violations", 0),
                                    base.get("deadline_violations", 0)),
        }
        if not (deltas["goodput_qps"][0] > deltas["goodput_qps"][1]
                and deltas["ttft_ms_p99"][0] <= deltas["ttft_ms_p99"][1]
                and deltas["tpot_ms_p99"][0] <= deltas["tpot_ms_p99"][1]
                and deltas["deadline_violations"][0]
                <= deltas["deadline_violations"][1]):
            print(f"SCENARIO_FAIL controller did not strictly beat the "
                  f"pinned baseline: {deltas}", flush=True)
            return 1
        extra = {"control_ab": {
            "baseline": {k: v[1] for k, v in deltas.items()},
            "controller": {k: v[0] for k, v in deltas.items()}}}
    engine.write_telemetry(logdir, slo_ttft_ms=slo_ttft_ms, extra=extra)
    tel.get_tracer().flush()
    line = (f"SCENARIO_DONE completed={s['completed']} shed={s['shed']} "
            f"goodput_qps={s.get('goodput_qps', 0.0):.3f} "
            f"ttft_p99={s.get('ttft_ms_p99', 0.0):.1f}ms "
            f"violations={s.get('deadline_violations', 0)}")
    if controller:
        c = s.get("control") or {}
        line += (f" baseline_goodput_qps={base.get('goodput_qps', 0.0):.3f}"
                 f" knob_sets={c.get('sets', 0)}"
                 f" rollbacks={c.get('rollbacks', 0)}")
    print(line, flush=True)
    return 0


def main(spec_json: str, task: int, nproc: int, shared: str,
         devices: int, chaos: str = "") -> int:
    from dtf_tpu import telemetry as tel
    from dtf_tpu.cluster import bootstrap
    from dtf_tpu.config import ClusterConfig, TrainConfig
    from dtf_tpu.resilience.chaos import FaultPlan
    from dtf_tpu.scenarios import zoo
    from dtf_tpu.scenarios.spec import ScenarioSpec
    from dtf_tpu.train.trainer import Trainer

    spec = ScenarioSpec.from_json(spec_json)
    if spec.workload == "serve":
        return _serve_cell(spec, os.path.join(shared, "logs"), chaos)
    cluster = bootstrap(ClusterConfig(simulated_devices=devices,
                                      mesh="data=-1"))
    elastic = spec.hosts > 1
    logdir = (os.path.join(shared, "logs") if task == 0
              else os.path.join(shared, f"logs_task{task}"))
    kit = zoo.build(spec)
    splits = kit.splits_factory()
    batch_count = max(splits.train.num_examples // spec.batch_size, 1)
    epochs = -(-spec.steps // batch_count) + 1     # ceil + resume slack
    cfg = TrainConfig(
        batch_size=spec.batch_size, learning_rate=spec.learning_rate,
        optimizer=spec.optimizer, epochs=epochs,
        log_frequency=spec.log_frequency, seed=spec.seed, logdir=logdir,
        checkpoint_every=spec.checkpoint_every,
        grad_sync=spec.grad_sync, grad_bucket_mb=spec.grad_bucket_mb,
        grad_comm_dtype=spec.grad_comm_dtype, plan=spec.plan,
        # Elastic relaunch rounds are FRESH processes: they re-read the
        # persistent compile cache instead of re-paying the backend
        # compile (the PR-4 machinery).  Per-TASK dir, not per-cell:
        # same-geometry hosts produce identical HLO, so a shared dir
        # means two processes racing writes to the same cache key —
        # observed heap corruption (SIGABRT/SIGSEGV) on this jaxlib's
        # CPU backend; rounds of one task are sequential, so a per-task
        # dir has exactly one writer.  Supervised cells must NOT arm it
        # either: their restarts are in-PROCESS, and deserializing a
        # cached executable into a process that already compiled it
        # corrupts the heap the same way (the in-memory jit cache is
        # the right reuse there anyway).
        compile_cache=(os.path.join(shared, f"compile_cache_t{task}")
                       if elastic else None),
        resume=elastic)
    fit_kwargs = {"max_steps": spec.steps, "epochs": epochs}

    if not elastic:
        from dtf_tpu.resilience.supervisor import run_supervised_fit
        result = run_supervised_fit(
            lambda c, plan: Trainer(cluster, kit.model,
                                    kit.make_optimizer(), c, chaos=plan),
            kit.splits_factory, cfg, max_restarts=spec.max_restarts,
            chaos=chaos or None, initial_splits=splits,
            fit_kwargs=fit_kwargs)
    else:
        from dtf_tpu.resilience.health import HealthMonitor, make_transport
        from dtf_tpu.telemetry import fleet

        plan = (FaultPlan.parse(chaos, process_index=task) if chaos
                else None)
        monitor = None
        if nproc > 1:
            # Fleet plane (ISSUE 12): explicit identity, same pattern as
            # the health mesh below — every host's span stream lands in
            # the JUDGED logdir (host 0's) under its fleet index, so the
            # cell's max_skew_ms / min_fleet_goodput gates read real
            # cross-host attribution.  Relaunch rounds run nproc==1 and
            # skip it; round-0's fleet.json and fleet/sync spans persist
            # for the post-hoc judgement.
            fleet.configure(os.path.join(shared, "fleet"), task, nproc,
                            spans_dir=os.path.join(shared, "logs"))
            # 0.5s x 8 = a 4s miss budget (vs the mp rig's 1s): matrix
            # cells run back-to-back on a loaded CI box where a GC or
            # compile pause past 1s makes BOTH hosts poison each other
            # (observed: round ends "2 -> 2 survivors", every host 71).
            # Detection still lands well inside the paced survivor's
            # remaining run.
            monitor = HealthMonitor(
                make_transport(os.path.join(shared, "health"), task,
                               is_coordinator=task == 0),
                task, nproc, interval_s=0.5, miss_budget=8,
                boot_grace_s=120.0, is_coordinator=task == 0).start()
            if plan is not None:
                plan.bind_partition(monitor.partition)
        trainer = Trainer(cluster, kit.model, kit.make_optimizer(), cfg,
                          chaos=plan)
        if monitor is not None:
            # Warm the step compile BEFORE the startup barrier, on a
            # throwaway state copy (step_fn donates its first argument)
            # and a dummy batch, so every host enters the fault schedule
            # in lockstep: compile skew must not let a fast host die
            # before a slow host has checkpointed anything.
            import jax

            from dtf_tpu.train.trainer import put_global_batch

            dummy = put_global_batch(
                cluster.mesh, splits.train.next_batch(spec.batch_size))
            splits = kit.splits_factory()      # rewind the probe batch
            throwaway = jax.tree_util.tree_map(lambda x: x + 0,
                                               trainer.state)
            jax.block_until_ready(
                trainer.step_fn(throwaway, dummy, jax.random.key(0)))
            monitor.wait_for_peers(120.0)
        completed = False
        try:
            result = trainer.fit(splits, **fit_kwargs)
            completed = True
        finally:
            if monitor is not None:
                # Only a COMPLETED fit departs cleanly; a crash lets the
                # beats stop so peers run the coordinated abort.
                monitor.close(mark_departed=completed)
            if trainer.ckpt is not None:
                trainer.ckpt.close()

    if task == 0:
        print(f"SCENARIO_DONE steps={result['steps']} "
              f"final_cost={result['final_cost']:.6f} "
              f"rollbacks={result.get('rollbacks', 0)} "
              f"skipped={result.get('skipped_steps', 0)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
                  sys.argv[4], int(sys.argv[5]),
                  sys.argv[6] if len(sys.argv) > 6 else ""))
