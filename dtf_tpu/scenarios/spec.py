"""Declarative scenario specs: workload x scale x chaos plan x triple gate.

A :class:`ScenarioSpec` is one CELL of the scenario matrix — everything
needed to (a) run a workload at a given scale under a fault schedule and
(b) judge the outcome.  The judgement is the **triple gate** (MLPerf-pods
style, arxiv 1909.09756, plus the fault axis that harness never had):

* **convergence** — the run's final cost must reach a PINNED per-workload
  target (the trajectory is deterministic: synthetic data + fixed seeds,
  so the target is a property of the cell, not of the machine);
* **goodput** — the productive fraction of wall-clock must clear a floor
  even with the injected faults' restarts/rollbacks/stalls on the books;
* **throughput/MFU** — examples-or-tokens per second (and, where the chip
  peak is known, MFU percent) must clear a floor, so a cell that
  "recovers" by grinding 10x slower still fails.

A cell passes only when it *recovers and still trains well enough, fast
enough*.  Specs are plain dataclasses with a JSON round-trip so matrices
can live in code (:data:`MATRICES`) or in a user's JSON file
(``python -m dtf_tpu.scenarios --matrix my_matrix.json``).

This module is jax-free (the CLI parses matrices before any backend
exists); chaos specs are validated by parsing them with the real
:class:`~dtf_tpu.resilience.chaos.FaultPlan` grammar so a typo'd fault
fails at matrix-load time, not minutes into the run.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

#: Training workload-zoo keys (mirrored by scenarios/zoo.py's builder
#: table; a pinned test keeps the two in sync so this module stays
#: jax-free).
TRAIN_WORKLOADS = ("mnist", "cifar", "gpt", "seq2seq")
#: All cell kinds: training workloads plus the SERVING cell — a chaos'd
#: closed-loop load run through the continuous-batching engine
#: (scenarios/_host.py's serve branch), judged on the serving gates
#: (goodput-QPS floor + p99 TTFT ceiling) instead of convergence.
WORKLOADS = TRAIN_WORKLOADS + ("serve",)


@dataclasses.dataclass(frozen=True)
class Gate:
    """The triple gate's thresholds for one cell.  ``min_goodput`` is
    always armed; ``max_final_cost`` is armed for every TRAINING cell
    (``None`` only for serve cells, which have no loss curve);
    throughput arms whichever floors are > 0 (the CPU sim has no known
    chip peak, so cells there gate on examples/tokens per second and
    leave ``min_mfu_pct`` at 0 — on real chips set it and the MFU gate
    arms via ``mfu/pct_peak``).  Serve cells gate on ``min_goodput_qps``
    (SLO-met completions per second of makespan) and ``max_ttft_p99_ms``
    instead — same :func:`~dtf_tpu.telemetry.report.check_gates`
    implementation, read off the telemetry the run left on disk."""

    max_final_cost: Optional[float]
    min_goodput: float
    min_examples_per_s: float = 0.0
    min_tokens_per_s: float = 0.0
    min_mfu_pct: float = 0.0
    max_rollbacks: Optional[int] = None
    min_goodput_qps: float = 0.0
    max_ttft_p99_ms: float = 0.0
    #: Streaming-cadence ceiling (0 = not armed): p99 time-per-output-
    #: token — the controller cells arm it so a goodput win bought with
    #: a decode-cadence blow-up still fails.
    max_tpot_p99_ms: float = 0.0
    #: Control-plane gate (ISSUE 17, dtf_tpu/control; None = not armed):
    #: ceiling on the knob controller's snap-backs.  Armed on controller
    #: cells it ALSO proves the controller ran at all — the counter
    #: registers eagerly at arm time, so its absence from telemetry.json
    #: fails the gate (never-armed != calm).
    max_control_rollbacks: Optional[int] = None
    #: Observability gate (ISSUE 11): floor on the fraction of COMPLETED
    #: requests whose per-request trace reconstructs the full
    #: admission->prefill->first_token->completion chain from the span
    #: files (0 = not armed; serve cells arm it so recovery is not just
    #: achieved but attributable).
    min_trace_complete_frac: float = 0.0
    #: Fleet gates (ISSUE 12, telemetry/fleet.py; 0 = not armed) — the
    #: multi-host cells arm them so pod-scale runs are judged on
    #: ATTRIBUTABLE skew, not just survival: ceiling on the median
    #: per-barrier arrival skew, floor on the fleet's joint productive
    #: fraction (coordinator rollup), ceiling on any one host's share
    #: of last-arrivals.
    max_skew_ms: float = 0.0
    min_fleet_goodput: float = 0.0
    max_blame_frac: float = 0.0
    #: Gradient-wire gate (ISSUE 19; 0 = not armed) — ceiling on the
    #: per-step scatter-leg wire payload (``comm/wire_bytes``).  The
    #: int8_ring cell pins it between the ring wire and the one-shot
    #: int8 wire, so a run that silently fell back to a fatter wire
    #: fails even when it converges; an absent gauge fails too.
    max_wire_bytes_per_step: float = 0.0
    #: Incident gate (ISSUE 18, telemetry/anomaly.py + diagnose.py;
    #: 0 = not armed) — chaos-bearing cells arm it so the incident
    #: plane is judged END TO END: the injected fault must be DETECTED
    #: (chaos fired with zero anomalies = frac None = not-measured =
    #: FAIL) and the detected anomalies must rank the injected fault
    #: kind TOP (a correlator that blames an innocent plane fails the
    #: same floor).  Virtual-clock cells pin it high (determinism);
    #: wall-clock cells sit looser for scheduler noise.
    min_attribution_frac: float = 0.0
    #: Prefix-cache gate (ISSUE 20, serve/paged_kv.py sharing tier;
    #: 0 = not armed) — floor on the serving summary's
    #: ``prefix_hit_rate`` (matched prefix blocks over probed blocks at
    #: admission).  The engine writes the key only when its prefix
    #: cache is armed, so an absent rate = the cell served cold = FAIL
    #: (the same falsifiability rule as ``max_control_rollbacks``).
    min_prefix_hit_rate: float = 0.0

    def thresholds(self) -> dict:
        """Kwargs for :func:`dtf_tpu.telemetry.report.check_gates` — the
        ONE gate implementation, shared with ``report --check``."""
        out = {"min_goodput": self.min_goodput}
        if self.max_final_cost is not None:
            out["max_final_cost"] = self.max_final_cost
        if self.min_examples_per_s > 0:
            out["min_examples_per_s"] = self.min_examples_per_s
        if self.min_tokens_per_s > 0:
            out["min_tokens_per_s"] = self.min_tokens_per_s
        if self.min_mfu_pct > 0:
            out["min_mfu"] = self.min_mfu_pct
        if self.max_rollbacks is not None:
            out["max_rollbacks"] = self.max_rollbacks
        if self.min_goodput_qps > 0:
            out["min_goodput_qps"] = self.min_goodput_qps
        if self.max_ttft_p99_ms > 0:
            out["max_ttft_p99_ms"] = self.max_ttft_p99_ms
        if self.max_tpot_p99_ms > 0:
            out["max_tpot_p99_ms"] = self.max_tpot_p99_ms
        if self.max_control_rollbacks is not None:
            out["max_control_rollbacks"] = self.max_control_rollbacks
        if self.min_trace_complete_frac > 0:
            out["min_trace_complete_frac"] = self.min_trace_complete_frac
        if self.max_skew_ms > 0:
            out["max_skew_ms"] = self.max_skew_ms
        if self.min_fleet_goodput > 0:
            out["min_fleet_goodput"] = self.min_fleet_goodput
        if self.max_blame_frac > 0:
            out["max_blame_frac"] = self.max_blame_frac
        if self.min_attribution_frac > 0:
            out["min_attribution_frac"] = self.min_attribution_frac
        if self.max_wire_bytes_per_step > 0:
            out["max_wire_bytes_per_step"] = self.max_wire_bytes_per_step
        if self.min_prefix_hit_rate > 0:
            out["min_prefix_hit_rate"] = self.min_prefix_hit_rate
        return out


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """One matrix cell: a workload at a scale, under a chaos plan,
    against a :class:`Gate`.

    ``hosts == 1`` runs the cell as ONE supervised process
    (:func:`~dtf_tpu.resilience.supervisor.run_supervised_fit`: crashes
    and preemptions restore the last checkpoint under the
    ``max_restarts`` budget).  ``hosts > 1`` runs it as a multi-host
    elastic job (:func:`~dtf_tpu.resilience.supervisor.run_elastic_hosts`
    over per-host child processes with the health subsystem armed): a
    ``host_down`` fault kills a host, survivors abort coordinated (exit
    71), and the relaunch resumes host 0's trajectory on a mesh shrunk to
    ``shrink_devices`` — the elastic-restart scenario."""

    name: str
    workload: str
    gate: Gate
    chaos: Optional[str] = None
    devices: int = 2                 # simulated CPU devices per host
    steps: int = 30                  # total optimizer-step budget
    batch_size: int = 64
    optimizer: str = "adam"
    learning_rate: float = 1e-3
    grad_sync: str = "dense"
    grad_bucket_mb: float = 0.1
    #: Gradient wire dtype (None = exact f32; "int8_ring" = the EQuARX
    #: per-hop requantizing ring, ISSUE 19) — forwarded verbatim to
    #: TrainConfig.grad_comm_dtype.
    grad_comm_dtype: Optional[str] = None
    #: "auto" hands the cell's sharding knobs to the planner
    #: (parallel/planner.py); hand-set spec fields remain the override,
    #: exactly like CLI flags under ``--plan auto``.
    plan: Optional[str] = None
    checkpoint_every: int = 5
    max_restarts: int = 2
    log_frequency: int = 5
    seed: int = 1
    hosts: int = 1
    shrink_devices: int = 0          # elastic relaunch mesh (0 = devices)
    max_rounds: int = 2              # elastic relaunch budget
    timeout_s: float = 420.0
    extra: tuple = ()                # workload knobs as sorted (k, v) pairs

    def __post_init__(self):
        if self.workload not in WORKLOADS:
            raise ValueError(f"unknown workload {self.workload!r}; "
                             f"one of {WORKLOADS}")
        if self.workload == "serve":
            if self.hosts > 1:
                raise ValueError(
                    f"cell {self.name!r}: serve cells are single-host "
                    f"(the engine is one process; multi-host serving is "
                    f"a load balancer's job, not a mesh's)")
            if self.gate.max_final_cost is not None:
                raise ValueError(
                    f"cell {self.name!r}: serve cells have no loss "
                    f"curve; set gate.max_final_cost=None and arm "
                    f"min_goodput_qps / max_ttft_p99_ms instead")
            if self.gate.min_goodput_qps <= 0:
                raise ValueError(
                    f"cell {self.name!r}: a serve cell must arm the "
                    f"goodput-QPS floor (gate.min_goodput_qps > 0) — "
                    f"without it the cell proves nothing about serving")
        elif self.gate.max_final_cost is None:
            raise ValueError(
                f"cell {self.name!r}: training cells must pin a "
                f"convergence target (gate.max_final_cost)")
        if self.hosts > 1 and "host_down" not in (self.chaos or ""):
            raise ValueError(
                f"cell {self.name!r}: hosts={self.hosts} is the elastic-"
                f"restart runner — its chaos plan must include a "
                f"host_down fault (otherwise nothing exercises the "
                f"relaunch and the extra hosts only slow the cell)")
        if self.chaos:
            # Fail at matrix-load time, with the cell named: the chaos
            # grammar is the real FaultPlan parser, not a mirror.
            from dtf_tpu.resilience.chaos import FaultPlan
            try:
                FaultPlan.parse(self.chaos, process_index=0)
            except ValueError as exc:
                raise ValueError(
                    f"cell {self.name!r}: bad chaos spec: {exc}") from exc

    @property
    def extra_dict(self) -> dict:
        return dict(self.extra)

    # -- JSON round-trip ----------------------------------------------------

    def to_json(self) -> str:
        doc = dataclasses.asdict(self)
        doc["gate"] = dataclasses.asdict(self.gate)
        doc["extra"] = dict(self.extra)
        return json.dumps(doc, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ScenarioSpec":
        doc = json.loads(text)
        doc["gate"] = Gate(**doc["gate"])
        doc["extra"] = tuple(sorted((doc.get("extra") or {}).items()))
        return cls(**doc)


# ---------------------------------------------------------------------------
# Curated matrices.  Gate thresholds are PINNED from measured CPU-sim runs
# (see DESIGN.md §8's table): convergence targets sit between the measured
# final cost and the run's EARLY loss (a target the untrained model would
# pass proves nothing — every target here is well under the step-5 cost),
# goodput floors at ~50% of measured (restart/rollback cost is
# deterministic, wall-clock is not; on the CPU sim compile dominates toy
# steps, so absolute fractions are small — the floor still catches a run
# whose wall-clock doubles), throughput floors at ~30% of measured (CI
# machines vary widely).
# ---------------------------------------------------------------------------


def default_matrix() -> List[ScenarioSpec]:
    """The CI matrix: >= 4 workloads, chaos-off baselines vs host-down /
    straggler / recurring-preemption / nan+corrupt-checkpoint plans, one
    elastic-restart cell, one LAMB+zero1 large-batch cell."""
    return [
        # -- chaos-off baselines (the control row: the same gates the
        #    chaos cells must clear, no faults to blame) ------------------
        ScenarioSpec(
            # measured: final 4.42 (step-5 cost 4.79), goodput
            # 0.06-0.12 and 2.9k-7.4k tok/s across runs (box-load
            # variance; floors sit at ~half the worst observed)
            name="gpt_baseline", workload="gpt", devices=2,
            steps=30, batch_size=32, learning_rate=3e-3,
            chaos=None, max_restarts=0,
            gate=Gate(max_final_cost=4.6, min_goodput=0.03,
                      min_tokens_per_s=900.0, max_rollbacks=0)),
        ScenarioSpec(
            # measured: final 1.72 (step-5 cost 2.32), goodput
            # 0.51-0.56, 32-71 ex/s (conv steps are ~1-2 s on the sim)
            name="cifar_baseline", workload="cifar", devices=2,
            steps=20, batch_size=64, learning_rate=3e-3,
            chaos=None, max_restarts=0,
            gate=Gate(max_final_cost=2.0, min_goodput=0.25,
                      min_examples_per_s=10.0, max_rollbacks=0)),
        # -- fault cells --------------------------------------------------
        ScenarioSpec(
            # nan-spike + checkpoint corruption + one preemption: the
            # guard skips the poisoned steps, restore_robust falls back
            # past the corrupted step, the supervisor restarts — and the
            # run must STILL converge fast enough.
            # measured: final 0.15 (step-5 cost 1.66), goodput
            # 0.034-0.05, 1.4k-1.7k ex/s — one restart, one
            # guard-skipped step
            name="mnist_nan_corrupt", workload="mnist", devices=2,
            steps=40, batch_size=128, learning_rate=1e-3,
            chaos="nan_grad@7,corrupt_ckpt@10,sigterm@17,seed=3",
            max_restarts=2,
            gate=Gate(max_final_cost=0.5, min_goodput=0.018,
                      min_examples_per_s=450.0, max_rollbacks=1)),
        ScenarioSpec(
            # recurring spot reclamation: every 12th step is a clean
            # preemption + supervisor restart; the budget completes
            # across attempts with the goodput books carrying the
            # restart windows.
            # measured: final 4.41 (step-5 cost 4.79), goodput
            # 0.049-0.05, 0.9k-1.8k tok/s — two preemptions, three
            # attempts
            name="gpt_preempt_recurring", workload="gpt", devices=2,
            steps=30, batch_size=32, learning_rate=3e-3,
            chaos="preempt@every:12", max_restarts=4,
            gate=Gate(max_final_cost=4.6, min_goodput=0.025,
                      min_tokens_per_s=300.0, max_rollbacks=0)),
        ScenarioSpec(
            # persistent straggler + checkpoint-write stalls: no restart
            # at all, just injected slowness — the goodput and throughput
            # floors are what catch it (and must still clear).
            # measured: final 3.68 (step-5 cost 4.07), goodput
            # 0.12-0.16, 77-184 ex/s — 40ms/step injected drag + 6
            # ckpt stalls
            name="seq2seq_straggler_ckpt_stall", workload="seq2seq",
            devices=2, steps=60, batch_size=32, learning_rate=1e-2,
            chaos="slow_host@5:0:40ms,ckpt_stall@every:10:250ms",
            max_restarts=1, checkpoint_every=2,
            # Incident gate (ISSUE 18): each 250ms ckpt_stall onset is a
            # checkpoint/save_ms discontinuity the anomaly plane must
            # both DETECT and pin on the injected chaos/ckpt_stall mark.
            # checkpoint_every=2 keeps stalled saves a 1-in-5 minority
            # of the detector window (at the default cadence of 5 every
            # SECOND save stalls, the window's MAD absorbs the stall
            # level and no robust detector can call it a changepoint).
            # Wall-clock run — the floor sits below 1.0 for scheduler
            # noise in the save-time baseline.
            gate=Gate(max_final_cost=3.85, min_goodput=0.04,
                      min_examples_per_s=25.0, max_rollbacks=0,
                      min_attribution_frac=0.75)),
        ScenarioSpec(
            # THE elastic cell: 2 hosts, host 1 dies abruptly (SIGKILL)
            # mid-run; host 0 exits via the coordinated abort (71) and
            # the relaunch resumes its checkpoint on a 4->2 shrunken
            # mesh.  Gates read host 0's books across both rounds.
            # Timing: host 1 (100ms/step) dies at its step 12 (~1.2s
            # past the lockstep barrier); host 0 (250ms/step, 40-step
            # budget ~10s) detects the loss at ~5s — reliably MID-run,
            # so the abort+relaunch path is exercised even when a loaded
            # box skews either side — and the relaunch round runs ~20
            # unpaced steps, enough sync windows to re-measure
            # throughput (gauges are per-process by contract).
            # measured: final 0.60 (step-5 cost 2.13), goodput
            # 0.013-0.034 (the pacing dominates wall-clock), ex/s noisy
            # across runs (last-window gauge) — floors stay loose
            name="mnist_host_down_elastic", workload="mnist",
            devices=4, shrink_devices=2, hosts=2, max_rounds=2,
            steps=40, batch_size=64, learning_rate=5e-2,
            optimizer="sgd",
            chaos=("slow_host@0:0:250ms,slow_host@0:1:100ms,"
                   "host_down@12:1"),
            timeout_s=600.0,
            # Fleet gates (ISSUE 12): round 0's two hosts feed the fleet
            # plane (skew from the 150 ms/step pacing differential —
            # measured p50 ~0.8-2.5 s across box loads — and the joint
            # goodput rollup); a 2-host cell that leaves no attributable
            # skew books is a failing cell.  max_skew_ms sits far above
            # the measured band because box-load variance inflates it,
            # but absence or a pathological (>15 s) skew still fails.
            gate=Gate(max_final_cost=0.9, min_goodput=0.006,
                      min_examples_per_s=50.0, max_rollbacks=0,
                      max_skew_ms=15000.0, min_fleet_goodput=0.002)),
        ScenarioSpec(
            # THE serving cell (ISSUE 10): a closed-loop Poisson load
            # run with completion deadlines and mixed priority classes
            # through the continuous-batching engine, under a PERSISTENT
            # decode-rate brownout (slow_decode from iteration 30) plus
            # a client disconnect and a KV-corruption hit — the engine
            # must shed at the front door (never blow an admitted
            # deadline), evict exactly the poisoned victim, free the
            # dropped client's blocks, and still clear a goodput-QPS
            # floor at the p99 TTFT ceiling.  The SLO quantities are
            # DETERMINISTIC (virtual clock + seeded trace + seeded
            # fault plan); only the goodput fraction is wall-clock (a
            # fresh child pays the compile, so that floor sits low).
            # measured: 30 completed / 28 shed (20 brownout_admissions
            # + 8 low-priority) / 1 client drop / 1 kv eviction,
            # goodput 7.14 qps, ttft p99 519 ms, 0 deadline violations.
            # goodput FRACTION re-pinned for ISSUE 14's fast decode
            # data path: narrowed gather + batched prefill cut the
            # productive device seconds per token ~2.4x while the
            # virtual-clock child's wall stays compile/idle-dominated,
            # so the measured fraction fell 0.021 -> 0.0084; the floor
            # guards books-sanity, not throughput (goodput_qps does
            # that), so it tracks the faster engine down.
            # Observability gate (ISSUE 11): >= 99% of completed
            # requests must leave a gap-free admission->completion
            # trace chain in the span files, chaos notwithstanding
            # (measured 1.0 — every completion fully attributed).
            name="serve_overload_brownout", workload="serve", devices=1,
            chaos="slow_decode@30:60ms,client_drop@10,kv_poison@20",
            max_restarts=0,
            extra=(("deadline_ms", 2500.0), ("qps", 10.0),
                   ("requests", 60), ("slo_ttft_ms", 400.0)),
            # Incident gate (ISSUE 18): the iteration-30 slow_decode
            # onset is a TTFT/TPOT discontinuity; virtual clock makes
            # detection + chaos-top attribution deterministic.
            gate=Gate(max_final_cost=None, min_goodput=0.004,
                      min_goodput_qps=3.5, max_ttft_p99_ms=1200.0,
                      min_trace_complete_frac=0.99,
                      min_attribution_frac=0.99)),
        ScenarioSpec(
            # fleet failure-domain cell (ISSUE 16): a 3-replica serving
            # fleet behind the acceptor, replica 1 SIGKILL'd (in-process
            # kill) at measured dispatch 8 — mid-trace, with streams in
            # flight — and the triple gate is judged ACROSS the
            # failover: goodput-QPS floor, p99-TTFT ceiling (wall
            # clock: the fleet needs live sockets + stream timeouts, so
            # both sit loose vs. measured), and >= 99% gap-free
            # admission->completion trace chains — a failed-over
            # request's chain spans BOTH replicas stitched by trace_id,
            # with the survivor's submit span marked resubmit=true.
            # Offered qps sits AT the rig's fleet service rate (~6/s) —
            # the overload regime is serve_overload_brownout's job;
            # this cell isolates the failover cost.  measured (1-core
            # rig, 2 runs): 36/36 completed, 0 lost, 1-4 failovers all
            # replayed token-identically, goodput 2.9-3.3 qps, ttft
            # p99 3.9-4.8 s, trace_complete_frac 1.0, books 0.04-0.05.
            name="serve_fleet_replica_down", workload="serve",
            devices=1, chaos="replica_down@8:1", max_restarts=0,
            timeout_s=600.0,
            extra=(("qps", 6.0), ("replicas", 3), ("requests", 36),
                   ("slo_ttft_ms", 2000.0), ("slots", 2)),
            # Incident gate (ISSUE 18): the SIGKILL'd replica shows up
            # as a TTFT/queue discontinuity on the survivors; the
            # chaos/replica_down mark (with event/fleet_detach and
            # event/fleet_failover right behind it) must rank TOP.
            # Wall-clock fleet run — the floor sits loose.
            gate=Gate(max_final_cost=None, min_goodput=0.003,
                      min_goodput_qps=1.8, max_ttft_p99_ms=9000.0,
                      min_trace_complete_frac=0.99,
                      min_attribution_frac=0.75)),
        ScenarioSpec(
            # Prefix-cache cell (ISSUE 20): the shared-prefix chatbot
            # trace (3 long system prompts, short fresh suffixes,
            # greedy/sampled alternating) through the engine with the
            # sharing-aware KV pool armed — suffix-only prefill over
            # shared blocks.  Judged on the serving triple gate PLUS
            # min_prefix_hit_rate, the falsifiable arm: the engine
            # writes prefix_hit_rate only when its cache is on, so a
            # cell that silently served cold FAILS the gate rather than
            # passing vacuously.  Virtual clock -> the hit rate and SLO
            # quantities are deterministic; only the goodput fraction is
            # wall-clock (fresh child pays the compile; floor sits low).
            # measured: hit rate 0.9375, goodput 9.59 qps, ttft p99
            # 22.4 ms, trace_complete_frac 1.0, books fraction 0.030.
            name="serve_prefix_cache", workload="serve", devices=1,
            chaos=None, max_restarts=0,
            extra=(("block_size", 8), ("prefix_cache", 1),
                   ("qps", 10.0), ("requests", 48),
                   ("slo_ttft_ms", 400.0)),
            gate=Gate(max_final_cost=None, min_goodput=0.002,
                      min_goodput_qps=4.0, max_ttft_p99_ms=400.0,
                      min_trace_complete_frac=0.99,
                      min_prefix_hit_rate=0.8)),
        ScenarioSpec(
            # Self-tuning control plane, adversarial cell 1 (ISSUE 17):
            # OSCILLATING load — a square-wave arrival rate (1.5x/0.5x
            # the offered 36 qps, period span/4) that a pinned operating
            # point cannot be right for on both halves.  controller=1
            # makes the cell a same-trace A/B inside _host.py: the knob
            # controller must STRICTLY beat the pinned-knob baseline on
            # goodput QPS with p99 TTFT / p99 TPOT / deadline violations
            # no worse, or the cell fails before any threshold is read.
            # trace_vocab=12 gives the n-gram drafter material, so
            # raising spec_k under burst pressure is a real lever.
            # measured (virtual clock, deterministic): controller
            # 35.42 qps / ttft p99 221 ms / tpot p99 12.9 ms vs baseline
            # 34.74 qps / 232 ms / 13.1 ms — 7 audited knob sets, 0
            # rollbacks.  Absolute gates sit well outside the measured
            # point; max_control_rollbacks=1 tolerates one explained
            # snap-back and (counter registered eagerly at arm time)
            # fails if the controller never armed at all.
            name="serve_oscillating_load_controller", workload="serve",
            devices=1, chaos=None, max_restarts=0,
            extra=(("controller", 1), ("deadline_ms", 2500.0),
                   ("qps", 36.0), ("qps_profile", "square"),
                   ("requests", 64), ("slo_ttft_ms", 400.0),
                   ("trace_vocab", 12)),
            gate=Gate(max_final_cost=None, min_goodput=0.002,
                      min_goodput_qps=18.0, max_ttft_p99_ms=600.0,
                      max_tpot_p99_ms=30.0, max_control_rollbacks=1)),
        ScenarioSpec(
            # Self-tuning control plane, adversarial cell 2 (ISSUE 17):
            # SLOW-DRIFT decode degradation — a periodic slow_decode hit
            # (every 6th iteration, +50 ms) that gradually poisons the
            # decode cadence the pinned knobs were sized for.  Same
            # in-cell strict A/B contract as the oscillating cell.
            # measured (virtual clock, deterministic): controller
            # 24.22 qps / ttft p99 419 ms / tpot p99 21.9 ms vs baseline
            # 21.89 qps / 472 ms / 22.1 ms — 12 audited knob sets, 0
            # rollbacks (the controller leans on spec_k + brownout
            # cheapening to buy back the injected drag).
            name="serve_slow_drift_controller", workload="serve",
            devices=1, chaos="slow_decode@every:6:50ms", max_restarts=0,
            extra=(("controller", 1), ("deadline_ms", 2500.0),
                   ("qps", 28.0), ("requests", 64),
                   ("slo_ttft_ms", 400.0), ("trace_vocab", 12)),
            # Incident gate (ISSUE 18): the periodic +50ms slow_decode
            # hits are TPOT discontinuities; with the controller's own
            # control/set instants in the evidence stream the chaos
            # mark must STILL out-rank them (prior 1.0 vs 0.6) — the
            # cell that proves attribution is not fooled by a busy
            # control plane.  Virtual clock -> deterministic.
            gate=Gate(max_final_cost=None, min_goodput=0.002,
                      min_goodput_qps=12.0, max_ttft_p99_ms=1000.0,
                      max_tpot_p99_ms=45.0, max_control_rollbacks=1,
                      min_attribution_frac=0.99)),
        ScenarioSpec(
            # Pod-gradient cell (ISSUE 19): --plan auto on the 8-way
            # mesh (the planner derives zero1 + no-remat; the cell name
            # pins the expectation) with the EQuARX int8_ring wire and
            # a mid-run preemption, so checkpoint restore replays under
            # a PLANNED config.  Judged on the triple gate PLUS the
            # wire-bytes ceiling: the bound sits between the ring
            # scatter leg and the one-shot int8 wire, so a silent
            # fallback to any fatter wire fails even if the run
            # converges.  Convergence target pinned for PARITY with the
            # measured dense/f32 oracle (same cell, no plan, exact
            # wire): oracle final 0.1337, int8_ring final 0.1338 (per-
            # hop requant noise ~6e-5 on this trajectory) — the 0.45
            # target sits far under the early-step cost and holds for
            # both, so the planned+quantized cell is judged against the
            # exact path's bar, not a softened one.
            # measured: goodput 0.10-0.14, 6.1k-7.3k ex/s, wire
            # 72800 B/step (one-shot int8: 81120; f32: ~318 kB).
            name="mnist_zero1_int8_ring", workload="mnist",
            devices=8, steps=40, batch_size=256, learning_rate=1e-3,
            plan="auto", grad_comm_dtype="int8_ring",
            chaos="preempt@11,seed=7", max_restarts=2,
            gate=Gate(max_final_cost=0.45, min_goodput=0.04,
                      min_examples_per_s=1500.0, max_rollbacks=0,
                      max_wire_bytes_per_step=76000.0)),
        ScenarioSpec(
            # large-batch cell: LAMB under ZeRO-1 (trust-ratio norms
            # psum'd across shards) on the 8-way mesh, with a nan spike
            # to prove the guard composes with the sharded update.
            # measured: final 0.44 (step-5 cost 2.07), goodput
            # 0.18-0.21, 3.5k-10.4k ex/s — one guard-skipped step
            name="mnist_lamb_zero1_large_batch", workload="mnist",
            devices=8, steps=30, batch_size=512, learning_rate=1e-2,
            optimizer="lamb", grad_sync="zero1",
            chaos="nan_grad@9,seed=5", max_restarts=1,
            gate=Gate(max_final_cost=0.9, min_goodput=0.06,
                      min_examples_per_s=1200.0, max_rollbacks=0)),
    ]


def mini_matrix() -> List[ScenarioSpec]:
    """The full-suite lane's 2-cell smoke matrix: one chaos-off GPT cell,
    one host-down elastic MNIST cell — the cheapest pair that still
    exercises a clean baseline AND the detect/abort/relaunch path."""
    cells = {c.name: c for c in default_matrix()}
    return [cells["gpt_baseline"], cells["mnist_host_down_elastic"]]


MATRICES: Dict[str, "callable"] = {"default": default_matrix,
                                   "mini": mini_matrix}


def load_matrix(name_or_path: str) -> List[ScenarioSpec]:
    """Resolve ``--matrix``: a built-in name (:data:`MATRICES`) or a path
    to a JSON file holding a list of spec documents."""
    if name_or_path in MATRICES:
        return MATRICES[name_or_path]()
    with open(name_or_path) as f:
        docs = json.load(f)
    if not isinstance(docs, list) or not docs:
        raise ValueError(f"{name_or_path}: expected a non-empty JSON list "
                         f"of scenario specs")
    out = [ScenarioSpec.from_json(json.dumps(d)) for d in docs]
    names = [s.name for s in out]
    if len(set(names)) != len(names):
        raise ValueError(f"{name_or_path}: duplicate cell names {names}")
    return out
