"""Scenario matrix: workload zoo x chaos x triple gate (DESIGN.md §8).

The north star's "handles as many scenarios as you can imagine" as a CI
matrix instead of a claim: every cell runs a workload at a declared
scale under a declared fault schedule and must pass ALL THREE gates —
convergence to a pinned target, a goodput-fraction floor, and a
throughput/MFU floor — read from the telemetry spine the run left on
disk.  PR 1-2's chaos/self-healing/elastic machinery supplies the
faults and the recovery; PR 3's goodput/MFU accounting supplies the
measurements; this package supplies the enforceable contract between
them.

    python -m dtf_tpu.scenarios --matrix default --check

* :mod:`.spec` — declarative cell specs + the curated matrices;
* :mod:`.zoo` — per-workload (model, optimizer, data) builders;
* :mod:`.runner` — child-process cell execution + gate evaluation
  (gates via :func:`dtf_tpu.telemetry.report.check_gates`, shared with
  ``report --check``);
* :mod:`._host` — the per-host child (supervised or elastic-health
  shape).
"""

from dtf_tpu.scenarios.spec import (Gate, MATRICES, ScenarioSpec,  # noqa: F401
                                    WORKLOADS, default_matrix,
                                    load_matrix, mini_matrix)
from dtf_tpu.scenarios.runner import CellResult, run_cell  # noqa: F401
