"""Scenario-matrix CLI: run cells, emit per-cell JSON, summarize, gate.

    python -m dtf_tpu.scenarios --matrix default --check
    python -m dtf_tpu.scenarios --matrix mini --out results/ --check
    python -m dtf_tpu.scenarios --matrix my_cells.json --only gpt_baseline

``--matrix`` is a built-in name (``default``, ``mini``) or a path to a
JSON list of spec documents.  Each cell writes ``<out>/<name>.json``
(spec + measured quantities + per-gate verdicts) and the run ends with a
summary table.  ``--check`` exits non-zero unless EVERY cell passes all
its gates — the CI entry point that turns "handles many scenarios" from
a claim into a matrix.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import List, Optional

from dtf_tpu.scenarios.runner import CellResult, run_cell
from dtf_tpu.scenarios.spec import MATRICES, load_matrix


def _fmt(v, width=9, digits=4) -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float):
        return f"{v:{width}.{digits}g}"
    return str(v).rjust(width)


def _triage(logdir: str) -> dict:
    """Run the incident diagnoser over a failed repeat's logdir: the
    top-ranked suspect across its incidents + the counts.  Never raises
    (a triage that crashes must not mask the cell failure it explains)."""
    try:
        from dtf_tpu.telemetry import diagnose
        doc = diagnose.diagnose_logdir(logdir)
    except Exception as exc:
        return {"error": str(exc)}
    tops = [i["top"] for i in doc.get("incidents", []) if i.get("top")]
    best = max(tops, key=lambda t: t["score"], default=None)
    return {"anomalies": doc.get("anomalies", 0),
            "attributed": doc.get("attributed", 0),
            "attribution_frac": doc.get("attribution_frac"),
            "top_suspect": ({"plane": best["plane"], "kind": best["kind"],
                             "score": round(best["score"], 4)}
                            if best else None),
            "standing": [s.get("summary") for s in
                         doc.get("standing", [])]}


def summary_table(results: List[CellResult]) -> str:
    lines = [f"{'cell':<30} {'workload':<9} {'chaos':<7} "
             f"{'final':>9} {'goodput':>9} {'ex/s':>9} {'tok/s':>9} "
             f"{'rnds':>4}  verdict"]
    for r in results:
        m = r.measured
        lines.append(
            f"{r.spec.name:<30} {r.spec.workload:<9} "
            f"{'yes' if r.spec.chaos else 'off':<7} "
            f"{_fmt(m.get('final_cost'))} "
            f"{_fmt(m.get('goodput_fraction'))} "
            f"{_fmt(m.get('examples_per_s'), digits=5)} "
            f"{_fmt(m.get('tokens_per_s'), digits=5)} "
            f"{r.rounds:>4}  {'PASS' if r.ok else 'FAIL'}")
    passed = sum(r.ok for r in results)
    lines.append(f"{passed}/{len(results)} cells passed")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dtf_tpu.scenarios",
        description="Run the workload x chaos x triple-gate scenario "
                    "matrix (DESIGN.md §8).")
    p.add_argument("--matrix", default="default",
                   help=f"built-in matrix name ({sorted(MATRICES)}) or a "
                        f"path to a JSON list of cell specs")
    p.add_argument("--only", default=None,
                   help="comma-separated cell names to run (subset)")
    p.add_argument("--repeat", type=int, default=1,
                   help="run each selected cell N times (flake hunt / "
                        "determinism check); every repeat must pass")
    p.add_argument("--out", default=None,
                   help="results directory (per-cell JSON + summary); "
                        "default: a fresh temp dir, printed")
    p.add_argument("--check", action="store_true",
                   help="CI gate: exit non-zero unless every cell passes "
                        "all three gates")
    p.add_argument("--list", action="store_true",
                   help="print the resolved cells and exit")
    ns = p.parse_args(argv)

    try:
        cells = load_matrix(ns.matrix)
    except (OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if ns.only:
        want = {n.strip() for n in ns.only.split(",") if n.strip()}
        unknown = want - {c.name for c in cells}
        if unknown:
            print(f"error: --only names not in the matrix: "
                  f"{sorted(unknown)}", file=sys.stderr)
            return 2
        cells = [c for c in cells if c.name in want]
    if ns.list:
        for c in cells:
            print(f"{c.name:<30} {c.workload:<9} hosts={c.hosts} "
                  f"devices={c.devices} steps={c.steps} "
                  f"chaos={c.chaos or '-'}")
        return 0

    out = ns.out or tempfile.mkdtemp(prefix="dtf_scenarios_")
    os.makedirs(out, exist_ok=True)
    workdir = os.path.join(out, "work")
    os.makedirs(workdir, exist_ok=True)
    print(f"[scenarios] matrix {ns.matrix!r}: {len(cells)} cell(s), "
          f"results under {out}", flush=True)

    if ns.repeat < 1:
        print(f"error: --repeat must be >= 1, got {ns.repeat}",
              file=sys.stderr)
        return 2

    results: List[CellResult] = []
    total = len(cells) * ns.repeat
    for i, spec in enumerate(cells):
        for rep in range(ns.repeat):
            tag = f" (repeat {rep + 1}/{ns.repeat})" if ns.repeat > 1 else ""
            print(f"[scenarios] [{i * ns.repeat + rep + 1}/{total}] "
                  f"{spec.name}{tag} (workload={spec.workload}, "
                  f"hosts={spec.hosts}, chaos={spec.chaos or 'off'}) ...",
                  flush=True)
            res = run_cell(spec, workdir)
            results.append(res)
            suffix = f".rep{rep}" if rep else ""
            doc = res.to_doc()
            if not res.ok and res.logdir:
                # failure triage (ISSUE 18): a failed repeat diagnoses
                # itself — the incident correlator's top suspect and
                # incident count land in the per-repeat JSON so a flake
                # hunt reads WHY, not just which repeat
                doc["triage"] = _triage(res.logdir)
            with open(os.path.join(out, f"{spec.name}{suffix}.json"),
                      "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            status = "PASS" if res.ok else "FAIL"
            print(f"[scenarios]   -> {status} in {res.duration_s:.1f}s",
                  flush=True)
            if res.error:
                print(f"[scenarios]   error: {res.error}", flush=True)
            if doc.get("triage"):
                t = doc["triage"]
                top = t.get("top_suspect")
                print(f"[scenarios]   triage: {t.get('anomalies', 0)} "
                      f"anomaly(ies), top suspect "
                      + (f"[{top['plane']}] {top['kind']}" if top
                         else "NONE"), flush=True)
            for line in res.gates:
                print(f"[scenarios]   {line}", flush=True)

    table = summary_table(results)
    print(table)
    with open(os.path.join(out, "summary.txt"), "w") as f:
        f.write(table + "\n")
    if ns.check and not all(r.ok for r in results):
        failed = [r.spec.name for r in results if not r.ok]
        print(f"scenario check: FAIL — {failed}", flush=True)
        return 1
    if ns.check:
        print("scenario check: OK — all cells passed the triple gate",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
