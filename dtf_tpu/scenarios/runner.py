"""Scenario-cell runner: spawn the cell, then judge it off the telemetry.

Each cell runs in CHILD processes (one per host) so every cell gets its
own simulated-device count, fresh jax backend, and fresh telemetry books
— the runner itself never imports jax.  Supervised cells are one child;
elastic cells go through :func:`~dtf_tpu.resilience.supervisor.
run_elastic_hosts` (the same decision procedure production's job
scheduler runs), which relaunches survivors on a shrunken mesh.

Judgement is deliberately OUT-of-band: the runner reads what the run
left on disk — ``telemetry.json`` goodput books, ``metrics.csv``
(attempt-deduplicated final cost), the instrument snapshot — through
:func:`dtf_tpu.telemetry.report.build_report` and gates it with
:func:`~dtf_tpu.telemetry.report.check_gates`, the SAME implementation
behind ``report --check``'s threshold flags.  A cell that trained but
left no legible books is a failing cell: the matrix's contract is that
recovery is *observable*, not just that the process exited 0.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
import time
from typing import List, Optional

import dtf_tpu
from dtf_tpu.scenarios.spec import ScenarioSpec

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(dtf_tpu.__file__)))


@dataclasses.dataclass
class CellResult:
    spec: ScenarioSpec
    ok: bool
    gates: List[str]                   # one verdict line per armed gate
    measured: dict                     # the quantities the gates read
    duration_s: float
    rounds: int = 0                    # elastic relaunch rounds used
    logdir: str = ""
    error: Optional[str] = None        # run-level failure (no gates ran)

    def to_doc(self) -> dict:
        import json
        return {"name": self.spec.name, "ok": self.ok,
                "gates": self.gates, "measured": self.measured,
                "duration_s": round(self.duration_s, 3),
                "rounds": self.rounds, "logdir": self.logdir,
                "error": self.error,
                "spec": json.loads(self.spec.to_json())}


def child_env(extra_pythonpath: str = REPO_ROOT) -> dict:
    """Cell-child environment: CPU backend, repo importable, and any
    sitecustomize shim dirs dropped (a sitecustomize that imports jax
    initializes the backend before ClusterConfig.simulated_devices can
    set the device count)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    inherited = [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and not os.path.exists(os.path.join(p, "sitecustomize.py"))]
    env["PYTHONPATH"] = os.pathsep.join([extra_pythonpath, *inherited])
    return env


def _host_cmd(spec: ScenarioSpec, task: int, nproc: int, shared: str,
              devices: int, chaos: str) -> List[str]:
    return [sys.executable, "-m", "dtf_tpu.scenarios._host",
            spec.to_json(), str(task), str(nproc), shared, str(devices),
            chaos]


def _tail(text: str, n: int = 2000) -> str:
    return text[-n:] if text else ""


def run_cell(spec: ScenarioSpec, workdir: str) -> CellResult:
    """Run one cell to completion (or failure) and gate it."""
    shared = os.path.join(workdir, spec.name)
    os.makedirs(shared, exist_ok=True)
    logdir = os.path.join(shared, "logs")
    env = child_env()
    t0 = time.monotonic()
    rounds = 0
    try:
        if spec.hosts == 1:
            proc = subprocess.run(
                _host_cmd(spec, 0, 1, shared, spec.devices,
                          spec.chaos or ""),
                cwd=workdir, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
                timeout=spec.timeout_s)
            with open(os.path.join(shared, "host.log"), "w") as f:
                f.write(proc.stdout or "")
            if proc.returncode != 0:
                return CellResult(
                    spec, False, [], {}, time.monotonic() - t0,
                    logdir=logdir,
                    error=f"host exited {proc.returncode}:\n"
                          f"{_tail(proc.stdout)}")
        else:
            from dtf_tpu.resilience.supervisor import (SupervisorGaveUp,
                                                       run_elastic_hosts)

            def build_cmd(slot, n_hosts, round_idx):
                # The fault schedule arms on round 0 only: a relaunch
                # must prove RECOVERY, not re-die on the same fault.
                chaos = spec.chaos if round_idx == 0 else ""
                devices = (spec.devices if round_idx == 0
                           else (spec.shrink_devices or spec.devices))
                return _host_cmd(spec, slot, n_hosts, shared, devices,
                                 chaos)

            try:
                outs, _, rounds = run_elastic_hosts(
                    build_cmd, spec.hosts, max_rounds=spec.max_rounds,
                    env=env, cwd=workdir, timeout_s=spec.timeout_s)
            except SupervisorGaveUp as exc:
                return CellResult(
                    spec, False, [], {}, time.monotonic() - t0,
                    logdir=logdir, error=f"elastic gave up: {exc}")
            with open(os.path.join(shared, "host.log"), "w") as f:
                f.write(outs[0] or "")
    except subprocess.TimeoutExpired:
        return CellResult(spec, False, [], {}, time.monotonic() - t0,
                          logdir=logdir,
                          error=f"cell timed out after {spec.timeout_s}s")
    duration = time.monotonic() - t0

    # -- the triple gate, off the on-disk telemetry -------------------------
    from dtf_tpu.telemetry.report import (build_report, check_gates,
                                          check_goodput)

    report = build_report(logdir)
    measured = _measured(report)
    gates: List[str] = []
    # books-consistency first: gating quantities read from books that
    # don't sum to wall-clock would be unfalsifiable
    books_ok, verdict = check_goodput(report)
    gates.append(f"gate goodput_books: {'OK' if books_ok else 'FAIL'} — "
                 f"{verdict}")
    gated_ok, lines = check_gates(report, **spec.gate.thresholds())
    gates.extend(lines)
    return CellResult(spec, books_ok and gated_ok, gates, measured,
                      duration, rounds=rounds, logdir=logdir)


def _measured(report: dict) -> dict:
    """The quantities the gates read, surfaced for the summary table and
    the per-cell JSON whether or not their gate is armed."""
    tel = report.get("telemetry", {})
    metrics = tel.get("metrics", {})

    def metric(name):
        m = metrics.get(name)
        return None if m is None else m.get("value")

    serving = tel.get("serving", {})
    return {
        "final_cost": report.get("steps", {}).get("final_cost"),
        "steps": report.get("steps", {}).get("last"),
        "goodput_fraction": tel.get("goodput", {})
        .get("productive_fraction"),
        "examples_per_s": metric("throughput/examples_per_s"),
        "tokens_per_s": metric("throughput/tokens_per_s"),
        "mfu_pct": metric("mfu/pct_peak"),
        "rollbacks": metric("checkpoint/rollbacks_total") or 0,
        "restarts": metric("supervisor/restarts_total") or 0,
        "faults_fired": metric("chaos/faults_fired_total") or 0,
        "attempts": report.get("attempts"),
        # gradient wire (ISSUE 19; absent when comm never instrumented):
        # what max_wire_bytes_per_step gates, plus the ring hop count
        "wire_bytes_per_step": metric("comm/wire_bytes"),
        "grad_hops": metric("comm/hops"),
        # serving cells (absent for training cells)
        "goodput_qps": serving.get("goodput_qps"),
        "ttft_ms_p99": serving.get("ttft_ms_p99"),
        "shed": serving.get("shed"),
        "deadline_violations": serving.get("deadline_violations"),
        "trace_complete_frac": report.get("request_traces", {})
        .get("complete_frac"),
        # knob-controller cells (absent when no controller armed; note
        # control/rollback_total deliberately has NO default — the gate
        # distinguishes "never armed" from "armed, zero rollbacks")
        "control_decisions": metric("control/decisions_total"),
        "control_sets": metric("control/sets_total"),
        "control_rollbacks": metric("control/rollback_total"),
        # fleet plane (absent for single-host cells)
        "fleet_skew_ms_p50": report.get("fleet", {})
        .get("attribution", {}).get("skew_ms_p50"),
        "fleet_barriers": report.get("fleet", {})
        .get("attribution", {}).get("barriers"),
        "fleet_goodput": report.get("fleet", {})
        .get("rollup", {}).get("goodput", {}).get("productive_fraction"),
        # incident plane (telemetry/anomaly.py + diagnose.py): how many
        # anomalies fired, what fraction attributed, and which plane the
        # top suspects blame (frac None = chaos fired, nothing detected)
        "anomalies": report.get("incidents", {}).get("anomalies"),
        "attribution_frac": report.get("incidents", {})
        .get("attribution_frac"),
        "incident_top_planes": report.get("incidents", {})
        .get("top_plane_counts"),
    }
