"""Workload-zoo cell builders: spec -> (model, optimizer, data) kits.

One builder per :data:`dtf_tpu.scenarios.spec.WORKLOADS` entry, all with
the same contract so the host driver (:mod:`dtf_tpu.scenarios._host`) is
workload-agnostic:

* ``model`` — anything the Trainer drives (loss / init / optional
  model_state), at TEST scale: the matrix's job is failure x recovery x
  efficiency coverage on the CPU sim, not model quality, so every cell
  uses the tiny config of its family (the real-scale knobs are the same
  dataclasses — a pod matrix swaps the preset, not the harness);
* ``make_optimizer()`` — a FRESH optimizer per call (supervisor attempts
  rebuild the trainer; optimizer state lives in the train state, but the
  wrapper objects carry introspection hooks that must not be shared);
* ``splits_factory()`` — a FRESH, rewound data stream per call (resume
  fast-forwards the cursor; a reused mid-stream dataset cannot rewind).

Data is synthetic and deterministic per seed (zero-egress, and the
convergence gate depends on a replayable trajectory).  nan_grad chaos
needs a float batch leaf, so token-only workloads (gpt, seq2seq) must use
the other fault kinds — the spec validation cannot see this, the fault
fails loudly at injection time instead.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from dtf_tpu.scenarios.spec import ScenarioSpec, TRAIN_WORKLOADS


@dataclasses.dataclass
class CellKit:
    model: Any
    make_optimizer: Callable[[], Any]
    splits_factory: Callable[[], Any]


def _classification_splits(n: int, shape: tuple, classes: int, seed: int,
                           noise: float = 2.0):
    """Learnable prototype data (the chaos-suite recipe): class
    prototypes + gaussian noise, identical on every host."""
    import numpy as np

    from dtf_tpu.data.datasets import Dataset, DataSplits

    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, n)
    protos = rng.normal(0, 1, (classes,) + shape).astype(np.float32)
    x = (protos[y] + rng.normal(0, noise, (n,) + shape)).astype(np.float32)
    return DataSplits(train=Dataset(x, np.eye(classes,
                                             dtype=np.float32)[y],
                                    seed=seed),
                      test=None)


def _make_opt(spec: ScenarioSpec):
    from dtf_tpu import optim
    return optim.get(spec.optimizer)(spec.learning_rate)


def _mnist(spec: ScenarioSpec) -> CellKit:
    from dtf_tpu.models.mlp import MnistMLP

    n = spec.batch_size * 8
    return CellKit(
        model=MnistMLP(init_scale="fan_in"),
        make_optimizer=lambda: _make_opt(spec),
        splits_factory=lambda: _classification_splits(
            n, (784,), 10, spec.seed))


def _cifar(spec: ScenarioSpec) -> CellKit:
    from dtf_tpu.models.resnet import ResNet, ResNetConfig

    n = spec.batch_size * 4
    return CellKit(
        model=ResNet(ResNetConfig.tiny()),
        make_optimizer=lambda: _make_opt(spec),
        splits_factory=lambda: _classification_splits(
            n, (32, 32, 3), 10, spec.seed, noise=1.0))


def _gpt(spec: ScenarioSpec) -> CellKit:
    from dtf_tpu.data.datasets import DataSplits, TokenDataset, synthetic_text
    from dtf_tpu.models.gpt import GPT, GPTConfig

    seq_len = int(spec.extra_dict.get("seq_len", 32))
    cfg = GPTConfig.tiny(max_len=seq_len)
    toks = synthetic_text(spec.batch_size * 8, seq_len, cfg.vocab_size,
                          seed=spec.seed)
    return CellKit(
        model=GPT(cfg),
        make_optimizer=lambda: _make_opt(spec),
        splits_factory=lambda: DataSplits(
            train=TokenDataset(toks, seed=spec.seed), test=None))


def _seq2seq(spec: ScenarioSpec) -> CellKit:
    import numpy as np

    from dtf_tpu.data.datasets import CallableDataset, DataSplits
    from dtf_tpu.models.t5 import T5, T5Config

    seq_len = int(spec.extra_dict.get("seq_len", 12))
    pad_to = max(seq_len, 16)
    cfg = T5Config.tiny(max_src_len=pad_to, max_tgt_len=pad_to)

    def batch_at(i):
        # the lm workload's reverse task, per-index rng: deterministic
        # and position-addressable, so resume replays the exact stream
        r = np.random.default_rng(spec.seed * 100003 + i)
        src = r.integers(2, cfg.vocab_size,
                         (spec.batch_size, seq_len)).astype(np.int32)
        tgt = src[:, ::-1].copy()
        pad = pad_to - seq_len
        if pad:
            src = np.pad(src, ((0, 0), (0, pad)),
                         constant_values=cfg.pad_id)
            tgt = np.pad(tgt, ((0, 0), (0, pad)),
                         constant_values=cfg.pad_id)
        return {"src": src, "tgt": tgt}

    return CellKit(
        model=T5(cfg),
        make_optimizer=lambda: _make_opt(spec),
        splits_factory=lambda: DataSplits(
            train=CallableDataset(batch_at, spec.batch_size,
                                  spec.steps + 8),
            test=None))


BUILDERS = {"mnist": _mnist, "cifar": _cifar, "gpt": _gpt,
            "seq2seq": _seq2seq}
# serve cells never come through here (scenarios/_host.py's serve branch
# drives the engine directly); the zoo covers the TRAINING workloads.
assert tuple(sorted(BUILDERS)) == tuple(sorted(TRAIN_WORKLOADS))


def build(spec: ScenarioSpec) -> CellKit:
    return BUILDERS[spec.workload](spec)
