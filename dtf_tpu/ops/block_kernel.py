"""Fused transformer-block Pallas kernels for the TRAIN step.

Not present in the reference (its model is a 3-layer MLP,
tf_distributed.py:50-76); this is the round-5 MFU push the round-3
breakdown pointed at: after the flash kernel, the unrolled layer loop and
the attn-only remat policy, the remaining step time is dominated by the
HBM round-trips BETWEEN the ops of a block — qkv projections written and
re-read around attention (B,T,3D ~ 150 MB/layer at BERT-base mb64), the
(B,T,F) MLP hidden written between fc1 and fc2 (~190 MB/layer), and the
LayerNorm/residual elementwise passes over (B,T,D).  XLA cannot fuse
across two matmuls; these kernels can, keeping a whole (sequence-row,
layer) slice of activations in VMEM.

Two kernels per block (attention megakernel + MLP megakernel), each a
``jax.custom_vjp``:

* ``fused_attn_block`` — LN -> qkv projection -> per-head softmax
  attention -> output projection -> residual (+LN for the post-LN
  variant) as ONE ``pallas_call`` on grid (B,): per grid step one batch
  row's full (T, ·) activations live in VMEM; the packed qkv/o weights
  are grid-invariant (index map constant), so Mosaic streams them into
  VMEM once and reuses them across all B steps.  The kernel emits the
  per-head attention output and lane-slim (B,H,T,8) lse exactly like
  ``ops.flash_attention`` (same ``checkpoint_name``s, so the "attn"
  remat policy saves them), and the backward pass REUSES the fused
  dq+dk+dv flash backward kernel — everything else in the backward is
  recomputed with plain XLA matmuls (165 TF/s territory, r3 breakdown)
  from the minimal residuals (x, attn_out, lse).
* ``fused_mlp_block`` — LN -> fc1 -> gelu -> fc2 -> residual (+LN) on a
  1D grid over flattened (B·T) row blocks, fc1/fc2 grid-invariant; the
  (rows, F) hidden never touches HBM.  Backward recomputes through an
  XLA reference (the hidden is cheap to rebuild: two matmuls at the
  shapes XLA already runs near roofline).

Both variants cover post-LN (BERT: ``LN(x + f(x))``) and pre-LN (GPT:
``x + f(LN(x))``) blocks, and the LLaMA family options: RoPE rotated
in-kernel from fp32 angle tables, GQA via a packed (D, D+2·KVH·hd) qkv
matmul with k/v strips shared per head group, SwiGLU with the gate as a
SEPARATE matmul operand (a (D, 2F) pack would break tensor-parallel
'mlp'-axis sharding — models/gpt.py GPTBlock).  Scope guards (clear errors, not
silent fallbacks): T % 8 == 0, T <= MAX_FUSED_T, KVH | H, even head dim
under RoPE.  On CPU the kernels run in interpreter mode automatically
(tests, the 8-device simulated mesh).

Sharding status (honest): correctness under GSPMD meshes is tested —
DP/FSDP/TP train steps and GPipe pipeline stages reproduce the unfused
losses exactly (tests + the driver dryrun's two-step fused leg).  TP
*efficiency* is not: GSPMD resolves the pallas_call by gathering the
sharded weight operands, so a tensor-sharded fused block pays an
all-gather the unfused megatron path avoids.  The benchmarked fused
configs are single-chip/DP; a shard-local fused block (shard_map with
per-shard head groups) is future work gated on multi-chip hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dtf_tpu.ops.flash_attention import (MASK_VALUE, _CompilerParams,
                                         _bwd as _flash_bwd_call,
                                         _interpret_default, _mask_bias)

# One batch row's full-T activations must fit VMEM next to the packed
# weights: at BERT-base (D=768, F=3072) T=1024 is ~25 MB of scratch +
# ~14 MB bf16 weights under the 100 MB scoped limit.  Longer sequences
# belong to the sequence-parallel paths (ring/ulysses), not this kernel.
MAX_FUSED_T = 1024


def _ln(x32, scale_row, bias_row, eps, kind="layernorm"):
    """LayerNorm or RMSNorm on fp32 (rows, D) with (1, D) scale/bias —
    the SAME expression the backward's XLA recompute differentiates, and
    the same fp32-statistics semantics as nn.layers.LayerNorm/RMSNorm
    (``bias_row`` is ignored under rmsnorm, which has no bias)."""
    if kind == "rmsnorm":
        return x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps) * scale_row
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return (x32 - mean) * jax.lax.rsqrt(var + eps) * scale_row + bias_row


def _ln_bias(ln_params):
    """The norm tree's bias, or a zeros placeholder when the norm has
    none (rmsnorm) — ONE definition for both public entry points."""
    lnb = ln_params.get("bias")
    return jnp.zeros_like(ln_params["scale"]) if lnb is None else lnb


def _q_block(t):
    """Largest q-block that divides t, is a multiple of 8, <= 256.

    A degenerate divisor (e.g. T=1016 = 8·127 -> bq=8) would python-
    unroll the causal loop into T/8 x H inlined bodies — a Mosaic
    code-size blowup — so awkward lengths raise instead."""
    for b in range(min(256, t), 7, -1):
        if t % b == 0 and b % 8 == 0:
            if t > 256 and b < 64:
                break
            return b
    raise ValueError(
        f"T={t} has no 8-aligned q-block divisor >= 64 for the causal "
        f"fused kernel; pad the sequence (e.g. to a multiple of 128) or "
        f"use the unfused block")


# Scoped-VMEM ceiling the kernels request (pltpu.CompilerParams); the
# estimate guards below keep requested working sets under it with an
# actionable error instead of an opaque Mosaic allocation failure.
VMEM_BUDGET = 100 * 1024 * 1024

# ---------------------------------------------------------------------------
# int8 operand path (--matmul_dtype int8 composing with --fused_block)
# ---------------------------------------------------------------------------
# Same quantization discipline as nn/lowp.py: per-OUTPUT-CHANNEL weight
# scales (computed OUTSIDE the pallas_call, inside the custom_vjp
# forward, so the saved residuals stay f32 and the existing
# XLA-recompute backwards become straight-through estimators for free),
# per-row (token) activation scales computed in-kernel, int8 x int8 ->
# i32 on the MXU with both scales folded into the f32 result.  Only the
# PROJECTIONS quantize (qkv / out / fc1 / gate / fc2) — the attention
# core, norms and residuals keep full precision, exactly like the
# unfused lowp path, so fused-int8 vs unfused-int8 parity is a
# reduction-order statement, not a formats one.

_Q_TINY = 1e-30


def _quant_cols(w):
    """(k, n) f32 weight -> (int8 (k, n), sublane-replicated (8, n) f32
    scale).  Column-wise symmetric quantization is independent per
    column, so quantizing a packed (D, W) qkv matrix == quantizing each
    projection separately (the parity tests lean on this)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0, keepdims=True)
    scale = amax / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32)
                           / jnp.maximum(scale, _Q_TINY)),
                 -127, 127).astype(jnp.int8)
    return q, jnp.broadcast_to(scale, (8, w.shape[1]))


def _q_rows(a32):
    """In-kernel per-row activation quantization: (rows, k) f32 ->
    (int8, (rows, 1) f32 scale).  Mirrors lowp._int8_pair(axis=1)."""
    amax = jnp.max(jnp.abs(a32), axis=1, keepdims=True)
    scale = amax / 127.0
    q = jnp.clip(jnp.round(a32 / jnp.maximum(scale, _Q_TINY)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _dot_maybe_q(h32, w_ref, scale_ref, cdt):
    """One projection matmul inside a kernel body: int8 path when a
    scale ref is present (quantize rows, i32 accumulate, fold both
    scales), the plain cdt-operand dot otherwise.  Returns f32."""
    if scale_ref is None:
        return jax.lax.dot(h32.astype(cdt), w_ref[:],
                           preferred_element_type=jnp.float32)
    hq, hs = _q_rows(h32)
    y = jax.lax.dot(hq, w_ref[:], preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * hs * scale_ref[:1, :]


def _check_fused_matmul_dtype(matmul_dtype):
    if matmul_dtype not in ("fp32", "int8"):
        raise ValueError(
            f"fused block kernels support matmul_dtype 'fp32' or 'int8' "
            f"(got {matmul_dtype!r}); bf16 compute comes from the model "
            f"dtype itself, and fp8 has no fused operand path — use the "
            f"unfused block for those")
    return matmul_dtype == "int8"


def _check_vmem(estimate_bytes, what):
    if estimate_bytes > VMEM_BUDGET:
        raise ValueError(
            f"{what} needs ~{estimate_bytes / 2**20:.0f} MB of VMEM "
            f"(> {VMEM_BUDGET / 2**20:.0f} MB budget); use the unfused "
            f"block (or sequence parallelism) at these dimensions")


def _check_block_args(t, d, num_heads, num_kv_heads, rope=False,
                      mlp_act="gelu"):
    kvh = num_kv_heads or num_heads
    if num_heads % kvh:
        raise ValueError(f"num_kv_heads {kvh} must divide num_heads "
                         f"{num_heads}")
    if rope and (d // num_heads) % 2:
        raise ValueError(f"RoPE needs an even head dim, got "
                         f"{d // num_heads}")
    if mlp_act not in ("gelu", "swiglu"):
        raise ValueError(f"fused block kernels support gelu/swiglu MLPs, "
                         f"got {mlp_act!r}")
    if t % 8 or t > MAX_FUSED_T:
        raise ValueError(
            f"fused block kernels need T % 8 == 0 and T <= {MAX_FUSED_T} "
            f"(got T={t}); longer sequences use ring/ulysses sequence "
            f"parallelism")
    if d % num_heads:
        raise ValueError(f"dim {d} not divisible by num_heads {num_heads}")


# --------------------------------------------------------------------------
# attention megakernel
# --------------------------------------------------------------------------

def _rope_rotate(x32, cos, sin):
    """Split-half rotation on fp32 (rows, hd) with (rows, hd/2) tables —
    the same expression as nn.rope.apply_rope."""
    hh = x32.shape[-1] // 2
    x1, x2 = x32[:, :hh], x32[:, hh:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                           axis=1)


def _attn_block_kernel(*refs, num_heads, num_kv_heads, causal, prenorm,
                       norm, eps, has_mask, has_rope, has_rel, emit_aux,
                       quant=False):
    """One batch row: LN/qkv/attention/out-proj/residual(/LN) in VMEM.

    refs (has_rope adds cos/sin tables, has_rel the T5-style (H,T,T)
    logit bias, has_mask adds bias_ref, all before the outputs; without
    ``emit_aux`` — the inference/eval primal — the raw/lse outputs are
    absent, so a no-grad forward never writes them to HBM).
    W = D + 2·KVH·hd (GQA packs KVH k/v heads):
      x_ref (1,T,D), wqkv_ref (D,W), bqkv_ref (8,W), wo_ref (D,D),
      bo_ref (8,D), lns_ref (8,D), lnb_ref (8,D) [, swqkv_ref (8,W),
      swo_ref (8,D) — the int8 weights' per-column scales when quant]
      [, cos_ref (T,hd/2), sin_ref (T,hd/2)] [, rel_ref (H,T,T)]
      [, bias_ref (1,8,T)], y_ref (1,T,D) [, raw_ref (1,T,D),
      lse_ref (1,H,T,8)], qkv_scr (T,W) f32, acc_scr (T,D) f32

    ``quant``: wqkv/wo arrive int8; the two projection matmuls run
    int8 x int8 -> i32 with per-row activation scales computed here
    (the attention core below stays full precision either way).
    """
    (x_ref, wqkv_ref, bqkv_ref, wo_ref, bo_ref, lns_ref, lnb_ref,
     *rest) = refs
    rest = list(rest)
    swqkv_ref = swo_ref = None
    if quant:
        swqkv_ref, swo_ref = rest.pop(0), rest.pop(0)
    cos_ref = sin_ref = None
    if has_rope:
        cos_ref, sin_ref = rest.pop(0), rest.pop(0)
    rel_ref = rest.pop(0) if has_rel else None
    bias_ref = rest.pop(0) if has_mask else None
    if emit_aux:
        y_ref, raw_ref, lse_ref, qkv_scr, acc_scr = rest
    else:
        y_ref, qkv_scr, acc_scr = rest
        raw_ref = lse_ref = None

    t, d = x_ref.shape[1], x_ref.shape[2]
    hd = d // num_heads
    kvh = num_kv_heads or num_heads
    group = num_heads // kvh
    kvw = kvh * hd
    scale = hd ** -0.5
    cdt = x_ref.dtype                       # matmul input dtype (MXU rate)

    x32 = x_ref[0].astype(jnp.float32)                        # (T, D)
    h = (_ln(x32, lns_ref[:1, :].astype(jnp.float32),
             lnb_ref[:1, :].astype(jnp.float32), eps, norm)
         if prenorm else x32)
    qkv_scr[:] = _dot_maybe_q(h, wqkv_ref, swqkv_ref, cdt) + bqkv_ref[
        :1, :].astype(jnp.float32)

    # Causal q-block loop (static python unroll): each q block only
    # multiplies against keys [0, q_end) — at T=1024/bq=256 that skips
    # ~44% of the attention matmul FLOPs the full (T, T) strip would
    # burn above the diagonal (the flash kernel's block-skipping,
    # without its online softmax: the visible key strip is whole).
    # Non-causal attention has nothing to skip, so it stays one strip
    # (blocking it would only multiply unrolled kernel code).  GQA: the
    # outer loop walks KV heads so each shared k/v strip (and its RoPE
    # rotation) is built once per group, not once per q head.
    bq = _q_block(t) if causal else t
    for g in range(kvh):
        k32 = qkv_scr[:, d + g * hd:d + (g + 1) * hd]
        if has_rope:
            k32 = _rope_rotate(k32, cos_ref[:], sin_ref[:])
        k_full = k32.astype(cdt)
        v_full = qkv_scr[:, d + kvw + g * hd:d + kvw + (g + 1) * hd
                         ].astype(cdt)
        for hi in range(g * group, (g + 1) * group):
            for qb in range(t // bq):
                q0 = qb * bq
                k_end = q0 + bq if causal else t
                q32 = qkv_scr[q0:q0 + bq, hi * hd:(hi + 1) * hd]
                if has_rope:
                    q32 = _rope_rotate(q32, cos_ref[q0:q0 + bq],
                                       sin_ref[q0:q0 + bq])
                s = jax.lax.dot_general(                   # (bq, k_end)
                    q32.astype(cdt), k_full[:k_end],
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                if causal:
                    row = q0 + jax.lax.broadcasted_iota(
                        jnp.int32, s.shape, 0)
                    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
                    s = jnp.where(row >= col, s, MASK_VALUE)
                if rel_ref is not None:                    # (bq, k_end)
                    s = s + rel_ref[hi, q0:q0 + bq, :k_end]
                if bias_ref is not None:
                    s = s + bias_ref[0][:1, :k_end]        # (1, k_end)
                m = jnp.max(s, axis=-1, keepdims=True)     # (bq, 1)
                p = jnp.exp(s - m)
                l = jnp.sum(p, axis=-1, keepdims=True)
                acc_scr[q0:q0 + bq, hi * hd:(hi + 1) * hd] = jax.lax.dot(
                    p.astype(cdt), v_full[:k_end],
                    preferred_element_type=jnp.float32) / l
                if lse_ref is not None:
                    lse_ref[0, hi, q0:q0 + bq] = jnp.broadcast_to(
                        m + jnp.log(l), (bq, 8))

    if raw_ref is not None:
        raw_ref[0] = acc_scr[:].astype(raw_ref.dtype)
    a = _dot_maybe_q(acc_scr[:], wo_ref, swo_ref, cdt) + bo_ref[
        :1, :].astype(jnp.float32)
    u = x32 + a
    y = u if prenorm else _ln(u, lns_ref[:1, :].astype(jnp.float32),
                              lnb_ref[:1, :].astype(jnp.float32), eps,
                              norm)
    y_ref[0] = y.astype(y_ref.dtype)


def _attn_fwd(x, wqkv, bqkv8, wo, bo8, lns8, lnb8, cos, sin, rel, bias,
              num_heads, num_kv_heads, causal, prenorm, norm, eps,
              interpret, emit_aux=True, quant=False):
    b, t, d = x.shape
    w = wqkv.shape[1]                 # D + 2·KVH·hd
    hh = d // num_heads // 2
    has_mask = bias is not None
    has_rope = cos is not None
    has_rel = rel is not None
    in_specs = [
        pl.BlockSpec((1, t, d), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((d, w), lambda bi: (0, 0)),
        pl.BlockSpec((8, w), lambda bi: (0, 0)),
        pl.BlockSpec((d, d), lambda bi: (0, 0)),
        pl.BlockSpec((8, d), lambda bi: (0, 0)),
        pl.BlockSpec((8, d), lambda bi: (0, 0)),
        pl.BlockSpec((8, d), lambda bi: (0, 0)),
    ]
    if quant:
        # Quantize here — outside the pallas_call but inside the
        # custom_vjp forward — so the backward's residuals keep the f32
        # weights (straight-through estimator, nn/lowp.py semantics).
        wqkv, swqkv = _quant_cols(wqkv)
        wo, swo = _quant_cols(wo)
        in_specs += [pl.BlockSpec((8, w), lambda bi: (0, 0)),
                     pl.BlockSpec((8, d), lambda bi: (0, 0))]
    args = [x, wqkv, bqkv8, wo, bo8, lns8, lnb8]
    if quant:
        args += [swqkv, swo]
    if has_rope:
        in_specs += [pl.BlockSpec((t, hh), lambda bi: (0, 0)),
                     pl.BlockSpec((t, hh), lambda bi: (0, 0))]
        args += [cos, sin]
    if has_rel:
        in_specs.append(
            pl.BlockSpec((num_heads, t, t), lambda bi: (0, 0, 0)))
        args.append(rel)
    if has_mask:
        in_specs.append(pl.BlockSpec((1, 8, t), lambda bi: (bi, 0, 0)))
        args.append(bias)
    out_specs = [pl.BlockSpec((1, t, d), lambda bi: (bi, 0, 0))]
    out_shape = [jax.ShapeDtypeStruct((b, t, d), x.dtype)]
    if emit_aux:
        out_specs += [
            pl.BlockSpec((1, t, d), lambda bi: (bi, 0, 0)),
            pl.BlockSpec((1, num_heads, t, 8), lambda bi: (bi, 0, 0, 0)),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((b, t, d), x.dtype),
            jax.ShapeDtypeStruct((b, num_heads, t, 8), jnp.float32),
        ]
    outs = pl.pallas_call(
        functools.partial(_attn_block_kernel, num_heads=num_heads,
                          num_kv_heads=num_kv_heads, causal=causal,
                          prenorm=prenorm, norm=norm, eps=eps,
                          has_mask=has_mask, has_rope=has_rope,
                          has_rel=has_rel, emit_aux=emit_aux,
                          quant=quant),
        grid=(b,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((t, w), jnp.float32),       # packed qkv
            pltpu.VMEM((t, d), jnp.float32),       # per-head out concat
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=VMEM_BUDGET),
        interpret=interpret,
    )(*args)
    return outs if emit_aux else (outs[0], None, None)


def _split_heads(packed, num_heads):
    """(B, T, H·hd) -> (B, H, T, hd) for the flash backward kernel."""
    b, t, dh = packed.shape
    hd = dh // num_heads
    return packed.reshape(b, t, num_heads, hd).transpose(0, 2, 1, 3)


def _prepare_qkv(h32, wqkv, bqkv_row, cos, sin, num_heads, num_kv_heads,
                 cdt):
    """The projection/rotation/expansion prologue as one differentiable
    jnp function: (B,T,D) fp32 -> q, k, v (B,H,T,hd) in ``cdt``, RoPE
    applied, GQA heads repeated up to H.  The backward takes jax.vjp of
    THIS, so dq/dk/dv from the flash kernel flow back through rotation
    and head expansion (grouped-head grads summed) by plain AD — no
    hand-maintained transpose math."""
    b, t, d = h32.shape
    kvh = num_kv_heads or num_heads
    hd = d // num_heads
    kvw = kvh * hd
    qkv = jax.lax.dot(h32.astype(cdt).reshape(b * t, d), wqkv,
                      preferred_element_type=jnp.float32)
    qkv = (qkv + bqkv_row.astype(jnp.float32)).reshape(b, t, d + 2 * kvw)
    q = qkv[..., :d].reshape(b, t, num_heads, hd)
    k = qkv[..., d:d + kvw].reshape(b, t, kvh, hd)
    v = qkv[..., d + kvw:].reshape(b, t, kvh, hd)
    if cos is not None:
        # Rotate with the SAME tables the forward kernel consumed (one
        # source of truth — a caller-supplied theta cannot diverge
        # between forward and backward).
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
        hh = hd // 2

        def rot(a):
            a1, a2 = a[..., :hh], a[..., hh:]
            return jnp.concatenate([a1 * c - a2 * s, a1 * s + a2 * c],
                                   axis=-1)

        q, k = rot(q), rot(k)
    reps = num_heads // kvh
    if reps > 1:
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    to_ph = lambda a: a.astype(cdt).transpose(0, 2, 1, 3)
    return to_ph(q), to_ph(k), to_ph(v)


def _attn_ref(x, wqkv, bqkv8, wo, bo8, lns8, lnb8, rel, cos, sin, bias,
              num_heads, num_kv_heads, causal, prenorm, norm, eps):
    """XLA reference of the whole attention half-block with the kernel's
    dtype discipline — the rel-bias backward differentiates THIS (the
    flash dq/dk/dv kernel has no per-head/per-query bias input, and the
    learned relpos table needs a real cotangent)."""
    b, t, d = x.shape
    cdt = x.dtype
    f32 = jnp.float32
    hd = d // num_heads
    x32 = x.astype(f32)
    lns, lnb = lns8[:1, :].astype(f32), lnb8[:1, :].astype(f32)
    h = _ln(x32, lns, lnb, eps, norm) if prenorm else x32
    q, k, v = _prepare_qkv(h, wqkv, bqkv8[:1, :], cos, sin, num_heads,
                           num_kv_heads, cdt)           # (B,H,T,hd) cdt
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=f32) * (hd ** -0.5)
    if causal:
        tri = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(tri[None, None], s, MASK_VALUE)
    if rel is not None:
        s = s + rel.astype(f32)[None]                   # (1,H,T,T)
    if bias is not None:
        s = s + bias[:, :1, :][:, None, :, :]           # (B,1,1,T)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(cdt), v,
                     preferred_element_type=f32)
    raw = out.transpose(0, 2, 1, 3).reshape(b, t, d)
    a = jax.lax.dot(raw.astype(cdt).reshape(b * t, d), wo,
                    preferred_element_type=f32).reshape(b, t, d)
    u = x32 + a + bo8[:1, :].astype(f32)
    y = u if prenorm else _ln(u, lns, lnb, eps, norm)
    return y.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(11, 12, 13, 14, 15,
                                                    16, 17, 18))
def _fused_attn(x, wqkv, bqkv8, wo, bo8, lns8, lnb8, cos, sin, rel, bias,
                num_heads, num_kv_heads, causal, prenorm, norm, eps,
                interpret, quant):
    # No-grad forward (eval/inference): the y-only kernel variant — the
    # raw/lse residuals are never written to HBM.
    y, _, _ = _attn_fwd(x, wqkv, bqkv8, wo, bo8, lns8, lnb8, cos, sin,
                        rel, bias, num_heads, num_kv_heads, causal,
                        prenorm, norm, eps, interpret, emit_aux=False,
                        quant=quant)
    return y


def _fused_attn_fwd_rule(x, wqkv, bqkv8, wo, bo8, lns8, lnb8, cos, sin,
                         rel, bias, num_heads, num_kv_heads, causal,
                         prenorm, norm, eps, interpret, quant):
    # With a rel bias the backward is the XLA-reference vjp (see
    # _fused_attn_bwd_rule), which rebuilds everything from the inputs —
    # skip emitting (and saving) raw/lse entirely.
    emit_aux = rel is None
    y, raw, lse = _attn_fwd(x, wqkv, bqkv8, wo, bo8, lns8, lnb8, cos,
                            sin, rel, bias, num_heads, num_kv_heads,
                            causal, prenorm, norm, eps, interpret,
                            emit_aux=emit_aux, quant=quant)
    if emit_aux:
        from jax.ad_checkpoint import checkpoint_name
        # Same names as ops.flash_attention: the "attn" remat policy
        # saves exactly these, so the backward never re-runs the
        # forward kernel.
        raw = checkpoint_name(raw, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
    return y, (x, wqkv, bqkv8, wo, bo8, lns8, lnb8, cos, sin, rel, bias,
               raw, lse)


def _fused_attn_bwd_rule(num_heads, num_kv_heads, causal, prenorm, norm,
                         eps, interpret, quant, res, dy):
    """XLA recompute (qkv projection, RoPE, LN statistics) + the fused
    flash dq/dk/dv kernel.  Matmul grads are plain XLA dots — the r3
    breakdown measured those at ~84% of roofline, so only attention's
    O(T^2) work runs in Pallas here.  With a T5-style rel bias the whole
    backward is instead the vjp of the XLA reference (the flash backward
    has no per-head bias input, and the learned relpos table needs its
    cotangent).  Under ``quant`` the residuals are the f32 weights, so
    this recompute IS the straight-through estimator — gradients as if
    the forward had run full precision (nn/lowp.py's int8 semantics)."""
    (x, wqkv, bqkv8, wo, bo8, lns8, lnb8, cos, sin, rel, bias, raw,
     lse) = res
    if rel is not None:
        diff = (x, wqkv, bqkv8, wo, bo8, lns8, lnb8, rel)
        _, vjp = jax.vjp(
            lambda x_, wq_, bq_, wo_, bo_, ls_, lb_, rel_: _attn_ref(
                x_, wq_, bq_, wo_, bo_, ls_, lb_, rel_, cos, sin, bias,
                num_heads, num_kv_heads, causal, prenorm, norm, eps),
            *diff)
        dx, d_wqkv, d_bqkv8, d_wo, d_bo8, d_lns8, d_lnb8, d_rel = vjp(dy)
        zlike = lambda a: None if a is None else jnp.zeros_like(a)
        return (dx, d_wqkv, d_bqkv8, d_wo, d_bo8, d_lns8, d_lnb8,
                zlike(cos), zlike(sin), d_rel, zlike(bias))
    b, t, d = x.shape
    hd = d // num_heads
    scale = hd ** -0.5
    cdt = x.dtype
    f32 = jnp.float32

    x32 = x.astype(f32)
    lns = lns8[:1, :].astype(f32)
    lnb = lnb8[:1, :].astype(f32)
    dy32 = dy.astype(f32)

    # --- recompute the projection input h (and its LN vjp for pre-LN) ---
    if prenorm:
        h, ln1_vjp = jax.vjp(
            lambda v_, s_, b_: _ln(v_, s_, b_, eps, norm), x32, lns, lnb)
    else:
        h, ln1_vjp = x32, None

    # --- recompute q/k/v exactly as the kernel produced them ---
    (q, k, v), prep_vjp = jax.vjp(
        lambda h_, w_, b_: _prepare_qkv(h_, w_, b_, cos, sin, num_heads,
                                        num_kv_heads, cdt),
        h, wqkv, bqkv8[:1, :])

    # --- residual/LN tail ---
    raw32 = raw.astype(f32)
    if prenorm:
        # y = x + raw @ wo + bo
        du = dy32
        d_lns_tail = d_lnb_tail = None  # pre-LN: ln grads come from ln1
    else:
        # y = LN(u), u = x + raw @ wo + bo: redo the (cheap) out
        # projection to rebuild u for the LN statistics; all LN grads
        # via vjp of _ln (covers both norm kinds).
        a = jax.lax.dot(raw.astype(cdt).reshape(b * t, d), wo,
                        preferred_element_type=f32).reshape(b, t, d)
        u = x32 + a + bo8[:1, :].astype(f32)
        _, ln2_vjp = jax.vjp(
            lambda u_, s_, b_: _ln(u_, s_, b_, eps, norm), u, lns, lnb)
        du, d_lns_row, d_lnb_row = ln2_vjp(dy32)
        d_lns_tail, d_lnb_tail = d_lns_row[0], d_lnb_row[0]

    # --- output projection grads ---
    d_wo = jax.lax.dot_general(
        raw32.reshape(b * t, d), du.reshape(b * t, d),
        (((0,), (0,)), ((), ())), preferred_element_type=f32)
    d_bo = jnp.sum(du, axis=(0, 1))
    d_raw = jax.lax.dot_general(du.reshape(b * t, d), wo.astype(f32),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=f32).reshape(b, t, d)

    # --- attention core: the fused flash dq+dk+dv kernel ---
    o_ph = _split_heads(raw, num_heads)
    do_ph = _split_heads(d_raw.astype(cdt), num_heads)
    dq, dk, dv = _flash_bwd_call(q, k, v, o_ph, lse, bias, do_ph, causal,
                                 scale, 512, 512, interpret)

    # --- projection/rotation/expansion grads + input cotangent (AD of
    # the prepare prologue: grouped-head dk/dv sum, RoPE transpose) ---
    dh, d_wqkv, d_bqkv_row = prep_vjp((dq, dk, dv))
    d_bqkv = d_bqkv_row[0]

    if prenorm:
        dx_ln, d_lns_row, d_lnb_row = ln1_vjp(dh)
        dx = dy32 + dx_ln
        d_lns, d_lnb = d_lns_row[0], d_lnb_row[0]
    else:
        dx = du + dh
        d_lns, d_lnb = d_lns_tail, d_lnb_tail

    def rep8(g_row, like):
        """Cotangent for an (8, N) sublane-replicated pack: the true grad
        in row 0, zeros elsewhere (the outer broadcast_to's vjp sums)."""
        out = jnp.zeros(like.shape, f32).at[0].set(g_row)
        return out.astype(like.dtype)

    # cos/sin are position tables and bias a 0/-1e30 mask — not
    # learnable inputs: zero cotangents (None where the primal was None).
    zlike = lambda a: None if a is None else jnp.zeros_like(a)
    return (dx.astype(x.dtype), d_wqkv.astype(wqkv.dtype),
            rep8(d_bqkv, bqkv8), d_wo.astype(wo.dtype), rep8(d_bo, bo8),
            rep8(d_lns, lns8), rep8(d_lnb, lnb8), zlike(cos), zlike(sin),
            None, zlike(bias))


_fused_attn.defvjp(_fused_attn_fwd_rule, _fused_attn_bwd_rule)


def fused_attn_block(x, attn_params, ln_params, *, num_heads,
                     num_kv_heads=None, causal=False, prenorm=False,
                     rope=False, kv_mask=None, rel_bias=None,
                     norm="layernorm", eps=1e-6, interpret=None,
                     matmul_dtype="fp32"):
    """Fused attention half-block.

    post-LN (BERT, ``prenorm=False``): ``LN(x + Attn(x))``
    pre-LN (GPT/T5, ``prenorm=True``): ``x + Attn(LN(x))``

    ``matmul_dtype="int8"`` runs the qkv and output projections as
    int8 x int8 -> i32 MXU matmuls (per-output-channel weight scales,
    per-token activation scales — nn/lowp.py's exact format) with a
    straight-through backward; the attention core stays full precision.

    ``attn_params`` is the MultiHeadAttention param tree (q/k/v/o with
    (D, H|KVH, hd) weights — GQA packs the smaller k/v projections);
    ``ln_params`` the LayerNorm/RMSNorm tree (``norm`` selects; rmsnorm
    has no bias).  ``rope`` rotates q/k in-kernel with train-step
    positions arange(T) (split-half convention, nn.rope).  ``kv_mask``
    (B, T) bool marks visible keys (BERT padding); composable with
    ``causal``.  ``rel_bias`` is a T5-style (1|·, H, T, T) additive
    logit bias (LEARNED — its cotangent flows back to the relpos
    table); it switches the backward to the XLA-reference vjp since the
    flash dq/dk/dv kernel has no per-head bias input.  Packing to the
    kernel layout (one (D, D+2·KVH·hd) qkv matmul, sublane-replicated
    vectors) happens here in plain jnp, so parameter gradients flow
    through the packing automatically.
    """
    b, t, d = x.shape
    _check_block_args(t, d, num_heads, num_kv_heads, rope=rope)
    quant = _check_fused_matmul_dtype(matmul_dtype)
    kvh = num_kv_heads or num_heads
    w_pack = d + 2 * kvh * (d // num_heads)
    isz = x.dtype.itemsize
    _check_vmem(
        4 * t * (w_pack + d)                       # qkv + acc scratch f32
        + isz * (d * w_pack + d * d)               # packed weights
        + isz * 3 * t * d                          # x/y/raw blocks
        + (4 * num_heads * t * t if rel_bias is not None else 0),
        "fused_attn_block")
    if interpret is None:
        interpret = _interpret_default()

    wqkv = jnp.concatenate(
        [attn_params[n]["w"].reshape(d, -1) for n in ("q", "k", "v")],
        axis=1)
    bqkv = jnp.concatenate(
        [attn_params[n]["b"].reshape(-1) for n in ("q", "k", "v")])
    wo = attn_params["o"]["w"].reshape(d, d)
    rep8 = lambda v_: jnp.broadcast_to(v_[None, :], (8, v_.shape[0]))
    bias = None if kv_mask is None else _mask_bias(kv_mask, t)
    cos = sin = None
    if rope:
        from dtf_tpu.nn.rope import rope_angles
        cos, sin = rope_angles(jnp.arange(t), d // num_heads)  # (T, hd/2)
    rel = None
    if rel_bias is not None:
        rel = rel_bias.reshape(num_heads, t, t).astype(jnp.float32)
    lnb = _ln_bias(ln_params)
    return _fused_attn(x, wqkv, rep8(bqkv), wo,
                       rep8(attn_params["o"]["b"]),
                       rep8(ln_params["scale"]), rep8(lnb),
                       cos, sin, rel, bias, num_heads, num_kv_heads,
                       causal, prenorm, norm, eps, interpret, quant)


# --------------------------------------------------------------------------
# MLP megakernel
# --------------------------------------------------------------------------

def _mlp_block_kernel(*refs, has_gate, prenorm, norm, eps, quant=False):
    """One (rows, D) block: LN/fc1/act/fc2/residual(/LN); the (rows, F)
    hidden exists only in VMEM.  With ``has_gate`` (SwiGLU) the gate is
    a SEPARATE matmul operand — NOT packed into fc1 — mirroring the
    model's split-projection design so tensor-parallel sharding of the
    'mlp' axis keeps silu(gate)*up local per shard (models/gpt.py
    GPTBlock comment).

    refs: x (bn,D), w1 (D,F), b1 (8,F) [, wg (D,F), bg (8,F)],
    w2 (F,D), b2 (8,D), lns (8,D), lnb (8,D)
    [, s1 (8,F) [, sg (8,F)], s2 (8,D) — int8 weight scales when
    ``quant``], y (bn,D)
    """
    rest = list(refs)
    x_ref, w1_ref, b1_ref = rest.pop(0), rest.pop(0), rest.pop(0)
    wg_ref = bg_ref = None
    if has_gate:
        wg_ref, bg_ref = rest.pop(0), rest.pop(0)
    w2_ref, b2_ref, lns_ref, lnb_ref = (rest.pop(0), rest.pop(0),
                                        rest.pop(0), rest.pop(0))
    s1_ref = sg_ref = s2_ref = None
    if quant:
        s1_ref = rest.pop(0)
        if has_gate:
            sg_ref = rest.pop(0)
        s2_ref = rest.pop(0)
    (y_ref,) = rest
    cdt = x_ref.dtype
    x32 = x_ref[:].astype(jnp.float32)
    lns = lns_ref[:1, :].astype(jnp.float32)
    lnb = lnb_ref[:1, :].astype(jnp.float32)
    h = _ln(x32, lns, lnb, eps, norm) if prenorm else x32
    h1 = _dot_maybe_q(h, w1_ref, s1_ref, cdt) + b1_ref[:1, :].astype(
        jnp.float32)
    if has_gate:
        hg = _dot_maybe_q(h, wg_ref, sg_ref, cdt) + bg_ref[:1, :].astype(
            jnp.float32)
        g = jax.nn.silu(hg) * h1
    else:
        g = jax.nn.gelu(h1)
    h2 = _dot_maybe_q(g, w2_ref, s2_ref, cdt) + b2_ref[:1, :].astype(
        jnp.float32)
    u = x32 + h2
    y_ref[:] = (u if prenorm else _ln(u, lns, lnb, eps,
                                     norm)).astype(y_ref.dtype)


def _mlp_rows(n):
    """Largest row-block that divides n, is a multiple of 8, <= 512."""
    for bn in range(min(512, n), 7, -1):
        if n % bn == 0 and bn % 8 == 0:
            return bn
    raise ValueError(f"B*T = {n} has no 8-aligned row block; pad the batch")


def _mlp_fwd(x2, w1, b18, wg, bg8, w2, b28, lns8, lnb8, prenorm, norm,
             eps, interpret, quant=False):
    n, d = x2.shape
    f = w1.shape[1]
    has_gate = wg is not None
    bn = _mlp_rows(n)
    s1 = sg = s2 = None
    if quant:
        # Outside the pallas_call, inside the custom_vjp forward — the
        # backward's residuals stay f32 (straight-through estimator).
        w1, s1 = _quant_cols(w1)
        w2, s2 = _quant_cols(w2)
        if has_gate:
            wg, sg = _quant_cols(wg)
    in_specs = [
        pl.BlockSpec((bn, d), lambda i: (i, 0)),
        pl.BlockSpec((d, f), lambda i: (0, 0)),
        pl.BlockSpec((8, f), lambda i: (0, 0)),
    ]
    args = [x2, w1, b18]
    if has_gate:
        in_specs += [pl.BlockSpec((d, f), lambda i: (0, 0)),
                     pl.BlockSpec((8, f), lambda i: (0, 0))]
        args += [wg, bg8]
    in_specs += [
        pl.BlockSpec((f, d), lambda i: (0, 0)),
        pl.BlockSpec((8, d), lambda i: (0, 0)),
        pl.BlockSpec((8, d), lambda i: (0, 0)),
        pl.BlockSpec((8, d), lambda i: (0, 0)),
    ]
    args += [w2, b28, lns8, lnb8]
    if quant:
        in_specs.append(pl.BlockSpec((8, f), lambda i: (0, 0)))
        args.append(s1)
        if has_gate:
            in_specs.append(pl.BlockSpec((8, f), lambda i: (0, 0)))
            args.append(sg)
        in_specs.append(pl.BlockSpec((8, d), lambda i: (0, 0)))
        args.append(s2)
    return pl.pallas_call(
        functools.partial(_mlp_block_kernel, has_gate=has_gate,
                          prenorm=prenorm, norm=norm, eps=eps,
                          quant=quant),
        grid=(n // bn,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2.dtype),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=VMEM_BUDGET),
        interpret=interpret,
    )(*args)


def _mlp_ref(x2, w1, b18, wg, bg8, w2, b28, lns8, lnb8, prenorm, norm,
             eps):
    """XLA reference with the kernel's exact dtype discipline — the
    backward differentiates THIS, so grads match the fused forward."""
    cdt = x2.dtype
    f32 = jnp.float32
    x32 = x2.astype(f32)
    lns, lnb = lns8[:1, :].astype(f32), lnb8[:1, :].astype(f32)
    h = _ln(x32, lns, lnb, eps, norm) if prenorm else x32
    h1 = jax.lax.dot(h.astype(cdt), w1,
                     preferred_element_type=f32) + b18[:1, :].astype(f32)
    if wg is not None:
        hg = jax.lax.dot(h.astype(cdt), wg,
                         preferred_element_type=f32) + bg8[:1, :].astype(
                             f32)
        g = jax.nn.silu(hg) * h1
    else:
        g = jax.nn.gelu(h1)
    h2 = jax.lax.dot(g.astype(cdt), w2,
                     preferred_element_type=f32) + b28[:1, :].astype(f32)
    u = x32 + h2
    return (u if prenorm else _ln(u, lns, lnb, eps,
                                  norm)).astype(x2.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13))
def _fused_mlp(x2, w1, b18, wg, bg8, w2, b28, lns8, lnb8, prenorm, norm,
               eps, interpret, quant):
    return _mlp_fwd(x2, w1, b18, wg, bg8, w2, b28, lns8, lnb8, prenorm,
                    norm, eps, interpret, quant=quant)


def _fused_mlp_fwd_rule(x2, w1, b18, wg, bg8, w2, b28, lns8, lnb8,
                        prenorm, norm, eps, interpret, quant):
    y = _mlp_fwd(x2, w1, b18, wg, bg8, w2, b28, lns8, lnb8, prenorm,
                 norm, eps, interpret, quant=quant)
    return y, (x2, w1, b18, wg, bg8, w2, b28, lns8, lnb8)


def _fused_mlp_bwd_rule(prenorm, norm, eps, interpret, quant, res, dy):
    # Rebuilding the (rows, F) hidden costs two matmuls XLA runs near
    # roofline — cheaper than saving ~190 MB/layer of it to HBM.  The
    # residuals are the f32 weights even under ``quant``, so the int8
    # backward is the straight-through estimator by construction.
    _, vjp = jax.vjp(
        lambda *a: _mlp_ref(*a, prenorm=prenorm, norm=norm, eps=eps),
        *res)
    return vjp(dy)


_fused_mlp.defvjp(_fused_mlp_fwd_rule, _fused_mlp_bwd_rule)


def fused_mlp_block(x, fc1_params, fc2_params, ln_params, *,
                    fc_gate_params=None, prenorm=False, norm="layernorm",
                    eps=1e-6, interpret=None, matmul_dtype="fp32"):
    """Fused MLP half-block.

    post-LN (BERT):    ``LN(x + fc2(act(fc1(x))))``
    pre-LN (GPT/T5):   ``x + fc2(act(fc1(LN(x))))``

    ``fc_gate_params`` switches the activation to SwiGLU
    (``silu(gate(h)) * fc1(h)``, models/gpt.py GPTBlock); the gate stays
    a SEPARATE matmul operand so tensor-parallel sharding of the 'mlp'
    axis keeps the elementwise product local per shard (the model's
    split-projection rationale).  ``norm`` selects LayerNorm or RMSNorm
    (T5; no bias).  Operates on flattened (B·T, D) rows — no cross-row
    coupling.  ``matmul_dtype="int8"``: fc1/gate/fc2 run int8 with
    per-channel/per-token scales and a straight-through backward
    (nn/lowp.py's format; the activation nonlinearity stays f32)."""
    b, t, d = x.shape
    quant = _check_fused_matmul_dtype(matmul_dtype)
    f = fc1_params["w"].shape[1]
    isz = x.dtype.itemsize
    n_mats = 3 if fc_gate_params is not None else 2
    bn = _mlp_rows(b * t)
    _check_vmem(isz * n_mats * d * f               # fc1 [+gate] + fc2
                + 4 * bn * (n_mats - 1) * f        # f32 hidden(s)
                + isz * 2 * bn * d,                # x/y blocks
                "fused_mlp_block")
    if interpret is None:
        interpret = _interpret_default()
    rep8 = lambda v_: jnp.broadcast_to(v_[None, :], (8, v_.shape[0]))
    wg = bg8 = None
    if fc_gate_params is not None:
        wg, bg8 = fc_gate_params["w"], rep8(fc_gate_params["b"])
    lnb = _ln_bias(ln_params)
    y = _fused_mlp(x.reshape(b * t, d), fc1_params["w"],
                   rep8(fc1_params["b"]), wg, bg8, fc2_params["w"],
                   rep8(fc2_params["b"]), rep8(ln_params["scale"]),
                   rep8(lnb), prenorm, norm, eps, interpret, quant)
    return y.reshape(b, t, d)


# --------------------------------------------------------------------------
# cross-attention megakernel (T5 decoder)
# --------------------------------------------------------------------------

def _cross_block_kernel(x_ref, ctx_ref, wq_ref, bq_ref, wkv_ref, bkv_ref,
                        wo_ref, bo_ref, lns_ref, lnb_ref, *rest,
                        num_heads, norm, eps, has_mask):
    """One batch row of ``x + O(attn(Q(norm(x)), K(ctx), V(ctx)))`` —
    the T5 decoder's pre-LN cross-attention half-block.  q comes from
    the normalized decoder states, k/v from the RAW encoder output
    (T5DecoderLayer contract).  refs:
      x (1,T,D), ctx (1,S,D), wq (D,D), bq (8,D), wkv (D,2D),
      bkv (8,2D) [, bias (1,8,S)], y (1,T,D),
      q_scr (T,D) f32, kv_scr (S,2D) f32, acc_scr (T,D) f32
    """
    rest = list(rest)
    bias_ref = rest.pop(0) if has_mask else None
    y_ref, q_scr, kv_scr, acc_scr = rest

    t, d = x_ref.shape[1], x_ref.shape[2]
    hd = d // num_heads
    scale = hd ** -0.5
    cdt = x_ref.dtype

    x32 = x_ref[0].astype(jnp.float32)                        # (T, D)
    h = _ln(x32, lns_ref[:1, :].astype(jnp.float32),
            lnb_ref[:1, :].astype(jnp.float32), eps, norm)
    q_scr[:] = jax.lax.dot(
        h.astype(cdt), wq_ref[:],
        preferred_element_type=jnp.float32) + bq_ref[:1, :].astype(
            jnp.float32)
    kv_scr[:] = jax.lax.dot(
        ctx_ref[0], wkv_ref[:],
        preferred_element_type=jnp.float32) + bkv_ref[:1, :].astype(
            jnp.float32)

    for hi in range(num_heads):
        q = q_scr[:, hi * hd:(hi + 1) * hd].astype(cdt)       # (T, hd)
        k = kv_scr[:, hi * hd:(hi + 1) * hd].astype(cdt)      # (S, hd)
        v = kv_scr[:, d + hi * hd:d + (hi + 1) * hd].astype(cdt)
        s = jax.lax.dot_general(                              # (T, S)
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if bias_ref is not None:
            s = s + bias_ref[0][:1, :]                        # (1, S)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:, hi * hd:(hi + 1) * hd] = jax.lax.dot(
            p.astype(cdt), v, preferred_element_type=jnp.float32) / l

    a = jax.lax.dot(
        acc_scr[:].astype(cdt), wo_ref[:],
        preferred_element_type=jnp.float32) + bo_ref[:1, :].astype(
            jnp.float32)
    y_ref[0] = (x32 + a).astype(y_ref.dtype)


def _cross_fwd(x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8, lnb8, bias,
               num_heads, norm, eps, interpret):
    b, t, d = x.shape
    s_len = ctx.shape[1]
    has_mask = bias is not None
    in_specs = [
        pl.BlockSpec((1, t, d), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((1, s_len, d), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((d, d), lambda bi: (0, 0)),
        pl.BlockSpec((8, d), lambda bi: (0, 0)),
        pl.BlockSpec((d, 2 * d), lambda bi: (0, 0)),
        pl.BlockSpec((8, 2 * d), lambda bi: (0, 0)),
        pl.BlockSpec((d, d), lambda bi: (0, 0)),
        pl.BlockSpec((8, d), lambda bi: (0, 0)),
        pl.BlockSpec((8, d), lambda bi: (0, 0)),
        pl.BlockSpec((8, d), lambda bi: (0, 0)),
    ]
    args = [x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8, lnb8]
    if has_mask:
        in_specs.append(
            pl.BlockSpec((1, 8, s_len), lambda bi: (bi, 0, 0)))
        args.append(bias)
    return pl.pallas_call(
        functools.partial(_cross_block_kernel, num_heads=num_heads,
                          norm=norm, eps=eps, has_mask=has_mask),
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, t, d), lambda bi: (bi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, t, d), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((t, d), jnp.float32),         # q
            pltpu.VMEM((s_len, 2 * d), jnp.float32), # packed k|v
            pltpu.VMEM((t, d), jnp.float32),         # per-head out concat
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=VMEM_BUDGET),
        interpret=interpret,
    )(*args)


def _cross_ref(x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8, lnb8, bias,
               num_heads, norm, eps):
    """XLA reference with the kernel's dtype discipline — the backward
    differentiates THIS (flash bwd is self-attention-only: Tq != Tk)."""
    b, t, d = x.shape
    s_len = ctx.shape[1]
    cdt = x.dtype
    f32 = jnp.float32
    hd = d // num_heads
    x32 = x.astype(f32)
    h = _ln(x32, lns8[:1, :].astype(f32), lnb8[:1, :].astype(f32), eps,
            norm)
    q = (jax.lax.dot(h.astype(cdt).reshape(b * t, d), wq,
                     preferred_element_type=f32)
         + bq8[:1, :].astype(f32)).reshape(b, t, num_heads, hd)
    kv = (jax.lax.dot(ctx.reshape(b * s_len, d), wkv,
                      preferred_element_type=f32)
          + bkv8[:1, :].astype(f32)).reshape(b, s_len, 2 * d)
    k = kv[..., :d].reshape(b, s_len, num_heads, hd)
    v = kv[..., d:].reshape(b, s_len, num_heads, hd)
    sc = jnp.einsum("bqhd,bkhd->bhqk", q.astype(cdt), k.astype(cdt),
                    preferred_element_type=f32) * (hd ** -0.5)
    if bias is not None:
        sc = sc + bias[:, :1, :][:, None, :, :]               # (B,1,1,S)
    p = jax.nn.softmax(sc, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(cdt), v.astype(cdt),
                     preferred_element_type=f32)
    raw = out.reshape(b, t, d)
    a = jax.lax.dot(raw.astype(cdt).reshape(b * t, d), wo,
                    preferred_element_type=f32).reshape(b, t, d)
    return (x32 + a + bo8[:1, :].astype(f32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(11, 12, 13, 14))
def _fused_cross(x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8, lnb8, bias,
                 num_heads, norm, eps, interpret):
    return _cross_fwd(x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8, lnb8,
                      bias, num_heads, norm, eps, interpret)


def _fused_cross_fwd_rule(x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8,
                          lnb8, bias, num_heads, norm, eps, interpret):
    y = _cross_fwd(x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8, lnb8, bias,
                   num_heads, norm, eps, interpret)
    return y, (x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8, lnb8, bias)


def _fused_cross_bwd_rule(num_heads, norm, eps, interpret, res, dy):
    x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8, lnb8, bias = res
    diff = (x, ctx, wq, bq8, wkv, bkv8, wo, bo8, lns8, lnb8)
    _, vjp = jax.vjp(
        lambda *a: _cross_ref(*a, bias, num_heads, norm, eps), *diff)
    grads = vjp(dy)
    return (*grads, None if bias is None else jnp.zeros_like(bias))


_fused_cross.defvjp(_fused_cross_fwd_rule, _fused_cross_bwd_rule)


def fused_cross_attn_block(x, ctx, attn_params, ln_params, *, num_heads,
                           ctx_kv_mask=None, norm="layernorm", eps=1e-6,
                           interpret=None):
    """Fused pre-LN cross-attention half-block (T5 decoder):
    ``x + O(attn(Q(norm(x)), K(ctx), V(ctx)))`` with q from the
    normalized decoder states and k/v from the RAW encoder output.
    ``ctx_kv_mask`` (B, S) bool masks padded encoder positions.  The
    backward is the vjp of an XLA reference — the flash dq/dk/dv kernel
    is self-attention-only (Tq must equal Tk)."""
    b, t, d = x.shape
    s_len = ctx.shape[1]
    _check_block_args(t, d, num_heads, None)
    if s_len % 8 or s_len > MAX_FUSED_T:
        raise ValueError(
            f"fused cross-attention needs S % 8 == 0 and S <= "
            f"{MAX_FUSED_T} (got S={s_len})")
    isz = x.dtype.itemsize
    _check_vmem(4 * (t * 2 * d + s_len * 2 * d)    # q/acc + kv scratch f32
                + isz * 4 * d * d                  # wq/wkv/wo
                + isz * (2 * t * d + s_len * d),   # x/y/ctx blocks
                "fused_cross_attn_block")
    if interpret is None:
        interpret = _interpret_default()
    rep8 = lambda v_: jnp.broadcast_to(v_[None, :], (8, v_.shape[0]))
    wq = attn_params["q"]["w"].reshape(d, d)
    bq = attn_params["q"]["b"].reshape(d)
    wkv = jnp.concatenate([attn_params[n]["w"].reshape(d, d)
                           for n in ("k", "v")], axis=1)
    bkv = jnp.concatenate([attn_params[n]["b"].reshape(d)
                           for n in ("k", "v")])
    wo = attn_params["o"]["w"].reshape(d, d)
    bias = (None if ctx_kv_mask is None
            else _mask_bias(ctx_kv_mask, s_len))
    return _fused_cross(x, ctx, wq, rep8(bq), wkv, rep8(bkv), wo,
                        rep8(attn_params["o"]["b"]),
                        rep8(ln_params["scale"]),
                        rep8(_ln_bias(ln_params)), bias, num_heads, norm,
                        eps, interpret)
