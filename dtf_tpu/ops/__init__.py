"""Pallas TPU kernels and distributed ops (flash attention, ring attention)."""

from dtf_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention, flash_attention_impl)
from dtf_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention, ring_attention_impl)
