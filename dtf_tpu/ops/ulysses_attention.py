"""Ulysses attention: all-to-all sequence parallelism over a ``seq`` axis.

The second of the framework's two long-context strategies (the reference has
no sequence models at all, SURVEY.md §5.7 — this is new capability, not
parity).  Complements :mod:`dtf_tpu.ops.ring_attention`:

* **ring**: Q stays put, K/V chunks rotate n times via ``lax.ppermute``;
  per-device memory O(T/n) in the sequence; attention math is a bespoke
  online-softmax recurrence.
* **ulysses** (this module, DeepSpeed-Ulysses style): two ``lax.all_to_all``
  re-shards — heads->sequence on the way in, sequence->heads on the way
  out — so each device briefly holds the FULL sequence for H/n of the
  heads and runs a completely *local, dense* attention there.  That local
  attention is any single-device implementation, including the Pallas
  flash kernel (:mod:`dtf_tpu.ops.flash_attention`), so the MXU-optimized
  kernel and sequence parallelism compose for free.

Trade-offs (why both exist): ulysses does 2 all-to-alls of the activations
total (O(T·d/n) bytes per device, bandwidth-optimal on ICI) vs ring's n
ppermutes of K/V overlapped with compute; ulysses' parallel degree is
bounded by the head count (n must divide H) and its peak memory is O(T)
in the local attention unless the flash inner kernel is used (then O(T/n)
again for activations, O(T) only for K/V); ring has no head-count bound.

Implemented as per-device code under ``jax.shard_map`` (explicit collective
schedule), composing with the data axes for the batch dim, differentiable
(``all_to_all`` transposes to the opposite all-to-all in reverse mode).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.parallel.collectives import shard_map_fn

from dtf_tpu.nn.attention import causal_mask, dot_product_attention


def _ulysses_body(q, k, v, *rest, axis: str, causal: bool,
                  scale: Optional[float], inner: Optional[Callable],
                  has_mask: bool):
    """Per-device ulysses attention.  q,k,v: (B, T/n, H, D) local chunks;
    with ``has_mask`` a (B, T/n) key-validity chunk is all-gathered to the
    full (B, T) mask every local attention needs (tiny next to the K/V
    all-to-alls)."""
    kv_mask = rest[0] if has_mask else None
    # heads -> sequence: (B, T/n, H, D) -> (B, T, H/n, D).  tiled=True splits
    # the head dim into n blocks and concatenates the gathered chunks along
    # the sequence dim, so afterwards the device holds the whole sequence
    # for a contiguous block of heads.
    a2a_in = lambda x: lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)
    qh, kh, vh = a2a_in(q), a2a_in(k), a2a_in(v)
    mask4 = None
    if kv_mask is not None:
        full = lax.all_gather(kv_mask, axis, axis=1, tiled=True)  # (B, T)
        mask4 = full[:, None, None, :]

    if inner is not None:
        out = inner(qh, kh, vh, mask4)
    else:
        mask = causal_mask(qh.shape[1]) if causal else None
        if mask4 is not None:
            mask = mask4 if mask is None else (mask & mask4)
        out = dot_product_attention(qh, kh, vh, mask=mask, scale=scale)

    # sequence -> heads: (B, T, H/n, D) -> (B, T/n, H, D).
    return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, *, axis: str = "seq",
                      causal: bool = False, scale: Optional[float] = None,
                      batch_axes: Optional[tuple] = None,
                      inner: Optional[Callable] = None, kv_mask=None):
    """All-to-all sequence-parallel attention.

    q, k, v: (B, T, H, D) *global* arrays whose T dim is (to be) sharded
    over ``axis``; returns (B, T, H, D) sharded the same way.  ``inner``
    optionally supplies the local attention ``f(q, k, v, mask) -> out``
    run on the post-all-to-all (B, T, H/n, D) arrays — e.g.
    ``flash_attention_impl(causal=True)`` to fuse with the Pallas kernel;
    when given, it is responsible for causal masking itself.  ``kv_mask``
    (B, T) bool, True = key visible (padding masks); passed through to
    the local attention as a per-key mask.
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(f"seq len {q.shape[1]} not divisible by {axis}={n}")
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses parallelism is bounded by the head count: "
            f"{q.shape[2]} heads not divisible by {axis}={n} "
            f"(use ring_attention for head-count-free sequence parallelism)")
    if inner is not None and (causal or scale is not None):
        raise ValueError(
            "when `inner` is supplied it owns masking and scaling — "
            "construct it causal/scaled (e.g. flash_attention_impl("
            "causal=True)) instead of passing causal/scale here")
    if batch_axes is None:
        from dtf_tpu.parallel.sharding import data_axes as _data_axes
        batch_axes = _data_axes(mesh)
    spec = P(batch_axes or None, axis, None, None)
    has_mask = kv_mask is not None
    body = functools.partial(_ulysses_body, axis=axis, causal=causal,
                             scale=scale, inner=inner, has_mask=has_mask)
    in_specs = [spec, spec, spec]
    args = [q, k, v]
    if has_mask:
        in_specs.append(P(batch_axes or None, axis))
        args.append(kv_mask)
    mapped = shard_map_fn(body, mesh=mesh, in_specs=tuple(in_specs),
                           out_specs=spec)
    return mapped(*args)


def ulysses_attention_impl(mesh: Mesh, axis: str = "seq",
                           causal: bool = False,
                           inner: Optional[Callable] = None):
    """MultiHeadAttention ``attn_impl`` adapter ((B,T,H,D) layout).

    mask=None and key-padding masks ((B|1, 1, 1, Tk)) are supported — the
    validity chunks all-gather to the full per-key mask, which the flash
    inner kernel consumes directly.  General per-query masks are rejected.
    """

    def impl(q, k, v, mask=None):
        kv_mask = None
        if mask is not None:
            from dtf_tpu.ops.flash_attention import require_kv_mask
            kv_mask = require_kv_mask(mask, q, k, "ulysses_attention_impl")
        return ulysses_attention(q, k, v, mesh, axis=axis, causal=causal,
                                 inner=inner, kv_mask=kv_mask)

    return impl
