"""Ring attention: exact attention over sequences sharded on a ``seq`` axis.

Long-context support is a first-class design axis of this framework (the
reference has no sequence models at all, SURVEY.md §5.7 — this is new
capability, not parity).  Each device holds a T/n slice of the sequence;
K/V chunks rotate around the ring via ``lax.ppermute`` over ICI while every
device accumulates its queries' attention with the online-softmax
recurrence — O(T/n) memory per device, exact result, no T×T tensor ever
materialized.

Design notes:

* implemented as per-device code under ``jax.shard_map`` so the collective
  schedule is explicit (ppermute ring), composing with the data axes for
  the batch dim;
* the ring loop is a ``lax.scan`` over ring steps (static trip count =
  mesh axis size) carrying (acc, m, l, k_chunk, v_chunk) — reverse-mode
  differentiable, so the same code trains;
* masked logits use a large-negative finite constant instead of -inf so
  fully-masked (future) chunks stay NaN-free through exp;
* statistics in fp32 regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dtf_tpu.parallel.collectives import shard_map_fn

NEG_BIG = -1e30   # finite "-inf": keeps exp() NaN-free for all-masked rows


def _ring_body(q, k, v, *rest, axis: str, n: int, causal: bool,
               scale: float, has_mask: bool):
    """Per-device ring attention.  q,k,v: (B, t_loc, H, D) local chunks;
    with ``has_mask`` a (B, t_loc) key-validity chunk rotates around the
    ring alongside its K/V chunk (a padded key must stay masked no matter
    which device currently holds it)."""
    mask = rest[0] if has_mask else None
    b, t_loc, h, d = q.shape
    me = lax.axis_index(axis)
    qf = q.astype(jnp.float32)

    q_pos = me * t_loc + lax.broadcasted_iota(jnp.int32, (t_loc, t_loc), 0)

    def step(carry, s):
        acc, m, l, kc, vc, mc = carry
        src = (me - s) % n                     # whose chunk we hold now
        sblk = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32),
                          preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src * t_loc + lax.broadcasted_iota(
                jnp.int32, (t_loc, t_loc), 1)
            sblk = jnp.where((q_pos >= k_pos)[None, None], sblk, NEG_BIG)
        if mc is not None:
            sblk = jnp.where(mc[:, None, None, :], sblk, NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=-1))          # (B,H,Tq)
        p = jnp.exp(sblk - m_new[..., None])                    # (B,H,Tq,Tk)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, vc.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        acc_new = acc * corr[..., None] + pv
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        if mc is not None:
            mc = lax.ppermute(mc, axis, perm)
        return (acc_new, m_new, l_new, kc, vc, mc), None

    acc0 = jnp.zeros((b, h, t_loc, d), jnp.float32)
    m0 = jnp.full((b, h, t_loc), NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, t_loc), jnp.float32)
    (acc, _, l, _, _, _), _ = lax.scan(step, (acc0, m0, l0, k, v, mask),
                                       jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]                # (B,H,Tq,D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)            # (B,Tq,H,D)


def ring_attention(q, k, v, mesh: Mesh, *, axis: str = "seq",
                   causal: bool = False, scale: Optional[float] = None,
                   batch_axes: Optional[tuple] = None, kv_mask=None):
    """Exact sequence-parallel attention.

    q, k, v: (B, T, H, D) *global* arrays whose T dim is (to be) sharded
    over ``axis``; returns (B, T, H, D) sharded the same way.  Call inside
    or outside jit — shard_map composes with the surrounding program.
    ``kv_mask`` (B, T) bool, True = key visible (padding masks); its
    chunks rotate with the K/V chunks.  Rows must keep >=1 visible key.
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(f"seq len {q.shape[1]} not divisible by "
                         f"{axis}={n}")
    scale = float(scale if scale is not None else q.shape[-1] ** -0.5)
    if batch_axes is None:
        from dtf_tpu.parallel.sharding import data_axes as _data_axes
        batch_axes = _data_axes(mesh)
    spec = P(batch_axes or None, axis, None, None)
    has_mask = kv_mask is not None
    body = functools.partial(_ring_body, axis=axis, n=n, causal=causal,
                             scale=scale, has_mask=has_mask)
    in_specs = [spec, spec, spec]
    args = [q, k, v]
    if has_mask:
        in_specs.append(P(batch_axes or None, axis))
        args.append(kv_mask)
    mapped = shard_map_fn(body, mesh=mesh, in_specs=tuple(in_specs),
                           out_specs=spec)
    return mapped(*args)


def ring_attention_impl(mesh: Mesh, axis: str = "seq", causal: bool = False):
    """MultiHeadAttention ``attn_impl`` adapter ((B,T,H,D) layout).

    mask=None and key-padding masks ((B|1, 1, 1, Tk) — BERT's
    ``pad_mask[:, None, None, :]``) are supported; the validity chunks
    rotate around the ring with their K/V.  General per-query masks are
    rejected (they cannot ride the ring as per-key state)."""

    def impl(q, k, v, mask=None):
        kv_mask = None
        if mask is not None:
            from dtf_tpu.ops.flash_attention import require_kv_mask
            kv_mask = require_kv_mask(mask, q, k, "ring_attention_impl")
        return ring_attention(q, k, v, mesh, axis=axis, causal=causal,
                              kv_mask=kv_mask)

    return impl
