"""Fused decode: the whole transformer stack as ONE Pallas kernel per
token, for up to 32 simultaneous streams (sublane tiles of 8 on an
inner grid dimension beyond the first tile).

Why: KV-cache decode at B=1 is op-latency-bound, not bandwidth-bound — the
unfused loop issues ~170 tiny XLA ops per token (measured ~1.04 ms/token vs
~0.36 ms of HBM weight traffic on GPT-2-small, BASELINE.md round 2).  The
reference has no decode path at all (it is a TF1 parameter-server MNIST
demo, `/root/reference/tf_distributed.py`); this kernel exists to push the
framework's serving headline past the dispatch floor the op-per-op design
hits.

Design (all control flow static — Mosaic-friendly):

* ``grid=(num_layers,)`` — TPU grids run **sequentially**, so the residual
  stream lives in a VMEM scratch that carries across grid steps; layer
  ``l``'s weights are that grid step's blocks (Pallas double-buffers the
  HBM->VMEM streaming of layer l+1 behind layer l's compute).
* FIVE matmuls per layer (packed qkv, o-proj, 2-3 MLP) — a first cut with
  per-head matmul loops measured ~1.0 ms/token on GPT-2-small, i.e. the
  in-kernel latency of ~900 M=1 matmuls re-created the dispatch floor it
  was built to kill.  Attention instead runs in **lane-segment
  arithmetic**: scores are an elementwise ``q ⊙ K`` over the (T, H·Dh)
  cache block followed by a per-64-lane-segment reduction to (T, H), the
  softmax reduces over the sublane (T) dim, and ``P·V`` is the reverse
  broadcast-multiply reduced over T — all VPU work on arrays that already
  sit in VMEM, no per-head slicing of matmul operands.
* The KV cache is read-only input, row-major (L, B, T, KVH·Dh).  The current
  token's k/v never touch the cache inside the kernel: its attention term
  is folded in online-softmax style (separate self-score joined at the
  max/denominator), and the (L, B, KVH·Dh) k/v outputs are written into
  the cache by ONE ``dynamic_update_slice`` per token outside — writing
  only the row instead of round-tripping an aliased cache block.
* int8 mode: every matmul operand streams from HBM as int8 with a
  per-output-channel fp32 scale and widens to bf16 in VMEM — same
  quantization contract as ``GPT._decode_pack`` (models/gpt.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dtf_tpu.ops.flash_attention import (_CompilerParams,
                                          _interpret_default)

NEG_BIG = -1e30

# Stream capacity of the fused decode kernel.  Streams run in sublane
# tiles of 8: 1-8 streams are one tile; 9-32 must be a multiple of 8 and
# ride a (layers, batch_tiles) grid with the batch-tile dim INNERMOST, so
# each layer's weights stream to VMEM once and are reused by every tile —
# the whole point of batched decode.  Above 32 the per-tile cache blocks
# plus double-buffered weights outgrow VMEM.  Shared by the kernel guard,
# GPT._check_fused_decode, and the lm workload's CLI pre-check so the cap
# cannot drift.
MAX_FUSED_STREAMS = 32
STREAM_TILE = 8


def validate_stream_count(n: int) -> None:
    """The ONE definition of which stream counts the fused kernel takes."""
    if n < 1:
        raise ValueError(f"fused decode needs at least one stream; got {n}")
    if n > MAX_FUSED_STREAMS:
        raise ValueError(
            f"fused decode streams (batch, or batch x beams) are capped "
            f"at {MAX_FUSED_STREAMS}; got {n} — use the unfused path (the "
            f"op-per-op loop already amortizes weight streaming at large "
            f"batch) or shrink the batch/beam")
    if n > STREAM_TILE and n % STREAM_TILE:
        raise ValueError(
            f"fused decode streams beyond {STREAM_TILE} must be a "
            f"multiple of the sublane tile ({STREAM_TILE}); got {n} — "
            f"pad the batch or use the unfused path")


def quantize_cols(w):
    """Symmetric per-output-channel (last dim) int8 weight quantization:
    (..., K, N) -> (int8 same shape, fp32 scale (..., 1, N)).  The ONE
    definition shared by this kernel's pack and GPT._decode_pack, so the
    fused and unfused --decode_int8 paths stay bit-compatible."""
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2,
                    keepdims=True) / 127.0
    safe = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / safe), -127,
                 127).astype(jnp.int8)
    return q, scale


def quantize_rows(x):
    """Symmetric per-row (last dim) int8 quantization for KV-cache rows:
    (..., N) -> (int8 same shape, fp32 scale (..., 8) lane-replicated).
    The scale is stored 8-lanes-wide because a 1-lane trailing dim is not
    a legal Mosaic block; the kernel re-broadcasts lane 0 across the row
    with a constant matmul."""
    m = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(m / 127.0, 1e-30)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127,
                 127).astype(jnp.int8)
    return q, jnp.broadcast_to(scale, (*scale.shape[:-1], 8))


def fused_decode_pack(params, cfg, int8: bool = False) -> dict:
    """Repack GPT params for the fused kernel (once per generate call).

    Returns a dict of stacked arrays with static key order (see
    ``_PACK_KEYS``); head-owning weights get the head dim LEADING so the
    kernel indexes heads on an untiled dim.
    """
    lay = params["layers"]
    attn = lay["attn"]
    n_layers = lay["fc1"]["w"].shape[0]
    d = cfg.dim
    flat_w = lambda t: t["w"].reshape(n_layers, d, -1)
    flat_b = lambda t: t["b"].reshape(n_layers, 1, -1)
    # Per-layer vectors get a singleton middle dim — Mosaic requires the
    # last two block dims to be (8|full, 128|full), and a (1, D) block of
    # an (L, D) array satisfies neither; (L, 1, D) with block (1, 1, D)
    # does.  The kernel reads them as ``ref[0]`` -> (1, D).
    vec = lambda a: a[:, None, :]
    # Dtypes stay as stored (bf16 in the decode benchmarks; fp32 in the
    # CPU parity tests, where the kernel then computes in fp32 too).
    pack = {
        "ln1_s": vec(lay["ln1"]["scale"]), "ln1_b": vec(lay["ln1"]["bias"]),
        "ln2_s": vec(lay["ln2"]["scale"]), "ln2_b": vec(lay["ln2"]["bias"]),
        # ONE (D, (H+2·KVH)·Dh) projection operand per layer — same
        # concatenation as GPT._packed_qkv, so the int8 per-column scales
        # match the unfused --decode_int8 path exactly.
        "w_qkv": jnp.concatenate(
            [flat_w(attn["q"]), flat_w(attn["k"]), flat_w(attn["v"])],
            axis=-1),
        "b_qkv": jnp.concatenate(
            [flat_b(attn["q"]), flat_b(attn["k"]), flat_b(attn["v"])],
            axis=-1),
        "w_o": attn["o"]["w"].reshape(n_layers, -1, d),   # (L, H·Dh, D)
        "b_o": vec(attn["o"]["b"]),                       # (L, 1, D)
        "w_fc1": lay["fc1"]["w"], "b_fc1": vec(lay["fc1"]["b"]),
        "w_fc2": lay["fc2"]["w"], "b_fc2": vec(lay["fc2"]["b"]),
    }
    if cfg.mlp_act == "swiglu":
        pack["w_gate"] = lay["fc_gate"]["w"]
        pack["b_gate"] = vec(lay["fc_gate"]["b"])
    if int8:
        for key in ("w_qkv", "w_o", "w_fc1", "w_fc2", "w_gate"):
            if key in pack:
                pack[key], pack[key + "_sc"] = quantize_cols(pack[key])
    return pack


def _ln(x, scale_ref, bias_ref, eps=1e-6):
    """LayerNorm of (B, D) fp32 x (row-wise) with (1, 1, D) param refs."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale_ref[0].astype(jnp.float32)
            + bias_ref[0].astype(jnp.float32))


def _mm(x_c, w_ref, sc_ref, idx, compute_dtype):
    """x (1, K) @ weight block ``w_ref[idx]`` in ``compute_dtype`` with
    fp32 MXU accumulation; int8 weights widen in VMEM and fold their
    per-output-channel scale into the fp32 output."""
    w = w_ref[idx] if idx is not None else w_ref[...]
    y = jax.lax.dot_general(
        x_c, w.astype(compute_dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if sc_ref is not None:
        sc = sc_ref[idx] if idx is not None else sc_ref[...]
        y = y * sc
    return y


def _qkv_project(r, x, mm, mmc, hn, kn, eps, cd):
    """LN1 + packed qkv projection + optional in-kernel RoPE -> (q_row,
    k_t, v_t) in fp32.  Shared by the single-chunk and chunked kernels."""
    f32 = jnp.float32
    hb = _ln(x, r["ln1_s"], r["ln1_b"], eps).astype(cd)
    qkv = mm(hb, "w_qkv") + r["b_qkv"][0].astype(f32)
    q_row = qkv[:, :hn]
    k_t = qkv[:, hn:hn + kn]
    v_t = qkv[:, hn + kn:]
    if "rope_cos_q" in r:
        # RoPE as lane arithmetic: rope(x) = x ⊙ [cos,cos] +
        # swap_halves(x) ⊙ [sin,sin], where swap_halves is the constant
        # per-head [[0, I], [-I, 0]] matmul (r["rope_swap_*"]) — the same
        # no-lane-reshape trick as the segment matrices.  Without GQA the
        # k tables are byte-identical to the q tables, so they are only
        # passed (and streamed) separately when KVH != H.
        q_row = (q_row * r["rope_cos_q"][...]
                 + mmc(q_row.astype(cd), r["rope_swap_q"][...])
                 * r["rope_sin_q"][...])
        side = "k" if "rope_cos_k" in r else "q"
        k_t = (k_t * r[f"rope_cos_{side}"][...]
               + mmc(k_t.astype(cd), r[f"rope_swap_{side}"][...])
               * r[f"rope_sin_{side}"][...])
    return q_row, k_t, v_t


def _mlp_residual_tail(r, x, mm, mlp_act, eps, cd):
    """x + MLP(LN2(x)) in fp32 — shared kernel tail."""
    f32 = jnp.float32
    h2 = _ln(x, r["ln2_s"], r["ln2_b"], eps).astype(cd)
    u = mm(h2, "w_fc1") + r["b_fc1"][0].astype(f32)
    if mlp_act == "swiglu":
        gate = mm(h2, "w_gate") + r["b_gate"][0].astype(f32)
        u = jax.nn.silu(gate) * u
    else:
        u = jax.nn.gelu(u)
    return x + mm(u.astype(cd), "w_fc2") + r["b_fc2"][0].astype(f32)


def _cache_dq(r, cd, mmc):
    """Row-dequant closure for the (possibly int8) cache blocks."""
    if "kc_sc" in r:
        brd = r["sc_brd"][...]
        return lambda c, s_: (c.astype(jnp.float32)
                              * mmc(s_, brd)).astype(cd)
    return lambda c, s_: c.astype(cd)


def _decode_kernel(*refs, keys, num_layers, num_heads, kv_heads, head_dim,
                   batch, mlp_act, compute_dtype, new_dtype, out_dtype,
                   eps):
    n_in = len(keys)
    r = dict(zip(keys, refs[:n_in]))
    x_out, k_new, v_new = refs[n_in:n_in + 3]
    x_s = refs[n_in + 3]
    l = pl.program_id(0)
    bt = pl.program_id(1)
    g = num_heads // kv_heads
    scale = head_dim ** -0.5
    pos = r["pos"][0]
    cd = compute_dtype
    # This grid step's slice of the residual scratch: the scratch holds
    # ALL streams (total_b, D); each (layer, batch-tile) step works on
    # its tile's rows and carries them to the next layer's visit.
    rows = pl.ds(bt * batch, batch)

    @pl.when(l == 0)
    def _init():
        x_s[rows] = r["x"][...].astype(jnp.float32)

    x = x_s[rows]                                      # (tile_b, D) f32
    sc = lambda name: r.get(name + "_sc")
    mm = lambda h, name: _mm(h, r[name], sc(name), 0, cd)
    f32 = jnp.float32
    mmc = lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=f32)
    hn, kn = num_heads * head_dim, kv_heads * head_dim

    # --- attention (lane-segment arithmetic; see module docstring) ----
    t_cache = r["kc"].shape[2]
    q_row, k_t, v_t = _qkv_project(r, x, mm, mmc, hn, kn, eps, cd)
    k_new[0] = k_t.astype(new_dtype)
    v_new[0] = v_t.astype(new_dtype)

    # Segment arithmetic via constant 0/1 matmuls (Mosaic does not lower
    # lane-splitting reshapes like (T, H·Dh)->(T, H, Dh)):
    #   reduce per head:     a (·, H·Dh) @ segm (H·Dh, H) -> (·, H)
    #   broadcast per head:  a (·, H)    @ segb (H, H·Dh) -> (·, H·Dh)
    #   GQA lane expand:     a (·, KVH·Dh) @ expm (KVH·Dh, H·Dh)
    segm, segb = r["segm"][...], r["segb"][...]
    expand = ((lambda a: a) if g == 1
              else (lambda a: mmc(a, r["expm"][...]).astype(cd)))
    q_c = q_row.astype(cd)
    s_self = mmc(expand(k_t.astype(cd)) * q_c, segm) * scale    # (B, H)

    # int8 KV cache rows widen in VMEM with their per-row scale
    # re-broadcast by the constant lane-0 selector matmul (sc_brd) —
    # the same no-lane-reshape vocabulary as the segment matrices.
    dq = _cache_dq(r, cd, mmc)

    if batch == 1:
        # Deliberate specialization for the single-stream latency headline:
        # rank-2 arrays, no (B·T) reshape round-trips.  Keep in sync with
        # the general branch below (tests cover both at every config).
        ksc = r["kc_sc"][0, 0] if "kc_sc" in r else None
        vsc = r["vc_sc"][0, 0] if "kc_sc" in r else None
        kc = expand(dq(r["kc"][0, 0], ksc))            # (T, H·Dh)
        vc = expand(dq(r["vc"][0, 0], vsc))
        s = mmc(kc * q_c, segm) * scale                # (T, H) f32
        visible = (jax.lax.broadcasted_iota(jnp.int32, (t_cache, 1), 0)
                   < pos)                              # strictly-older rows
        s = jnp.where(visible, s, NEG_BIG)
        m = jnp.maximum(jnp.max(s, axis=0, keepdims=True), s_self)
        p = jnp.exp(s - m)                             # (T, H) f32
        p_self = jnp.exp(s_self - m)
        denom = jnp.sum(p, axis=0, keepdims=True) + p_self     # (1, H)
        pv = mmc(p.astype(cd), segb).astype(cd) * vc   # (T, H·Dh)
        o_row = jnp.sum(pv, axis=0, keepdims=True, dtype=f32)
        o_row = (o_row
                 + mmc(p_self.astype(cd), segb) * expand(v_t.astype(cd)))
        o_row = o_row * mmc((1.0 / denom).astype(cd), segb)
    else:
        # Batched rows ride the leading (untiled) dims: per-row caches
        # collapse (B, T, ·) -> (B·T, ·) for the segment matmuls and
        # split back for the per-row softmax reductions — major-dim
        # reshapes only, the lane dim never splits.
        b = batch
        if "kc_sc" in r:
            ksc = r["kc_sc"][0].reshape(b * t_cache, 8)
            vsc = r["vc_sc"][0].reshape(b * t_cache, 8)
        else:
            ksc = vsc = None
        kc2 = expand(dq(r["kc"][0].reshape(b * t_cache, kn), ksc))
        vc2 = expand(dq(r["vc"][0].reshape(b * t_cache, kn), vsc))
        q_rep = jnp.broadcast_to(
            q_c[:, None, :], (b, t_cache, hn)).reshape(b * t_cache, hn)
        s = mmc(kc2 * q_rep, segm).reshape(b, t_cache, num_heads) * scale
        visible = (jax.lax.broadcasted_iota(
            jnp.int32, (1, t_cache, 1), 1) < pos)
        s = jnp.where(visible, s, NEG_BIG)
        m = jnp.maximum(jnp.max(s, axis=1), s_self)    # (B, H)
        p = jnp.exp(s - m[:, None, :])                 # (B, T, H)
        p_self = jnp.exp(s_self - m)
        denom = jnp.sum(p, axis=1) + p_self            # (B, H)
        pv = (mmc(p.reshape(b * t_cache, num_heads).astype(cd), segb)
              .astype(cd) * vc2)                       # (B·T, H·Dh)
        o_row = jnp.sum(pv.reshape(b, t_cache, hn), axis=1, dtype=f32)
        o_row = (o_row
                 + mmc(p_self.astype(cd), segb) * expand(v_t.astype(cd)))
        o_row = o_row * mmc((1.0 / denom).astype(cd), segb)
    x = x + mm(o_row.astype(cd), "w_o") + r["b_o"][0].astype(f32)
    x = _mlp_residual_tail(r, x, mm, mlp_act, eps, cd)

    x_s[rows] = x
    x_out[...] = x.astype(out_dtype)


def _decode_kernel_chunked(*refs, keys, num_layers, num_heads, kv_heads,
                           head_dim, batch, mlp_act, compute_dtype,
                           new_dtype, out_dtype, eps, chunk):
    """Long-context variant: a third (innermost) grid dim walks the KV
    cache in chunks with an online softmax, so per-step VMEM holds one
    (tile_b, chunk, KVH·Dh) cache block instead of the whole T.  The
    running (max, denominator, accumulator) live in VMEM scratch per
    stream; the current token's self-term seeds them (m=s_self, den=1,
    acc=v_t) so chunk passes only fold strictly-older rows.  The
    single-chunk kernel (`_decode_kernel`) is kept verbatim for caches
    that fit — its one-shot softmax is bit-stable against round-3's
    chip-validated behavior."""
    n_in = len(keys)
    r = dict(zip(keys, refs[:n_in]))
    x_out, k_new, v_new = refs[n_in:n_in + 3]
    x_s, q_s, m_s, den_s, acc_s = refs[n_in + 3:n_in + 8]
    l = pl.program_id(0)
    bt = pl.program_id(1)
    tc = pl.program_id(2)
    n_tc = pl.num_programs(2)
    g = num_heads // kv_heads
    scale = head_dim ** -0.5
    pos = r["pos"][0]
    cd = compute_dtype
    rows = pl.ds(bt * batch, batch)

    sc = lambda name: r.get(name + "_sc")
    mm = lambda h, name: _mm(h, r[name], sc(name), 0, cd)
    f32 = jnp.float32
    mmc = lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=f32)
    hn, kn = num_heads * head_dim, kv_heads * head_dim
    segm, segb = r["segm"][...], r["segb"][...]
    expand = ((lambda a: a) if g == 1
              else (lambda a: mmc(a, r["expm"][...]).astype(cd)))
    dq = _cache_dq(r, cd, mmc)
    b = batch

    @pl.when((l == 0) & (tc == 0))
    def _init_residual():
        x_s[rows] = r["x"][...].astype(jnp.float32)

    @pl.when(tc == 0)
    def _project_and_seed():
        x = x_s[rows]
        q_row, k_t, v_t = _qkv_project(r, x, mm, mmc, hn, kn, eps, cd)
        k_new[0] = k_t.astype(new_dtype)
        v_new[0] = v_t.astype(new_dtype)
        q_c = q_row.astype(cd)
        q_s[rows] = q_row
        s_self = mmc(expand(k_t.astype(cd)) * q_c, segm) * scale
        m_s[rows] = s_self                      # running max
        den_s[rows] = jnp.ones_like(s_self)     # p_self = exp(0) = 1
        acc_s[rows] = expand(v_t.astype(cd)).astype(f32)

    # ---- fold this cache chunk into the running softmax ----
    q_c = q_s[rows].astype(cd)                  # (B, H·Dh)
    if "kc_sc" in r:
        ksc = r["kc_sc"][0].reshape(b * chunk, 8)
        vsc = r["vc_sc"][0].reshape(b * chunk, 8)
    else:
        ksc = vsc = None
    kc2 = expand(dq(r["kc"][0].reshape(b * chunk, kn), ksc))
    vc2 = expand(dq(r["vc"][0].reshape(b * chunk, kn), vsc))
    q_rep = jnp.broadcast_to(
        q_c[:, None, :], (b, chunk, hn)).reshape(b * chunk, hn)
    s = mmc(kc2 * q_rep, segm).reshape(b, chunk, num_heads) * scale
    # strictly-older rows only, at this chunk's global offset
    visible = (tc * chunk
               + jax.lax.broadcasted_iota(jnp.int32, (1, chunk, 1), 1)
               < pos)
    s = jnp.where(visible, s, NEG_BIG)
    m_old = m_s[rows]                           # (B, H)
    m_new = jnp.maximum(m_old, jnp.max(s, axis=1))
    alpha = jnp.exp(m_old - m_new)              # (B, H)
    p = jnp.exp(s - m_new[:, None, :])          # (B, C, H)
    den_s[rows] = den_s[rows] * alpha + jnp.sum(p, axis=1)
    pv = (mmc(p.reshape(b * chunk, num_heads).astype(cd), segb)
          .astype(cd) * vc2)                    # (B·C, H·Dh)
    acc_s[rows] = (acc_s[rows] * mmc(alpha.astype(cd), segb)
                   + jnp.sum(pv.reshape(b, chunk, hn), axis=1, dtype=f32))
    m_s[rows] = m_new

    @pl.when(tc == n_tc - 1)
    def _finalize():
        x = x_s[rows]
        o_row = acc_s[rows] * mmc((1.0 / den_s[rows]).astype(cd), segb)
        x = x + mm(o_row.astype(cd), "w_o") + r["b_o"][0].astype(f32)
        x = _mlp_residual_tail(r, x, mm, mlp_act, eps, cd)
        x_s[rows] = x
        x_out[...] = x.astype(out_dtype)


def _segment_matrices(num_heads, head_dim, dtype):
    """The constant 0/1 lane-segment matmul pair (reduce / broadcast per
    head) shared by the fused decode kernel and the paged-attention
    kernel — Mosaic does not lower lane-splitting reshapes, so per-head
    reductions ride these instead."""
    hn = num_heads * head_dim
    lane = lambda shape, dim: jax.lax.broadcasted_iota(jnp.int32, shape,
                                                       dim)
    segm = (lane((hn, num_heads), 0) // head_dim
            == lane((hn, num_heads), 1)).astype(dtype)
    return segm, segm.T


def _gqa_expand_matrix(num_heads, kv_heads, head_dim, dtype):
    """(KVH·Dh, H·Dh) constant matmul that replicates each kv head's
    lanes across its query group (the GQA lane expand)."""
    g = num_heads // kv_heads
    kn, hn = kv_heads * head_dim, num_heads * head_dim
    lane = lambda shape, dim: jax.lax.broadcasted_iota(jnp.int32, shape,
                                                       dim)
    i, j = lane((kn, hn), 0), lane((kn, hn), 1)
    return (i == (j // (g * head_dim)) * head_dim
            + j % head_dim).astype(dtype)


def _paged_attn_kernel(table_ref, pos_ref, q_ref, ks_ref, vs_ref,
                       kc_ref, vc_ref, segm_ref, segb_ref, *rest,
                       num_heads, kv_heads, head_dim, block_size):
    """Block-indexed paged attention, one decode token per slot.

    Grid (slots, blocks_per_slot): the slot's block table (scalar
    prefetch) drives each grid step's cache-block DMA — the gather IS
    the index_map, no whole-pool materialization.  Online softmax state
    (running max / denominator / accumulator) lives in VMEM scratch and
    is seeded at block 0 with the current token's self term, exactly
    the fused decode kernel's join."""
    has_g = kv_heads != num_heads
    if has_g:
        expm_ref, out_ref = rest[0], rest[1]
        m_s, den_s, acc_s = rest[2:]
    else:
        out_ref = rest[0]
        m_s, den_s, acc_s = rest[1:]
    b = pl.program_id(0)
    i = pl.program_id(1)
    f32 = jnp.float32
    mmc = lambda a, bb: jax.lax.dot_general(
        a, bb, (((1,), (0,)), ((), ())), preferred_element_type=f32)
    segm = segm_ref[...].astype(f32)
    segb = segb_ref[...].astype(f32)
    expand = ((lambda a: a) if not has_g
              else (lambda a: mmc(a, expm_ref[...].astype(f32))))
    q = q_ref[...].astype(f32)                      # (1, H·Dh)
    scale = head_dim ** -0.5

    @pl.when(i == 0)
    def _seed():
        k_s = expand(ks_ref[...].astype(f32))       # (1, H·Dh)
        s_self = mmc(k_s * q, segm) * scale         # (1, H)
        m_s[...] = s_self
        den_s[...] = jnp.ones_like(s_self)          # p_self = exp(0)
        acc_s[...] = expand(vs_ref[...].astype(f32))

    kc = expand(kc_ref[0].astype(f32))              # (bs, H·Dh)
    vc = expand(vc_ref[0].astype(f32))
    q_rep = jnp.broadcast_to(q, (block_size, q.shape[1]))
    s = mmc(kc * q_rep, segm) * scale               # (bs, H)
    gpos = (i * block_size
            + jax.lax.broadcasted_iota(jnp.int32, (block_size, 1), 0))
    s = jnp.where(gpos < pos_ref[b], s, NEG_BIG)    # strictly-older rows
    m_old = m_s[...]
    m_new = jnp.maximum(m_old, jnp.max(s, axis=0, keepdims=True))
    alpha = jnp.exp(m_old - m_new)                  # (1, H)
    p = jnp.exp(s - m_new)                          # (bs, H)
    den_s[...] = den_s[...] * alpha + jnp.sum(p, axis=0, keepdims=True)
    pv = mmc(p, segb) * vc                          # (bs, H·Dh)
    acc_s[...] = (acc_s[...] * mmc(alpha, segb)
                  + jnp.sum(pv, axis=0, keepdims=True))
    m_s[...] = m_new

    @pl.when(i == pl.num_programs(1) - 1)
    def _finalize():
        out_ref[...] = acc_s[...] * mmc(1.0 / den_s[...], segb)


def paged_attention(q, k_self, v_self, pool_k, pool_v, table, pos, *,
                    num_heads: int, kv_heads: int, interpret=None):
    """Paged attention over a block pool: the TPU-build replacement for
    the serving decode step's ``pool[table]`` XLA gather.

    q: (B, H·Dh) this token's queries; k_self/v_self: (B, KVH·Dh) its
    k/v (folded online, never written to the pool here); pool_k/pool_v:
    (num_blocks, block_size, KVH·Dh) ONE layer's hot pool; table:
    (B, nb) int32 physical block ids (callers clamp -1 to the trash
    block); pos: (B,) int32 — cache rows strictly below ``pos[b]`` are
    visible, the self term joins at the softmax.

    Per grid step the kernel DMAs exactly one (block_size, KVH·Dh)
    cache block chosen by the scalar-prefetched table — per-token cost
    is O(nb · block_size) regardless of pool size, which is the whole
    point.  Attention itself is the fused decode kernel's lane-segment
    arithmetic with an online softmax across block steps.  Returns the
    fp32 (B, H·Dh) context rows.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, hn = q.shape
    nb = table.shape[1]
    _, bs, kn = pool_k.shape
    hd = hn // num_heads
    f32 = jnp.float32
    segm, segb = _segment_matrices(num_heads, hd, f32)
    grid_invariant = lambda blk: pl.BlockSpec(
        blk, lambda bb, ii, tr, pr: (0,) * len(blk))
    row = lambda width: pl.BlockSpec((1, width),
                                     lambda bb, ii, tr, pr: (bb, 0))
    in_specs = [
        row(hn),                                    # q
        row(kn),                                    # k_self
        row(kn),                                    # v_self
        pl.BlockSpec((1, bs, kn),
                     lambda bb, ii, tr, pr: (tr[bb, ii], 0, 0)),
        pl.BlockSpec((1, bs, kn),
                     lambda bb, ii, tr, pr: (tr[bb, ii], 0, 0)),
        grid_invariant((hn, num_heads)),            # segm
        grid_invariant((num_heads, hn)),            # segb
    ]
    args = [q, k_self, v_self, pool_k, pool_v, segm, segb]
    if kv_heads != num_heads:
        in_specs.append(grid_invariant((kn, hn)))
        args.append(_gqa_expand_matrix(num_heads, kv_heads, hd, f32))
    kernel = functools.partial(
        _paged_attn_kernel, num_heads=num_heads, kv_heads=kv_heads,
        head_dim=hd, block_size=bs)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hn), lambda bb, ii, tr, pr: (bb, 0)),
        scratch_shapes=[pltpu.VMEM((1, num_heads), f32),
                        pltpu.VMEM((1, num_heads), f32),
                        pltpu.VMEM((1, hn), f32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hn), f32),
        interpret=interpret,
    )(jnp.asarray(table, jnp.int32), jnp.asarray(pos, jnp.int32), *args)


def fused_decode_step(pack, cache_k, cache_v, x, pos, cfg, *,
                      cache_k_scale=None, cache_v_scale=None,
                      rope_cos=None, rope_sin=None, cache_chunk=None,
                      interpret=None):
    """One token through the whole layer stack as a single ``pallas_call``.

    pack: ``fused_decode_pack`` output; cache_k/v: row-major
    (L, B, T, KVH·Dh) in the cache dtype; x: (B, D) embedded tokens
    (B <= MAX_FUSED_STREAMS; beyond one sublane tile of 8 the batch
    rides an inner grid dimension in tiles of STREAM_TILE, so layer
    weights stream to VMEM once per layer and every tile reuses them);
    pos: scalar int32 position of this token (its row in the cache is
    written by the CALLER from the returned k/v — the kernel only reads
    strictly-older rows and folds the current token in online-softmax
    style).
    ``rope_cos``/``rope_sin``: fp32 (Dh//2,) angle tables for THIS position
    (``nn.rope.rope_angles(pos, Dh)``) — when given, q and the new k are
    rotated in-kernel (split-half convention, matching ``apply_rope``).

    ``cache_k_scale``/``cache_v_scale``: required iff the caches are
    int8 — fp32 (L, B, T, 8) lane-replicated per-row scales
    (``quantize_rows``).  The returned k/v rows are ALWAYS in x's dtype;
    an int8-cache caller quantizes them before writing.

    Returns (x_out (B, D), k_new (L, B, KVH·Dh), v_new (L, B, KVH·Dh)).
    """
    if interpret is None:
        interpret = _interpret_default()
    n_layers, b, t_cache, kn = cache_k.shape
    nh = cfg.num_heads
    kvh = cfg.num_kv_heads or nh
    hd = kn // kvh
    d = cfg.dim
    if x.shape != (b, d):
        raise ValueError(f"x must be ({b}, {d}) to match the cache's "
                         f"batch dim, got {x.shape}")
    validate_stream_count(b)
    if t_cache % 8:
        # Sublane tiling: an odd-T cache block is the Mosaic-legality
        # hazard ADVICE r4 flagged; the GPT entry points guarantee an
        # 8-aligned T (_cache_len / _check_fused_decode) — hold direct
        # callers to the same contract.
        raise ValueError(f"fused decode needs an 8-aligned cache length, "
                         f"got T={t_cache}")
    kv_int8 = cache_k.dtype == jnp.int8
    if cache_v.dtype != cache_k.dtype:
        raise ValueError(f"cache_k/cache_v dtypes must match, got "
                         f"{cache_k.dtype} vs {cache_v.dtype}")
    if (kv_int8 != (cache_k_scale is not None)
            or kv_int8 != (cache_v_scale is not None)):
        raise ValueError("int8 caches require BOTH cache_k_scale and "
                         "cache_v_scale; fp caches must pass neither")
    tile_b = b if b <= STREAM_TILE else STREAM_TILE
    n_bt = b // tile_b

    # The VMEM budget covers the kernel's WORKING footprint, which the
    # in-kernel widened (compute-dtype) cache copies dominate — int8
    # halves the streamed bytes but not those copies, so the budget uses
    # the compute itemsize (>=2) either way, plus the int8 path's two
    # fp32 (tile_b, chunk, 8) scale blocks.  A cache too long for one
    # block walks in chunks on a third (innermost) grid dim with an
    # online softmax (`_decode_kernel_chunked`).
    def _fits(ch):
        sb = 2 * tile_b * ch * 8 * 4 if kv_int8 else 0
        return (2 * tile_b * ch * kn * max(cache_k.dtype.itemsize, 2)
                + sb) / 2 ** 20 <= 40
    if cache_chunk is not None:
        # explicit override (tests; chip tuning) — must tile the cache
        # and still fit the VMEM budget
        if cache_chunk < 1 or t_cache % cache_chunk or cache_chunk % 8:
            raise ValueError(
                f"cache_chunk {cache_chunk} must be a positive 8-aligned "
                f"divisor of T={t_cache}")
        if not _fits(cache_chunk):
            raise ValueError(
                f"cache_chunk {cache_chunk} exceeds the per-(layer, "
                f"tile) VMEM budget at tile {tile_b} — choose a smaller "
                f"chunk")
        chunk, n_tc = cache_chunk, t_cache // cache_chunk
    elif _fits(t_cache):
        chunk, n_tc = t_cache, 1
    else:
        for n in range(2, t_cache // 8 + 1):
            cand = t_cache // n
            if t_cache % n == 0 and cand % 8 == 0 and _fits(cand):
                chunk, n_tc = cand, n
                break
        else:
            raise ValueError(
                f"no 8-aligned divisor of T={t_cache} gives a per-"
                f"(layer, tile) cache chunk within the VMEM budget at "
                f"tile {tile_b} — use the unfused path")

    compute_dtype = pack["ln1_s"].dtype
    hn = nh * hd
    g = nh // kvh
    # Constant 0/1 segment matrices (see kernel docstring); grid-invariant
    # inputs, so they stream to VMEM once.
    lane = lambda shape, dim: jax.lax.broadcasted_iota(jnp.int32, shape,
                                                       dim)
    segm = (lane((hn, nh), 0) // hd == lane((hn, nh), 1)).astype(
        compute_dtype)
    segb = segm.T
    # Every index_map takes (layer, batch_tile, chunk); grid-invariant
    # inputs pin all three to block 0.
    keys, args, in_specs = ["pos", "x", "kc", "vc", "segm", "segb"], [
        jnp.asarray(pos, jnp.int32).reshape(1), x, cache_k, cache_v,
        segm, segb], [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((tile_b, d), lambda l, t, c: (t, 0)),
        pl.BlockSpec((1, tile_b, chunk, kn), lambda l, t, c: (l, t, c, 0)),
        pl.BlockSpec((1, tile_b, chunk, kn), lambda l, t, c: (l, t, c, 0)),
        pl.BlockSpec((hn, nh), lambda l, t, c: (0, 0)),
        pl.BlockSpec((nh, hn), lambda l, t, c: (0, 0)),
    ]
    if kv_int8:
        keys += ["kc_sc", "vc_sc", "sc_brd"]
        # lane-0 selector: (T, 8) scales @ (8, KVH·Dh) -> row-broadcast
        sc_brd = (lane((8, kn), 0) == 0).astype(jnp.float32)
        args += [cache_k_scale, cache_v_scale, sc_brd]
        in_specs += [
            pl.BlockSpec((1, tile_b, chunk, 8),
                         lambda l, t, c: (l, t, c, 0)),
            pl.BlockSpec((1, tile_b, chunk, 8),
                         lambda l, t, c: (l, t, c, 0)),
            pl.BlockSpec((8, kn), lambda l, t, c: (0, 0)),
        ]
    if g > 1:
        i, j = lane((kn, hn), 0), lane((kn, hn), 1)
        expm = (i == (j // (g * hd)) * hd + j % hd).astype(compute_dtype)
        keys.append("expm")
        args.append(expm)
        in_specs.append(pl.BlockSpec((kn, hn), lambda l, t, c: (0, 0)))
    if rope_cos is not None:
        half = hd // 2
        # per-head swap-halves with sign: out[h·Dh+i] = -x[h·Dh+i+half]
        # for i < half, +x[h·Dh+i-half] for i >= half
        def swap_matrix(n_lanes):
            i, j = lane((n_lanes, n_lanes), 0), lane((n_lanes, n_lanes), 1)
            same_head = (i // hd) == (j // hd)
            ii, jj = i % hd, j % hd
            up = same_head & (jj < half) & (ii == jj + half)     # -x2 -> x1'
            lo = same_head & (jj >= half) & (ii == jj - half)    # +x1 -> x2'
            return (jnp.where(lo, 1.0, 0.0)
                    - jnp.where(up, 1.0, 0.0)).astype(compute_dtype)

        doubled = jnp.concatenate([rope_cos, rope_cos]).astype(jnp.float32)
        sdoubled = jnp.concatenate([rope_sin, rope_sin]).astype(jnp.float32)
        sides = [("q", nh)] + ([("k", kvh)] if kvh != nh else [])
        for suffix, reps in sides:
            keys += [f"rope_cos_{suffix}", f"rope_sin_{suffix}",
                     f"rope_swap_{suffix}"]
            args += [jnp.tile(doubled, reps)[None],
                     jnp.tile(sdoubled, reps)[None],
                     swap_matrix(reps * hd)]
            n_l = reps * hd
            in_specs += [pl.BlockSpec((1, n_l), lambda l, t, c: (0, 0)),
                         pl.BlockSpec((1, n_l), lambda l, t, c: (0, 0)),
                         pl.BlockSpec((n_l, n_l),
                                      lambda l, t, c: (0, 0))]
    for name, arr in pack.items():
        keys.append(name)
        args.append(arr)
        blk = (1, *arr.shape[1:])
        in_specs.append(pl.BlockSpec(
            blk,
            lambda l, t, c, _n=len(arr.shape): (l,) + (0,) * (_n - 1)))

    # Compute in the packed weights' dtype (bf16 in the benchmarks, fp32
    # in CPU parity tests); int8-packed weights widen to the LN params'
    # dtype, which the int8 pack leaves unquantized.
    kw = dict(keys=tuple(keys), num_layers=n_layers,
              num_heads=nh, kv_heads=kvh, head_dim=hd, batch=tile_b,
              mlp_act=cfg.mlp_act,
              compute_dtype=compute_dtype, new_dtype=x.dtype,
              out_dtype=x.dtype, eps=1e-6)
    scratches = [pltpu.VMEM((b, d), jnp.float32)]
    if n_tc == 1:
        kernel = functools.partial(_decode_kernel, **kw)
    else:
        kernel = functools.partial(_decode_kernel_chunked, chunk=chunk,
                                   **kw)
        # online-softmax state: q, running max, denominator, accumulator
        scratches += [pltpu.VMEM((b, hn), jnp.float32),
                      pltpu.VMEM((b, nh), jnp.float32),
                      pltpu.VMEM((b, nh), jnp.float32),
                      pltpu.VMEM((b, hn), jnp.float32)]

    # Grid: batch tiles then cache chunks INNERMOST, so a layer's weight
    # blocks stay resident in VMEM while every tile/chunk consumes them
    # (one weight DMA per layer per token regardless of stream count).
    x_out, k_new, v_new = pl.pallas_call(
        kernel,
        grid=(n_layers, n_bt, n_tc),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((tile_b, d), lambda l, t, c: (t, 0)),
            pl.BlockSpec((1, tile_b, kn), lambda l, t, c: (l, t, 0)),
            pl.BlockSpec((1, tile_b, kn), lambda l, t, c: (l, t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, d), x.dtype),
            jax.ShapeDtypeStruct((n_layers, b, kn), x.dtype),
            jax.ShapeDtypeStruct((n_layers, b, kn), x.dtype),
        ],
        scratch_shapes=scratches,
        # Double-buffered layer weights (~2x14 MB at GPT-2-small) exceed
        # the 16 MB default scoped-vmem limit; v5e has 128 MB VMEM.
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*args)
    return x_out, k_new, v_new
