"""Flash attention as a Pallas TPU kernel (fwd + custom-VJP bwd).

Not present in the reference (it has no attention at all, SURVEY.md §5.7);
this is the framework's hot-op kernel for the BERT/long-context workloads.
Memory-efficient attention: O(T) memory instead of the O(T^2) logits tensor,
with the online-softmax recurrence.

TPU mapping (pallas_guide.md patterns):

* grid ``(B, H, num_q_blocks, num_k_blocks)`` — the innermost (k) dimension
  iterates sequentially on-core, so the running max/denominator/accumulator
  live in VMEM scratch that persists across k steps; ``@pl.when(ki == 0)``
  initializes, ``@pl.when(ki == nk-1)`` finalizes and writes out;
* all matmuls hit the MXU with ``preferred_element_type=float32``; softmax
  statistics are kept in fp32 even for bf16 inputs;
* causal masking skips fully-masked k blocks via ``@pl.when`` (no wasted
  MXU work past the diagonal) and masks within the diagonal block;
* per-key padding masks (``kv_mask``) enter as a sublane-replicated
  (B, 8, T) additive fp32 bias with a finite mask value — see MASK_VALUE —
  so BERT-style variable-length batches run on the kernel, not a fallback;
* backward = ONE fused kernel producing dq+dk+dv on grid (B, H, nk, nq),
  sharing a single s/p/ds recompute per block pair (the earlier two-kernel
  split recomputed them twice and re-streamed every operand); dq
  accumulates across the outer k loop in a (T, D) fp32 VMEM scratch, so
  differentiable flash has a T-proportional VMEM term (16 MB at T=64k,
  D=64 — the bwd call raises the scoped-vmem limit accordingly);
* softmax statistics are stored lane-slim as (B, H, T, 8) fp32 (a 128-wide
  stats array was ~200 MB of pure replication traffic per BERT-base layer)
  and the kernel outputs carry ``checkpoint_name``s ("flash_out",
  "flash_lse") so the framework's "dots" remat policy saves them instead
  of recomputing the whole forward inside the backward pass.

On CPU (tests / the 8-device simulated mesh) kernels run in interpreter
mode automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
# jax < 0.5 spells pltpu.CompilerParams 'TPUCompilerParams' (same fields).
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

NEG_INF = float("-inf")
# Additive value for padding masks.  Finite on purpose: a k block that is
# entirely padded then yields s = -1e30 everywhere and a *finite* running
# max, so p = exp(0) = 1 briefly over-counts — and the very next block with
# any visible key applies corr = exp(-1e30 - m_real) = 0, zeroing the bogus
# contribution.  -inf would instead produce exp(-inf - -inf) = nan.  Rows
# whose keys are ALL padded are undefined (callers guarantee >=1 visible
# key per row, true for any non-empty sequence).
MASK_VALUE = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _block_sizes(t: int, block_q: int, block_k: int) -> tuple:
    """Largest sublane-aligned divisors of t within the requested sizes.

    T = 768 with 512 requested -> 384; T <= 8 -> T itself (single block).
    Candidates must divide T AND be a multiple of 8 (the fp32 sublane tile
    — odd block heights fail Mosaic lowering on real TPU), so awkward T
    (e.g. primes) raise an actionable error instead of degrading silently.
    """
    def pick(want: int) -> int:
        if t <= 8:
            return t
        for b in range(min(want, t), 7, -1):
            if t % b == 0 and b % 8 == 0:
                return b
        raise ValueError(
            f"seq len {t} has no block size that divides it and is a "
            f"multiple of 8 (<= {want}); pad the sequence")

    return pick(block_q), pick(block_k)


def _causal_mask_block(s, q_start, k_start):
    """Mask s (bq, bk) so query row attends only to keys <= its position."""
    row = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(row >= col, s, NEG_INF)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(*refs, scale, causal, block_q, block_k, has_mask):
    if has_mask:
        q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, acc, m_scr, l_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr = refs
        mask_ref = None
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32)            # (bq, D)
        k = k_ref[0, 0].astype(jnp.float32)            # (bk, D)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(                       # (bq, bk) on MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask_block(s, qi * block_q, ki * block_k)
        if mask_ref is not None:
            s = s + mask_ref[0][:1, :]                 # (1, bk) key bias
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, :1] = corr * l_scr[:, :1] + jnp.sum(p, -1, keepdims=True)
        acc[:] = acc[:] * corr + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_new

    if causal:
        # Skip k blocks entirely above the diagonal.
        pl.when(qi * block_q + block_q - 1 >= ki * block_k)(compute)
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0, 0] = (acc[:] / l).astype(o_ref.dtype)
        # lse stored lane-replicated (bq, 8): rank-3 (B,H,T) blocks of
        # shape (1,1,bq) violate Mosaic's last-two-dims tiling rule on real
        # TPU (second-to-last block dim 1 != H), so the stats array is
        # (B,H,T,8) with legal full-lane-dim (bq,8) blocks.  8 lanes, not
        # 128: at BERT-base shapes a 128-wide stats array was 201 MB/layer
        # of pure replication traffic (written fwd, read bwd, and saved
        # under the remat policy).
        lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l),
                                         lse_ref.shape[2:])


def _mask_bias(kv_mask, t):
    """(B, Tk) bool -> (B, 8, Tk) fp32 additive bias (0 / MASK_VALUE).

    Sublane-replicated to 8 rows so rank-3 blocks (1, 8, bk) satisfy
    Mosaic's last-two-dims tiling rule (same trick as the (bq, 8)
    lane-replicated lse stats)."""
    if kv_mask.shape[-1] != t:
        raise ValueError(
            f"kv_mask last dim {kv_mask.shape[-1]} must equal the key "
            f"length Tk={t} (kv_mask shape {kv_mask.shape})")
    bias = jnp.where(kv_mask, 0.0, MASK_VALUE).astype(jnp.float32)
    return jnp.broadcast_to(bias[:, None, :], (kv_mask.shape[0], 8, t))


def _fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    b, h, t, d = q.shape
    bq, bk = _block_sizes(t, block_q, block_k)
    has_mask = bias is not None
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk, has_mask=has_mask)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
    ]
    args = [q, k, v]
    if has_mask:
        in_specs.append(
            pl.BlockSpec((1, 8, bk), lambda b_, h_, qi, ki: (b_, 0, ki)))
        args.append(bias)
    return pl.pallas_call(
        kernel,
        grid=(b, h, t // bq, t // bk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, bq, 8), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),     # output accumulator
            pltpu.VMEM((bq, 128), jnp.float32),   # running max (col 0)
            pltpu.VMEM((bq, 128), jnp.float32),   # running denom (col 0)
        ],
        interpret=interpret,
    )(*args)


# --------------------------------------------------------------------------
# backward: ONE fused dq+dk+dv kernel on grid (B, H, nk, nq)
# --------------------------------------------------------------------------

def _bwd_kernel(*refs, scale, causal, block_q, block_k, has_mask):
    """Fused dq+dk+dv backward: ONE kernel on grid (b, h, nk, nq).

    The two-kernel version recomputed s/p twice and re-streamed every
    operand twice; at T=512 (single 512-block per head) that meant 2x768
    latency-bound programs and a measured ~28 TF/s backward.  Here every
    cotangent comes from one (bq, bk)-oriented s/p/ds via dot_general
    dimension numbers (no transposes):

        dq[qi] += ds @ k          dk = ds^T q = dot(ds, q, contract bq)
        dv = p^T dO = dot(p, do, contract bq)

    dq accumulates across the OUTER ki loop, so it lives in a full (T, D)
    f32 scratch (131 KB at T=512, 1 MB at T=4096) indexed at the qi
    block; every (ki==nk-1) pass rewrites the dq output blocks with the
    final accumulator (earlier passes emit dead writes — the last pass
    wins, nk is 1 for T <= block_q anyway).
    """
    if has_mask:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, mask_ref,
         dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
         dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc) = refs
        mask_ref = None
    ki, qi = pl.program_id(2), pl.program_id(3)
    nk, nq = pl.num_programs(2), pl.num_programs(3)

    @pl.when((ki == 0) & (qi == 0))
    def _init_dq():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32)            # (bq, D)
        k = k_ref[0, 0].astype(jnp.float32)            # (bk, D)
        v = v_ref[0, 0].astype(jnp.float32)
        o = o_ref[0, 0].astype(jnp.float32)            # (bq, D)
        do = do_ref[0, 0].astype(jnp.float32)          # (bq, D)
        lse = lse_ref[0, 0][:, :1]                     # (bq, 1)
        # delta_i = sum_d dO_id O_id, recomputed per block (elementwise VPU
        # work, cheaper than a third stats array in HBM)
        delta = jnp.sum(do * o, axis=-1, keepdims=True)
        s = jax.lax.dot_general(                       # Q @ K^T: (bq, bk)
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask_block(s, qi * block_q, ki * block_k)
        if mask_ref is not None:
            s = s + mask_ref[0][:1, :]                 # (1, bk)
        p = jnp.exp(s - lse)                           # (bq, bk)
        dp = jax.lax.dot_general(                      # dO @ V^T: (bq, bk)
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        row = pl.ds(qi * block_q, block_q)
        dq_acc[row, :] = dq_acc[row, :] + jax.lax.dot(
            ds, k, preferred_element_type=jnp.float32) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(   # ds^T @ Q: (bk, D)
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(   # p^T @ dO: (bk, D)
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(qi * block_q + block_q - 1 >= ki * block_k)(compute)
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _write_dq():
        dq_ref[0, 0] = dq_acc[pl.ds(qi * block_q, block_q), :].astype(
            dq_ref.dtype)

    @pl.when(qi == nq - 1)
    def _write_dkv():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, bias, do, causal, scale, block_q, block_k,
         interpret):
    b, h, t, d = q.shape
    bq, bk = _block_sizes(t, block_q, block_k)
    has_mask = bias is not None

    # ki outer, qi inner (sequential on-core): dk/dv accumulate over the
    # inner loop; dq accumulates across the outer loop in the (T, D)
    # scratch.
    q_spec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, ki, qi: (b_, h_, qi, 0))
    k_spec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0))
    l_spec = pl.BlockSpec((1, 1, bq, 8), lambda b_, h_, ki, qi: (b_, h_, qi, 0))
    m_spec = pl.BlockSpec((1, 8, bk), lambda b_, h_, ki, qi: (b_, 0, ki))

    in_specs = [q_spec, k_spec, k_spec, q_spec, q_spec, l_spec]
    args = [q, k, v, o, do, lse]
    if has_mask:
        in_specs.append(m_spec)
        args.append(bias)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, has_mask=has_mask),
        grid=(b, h, t // bk, t // bq),
        in_specs=in_specs,
        out_specs=[q_spec, k_spec, k_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, t, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((t, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        # The (T, D) dq accumulator exceeds the 16 MB default scoped-vmem
        # limit for very long sequences (T=64k, D=64 -> 16 MB + blocks).
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*args)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret)
    # Named so a remat policy can SAVE the kernel's outputs: without these,
    # jax.checkpoint recomputes the whole flash forward inside the backward
    # pass to re-produce lse/out (~0.8 ms/layer at BERT-base shapes).  The
    # slim (B,H,T,8) lse makes saving both nearly free.
    from jax.ad_checkpoint import checkpoint_name
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse, bias)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse, bias = res
    dq, dk, dv = _bwd(q, k, v, o, lse, bias, g, causal, scale, block_q,
                      block_k, interpret)
    # bias is a 0/-1e30 mask, not a learnable input: zero cotangent (must
    # still match the primal's pytree structure, so zeros, not None).
    return dq, dk, dv, None if bias is None else jnp.zeros_like(bias)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False, kv_mask=None,
                    scale=None, block_q: int = 512, block_k: int = 512,
                    interpret=None):
    """Flash attention over (B, H, T, D) tensors; returns (B, H, T, D).

    Differentiable (custom VJP with the flash backward kernels).  ``scale``
    defaults to D**-0.5.  T must be divisible by the (clamped) block sizes.
    ``kv_mask`` (B, Tk) bool, True = key visible, masks padded keys for
    every query (composable with ``causal``); rows must keep >=1 visible
    key.  The mask is not differentiated.

    Self-attention only: the kernel's grid tiles one sequence length, so
    Tq must equal Tk (cross-attention uses the XLA path in nn.attention).
    """
    if q.shape[2] != k.shape[2]:
        raise ValueError(
            f"flash_attention is self-attention only (Tq {q.shape[2]} != "
            f"Tk {k.shape[2]}); use nn.attention.dot_product_attention "
            f"for cross-attention")
    if interpret is None:
        interpret = _interpret_default()
    scale = float(scale if scale is not None else q.shape[-1] ** -0.5)
    bias = None if kv_mask is None else _mask_bias(kv_mask, k.shape[2])
    return _flash(q, k, v, bias, causal, scale, block_q, block_k, interpret)


def _as_kv_mask(mask, b, tq, tk):
    """Recognize a key-padding mask broadcastable to (B, H, Tq, Tk) whose
    value depends only on the key position -> (B, Tk) bool, else None."""
    if mask.ndim != 4 or mask.shape[-1] != tk:
        return None
    if mask.shape[1] != 1 or mask.shape[2] != 1:
        return None                       # varies per head or per query
    if mask.shape[0] not in (1, b):
        return None
    return jnp.broadcast_to(mask[:, 0, 0, :], (b, tk))


def require_kv_mask(mask, q, k, impl_name: str):
    """Shared adapter guard: convert an attn_impl ``mask`` to the (B, Tk)
    key-padding form or raise — so every distributed attention impl
    (ring/ulysses) accepts exactly the same mask shapes with the same
    wording.  (flash_attention_impl instead falls back to the XLA path for
    general masks, since it has a local dense equivalent to fall back TO.)
    """
    kv_mask = _as_kv_mask(mask, q.shape[0], q.shape[1], k.shape[1])
    if kv_mask is None:
        raise ValueError(
            f"{impl_name} supports mask=None or key-padding masks of "
            f"shape (B|1, 1, 1, Tk); per-query masks are not supported")
    return kv_mask


def flash_attention_impl(causal: bool = False, block_q: int = 512,
                         block_k: int = 512):
    """Adapter matching MultiHeadAttention's ``attn_impl`` contract:
    f(q, k, v, mask) with (B, T, H, D) layout.

    mask=None and key-padding masks (shape (B|1, 1, 1, Tk) — BERT's
    ``pad_mask[:, None, None, :]``) run on the Pallas kernel; a general
    per-query mask falls back to the XLA path (the kernel's only mask
    primitives are the causal flag and a per-key bias)."""

    def impl(q, k, v, mask=None):
        kv_mask = None
        if mask is not None:
            kv_mask = _as_kv_mask(mask, q.shape[0], q.shape[1], k.shape[1])
            if kv_mask is None:
                from dtf_tpu.nn.attention import dot_product_attention
                if causal:
                    t = q.shape[1]
                    tri = jnp.tril(jnp.ones((t, t), bool))[None, None]
                    mask = mask & tri
                return dot_product_attention(q, k, v, mask)
        out = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                              v.transpose(0, 2, 1, 3), causal=causal,
                              kv_mask=kv_mask,
                              block_q=block_q, block_k=block_k)
        return out.transpose(0, 2, 1, 3)

    return impl
