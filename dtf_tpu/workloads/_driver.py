"""Shared fixed-step pretrain-benchmark driver for the LM workloads.

One implementation of the mesh/sharding setup, the two-step warmup protocol
(first compiles, second settles post-step sharding layouts), the windowed
step timing with ``block_until_ready`` sync points, and the summary line —
used by ``bert_pretrain`` and ``lm`` so the timing methodology cannot
drift between workloads.
"""

from __future__ import annotations

import time
from contextlib import nullcontext as _nullcontext
from typing import Any, Callable, Optional

import jax
import numpy as np


def global_batch_size(cluster, train_cfg) -> int:
    """THE global batch formula — workloads size their datasets with this
    and the driver slices with it, so there is exactly one copy."""
    return (train_cfg.per_device_batch * cluster.num_devices
            if train_cfg.per_device_batch else train_cfg.batch_size)


def pretrain_benchmark(cluster, logger, model, train_cfg, toks,
                       steps: int, *, tokens_per_example: int,
                       throughput_unit: str = "tok",
                       flops_tokens_per_example: Optional[int] = None) -> tuple:
    """Run ``steps`` timed train steps.

    ``toks`` is either an (N, T) int32 array sliced into global batches, or
    a callable ``i -> host batch`` (any pytree the model's loss accepts) —
    the seam that lets every workload share ONE timing methodology
    (two-step untimed compile warmup, windowed ``block_until_ready``
    timing, watchdog, sharding rules).

    Returns (state, metrics, ms_per_step).  Prints the reference step-line
    contract plus a Step-Time/Throughput summary, and — when the chip's
    peak is known — the model FLOPs utilization (MFU) via the standard
    ``6 · params · tokens`` train-step approximation (fwd 2PT + bwd 4PT;
    attention's quadratic term and the embedding gather are ignored, so
    this slightly *understates* at long sequence lengths — remat recompute
    is correctly NOT counted as useful work).
    ``flops_tokens_per_example`` overrides the per-example token count in
    that formula (defaults to the array's T; REQUIRED for callable
    ``toks`` — e.g. src_len + tgt_len for an encoder-decoder).
    """
    from dtf_tpu import optim
    from dtf_tpu.parallel import sharding as sh
    from dtf_tpu.train.metrics import format_step_line
    from dtf_tpu.train.trainer import init_state, make_train_step, put_global_batch
    from dtf_tpu.utils.timing import block

    mesh = cluster.mesh
    global_batch = global_batch_size(cluster, train_cfg)
    rules = (sh.fsdp_rules() if "fsdp" in mesh.axis_names
             else sh.DEFAULT_RULES)
    shardings = sh.apply_rules(model.axes(), mesh, rules)
    # +2: the two untimed compile-warmup steps below also advance the
    # optimizer's schedule counter.
    lr = optim.schedule_from_config(train_cfg, steps + 2)
    opt = optim.get(train_cfg.optimizer)(lr)
    state = init_state(model, opt, seed=train_cfg.seed, mesh=mesh,
                       param_shardings=shardings)
    step_fn = make_train_step(model.loss, opt, mesh,
                              grad_accum=train_cfg.grad_accum)

    rng_base = jax.random.key(train_cfg.seed + 17)

    if callable(toks):
        if flops_tokens_per_example is None:
            raise ValueError("flops_tokens_per_example is required when "
                             "toks is a batch-producing callable")

        def batch_at(i):
            return put_global_batch(mesh, toks(i))
    else:
        n_batches = len(toks) // global_batch

        def batch_at(i):
            j = (i % n_batches) * global_batch
            return put_global_batch(mesh, toks[j:j + global_batch])

    # Fail-fast watchdog (--hang_timeout_s), same contract as Trainer.fit:
    # armed only for the loop, suspended across the compile-heavy warmup.
    watchdog = None
    if train_cfg.hang_timeout_s > 0:
        from dtf_tpu.utils.watchdog import HangWatchdog
        watchdog = HangWatchdog(train_cfg.hang_timeout_s)

    try:
        # two warmup steps (untimed): first compiles, second runs with the
        # settled post-step state shardings (a sharding-layout change after
        # step one can trigger one more compile)
        metrics = {}
        with (watchdog.suspend() if watchdog is not None
              else _nullcontext()):
            for w in range(2):
                state, metrics = step_fn(state, batch_at(w), jax.random.key(w))
                block(state)

        # Active params: MoE models route each token through top_k of E
        # experts, so only a fraction of expert weights do FLOPs per token —
        # models expose active_param_count; dense models use the total.
        if hasattr(model, "active_param_count"):
            n_params = int(model.active_param_count(state["params"]))
        else:
            from dtf_tpu.nn.core import count_params
            n_params = int(count_params(state["params"]))
        flops_tokens = (flops_tokens_per_example if flops_tokens_per_example
                        is not None else toks.shape[1])
        model_flops = 6.0 * n_params * global_batch * flops_tokens

        t0 = time.perf_counter()
        window_t, window_n = t0, 0
        for i in range(steps):
            state, metrics = step_fn(
                state, batch_at(i + 1), jax.random.fold_in(rng_base, i))
            window_n += 1
            if watchdog is not None:
                watchdog.tick()
            if (i + 1) % train_cfg.log_frequency == 0 or i + 1 == steps:
                block(state)
                now = time.perf_counter()
                avg_ms = (now - window_t) * 1000.0 / max(window_n, 1)
                logger.print(format_step_line(
                    int(state["step"]), 1, i + 1, steps,
                    float(metrics["loss"]), avg_ms))
                logger.scalar(int(state["step"]), "cost", float(metrics["loss"]))
                logger.scalar(int(state["step"]), "avg_ms", avg_ms)
                window_t, window_n = now, 0
        block(state)
    finally:
        if watchdog is not None:
            watchdog.close()
    total_s = time.perf_counter() - t0
    ms_per_step = total_s * 1000.0 / steps
    per_s = steps * global_batch * tokens_per_example / total_s
    logger.print("Total Time: %3.2fs" % total_s)
    logger.print(f"Step-Time: {ms_per_step:.2f}ms  "
                 f"Throughput: {per_s:.1f} {throughput_unit}/s  "
                 f"(global batch {global_batch}, mesh {dict(mesh.shape)})")
    tflops_chip = model_flops / mesh.size / (ms_per_step / 1e3) / 1e12
    from dtf_tpu.bench.matmul import peak_flops_per_chip
    # Peak denominator follows the model's compute dtype, not a CLI flag.
    dtype_str = np.dtype(getattr(model.cfg, "dtype", np.float32)).name
    peak = peak_flops_per_chip(mesh.devices.flat[0], dtype_str)
    mfu = (f"  MFU: {tflops_chip * 1e12 / peak * 100.0:.1f}% of "
           f"{dtype_str} peak" if peak else "")
    logger.print(f"Model-Compute: {tflops_chip:.1f} TFLOP/s/chip "
                 f"(6·P·T, {n_params / 1e6:.1f}M active params){mfu}")
    logger.scalar(int(state["step"]), "model_tflops_per_chip", tflops_chip)
    return state, metrics, ms_per_step
