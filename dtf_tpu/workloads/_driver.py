"""Shared pretrain-benchmark driver: Trainer-backed fixed-step runs with
timing/MFU reporting.

The training loop itself is :class:`dtf_tpu.train.trainer.Trainer` — ONE
loop for every model family, so the LM/seq2seq benchmarks get checkpoint/
resume, preemption saves, the hang watchdog, and per-host data sharding
exactly like the MNIST/CIFAR workloads.  This module adds only what a
benchmark needs on top: the two-step untimed compile warmup (first step
compiles, second settles post-step sharding layouts), wall-clock step
timing around ``fit``, and the throughput / model-FLOPs-utilization
summary.
"""

from __future__ import annotations

import time
from typing import Optional

import jax


def global_batch_size(cluster, train_cfg) -> int:
    """THE global batch formula — workloads size their datasets with this
    and the driver slices with it, so there is exactly one copy."""
    return (train_cfg.per_device_batch * cluster.num_devices
            if train_cfg.per_device_batch else train_cfg.batch_size)


def pretrain_benchmark(cluster, logger, model, train_cfg, toks,
                       steps: int, *, tokens_per_example: int,
                       throughput_unit: str = "tok",
                       flops_tokens_per_example: Optional[int] = None) -> tuple:
    """Run ``steps`` timed train steps through the Trainer.

    ``toks`` is either an (N, T) int32 array (wrapped in a TokenDataset —
    shuffled epochs, per-host sharding in multi-process runs) or a callable
    ``i -> host batch`` (any pytree the model's loss accepts).

    Returns (state, metrics, ms_per_step).  Prints the reference step-line
    contract plus a Step-Time/Throughput summary, and — when the chip's
    peak is known — the model FLOPs utilization (MFU) via the standard
    ``6 · params · tokens`` train-step approximation (fwd 2PT + bwd 4PT;
    attention's quadratic term and the embedding gather are ignored, so
    this slightly *understates* at long sequence lengths — remat recompute
    is correctly NOT counted as useful work).
    ``flops_tokens_per_example`` overrides the per-example token count in
    that formula (defaults to the array's T; REQUIRED for callable
    ``toks`` — e.g. src_len + tgt_len for an encoder-decoder).
    """
    from dtf_tpu import optim
    from dtf_tpu.data.datasets import (CallableDataset, DataSplits,
                                       TokenDataset)
    from dtf_tpu.train.trainer import Trainer, put_global_batch
    from dtf_tpu.utils.timing import block

    mesh = cluster.mesh
    global_batch = global_batch_size(cluster, train_cfg)
    # +2: the two untimed compile-warmup steps below also advance the
    # optimizer's schedule counter.
    budget = steps + 2
    lr = optim.schedule_from_config(train_cfg, budget)
    opt = optim.get(train_cfg.optimizer)(lr)

    if callable(toks):
        if flops_tokens_per_example is None:
            raise ValueError("flops_tokens_per_example is required when "
                             "toks is a batch-producing callable")
        train = CallableDataset(toks, global_batch, budget)
    else:
        train = TokenDataset(toks, seed=train_cfg.seed)
    splits = DataSplits(train=train, test=None)
    batch_count = max(train.num_examples // global_batch, 1)
    epochs = -(-budget // batch_count)          # ceil: enough epochs for all

    if train_cfg.chaos:
        # Benchmarks accept --chaos too (the Trainer injects the plan);
        # flag it loudly so a chaos run's numbers are never mistaken for a
        # clean measurement.
        logger.print(f"[dtf_tpu] CHAOS plan active ({train_cfg.chaos}): "
                     f"timings/MFU below include injected faults")
    if train_cfg.straggler_factor > 1.0 and jax.process_count() > 1:
        # Benchmarks inherit straggler detection through the Trainer; the
        # per-host timing allgather at each logging sync point is a small
        # DCN collective the clean numbers don't pay.
        logger.print(
            f"[dtf_tpu] straggler detection active (factor "
            f"{train_cfg.straggler_factor:g}): Step-Time includes the "
            f"per-host timing allgather at logging sync points")
    if train_cfg.max_restarts > 0:
        # An accepted-but-ignored flag would let the user believe the job
        # is supervised when it is not.  Benchmark runs are single-attempt
        # by design (restart-resume would corrupt the timing): the outer
        # scheduler owns restarts here (run with --resume).
        logger.print(
            "[dtf_tpu] WARNING: --max_restarts is not supervised in "
            "benchmark workloads (single attempt; timings would span "
            "restarts) — use the mnist workload or "
            "resilience.run_supervised, or rely on the job scheduler + "
            "--resume")
    trainer = Trainer(cluster, model, opt, train_cfg, logger=logger)

    # Warmup (fresh runs only — a --resume continuation is already
    # compiled-shaped by its restored state and must not re-feed batches):
    # two real trajectory steps, untimed, same per-step rng derivation as
    # Trainer.fit so the overall batch/rng stream is identical to one
    # uninterrupted run.
    rng_base = jax.random.key(train_cfg.seed + 17)
    if trainer._host_step == 0:
        from dtf_tpu import telemetry as _tel
        tracker = _tel.get_tracker()
        for k in range(2):
            batch = put_global_batch(mesh, train.next_batch(global_batch))
            step_rng = jax.random.fold_in(rng_base, trainer._host_step)
            # Warmup 0 pays trace+compile: goodput books it as compile
            # time, and fit() must not re-book its own first step.
            with tracker.measure("compile" if k == 0 else "productive"):
                trainer.state, trainer.last_metrics = trainer.step_fn(
                    trainer.state, batch, step_rng)
                trainer._host_step += 1
                block(trainer.state)
        trainer._compile_seen = True

    if hasattr(model, "active_param_count"):
        n_params = int(model.active_param_count(trainer.state["params"]))
    else:
        from dtf_tpu.nn.core import count_params
        n_params = int(count_params(trainer.state["params"]))
    if hasattr(model, "train_flops_per_example"):
        # Model-accounted FLOPs (e.g. BERT's K-position MLM head runs the
        # vocab projection on K < T positions — 6·P·T would overcount).
        model_flops = (model.train_flops_per_example(trainer.state["params"])
                       * global_batch)
    else:
        flops_tokens = (flops_tokens_per_example if flops_tokens_per_example
                        is not None else toks.shape[1])
        model_flops = 6.0 * n_params * global_batch * flops_tokens

    pre_fit = trainer._host_step
    t0 = time.perf_counter()
    trainer.fit(splits, epochs=epochs, max_steps=budget)
    total_s = time.perf_counter() - t0
    steps_run = max(trainer._host_step - pre_fit, 1)

    metrics = trainer.last_metrics
    if not metrics:
        # Resumed at/past the step budget: no step ran this invocation.
        # Report eval-computed metrics so callers' summary lines still work.
        logger.print(f"[dtf_tpu] resumed at step {trainer._host_step} >= "
                     f"budget {budget}; no further training steps")
        batch = put_global_batch(mesh, train.next_batch(global_batch))
        metrics = jax.jit(model.eval_metrics)(trainer.state["params"], batch)
    ms_per_step = total_s * 1000.0 / steps_run
    examples_per_s = steps_run * global_batch / total_s
    per_s = examples_per_s * tokens_per_example
    logger.print("Total Time: %3.2fs" % total_s)
    logger.print(f"Step-Time: {ms_per_step:.2f}ms  "
                 f"Throughput: {per_s:.1f} {throughput_unit}/s  "
                 f"(global batch {global_batch}, mesh {dict(mesh.shape)})")
    # ONE MFU/throughput formula (telemetry/goodput.py), shared with the
    # Trainer's sync points; also lands the throughput/* and mfu/* gauges
    # in the registry for telemetry.json and the report CLI.  Peak
    # denominator follows the model's compute dtype, not a CLI flag.
    from dtf_tpu import telemetry as tel
    peak, dtype_str = tel.goodput.peak_flops_for_model(
        model, mesh.devices.flat[0])
    thr = tel.goodput.record_throughput(
        examples_per_s=examples_per_s,
        tokens_per_example=tokens_per_example,
        step_ms=ms_per_step,
        model_flops_per_example=model_flops / global_batch,
        n_chips=mesh.size,
        peak_flops_per_chip=peak)
    tflops_chip = thr["model_tflops_per_chip"]
    mfu = (f"  MFU: {thr['mfu_pct']:.1f}% of "
           f"{dtype_str} peak" if thr["mfu_pct"] is not None else "")
    logger.print(f"Model-Compute: {tflops_chip:.1f} TFLOP/s/chip "
                 f"(6·P·T, {n_params / 1e6:.1f}M active params){mfu}")
    logger.scalar(int(trainer.state["step"]), "model_tflops_per_chip",
                  tflops_chip)
    if train_cfg.telemetry and train_cfg.logdir and cluster.is_coordinator:
        # Re-snapshot: the gauges above were set after fit()'s final
        # write.  Best-effort — a full disk must not turn the completed
        # benchmark into a crash.
        try:
            tel.write_telemetry_json(train_cfg.logdir)
        except OSError:
            pass
    return trainer.state, metrics, ms_per_step
