"""T5 encoder-decoder workload: synthetic copy/reverse seq2seq task.

Third model family's runnable entry point (BERT: bert_pretrain, GPT: lm).
Zero-egress: the task is algorithmic (copy or reverse a random token
sequence), so convergence and generation exact-match are measurable
without any dataset.

    python -m dtf_tpu.workloads.seq2seq --task reverse --steps 400
    python -m dtf_tpu.workloads.seq2seq --preset small --bf16 \
        --per_device_batch 16 --mesh data=-1
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    import jax
    import numpy as np

    from dtf_tpu.cluster import bootstrap
    from dtf_tpu.config import ClusterConfig, TrainConfig, build_parser, _from_namespace
    from dtf_tpu.models.t5 import T5, T5Config
    from dtf_tpu.train.metrics import MetricLogger
    from dtf_tpu.workloads._driver import global_batch_size, pretrain_benchmark

    parser = build_parser("dtf_tpu T5 seq2seq (synthetic copy/reverse)")
    parser.add_argument("--preset", choices=["small", "tiny"], default="tiny")
    parser.add_argument("--task", choices=["copy", "reverse"],
                        default="reverse")
    parser.add_argument("--steps", type=int, default=400)
    parser.add_argument("--seq_len", type=int, default=12)
    parser.add_argument("--bf16", action="store_true")
    parser.add_argument("--eval_examples", type=int, default=32,
                        help="held-out sources to decode for exact-match")
    parser.add_argument("--label_smoothing", type=float, default=0.0,
                        help="eps of uniform mass in the CE loss")
    parser.add_argument("--pipeline_microbatches", type=int, default=0,
                        help=">0: pipeline both stacks over the 'pipe' "
                             "mesh axis")
    parser.add_argument("--pipeline_schedule", choices=["gpipe", "1f1b"],
                        default="gpipe",
                        help="1f1b: decoder stack runs the interleaved "
                             "schedule (O(stages) activations), encoder "
                             "keeps GPipe-by-AD")
    parser.add_argument("--loss_chunk", type=int, default=0,
                        help=">0: compute the CE loss in decoder-T "
                             "chunks of this size (never materializes "
                             "the (B,T,V) fp32 logits; backward "
                             "recomputes per chunk)")
    parser.add_argument("--fused_block", action="store_true",
                        help="every encoder/decoder half-block "
                             "(self-attn, cross-attn, FFN) as a fused "
                             "Pallas megakernel (ops/block_kernel.py; "
                             "RMSNorm + relpos bias in-kernel)")
    parser.set_defaults(learning_rate=3e-3)   # task-suited default
    ns = parser.parse_args(argv)
    if (ns.loss_chunk > 0 and ns.pipeline_microbatches > 0
            and ns.pipeline_schedule == "1f1b"):
        parser.error("--loss_chunk has no effect under "
                     "--pipeline_schedule 1f1b (the interleaved schedule "
                     "computes its per-microbatch head loss densely); "
                     "drop one of the two flags")
    cluster_cfg = _from_namespace(ClusterConfig, ns)
    train_cfg = _from_namespace(TrainConfig, ns)

    cluster = bootstrap(cluster_cfg)
    mesh = cluster.mesh
    logger = MetricLogger.for_config(train_cfg, cluster.is_coordinator)

    import jax.numpy as jnp
    dtype = jnp.bfloat16 if ns.bf16 else jnp.float32
    kw = dict(dtype=dtype, max_src_len=max(ns.seq_len, 16),
              max_tgt_len=max(ns.seq_len, 16),
              label_smoothing=ns.label_smoothing,
              fused_block=ns.fused_block, loss_chunk=ns.loss_chunk)
    if ns.pipeline_microbatches > 0:
        kw["pipeline_mesh"] = mesh
        kw["pipeline_microbatches"] = ns.pipeline_microbatches
        kw["pipeline_schedule"] = ns.pipeline_schedule
    cfg = (T5Config.small(**kw) if ns.preset == "small"
           else T5Config.tiny(**kw))
    model = T5(cfg)

    bs = global_batch_size(cluster, train_cfg)

    def batch_at(i):
        # per-index rng: deterministic, identical on every process (the
        # multi-host contract of put_global_batch).  Sequences are padded
        # to the model's max length so the FLOPs accounting
        # (T5.train_flops_per_example, billed at max_src/tgt_len) matches
        # the positions actually processed — pads are masked in the loss
        # and the encoder attention but still run through the matmuls.
        r = np.random.default_rng(train_cfg.seed * 100003 + i)
        src = r.integers(2, cfg.vocab_size, (bs, ns.seq_len)).astype(
            np.int32)
        tgt = src[:, ::-1].copy() if ns.task == "reverse" else src
        pad = cfg.max_src_len - ns.seq_len
        if pad:
            src = np.pad(src, ((0, 0), (0, pad)),
                         constant_values=cfg.pad_id)
            tgt = np.pad(tgt, ((0, 0), (0, pad)),
                         constant_values=cfg.pad_id)
        return {"src": src, "tgt": tgt}

    # shared timing/warmup/sharding methodology (workloads/_driver.py).
    # MFU accounting comes from T5.train_flops_per_example (each stack's
    # params x its own side's tokens — 6·P_total·2T would double-count);
    # the flops_tokens value below is only the fallback for models
    # without the method.
    state, m, _ = pretrain_benchmark(
        cluster, logger, model, train_cfg, batch_at, ns.steps,
        tokens_per_example=1, throughput_unit="seq",
        flops_tokens_per_example=ns.seq_len)
    if "accuracy" in m:           # 1F1B reduces only the loss
        logger.print(f"Teacher-forced accuracy: {float(m['accuracy']):.4f}")
    rng = np.random.default_rng(train_cfg.seed + 999)

    # held-out generation: exact sequence match
    n_eval = ns.eval_examples
    src = rng.integers(2, cfg.vocab_size, (n_eval, ns.seq_len)).astype(
        np.int32)
    want = src[:, ::-1] if ns.task == "reverse" else src
    gen_fn = jax.jit(lambda p, s: model.generate(p, s, ns.seq_len,
                                                 temperature=0.0))
    gen = gen_fn(state["params"], jnp.asarray(src))
    exact = float((np.asarray(gen) == want).all(axis=1).mean())
    logger.print(f"Generation exact-match: {exact:.2f} "
                 f"({n_eval} held-out {ns.task} sequences)")
    if cluster.is_coordinator:
        print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
