"""ResNet-50 / CIFAR-10 sync all-reduce training (BASELINE.md config row).

The reference has no conv workload; this is the "ResNet-50 / CIFAR-10 sync
all-reduce" north-star config from BASELINE.json, run with the same driver
contract as the MNIST workload (console step lines, per-epoch test accuracy):

    python -m dtf_tpu.workloads.cifar [--epochs 10] [--mesh data=-1]
        [--batch_size 256] [--learning_rate 0.1]
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    from dtf_tpu import optim
    from dtf_tpu.cluster import bootstrap
    from dtf_tpu.config import ClusterConfig, TrainConfig, build_parser, _from_namespace
    from dtf_tpu.data import load_cifar10
    from dtf_tpu.models.resnet import ResNet, ResNetConfig
    from dtf_tpu.train.trainer import Trainer

    parser = build_parser("dtf_tpu ResNet-50/CIFAR-10 (BASELINE.json config)")
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--arch", choices=["resnet50", "tiny"],
                        default="resnet50",
                        help="tiny = 2-stage test model (CPU-friendly)")
    parser.add_argument("--data_dir", default="cifar-10-batches-py",
                        help="directory with the CIFAR-10 pickle batches "
                             "(real or dtf_tpu.data.fixtures-written); "
                             "synthetic fallback when absent")
    parser.set_defaults(batch_size=256, learning_rate=0.1, epochs=10)
    ns = parser.parse_args(argv)
    cluster_cfg = _from_namespace(ClusterConfig, ns)
    train_cfg = _from_namespace(TrainConfig, ns)

    cluster = bootstrap(cluster_cfg)
    splits = load_cifar10(ns.data_dir, seed=train_cfg.seed)
    if splits.synthetic and cluster.is_coordinator:
        print("[dtf_tpu] cifar-10-batches-py/ not found; using deterministic "
              "synthetic data (zero-egress environment)")

    model = ResNet(ResNetConfig.resnet50() if ns.arch == "resnet50"
                   else ResNetConfig.tiny())
    from dtf_tpu.workloads._driver import global_batch_size
    bs = global_batch_size(cluster, train_cfg)
    total_steps = (splits.train.num_examples // bs) * train_cfg.epochs
    lr = optim.schedule_from_config(train_cfg, total_steps)
    # --optimizer overrides this workload's default (SGD+momentum); the
    # momentum path always honors --momentum.
    if ns.optimizer and ns.optimizer != "momentum":
        opt = optim.get(train_cfg.optimizer)(lr)
    else:
        opt = optim.momentum(lr, beta=ns.momentum)
    if train_cfg.max_restarts > 0:
        # Self-healing mode: resilience.run_supervised_fit owns the
        # shared-plan / fresh-trainer-per-attempt / resume mechanics.
        from dtf_tpu.resilience import run_supervised_fit
        run_supervised_fit(
            lambda cfg, plan: Trainer(cluster, model, opt, cfg, chaos=plan),
            lambda: load_cifar10(ns.data_dir, seed=train_cfg.seed),
            train_cfg, max_restarts=train_cfg.max_restarts,
            chaos=train_cfg.chaos, initial_splits=splits)
    else:
        trainer = Trainer(cluster, model, opt, train_cfg)
        trainer.fit(splits)
    if cluster.is_coordinator:
        print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
