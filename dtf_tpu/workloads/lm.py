"""GPT causal-LM pretraining benchmark + generation demo.

Decoder-only counterpart of ``bert_pretrain`` (the reference has no sequence
models; this extends the framework's model families):

    python -m dtf_tpu.workloads.lm --preset tiny --steps 20
    python -m dtf_tpu.workloads.lm --preset gpt2_small --bf16 --remat \
        --per_device_batch 8 --mesh data=-1
    python -m dtf_tpu.workloads.lm --preset tiny --steps 20 --generate 32
"""

from __future__ import annotations

import sys
import time

# Held-out generation prompt width (tokens), shared by the parse-time
# fused-decode pre-check and the actual prompt slice so they cannot drift.
PROMPT_LEN = 8


def main(argv=None) -> int:
    import jax.numpy as jnp
    import numpy as np

    from dtf_tpu.cluster import bootstrap
    from dtf_tpu.config import ClusterConfig, TrainConfig, build_parser, _from_namespace
    from dtf_tpu.data.datasets import synthetic_text
    from dtf_tpu.models.gpt import GPT, GPTConfig
    from dtf_tpu.ops.decode_kernel import MAX_FUSED_STREAMS, STREAM_TILE
    from dtf_tpu.train.metrics import MetricLogger
    from dtf_tpu.utils.timing import block
    from dtf_tpu.workloads._driver import global_batch_size, pretrain_benchmark

    parser = build_parser("dtf_tpu GPT causal-LM pretrain")
    parser.add_argument("--preset", choices=["gpt2_small", "llama", "tiny"],
                        default="gpt2_small",
                        help="llama = GPT-2-small scale with RoPE + GQA(4) "
                             "+ SwiGLU")
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--seq_len", type=int, default=None)
    parser.add_argument("--bf16", action="store_true")
    parser.add_argument("--remat", action="store_true")
    parser.add_argument("--remat_policy",
                        choices=["full", "dots", "attn"],
                        default="full",
                        help="with --remat: 'dots' saves matmul outputs, "
                             "recomputing only elementwise work")
    parser.add_argument("--loss_chunk", type=int, default=0,
                        help=">0: compute the CE loss in T-chunks of this "
                             "size (never materializes the (B,T,V) fp32 "
                             "logits; backward recomputes per chunk)")
    parser.add_argument("--pipeline_microbatches", type=int, default=0,
                        help=">0: pipeline the decoder stack over the "
                             "'pipe' mesh axis")
    parser.add_argument("--pipeline_schedule", choices=["gpipe", "1f1b"],
                        default="gpipe",
                        help="gpipe: forward pipeline + AD backward; "
                             "1f1b: interleaved fwd/bwd, O(stages) "
                             "activation memory")
    parser.add_argument("--layer_loop", choices=["scan", "unroll"],
                        default="scan",
                        help="'unroll' trades compile time for ~15%% "
                             "faster steps (remat saves become plain "
                             "buffers instead of scan-stacked slices)")
    parser.add_argument("--attn", choices=["auto", "flash", "xla"],
                        default="auto",
                        help="inner attention: pallas flash kernel vs XLA "
                             "softmax attention (auto = flash on TPU)")
    parser.add_argument("--matmul_dtype",
                        choices=["fp32", "bf16", "int8", "fp8"],
                        default="fp32",
                        help="training-forward compute format for the "
                             "block projections (nn/lowp.py): int8/fp8 "
                             "quantize per channel with a straight-"
                             "through backward; quality-gate with "
                             "bench.int8_quality --trajectory")
    parser.add_argument("--fused_block", action="store_true",
                        help="run each decoder block as two fused Pallas "
                             "megakernels (attention + MLP halves; "
                             "ops/block_kernel.py) for the TRAIN step — "
                             "generation keeps its own decode paths")
    parser.add_argument("--generate", type=int, default=0, metavar="N",
                        help="after training, generate N tokens from a "
                             "held-out prompt (KV-cache decode)")
    parser.add_argument("--gen_batch", type=int, default=1,
                        help="decode this many streams at once (the "
                             "serving-throughput axis: weights stream "
                             "once per step regardless of batch)")
    parser.add_argument("--decode_fused", action="store_true",
                        help=f"decode through the fused stack kernel "
                             f"(ops/decode_kernel.py): ONE pallas_call "
                             f"per token instead of the op-per-op layer "
                             f"scan (gen_batch x max(beam_size, 1) <= "
                             f"{MAX_FUSED_STREAMS}; beyond {STREAM_TILE} "
                             f"streams, a multiple of {STREAM_TILE})")
    parser.add_argument("--decode_kv_int8", action="store_true",
                        help="int8-quantize the KV cache rows (fused "
                             "decode only): halves the per-token cache "
                             "DMA, the dominant traffic at batched "
                             "long-context decode")
    parser.add_argument("--decode_int8", action="store_true",
                        help="int8-quantize the decode weights (per "
                             "output channel): half the HBM weight "
                             "traffic per token")
    parser.add_argument("--temperature", type=float, default=0.0,
                        help="sampling temperature (0 = greedy)")
    parser.add_argument("--top_k", type=int, default=0,
                        help="keep only the k most likely tokens (0 = all)")
    parser.add_argument("--top_p", type=float, default=1.0,
                        help="nucleus sampling mass (1.0 = all)")
    parser.add_argument("--beam_size", type=int, default=0,
                        help=">1: deterministic beam search instead of "
                             "sampling")
    parser.add_argument("--label_smoothing", type=float, default=0.0,
                        help="eps of uniform mass in the CE loss")
    ns = parser.parse_args(argv)
    if (ns.loss_chunk > 0 and ns.pipeline_microbatches > 0
            and ns.pipeline_schedule == "1f1b"):
        parser.error("--loss_chunk has no effect under "
                     "--pipeline_schedule 1f1b (the interleaved schedule "
                     "computes its per-microbatch head loss densely); "
                     "drop one of the two flags")
    # Decode-mode flag validation; the full fused-decode precondition set
    # runs once, post-model-construction, via _check_fused_decode below.
    if ns.decode_kv_int8 and not ns.decode_fused:
        parser.error("--decode_kv_int8 requires --decode_fused (the "
                     "op-per-op loop keeps the fp cache)")
    cluster_cfg = _from_namespace(ClusterConfig, ns)
    train_cfg = _from_namespace(TrainConfig, ns)

    cluster = bootstrap(cluster_cfg)
    logger = MetricLogger.for_config(train_cfg, cluster.is_coordinator)

    kw = {"dtype": jnp.bfloat16 if ns.bf16 else jnp.float32,
          "remat": ns.remat, "remat_policy": ns.remat_policy,
          "layer_loop": ns.layer_loop, "fused_block": ns.fused_block,
          "label_smoothing": ns.label_smoothing,
          "loss_chunk": ns.loss_chunk,
          "matmul_dtype": ns.matmul_dtype}
    if ns.attn != "auto":
        kw["use_flash"] = ns.attn == "flash"
    if ns.seq_len:
        kw["max_len"] = ns.seq_len
    if ns.pipeline_microbatches > 0:
        kw["pipeline_mesh"] = cluster.mesh
        kw["pipeline_microbatches"] = ns.pipeline_microbatches
        kw["pipeline_schedule"] = ns.pipeline_schedule
    cfg = GPTConfig.from_preset(ns.preset, **kw)
    model = GPT(cfg)
    if ns.generate > 0:
        # Validate the exact generation this run will attempt BEFORE the
        # training run, not after it: window overflow for any decode
        # mode, plus the full fused-decode precondition set (stream
        # count, pipeline, 8-aligned cache window — models/gpt.py
        # _check_fused_decode).
        total = PROMPT_LEN + ns.generate
        if total > cfg.max_len:
            parser.error(f"--generate {ns.generate}: prompt+new = {total} "
                         f"exceeds max_len {cfg.max_len} (raise --seq_len "
                         f"or generate fewer tokens)")
        if ns.decode_fused:
            try:
                model._check_fused_decode(
                    ns.gen_batch * max(ns.beam_size, 1), total)
            except ValueError as exc:
                parser.error(str(exc))

    global_batch = global_batch_size(cluster, train_cfg)
    toks = synthetic_text(max(global_batch * 8, 256), cfg.max_len,
                          cfg.vocab_size, seed=train_cfg.seed)

    state, metrics, _ = pretrain_benchmark(
        cluster, logger, model, train_cfg, toks, ns.steps,
        tokens_per_example=cfg.max_len - 1, throughput_unit="tok")
    if "perplexity" in metrics:   # 1F1B reduces only the loss
        logger.print(f"Perplexity: {float(metrics['perplexity']):.2f}")

    if ns.generate > 0:
        import jax

        prompt = jnp.asarray(toks[:ns.gen_batch, :PROMPT_LEN])
        if ns.beam_size > 1:
            gen = jax.jit(lambda p, pr, key: model.beam_search(
                p, pr, ns.generate, beam_size=ns.beam_size,
                int8_weights=ns.decode_int8, fused=ns.decode_fused,
                kv_int8=ns.decode_kv_int8)[0][:, 0])
        else:
            gen = jax.jit(lambda p, pr, key: model.generate(
                p, pr, ns.generate, temperature=ns.temperature,
                top_k=ns.top_k, top_p=ns.top_p, rng=key,
                int8_weights=ns.decode_int8, fused=ns.decode_fused,
                kv_int8=ns.decode_kv_int8))
        t0 = time.perf_counter()
        out = gen(state["params"], prompt, jax.random.key(0))
        block(out)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = gen(state["params"], prompt, jax.random.key(1))
        block(out)
        dt = time.perf_counter() - t0
        logger.print(f"Generated: {np.asarray(out[0]).tolist()}")
        agg = ns.generate * prompt.shape[0] / dt
        per = (f" ({agg / prompt.shape[0]:.1f}/stream x "
               f"{prompt.shape[0]} streams)" if prompt.shape[0] > 1 else "")
        logger.print(f"Decode: {agg:.1f} tok/s steady-state{per} "
                     f"(first call incl. compile: {compile_s:.1f}s)")
    if cluster.is_coordinator:
        print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
