"""MNIST MLP data-parallel training — the reference's main workload.

Reference: ``python tf_distributed.py --job_name=worker --task_index=k``
(async PS SGD, 1 PS + 5 workers, tf_distributed.py).  Here:

    python -m dtf_tpu.workloads.mnist [--epochs 20] [--mesh data=-1]
        [--job_name worker --task_index k --coordinator_address h:p
         --num_processes N]           # multi-host
        [--mode explicit]             # literal psum shard_map step
        [--grad_sync zero1]           # ZeRO-1 weight-update sharding:
                                      # sharded optimizer state + bucketed
                                      # reduce-scatter (DESIGN.md §4.1)
        [--prefetch N]                # async device-prefetch depth
                                      # (default 2; 0 = serial feed)
        [--compile_cache DIR]         # persistent XLA compile cache:
                                      # restarts reuse executables

Same architecture/hyperparams (784-100-10 sigmoid/softmax, SGD lr 5e-4,
batch 100, seed 1) and the same console log contract.
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    from dtf_tpu import optim
    from dtf_tpu.cluster import bootstrap
    from dtf_tpu.config import ClusterConfig, TrainConfig, build_parser, _from_namespace
    from dtf_tpu.data import load_mnist
    from dtf_tpu.models.mlp import MnistMLP
    from dtf_tpu.train.trainer import Trainer

    parser = build_parser("dtf_tpu MNIST MLP (reference: tf_distributed.py)")
    parser.add_argument("--mode", choices=["implicit", "explicit"],
                        default="implicit",
                        help="gradient sync: GSPMD-inserted (implicit) or "
                             "shard_map+psum (explicit)")
    parser.add_argument("--native_loader", action="store_true",
                        help="serve train batches through the C++ "
                             "prefetching loader (dtf_tpu/native)")
    parser.add_argument("--data_dir", default="MNIST_data",
                        help="directory with the IDX files (real MNIST or "
                             "dtf_tpu.data.fixtures-written); synthetic "
                             "fallback when absent")
    parser.add_argument("--init", choices=["reference", "fan_in"],
                        default="reference",
                        help="weight init: the reference's N(0,1) "
                             "(tf.random_normal — saturates the sigmoid "
                             "layer, which freezes it into a random-"
                             "feature model that cannot learn the "
                             "multimodal synthetic task) or fan-in "
                             "scaled")
    parser.add_argument("--grad_compression", choices=["int8"], default=None,
                        help="int8-wire ring all-reduce for gradient sync "
                             "(requires --mode explicit)")
    ns = parser.parse_args(argv)
    cluster_cfg = _from_namespace(ClusterConfig, ns)
    train_cfg = _from_namespace(TrainConfig, ns)

    from dtf_tpu.workloads._driver import global_batch_size

    cluster = bootstrap(cluster_cfg)
    # The native prefetcher needs the trainer's GLOBAL batch size (fixed
    # shapes): per_device_batch scales by the device count.
    global_batch = global_batch_size(cluster, train_cfg)
    # Supervised mode loads a FRESH dataset per attempt inside fit_once;
    # this load then only sizes total_steps, so don't spin up a C++
    # prefetcher that would never be consumed.
    supervised = train_cfg.max_restarts > 0
    splits = load_mnist(
        ns.data_dir, seed=train_cfg.seed,
        native_train_batch=(global_batch if ns.native_loader
                            and not supervised else None))
    if splits.synthetic and cluster.is_coordinator:
        print("[dtf_tpu] MNIST_data/ not found; using deterministic "
              "synthetic data (zero-egress environment)")

    model = MnistMLP(init_scale=ns.init)
    total_steps = (splits.train.num_examples // global_batch) * train_cfg.epochs
    lr = optim.schedule_from_config(train_cfg, total_steps)
    # --optimizer overrides the reference's SGD (tf_distributed.py:73).
    opt = (optim.get(train_cfg.optimizer)(lr) if ns.optimizer
           else optim.sgd(lr))

    if supervised:
        # Self-healing mode: retryable crashes and SIGTERM preemptions
        # restore the last checkpoint and go again, under a bounded
        # restart budget (resilience.run_supervised_fit owns the
        # shared-plan / fresh-trainer-per-attempt mechanics).  Terminal
        # failures — TrainingDiverged, checkpoint schema mismatches —
        # fail fast (supervisor.classify_exit).
        from dtf_tpu.resilience import run_supervised_fit
        result = run_supervised_fit(
            lambda cfg, plan: Trainer(
                cluster, model, opt, cfg, mode=ns.mode,
                grad_compression=ns.grad_compression, chaos=plan),
            lambda: load_mnist(
                ns.data_dir, seed=train_cfg.seed,
                native_train_batch=(global_batch if ns.native_loader
                                    else None)),
            train_cfg, max_restarts=train_cfg.max_restarts,
            chaos=train_cfg.chaos,
            # The sizing load above skipped the native prefetcher, so it
            # can seed attempt 0 only on the pure-Python path.
            initial_splits=None if ns.native_loader else splits)
    else:
        trainer = Trainer(cluster, model, opt, train_cfg, mode=ns.mode,
                          grad_compression=ns.grad_compression)
        result = trainer.fit(splits)
    if cluster.is_coordinator:
        print("done")   # tf_distributed.py:131
    return 0


if __name__ == "__main__":
    sys.exit(main())
