"""BERT masked-LM pretraining benchmark (BASELINE.md config row
"BERT-base data-parallel pretrain").

Synthetic Markov token streams (zero-egress environment), fixed-step
benchmark loop with the reference's console contract and honest
``block_until_ready`` step timing.  Parallelism comes from the mesh spec:

    python -m dtf_tpu.workloads.bert_pretrain --preset tiny --steps 20
    python -m dtf_tpu.workloads.bert_pretrain --preset base \
        --mesh data=4,fsdp=2 --per_device_batch 8 --bf16

FSDP weight sharding activates automatically when the mesh has an ``fsdp``
axis; sequence parallelism via ``--ring_attention`` or ``--ulysses``
(requires a ``seq`` axis); pipeline stages via ``--pipeline_microbatches``
(requires ``pipe``).
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    from dtf_tpu.cluster import bootstrap
    from dtf_tpu.config import ClusterConfig, TrainConfig, build_parser, _from_namespace
    from dtf_tpu.data.datasets import synthetic_text
    from dtf_tpu.models.bert import BertConfig, BertMLM
    from dtf_tpu.train.metrics import MetricLogger
    from dtf_tpu.workloads._driver import global_batch_size, pretrain_benchmark

    parser = build_parser("dtf_tpu BERT MLM pretrain (BASELINE.json config)")
    parser.add_argument("--preset", choices=["base", "tiny"], default="base")
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--seq_len", type=int, default=None)
    parser.add_argument("--bf16", action="store_true",
                        help="bfloat16 activations/weights (MXU native)")
    parser.add_argument("--remat", action="store_true",
                        help="recompute encoder activations in backward "
                             "(jax.checkpoint): less HBM, ~30%% more FLOPs")
    parser.add_argument("--remat_policy",
                        choices=["full", "dots", "attn"],
                        default="full",
                        help="with --remat: 'dots' saves matmul outputs and "
                             "recomputes only elementwise work (most of the "
                             "memory win at a few %% recompute); 'attn' "
                             "saves only the flash kernel outputs — the "
                             "fastest measured policy at BERT-base on "
                             "v5e (BASELINE.md round 3)")
    parser.add_argument("--layer_loop", choices=["scan", "unroll"],
                        default="scan",
                        help="'unroll' trades compile time for ~15%% "
                             "faster steps (remat saves become plain "
                             "buffers instead of scan-stacked slices)")
    parser.add_argument("--attn", choices=["auto", "flash", "xla"],
                        default="auto",
                        help="inner attention: pallas flash kernel (mask-"
                             "capable) vs XLA softmax (auto = flash on TPU)")
    parser.add_argument("--fused_block", action="store_true",
                        help="run each encoder block as two fused Pallas "
                             "megakernels (attention + MLP halves; "
                             "ops/block_kernel.py) — qkv and the MLP "
                             "hidden never touch HBM")
    parser.add_argument("--ring_attention", action="store_true",
                        help="sequence-parallel ring attention over 'seq'")
    parser.add_argument("--ulysses", action="store_true",
                        help="all-to-all (ulysses) sequence parallelism "
                             "over 'seq'; local attention uses the flash "
                             "kernel")
    parser.add_argument("--pipeline_microbatches", type=int, default=0,
                        help=">0: pipeline the encoder over the 'pipe' axis")
    parser.add_argument("--pipeline_schedule", choices=["gpipe", "1f1b"],
                        default="gpipe",
                        help="gpipe: fwd pipeline + AD backward; 1f1b: "
                             "interleaved fwd/bwd (O(stages) activations; "
                             "needs --mlm_predictions > 0)")
    parser.add_argument("--moe_experts", type=int, default=0,
                        help=">0: MoE FFN with this many experts "
                             "(expert-parallel over the 'expert' axis)")
    parser.add_argument("--mlm_predictions", type=int, default=None,
                        help="fixed masked positions per sequence (the "
                             "standard max_predictions_per_seq recipe: "
                             "head + vocab projection run on K, not T, "
                             "positions).  Default: ~15%% of seq_len "
                             "rounded to 8 for preset base; 0 = dense "
                             "head over every position")
    ns = parser.parse_args(argv)
    cluster_cfg = _from_namespace(ClusterConfig, ns)
    train_cfg = _from_namespace(TrainConfig, ns)

    cluster = bootstrap(cluster_cfg)
    mesh = cluster.mesh
    logger = MetricLogger.for_config(train_cfg, cluster.is_coordinator)

    import jax.numpy as jnp
    dtype = jnp.bfloat16 if ns.bf16 else jnp.float32
    kw = {}
    if ns.attn != "auto":
        kw["use_flash"] = ns.attn == "flash"
    if ns.seq_len:
        kw["max_len"] = ns.seq_len
    if ns.ring_attention and ns.ulysses:
        parser.error("--ring_attention and --ulysses are mutually exclusive")
    if ns.ring_attention:
        from dtf_tpu.ops.ring_attention import ring_attention_impl
        kw["attn_impl"] = ring_attention_impl(mesh)
    if ns.ulysses:
        from dtf_tpu.ops.flash_attention import flash_attention_impl
        from dtf_tpu.ops.ulysses_attention import ulysses_attention_impl
        kw["attn_impl"] = ulysses_attention_impl(
            mesh, inner=flash_attention_impl())
    if ns.pipeline_microbatches > 0:
        kw["pipeline_mesh"] = mesh
        kw["pipeline_microbatches"] = ns.pipeline_microbatches
        kw["pipeline_schedule"] = ns.pipeline_schedule
    if ns.remat:
        kw["remat"] = True
        kw["remat_policy"] = ns.remat_policy
    if ns.layer_loop != "scan":
        kw["layer_loop"] = ns.layer_loop
    if ns.moe_experts > 0:
        kw["moe_experts"] = ns.moe_experts
    if ns.fused_block:
        kw["fused_block"] = True
    if ns.mlm_predictions is not None:
        kw["mlm_predictions"] = ns.mlm_predictions
    elif ns.preset == "base":
        # standard BERT recipe: ~15% of positions, lane-friendly multiple
        seq = ns.seq_len or 512
        kw["mlm_predictions"] = max(8, int(seq * 0.15) // 8 * 8)
    cfg = (BertConfig(dtype=dtype, **kw) if ns.preset == "base"
           else BertConfig.tiny(dtype=dtype, **kw))
    model = BertMLM(cfg)

    global_batch = global_batch_size(cluster, train_cfg)
    toks = synthetic_text(max(global_batch * 8, 256), cfg.max_len,
                          cfg.vocab_size, seed=train_cfg.seed)

    state, metrics, _ = pretrain_benchmark(
        cluster, logger, model, train_cfg, toks, ns.steps,
        tokens_per_example=1, throughput_unit="seq")
    if "accuracy" in metrics:     # 1F1B reduces only the loss
        logger.print(f"MLM-Accuracy: {float(metrics['accuracy']):.4f}")
    if cluster.is_coordinator:
        print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
