"""Runnable workload entry points (the reference's scripts, re-done).

Each preserves the reference CLI (``--job_name``, ``--task_index``) plus the
framework's topology flags; zero flags runs single-process on local devices.
"""
