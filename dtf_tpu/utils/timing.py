"""Honest timing under JAX's async dispatch.

The reference timed steps with ``time.time()`` around a synchronous
``sess.run`` (tf_distributed.py:94,100,116-117) — correct for TF1's blocking
session but wrong for JAX, where dispatch returns before the TPU finishes
(SURVEY.md §5.1).  Every timer here blocks on device completion
(``block_until_ready``) before reading the clock.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Any, Callable, Sequence

import jax
import numpy as np


def block(tree: Any) -> Any:
    """Block until every array in a pytree is computed on device.

    On tunneled/relay platforms (e.g. this image's 'axon' TPU relay),
    ``block_until_ready`` can return before the device finishes; pulling one
    scalar to the host is the only reliable completion barrier, so we do
    both.  The scalar pull touches a single element (one shard), not the
    whole array.
    """
    jax.block_until_ready(tree)
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if isinstance(x, jax.Array)]
    if leaves:
        x = leaves[0]
        idx = (0,) * x.ndim
        np.asarray(jax.device_get(x[idx] if x.ndim else x))
    return tree


@dataclasses.dataclass
class Timing:
    """Wall-clock measurements of a device computation, seconds."""

    times_s: tuple
    warmup_s: float          # first (compile-inclusive) call

    @property
    def median_s(self) -> float:
        return statistics.median(self.times_s)

    @property
    def best_s(self) -> float:
        return min(self.times_s)

    @property
    def mean_s(self) -> float:
        return statistics.fmean(self.times_s)


def time_fn(fn: Callable[[], Any], *, iters: int = 10, warmup: int = 1) -> Timing:
    """Time ``fn`` (a nullary closure over device arrays), blocking each call.

    The first call includes XLA compilation; it is recorded separately as
    ``warmup_s`` and never mixed into the steady-state stats.
    """
    t0 = time.perf_counter()
    block(fn())
    warmup_s = time.perf_counter() - t0
    for _ in range(max(warmup - 1, 0)):
        block(fn())
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        block(fn())
        times.append(time.perf_counter() - t0)
    return Timing(times_s=tuple(times), warmup_s=warmup_s)


@dataclasses.dataclass
class LinFit:
    """Per-iteration device time from a linear fit of chain length -> time."""

    per_iter_s: float        # slope
    overhead_s: float        # intercept (host/dispatch/relay constant)
    points: tuple            # (iters, best_time_s) pairs


def time_linfit(fn_of_iters: Callable[[int], Callable[[], Any]],
                iters_ladder: Sequence[int], *, reps: int = 4) -> LinFit:
    """Marginal per-iteration device time, free of fixed host/dispatch/relay
    overhead, via least squares over several chain lengths.

    ``fn_of_iters(k)`` must return a nullary closure running ``k`` chained
    iterations in one compiled program.  For each ladder entry the best of
    ``reps`` timed calls is kept (the relay's host-sync cost is ~50-80 ms
    with jitter of the same order, so a simple two-point difference is far
    too noisy — SURVEY.md §6.1's "honest timing" requirement).
    """
    points = []
    for k in iters_ladder:
        t = time_fn(fn_of_iters(k), iters=reps, warmup=1).best_s
        points.append((k, t))
    xs = np.array([p[0] for p in points], dtype=np.float64)
    ys = np.array([p[1] for p in points], dtype=np.float64)
    A = np.vstack([xs, np.ones_like(xs)]).T
    (slope, intercept), *_ = np.linalg.lstsq(A, ys, rcond=None)
    return LinFit(per_iter_s=float(max(slope, 1e-12)),
                  overhead_s=float(intercept), points=tuple(points))


class StepTimer:
    """Running per-step timer reproducing the reference's AvgTime contract.

    The reference printed ``AvgTime: elapsed/frequency`` ms per batch every
    ``frequency`` steps (tf_distributed.py:116-122) and cumulative
    ``Total Time`` at the end (:127).
    """

    def __init__(self) -> None:
        self.start = time.perf_counter()
        self._window_start = self.start

    def window_avg_ms(self, steps: int) -> float:
        """Average ms/step since the last call (the reference's AvgTime)."""
        now = time.perf_counter()
        avg = (now - self._window_start) * 1000.0 / max(steps, 1)
        self._window_start = now
        return avg

    def total_s(self) -> float:
        return time.perf_counter() - self.start
