"""Profiling and cross-process determinism checks.

Tracing (SURVEY.md §5.1): the reference's only observability was wall-clock
prints (tf_distributed.py:116-122).  Here the framework exposes the XLA
profiler: ``trace()`` captures a TensorBoard/Perfetto trace of a step window
and ``start_server()`` opens the live-capture port.  The trainer hooks these
via TrainConfig.profile_dir / profile_steps.

Determinism (SURVEY.md §5.2): the reference's async PS *embraced* races
(stale gradients were the design); SPMD psum is race-free by construction,
and the moral equivalent of a race detector is checking that every process
computes bitwise-identical results each step.  ``fingerprint()`` +
``assert_replicas_agree()`` implement that cross-host check.
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture an XLA profiler trace into ``logdir`` (TensorBoard's profile
    plugin / Perfetto read it)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """Start the live-capture profiler server (tensorboard can connect)."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a host-side region in the trace (TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield


class StepWindowProfiler:
    """Capture one XLA trace over a window of training steps.

    Owns the start/stop lifecycle so the trainer can't leak an open trace:
    ``after_step(h)`` starts once h enters [start, start+steps) and stops
    when it leaves; ``close()`` stops unconditionally (end of training
    before the window completes).  A resume past the window records
    nothing; the window never restarts.
    """

    def __init__(self, logdir: str, start: int, steps: int):
        self.logdir = logdir
        self.start = start
        self.end = start + steps
        self.active = False
        self.done = False

    def after_step(self, host_step: int, state: Any = None) -> None:
        if self.done:
            return
        if not self.active and self.start <= host_step < self.end:
            jax.profiler.start_trace(self.logdir)
            self.active = True
        elif self.active and host_step >= self.end:
            self._stop(state)

    def close(self, state: Any = None) -> None:
        if self.active:
            self._stop(state)
        self.done = True

    def _stop(self, state: Any) -> None:
        if state is not None:
            jax.block_until_ready(state)   # trace covers real device work
        jax.profiler.stop_trace()
        self.active = False
        self.done = True


def fingerprint(tree: Any) -> np.ndarray:
    """Order-stable 32-bit digest of a pytree of arrays.

    Bitwise (CRC over raw bytes, not float sums), so it detects even
    ULP-level divergence across processes.  For multi-process arrays only
    the first locally-addressable shard is hashed — meaningful for
    REPLICATED values (loss, metrics, step, unsharded params), where every
    process should hold identical bytes; a data/fsdp-sharded leaf holds
    legitimately different shards per process and must not be passed here.
    """
    import zlib

    acc = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            a = np.asarray(leaf.addressable_shards[0].data)
        else:
            a = np.asarray(leaf)
        acc = zlib.crc32(np.ascontiguousarray(a).tobytes(), acc)
    return np.asarray([acc], np.uint32)


def assert_replicas_agree(tree: Any, what: str = "state") -> None:
    """Verify every process holds a bitwise-identical (replicated) ``tree``.

    Single-process: no-op (early return before any device sync, so the
    async dispatch pipeline is never stalled).  Multi-process: all-gather
    the digest over the coordination service and compare.  Raises
    RuntimeError naming the divergent processes.
    """
    if jax.process_count() == 1:
        return
    digest = fingerprint(tree)
    from jax.experimental import multihost_utils

    all_digests = np.asarray(
        multihost_utils.process_allgather(digest))       # (P, 1)
    if not (all_digests == all_digests[0]).all():
        bad = [i for i, d in enumerate(all_digests)
               if int(d[0]) != int(all_digests[0][0])]
        raise RuntimeError(
            f"cross-process determinism violation in {what}: processes "
            f"{bad} diverge from process 0 "
            f"(digests={[hex(int(d[0])) for d in all_digests]})")
