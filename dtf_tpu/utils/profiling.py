"""Profiling and cross-process determinism checks.

Tracing (SURVEY.md §5.1): the reference's only observability was wall-clock
prints (tf_distributed.py:116-122).  Here the framework exposes the XLA
profiler: ``trace()`` captures a TensorBoard/Perfetto trace of a step window
and ``start_server()`` opens the live-capture port.  The trainer hooks these
via TrainConfig.profile_dir / profile_steps.

Determinism (SURVEY.md §5.2): the reference's async PS *embraced* races
(stale gradients were the design); SPMD psum is race-free by construction,
and the moral equivalent of a race detector is checking that every process
computes bitwise-identical results each step.  ``fingerprint()`` +
``assert_replicas_agree()`` implement that cross-host check.
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture an XLA profiler trace into ``logdir`` (TensorBoard's profile
    plugin / Perfetto read it)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """Start the live-capture profiler server (tensorboard can connect)."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a host-side region in the trace (TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield


class StepWindowProfiler:
    """Capture one XLA trace over a window of training steps.

    Owns the start/stop lifecycle so the trainer can't leak an open trace:
    ``after_step(h)`` starts once h enters [start, start+steps) and stops
    when it leaves; ``close()`` stops unconditionally (end of training
    before the window completes).  A resume past the window records
    nothing; the window never restarts.
    """

    def __init__(self, logdir: str, start: int, steps: int):
        self.logdir = logdir
        self.start = start
        self.end = start + steps
        self.active = False
        self.done = False
        # Full steps actually covered by the trace — the denominator for
        # any per-step average (a truncated window must not be divided
        # by the CONFIGURED step count) — and whether stop_trace actually
        # wrote a trace (a failed stop must not let a PREVIOUS run's
        # files be summarized as this run's).
        self.captured_steps = 0
        self.wrote_trace = False

    def after_step(self, host_step: int, state: Any = None) -> None:
        if self.done:
            return
        if not self.active and self.start <= host_step < self.end:
            jax.profiler.start_trace(self.logdir)
            self.active = True
        elif self.active:
            # every completed step while the trace is open is covered —
            # including the one observed by the stopping call
            self.captured_steps += 1
            if host_step >= self.end:
                self._stop(state)
                self.wrote_trace = True

    def close(self, state: Any = None) -> None:
        if self.active:
            try:
                self._stop(state)
                self.wrote_trace = True
            except Exception:
                # The error path must neither mask the original loop
                # exception nor leak the open trace: retry the stop
                # without syncing on (possibly poisoned) state.
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self.active = False
        self.done = True

    def _stop(self, state: Any) -> None:
        if state is not None:
            jax.block_until_ready(state)   # trace covers real device work
        jax.profiler.stop_trace()
        self.active = False
        self.done = True


def summarize_trace(logdir: str, top: int = 20,
                    steps: Optional[int] = None) -> list:
    """Aggregate device-op wall time from a captured XLA trace.

    Reads the ``*.trace.json.gz`` Chrome-trace file that
    ``jax.profiler.stop_trace`` leaves under
    ``logdir/plugins/profile/<run>/`` and returns ``[(op_name,
    total_seconds), ...]`` for device-side ops, largest first — the tool
    that located round 3's MFU eaters (the scan-stacked
    dynamic-update-slice fusions; BASELINE.md).  Durations are summed
    over all occurrences and every host's file in the run, restricted to
    each device pid's "XLA Ops" lane when the trace labels one (the
    Steps/Modules lanes cover the same wall time and would double-count
    2-3x).

    ``steps``: the number of training steps the trace window covered
    (``StepWindowProfiler.captured_steps``).  When given, every returned
    duration is normalized to PER-STEP seconds; when None the historical
    per-window totals are returned."""
    if steps is not None and steps <= 0:
        raise ValueError(f"steps must be a positive traced-step count, "
                         f"got {steps}")
    rows = _trace_totals(logdir)[:top]
    if steps is not None:
        rows = [(name, secs / steps) for name, secs in rows]
    return rows


def _trace_totals(logdir: str) -> list:
    """Per-window total device-op seconds, largest first (the raw sum
    summarize_trace optionally normalizes).

    The reference's only observability was wall-clock prints around
    ``sess.run`` (tf_distributed.py:116-122); this closes the loop from
    "the step is slow" to "THIS op is slow".
    """
    import glob
    import gzip
    import json
    import os
    from collections import defaultdict

    paths = sorted(glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {logdir}/plugins/profile/ — did the "
            f"trace window run and stop_trace() execute?")
    run_dir = os.path.dirname(paths[-1])     # newest run, EVERY host's file
    total = defaultdict(float)
    for path in (p for p in paths if os.path.dirname(p) == run_dir):
        with gzip.open(path) as f:
            tr = json.load(f)
        events = tr.get("traceEvents", [])
        device_pids, op_lanes = set(), set()
        for e in events:
            if e.get("ph") != "M":
                continue
            label = e.get("args", {}).get("name", "")
            if (e.get("name") == "process_name"
                    and ("TPU" in label or "/device" in label)):
                device_pids.add(e["pid"])
            # jax device traces stack several lanes per pid whose spans
            # COVER each other ("Steps" ⊃ "XLA Modules" ⊃ "XLA Ops");
            # summing all of them would double-count 2-3x, so restrict to
            # the per-op lane when the trace labels one.
            if e.get("name") == "thread_name" and "XLA Ops" in label:
                op_lanes.add((e["pid"], e.get("tid")))
        # lane filter is PER PID: a device pid without a labeled op lane
        # keeps all its events (don't let one labeled pid hide another)
        lane_pids = {pid for pid, _ in op_lanes}
        for e in events:
            if (e.get("ph") != "X" or "dur" not in e
                    or e.get("pid") not in device_pids):
                continue
            if (e["pid"] in lane_pids
                    and (e["pid"], e.get("tid")) not in op_lanes):
                continue
            total[e.get("name", "?")] += e["dur"] / 1e6
    return sorted(total.items(), key=lambda kv: -kv[1])


def fingerprint(tree: Any) -> np.ndarray:
    """Order-stable 32-bit digest of a pytree of arrays.

    Bitwise (CRC over raw bytes, not float sums), so it detects even
    ULP-level divergence across processes.  For multi-process arrays only
    the first locally-addressable shard is hashed — meaningful for
    REPLICATED values (loss, metrics, step, unsharded params), where every
    process should hold identical bytes; a data/fsdp-sharded leaf holds
    legitimately different shards per process and must not be passed here.
    """
    import zlib

    acc = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            a = np.asarray(leaf.addressable_shards[0].data)
        else:
            a = np.asarray(leaf)
        acc = zlib.crc32(np.ascontiguousarray(a).tobytes(), acc)
    return np.asarray([acc], np.uint32)


def assert_replicas_agree(tree: Any, what: str = "state") -> None:
    """Verify every process holds a bitwise-identical (replicated) ``tree``.

    Single-process: no-op (early return before any device sync, so the
    async dispatch pipeline is never stalled).  Multi-process: all-gather
    the digest over the coordination service and compare.  Raises
    RuntimeError naming the divergent processes.
    """
    if jax.process_count() == 1:
        return
    digest = fingerprint(tree)
    from jax.experimental import multihost_utils

    all_digests = np.asarray(
        multihost_utils.process_allgather(digest))       # (P, 1)
    if not (all_digests == all_digests[0]).all():
        bad = [i for i, d in enumerate(all_digests)
               if int(d[0]) != int(all_digests[0][0])]
        raise RuntimeError(
            f"cross-process determinism violation in {what}: processes "
            f"{bad} diverge from process 0 "
            f"(digests={[hex(int(d[0])) for d in all_digests]})")
