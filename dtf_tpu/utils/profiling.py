"""Profiling and cross-process determinism checks.

Tracing (SURVEY.md §5.1): the reference's only observability was wall-clock
prints (tf_distributed.py:116-122).  Here the framework exposes the XLA
profiler: ``trace()`` captures a TensorBoard/Perfetto trace of a step window
and ``start_server()`` opens the live-capture port.  The trainer hooks these
via TrainConfig.profile_dir / profile_steps.

Determinism (SURVEY.md §5.2): the reference's async PS *embraced* races
(stale gradients were the design); SPMD psum is race-free by construction,
and the moral equivalent of a race detector is checking that every process
computes bitwise-identical results each step.  ``fingerprint()`` +
``assert_replicas_agree()`` implement that cross-host check.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Iterator, Optional

import jax
import numpy as np


# -- per-chip roofline table (telemetry/costobs.py classification) -----------

@dataclasses.dataclass(frozen=True)
class ChipRoofline:
    """Peak compute, HBM bandwidth and HBM capacity for one chip kind —
    the denominator set of the cost observatory: operational intensity
    above ``ridge_flops_per_byte`` is compute-bound, below is
    memory-bound, and ``hbm_capacity_bytes`` turns a peak-bytes gauge
    into the ``hbm/frac`` fraction the ``--max_hbm_frac`` gate reads.
    ``synthetic=True`` marks the pinned CPU-sim entry: the NUMBERS are
    arbitrary-but-fixed so classification and the capacity fraction are
    deterministic in tests, not a claim about the host."""

    kind: str
    peak_flops: float            # dense-matmul peak, FLOP/s per chip
    hbm_bytes_per_s: float       # HBM bandwidth per chip
    hbm_capacity_bytes: float    # HBM per chip
    synthetic: bool = False

    @property
    def ridge_flops_per_byte(self) -> float:
        return self.peak_flops / self.hbm_bytes_per_s


# Public figures (bf16 peak mirrors bench/matmul._PEAK_BF16; bandwidth/
# capacity: v4 1.2 TB/s / 32 GB, v5e 0.82 TB/s / 16 GB, v5p 2.765 TB/s /
# 95 GB, v6e 1.64 TB/s / 32 GB).
_ROOFLINES = {
    "v4": (275e12, 1.2e12, 32e9),
    "v5 lite": (197e12, 0.82e12, 16e9),
    "v5e": (197e12, 0.82e12, 16e9),
    "v5p": (459e12, 2.765e12, 95e9),
    "v6 lite": (918e12, 1.64e12, 32e9),
    "v6e": (918e12, 1.64e12, 32e9),
}

#: The pinned synthetic CPU-sim entry: ridge = 2.0 flops/byte, capacity
#: 4 GiB.  Fixed forever so test classifications and hbm/frac readings
#: are deterministic across rigs.
CPU_SIM_ROOFLINE = ChipRoofline("cpu_sim", 1.0e11, 5.0e10,
                                4.0 * 1024 ** 3, synthetic=True)


def chip_roofline(device: Optional[jax.Device] = None
                  ) -> Optional[ChipRoofline]:
    """Roofline entry for ``device`` (default: the first local device).
    TPU kinds match by substring against the public table; the CPU
    backend gets :data:`CPU_SIM_ROOFLINE`; an unknown accelerator
    returns None — classification then reports "unknown" rather than
    guessing."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, (peak, bw, cap) in _ROOFLINES.items():
        if key in kind:
            return ChipRoofline(kind or key, peak, bw, cap)
    if getattr(device, "platform", "") == "cpu" or kind == "cpu":
        return CPU_SIM_ROOFLINE
    return None


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture an XLA profiler trace into ``logdir`` (TensorBoard's profile
    plugin / Perfetto read it)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_server(port: int = 9999):
    """Start the live-capture profiler server (tensorboard can connect)."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a host-side region in the trace (TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield


class StepWindowProfiler:
    """Capture one XLA trace over a window of training steps.

    Owns the start/stop lifecycle so the trainer can't leak an open trace:
    ``after_step(h)`` starts once h enters [start, start+steps) and stops
    when it leaves; ``close()`` stops unconditionally (end of training
    before the window completes).  A resume past the window records
    nothing; the window never restarts.
    """

    def __init__(self, logdir: str, start: int, steps: int):
        self.logdir = logdir
        self.start = start
        self.end = start + steps
        self.active = False
        self.done = False
        # Full steps actually covered by the trace — the denominator for
        # any per-step average (a truncated window must not be divided
        # by the CONFIGURED step count) — and whether stop_trace actually
        # wrote a trace (a failed stop must not let a PREVIOUS run's
        # files be summarized as this run's).
        self.captured_steps = 0
        self.wrote_trace = False

    def after_step(self, host_step: int, state: Any = None) -> None:
        if self.done:
            return
        if not self.active and self.start <= host_step < self.end:
            jax.profiler.start_trace(self.logdir)
            self.active = True
        elif self.active:
            # every completed step while the trace is open is covered —
            # including the one observed by the stopping call
            self.captured_steps += 1
            if host_step >= self.end:
                self._stop(state)
                self.wrote_trace = True

    def close(self, state: Any = None) -> None:
        if self.active:
            try:
                self._stop(state)
                self.wrote_trace = True
            except Exception:
                # The error path must neither mask the original loop
                # exception nor leak the open trace: retry the stop
                # without syncing on (possibly poisoned) state.
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self.active = False
        self.done = True

    def _stop(self, state: Any) -> None:
        if state is not None:
            jax.block_until_ready(state)   # trace covers real device work
        jax.profiler.stop_trace()
        self.active = False
        self.done = True


def summarize_trace(logdir: str, top: int = 20,
                    steps: Optional[int] = None) -> list:
    """Aggregate device-op wall time from a captured XLA trace.

    Reads the ``*.trace.json.gz`` Chrome-trace file that
    ``jax.profiler.stop_trace`` leaves under
    ``logdir/plugins/profile/<run>/`` and returns ``[(op_name,
    total_seconds), ...]`` for device-side ops, largest first — the tool
    that located round 3's MFU eaters (the scan-stacked
    dynamic-update-slice fusions; BASELINE.md).  Durations are summed
    over all occurrences and every host's file in the run, restricted to
    each device pid's "XLA Ops" lane when the trace labels one (the
    Steps/Modules lanes cover the same wall time and would double-count
    2-3x).

    ``steps``: the number of training steps the trace window covered
    (``StepWindowProfiler.captured_steps``).  When given, every returned
    duration is normalized to PER-STEP seconds; when None the historical
    per-window totals are returned."""
    if steps is not None and steps <= 0:
        raise ValueError(f"steps must be a positive traced-step count, "
                         f"got {steps}")
    rows = _trace_totals(logdir)[:top]
    if steps is not None:
        rows = [(name, secs / steps) for name, secs in rows]
    return rows


def _trace_totals(logdir: str) -> list:
    """Per-window total device-op seconds, largest first (the raw sum
    summarize_trace optionally normalizes).

    The reference's only observability was wall-clock prints around
    ``sess.run`` (tf_distributed.py:116-122); this closes the loop from
    "the step is slow" to "THIS op is slow".
    """
    import glob
    import gzip
    import json
    import os
    from collections import defaultdict

    paths = sorted(glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {logdir}/plugins/profile/ — did the "
            f"trace window run and stop_trace() execute?")
    run_dir = os.path.dirname(paths[-1])     # newest run, EVERY host's file
    total = defaultdict(float)
    for path in (p for p in paths if os.path.dirname(p) == run_dir):
        with gzip.open(path) as f:
            tr = json.load(f)
        events = tr.get("traceEvents", [])
        device_pids, op_lanes = set(), set()
        for e in events:
            if e.get("ph") != "M":
                continue
            label = e.get("args", {}).get("name", "")
            if (e.get("name") == "process_name"
                    and ("TPU" in label or "/device" in label)):
                device_pids.add(e["pid"])
            # jax device traces stack several lanes per pid whose spans
            # COVER each other ("Steps" ⊃ "XLA Modules" ⊃ "XLA Ops");
            # summing all of them would double-count 2-3x, so restrict to
            # the per-op lane when the trace labels one.
            if e.get("name") == "thread_name" and "XLA Ops" in label:
                op_lanes.add((e["pid"], e.get("tid")))
        # lane filter is PER PID: a device pid without a labeled op lane
        # keeps all its events (don't let one labeled pid hide another)
        lane_pids = {pid for pid, _ in op_lanes}
        for e in events:
            if (e.get("ph") != "X" or "dur" not in e
                    or e.get("pid") not in device_pids):
                continue
            if (e["pid"] in lane_pids
                    and (e["pid"], e.get("tid")) not in op_lanes):
                continue
            total[e.get("name", "?")] += e["dur"] / 1e6
    return sorted(total.items(), key=lambda kv: -kv[1])


def fingerprint(tree: Any) -> np.ndarray:
    """Order-stable 32-bit digest of a pytree of arrays.

    Bitwise (CRC over raw bytes, not float sums), so it detects even
    ULP-level divergence across processes.  For multi-process arrays only
    the first locally-addressable shard is hashed — meaningful for
    REPLICATED values (loss, metrics, step, unsharded params), where every
    process should hold identical bytes; a data/fsdp-sharded leaf holds
    legitimately different shards per process and must not be passed here.
    """
    import zlib

    acc = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            a = np.asarray(leaf.addressable_shards[0].data)
        else:
            a = np.asarray(leaf)
        acc = zlib.crc32(np.ascontiguousarray(a).tobytes(), acc)
    return np.asarray([acc], np.uint32)


def assert_replicas_agree(tree: Any, what: str = "state") -> None:
    """Verify every process holds a bitwise-identical (replicated) ``tree``.

    Single-process: no-op (early return before any device sync, so the
    async dispatch pipeline is never stalled).  Multi-process: all-gather
    the digest over the coordination service and compare.  Raises
    RuntimeError naming the divergent processes.
    """
    if jax.process_count() == 1:
        return
    digest = fingerprint(tree)
    from jax.experimental import multihost_utils

    all_digests = np.asarray(
        multihost_utils.process_allgather(digest))       # (P, 1)
    if not (all_digests == all_digests[0]).all():
        bad = [i for i, d in enumerate(all_digests)
               if int(d[0]) != int(all_digests[0][0])]
        raise RuntimeError(
            f"cross-process determinism violation in {what}: processes "
            f"{bad} diverge from process 0 "
            f"(digests={[hex(int(d[0])) for d in all_digests]})")
