"""Failure detection: fail-fast hang watchdog.

The reference had NO failure handling (SURVEY.md §5.3): the PS blocked in
``server.join()`` forever (tf_distributed.py:31), non-chief workers blocked
indefinitely in ``prepare_or_wait_for_session`` if the chief or PS died
(tf_distributed.py:96) — a dead process hung the whole cluster silently.

Here the recovery story is fail-fast + checkpoint/resume (train/checkpoint):

* process death: the ``jax.distributed`` coordination service propagates
  missing-heartbeat failures and tears the job down (given, not built);
* silent *hangs* (a wedged collective, a deadlocked host thread, a stuck
  data loader) are what this module detects: a daemon thread trips when the
  training loop stops making progress for ``timeout_s`` and kills the
  process with a loud message, so the job dies (and can be restarted from
  the last checkpoint) instead of wedging forever like the reference.

Note on async dispatch: the train loop ticks once per *dispatched* step,
but XLA execution is asynchronous — a device-side deadlock surfaces when
the loop blocks reading metrics at the next logging sync point.  Size
``timeout_s`` above the worst expected gap between log syncs (compile time
included), not above the step time.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import Callable, Optional


def dump_all_stacks(file=None) -> None:
    """Write every thread's Python stack to ``file`` (default stderr) —
    the post-mortem that makes a tripped watchdog diagnosable (WHERE was
    the main thread wedged: a collective, the data loader, a lock?)
    instead of just fatal.  Must never raise: it runs on the kill path."""
    import faulthandler
    try:
        faulthandler.dump_traceback(all_threads=True,
                                    file=file if file is not None
                                    else sys.stderr)
    except Exception as exc:        # no diagnosis is still better than
        try:                        # dying without the loud exit below
            print(f"[dtf_tpu] WATCHDOG: stack dump failed: {exc}",
                  file=sys.stderr, flush=True)
        except Exception:
            pass


def _default_on_hang(what: str, timeout_s: float) -> None:
    print(f"[dtf_tpu] WATCHDOG: no {what} progress in {timeout_s:g}s — "
          f"failing fast (the reference would hang forever here, "
          f"tf_distributed.py:96). Restart resumes from the last "
          f"checkpoint. All-thread stacks follow:", file=sys.stderr,
          flush=True)
    dump_all_stacks()
    # os._exit, not sys.exit: the main thread is wedged (that's the point);
    # only a hard exit gets the process out of a stuck collective.
    os._exit(70)   # EX_SOFTWARE


class HangWatchdog:
    """Trips ``on_hang`` when :meth:`tick` isn't called for ``timeout_s``.

    Daemon-threaded; ``close()`` disarms it.  ``on_hang(what, timeout_s)``
    defaults to printing and hard-exiting the process (fail-fast).
    """

    def __init__(self, timeout_s: float, what: str = "train step",
                 on_hang: Optional[Callable[[str, float], None]] = None,
                 poll_s: Optional[float] = None):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s
        self.what = what
        self._on_hang = on_hang or _default_on_hang
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._fired = False
        self._suspended = False
        self._poll = poll_s if poll_s is not None else min(timeout_s / 4, 1.0)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtf_tpu-watchdog")
        self._thread.start()

    def tick(self) -> None:
        """Record progress (called once per loop iteration)."""
        self._last = time.monotonic()

    @contextlib.contextmanager
    def suspend(self):
        """Disarm across a legitimately-slow blocking host call (full-set
        eval, checkpoint save) whose duration shouldn't count as a hang;
        re-arms with a fresh deadline on exit."""
        self._suspended = True
        try:
            yield
        finally:
            self._last = time.monotonic()
            self._suspended = False

    @property
    def fired(self) -> bool:
        return self._fired

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            if (not self._suspended
                    and time.monotonic() - self._last > self.timeout_s):
                self._fired = True
                self._on_hang(self.what, self.timeout_s)
                return

    def close(self) -> None:
        """Disarm and join the watchdog thread."""
        self._stop.set()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
