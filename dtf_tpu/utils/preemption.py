"""Preemption-safe training: SIGTERM (optionally SIGINT) -> checkpoint at
the next step boundary.

The reference lost all state on any interruption (no Saver, SURVEY.md §5.4).
TPU VMs are routinely preempted (maintenance events, spot reclamation) with
a SIGTERM and a grace window; this handler turns that into a clean
checkpoint+exit instead of a kill, completing the fail-fast + resume
recovery story (utils/watchdog.py, train/checkpoint.py).

Signal-async-safe by design: the handler only sets a flag; the training
loop polls it at step boundaries and does the actual (non-reentrant) orbax
save there.

Multi-host: SIGTERM delivery is not synchronized across hosts, and the
orbax save and the train step are both collectives — hosts deciding to
save at *different* step boundaries would deadlock (one blocks in the save
barrier, another in the next step's gradient psum).  :meth:`agreed` is the
race-free decision: an allgather of the local flags, called at boundaries
every process already reaches together (the trainer uses its logging sync
points), so either ALL processes save at that boundary or none do.
"""

from __future__ import annotations

import signal
import sys
import threading
from typing import Iterable


class PreemptionHandler:
    """Installs handlers for ``signals``; :attr:`triggered` flips at the
    first delivery.  ``restore()`` reinstates the previous handlers.

    Signal handlers are a main-thread-only facility; constructed from any
    other thread the handler stays disarmed (``triggered`` always False)
    and says so, rather than crashing the trainer.
    """

    @classmethod
    def signals_for(cls, include_sigint: bool = False) -> tuple:
        """The signal set for a config: SIGTERM always (TPU preemption /
        spot reclamation), plus SIGINT when ``--preempt_sigint`` asks for
        ctrl-C / scheduler-nudge drains to checkpoint instead of dying
        with KeyboardInterrupt mid-step."""
        return ((signal.SIGTERM, signal.SIGINT) if include_sigint
                else (signal.SIGTERM,))

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self._flag = threading.Event()
        self._prev = {}
        # Deliveries observed (signum per delivery): `received` feeds the
        # preemption metrics counter so drains are countable post-mortem.
        self.received: list = []
        try:
            for s in signals:
                self._prev[s] = signal.signal(s, self._on_signal)
        except ValueError:   # not the main thread
            self.restore()
            print("[dtf_tpu] preemption handler disabled: signals can only "
                  "be installed from the main thread", file=sys.stderr,
                  flush=True)

    def _on_signal(self, signum, frame) -> None:
        self.received.append(signum)
        self._flag.set()
        # print() is not strictly async-signal-safe but CPython serializes
        # handler execution on the main thread; keep it one short line.
        print(f"[dtf_tpu] signal {signum}: preemption — will checkpoint at "
              f"the next sync boundary and exit", file=sys.stderr, flush=True)

    @property
    def trigger_count(self) -> int:
        """How many preemption signals have been delivered locally."""
        return len(self.received)

    @property
    def triggered(self) -> bool:
        """This process's local flag (race-free only single-process; use
        :meth:`agreed` across hosts)."""
        return self._flag.is_set()

    def agreed(self) -> bool:
        """True iff ANY process has been signalled — same answer on every
        process.  Call at a boundary all processes reach together (host
        sync: one small allgather over DCN); single-process it is just the
        local flag."""
        import jax
        if jax.process_count() == 1:
            return self.triggered
        import numpy as np
        from jax.experimental import multihost_utils
        local = np.asarray([1 if self.triggered else 0], np.int32)
        return bool(np.asarray(
            multihost_utils.process_allgather(local)).any())

    def restore(self) -> None:
        for s, prev in self._prev.items():
            # signal.signal returned None when the previous handler was not
            # installed from Python (e.g. a C extension's); there is nothing
            # restorable — leave ours in place rather than TypeError.
            if prev is not None:
                signal.signal(s, prev)
        self._prev = {}
