from dtf_tpu.utils import retry, timing  # noqa: F401
