from dtf_tpu.utils import timing  # noqa: F401
