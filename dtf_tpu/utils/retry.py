"""Bounded retry with exponential backoff + jitter.

The reference had exactly one answer to any transient failure: hang or die
(SURVEY.md §5.3).  This module is the shared retry policy for the places a
*transient* error is routine and a bounded number of re-attempts is the
right response:

* ``cluster.bootstrap`` — workers racing a slow coordinator retry
  ``jax.distributed.initialize`` instead of dying on first connect;
* the data path — flaky dataset/loader I/O (``trainer`` batch fetch,
  ``native_loader``) retries and then fails with a CLEAR terminal error
  (never a silent infinite loop);
* ``resilience/supervisor.py`` — whole-fit restarts reuse the same
  :class:`Backoff` schedule between attempts.

Design rules: retries are *bounded* (``attempts``), the exception filter is
*explicit* (``retry_on`` — config errors like ``ValueError`` must stay
terminal), jitter is *seeded* (deterministic under test; decorrelated across
processes by seeding with the process index), and the clock is injectable
(tests pass a fake ``sleep`` and assert the exact delay sequence).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Optional, Sequence

import numpy as np

log = logging.getLogger("dtf_tpu")


class RetryExhausted(RuntimeError):
    """Terminal failure after the full retry budget.

    Carries the attempt count and chains the last underlying error
    (``__cause__``) so post-mortems see both the policy and the root cause.
    """

    def __init__(self, what: str, attempts: int, last: BaseException):
        super().__init__(
            f"{what}: failed after {attempts} attempt(s); last error: "
            f"{type(last).__name__}: {last}")
        self.what = what
        self.attempts = attempts
        self.last = last


@dataclasses.dataclass
class Backoff:
    """Exponential backoff schedule with multiplicative jitter.

    Attempt k (0-based) sleeps ``min(base_s * factor**k, max_s)`` scaled by
    a uniform jitter in ``[1 - jitter, 1 + jitter]``.  ``seed`` makes the
    jitter stream deterministic (seed with the process index so a fleet of
    restarting workers decorrelates instead of thundering back in lockstep).
    """

    base_s: float = 0.5
    max_s: float = 30.0
    factor: float = 2.0
    jitter: float = 0.25
    seed: Optional[int] = None

    def __post_init__(self):
        if self.base_s < 0 or self.max_s < 0:
            raise ValueError(f"backoff delays must be >= 0, got "
                             f"base_s={self.base_s}, max_s={self.max_s}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        self._rng = np.random.default_rng(self.seed)

    def delay_s(self, attempt: int) -> float:
        """Sleep duration after failed attempt ``attempt`` (0-based)."""
        d = min(self.base_s * self.factor ** attempt, self.max_s)
        if self.jitter:
            d *= 1.0 + self.jitter * float(self._rng.uniform(-1.0, 1.0))
        return d


def retry_call(fn: Callable, *, attempts: int = 5,
               backoff: Optional[Backoff] = None,
               retry_on: Sequence[type] = (OSError,),
               what: str = "call",
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               sleep: Optional[Callable[[float], None]] = None):
    """Call ``fn()`` under a bounded retry budget; return its result.

    Exceptions matching ``retry_on`` consume an attempt and back off;
    anything else propagates immediately (a config error is not transient).
    After ``attempts`` failures raises :class:`RetryExhausted` chained to
    the last error — the guaranteed-terminal, guaranteed-loud exit.
    ``on_retry(attempt, exc)`` observes each failure before the sleep.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    if sleep is None:      # bound late so tests can monkeypatch time.sleep
        sleep = time.sleep
    backoff = backoff or Backoff()
    retry_on = tuple(retry_on)
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as exc:        # noqa: PERF203 (the loop IS the policy)
            last = exc
            if on_retry is not None:
                on_retry(attempt, exc)
            if attempt + 1 < attempts:
                d = backoff.delay_s(attempt)
                log.warning("%s: attempt %d/%d failed (%s: %s); retrying "
                            "in %.2fs", what, attempt + 1, attempts,
                            type(exc).__name__, exc, d)
                sleep(d)
    raise RetryExhausted(what, attempts, last) from last
