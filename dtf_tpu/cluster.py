"""Cluster bootstrap: topology, process init, mesh construction.

Replaces the reference's L1 layer (SURVEY.md §1): the hardcoded ClusterSpec
(tf_distributed.py:9-11), the per-task gRPC ``tf.train.Server``
(tf_distributed.py:18), the ``ps``/``worker`` role dispatch
(tf_distributed.py:30-32) and the Supervisor's coordinated init
(tf_distributed.py:92-96).

TPU-native design:

* control plane: ``jax.distributed.initialize`` (coordination service over
  DCN) instead of a per-task gRPC server;
* no roles: SPMD runs the same program on every process.  ``--job_name=ps``
  is accepted for CLI compatibility but the process joins as a peer (there is
  no parameter-hosting process in an all-reduce design);
* coordinated init: parameters are initialized identically on every process
  from the same seed (deterministic SPMD init) — no chief, no polling, no
  "wait for PS" (the reference's non-chief workers blocked in
  ``prepare_or_wait_for_session``, tf_distributed.py:96);
* the device mesh replaces the cluster spec: topology is a mesh-shape string,
  not host:port lists.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

import jax
from jax.sharding import Mesh

from dtf_tpu.config import ClusterConfig
from dtf_tpu.parallel.mesh import MeshSpec, make_mesh

log = logging.getLogger("dtf_tpu")

_INITIALIZED = False


@dataclasses.dataclass
class Cluster:
    """A bootstrapped job: process identity + the global device mesh."""

    config: ClusterConfig
    mesh: Mesh

    @property
    def process_id(self) -> int:
        return jax.process_index()

    @property
    def num_processes(self) -> int:
        return jax.process_count()

    @property
    def is_coordinator(self) -> bool:
        """Chief election, reference-style ``task_index == 0``
        (tf_distributed.py:92) — used only to de-duplicate host-side I/O
        (logging, checkpoint writes), never for init."""
        return self.process_id == 0

    @property
    def num_devices(self) -> int:
        return self.mesh.size

    def start_health(self, print_fn=None):
        """Arm the multi-host failure domain (resilience/health.py): a
        heartbeat + liveness-monitor daemon thread, per the cluster
        config's ``hb_*`` knobs.  Returns the started
        :class:`~dtf_tpu.resilience.health.HealthMonitor`, or None when
        disabled (``hb_interval_s <= 0``) or single-process (there are no
        peers whose death could wedge a collective).  The caller owns
        ``close()`` — the trainer arms it for the duration of ``fit``."""
        cfg = self.config
        if cfg.hb_interval_s <= 0 or jax.process_count() <= 1:
            return None
        if not cfg.health_dir:
            # ClusterConfig.__post_init__ already rejects this pairing;
            # this guards Cluster objects built with a mutated config.
            raise ValueError(
                "--hb_interval_s > 0 needs --health_dir (shared path or "
                "tcp://host:port)")
        from dtf_tpu.resilience.health import HealthMonitor, make_transport
        transport = make_transport(cfg.health_dir, jax.process_index(),
                                   self.is_coordinator)
        monitor = HealthMonitor(
            transport, jax.process_index(), jax.process_count(),
            interval_s=cfg.hb_interval_s, miss_budget=cfg.hb_miss_budget,
            boot_grace_s=cfg.hb_boot_grace_s,
            is_coordinator=self.is_coordinator, print_fn=print_fn)
        monitor.start()
        log.info("health monitor armed: interval %gs, miss budget %d, "
                 "rendezvous %s", cfg.hb_interval_s, cfg.hb_miss_budget,
                 cfg.health_dir)
        return monitor


# --xla_overlap: the latency-hiding-scheduler preset.  These are libtpu
# flags, so they ride LIBTPU_INIT_ARGS (read once when libtpu loads):
# inert on CPU/simulated runs, and PREPENDED — an operator's own
# LIBTPU_INIT_ARGS stays last and wins on conflicts (libtpu takes the
# LAST value), so e.g. an explicit ...latency_hiding_scheduler=false
# survives --xla_overlap.
# What it buys: the scheduler reorders async collective start/done pairs
# so zero1's bucket reduce-scatters and the param all-gather overlap the
# backward's compute instead of serializing after it (DESIGN.md §4.1).
_XLA_OVERLAP_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def apply_xla_overlap_preset() -> str:
    """Append the overlap preset to LIBTPU_INIT_ARGS (idempotent).  Must
    run BEFORE the first device query — bootstrap does; calling it after a
    TPU backend initialized leaves the env set for child processes but
    cannot affect the live backend."""
    current = os.environ.get("LIBTPU_INIT_ARGS", "")
    missing = [f for f in _XLA_OVERLAP_FLAGS if f not in current]
    if missing:
        os.environ["LIBTPU_INIT_ARGS"] = " ".join(
            filter(None, [*missing, current]))
        log.info("xla_overlap: LIBTPU_INIT_ARGS = preset + %r", current)
    return os.environ["LIBTPU_INIT_ARGS"]


def simulate_cpu_devices(n: int) -> None:
    """Pin the backend to ``n`` simulated CPU devices (the CLI version of
    the tests' simulated mesh).  Must run before the first device query:
    config.update works post-import as long as no backend initialized
    yet; older jax (< 0.5) has no ``jax_num_cpu_devices`` option, and
    there the XLA_FLAGS route works for the same reason (read at backend
    init).  The one definition behind ``--simulated_devices`` everywhere
    (bootstrap and the bench CLIs)."""
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}").strip()


def bootstrap(config: Optional[ClusterConfig] = None) -> Cluster:
    """Initialize the process and build the global mesh.

    Zero-config single-process mode works out of the box (the reference could
    not run outside its hardcoded 6-8 host network, tf_distributed.py:9-10).
    Multi-process mode mirrors the reference's CLI:

        python -m dtf_tpu.workloads.mnist --job_name=worker --task_index=k \
            --coordinator_address=host:port --num_processes=N

    vs the reference's ``python tf_distributed.py --job_name=worker
    --task_index=k`` with in-source IP edits.
    """
    global _INITIALIZED
    config = config or ClusterConfig()

    if config.xla_overlap:
        apply_xla_overlap_preset()
    if config.platform:
        # Env vars are too late if jax was already imported (this image's
        # sitecustomize does); config.update is the reliable path.
        jax.config.update("jax_platforms", config.platform)
    if config.simulated_devices > 0:
        if config.platform not in (None, "cpu"):
            raise ValueError(
                f"--simulated_devices runs on CPU; conflicting "
                f"--platform={config.platform}")
        simulate_cpu_devices(config.simulated_devices)

    if config.num_processes > 1 and not _INITIALIZED:
        if not config.coordinator_address:
            raise ValueError("--coordinator_address required when num_processes > 1")
        # Bounded retry-with-backoff: at pod scale, workers routinely race
        # a coordinator that is still scheduling/binding its port, and the
        # first connect attempt failing is NOT a config error.  Jitter is
        # seeded by the process index so a fleet of retriers decorrelates.
        # ValueError (bad topology/config) stays terminal; exhaustion
        # raises RetryExhausted chained to the last connect error.
        from dtf_tpu.utils.retry import Backoff, retry_call

        def reset_distributed(_attempt, _exc):
            # A failed connect can leave jax's global distributed state
            # assigned; without this, every later attempt would die on
            # "initialize should only be called once" instead of actually
            # re-dialing the coordinator.
            try:
                jax.distributed.shutdown()
            except Exception:
                pass

        retry_call(
            lambda: jax.distributed.initialize(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
            ),
            attempts=5,
            backoff=Backoff(base_s=1.0, max_s=15.0,
                            seed=config.process_id),
            retry_on=(RuntimeError, OSError, ConnectionError),
            on_retry=reset_distributed,
            what=f"jax.distributed.initialize "
                 f"({config.coordinator_address})",
        )
        _INITIALIZED = True
        log.info("jax.distributed initialized: process %d/%d, coordinator %s",
                 jax.process_index(), jax.process_count(),
                 config.coordinator_address)

    spec = MeshSpec.parse(config.mesh)
    if config.elastic:
        # Elastic relaunch on a shrunken host set: a fixed mesh spec sized
        # for the ORIGINAL cluster no longer matches the surviving device
        # count — resize the data axis to fit (model axes stay fixed).
        from dtf_tpu.parallel.mesh import shrink_to_devices
        shrunk = shrink_to_devices(spec, len(jax.devices()))
        if shrunk.sizes != spec.sizes:
            log.warning("elastic: mesh %s re-fit to %d device(s) as %s",
                        config.mesh, len(jax.devices()),
                        ",".join(f"{n}={s}" for n, s in
                                 zip(shrunk.names, shrunk.sizes)))
        spec = shrunk
    mesh = make_mesh(spec)
    if jax.process_index() == 0:
        log.info("mesh: axes=%s shape=%s over %d %s device(s)",
                 mesh.axis_names, dict(mesh.shape), mesh.size,
                 jax.devices()[0].platform)
    return Cluster(config=config, mesh=mesh)
