"""Optimizers: pure pytree transforms.

The reference used ``tf.train.GradientDescentOptimizer(0.0005).minimize(...)``
with variables on the PS and asynchronous per-worker applies
(tf_distributed.py:73-76).  Here an optimizer is a pair of pure functions —

    state = opt.init(params)
    updates, state = opt.update(grads, state, params)
    params = apply_updates(params, updates)

— applied identically on every device to psum-reduced gradients, so the
update is synchronous and deterministic by construction (the framework's
answer to the reference's embraced races, SURVEY.md §5.2).

Optimizer state is a pytree like any other, so FSDP/ZeRO-style sharding rules
apply to it unchanged (cf. PAPERS.md, "Automatic Cross-Replica Sharding of
Weight Update").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], tuple]   # (grads, state, params) -> (updates, state)


def apply_updates(params: Any, updates: Any) -> Any:
    return jax.tree_util.tree_map(lambda p, u: (p + u).astype(p.dtype), params, updates)


def sgd(lr: float) -> Optimizer:
    """Plain SGD — the reference's optimizer (lr 0.0005, tf_distributed.py:73)."""

    def init(params):
        return ()

    def update(grads, state, params=None):
        return jax.tree_util.tree_map(lambda g: -lr * g, grads), state

    return Optimizer(init, update)


def momentum(lr: float, beta: float = 0.9, nesterov: bool = False) -> Optimizer:
    def init(params):
        return {"m": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def update(grads, state, params=None):
        m = jax.tree_util.tree_map(lambda m_, g: beta * m_ + g, state["m"], grads)
        if nesterov:
            upd = jax.tree_util.tree_map(lambda m_, g: -lr * (beta * m_ + g), m, grads)
        else:
            upd = jax.tree_util.tree_map(lambda m_: -lr * m_, m)
        return upd, {"m": m}

    return Optimizer(init, update)


def adam(lr: "float | Callable[[jax.Array], jax.Array]", b1: float = 0.9,
         b2: float = 0.999, eps: float = 1e-8,
         weight_decay: float = 0.0) -> Optimizer:
    """Adam / AdamW (decoupled weight decay).  ``lr`` may be a schedule
    (step -> lr)."""

    def init(params):
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return {"m": zeros(), "v": zeros(), "step": jnp.zeros((), jnp.int32)}

    def update(grads, state, params=None):
        step = state["step"] + 1
        lr_t = lr(step) if callable(lr) else lr
        m = jax.tree_util.tree_map(
            lambda m_, g: b1 * m_ + (1 - b1) * g.astype(jnp.float32),
            state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            state["v"], grads)
        bc1 = 1 - b1 ** step.astype(jnp.float32)
        bc2 = 1 - b2 ** step.astype(jnp.float32)

        def upd(m_, v_, p):
            u = -lr_t * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + eps)
            if weight_decay and p is not None:
                u = u - lr_t * weight_decay * p.astype(jnp.float32)
            return u

        if params is None:
            updates = jax.tree_util.tree_map(lambda m_, v_: upd(m_, v_, None), m, v)
        else:
            updates = jax.tree_util.tree_map(upd, m, v, params)
        return updates, {"m": m, "v": v, "step": step}

    return Optimizer(init, update)


def adamw(lr, weight_decay: float = 0.01, **kw) -> Optimizer:
    return adam(lr, weight_decay=weight_decay, **kw)


def clip_by_global_norm(opt: Optimizer, max_norm: float) -> Optimizer:
    """Wrap an optimizer with global-norm gradient clipping."""

    def update(grads, state, params=None):
        leaves = jax.tree_util.tree_leaves(grads)
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for g in leaves))
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
        grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
        return opt.update(grads, state, params)

    return Optimizer(opt.init, update)


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int,
                  final_frac: float = 0.0) -> Callable:
    """LR schedule for the BERT/ResNet workloads."""

    def schedule(step):
        step = step.astype(jnp.float32)
        warm = peak_lr * step / max(warmup_steps, 1)
        prog = jnp.clip((step - warmup_steps) / max(total_steps - warmup_steps, 1),
                        0.0, 1.0)
        cos = peak_lr * (final_frac + (1 - final_frac) * 0.5 *
                         (1 + jnp.cos(jnp.pi * prog)))
        return jnp.where(step < warmup_steps, warm, cos)

    return schedule
