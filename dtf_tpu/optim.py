"""Optimizers: pure pytree transforms.

The reference used ``tf.train.GradientDescentOptimizer(0.0005).minimize(...)``
with variables on the PS and asynchronous per-worker applies
(tf_distributed.py:73-76).  Here an optimizer is a pair of pure functions —

    state = opt.init(params)
    updates, state = opt.update(grads, state, params)
    params = apply_updates(params, updates)

— applied identically on every device to psum-reduced gradients, so the
update is synchronous and deterministic by construction (the framework's
answer to the reference's embraced races, SURVEY.md §5.2).

Optimizer state is a pytree like any other, so FSDP/ZeRO-style sharding rules
apply to it unchanged (cf. PAPERS.md, "Automatic Cross-Replica Sharding of
Weight Update").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], tuple]   # (grads, state, params) -> (updates, state)
    # True when the update rule is purely ELEMENTWISE over (grad, state,
    # param) entries — no per-tensor norms, factored moments, or other
    # cross-element structure.  Elementwise rules commute with any
    # partitioning of the flattened parameter vector, which is exactly the
    # property ZeRO-1 weight-update sharding (parallel/grad_sync.py) needs
    # to run the update on disjoint shards: update(shard) == update(full)
    # restricted to the shard.  adafactor (row/col means) and lamb
    # (per-tensor trust ratios) are NOT elementwise and keep the default.
    elementwise: bool = False


class _Pair:
    """(update, slot) carrier that is deliberately NOT a pytree node, so
    tree_map treats it as a leaf when unzipping adafactor's results."""

    __slots__ = ("u", "slot")

    def __init__(self, u, slot):
        self.u, self.slot = u, slot


def apply_updates(params: Any, updates: Any) -> Any:
    return jax.tree_util.tree_map(lambda p, u: (p + u).astype(p.dtype), params, updates)


def sgd(lr: "float | Callable") -> Optimizer:
    """Plain SGD — the reference's optimizer (lr 0.0005, tf_distributed.py:73).
    ``lr`` may be a schedule (step -> lr); a step counter is carried in the
    state only then."""

    def init(params):
        return {"step": jnp.zeros((), jnp.int32)} if callable(lr) else ()

    def update(grads, state, params=None):
        if callable(lr):
            step = state["step"] + 1
            lr_t, state = lr(step), {"step": step}
        else:
            lr_t = lr
        return jax.tree_util.tree_map(lambda g: -lr_t * g, grads), state

    return Optimizer(init, update, elementwise=True)


def momentum(lr: "float | Callable", beta: float = 0.9,
             nesterov: bool = False) -> Optimizer:
    def init(params):
        state = {"m": jax.tree_util.tree_map(jnp.zeros_like, params)}
        if callable(lr):
            state["step"] = jnp.zeros((), jnp.int32)
        return state

    def update(grads, state, params=None):
        if callable(lr):
            step = state["step"] + 1
            lr_t, extra = lr(step), {"step": step}
        else:
            lr_t, extra = lr, {}
        m = jax.tree_util.tree_map(lambda m_, g: beta * m_ + g, state["m"], grads)
        if nesterov:
            upd = jax.tree_util.tree_map(lambda m_, g: -lr_t * (beta * m_ + g), m, grads)
        else:
            upd = jax.tree_util.tree_map(lambda m_: -lr_t * m_, m)
        return upd, {"m": m, **extra}

    return Optimizer(init, update, elementwise=True)


def adam(lr: "float | Callable[[jax.Array], jax.Array]", b1: float = 0.9,
         b2: float = 0.999, eps: float = 1e-8,
         weight_decay: float = 0.0) -> Optimizer:
    """Adam / AdamW (decoupled weight decay).  ``lr`` may be a schedule
    (step -> lr)."""

    def init(params):
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return {"m": zeros(), "v": zeros(), "step": jnp.zeros((), jnp.int32)}

    def update(grads, state, params=None):
        step = state["step"] + 1
        lr_t = lr(step) if callable(lr) else lr
        m = jax.tree_util.tree_map(
            lambda m_, g: b1 * m_ + (1 - b1) * g.astype(jnp.float32),
            state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            state["v"], grads)
        bc1 = 1 - b1 ** step.astype(jnp.float32)
        bc2 = 1 - b2 ** step.astype(jnp.float32)

        def upd(m_, v_, p):
            u = -lr_t * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + eps)
            if weight_decay and p is not None:
                u = u - lr_t * weight_decay * p.astype(jnp.float32)
            return u

        if params is None:
            updates = jax.tree_util.tree_map(lambda m_, v_: upd(m_, v_, None), m, v)
        else:
            updates = jax.tree_util.tree_map(upd, m, v, params)
        return updates, {"m": m, "v": v, "step": step}

    return Optimizer(init, update, elementwise=True)


def adamw(lr, weight_decay: float = 0.01, **kw) -> Optimizer:
    return adam(lr, weight_decay=weight_decay, **kw)


def adafactor(lr: "float | Callable" = 1e-2, eps: float = 1e-30,
              clip_threshold: float = 1.0, decay_rate: float = 0.8,
              min_dim_size_to_factor: int = 128) -> Optimizer:
    """Adafactor (Shazeer & Stern 2018) — the TPU-classic memory-efficient
    optimizer (T5/PaLM lineage): for matrices, the second moment is stored
    FACTORED as one row vector + one column vector (O(n+m) state instead of
    Adam's O(nm) ``v``), reconstructed as the rank-1 outer product scaled
    by the row mean.  Vectors/scalars and small matrices keep the full
    second moment.  No first moment at all.

    State per (n, m) matrix: ``vr`` (n,), ``vc`` (m,) — with FSDP sharding
    rules the factored state shrinks optimizer HBM by ~mlp_dim/2 per dense
    layer.  Update clipping by RMS (``clip_threshold``) replaces momentum
    for stability; ``decay_rate`` anneals beta2 as 1 - step^-0.8 per the
    paper.
    """

    def factored(p) -> bool:
        return (p.ndim >= 2
                and p.shape[-1] >= min_dim_size_to_factor
                and p.shape[-2] >= min_dim_size_to_factor)

    def init(params):
        def per_leaf(p):
            if factored(p):
                return {"vr": jnp.zeros(p.shape[:-1], jnp.float32),
                        "vc": jnp.zeros(p.shape[:-2] + p.shape[-1:],
                                        jnp.float32)}
            return {"v": jnp.zeros(p.shape, jnp.float32)}
        return {"slots": jax.tree_util.tree_map(per_leaf, params),
                "step": jnp.zeros((), jnp.int32)}

    def update(grads, state, params=None):
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        beta2 = 1.0 - t ** (-decay_rate)
        lr_t = lr(step) if callable(lr) else lr

        def per_leaf(g, slot):
            g = g.astype(jnp.float32)
            g2 = jnp.square(g) + eps
            if "vr" in slot:
                vr = beta2 * slot["vr"] + (1 - beta2) * jnp.mean(g2, axis=-1)
                vc = beta2 * slot["vc"] + (1 - beta2) * jnp.mean(g2, axis=-2)
                # rank-1 reconstruction: v ~= vr vc^T / mean(vr)
                denom = jnp.maximum(jnp.mean(vr, axis=-1, keepdims=True),
                                    eps)
                rsqrt_v = (jax.lax.rsqrt(vr / denom)[..., None]
                           * jax.lax.rsqrt(vc)[..., None, :])
                u = g * rsqrt_v
                new = {"vr": vr, "vc": vc}
            else:
                v = beta2 * slot["v"] + (1 - beta2) * g2
                u = g * jax.lax.rsqrt(v)
                new = {"v": v}
            # update clipping: cap the RMS of the scaled update at
            # clip_threshold (the paper's momentum-free stabilizer)
            rms = jnp.sqrt(jnp.mean(jnp.square(u)))
            u = u / jnp.maximum(1.0, rms / clip_threshold)
            return -lr_t * u, new

        # tree_map flattens up to the grad leaves, handing per_leaf each
        # grad array with its (deeper) slot subtree.  Results ride in
        # _Pair, which is NOT a registered pytree node, so the unzip
        # cannot confuse a tuple/list container inside the grads tree for
        # a result pair.
        flat = jax.tree_util.tree_map(
            lambda g, s: _Pair(*per_leaf(g, s)), grads, state["slots"])
        updates = jax.tree_util.tree_map(lambda pr: pr.u, flat)
        slots = jax.tree_util.tree_map(lambda pr: pr.slot, flat)
        return updates, {"slots": slots, "step": step}

    return Optimizer(init, update)


def lamb(lr: "float | Callable", b1: float = 0.9, b2: float = 0.999,
         eps: float = 1e-6, weight_decay: float = 0.01) -> Optimizer:
    """LAMB (You et al. 2020): Adam with per-layer trust-ratio scaling —
    the large-batch BERT optimizer (the BASELINE.json BERT config's path
    to big global batches on wide meshes).

    Not elementwise (the trust ratio is a per-TENSOR norm pair), but the
    norms are plain sums of squares — so ZeRO-1 weight-update sharding
    can still run it by segment-summing each shard's contribution and
    ``psum``-ing across the data axis (the same trick
    :func:`clip_by_global_norm` uses for the global clip norm).  The
    ``_lamb_args`` introspection attribute below is that path's hook:
    :class:`~dtf_tpu.parallel.grad_sync.GradSyncEngine` rebuilds the
    update against its bucket layout from these hyperparameters
    (``grad_sync._build_sharded_lamb``), exactly as the clip wrapper is
    rebuilt partition-aware from ``_clip_max_norm``."""
    inner = adam(1.0, b1=b1, b2=b2, eps=eps)   # raw Adam direction

    def update(grads, state, params):
        dirs, state = inner.update(grads, state, None)
        lr_t = lr(state["step"]) if callable(lr) else lr

        def per_leaf(d, p):
            # adamized direction (+ decoupled weight decay), then scale by
            # ||p|| / ||update|| per parameter tensor
            u = -d + weight_decay * p.astype(jnp.float32)
            pn = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
            un = jnp.sqrt(jnp.sum(jnp.square(u)))
            trust = jnp.where((pn > 0) & (un > 0), pn / jnp.maximum(un, eps),
                              1.0)
            return -lr_t * trust * u

        return jax.tree_util.tree_map(per_leaf, dirs, params), state

    update._lamb_args = {"lr": lr, "b1": b1, "b2": b2, "eps": eps,
                         "weight_decay": weight_decay}
    return Optimizer(inner.init, update)


def clip_by_global_norm(opt: Optimizer, max_norm: float, *,
                        axis: "str | None" = None) -> Optimizer:
    """Wrap an optimizer with global-norm gradient clipping.

    ``axis=None`` (the default) assumes every device holds the FULL
    gradient tree (implicit mode, or explicit mode after the pmean), so
    the local sum of squares already IS the global one.  Under ZeRO-1
    weight-update sharding each device holds a disjoint 1/N shard of the
    reduced gradients — a local norm there would clip each shard by its
    own magnitude and the trajectory would silently diverge from dense.
    ``axis="data"`` is the partition-aware variant: local squared sums are
    ``psum``'d over the mesh axis before the sqrt, so the clip scale is
    the true global norm on every shard (grad_sync rebuilds its wrapped
    optimizer with this automatically; see GradSyncEngine).
    """

    def update(grads, state, params=None):
        leaves = jax.tree_util.tree_leaves(grads)
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        if axis is not None:
            from jax import lax
            sq = lax.psum(sq, axis)
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
        grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
        return opt.update(grads, state, params)

    # Introspection hooks for grad_sync: the engine must re-derive this
    # wrapper with the data axis when the optimizer runs on shards.
    update._clip_inner = opt
    update._clip_max_norm = max_norm
    update._clip_axis = axis
    return Optimizer(opt.init, update, elementwise=opt.elementwise)


def init_partitioned(opt: Optimizer, params: Any, out_shardings: Any) -> Any:
    """Partition-aware ``Optimizer.init``: materialize the optimizer state
    with explicit per-leaf shardings instead of inheriting the params'
    (usually replicated) placement.

    This is the ZeRO-1 memory lever (cf. PAPERS.md, "Automatic
    Cross-Replica Sharding of Weight Update"): Adam moments for ``params``
    sharded over an N-way data axis cost 1/N the replicated HBM, because
    the state is BORN sharded — there is never a replicated copy to shard
    after the fact.  ``out_shardings`` is a sharding (or pytree of
    shardings, prefix-broadcast like ``jax.jit``'s) for the state that
    ``opt.init(params)`` returns; GSPMD materializes each leaf directly
    into its shards.  States with no array leaves (plain SGD's ``()``)
    return as-is."""
    if not jax.tree_util.tree_leaves(jax.eval_shape(opt.init, params)):
        return opt.init(params)
    return jax.jit(opt.init, out_shardings=out_shardings)(params)


#: Single source of the optimizer-name registry (the --optimizer CLI flag
#: and anything else resolving optimizers by name go through get()).
BY_NAME = {"sgd": sgd, "momentum": momentum, "adam": adam, "adamw": adamw,
           "adafactor": adafactor, "lamb": lamb}


def get(name: str) -> Callable[..., Optimizer]:
    """Optimizer constructor by name; raises with the valid names."""
    try:
        return BY_NAME[name]
    except KeyError:
        raise ValueError(f"--optimizer must be one of {sorted(BY_NAME)}, "
                         f"got {name!r}") from None


def schedule_from_config(train_cfg, total_steps: int):
    """Resolve TrainConfig's lr fields into a float or schedule — the ONE
    place --lr_schedule is interpreted, shared by every workload.
    ``total_steps`` must count every optimizer update the run will perform
    (benchmark drivers include their compile-warmup steps)."""
    if train_cfg.lr_schedule == "constant":
        return train_cfg.learning_rate
    if train_cfg.lr_schedule == "cosine":
        return warmup_cosine(train_cfg.learning_rate, train_cfg.warmup_steps,
                             total_steps, final_frac=train_cfg.lr_final_frac)
    raise ValueError(f"--lr_schedule must be 'constant' or 'cosine', got "
                     f"{train_cfg.lr_schedule!r}")


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int,
                  final_frac: float = 0.0) -> Callable:
    """LR schedule for the BERT/ResNet workloads."""

    def schedule(step):
        step = step.astype(jnp.float32)
        warm = peak_lr * step / max(warmup_steps, 1)
        prog = jnp.clip((step - warmup_steps) / max(total_steps - warmup_steps, 1),
                        0.0, 1.0)
        cos = peak_lr * (final_frac + (1 - final_frac) * 0.5 *
                         (1 + jnp.cos(jnp.pi * prog)))
        return jnp.where(step < warmup_steps, warm, cos)

    return schedule
