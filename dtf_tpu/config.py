"""Configuration system.

The reference had two overlapping, half-dead flag systems (``tf.app.flags``
with exactly ``job_name``/``task_index`` at tf_distributed.py:14-16, plus a
vestigial argparse block at tf_distributed.py:133-163 whose parsed host lists
were never wired into the ClusterSpec) and hardcoded everything else: cluster
membership (tf_distributed.py:9-10), hyperparameters (batch_size=100,
learning_rate=0.0005, training_epochs, tf_distributed.py:21-24) and the log
dir (``/tmp/mnist/1``, tf_distributed.py:24).

Here there is ONE config system built on dataclasses + argparse:

* the reference CLI contract is preserved: ``--job_name`` and ``--task_index``
  are accepted (BASELINE.json north star).  Under SPMD there are no
  per-role programs, so ``--job_name`` values map as follows:
  ``worker`` -> normal participant, ``ps`` -> accepted with a warning (the
  parameter-server role does not exist in an all-reduce design; the process
  participates as a peer).  ``--task_index`` resolves to the JAX process
  index (a mesh coordinate), not a gRPC host:port slot.
* cluster topology is a flag (``--coordinator_address``, ``--num_processes``)
  — finishing what the reference's dead argparse block started — instead of
  hardcoded IPs; zero flags == single-process mode, which the reference could
  not do at all.
* hyperparameters live in :class:`TrainConfig` with the reference's values as
  defaults for the MNIST workload (for comparability).
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
from typing import Optional

log = logging.getLogger("dtf_tpu")


@dataclasses.dataclass
class ClusterConfig:
    """Where this process sits in the (possibly multi-host) job.

    Replaces the reference's ClusterSpec + Server + flags
    (tf_distributed.py:9-18).
    """

    job_name: str = "worker"          # compat: reference tf_distributed.py:14
    task_index: int = 0               # compat: reference tf_distributed.py:15
    coordinator_address: Optional[str] = None  # host:port of process 0 (DCN control plane)
    num_processes: int = 1
    # Mesh request, e.g. "data=-1" or "data=4,tensor=2"; -1 infers from device count.
    mesh: str = "data=-1"
    platform: Optional[str] = None    # force jax platform (cpu/tpu); None = auto
    # >0: run on N simulated CPU devices (the SURVEY.md §4 test trick,
    # usable from the CLI: --simulated_devices 8 --mesh data=2,seq=4).
    # Implies platform=cpu.  Must be set before the first device query.
    simulated_devices: int = 0
    # Multi-host failure domain (resilience/health.py).  hb_interval_s > 0
    # arms per-process heartbeats + the poison-pill coordinated abort: a
    # peer whose beats stop for hb_miss_budget intervals gets the healthy
    # hosts OUT of the wedged collective (exit 71) instead of hanging
    # forever.  health_dir is the rendezvous: a SHARED directory
    # (GCS/NFS), or "tcp://host:port" to run the coordinator-hosted beat
    # service when there is no shared filesystem.  hb_boot_grace_s covers
    # startup skew (a peer that has never beaten is only aged after it).
    health_dir: Optional[str] = None
    hb_interval_s: float = 0.0        # 0 disables the health subsystem
    hb_miss_budget: int = 3
    hb_boot_grace_s: float = 30.0
    # Elastic restart: when the fixed --mesh no longer matches the device
    # count (a relaunch on fewer surviving hosts), shrink the data axis to
    # fit instead of failing (parallel/mesh.shrink_to_devices).
    elastic: bool = False
    # XLA latency-hiding-scheduler preset (TPU): lets the compiler slide
    # async collectives (zero1's bucket reduce-scatters, the param
    # all-gather) under compute instead of serializing them at the end of
    # the backward.  Applied by cluster.bootstrap via LIBTPU_INIT_ARGS
    # BEFORE backend init, so it is inert on CPU/simulated runs (libtpu
    # never loads) and a no-op once a backend exists.  Pair with
    # --grad_sync zero1_overlap (DESIGN.md §4.1).
    xla_overlap: bool = False

    def __post_init__(self):
        if self.job_name not in ("ps", "worker"):
            raise ValueError(
                f"job_name must be 'ps' or 'worker' (reference CLI contract, "
                f"tf_distributed.py:14), got {self.job_name!r}")
        if self.hb_interval_s > 0 and not self.health_dir:
            # Validate here, not first at fit time: a multi-host job must
            # not burn bootstrap + compile on every host before learning
            # its heartbeat config is incomplete.
            raise ValueError(
                "--hb_interval_s > 0 needs --health_dir: a SHARED "
                "directory every host can reach, or tcp://host:port for "
                "the coordinator-hosted beat service")
        if self.job_name == "ps":
            log.warning(
                "--job_name=ps: the parameter-server role does not exist in "
                "the all-reduce design (SURVEY.md §3.1); this process joins "
                "as a peer.")

    @property
    def process_id(self) -> int:
        """The reference's task_index becomes the SPMD process index."""
        return self.task_index

    @property
    def is_coordinator(self) -> bool:
        """Chief election: reference used ``is_chief=(task_index==0)``
        (tf_distributed.py:92)."""
        return self.process_id == 0


@dataclasses.dataclass
class TrainConfig:
    """Training hyperparameters.

    Defaults match the reference MNIST run for comparability:
    batch_size=100, learning_rate=0.0005, epochs=20 (tf_distributed.py:21-23),
    log frequency 100 steps (tf_distributed.py:25), seed 1
    (tf_distributed.py:49).
    """

    batch_size: int = 100             # per-step GLOBAL batch (see note below)
    learning_rate: float = 0.0005
    # Optimizer for the pretrain-benchmark workloads (mnist keeps the
    # reference's SGD); valid names are optim.BY_NAME's keys.
    optimizer: str = "adam"
    # LR schedule for the pretrain benchmarks: "constant" or "cosine"
    # (optim.warmup_cosine: linear warmup over warmup_steps, cosine decay
    # to lr_final_frac * learning_rate by the end of the run).
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    lr_final_frac: float = 0.0
    epochs: int = 20
    log_frequency: int = 100
    seed: int = 1
    logdir: str = "/tmp/dtf_tpu"      # ref hardcoded /tmp/mnist/1 (tf_distributed.py:24)
    # Async->sync semantics note (SURVEY.md §7 "hard parts"): the reference's
    # async PS applies each worker's 100-sample gradient independently; under
    # synchronous psum the framework uses a GLOBAL batch of `batch_size`
    # sharded over the data axis by default (matches the optimization
    # trajectory of one reference worker).  Set per_device_batch instead to
    # match per-worker *compute* (global = per_device * num_devices).
    per_device_batch: Optional[int] = None
    # Gradient accumulation: split each global batch into this many
    # microbatches inside the compiled step (same trajectory, less
    # activation memory).
    grad_accum: int = 1
    # Gradient-sync + weight-update strategy (parallel/grad_sync.py):
    # "dense" = pmean the full gradient tree and run a fully replicated
    # optimizer update (the default and correctness oracle); "zero1" =
    # ZeRO-1 weight-update sharding — bucketed reduce-scatter of the
    # gradients, per-shard optimizer update against SHARDED optimizer
    # state (Adam moments cost 1/N per device on an N-way data axis),
    # all-gather of the updated params; "zero1_overlap" = zero1 scheduled
    # inside the grad-accumulation skeleton so each microbatch's bucket
    # reduce-scatter overlaps the next microbatch's backward (pair with
    # --grad_accum > 1 and, on TPU, --xla_overlap).  zero1* strategies
    # run the explicit shard_map step (implicit mode auto-switches) and
    # need an elementwise optimizer (sgd/momentum/adam/adamw) or lamb
    # (trust-ratio norms psum'd across shards); adafactor is rejected.
    grad_sync: str = "dense"
    # Reduced-precision collective wire format for gradient sync
    # (EQuARX-motivated, PAPERS.md): "bf16" ships (g/N).astype(bf16) —
    # mean-preserving pre-scaling, one rounding per value — through the
    # reduce-scatter/pmean; "int8" ships the block-scaled format
    # (parallel/quantize.py: int8 payload + one f32 scale per 256
    # values, ~4x less wire than f32, ~2x less than bf16); "int8_ring"
    # is the EQuARX schedule — a segmented ring reduce-scatter that
    # requantizes the int8 partial sum on EVERY hop, (n-1)/n of the
    # int8 wire bytes on an n-way axis (comm/hops counts the hops);
    # None/"f32" keeps the exact f32 wire.  Composes with every
    # --grad_sync strategy; requires the explicit step (shard_map owns
    # the collectives).
    grad_comm_dtype: Optional[str] = None
    # Sharding planner (parallel/planner.py): "auto" derives a
    # measurement-driven ShardingPlan (grad-sync strategy, wire dtype,
    # bucket size, activation sharding, remat policy) from the model
    # template + mesh + HBM budget, predicting per-device HBM/step time
    # from captured CostCards (analytic fallback) and rejecting
    # infeasible pairs loudly.  Hand-pinned flags always override the
    # plan's choices.  None keeps today's fully manual behavior.
    plan: Optional[str] = None
    # Per-device HBM budget (GiB) the planner plans against; 0/None =
    # the detected device capacity (CPU sim pins a synthetic 4 GiB).
    plan_hbm_gb: float = 0.0
    # int8-wire rounding mode: "nearest" (deterministic) or "stochastic"
    # (unbiased floor(v/s + u) draws seeded from the step rng, so
    # trajectories stay reproducible run-to-run).
    quant_rounding: str = "nearest"
    # zero1 bucket size (MB of f32 gradient per flattened bucket): smaller
    # buckets pipeline the reduce-scatter earlier under zero1_overlap,
    # larger buckets amortize per-collective latency.
    grad_bucket_mb: float = 4.0
    # Multi-process data path: each host feeds only ITS contiguous slice of
    # every global batch (Dataset.process_shard + put_process_batch —
    # bitwise-identical trajectory to the global-batch path).  Disable to
    # fall back to every host materializing the full global batch.
    shard_data: bool = True
    checkpoint_every: int = 0         # steps; 0 disables (ref had no checkpointing, SURVEY §5.4)
    resume: bool = False
    # SIGTERM (TPU preemption / spot reclamation) -> checkpoint at the next
    # step boundary and exit cleanly.  Active whenever checkpointing is
    # configured (checkpoint_every > 0 or resume).
    preemption_save: bool = True
    # Also treat SIGINT (ctrl-C, some schedulers' first nudge) as a
    # preemption: checkpoint at the next boundary and exit 0 instead of
    # dying with KeyboardInterrupt mid-step.
    preempt_sigint: bool = False
    # Straggler detection (resilience/health.flag_stragglers): at every
    # logging sync point, allgather each host's avg step time and flag
    # hosts slower than median * straggler_factor (metrics
    # health/step_ms_p<k> and health/stragglers).  <= 1 disables; 1.5-2.0
    # is a sane production range.  Multi-process only.
    straggler_factor: float = 0.0
    dtype: str = "float32"
    # Observability (SURVEY §5.1/§5.2; the reference had wall-clock prints
    # only).  profile_dir: capture an XLA trace of steps
    # [profile_start, profile_start + profile_steps).  determinism_every:
    # every N steps verify all processes hold bitwise-identical metrics
    # (the SPMD moral equivalent of the reference's absent race detector).
    profile_dir: Optional[str] = None
    profile_start: int = 10
    profile_steps: int = 3
    # After the run, aggregate the captured trace's device-op time and
    # print the top entries (utils.profiling.summarize_trace) — the
    # one-flag MFU-eater locator.
    profile_summary: bool = False
    determinism_every: int = 0        # 0 disables
    # Failure detection (SURVEY §5.3; the reference hung forever on a dead
    # peer): fail the process fast if the train loop makes no progress for
    # this many seconds.  0 disables.  Size above the worst gap between
    # logging sync points (compile time included), not above the step time;
    # eval and checkpoint saves are excluded (the watchdog suspends around
    # them).
    hang_timeout_s: float = 0.0
    # Self-healing (DESIGN.md §5): guard every update against non-finite
    # loss/gradients inside the compiled step — a bad step is SKIPPED
    # (params/opt state unchanged, a replicated `skipped` counter bumps)
    # instead of poisoning the parameters.  Replica-uniform by construction,
    # one isfinite scan per step of overhead.
    nonfinite_guard: bool = True
    # After this many CONSECUTIVE guarded-bad steps (a device-side streak
    # counter, checked at logging sync points so the hot loop stays free of
    # per-step host syncs), roll the params/opt state back to the last good
    # checkpoint — or raise TrainingDiverged when there is none / the
    # rollback budget (max_rollbacks) is spent.  0 disables the policy
    # (bad steps are still skipped and counted).
    bad_step_limit: int = 5
    max_rollbacks: int = 2
    # Async device-prefetch input pipeline (data/prefetch.py): a background
    # producer thread runs fetch -> chaos poison -> sharded device_put for
    # the next N batches into a bounded queue of DEVICE-resident batches,
    # so host data time overlaps the dispatched step instead of
    # serializing with it.  Same trajectory bitwise (same batch order,
    # same per-step rng); goodput books "data" time only when the loop
    # actually blocks on an empty queue (data/prefetch_stall).  0 restores
    # the serial fetch->put->dispatch path; 2 = double buffering.
    prefetch: int = 2
    # Persistent XLA compilation cache directory (train/compile_cache.py):
    # compiled executables are keyed by HLO and reused ACROSS processes,
    # so supervisor restarts / elastic relaunches / --resume relaunches
    # skip the backend compile instead of re-paying it every attempt.
    # Hits/misses surface as compile/cache_hit + compile/cache_miss
    # counters.  None disables (jax default behavior).
    compile_cache: Optional[str] = None
    # AOT warmup: .lower().compile() the train step before the first loop
    # dispatch (shapes probed from the dataset), overlapping the
    # prefetcher's initial fill — the compile books into an explicit
    # "compile" goodput bucket instead of hiding in the first step, and
    # with --compile_cache a warm attempt's warmup is a cache read.
    # Falls back silently to compile-on-first-dispatch for datasets that
    # can't be shape-probed (no ``examples`` accessor).
    aot_warmup: bool = True
    # Fault-injection spec for the chaos harness (resilience/chaos.py), e.g.
    # "nan_grad@17,corrupt_ckpt@latest,sigterm@40,stall@25:3s,
    # loader_error@9,seed=7".  None disables.
    chaos: Optional[str] = None
    # Workload CLIs with supervision support (workloads/mnist.py) wrap the
    # fit in resilience.supervisor.run_supervised with this restart budget:
    # crash or preemption -> restore the last checkpoint and go again.
    # 0 disables (single attempt).
    max_restarts: int = 0
    # Telemetry spine (dtf_tpu/telemetry): span tracer to
    # <logdir>/spans.p<k>.jsonl, registry snapshots to
    # <logdir>/telemetry.json, goodput accounting.  --no-telemetry turns
    # the on-disk artifacts off (the in-process registry still runs).
    telemetry: bool = True
    # Live introspection endpoint (telemetry/live.py): mount
    # /statz /healthz /tracez /slo on 127.0.0.1:admin_port for the whole
    # process life (supervisor restarts rebind onto the same server; 0 =
    # ephemeral port).  None disables.  Long training runs get the same
    # live window as the serving CLI's --admin_port.
    admin_port: Optional[int] = None
    # Fleet observability plane (telemetry/fleet.py): a SHARED directory
    # (GCS/NFS) or "tcp://host:port" every host can reach.  Arms
    # fleet/sync barrier marks at the logging-sync and checkpoint
    # boundaries, per-host book publication, and (on the coordinator)
    # the live skew/blame attribution + /fleetz rollup + fleet.json.
    # The multi-process test rigs configure the plane explicitly with
    # their out-of-band identity instead (telemetry.fleet.configure).
    fleet_dir: Optional[str] = None
    # Attempt tag for metrics.csv rows (telemetry/report de-duplicates
    # overlapping step ranges by latest attempt).  0 = automatic: any
    # resumed run — in-process supervisor restart or --resume relaunch —
    # continues past the file's last recorded attempt (MetricLogger.
    # for_config); set explicitly only when an external scheduler counts
    # its own relaunches.
    attempt: int = 0

    def __post_init__(self):
        if self.profile_summary and not self.profile_dir:
            raise ValueError(
                "--profile_summary aggregates a captured trace; it needs "
                "--profile_dir to capture one")
        if self.prefetch < 0:
            raise ValueError(
                f"--prefetch is a queue depth (0 disables the async input "
                f"pipeline); got {self.prefetch}")
        # Literal mirror of parallel.grad_sync.STRATEGIES — config must
        # stay importable without jax (a pinned test keeps them in sync).
        if self.grad_sync not in ("dense", "zero1", "zero1_overlap"):
            raise ValueError(
                f"--grad_sync must be one of "
                f"('dense', 'zero1', 'zero1_overlap'), got "
                f"{self.grad_sync!r}")
        if self.grad_comm_dtype not in (None, "bf16", "bfloat16", "f32",
                                        "float32", "int8", "int8_ring"):
            raise ValueError(
                f"--grad_comm_dtype must be 'f32', 'bf16', 'int8' or "
                f"'int8_ring', got {self.grad_comm_dtype!r}")
        # Literal mirror of parallel.quantize.ROUNDINGS (jax-free import,
        # same pinning rule as the STRATEGIES mirror above).
        if self.quant_rounding not in ("nearest", "stochastic"):
            raise ValueError(
                f"--quant_rounding must be 'nearest' or 'stochastic', "
                f"got {self.quant_rounding!r}")
        if (self.quant_rounding == "stochastic"
                and self.grad_comm_dtype not in ("int8", "int8_ring")):
            # Only the block-scaled int8 wires consult the rounding mode;
            # silently running nearest under a flag that asked for
            # stochastic would poison trajectory attribution.
            raise ValueError(
                "--quant_rounding stochastic only applies to the "
                "--grad_comm_dtype int8/int8_ring wires (the f32/bf16 "
                "wires have no quantizer); drop the flag or switch the "
                "wire to int8")
        if self.grad_bucket_mb <= 0:
            raise ValueError(
                f"--grad_bucket_mb must be > 0, got {self.grad_bucket_mb}")
        if self.plan not in (None, "auto"):
            raise ValueError(
                f"--plan must be 'auto' (or unset for fully manual "
                f"sharding), got {self.plan!r}")
        if self.plan_hbm_gb < 0:
            raise ValueError(
                f"--plan_hbm_gb must be >= 0 (0 = detected device "
                f"capacity), got {self.plan_hbm_gb}")


def _field_type(cls, f: dataclasses.Field) -> type:
    """Resolve a dataclass field's runtime type (annotations are strings under
    ``from __future__ import annotations``; unwrap Optional[T])."""
    import typing
    hints = typing.get_type_hints(cls)
    t = hints[f.name]
    if typing.get_origin(t) is typing.Union:
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if len(args) == 1:
            t = args[0]
    return t if isinstance(t, type) else str


def _add_dataclass_args(parser: argparse.ArgumentParser, cls, prefix: str = "") -> None:
    for f in dataclasses.fields(cls):
        if f.name in ("job_name", "task_index"):
            continue  # added explicitly to preserve reference help text
        typ = _field_type(cls, f)
        kwargs = {"default": None}
        if typ is bool:
            # default-True bools need an off switch (--no-<flag>)
            kwargs["action"] = (argparse.BooleanOptionalAction
                                if f.default is True else "store_true")
        elif typ in (int, float, str):
            kwargs["type"] = typ
        else:
            kwargs["type"] = str
        parser.add_argument(f"--{prefix}{f.name}", **kwargs)


def build_parser(description: str = "dtf_tpu") -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=description)
    # Reference CLI contract (tf_distributed.py:14-15), semantics re-targeted.
    parser.add_argument(
        "--job_name", default="worker",
        help="Compat with the reference ('ps'|'worker'). SPMD has no PS role; "
             "'ps' is accepted with a warning and the process joins as a peer.")
    parser.add_argument(
        "--task_index", type=int, default=0,
        help="Compat with the reference; resolves to the JAX process index "
             "(a mesh coordinate), not a gRPC host:port slot.")
    _add_dataclass_args(parser, ClusterConfig)
    _add_dataclass_args(parser, TrainConfig)
    return parser


def _from_namespace(cls, ns: argparse.Namespace):
    kwargs = {}
    for f in dataclasses.fields(cls):
        v = getattr(ns, f.name, None)
        if v is not None:
            kwargs[f.name] = v
    return cls(**kwargs)


def parse_args(argv: Optional[list] = None,
               description: str = "dtf_tpu") -> tuple[ClusterConfig, TrainConfig]:
    ns = build_parser(description).parse_args(argv)
    cluster_cfg = _from_namespace(ClusterConfig, ns)  # validates job_name
    train_cfg = _from_namespace(TrainConfig, ns)
    return cluster_cfg, train_cfg
