# Shared chip-blitz step runner, sourced by scripts/chip_blitz_r*.sh.
# Requires $OUT to be set.  Counts failures in $FAILS; a step that fails
# must NOT stop the rest, and a post-step health probe catches a wedged
# relay early (a timeout firing mid-compile is the known wedging action,
# so step timeouts are sized generously by the callers).
FAILS=0
run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 to=$2 rc; shift 2
  echo "=== $name (timeout ${to}s) ==="
  timeout "$to" "$@" >"$OUT/$name.log" 2>&1
  rc=$?
  echo "rc=$rc -> $OUT/$name.log"
  [ "$rc" -ne 0 ] && FAILS=$((FAILS + 1))
  tail -5 "$OUT/$name.log"
  timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1 \
    || echo "WARNING: relay health probe FAILED after $name - STOP and check"
}
