#!/usr/bin/env bash
# Full chaos / self-healing matrix (DESIGN.md §5, "Failure model & recovery").
#
# Tier-1 already runs the fast chaos unit+integration tests (marker `chaos`,
# none marked `slow`); this script is the exhaustive pass: every chaos-marked
# test INCLUDING slow ones, plus CLI-level injection runs of the mnist
# workload that exercise the spec parser, the supervisor and the watchdog
# through the real entry point.
#
# Usage: scripts/run_chaos_suite.sh [extra pytest args]
set -uo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
# tests/_mp_health.py imports dtf_tpu when spawned as a script (pytest's
# rig injects the repo root via child_env; here we do it ourselves).
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

fail=0

echo "== chaos-marked tests (including slow) =="
# A trailing -m overrides pytest.ini's default '-m "not slow"'.
python -m pytest tests/ -q -p no:cacheprovider -m chaos "$@" || fail=1

logdir=$(mktemp -d)
echo "== CLI: supervised self-healing run (nan_grad + sigterm + corrupt) =="
# 12800 synthetic examples / batch 512 = 25 steps/epoch; SIGTERM at step 12
# preempts attempt 1, the corrupted latest checkpoint forces the restore to
# fall back, attempt 2 completes -> exit 0 and the reference's final "done".
python -m dtf_tpu.workloads.mnist \
    --epochs 1 --batch_size 512 --init fan_in --log_frequency 5 \
    --logdir "$logdir/heal" --checkpoint_every 5 --max_restarts 2 \
    --chaos "nan_grad@4,sigterm@12,corrupt_ckpt@latest,loader_error@2" \
    | tee "$logdir/heal.log"
grep -q "^done$" "$logdir/heal.log" || { echo "FAIL: supervised run did not complete"; fail=1; }

echo "== CLI: stall trips the watchdog (exit 70 + all-thread stacks) =="
python -m dtf_tpu.workloads.mnist \
    --epochs 1 --batch_size 512 --init fan_in --log_frequency 5 \
    --logdir "$logdir/hang" --hang_timeout_s 2 \
    --chaos "stall@6:30s" 2> "$logdir/hang.err"
rc=$?
if [ "$rc" -ne 70 ]; then
    echo "FAIL: expected watchdog exit 70, got rc=$rc"; fail=1
fi
grep -q "WATCHDOG" "$logdir/hang.err" || { echo "FAIL: no watchdog message"; fail=1; }
grep -Eq "Thread 0x|Current thread" "$logdir/hang.err" \
    || { echo "FAIL: no thread stacks in watchdog dump"; fail=1; }

echo "== CLI: diverged-without-checkpoint fails fast (nonzero exit) =="
if python -m dtf_tpu.workloads.mnist \
    --epochs 1 --batch_size 512 --init fan_in --log_frequency 1 \
    --logdir "$logdir/div" --bad_step_limit 2 \
    --chaos "nan_grad@3,nan_grad@4" 2> "$logdir/div.err"; then
    echo "FAIL: persistent NaNs should not exit 0"; fail=1
fi
grep -q "TrainingDiverged" "$logdir/div.err" || { echo "FAIL: no TrainingDiverged"; fail=1; }

echo "== CLI: diverged under supervision fails FAST (no restart burned) =="
# Terminal-failure classification: a deterministic divergence must raise
# through the supervisor on attempt 0, not replay through --max_restarts.
python -m dtf_tpu.workloads.mnist \
    --epochs 1 --batch_size 512 --init fan_in --log_frequency 1 \
    --logdir "$logdir/div2" --bad_step_limit 2 --max_restarts 3 \
    --checkpoint_every 1000000 \
    --chaos "nan_grad@3,nan_grad@4" > "$logdir/div2.log" 2>&1
rc=$?
if [ "$rc" -eq 0 ]; then
    echo "FAIL: supervised persistent NaNs should not exit 0"; fail=1
fi
grep -q "TrainingDiverged" "$logdir/div2.log" || { echo "FAIL: no TrainingDiverged"; fail=1; }
if grep -q "restarting from last" "$logdir/div2.log"; then
    echo "FAIL: supervisor burned a restart on a terminal failure"; fail=1
fi

echo "== CLI: host-fault matrix (host_down -> coordinated abort -> elastic restart) =="
# Two simulated hosts sharing a rendezvous dir; host 1 dies abruptly at
# its step 20, host 0 must exit 71 via the poison pill, and the elastic
# relaunch of the survivor must resume and complete (tests/_mp_health.py
# is the same worker the pytest acceptance pair drives).
hostdir=$(mktemp -d)
chaos_spec="slow_host@0:0:250ms,slow_host@0:1:100ms,host_down@20:1"
python tests/_mp_health.py 0 2 "$hostdir" 2000 4 "$chaos_spec" > "$logdir/h0.log" 2>&1 &
pid0=$!
python tests/_mp_health.py 1 2 "$hostdir" 2000 4 "$chaos_spec" > "$logdir/h1.log" 2>&1 &
pid1=$!
wait "$pid1"; rc1=$?
wait "$pid0"; rc0=$?
if [ "$rc0" -ne 71 ]; then
    echo "FAIL: healthy host should exit EXIT_PEER_LOST(71), got $rc0"; fail=1
fi
if [ "$rc1" -ne 137 ] && [ "$rc1" -ne 9 ]; then
    echo "FAIL: host_down host should die by SIGKILL, got $rc1"; fail=1
fi
[ -f "$hostdir/health/poison.json" ] || { echo "FAIL: no poison pill planted"; fail=1; }
python tests/_mp_health.py 0 1 "$hostdir" 30 2 > "$logdir/h_elastic.log" 2>&1
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "FAIL: elastic relaunch on the survivor failed (rc=$rc)"; fail=1
fi
grep -q "resumed from step" "$logdir/h_elastic.log" \
    || { echo "FAIL: elastic relaunch did not resume the checkpoint"; fail=1; }
grep -q "MP_HEALTH_DONE" "$logdir/h_elastic.log" \
    || { echo "FAIL: elastic relaunch did not complete"; fail=1; }
rm -rf "$hostdir"

rm -rf "$logdir"
if [ "$fail" -ne 0 ]; then
    echo "CHAOS SUITE: FAIL"
    exit 1
fi
echo "CHAOS SUITE: PASS"
