#!/bin/bash
# Full test suite (fast + slow), one pytest PROCESS PER FILE.
# A single-process run of all ~420 tests accumulates enough XLA-CPU
# client state on this 1-core rig to segfault partway through
# (reproduced twice at different tests; every file passes in
# isolation) — per-file processes bound the accumulation and give the
# same coverage.  Multi-process tests manage their own subprocesses.
# Usage: bash scripts/run_full_suite.sh [extra pytest args...]
set -u
cd "$(dirname "$0")/.." || exit 1
FAILS=0
for f in tests/test_*.py; do
  echo "=== $f ==="
  python -m pytest "$f" -q -m "slow or not slow" -p no:cacheprovider "$@"
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: $f (rc=$rc)"; }
done
# Chaos lane: the full fault-injection matrix (pytest -m chaos plus the
# CLI-level injection runs, including the host-fault matrix) so ONE
# command covers the whole suite.  Skip with NO_CHAOS_LANE=1.
if [ "${NO_CHAOS_LANE:-0}" != "1" ]; then
  echo "=== chaos lane (scripts/run_chaos_suite.sh) ==="
  bash scripts/run_chaos_suite.sh
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: chaos lane (rc=$rc)"; }
fi
# Telemetry lane (DESIGN.md §6): name lint, then a short chaos'd MNIST
# job whose run report must render AND whose goodput categories must sum
# to measured wall-clock within 10% (report --check).  Skip with
# NO_TELEMETRY_LANE=1.
if [ "${NO_TELEMETRY_LANE:-0}" != "1" ]; then
  echo "=== telemetry lane (name lint + chaos'd run + report --check) ==="
  python scripts/check_telemetry_names.py \
    || { FAILS=$((FAILS + 1)); echo "FAILED: telemetry name lint"; }
  tdir=$(mktemp -d)
  JAX_PLATFORMS=cpu python -m dtf_tpu.workloads.mnist \
      --epochs 1 --batch_size 512 --init fan_in --log_frequency 5 \
      --logdir "$tdir" --checkpoint_every 5 --max_restarts 2 \
      --chaos "nan_grad@4,stall@7:1s,sigterm@11" > "$tdir/run.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: telemetry lane run (rc=$rc)"; tail -5 "$tdir/run.log"; }
  python -m dtf_tpu.telemetry.report "$tdir" --check | tee "$tdir/report.log"
  rc=${PIPESTATUS[0]}       # the report's exit status, not tee's
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: report --check (rc=$rc)"; }
  grep -q "Goodput breakdown" "$tdir/report.log" \
    && grep -q "Top spans" "$tdir/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: report missing sections"; }
fi
echo "=== full suite done; failed files: $FAILS ==="
exit $([ "$FAILS" -eq 0 ] && echo 0 || echo 1)
