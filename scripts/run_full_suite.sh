#!/bin/bash
# Full test suite (fast + slow), one pytest PROCESS PER FILE.
# A single-process run of all ~420 tests accumulates enough XLA-CPU
# client state on this 1-core rig to segfault partway through
# (reproduced twice at different tests; every file passes in
# isolation) — per-file processes bound the accumulation and give the
# same coverage.  Multi-process tests manage their own subprocesses.
# Usage: bash scripts/run_full_suite.sh [extra pytest args...]
set -u
cd "$(dirname "$0")/.." || exit 1
FAILS=0
for f in tests/test_*.py; do
  echo "=== $f ==="
  python -m pytest "$f" -q -m "slow or not slow" -p no:cacheprovider "$@"
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: $f (rc=$rc)"; }
done
# Chaos lane: the full fault-injection matrix (pytest -m chaos plus the
# CLI-level injection runs, including the host-fault matrix) so ONE
# command covers the whole suite.  Skip with NO_CHAOS_LANE=1.
if [ "${NO_CHAOS_LANE:-0}" != "1" ]; then
  echo "=== chaos lane (scripts/run_chaos_suite.sh) ==="
  bash scripts/run_chaos_suite.sh
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: chaos lane (rc=$rc)"; }
fi
# Telemetry lane (DESIGN.md §6): name lint, then a short chaos'd MNIST
# job whose run report must render AND whose goodput categories must sum
# to measured wall-clock within 10% (report --check).  Skip with
# NO_TELEMETRY_LANE=1.
if [ "${NO_TELEMETRY_LANE:-0}" != "1" ]; then
  echo "=== telemetry lane (name lint + chaos'd run + report --check) ==="
  python scripts/check_telemetry_names.py \
    || { FAILS=$((FAILS + 1)); echo "FAILED: telemetry name lint"; }
  tdir=$(mktemp -d)
  # Environment-sized flake fix (ISSUE 12): on zero-egress rigs the
  # MNIST fallback set is 12800 examples = 25 steps at batch 512, while
  # real-MNIST rigs get 117 — the old cost gate was calibrated on the
  # latter and failed AT SEED on the former (final cost 2.42).  Write a
  # fixture dataset SIZED BY STEPS (60 steps/epoch, deterministic IDX
  # bytes; separable enough that the 2-epoch budget descends WELL below
  # chance) and train on it everywhere, so the lane's trajectory — and
  # the gate pinned from it — is rig-independent.
  python - "$tdir/data" <<'PYEOF'
import sys
from dtf_tpu.data.fixtures import write_mnist_idx
write_mnist_idx(sys.argv[1], n_train=512 * 60, n_test=1024, seed=1,
                noise=0.15, label_noise=0.02, spread=0.5)
PYEOF
  JAX_PLATFORMS=cpu python -m dtf_tpu.workloads.mnist \
      --epochs 2 --batch_size 512 --init fan_in --log_frequency 5 \
      --learning_rate 0.3 --data_dir "$tdir/data" \
      --logdir "$tdir" --checkpoint_every 5 --max_restarts 2 \
      --chaos "nan_grad@4,stall@7:1s,sigterm@11" > "$tdir/run.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: telemetry lane run (rc=$rc)"; tail -5 "$tdir/run.log"; }
  # --max_rollbacks/--max_final_cost arm the same check_gates the
  # scenario matrix gates with (one gate implementation, DESIGN.md §8);
  # the run above restarts once but never rolls back.  The 120-step
  # fixture trajectory lands at 1.3978 — the 1.6 pin keeps ~14%
  # headroom while sitting far below random-chance cross-entropy
  # (ln 10 ~= 2.303), so a run that learns NOTHING still fails.
  python -m dtf_tpu.telemetry.report "$tdir" --check \
      --max_rollbacks 0 --max_final_cost 1.6 | tee "$tdir/report.log"
  rc=${PIPESTATUS[0]}       # the report's exit status, not tee's
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: report --check (rc=$rc)"; }
  grep -q "gate max_final_cost: OK" "$tdir/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: report threshold gates missing"; }
  grep -q "Goodput breakdown" "$tdir/report.log" \
    && grep -q "Top spans" "$tdir/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: report missing sections"; }
fi
# Prefetch/compile-cache lane (DESIGN.md "Compilation discipline"):
# the same chaos'd MNIST job serial (--prefetch 0) then overlapped
# (--prefetch 2), both against one --compile_cache dir.  Asserts the
# goodput "data" fraction strictly drops with prefetch, the prefetch
# instruments landed, the second run hit the persistent compile cache,
# and its "compile" bucket shrank.  Skip with NO_PREFETCH_LANE=1.
if [ "${NO_PREFETCH_LANE:-0}" != "1" ]; then
  echo "=== prefetch/compile-cache lane (overlap A/B + cache reuse) ==="
  pdir=$(mktemp -d)
  # Three runs against ONE cache dir: "cold" primes the persistent
  # compile cache (and is the compile-shrink baseline); p0/p2 then run
  # WARM so their walls are comparable for the data-fraction A/B.
  for run in cold p0 p2; do
    case "$run" in
      cold) pf=2 ;;
      p0)   pf=0 ;;
      p2)   pf=2 ;;
    esac
    JAX_PLATFORMS=cpu python -m dtf_tpu.workloads.mnist \
        --epochs 1 --batch_size 512 --init fan_in --log_frequency 5 \
        --logdir "$pdir/$run" --prefetch "$pf" \
        --compile_cache "$pdir/xla_cache" \
        --chaos "nan_grad@4,loader_error@7" > "$pdir/$run.log" 2>&1
    rc=$?
    [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: $run run (rc=$rc)"; tail -5 "$pdir/$run.log"; }
    python -m dtf_tpu.telemetry.report "$pdir/$run" --check > /dev/null \
      || { FAILS=$((FAILS + 1)); echo "FAILED: report --check ($run)"; }
  done
  python - "$pdir" <<'PYEOF'
import json, sys, os
d = sys.argv[1]
def load(p):
    doc = json.load(open(os.path.join(d, p, "telemetry.json")))
    return doc["goodput"], doc.get("metrics", {})
gc, mc = load("cold")
g0, m0 = load("p0")
g2, m2 = load("p2")
f0, f2 = g0["data_s"] / g0["wall_s"], g2["data_s"] / g2["wall_s"]
assert f2 < f0, f"data fraction did not drop: prefetch2 {f2:.4f} >= prefetch0 {f0:.4f}"
assert "data/prefetch_depth" in m2, "data/prefetch_depth missing from the report payload"
assert "data/prefetch_stall_s" in m2, "data/prefetch_stall_s missing from the report payload"
assert m2.get("compile/cache_hit", {}).get("value", 0) >= 1, \
    "warm run recorded no compile cache hits"
assert g2["compile_s"] < gc["compile_s"], \
    f"warm compile bucket did not shrink: {g2['compile_s']:.2f}s >= {gc['compile_s']:.2f}s (cold)"
print(f"prefetch lane OK: data fraction {f0:.4f} -> {f2:.4f}; "
      f"compile cold {gc['compile_s']:.2f}s -> warm {g2['compile_s']:.2f}s "
      f"(cache hits {m2['compile/cache_hit']['value']:.0f})")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: prefetch lane assertions (rc=$rc)"; }
  rm -rf "$pdir"
fi
# Grad-sync lane (DESIGN.md §4.1): dense vs zero1 vs zero1_overlap on the
# MNIST MLP — same seed, same batches.  Asserts the three loss
# trajectories match within float tolerance, the measured per-device
# optimizer-state bytes strictly drop under zero1 (~(N-1)/N), and the
# run-report CLI renders the "Gradient sync" section from a chaos'd
# SUPERVISED zero1 run.  Skip with NO_GRADSYNC_LANE=1.
if [ "${NO_GRADSYNC_LANE:-0}" != "1" ]; then
  echo "=== grad-sync lane (dense/zero1/zero1_overlap A/B + report section) ==="
  sdir=$(mktemp -d)
  for strat in dense zero1 zero1_overlap; do
    extra=""
    [ "$strat" = "zero1_overlap" ] && extra="--grad_accum 2"
    JAX_PLATFORMS=cpu python -m dtf_tpu.workloads.mnist \
        --epochs 1 --batch_size 512 --init fan_in --log_frequency 20 \
        --optimizer adam --learning_rate 1e-3 \
        --grad_sync "$strat" --grad_bucket_mb 0.1 --simulated_devices 8 $extra \
        --logdir "$sdir/$strat" > "$sdir/$strat.log" 2>&1
    rc=$?
    [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: grad-sync $strat run (rc=$rc)"; tail -5 "$sdir/$strat.log"; }
  done
  # Chaos'd supervised zero1 run: nan_grad exercises the where-select
  # guard skip, sigterm+restart exercises restore of SHARDED optimizer
  # state; the report must render the Gradient sync section from it.
  JAX_PLATFORMS=cpu python -m dtf_tpu.workloads.mnist \
      --epochs 1 --batch_size 512 --init fan_in --log_frequency 5 \
      --optimizer adam --learning_rate 1e-3 \
      --grad_sync zero1 --grad_bucket_mb 0.1 --simulated_devices 8 \
      --logdir "$sdir/chaos" --checkpoint_every 5 --max_restarts 2 \
      --chaos "nan_grad@4,sigterm@11" > "$sdir/chaos.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: grad-sync chaos run (rc=$rc)"; tail -5 "$sdir/chaos.log"; }
  python -m dtf_tpu.telemetry.report "$sdir/chaos" | tee "$sdir/report.log" > /dev/null
  grep -q "Gradient sync" "$sdir/report.log" \
    && grep -q "zero1" "$sdir/report.log" \
    && grep -q "comm/optimizer_state_bytes" "$sdir/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: report missing Gradient sync section"; }
  python - "$sdir" <<'PYEOF'
import csv, json, os, sys
d = sys.argv[1]
def costs(run):
    out = {}
    with open(os.path.join(d, run, "metrics.csv"), newline="") as f:
        for rec in csv.reader(f):
            if rec and rec[0] != "step" and rec[1] == "cost":
                out[int(rec[0])] = float(rec[2])
    return out
def opt_bytes(run):
    doc = json.load(open(os.path.join(d, run, "telemetry.json")))
    return doc["metrics"]["comm/optimizer_state_bytes"]["value"]
dense, z1, zo = costs("dense"), costs("zero1"), costs("zero1_overlap")
steps = sorted(set(dense) & set(z1) & set(zo))
assert steps, "no common cost steps across the A/B runs"
for s in steps:
    for name, c in (("zero1", z1[s]), ("zero1_overlap", zo[s])):
        assert abs(c - dense[s]) <= 0.02 * abs(dense[s]) + 1e-3, \
            f"{name} diverged from dense at step {s}: {c} vs {dense[s]}"
bd, b1, bo = opt_bytes("dense"), opt_bytes("zero1"), opt_bytes("zero1_overlap")
assert b1 < bd and bo < bd, f"optimizer-state bytes did not drop: {b1}/{bo} vs dense {bd}"
assert b1 < 0.25 * bd, f"zero1 opt-state drop too small: {b1} vs dense {bd} (8-way axis)"
print(f"grad-sync lane OK: {len(steps)} cost points within tolerance; "
      f"opt-state bytes dense {bd:.0f} -> zero1 {b1:.0f} "
      f"({1 - b1 / bd:.1%} drop)")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: grad-sync lane assertions (rc=$rc)"; }
  rm -rf "$sdir"
fi
# Quantized-comm lane (DESIGN.md §4.2): 3-way wire-dtype A/B
# (f32/bf16/int8) on the simulated 8-device mesh — same seed, same
# batches, zero1 — asserting the int8 wire-bytes drop (~4x vs f32,
# ~2x vs bf16 from the comm/wire_bytes gauge), loss trajectories within
# tolerance of the exact wire, and the quant-error gauge present; then
# a chaos'd zero1+int8 run whose report must render the wire dtype in
# the Gradient sync section.  Skip with NO_QUANTCOMM_LANE=1.
if [ "${NO_QUANTCOMM_LANE:-0}" != "1" ]; then
  echo "=== quantized-comm lane (f32/bf16/int8 wire A/B + chaos'd int8 run) ==="
  qdir=$(mktemp -d)
  for wire in f32 bf16 int8; do
    JAX_PLATFORMS=cpu python -m dtf_tpu.workloads.mnist \
        --epochs 1 --batch_size 512 --init fan_in --log_frequency 20 \
        --optimizer adam --learning_rate 1e-3 \
        --grad_sync zero1 --grad_bucket_mb 0.1 --simulated_devices 8 \
        --grad_comm_dtype "$wire" \
        --logdir "$qdir/$wire" > "$qdir/$wire.log" 2>&1
    rc=$?
    [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: quant-comm $wire run (rc=$rc)"; tail -5 "$qdir/$wire.log"; }
  done
  # Chaos'd supervised zero1+int8 run: nan_grad exercises the pre-sync
  # guard under the quantized wire (a NaN must be skipped, not laundered
  # into finite garbage), sigterm+restart exercises resume with the wire
  # format recorded in the manifest.
  JAX_PLATFORMS=cpu python -m dtf_tpu.workloads.mnist \
      --epochs 1 --batch_size 512 --init fan_in --log_frequency 5 \
      --optimizer adam --learning_rate 1e-3 \
      --grad_sync zero1 --grad_bucket_mb 0.1 --simulated_devices 8 \
      --grad_comm_dtype int8 --quant_rounding stochastic \
      --logdir "$qdir/chaos" --checkpoint_every 5 --max_restarts 2 \
      --chaos "nan_grad@4,sigterm@11" > "$qdir/chaos.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: quant-comm chaos run (rc=$rc)"; tail -5 "$qdir/chaos.log"; }
  python -m dtf_tpu.telemetry.report "$qdir/chaos" | tee "$qdir/report.log" > /dev/null
  grep -q "Gradient sync" "$qdir/report.log" \
    && grep -q "int8" "$qdir/report.log" \
    && grep -q "comm/wire_bytes" "$qdir/report.log" \
    && grep -q "comm/quant_error" "$qdir/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: report missing int8 wire section"; }
  python - "$qdir" <<'PYEOF'
import csv, json, os, sys
d = sys.argv[1]
def costs(run):
    out = {}
    with open(os.path.join(d, run, "metrics.csv"), newline="") as f:
        for rec in csv.reader(f):
            if rec and rec[0] != "step" and rec[1] == "cost":
                out[int(rec[0])] = float(rec[2])
    return out
def gauge(run, name):
    doc = json.load(open(os.path.join(d, run, "telemetry.json")))
    m = doc["metrics"].get(name)
    return None if m is None else m["value"]
f32, bf16, i8 = costs("f32"), costs("bf16"), costs("int8")
steps = sorted(set(f32) & set(bf16) & set(i8))
assert steps, "no common cost steps across the wire A/B runs"
for s in steps:
    for name, c in (("bf16", bf16[s]), ("int8", i8[s])):
        assert abs(c - f32[s]) <= 0.02 * abs(f32[s]) + 1e-3, \
            f"{name} wire diverged from f32 at step {s}: {c} vs {f32[s]}"
w = {r: gauge(r, "comm/wire_bytes") for r in ("f32", "bf16", "int8")}
assert w["int8"] <= 0.30 * w["f32"], f"int8 wire not ~4x below f32: {w}"
assert w["int8"] <= 0.55 * w["bf16"], f"int8 wire not ~2x below bf16: {w}"
qe = gauge("int8", "comm/quant_error")
assert qe is not None and 0 < qe < 0.1, f"quant error gauge off: {qe}"
assert gauge("chaos", "comm/wire_dtype_idx") == 2     # int8
print(f"quantized-comm lane OK: {len(steps)} cost points within "
      f"tolerance; wire bytes f32 {w['f32']:.0f} -> bf16 {w['bf16']:.0f} "
      f"-> int8 {w['int8']:.0f} ({w['int8']/w['f32']:.2f}x of f32, "
      f"{w['int8']/w['bf16']:.2f}x of bf16); quant error rms {qe:.1e}")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: quantized-comm lane assertions (rc=$rc)"; }
  rm -rf "$qdir"
fi
# Serve lane (DESIGN.md §7): the closed-loop load generator on the CPU
# sim under the deterministic virtual clock — continuous batching must
# sustain >= 1.5x the static baseline's goodput QPS at the same p99
# TTFT budget (serve_load --check); then a chaos'd supervised serve
# session (--wedge_at crash + restart + health beats) whose telemetry
# must render the Serving SLO section with the TTFT/TPOT instruments
# and pass report --check.  Skip with NO_SERVE_LANE=1.
if [ "${NO_SERVE_LANE:-0}" != "1" ]; then
  echo "=== serve lane (continuous-vs-static load A/B + chaos'd server) ==="
  sdir=$(mktemp -d)
  JAX_PLATFORMS=cpu python -m dtf_tpu.bench.serve_load --preset tiny \
      --clock virtual --qps 4,8,16,24 --requests 48 --mode both \
      --check --json "$sdir/ab.json" > "$sdir/ab.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: serve load A/B (rc=$rc)"; tail -8 "$sdir/ab.log"; }
  grep -q "CHECK OK" "$sdir/ab.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: serve A/B check line missing"; }
  JAX_PLATFORMS=cpu python -m dtf_tpu.serve --preset tiny --demo 12 \
      --qps 20 --clock virtual --wedge_at 3 --max_restarts 1 \
      --health_dir "$sdir/health" --logdir "$sdir/run" \
      > "$sdir/serve.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: chaos'd serve session (rc=$rc)"; tail -8 "$sdir/serve.log"; }
  [ -s "$sdir/health/hb_0" ] \
    || { FAILS=$((FAILS + 1)); echo "FAILED: serve health beats missing"; }
  python -m dtf_tpu.telemetry.report "$sdir/run" --check \
      > "$sdir/report.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: serve report --check (rc=$rc)"; tail -5 "$sdir/report.log"; }
  grep -q "Serving (SLO / goodput)" "$sdir/report.log" \
    && grep -q "serve/ttft_ms" "$sdir/report.log" \
    && grep -q "serve/tpot_ms" "$sdir/report.log" \
    && grep -q "goodput_qps" "$sdir/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: report missing serving SLO section"; }
  python - "$sdir/ab.json" <<'PYEOF'
import json, sys
doc = json.load(open(sys.argv[1]))
ab = doc["ab"]
# ratio null = static sustained nothing at the SLO (continuous wins)
assert ab["ratio"] is None or ab["ratio"] >= ab["min_ratio"], ab
pts = doc["points"]
assert all("ttft_ms_p50" in p and "ttft_ms_p99" in p for p in pts), \
    "latency-vs-QPS curve incomplete"
shown = "inf" if ab["ratio"] is None else f"{ab['ratio']:.2f}"
print(f"serve lane OK: continuous {ab['continuous_sustained_qps']:.2f} "
      f"qps vs static {ab['static_sustained_qps']:.2f} qps sustained at "
      f"p99 TTFT <= {doc['slo_ttft_ms']:.0f} ms "
      f"(ratio {shown}, bar {ab['min_ratio']}); "
      f"{len(pts)} curve points")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: serve lane assertions (rc=$rc)"; }
  rm -rf "$sdir"
fi
# Serve-chaos lane (DESIGN.md §7.4): the overload/brownout gate
# (deadline'd load under an injected decode-rate spike, controller
# on/off same-trace A/B: zero deadline violations + sheds booked +
# controller strictly improves goodput-QPS), then a REAL SIGTERM mid-run
# against a wall-clock server — the drain must checkpoint unfinished
# requests, the supervisor replay must complete every accepted request
# TOKEN-IDENTICALLY to an uninterrupted run, and report --check must
# stay green with the shed/drain instruments present.  Finally the
# slow-marked TCP front-end tests (the `serve` marker split keeps them
# out of tier-1).  Skip with NO_SERVE_CHAOS_LANE=1.
if [ "${NO_SERVE_CHAOS_LANE:-0}" != "1" ]; then
  echo "=== serve-chaos lane (brownout gate + SIGTERM drain/replay + TCP tests) ==="
  scdir2=$(mktemp -d)
  JAX_PLATFORMS=cpu python -m dtf_tpu.bench.serve_load --preset tiny \
      --clock virtual --mode continuous --chaos 'slow_decode@30:60ms' \
      --deadline_ms 2500 --priorities 0,0,1 --output_lens 2,8,16 \
      --qps 10 --requests 60 \
      --check --json "$scdir2/chaos_ab.json" > "$scdir2/chaos.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: serve overload gate (rc=$rc)"; tail -8 "$scdir2/chaos.log"; }
  grep -q "CHECK OK" "$scdir2/chaos.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: overload CHECK OK line missing"; }
  # reference tokens from an uninterrupted run (tokens are clock- and
  # chaos-independent: per-request rng streams are (seed, rid)-keyed)
  JAX_PLATFORMS=cpu python -m dtf_tpu.serve --preset tiny --demo 16 \
      --qps 6 --clock virtual --seed 11 \
      --tokens_out "$scdir2/ref_tokens.json" > "$scdir2/ref.log" 2>&1 \
    || { FAILS=$((FAILS + 1)); echo "FAILED: drain reference run"; }
  # the loaded wall-clock server (slow_decode keeps it busy), SIGTERM'd
  # mid-run: graceful drain + in-process supervisor replay
  JAX_PLATFORMS=cpu python -m dtf_tpu.serve --preset tiny --demo 16 \
      --qps 6 --clock wall --seed 11 --chaos 'slow_decode@5:40ms' \
      --max_restarts 1 --drain_timeout_s 2 --logdir "$scdir2/drain_run" \
      --tokens_out "$scdir2/drain_tokens.json" \
      > "$scdir2/drain.log" 2>&1 &
  spid=$!
  sleep 4
  kill -TERM "$spid" 2>/dev/null
  wait "$spid"
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: SIGTERM drain/replay run (rc=$rc)"; tail -10 "$scdir2/drain.log"; }
  python - "$scdir2" <<'PYEOF'
import json, os, sys
d = sys.argv[1]
ref = json.load(open(os.path.join(d, "ref_tokens.json")))
got = json.load(open(os.path.join(d, "drain_tokens.json")))
assert got == ref, "drain+replay tokens diverged from uninterrupted run"
assert ref, "reference token map is empty"
print(f"drain replay OK: {len(got)} request(s) token-identical")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: drain replay token identity (rc=$rc)"; }
  python -m dtf_tpu.telemetry.report "$scdir2/drain_run" --check \
      > "$scdir2/report.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: drain-run report --check (rc=$rc)"; tail -5 "$scdir2/report.log"; }
  grep -q "drained_unfinished" "$scdir2/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: report missing drain accounting"; }
  JAX_PLATFORMS=cpu python -m pytest tests/test_serve_resilience.py \
      -q -m "serve and slow" -p no:cacheprovider \
      > "$scdir2/tcp.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: TCP front-end tests (rc=$rc)"; tail -10 "$scdir2/tcp.log"; }
  rm -rf "$scdir2"
fi
# Serve-fleet lane (DESIGN.md §7.6, ISSUE 16): the replica failure
# domain gate against REAL processes on the wall clock — three --listen
# replica processes (one preset+seed, so one weight tensor) behind a
# --connect acceptor with --admin_port, a SIGKILL of replica 1 while
# /fleetz shows it provably holding in-flight legs, and the client-side
# verdict: zero lost requests, every token stream bitwise identical to
# an uninterrupted in-process reference, the failover booked in the
# /fleetz rollup (up drops to 2/3), and the acceptor's report --check
# green.  Skip with NO_SERVE_FLEET_LANE=1.
if [ "${NO_SERVE_FLEET_LANE:-0}" != "1" ]; then
  echo "=== serve-fleet lane (3-replica SIGKILL failover + token identity) ==="
  sfdir=$(mktemp -d)
  mkdir -p "$sfdir/hb"
  rpids=()
  for k in 0 1 2; do
    JAX_PLATFORMS=cpu python -m dtf_tpu.serve --preset tiny --listen :0 \
        --replica_index "$k" --seed 11 --health_dir "$sfdir/hb" \
        --logdir "$sfdir/r$k" > "$sfdir/r$k.log" 2>&1 &
    rpids[$k]=$!
  done
  ports=()
  for k in 0 1 2; do
    for _ in $(seq 1 240); do
      grep -q "serving on tcp://" "$sfdir/r$k.log" 2>/dev/null && break
      sleep 0.5
    done
    ports[$k]=$(sed -n 's#.*serving on tcp://[^:]*:\([0-9]*\).*#\1#p' "$sfdir/r$k.log" | head -1)
    [ -n "${ports[$k]:-}" ] \
      || { FAILS=$((FAILS + 1)); echo "FAILED: fleet replica $k never came up"; tail -5 "$sfdir/r$k.log"; }
  done
  if [ -n "${ports[0]:-}" ] && [ -n "${ports[1]:-}" ] && [ -n "${ports[2]:-}" ]; then
    JAX_PLATFORMS=cpu python -m dtf_tpu.serve \
        --connect "127.0.0.1:${ports[0]},127.0.0.1:${ports[1]},127.0.0.1:${ports[2]}" \
        --listen :0 --admin_port 0 --seed 11 --health_dir "$sfdir/hb" \
        --logdir "$sfdir/fleet" > "$sfdir/acc.log" 2>&1 &
    apid=$!
    for _ in $(seq 1 60); do
      grep -q "fleet acceptor on tcp://" "$sfdir/acc.log" 2>/dev/null && break
      sleep 0.5
    done
    fport=$(sed -n 's#.*fleet acceptor on tcp://[^:]*:\([0-9]*\).*#\1#p' "$sfdir/acc.log" | head -1)
    aport=$(sed -n 's#.*admin endpoint on http://127.0.0.1:\([0-9]*\).*#\1#p' "$sfdir/acc.log" | head -1)
    if [ -z "$fport" ] || [ -z "$aport" ]; then
      FAILS=$((FAILS + 1)); echo "FAILED: fleet acceptor never came up"; tail -5 "$sfdir/acc.log"
    else
      JAX_PLATFORMS=cpu python - "$fport" "$aport" "${rpids[1]}" <<'PYEOF'
import json, os, signal, sys, threading, time, urllib.request

import jax
from dtf_tpu.bench.serve_load import poisson_trace
from dtf_tpu.models.gpt import GPT, GPTConfig
from dtf_tpu.serve import ServingEngine, VirtualClock
from dtf_tpu.serve.fleet import client_summary, drive_trace

fport, aport, victim = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
cfg = GPTConfig.from_preset("tiny")
model = GPT(cfg)
params = model.init(jax.random.key(11))
trace = poisson_trace(seed=11, n_requests=24, qps=6.0,
                      prompt_lens=[4, 8], output_lens=[16],
                      vocab_size=cfg.vocab_size, temperature=0.0)
# the uninterrupted reference: one in-process engine on the virtual
# clock (greedy tokens are clock-, batching- and replica-independent)
eng = ServingEngine(model, params, seed=11, clock=VirtualClock())
eng.run(trace)
ref = {kw["rid"]: eng.results[kw["rid"]].tokens for _, kw in trace}
assert all(ref.values()), "reference run rejected a request"

fleetz = f"http://127.0.0.1:{aport}/fleetz"

def kill_when_inflight():
    # SIGKILL replica 1 the moment /fleetz shows it holding live legs —
    # the failover is then provable, not a race against an idle replica
    deadline = time.monotonic() + 25.0
    while time.monotonic() < deadline:
        try:
            roll = json.load(urllib.request.urlopen(fleetz, timeout=5))
            r1 = roll["replicas"]["1"]
            if r1["state"] == "up" and r1["inflight"] >= 1:
                break
        except OSError:
            pass
        time.sleep(0.1)
    os.kill(victim, signal.SIGKILL)

killer = threading.Thread(target=kill_when_inflight, daemon=True)
killer.start()
res = drive_trace(("127.0.0.1", fport), trace, request_timeout_s=120.0)
killer.join(timeout=30.0)
cs = client_summary(res, slo_ttft_ms=2000.0)
assert cs["lost"] == 0, f"lost requests across the SIGKILL: {cs}"
assert cs["completed"] == len(trace), f"not all completed: {cs}"
diffs = [i for i in range(len(trace))
         if list(res[i]["tokens"]) != list(ref[i])]
assert not diffs, f"token divergence vs reference at indices {diffs[:8]}"
roll = json.load(urllib.request.urlopen(fleetz, timeout=5))
assert roll["up"] == 2, f"expected 2/3 replicas up, got {roll['up']}"
assert roll["totals"]["failovers"] >= 1, roll["totals"]
print(f"serve-fleet OK: {cs['completed']}/{len(trace)} completed, 0 lost "
      f"across SIGKILL of replica 1; {roll['totals']['failovers']} "
      f"failover(s), {roll['totals']['replayed']} replayed, "
      f"up={roll['up']}/{roll['size']}; tokens identical to "
      f"uninterrupted reference")
PYEOF
      rc=$?
      [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: serve-fleet drive (rc=$rc)"; tail -8 "$sfdir/acc.log"; }
    fi
    kill -TERM "$apid" 2>/dev/null
    wait "$apid"
    rc=$?
    [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: fleet acceptor shutdown (rc=$rc)"; tail -8 "$sfdir/acc.log"; }
    python -m dtf_tpu.telemetry.report "$sfdir/fleet" --check \
        > "$sfdir/report.log" 2>&1
    rc=$?
    [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: fleet report --check (rc=$rc)"; tail -5 "$sfdir/report.log"; }
  fi
  # replica 1 died by SIGKILL above (rc 137 is the lane working); 0 and
  # 2 drain gracefully
  kill -TERM "${rpids[0]}" "${rpids[2]}" 2>/dev/null
  wait "${rpids[0]}" "${rpids[2]}" 2>/dev/null
  rm -rf "$sfdir"
fi
# Decode-fast lane (DESIGN.md §7.5, ISSUE 14): the decode data path at
# the hardware floor.  (1) paged-vs-baseline ladder A/B on tight AND
# oversized pools: the narrowed path's marginal ms/token must be
# pool-size invariant and strictly beat the whole-pool baseline on the
# oversized pool, while the baseline must demonstrably degrade (the
# falsifiability half of the invariance claim); (2) same-trace
# spec-decode serve_load A/B at fixed QPS: p99 TPOT strictly drops,
# zero token-identity diffs, acceptance > 0, absolute TPOT ceiling via
# the shared check_gates path; (3) a spec-decode serve session whose
# report --check stays green with the new spec/prefill instruments.
# Skip with NO_DECODE_FAST_LANE=1.
if [ "${NO_DECODE_FAST_LANE:-0}" != "1" ]; then
  echo "=== decode-fast lane (paged ladder A/B + spec-decode TPOT gate) ==="
  dfdir=$(mktemp -d)
  for arm in paged_tight:"":"" paged_over:"--pool_blocks 4096":"" \
             base_tight:"":"--no_narrow" base_over:"--pool_blocks 4096":"--no_narrow"; do
    name="${arm%%:*}"; rest="${arm#*:}"
    pool="${rest%%:*}"; narrow="${rest#*:}"
    JAX_PLATFORMS=cpu python -m dtf_tpu.bench.decode_ladder \
        --preset tiny --mode paged --streams 3 --ladder 8,24,48 \
        --reps 4 --block_size 16 $pool $narrow \
        --json "$dfdir/$name.json" > "$dfdir/$name.log" 2>&1
    rc=$?
    [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: decode ladder arm $name (rc=$rc)"; tail -4 "$dfdir/$name.log"; }
  done
  python - "$dfdir" <<'PYEOF'
import json, os, sys
d = sys.argv[1]
arm = {n: json.load(open(os.path.join(d, n + ".json")))
       for n in ("paged_tight", "paged_over", "base_tight", "base_over")}
us = {n: a["per_token_us"] for n, a in arm.items()}
# the baseline's marginal cost must grow with pool size (the disease)
assert us["base_over"] >= 1.5 * us["base_tight"], \
    f"baseline did not degrade with pool size: {us}"
# the narrowed path must be pool-size invariant (the cure) ...
drift = abs(us["paged_over"] - us["paged_tight"]) / us["paged_tight"]
assert drift <= 0.5, f"paged marginal drifted {drift:.2f} with pool size: {us}"
# ... and strictly cheaper than the baseline where it matters
assert us["paged_over"] < 0.6 * us["base_over"], \
    f"paged did not beat baseline on the oversized pool: {us}"
print(f"decode ladder OK: paged {us['paged_tight']:.0f}->"
      f"{us['paged_over']:.0f} us/tok (drift {drift:.2f}) vs baseline "
      f"{us['base_tight']:.0f}->{us['base_over']:.0f} us/tok")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: decode ladder A/B assertions (rc=$rc)"; }
  JAX_PLATFORMS=cpu python -m dtf_tpu.bench.serve_load --preset tiny \
      --clock virtual --mode continuous --qps 10 --requests 32 --seed 5 \
      --prompt_lens 4,8,16 --output_lens 16,32,48 \
      --spec_ab --spec_k 4 --max_tpot_p99_ms 11.5 \
      --check --json "$dfdir/spec_ab.json" > "$dfdir/spec.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: spec-decode serve_load A/B (rc=$rc)"; tail -8 "$dfdir/spec.log"; }
  grep -q "CHECK OK" "$dfdir/spec.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: spec-decode CHECK OK line missing"; }
  JAX_PLATFORMS=cpu python -m dtf_tpu.serve --preset tiny --demo 12 \
      --qps 20 --clock virtual --seed 3 --spec_k 4 \
      --logdir "$dfdir/specrun" > "$dfdir/specrun.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: spec-decode serve session (rc=$rc)"; tail -6 "$dfdir/specrun.log"; }
  python -m dtf_tpu.telemetry.report "$dfdir/specrun" --check \
      > "$dfdir/report.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: spec-run report --check (rc=$rc)"; tail -5 "$dfdir/report.log"; }
  grep -q "serve/spec_proposed_total" "$dfdir/report.log" \
    && grep -q "serve/prefill_batch_size" "$dfdir/report.log" \
    && grep -q "spec_acceptance" "$dfdir/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: report missing spec/prefill instruments"; }
  rm -rf "$dfdir"
fi
# Live-introspection lane (DESIGN.md §6.4, ISSUE 11): a chaos'd
# wall-clock serve session with --admin_port, scraped WHILE it runs
# (/statz consistent snapshot, /healthz liveness, /tracez flight
# recorder, /slo burn state); afterwards the on-disk request traces
# must reconstruct gap-free chains (report --min_trace_complete_frac
# 0.99 + the --request view), and the pinned-spike A/B must show the
# fast-burn SLO alert firing strictly BEFORE brownout reject_all
# (serve_load --chaos --check, gate alert_leads_control).  Skip with
# NO_LIVE_LANE=1.
if [ "${NO_LIVE_LANE:-0}" != "1" ]; then
  echo "=== live-introspection lane (admin scrape + request traces + alert-leads-control) ==="
  lidir=$(mktemp -d)
  JAX_PLATFORMS=cpu python - "$lidir" <<'PYEOF'
import json, os, socket, subprocess, sys, time, urllib.request
d = sys.argv[1]
logdir = os.path.join(d, "run")
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "dtf_tpu.serve", "--preset", "tiny",
     "--demo", "24", "--qps", "3", "--clock", "wall",
     "--chaos", "slow_decode@5:40ms:60", "--brownout",
     "--admin_port", str(port), "--logdir", logdir],
    stdout=open(os.path.join(d, "serve.log"), "w"),
    stderr=subprocess.STDOUT,
    env={**os.environ, "JAX_PLATFORMS": "cpu"})

def get(path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return json.loads(r.read())

try:
    statz = None
    deadline = time.time() + 180
    while time.time() < deadline and proc.poll() is None:
        try:
            statz = get("/statz"); break
        except OSError:
            time.sleep(0.3)
    assert statz is not None, "admin endpoint never came up"
    assert "metrics" in statz and "goodput" in statz
    health = get("/healthz")
    assert health["ok"], health
    slo = get("/slo")
    assert "objectives" in slo and "ttft" in slo["objectives"], slo
    # live scrape catches completed traces in the flight recorder
    # while the engine is still serving
    tracez = {"count": 0}
    while time.time() < deadline and proc.poll() is None:
        tracez = get("/tracez")
        if tracez["count"] > 0:
            break
        time.sleep(0.3)
    assert tracez["count"] > 0, "flight recorder stayed empty"
    ev = tracez["traces"][0]["events"]
    assert ev[0]["phase"] == "submit", ev
finally:
    # never leak the server (and never let a wait timeout mask the
    # scrape assertion that got us here)
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        rc = -1
assert rc == 0, f"serve session exited {rc}"
print(f"live scrape OK: statz {len(statz['metrics'])} instruments, "
      f"tracez {tracez['count']} trace(s) mid-run")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: live admin scrape (rc=$rc)"; tail -10 "$lidir/serve.log" 2>/dev/null; }
  python -m dtf_tpu.telemetry.report "$lidir/run" --check \
      --min_trace_complete_frac 0.99 > "$lidir/report.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: request-trace gate (rc=$rc)"; tail -8 "$lidir/report.log"; }
  python -m dtf_tpu.telemetry.report "$lidir/run" --request 0 \
      > "$lidir/request.log" 2>&1 \
    && grep -q "completed\|shed\|drained" "$lidir/request.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: --request view"; tail -5 "$lidir/request.log"; }
  JAX_PLATFORMS=cpu python -m dtf_tpu.bench.serve_load --preset tiny \
      --clock virtual --mode continuous --chaos 'slow_decode@30:60ms' \
      --deadline_ms 2500 --priorities 0,0,1 --output_lens 2,8,16 \
      --qps 10 --requests 60 --check > "$lidir/ab.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: alert-leads-control A/B (rc=$rc)"; tail -8 "$lidir/ab.log"; }
  grep -q "gate alert_leads_control: OK" "$lidir/ab.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: alert_leads_control gate line missing"; }
  rm -rf "$lidir"
fi

# Controller lane (DESIGN.md §9, ISSUE 17): the self-tuning control
# plane end to end.  (1) a chaos'd wall-clock serve session with
# --controller and --admin_port, /controlz scraped WHILE it runs (knob
# table + audit trail + loop state, decisions advancing mid-run), whose
# report --check must stay green with the control/* instruments AND the
# --max_control_rollbacks gate armed (absence of the counter = the
# controller never armed = FAIL, by design); (2) the same-trace knob
# on/off A/B under an adversarial sine load shape (serve_load --knob_ab
# --check): the controller must STRICTLY beat the pinned baseline on
# goodput QPS with p99 TTFT/TPOT no worse, knobs provably moved, and
# every rollback explained + bounded.  The control/* names lint rides
# in the telemetry lane's check_telemetry_names.py (both directions).
# Skip with NO_CONTROLLER_LANE=1.
if [ "${NO_CONTROLLER_LANE:-0}" != "1" ]; then
  echo "=== controller lane (/controlz scrape + knob on/off A/B gates) ==="
  cldir=$(mktemp -d)
  JAX_PLATFORMS=cpu python - "$cldir" <<'PYEOF'
import json, os, socket, subprocess, sys, time, urllib.request
d = sys.argv[1]
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "dtf_tpu.serve", "--preset", "tiny",
     "--demo", "24", "--qps", "3", "--clock", "wall",
     "--chaos", "slow_decode@5:40ms:60", "--brownout", "--controller",
     "--admin_port", str(port), "--logdir", os.path.join(d, "run")],
    stdout=open(os.path.join(d, "serve.log"), "w"),
    stderr=subprocess.STDOUT,
    env={**os.environ, "JAX_PLATFORMS": "cpu"})

def get(path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return json.loads(r.read())

ctlz = None
try:
    deadline = time.time() + 180
    while time.time() < deadline and proc.poll() is None:
        try:
            doc = get("/controlz")
        except OSError:
            time.sleep(0.3); continue
        # armed payload: knob table + loop state; wait until the loop
        # has actually evaluated at least once mid-run
        if doc.get("knobs") and doc.get("controller", {}).get(
                "decisions", 0) >= 1:
            ctlz = doc
            break
        time.sleep(0.3)
finally:
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        proc.kill(); proc.wait(); rc = -1
assert rc == 0, f"controller serve session exited {rc}"
assert ctlz is not None, "/controlz never served an armed mid-run cut"
knobs = ctlz["knobs"]
assert "spec_k" in knobs and "brownout_enter_ratio" in knobs, knobs.keys()
for k in knobs.values():
    assert k["lo"] <= k["value"] <= k["hi"], knobs  # rails hold live
print(f"controlz scrape OK: {len(knobs)} knob(s), "
      f"{ctlz['controller']['decisions']} decision(s) mid-run, "
      f"{len(ctlz['audit'])} audit entr(ies)")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: controlz scrape (rc=$rc)"; tail -8 "$cldir/serve.log" 2>/dev/null; }
  python -m dtf_tpu.telemetry.report "$cldir/run" --check \
      --max_control_rollbacks 2 > "$cldir/report.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: controller report --check (rc=$rc)"; tail -5 "$cldir/report.log"; }
  grep -q "gate max_control_rollbacks: OK" "$cldir/report.log" \
    && grep -q "control/" "$cldir/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: report missing control gate/section"; }
  # same-trace knob on/off A/B under the adversarial sine load shape —
  # pinned from a measured run (controller 18.6 vs pinned 15.4 goodput
  # qps at this geometry); the gates themselves are relative, so the pin
  # is the SHAPE, not the numbers
  JAX_PLATFORMS=cpu python -m dtf_tpu.bench.serve_load --preset tiny \
      --clock virtual --mode continuous --qps 36 --requests 64 \
      --qps_profile sine --trace_vocab 12 --deadline_ms 2500 \
      --priorities 0,0,1 --knob_ab --max_control_rollbacks 2 \
      --check --json "$cldir/knob_ab.json" > "$cldir/ab.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: knob on/off A/B (rc=$rc)"; tail -10 "$cldir/ab.log"; }
  grep -q "CHECK OK" "$cldir/ab.log" \
    && grep -q "gate knob_controller_improves_goodput: OK" "$cldir/ab.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: knob A/B gate lines missing"; }
  rm -rf "$cldir"
fi
# Fleet lane (DESIGN.md §6.5, ISSUE 12): a 2-host chaos'd run through
# the fleet plane — host 1 carries an injected 40 ms/step straggler,
# every host's span stream lands in the shared logdir, /fleetz is
# scraped MID-run for a consistent fleet cut, and afterwards
# report --fleet must attribute the blame to the injected host, pass
# the skew/goodput gates, and FAIL an absurd threshold (falsifiability,
# same pattern as the scenario runner).  The perf-regression ledger
# gate rides here too.  Skip with NO_FLEET_LANE=1.
if [ "${NO_FLEET_LANE:-0}" != "1" ]; then
  echo "=== fleet lane (2-host straggler + /fleetz scrape + report --fleet gates + ledger) ==="
  fdir=$(mktemp -d)
  JAX_PLATFORMS=cpu python - "$fdir" <<'PYEOF'
import json, os, socket, subprocess, sys, time, urllib.request
d = sys.argv[1]
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
env = {**os.environ, "JAX_PLATFORMS": "cpu",
       "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
       "PYTHONPATH": os.pathsep.join(
           [os.getcwd()] + [p for p in os.environ.get(
               "PYTHONPATH", "").split(os.pathsep) if p])}
driver = os.path.abspath(os.path.join("tests", "_mp_fleet.py"))
procs = [subprocess.Popen(
    [sys.executable, driver, str(task), "2", d, "40", "2",
     "slow_host@0:1:40ms", str(port) if task == 0 else ""],
    stdout=open(os.path.join(d, f"host{task}.log"), "w"),
    stderr=subprocess.STDOUT, env=env) for task in range(2)]

def get(path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return json.loads(r.read())

scraped = None
try:
    deadline = time.time() + 240
    while time.time() < deadline and procs[0].poll() is None:
        try:
            doc = get("/fleetz")
        except OSError:
            time.sleep(0.3); continue
        att = doc.get("attribution") or {}
        if att.get("barriers", 0) >= 2 and len(
                doc.get("hosts_reporting", [])) == 2:
            scraped = doc
            break
        time.sleep(0.3)
finally:
    rcs = []
    for p in procs:
        try:
            rcs.append(p.wait(timeout=240))
        except subprocess.TimeoutExpired:
            p.kill(); p.wait(); rcs.append(-1)
assert rcs == [0, 0], f"fleet hosts exited {rcs}"
assert scraped is not None, "/fleetz never served a 2-host cut mid-run"
# one consistent cut: the goodput aggregate must be computed from
# exactly the per-host docs in this payload
g = scraped["goodput"]
hosts = scraped["hosts"]
prod = sum(h["goodput"]["productive_s"] for h in hosts.values())
assert abs(prod - g["productive_s_total"]) < 1e-6, (prod, g)
for k, h in hosts.items():
    assert h["rev"] == h["rev_echo"], f"torn host doc {k}: {h['rev']} != {h['rev_echo']}"
print(f"fleet scrape OK: {scraped['attribution']['barriers']} barrier(s), "
      f"hosts {sorted(hosts)}, fleet goodput {g['productive_fraction']}")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: fleet 2-host run / scrape (rc=$rc)"; tail -8 "$fdir"/host*.log 2>/dev/null; }
  python -m dtf_tpu.telemetry.report "$fdir/logs" --fleet \
      --max_skew_ms 5000 --min_fleet_goodput 0.0005 \
      --export-trace "$fdir/fleet_trace.json" | tee "$fdir/report.log"
  rc=${PIPESTATUS[0]}
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: report --fleet gates (rc=$rc)"; }
  grep -q "Fleet (telemetry/fleet.py)" "$fdir/report.log" \
    && grep -q "gate max_skew_ms: OK" "$fdir/report.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: fleet report section/gates missing"; }
  python - "$fdir" <<'PYEOF'
import json, sys
d = sys.argv[1]
from dtf_tpu.telemetry.report import build_report
rep = build_report(d + "/logs")
att = rep["fleet"]["attribution"]
blamed = max(att["per_host"].items(), key=lambda kv: kv[1]["blame_frac"])
assert blamed[0] == "1" and blamed[1]["blame_frac"] >= 0.8, att["per_host"]
drift = att["per_host"]["1"]["drift_ms_per_step"]
assert 15.0 <= drift <= 90.0, f"drift {drift} vs injected 40 ms/step"
trace = json.load(open(d + "/fleet_trace.json"))
pids = {e.get("pid") for e in trace["traceEvents"]}
assert {0, 1} <= pids, pids
print(f"fleet attribution OK: blame p1 {blamed[1]['blame_frac']:.0%}, "
      f"drift {drift:.1f} ms/step (injected 40), "
      f"{len(trace['traceEvents'])} merged trace events")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: fleet attribution assertions (rc=$rc)"; }
  # falsifiability: an absurd threshold must FAIL the same report
  python -m dtf_tpu.telemetry.report "$fdir/logs" \
      --max_skew_ms 0.001 --max_blame_frac 0.01 > /dev/null 2>&1
  [ $? -eq 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: absurd fleet thresholds did not fail"; }
  rm -rf "$fdir"
fi
# Cost-observatory lane (DESIGN.md §6.6, ISSUE 15): (1) a train run and
# a serve run must both emit CostCards (train/step from the AOT warmup;
# serve/prefill+decode from the engine's builders — card count >= the
# distinct compiled geometries, i.e. every card compiled at least once);
# (2) /memz scraped MID-run serves the cards + hbm/cost instrument cut;
# (3) an injected A/B where arm B doubles decode context — the
# step-time regression explainer must rank serve/decode's bytes growth
# FIRST; (4) the --max_hbm_frac gate is falsifiable: green at a sane
# threshold, exit 1 at an absurd one, on the SAME logdir.  Skip with
# NO_COSTOBS_LANE=1.
if [ "${NO_COSTOBS_LANE:-0}" != "1" ]; then
  echo "=== cost-observatory lane (cards + /memz scrape + explain A/B + hbm gates) ==="
  codir=$(mktemp -d)
  # (1a) train: AOT warmup -> train/step card, hbm gauges at sync points
  JAX_PLATFORMS=cpu python -m dtf_tpu.workloads.mnist \
      --epochs 1 --batch_size 512 --init fan_in --log_frequency 20 \
      --logdir "$codir/train" > "$codir/train.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: costobs train run (rc=$rc)"; tail -5 "$codir/train.log"; }
  # (1b) serve arm A, and (2) arm B with doubled decode context scraped
  # mid-run on /memz
  JAX_PLATFORMS=cpu python -m dtf_tpu.serve --preset tiny --demo 12 \
      --qps 20 --clock virtual --seed 7 --block_size 4 \
      --prompt_lens 4,8 --output_lens 4,8,8 \
      --logdir "$codir/a" > "$codir/a.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: costobs serve arm A (rc=$rc)"; tail -5 "$codir/a.log"; }
  JAX_PLATFORMS=cpu python - "$codir" <<'PYEOF'
import json, os, socket, subprocess, sys, time, urllib.request
d = sys.argv[1]
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "dtf_tpu.serve", "--preset", "tiny",
     "--demo", "12", "--qps", "20", "--clock", "wall", "--seed", "7",
     "--block_size", "4", "--prompt_lens", "4,8",
     "--output_lens", "16,32,32",      # arm B: decode context doubled+
     "--admin_port", str(port), "--logdir", os.path.join(d, "b")],
    stdout=open(os.path.join(d, "b.log"), "w"), stderr=subprocess.STDOUT,
    env={**os.environ, "JAX_PLATFORMS": "cpu"})
memz = None
try:
    deadline = time.time() + 180
    while time.time() < deadline and proc.poll() is None:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/memz", timeout=5) as r:
                doc = json.loads(r.read())
        except OSError:
            time.sleep(0.2); continue
        sites = {c["site"] for c in doc.get("cards", [])}
        # wait for a decode card AND the end-of-iteration KV gauges —
        # the first scrape can land mid-compile, before the engine's
        # first iteration ever reached its gauge block
        if "serve/decode" in sites and "hbm/kv_pool_bytes" in doc["metrics"]:
            memz = doc
            break
        time.sleep(0.2)
finally:
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        proc.kill(); proc.wait(); rc = -1
assert rc == 0, f"serve arm B exited {rc}"
assert memz is not None, "/memz never served a decode card mid-run"
assert "cost/compiles_total" in memz["metrics"], memz["metrics"].keys()
assert "hbm/kv_pool_bytes" in memz["metrics"], "kv pool bytes missing"
cards = memz["cards"]
assert all(c["n_compiles"] >= 1 for c in cards)
print(f"memz scrape OK: {len(cards)} card(s) mid-run, sites "
      f"{sorted({c['site'] for c in cards})}")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: costobs /memz scrape (rc=$rc)"; tail -8 "$codir/b.log" 2>/dev/null; }
  # (1c) every compile site emitted cards; count >= distinct geometries
  python - "$codir" <<'PYEOF'
import json, os, sys
d = sys.argv[1]
def cards(run):
    path = os.path.join(d, run, "costcards.jsonl")
    assert os.path.exists(path), f"{run}: no costcards.jsonl"
    return [json.loads(ln) for ln in open(path) if ln.strip()]
train = cards("train")
assert any(c["site"] == "train/step" for c in train), train
a, b = cards("a"), cards("b")
for name, cs in (("a", a), ("b", b)):
    sites = {c["site"] for c in cs}
    assert "serve/decode" in sites, (name, sites)
    assert sites & {"serve/prefill", "serve/prefill_batched"}, (name, sites)
    # one card per distinct geometry (no duplicates in the stream) and
    # every geometry actually compiled at least once
    geoms = {(c["site"], str(c["geometry"])) for c in cs}
    assert len(cs) == len(geoms), (name, len(cs), len(geoms))
    assert all(c["n_compiles"] >= 1 for c in cs)
tele = json.load(open(os.path.join(d, "b", "telemetry.json")))
assert tele["cost"]["compiles"] >= len(b)
assert tele["metrics"]["hbm/frac"]["value"] > 0
print(f"cards OK: train {len(train)}, serve A {len(a)}, serve B {len(b)} "
      f"(B compiles {tele['cost']['compiles']})")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: costobs card assertions (rc=$rc)"; }
  # (3) the explainer must rank arm B's decode bytes-growth first
  python -m dtf_tpu.telemetry.report --explain "$codir/a" "$codir/b" \
      --json > "$codir/explain.json" 2>"$codir/explain.err"
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: report --explain (rc=$rc)"; tail -3 "$codir/explain.err"; }
  python - "$codir/explain.json" <<'PYEOF'
import json, sys
doc = json.load(open(sys.argv[1]))
top = doc["ranked"][0]
assert top["site"] == "serve/decode", [r["site"] for r in doc["ranked"]]
assert top["bytes_b"] and top["bytes_a"] and top["bytes_b"] > top["bytes_a"], top
assert "growth" in top["verdict"], top
print(f"explain OK: ranked #1 {top['site']} bytes "
      f"{top['bytes_a']:.3g} -> {top['bytes_b']:.3g} ({top['verdict']})")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: explain ranking (rc=$rc)"; }
  # (4) falsifiability: sane thresholds green, absurd threshold exits 1,
  # same logdir
  python -m dtf_tpu.telemetry.report "$codir/b" \
      --max_hbm_frac 0.9 --max_compiles 500 > "$codir/gates.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: sane hbm gates (rc=$rc)"; tail -5 "$codir/gates.log"; }
  grep -q "gate max_hbm_frac: OK" "$codir/gates.log" \
    && grep -q "gate max_compiles: OK" "$codir/gates.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: hbm gate lines missing"; }
  python -m dtf_tpu.telemetry.report "$codir/b" \
      --max_hbm_frac 0.0000001 > /dev/null 2>&1
  [ $? -eq 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: absurd max_hbm_frac did not fail"; }
  rm -rf "$codir"
fi
# Perf-regression ledger gate: needs no TPU, no multi-process run, no
# fleet plane — it must run even on rigs that skip the fleet lane.
# Skip with NO_LEDGER_GATE=1.
if [ "${NO_LEDGER_GATE:-0}" != "1" ]; then
  echo "=== ledger gate (bench.py --check-ledger) ==="
  python bench.py --check-ledger \
    || { FAILS=$((FAILS + 1)); echo "FAILED: bench.py --check-ledger"; }
fi
# Scenario lane (DESIGN.md §8): the 2-cell mini-matrix through the real
# cell runner with --check — one chaos-off GPT baseline cell (the
# control row) and the host_down MNIST elastic cell (SIGKILL mid-run ->
# coordinated abort -> relaunch on a 4->2 shrunken mesh), each gated on
# all three of pinned convergence / goodput floor / throughput floor
# read from the on-disk telemetry.  Skip with NO_SCENARIO_LANE=1.
if [ "${NO_SCENARIO_LANE:-0}" != "1" ]; then
  echo "=== scenario lane (mini matrix: baseline + elastic, triple gate) ==="
  scdir=$(mktemp -d)
  JAX_PLATFORMS=cpu python -m dtf_tpu.scenarios --matrix mini \
      --out "$scdir" --check > "$scdir/lane.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: scenario mini-matrix --check (rc=$rc)"; tail -20 "$scdir/lane.log"; }
  grep -q "scenario check: OK" "$scdir/lane.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: scenario check line missing"; }
  python - "$scdir" <<'PYEOF'
import json, os, sys
d = sys.argv[1]
cells = {}
for name in ("gpt_baseline", "mnist_host_down_elastic"):
    doc = json.load(open(os.path.join(d, f"{name}.json")))
    assert doc["ok"], (name, doc["gates"], doc["error"])
    # all three gate families produced verdicts (plus the books check)
    text = "\n".join(doc["gates"])
    assert "goodput_books" in text and "min_goodput" in text \
        and "max_final_cost" in text, text
    assert any(k in text for k in ("min_examples_per_s",
                                   "min_tokens_per_s", "min_mfu")), text
    cells[name] = doc
# the elastic cell really relaunched on the shrunken mesh
assert cells["mnist_host_down_elastic"]["rounds"] == 1, \
    cells["mnist_host_down_elastic"]["rounds"]
print("scenario lane OK: 2/2 cells passed the triple gate "
      f"(elastic relaunch rounds={cells['mnist_host_down_elastic']['rounds']})")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: scenario lane assertions (rc=$rc)"; }
  rm -rf "$scdir"
fi
# Incident lane (DESIGN.md "Incident plane", ISSUE 18): (1) a chaos'd
# WALL-CLOCK serve run with --admin_port, /incidentz scraped MID-run —
# the live ring must already hold an incident whose top-ranked suspect
# is the injected fault; (2) post-hoc `report --diagnose` over the same
# logdir re-runs the correlator from the span files and must rank the
# injected chaos kind TOP (exit 0: every anomaly explained); (3) the
# --min_attribution_frac gate is green on the chaos run; (4) the
# FALSIFIABILITY twin: the identical run with chaos OFF must report
# zero incidents and still exit 0 (vacuous attribution — calm is a
# pass, silence about a real fault is not).  Skip with
# NO_INCIDENT_LANE=1.
if [ "${NO_INCIDENT_LANE:-0}" != "1" ]; then
  echo "=== incident lane (live /incidentz + report --diagnose + chaos-off twin) ==="
  idir=$(mktemp -d)
  # (1) chaos'd wall-clock serve, /incidentz scraped mid-run
  JAX_PLATFORMS=cpu python - "$idir" <<'PYEOF'
import json, os, socket, subprocess, sys, time, urllib.request
d = sys.argv[1]
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "dtf_tpu.serve", "--preset", "tiny",
     "--demo", "60", "--qps", "20", "--clock", "wall", "--seed", "7",
     "--chaos", "slow_decode@30:60ms",
     "--admin_port", str(port), "--logdir", os.path.join(d, "chaos")],
    stdout=open(os.path.join(d, "chaos.log"), "w"),
    stderr=subprocess.STDOUT, env={**os.environ, "JAX_PLATFORMS": "cpu"})
cut = index = None
try:
    deadline = time.time() + 240
    while time.time() < deadline and proc.poll() is None:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/incidentz", timeout=5) as r:
                doc = json.loads(r.read())
            if index is None:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/", timeout=5) as r:
                    index = json.loads(r.read())
        except OSError:
            time.sleep(0.2); continue
        if doc.get("total", 0) >= 1:
            cut = doc
            break
        time.sleep(0.2)
finally:
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        proc.kill(); proc.wait(); rc = -1
assert rc == 0, f"chaos'd serve exited {rc}"
assert cut is not None, "/incidentz never showed an incident mid-run"
top = cut["incidents"][0]["top"]
assert top and top["plane"] == "chaos" and top["kind"] == "slow_decode", \
    f"live top suspect {top} is not the injected fault"
assert index["endpoints"]["/incidentz"] == "armed", index
print(f"live scrape OK: {cut['total']} incident(s) mid-run, top suspect "
      f"[{top['plane']}] {top['kind']} (score {top['score']:.3f})")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: incident live scrape (rc=$rc)"; tail -8 "$idir/chaos.log" 2>/dev/null; }
  # (2) post-hoc diagnose: injected fault must be TOP-ranked, exit 0
  python -m dtf_tpu.telemetry.report --diagnose "$idir/chaos" \
      | tee "$idir/diagnose.log"
  rc=${PIPESTATUS[0]}
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: report --diagnose (rc=$rc)"; }
  grep -q "chaos.*slow_decode.*<< TOP" "$idir/diagnose.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: injected fault not top-ranked in --diagnose"; }
  # (3) the attribution gate is green on the chaos run (wall-clock floor)
  python -m dtf_tpu.telemetry.report "$idir/chaos" \
      --min_attribution_frac 0.75 > "$idir/gate.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: min_attribution_frac gate on chaos run (rc=$rc)"; tail -5 "$idir/gate.log"; }
  grep -q "gate min_attribution_frac: OK" "$idir/gate.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: attribution gate line missing"; }
  # (4) chaos-off twin: zero incidents, exit 0 (the falsifiability pin —
  # a detector that fires on a calm run would poison every attribution)
  JAX_PLATFORMS=cpu python -m dtf_tpu.serve --preset tiny --demo 60 \
      --qps 20 --clock wall --seed 7 \
      --logdir "$idir/calm" > "$idir/calm.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: chaos-off twin run (rc=$rc)"; tail -5 "$idir/calm.log"; }
  python -m dtf_tpu.telemetry.report --diagnose "$idir/calm" \
      | tee "$idir/calm_diag.log"
  rc=${PIPESTATUS[0]}
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: twin --diagnose (rc=$rc)"; }
  grep -q "anomalies 0 " "$idir/calm_diag.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: chaos-off twin detected anomalies"; }
  rm -rf "$idir"
fi
# Pod-gradient lane (DESIGN.md §4.3, ISSUE 19): (1) the sharding
# planner's A/B acceptance — `breakdown --plan_ab` on the 8-way sim
# mesh must show --plan auto (zero1 + int8_ring) shipping STRICTLY
# fewer wire bytes than the PR-6 pinned dense one-shot-int8 cell, with
# step time no worse (<= 1.10x) and the planner's peak-HBM prediction
# within 5% of the compile-time measurement — the CLI itself exits 1
# when any leg fails, and the JSON is re-asserted here leg by leg;
# (2) the int8_ring wire's per-hop requantization must keep the LM
# loss trajectory inside the pinned envelope (bench.int8_quality
# --trajectory); (3) the mnist_zero1_int8_ring scenario cell — a
# SIGTERM-preempted supervised --plan auto run on 8 devices — must
# pass its triple gate + the armed wire-bytes ceiling, and the SAME
# logdir must feed the report CLI: the explicit
# --max_wire_bytes_per_step gate green at the committed 76 kB ceiling
# but RED at an absurd 1-byte one (falsifiability twin), and the
# single-logdir `report --explain` plan audit showing predicted vs
# measured peak HBM from the recorded plan.json.  Skip with
# NO_PODGRADIENT_LANE=1.
if [ "${NO_PODGRADIENT_LANE:-0}" != "1" ]; then
  echo "=== pod-gradient lane (plan_ab A/B + ring trajectory envelope + chaos'd plan-auto cell) ==="
  pgdir=$(mktemp -d)
  # (1) planner A/B: exit 1 unless wire_win && step_time_ok && hbm ok
  JAX_PLATFORMS=cpu python -m dtf_tpu.bench.breakdown --plan_ab \
      --ab_steps 12 --simulated_devices 8 \
      > "$pgdir/plan_ab.json" 2>"$pgdir/plan_ab.err"
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: breakdown --plan_ab (rc=$rc)"; tail -5 "$pgdir/plan_ab.err"; cat "$pgdir/plan_ab.json"; }
  python - "$pgdir/plan_ab.json" <<'PYEOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["ok"], (doc["wire_win"], doc["step_time_ratio"],
                   doc["hbm_prediction_ok"])
auto, pinned = doc["plan_auto"], doc["pinned"]
assert doc["wire_win"] and doc["wire_bytes_ratio"] < 1.0, doc["wire_bytes_ratio"]
assert doc["step_time_ratio"] <= 1.0 + doc["step_time_tol_pct"] / 100.0
assert auto["grad_sync"] == "zero1", auto["grad_sync"]
assert auto["grad_comm_dtype"] == "int8_ring", auto["grad_comm_dtype"]
# hop-aware wire accounting: the ring pays n-1 hops, the one-shot pays 1
assert auto["hops"] == doc["data_axis"] - 1 and pinned["hops"] == 1, \
    (auto["hops"], pinned["hops"])
assert auto["hbm_prediction_rel_err"] <= doc["max_hbm_prediction_rel_err"]
print(f"plan_ab OK: wire {pinned['wire_bytes_per_step']:.0f} -> "
      f"{auto['wire_bytes_per_step']:.0f} B/step "
      f"(-{1 - doc['wire_bytes_ratio']:.1%}), step time ratio "
      f"{doc['step_time_ratio']:.3f}, HBM prediction rel err "
      f"{auto['hbm_prediction_rel_err']:.1%}")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: plan_ab leg assertions (rc=$rc)"; }
  # (2) per-hop requantization quality: trajectory inside the envelope
  JAX_PLATFORMS=cpu python -m dtf_tpu.bench.int8_quality --trajectory \
      --simulated_devices 8 --grad_comm_dtype int8_ring \
      | tee "$pgdir/traj.log"
  rc=${PIPESTATUS[0]}
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: int8_ring trajectory run (rc=$rc)"; }
  grep -q "data axis 8" "$pgdir/traj.log" \
    && grep -q "wire=int8_ring" "$pgdir/traj.log" \
    && grep -q "within envelope: YES" "$pgdir/traj.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: int8_ring trajectory outside the pinned envelope"; }
  # (3) the chaos'd plan-auto scenario cell, then the report CLI over
  # the cell's own logdir
  JAX_PLATFORMS=cpu python -m dtf_tpu.scenarios \
      --only mnist_zero1_int8_ring --out "$pgdir/sc" --check \
      > "$pgdir/sc.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: mnist_zero1_int8_ring cell --check (rc=$rc)"; tail -20 "$pgdir/sc.log"; }
  grep -q "scenario check: OK" "$pgdir/sc.log" \
    && grep -q "gate max_wire_bytes_per_step: OK" "$pgdir/sc.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: cell gate lines missing"; }
  pglogs="$pgdir/sc/work/mnist_zero1_int8_ring/logs"
  python -m dtf_tpu.telemetry.report "$pglogs" \
      --max_wire_bytes_per_step 76000 > "$pgdir/gate.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: wire-bytes gate on cell logdir (rc=$rc)"; tail -5 "$pgdir/gate.log"; }
  grep -q "gate max_wire_bytes_per_step: OK" "$pgdir/gate.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: wire gate line missing"; }
  # falsifiability: a 1-byte ceiling must FAIL the same logdir
  python -m dtf_tpu.telemetry.report "$pglogs" \
      --max_wire_bytes_per_step 1 > /dev/null 2>&1
  [ $? -eq 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: absurd wire ceiling did not fail"; }
  # the plan audit off the recorded plan.json (single-logdir --explain)
  python -m dtf_tpu.telemetry.report "$pglogs" --explain \
      | tee "$pgdir/audit.log"
  rc=${PIPESTATUS[0]}
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: report --explain plan audit (rc=$rc)"; }
  grep -q "Plan audit" "$pgdir/audit.log" \
    && grep -q "predicted peak HBM" "$pgdir/audit.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: plan audit lines missing"; }
  python - "$pgdir" <<'PYEOF'
import json, os, sys
d = sys.argv[1]
doc = json.load(open(os.path.join(d, "sc", "mnist_zero1_int8_ring.json")))
assert doc["ok"], (doc["gates"], doc.get("error"))
wire = doc["measured"]["wire_bytes_per_step"]
# the ring wire: strictly under the one-shot int8 cell's 81120 B/step
assert 0 < wire < 81120, wire
logs = os.path.join(d, "sc", "work", "mnist_zero1_int8_ring", "logs")
plan = json.load(open(os.path.join(logs, "plan.json")))
assert plan["grad_sync"] == "zero1", plan["grad_sync"]
assert plan["grad_comm_dtype"] == "int8_ring", plan["grad_comm_dtype"]
print(f"plan-auto cell OK: wire {wire:.0f} B/step under the 76000 "
      f"ceiling, plan.json pinned {plan['grad_sync']}+"
      f"{plan['grad_comm_dtype']} [{plan['source']}]")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: plan-auto cell assertions (rc=$rc)"; }
  rm -rf "$pgdir"
fi
# Prefix-cache lane (DESIGN.md §7.7, ISSUE 20): (1) the same-trace
# cache-on/off A/B (serve_load --prefix_ab --check) — TTFT p50 >= 1.5x,
# p99 strictly improves, tokens bitwise identical (greedy AND sampled),
# hits observed, zero leaked blocks after churn-with-random-cancels —
# the CLI itself exits 1 when any gate fails and the JSON is
# re-asserted here; (2) a wall-clock --prefix_cache serve with
# /memz scraped MID-run: the cached-tier gauge must show parked blocks
# while the run is live; (3) the report CLI over the A/B's cache-on
# logdir: --min_prefix_hit_rate green at the committed floor, RED at an
# absurd one, and RED over a cache-OFF logdir (absence = served cold =
# FAIL, the falsifiability twin pair).  Skip with NO_PREFIX_LANE=1.
if [ "${NO_PREFIX_LANE:-0}" != "1" ]; then
  echo "=== prefix-cache lane (cache on/off A/B + /memz cached-tier scrape + hit-rate gates) ==="
  pcdir=$(mktemp -d)
  # (1) the five-gate A/B on the virtual-clock CPU rig (the PREFIX_r*
  # round geometry: block 8, 40-token shared prefixes, 3 prefix pool)
  JAX_PLATFORMS=cpu python -m dtf_tpu.bench.serve_load --prefix_ab \
      --block_size 8 --requests 24 --qps 8 --clock virtual \
      --prompt_lens 1,4,7 --output_lens 2,4,8 --check \
      --json "$pcdir/ab.json" --logdir "$pcdir/on" \
      > "$pcdir/ab.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: serve_load --prefix_ab --check (rc=$rc)"; tail -10 "$pcdir/ab.log"; }
  python - "$pcdir/ab.json" <<'PYEOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["ok"], doc["gates"]
on, off, churn = doc["cache_on"], doc["cache_off"], doc["churn"]
assert doc["ttft_p50_ratio"] >= doc["min_ratio"], doc["ttft_p50_ratio"]
assert on["ttft_ms_p99"] < off["ttft_ms_p99"]
ident = doc["token_identity_detail"]
assert doc["token_identity"] and ident["greedy"] > 0 and ident["sampled"] > 0
assert on["prefix_hit_blocks"] > 0 and on["prefix_hit_rate"] > 0
assert churn["leaked_on"] == 0 and churn["leaked_off"] == 0, churn
print(f"prefix_ab OK: ttft p50 {off['ttft_ms_p50']:.1f} -> "
      f"{on['ttft_ms_p50']:.1f} ms ({doc['ttft_p50_ratio']:.2f}x), "
      f"hit rate {on['prefix_hit_rate']:.3f}, "
      f"{ident['greedy']}+{ident['sampled']} greedy+sampled streams "
      f"identical, 0 leaks after {churn['cancels']} cancels")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: prefix_ab leg assertions (rc=$rc)"; }
  # (2) wall-clock --prefix_cache serve, /memz scraped mid-run: the
  # cached tier must be visibly populated while the engine is live
  JAX_PLATFORMS=cpu python - "$pcdir" <<'PYEOF'
import json, os, socket, subprocess, sys, time, urllib.request
d = sys.argv[1]
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
proc = subprocess.Popen(
    [sys.executable, "-m", "dtf_tpu.serve", "--preset", "tiny",
     "--demo", "48", "--qps", "20", "--clock", "wall", "--seed", "7",
     "--block_size", "8", "--prompt_lens", "1,4,7",
     "--output_lens", "2,4,8", "--prefix_cache",
     "--admin_port", str(port), "--logdir", os.path.join(d, "wall")],
    stdout=open(os.path.join(d, "wall.log"), "w"),
    stderr=subprocess.STDOUT, env={**os.environ, "JAX_PLATFORMS": "cpu"})
cut = None
try:
    deadline = time.time() + 240
    while time.time() < deadline and proc.poll() is None:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/memz", timeout=5) as r:
                doc = json.loads(r.read())
        except OSError:
            time.sleep(0.2); continue
        m = doc.get("metrics", {})
        # wait for parked blocks AND a hit — the first scrape can land
        # before any stream has finished and released its prefix pins
        if (m.get("serve/kv_cached_blocks", {}).get("value", 0) > 0
                and m.get("serve/prefix_hit_blocks_total",
                          {}).get("value", 0) > 0):
            cut = m
            break
        time.sleep(0.2)
finally:
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        proc.kill(); proc.wait(); rc = -1
assert rc == 0, f"prefix_cache serve exited {rc}"
assert cut is not None, "/memz never showed a populated cached tier mid-run"
cached = cut["serve/kv_cached_blocks"]["value"]
hits = cut["serve/prefix_hit_blocks_total"]["value"]
looks = cut["serve/prefix_lookup_total"]["value"]
print(f"memz scrape OK: {cached:.0f} cached block(s) parked mid-run, "
      f"{hits:.0f} hit block(s) over {looks:.0f} lookup(s)")
PYEOF
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: prefix /memz scrape (rc=$rc)"; tail -8 "$pcdir/wall.log" 2>/dev/null; }
  # (3) report gates over the A/B's cache-on logdir: green at the
  # committed floor...
  python -m dtf_tpu.telemetry.report "$pcdir/on" \
      --min_prefix_hit_rate 0.5 > "$pcdir/gate.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: min_prefix_hit_rate gate on cache-on logdir (rc=$rc)"; tail -5 "$pcdir/gate.log"; }
  grep -q "gate min_prefix_hit_rate: OK" "$pcdir/gate.log" \
    || { FAILS=$((FAILS + 1)); echo "FAILED: hit-rate gate line missing"; }
  # ...RED at an absurd floor on the SAME logdir...
  python -m dtf_tpu.telemetry.report "$pcdir/on" \
      --min_prefix_hit_rate 0.999 > /dev/null 2>&1
  [ $? -eq 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: absurd min_prefix_hit_rate did not fail"; }
  # ...and RED over a cache-OFF logdir (no prefix_hit_rate key at all:
  # absence means the run served cold, which the armed gate must FAIL)
  JAX_PLATFORMS=cpu python -m dtf_tpu.serve --preset tiny --demo 8 \
      --qps 20 --clock virtual --seed 7 --block_size 8 \
      --prompt_lens 1,4,7 --output_lens 2,4,8 \
      --logdir "$pcdir/cold" > "$pcdir/cold.log" 2>&1
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: cache-off twin run (rc=$rc)"; tail -5 "$pcdir/cold.log"; }
  python -m dtf_tpu.telemetry.report "$pcdir/cold" \
      --min_prefix_hit_rate 0.5 > /dev/null 2>&1
  [ $? -eq 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: armed hit-rate gate passed a cache-off logdir"; }
  rm -rf "$pcdir"
fi
echo "=== full suite done; failed files: $FAILS ==="
exit $([ "$FAILS" -eq 0 ] && echo 0 || echo 1)
