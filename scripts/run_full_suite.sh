#!/bin/bash
# Full test suite (fast + slow), one pytest PROCESS PER FILE.
# A single-process run of all ~420 tests accumulates enough XLA-CPU
# client state on this 1-core rig to segfault partway through
# (reproduced twice at different tests; every file passes in
# isolation) — per-file processes bound the accumulation and give the
# same coverage.  Multi-process tests manage their own subprocesses.
# Usage: bash scripts/run_full_suite.sh [extra pytest args...]
set -u
cd "$(dirname "$0")/.." || exit 1
FAILS=0
for f in tests/test_*.py; do
  echo "=== $f ==="
  python -m pytest "$f" -q -m "slow or not slow" -p no:cacheprovider "$@"
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: $f (rc=$rc)"; }
done
# Chaos lane: the full fault-injection matrix (pytest -m chaos plus the
# CLI-level injection runs, including the host-fault matrix) so ONE
# command covers the whole suite.  Skip with NO_CHAOS_LANE=1.
if [ "${NO_CHAOS_LANE:-0}" != "1" ]; then
  echo "=== chaos lane (scripts/run_chaos_suite.sh) ==="
  bash scripts/run_chaos_suite.sh
  rc=$?
  [ "$rc" -ne 0 ] && { FAILS=$((FAILS + 1)); echo "FAILED: chaos lane (rc=$rc)"; }
fi
echo "=== full suite done; failed files: $FAILS ==="
exit $([ "$FAILS" -eq 0 ] && echo 0 || echo 1)
