#!/usr/bin/env python
"""Perf-regression ledger: fold the loose ``BENCH_r*.json`` /
``MULTICHIP_r*.json`` / ``DECODE_r*.json`` / ``PLAN_r*.json`` /
``PREFIX_r*.json`` round files into one machine-readable
``LEDGER.jsonl`` — one row per run with rig, commit, the rig's headline
metric (TFLOP/s for matmul rounds, aggregate tokens/s for decode-ladder
rounds, wire-byte reduction for plan_ab rounds, cold/warm TTFT p50
ratio for prefix_ab rounds), MFU (roofline fraction) and, for failed
rounds, the error + stage.

The round files alone hide the trajectory: r01-r02 held ~193 TFLOP/s at
~98% of roofline, then r03-r05 all died on ``tpu_unavailable`` relay
hangs — five loose JSON files in the repo root, invisible unless you
open each.  The ledger makes that one ``jq``-able stream, and
``python bench.py --check-ledger`` turns it into a CI gate: the newest
green run on each rig must not regress against the best prior green run
on the same rig (``DTF_LEDGER_TOL_PCT``, default 10), and a trailing
error streak prints loud instead of rotting silently.

Usage:
    python scripts/bench_ledger.py [--repo DIR] [--out LEDGER.jsonl]
    python bench.py --check-ledger [--ledger LEDGER.jsonl]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys


def _added_commit(repo: str, filename: str) -> "str | None":
    """The commit that first added ``filename`` (the round files carry no
    commit of their own) — best-effort: None outside a git checkout."""
    try:
        out = subprocess.run(
            ["git", "log", "--diff-filter=A", "--format=%h", "-n", "1",
             "--", filename],
            cwd=repo, capture_output=True, text=True, timeout=30)
        return out.stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        return None


#: Optional device-cost columns (telemetry/costobs.py, ISSUE 15):
#: present only when the round's doc carried them — rows written before
#: the cost observatory existed fold WITHOUT these keys, so the
#: committed LEDGER.jsonl is byte-stable and old rows keep parsing
#: (readers use .get; the round-trip test pins both directions).
#: peak_hbm_bytes semantics per kind (the gate compares within one rig,
#: and rigs never mix kinds, so the two readings never cross-diagnose):
#: bench rows carry the max per-executable compile-time HBM claim
#: (CostCard.peak_hbm_bytes); decode rows carry the invocation's live
#: device-bytes watermark sampled at ladder-point boundaries.
COST_COLUMNS = ("peak_hbm_bytes", "n_compiles")


def _fold_cost_columns(row: dict, doc: dict) -> None:
    for col in COST_COLUMNS:
        if doc.get(col) is not None and row.get(col) is None:
            row[col] = doc[col]


def _classify_legacy_tail(tail: str) -> "tuple[str, str]":
    """Rounds recorded before the structured failure line (r03: a raw
    traceback, parsed=null) still classify: the relay's signature error
    strings are stable."""
    low = (tail or "").lower()
    if "unavailable" in low and ("tpu" in low or "backend" in low):
        return "tpu_unavailable", "legacy_traceback"
    return "benchmark_error", "legacy_traceback"


def bench_row(path: str, repo: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    run = os.path.splitext(os.path.basename(path))[0]
    row = {
        "run": run,
        "kind": "bench",
        "n": doc.get("n"),
        "commit": _added_commit(repo, os.path.basename(path)),
        "rig": None,
        "tflops_per_chip": None,
        "mfu": None,               # roofline fraction, 0..1
        "vs_baseline": None,
        "ok": False,
        "error": None,
        "stage": None,
    }
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and parsed.get("error"):
        detail = parsed.get("detail") or {}
        row.update(error=parsed["error"], stage=detail.get("stage"),
                   rig=detail.get("device"))
    elif isinstance(parsed, dict) and parsed.get("value") is not None:
        detail = parsed.get("detail") or {}
        row.update(
            ok=doc.get("rc", 1) == 0,
            rig=detail.get("device"),
            tflops_per_chip=float(parsed["value"]),
            mfu=detail.get("roofline_fraction"),
            vs_baseline=parsed.get("vs_baseline"))
        _fold_cost_columns(row, detail)
    else:
        err, stage = _classify_legacy_tail(doc.get("tail", ""))
        row.update(error=err, stage=stage)
    _fold_cost_columns(row, doc)
    return row


def multichip_row(path: str, repo: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    run = os.path.splitext(os.path.basename(path))[0]
    ok = bool(doc.get("ok")) and not doc.get("skipped")
    row = {
        "run": run,
        "kind": "multichip",
        "n": doc.get("n", _run_index(run)),
        "commit": _added_commit(repo, os.path.basename(path)),
        "rig": (f"{doc.get('n_devices')}dev"
                if doc.get("n_devices") else None),
        "tflops_per_chip": None,
        "mfu": None,
        "vs_baseline": None,
        "ok": ok,
        "error": None if ok else "multichip_failed",
        "stage": None if ok else ("skipped" if doc.get("skipped")
                                  else "dryrun"),
    }
    _fold_cost_columns(row, doc)
    return row


def decode_row(path: str, repo: str) -> dict:
    """DECODE_r*.json: one ``bench.decode_ladder --json`` doc (plus an
    ``n`` round index).  Headline metric = aggregate tokens/s over the
    ladder's marginal fit; a doc carrying the fit's no-signal warning
    (or no tok_s at all) folds as an errored round, not a silent gap."""
    with open(path) as f:
        doc = json.load(f)
    run = os.path.splitext(os.path.basename(path))[0]
    tok_s = doc.get("tok_s_aggregate")
    ok = tok_s is not None and not doc.get("warning")
    row = {
        "run": run,
        "kind": "decode",
        "n": doc.get("n", _run_index(run)),
        "commit": _added_commit(repo, os.path.basename(path)),
        # rig = the ladder doc's full arm geometry (preset/mode/streams/
        # block_size/narrow/pool...) so deliberately-different arms (a
        # --no_narrow baseline, an oversized pool) never alias onto one
        # regression history
        "rig": doc.get("rig") or (
            f"decode_{doc.get('preset')}_{doc.get('mode')}"),
        "tok_s_aggregate": float(tok_s) if ok else None,
        "per_token_us": doc.get("per_token_us"),
        "spec_acceptance": doc.get("spec_acceptance"),
        "ok": ok,
        "error": None if ok else (doc.get("warning") or "no_tok_s"),
        "stage": None if ok else "ladder_fit",
    }
    _fold_cost_columns(row, doc)
    return row


def plan_row(path: str, repo: str) -> dict:
    """PLAN_r*.json: one ``bench.breakdown --plan_ab`` doc (plus an
    ``n`` round index).  Headline metric = ``wire_reduction`` (fraction
    of scatter-leg wire bytes the planned cell shaves off the PR-6
    pinned cell; higher is better); ok = the doc's triple gate (wire
    win AND step time within tolerance AND HBM prediction within
    tolerance), and the failing leg lands in ``stage``."""
    with open(path) as f:
        doc = json.load(f)
    run = os.path.splitext(os.path.basename(path))[0]
    ok = bool(doc.get("ok"))
    auto = doc.get("plan_auto") or {}
    row = {
        "run": run,
        "kind": "plan",
        "n": doc.get("n", _run_index(run)),
        "commit": _added_commit(repo, os.path.basename(path)),
        "rig": doc.get("rig") or f"plan_{doc.get('data_axis')}dev",
        "wire_reduction": (float(doc["wire_reduction"])
                           if doc.get("wire_reduction") is not None
                           else None),
        "step_time_ratio": doc.get("step_time_ratio"),
        "hbm_prediction_rel_err": auto.get("hbm_prediction_rel_err"),
        "ok": ok,
        "error": None if ok else "plan_ab_gate_failed",
        "stage": None if ok else (
            "wire" if not doc.get("wire_win")
            else "step_time" if not doc.get("step_time_ok")
            else "hbm_prediction"),
    }
    _fold_cost_columns(row, doc)
    return row


def prefix_row(path: str, repo: str) -> dict:
    """PREFIX_r*.json: one ``serve_load --prefix_ab --json`` doc (plus
    an ``n`` round index).  Headline metric = ``ttft_p50_ratio`` (cold
    p50 TTFT over cache-on p50 TTFT on the SAME trace; higher is
    better, 1.0 = the cache bought nothing); ok = the doc's five-gate
    verdict (p50 ratio >= bar AND p99 strictly improves AND tokens
    bitwise identical AND hits observed AND zero leaked blocks after
    churn-with-cancels), and the first failing gate lands in
    ``stage``."""
    with open(path) as f:
        doc = json.load(f)
    run = os.path.splitext(os.path.basename(path))[0]
    ok = bool(doc.get("ok"))
    on = doc.get("cache_on") or {}
    churn = doc.get("churn") or {}
    stage = None
    if not ok:
        for line in doc.get("gates") or []:
            if "FAIL" in line:
                # "gate prefix_ttft_p50: FAIL — ..." -> "prefix_ttft_p50"
                stage = line.split(":", 1)[0].replace("gate ", "").strip()
                break
        stage = stage or "prefix_ab_gate_failed"
    row = {
        "run": run,
        "kind": "prefix",
        "n": doc.get("n", _run_index(run)),
        "commit": _added_commit(repo, os.path.basename(path)),
        "rig": doc.get("rig") or (
            f"prefix_bs{on.get('kv_block_size')}_p{doc.get('prefix_len')}"),
        "ttft_p50_ratio": (float(doc["ttft_p50_ratio"])
                           if doc.get("ttft_p50_ratio") is not None
                           else None),
        "prefix_hit_rate": on.get("prefix_hit_rate"),
        "kv_cached_blocks": on.get("kv_cached_blocks"),
        "leaked_blocks": (None if "leaked_on" not in churn
                          else int(churn.get("leaked_on") or 0)
                          + int(churn.get("leaked_off") or 0)),
        "ok": ok,
        "error": None if ok else "prefix_ab_gate_failed",
        "stage": stage,
    }
    _fold_cost_columns(row, doc)
    return row


def _run_index(run: str) -> "int | None":
    m = re.search(r"_r(\d+)$", run)
    return int(m.group(1)) if m else None


def build_ledger(repo: str) -> "list[dict]":
    rows = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        rows.append(bench_row(path, repo))
    for path in sorted(glob.glob(os.path.join(repo, "MULTICHIP_r*.json"))):
        rows.append(multichip_row(path, repo))
    for path in sorted(glob.glob(os.path.join(repo, "DECODE_r*.json"))):
        rows.append(decode_row(path, repo))
    for path in sorted(glob.glob(os.path.join(repo, "PLAN_r*.json"))):
        rows.append(plan_row(path, repo))
    for path in sorted(glob.glob(os.path.join(repo, "PREFIX_r*.json"))):
        rows.append(prefix_row(path, repo))
    # one stream, ordered (kind, round) so the per-rig trajectory reads
    # top to bottom
    rows.sort(key=lambda r: (r["kind"], r["n"] if r["n"] is not None
                             else _run_index(r["run"]) or 0))
    return rows


def write_ledger(rows: "list[dict]", out_path: str) -> None:
    with open(out_path, "w") as f:
        for row in rows:
            f.write(json.dumps(row, sort_keys=True) + "\n")


def read_ledger(path: str) -> "list[dict]":
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def _gate_kind(rows: "list[dict]", kind: str, field: str, unit: str,
               tol_pct: float, lines: "list[str]") -> bool:
    """One kind's newest-green-vs-best-prior gate, per rig.  Returns
    ok; appends verdict lines."""
    ok = True
    kind_rows = sorted((r for r in rows if r.get("kind") == kind),
                       key=lambda r: r.get("n") or 0)
    by_rig: "dict[str, list[dict]]" = {}
    for r in kind_rows:
        if r.get("ok") and r.get(field) and r.get("rig"):
            by_rig.setdefault(r["rig"], []).append(r)
    if not by_rig and kind == "bench":
        lines.append("ledger: no green bench rows — nothing to compare")
    for rig, greens in sorted(by_rig.items()):
        latest = greens[-1]
        prior = greens[:-1]
        if not prior:
            lines.append(
                f"ledger[{rig}]: OK — first green run "
                f"{latest['run']} at {latest[field]:g} "
                f"{unit} (no prior to compare)")
            continue
        best = max(prior, key=lambda r: r[field])
        floor = best[field] * (1.0 - tol_pct / 100.0)
        passed = latest[field] >= floor
        ok = ok and passed
        lines.append(
            f"ledger[{rig}]: {'OK' if passed else 'REGRESSION'} — "
            f"{latest['run']} {latest[field]:g} {unit} vs "
            f"best prior green {best['run']} "
            f"{best[field]:g} (floor {floor:g}, "
            f"tol {tol_pct:g}%)")
        if not passed:
            # Name the regressed QUANTITY, not just the rig: the
            # headline delta always, plus the optional device-cost
            # columns (peak HBM, compile count) when both rounds
            # carried them — a compile-count or HBM jump alongside a
            # throughput drop is the diagnosis, not a coincidence.
            drop = (latest[field] - best[field]) / best[field]
            quant = [f"{field} {best[field]:g} -> {latest[field]:g} "
                     f"({drop:+.1%})"]
            for col, label in (("peak_hbm_bytes", "peak_hbm"),
                               ("n_compiles", "compiles")):
                # None-checks, not truthiness: a measured ZERO (e.g. 0
                # compiles, everything cache-served) is exactly the
                # reading whose jump is the diagnosis
                a, b = best.get(col), latest.get(col)
                if a is not None and b is not None:
                    pct = f" ({(b - a) / a:+.0%})" if a else ""
                    quant.append(f"{label} {a:g} -> {b:g}{pct}")
            lines.append(f"ledger[{rig}]:   regressed quantity: "
                         + "; ".join(quant))
    # trailing error streak: the stalled-trajectory alarm
    streak = []
    for r in reversed(kind_rows):
        if r.get("error"):
            streak.append(r)
        else:
            break
    if streak:
        streak.reverse()
        reasons = {f"{r.get('error')}@{r.get('stage')}" for r in streak}
        lines.append(
            f"ledger WARNING: last {len(streak)} {kind} run(s) errored "
            f"({', '.join(sorted(reasons))}) — "
            f"{streak[0]['run']}..{streak[-1]['run']}; the perf "
            f"trajectory is STALLED, fresh numbers needed")
    return ok


def check_ledger(rows: "list[dict]", tol_pct: float = 10.0
                 ) -> "tuple[bool, list[str]]":
    """The regression gate ``bench.py --check-ledger`` runs.

    Per rig and kind (bench rows gate TFLOP/s, decode rows gate
    aggregate tokens/s, plan rows gate the plan_ab wire-byte reduction,
    prefix rows gate the prefix-cache TTFT p50 speedup ratio; multichip
    rows are pass/fail dryruns): the
    NEWEST green run must hold at least ``(1 - tol) x`` the best of
    the EARLIER green runs on that rig.  A trailing streak of error rows
    (the stalled r03-r05 shape) prints loud as a warning — an outage is
    visible, not a perf regression.  Returns (ok, verdict lines)."""
    lines: "list[str]" = []
    ok = _gate_kind(rows, "bench", "tflops_per_chip", "TFLOP/s",
                    tol_pct, lines)
    ok = _gate_kind(rows, "decode", "tok_s_aggregate", "tok/s",
                    tol_pct, lines) and ok
    ok = _gate_kind(rows, "plan", "wire_reduction", "wire-frac",
                    tol_pct, lines) and ok
    ok = _gate_kind(rows, "prefix", "ttft_p50_ratio", "x",
                    tol_pct, lines) and ok
    return ok, lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python scripts/bench_ledger.py",
        description="Fold BENCH_r*/MULTICHIP_r* rounds into LEDGER.jsonl")
    p.add_argument("--repo", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    p.add_argument("--out", default=None,
                   help="output path (default <repo>/LEDGER.jsonl)")
    p.add_argument("--check", action="store_true",
                   help="also run the regression gate on the fresh rows")
    ns = p.parse_args(argv)
    rows = build_ledger(ns.repo)
    out = ns.out or os.path.join(ns.repo, "LEDGER.jsonl")
    write_ledger(rows, out)
    print(f"wrote {len(rows)} row(s) to {out}")
    if ns.check:
        ok, lines = check_ledger(rows)
        for line in lines:
            print(line)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
