#!/usr/bin/env python
"""Summarize a chip-blitz output directory into BASELINE.md-ready rows.

The blitz (scripts/chip_blitz_r5.sh) writes one log per step; after two
dark rounds, minutes on a live chip are the scarcest resource — this
turns a finished (or partial) blitz into a compact table immediately
instead of hand-scraping twenty logs.

    python scripts/blitz_rows.py [/tmp/r5_blitz]

Pure text processing (no jax import): safe to run anywhere, any time,
including against partial results while the blitz is still running.
"""

from __future__ import annotations

import pathlib
import re
import sys

# Last-matching-line patterns per interesting fact.
PATTERNS = [
    ("step", re.compile(r"^Step-Time: .*")),
    ("mfu", re.compile(r"^Model-Compute: .*")),
    ("bench", re.compile(r'^\{"(?:metric|error)".*')),
    ("ladder", re.compile(r"^per-token .*aggregate.*")),
    ("no_result", re.compile(r"^NO RESULT: .*")),
    ("ppl", re.compile(r"^perplexity ratio .*")),
    ("kv_ppl", re.compile(r"^KV-cache int8 .*")),
    ("trace", re.compile(r"^\[trace\] .*")),
    ("error", re.compile(r"^\w*Error: .*|^ValueError: .*")),
]


def summarize(log: pathlib.Path) -> list[str]:
    found: dict[str, str] = {}
    trace_rows: list[str] = []
    for line in log.read_text(errors="replace").splitlines():
        line = line.strip()
        for key, pat in PATTERNS:
            if pat.match(line):
                if key == "trace":
                    trace_rows.append(line)
                else:
                    found[key] = line
    out = [found[k] for k, _ in PATTERNS if k in found and k != "trace"]
    out += trace_rows[:5]                  # top device ops only
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    outdir = pathlib.Path(argv[0] if argv else "/tmp/r5_blitz")
    logs = sorted(outdir.glob("*.log"))
    if not logs:
        print(f"no logs in {outdir}")
        return 1
    for log in logs:
        rows = summarize(log)
        print(f"### {log.stem}")
        if rows:
            for r in rows:
                print(f"    {r}")
        else:
            tail = log.read_text(errors="replace").splitlines()[-3:]
            print("    (no recognized result lines; tail:)")
            for r in tail:
                print(f"    | {r.strip()}")
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
