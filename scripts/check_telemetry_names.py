#!/usr/bin/env python
"""Telemetry name lint (run by the full-suite telemetry lane and
tests/test_telemetry.py): every metric/span name literal in the package
must be snake_case/slash scoped AND declared in
dtf_tpu/telemetry/names.py — the report CLI and dashboards key on those
strings, and an undeclared name is a dashboard hole nobody notices until
the post-mortem needs it.

Usage: python scripts/check_telemetry_names.py
Exit 0 when clean; prints one line per violation otherwise.
"""

import glob
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from dtf_tpu.telemetry.names import check_source_names  # noqa: E402


def main() -> int:
    paths = sorted(glob.glob(os.path.join(ROOT, "dtf_tpu", "**", "*.py"),
                             recursive=True))
    problems = check_source_names(paths)
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} telemetry naming violation(s)")
        return 1
    print(f"telemetry names OK ({len(paths)} files scanned)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
