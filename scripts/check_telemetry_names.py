#!/usr/bin/env python
"""Telemetry name lint (run by the full-suite telemetry lane and
tests/test_telemetry.py), in BOTH directions:

* source -> table: every metric/span name literal in the package must be
  snake_case/slash scoped AND declared in dtf_tpu/telemetry/names.py —
  the report CLI and dashboards key on those strings, and an undeclared
  name is a dashboard hole nobody notices until the post-mortem needs
  it;
* runtime -> table: the process-wide registry must be STRICT — an
  instrument registered at runtime (e.g. a name assembled from variables
  that the AST lint could only see as a pattern) whose name no
  declaration covers must be REJECTED at creation.  This check arms the
  guard itself: it fails if the process registry would accept an
  undeclared instrument.

Usage: python scripts/check_telemetry_names.py
Exit 0 when clean; prints one line per violation otherwise.
"""

import glob
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from dtf_tpu.telemetry.names import check_source_names  # noqa: E402
from dtf_tpu.telemetry.registry import get_registry  # noqa: E402


def check_runtime_guard() -> list:
    """The reverse lint: the live registry must reject an undeclared
    instrument at registration time (and still accept declared names,
    exact and pattern-covered)."""
    problems = []
    reg = get_registry()
    if not getattr(reg, "strict", False):
        problems.append(
            "process registry is not strict: runtime-registered "
            "instruments are not checked against names.py")
        return problems
    for probe in ("lint_probe/definitely_not_declared",
                  # the fleet/* family is declared as exact names plus
                  # the per-host '*' patterns — a near-miss outside them
                  # must still be rejected
                  "fleet/definitely_not_declared",
                  # the cost/hbm families (telemetry/costobs.py) are
                  # exact-name declarations, no wildcards — a typo'd
                  # scope must fail at registration, not ship a run's
                  # worth of unplotted gauges
                  "cost/definitely_not_declared",
                  "hbm/definitely_not_declared",
                  "serve/kv_definitely_not_declared",
                  # the prefix-cache family (ISSUE 20) is exact-name
                  # declarations, no wildcard — a typo'd hit counter
                  # would silently zero the hit-rate gate
                  "serve/prefix_definitely_not_declared",
                  # the control/* family (ISSUE 17) mixes exact counters
                  # with the control/knob_* gauge pattern — a name
                  # outside both must be rejected
                  "control/definitely_not_declared",
                  # the incident plane (ISSUE 18) declares exact metric
                  # names only (anomaly/* is a SPAN pattern for the
                  # onset instants, but instruments outside the three
                  # exact counters must fail at registration)
                  "incident/definitely_not_declared",
                  # the sharding-planner family (ISSUE 19) and the comm/*
                  # gradient-wire gauges are exact-name declarations — a
                  # typo'd plan/comm instrument must fail at
                  # registration, not silently skip the plan audit
                  "plan/definitely_not_declared",
                  "comm/definitely_not_declared"):
        try:
            reg.counter(probe)
        except ValueError:
            pass
        else:
            problems.append(
                f"process registry ACCEPTED undeclared instrument "
                f"{probe!r} — the runtime guard is not enforcing "
                f"names.py")
    for name in ("serve/shed_deadline_expired",    # pattern serve/shed_*
                 "checkpoint/saves_total",         # exact declaration
                 "fleet/blame_p3",                 # pattern fleet/blame_p*
                 "fleet/barriers_total",           # exact (fleet family)
                 # the serving-fleet family (ISSUE 16): exact names only
                 # — the fleet/definitely_not_declared probe above is
                 # this family's rejection direction
                 "fleet/failovers_total",
                 "fleet/shed_acceptor_total",
                 "fleet/replay_mismatch_total",
                 # the knob-controller family (ISSUE 17): exact names
                 "control/rollback_total",
                 # the incident plane (ISSUE 18): exact counter names
                 "anomaly/detected_total",
                 "incident/recorded_total",
                 "incident/attributed_total",
                 # the prefix-cache family (ISSUE 20): exact names
                 "serve/prefix_lookup_total",
                 "serve/prefix_hit_blocks_total",
                 "cost/compiles_total"):           # exact (cost family)
        try:
            reg.counter(name)
        except ValueError as exc:
            problems.append(f"declared name {name!r} rejected at "
                            f"runtime: {exc}")
    # gauge-typed declarations probe through gauge() — the live process
    # may already hold them as gauges, and a counter() probe would trip
    # the type guard instead of exercising the naming guard
    for name in ("hbm/live_bytes",                 # exact (hbm family)
                 "cost/cards",                     # exact (cost family)
                 "fleet/replicas_up",              # exact (serving fleet)
                 "control/knob_spec_k",            # pattern control/knob_*
                 "serve/kv_pool_frac",             # exact (kv gauges)
                 "serve/kv_cached_blocks",         # exact (ISSUE 20)
                 # the pod-gradient path (ISSUE 19): ring-hop accounting
                 # and the planner's predicted-vs-measured audit gauges
                 "comm/hops",
                 "plan/active",
                 "plan/predicted_hbm_bytes",
                 "plan/predicted_step_ms",
                 "plan/source_idx",
                 "plan/hbm_budget_bytes"):
        try:
            reg.gauge(name)
        except ValueError as exc:
            problems.append(f"declared name {name!r} rejected at "
                            f"runtime: {exc}")
    return problems


def main() -> int:
    paths = sorted(glob.glob(os.path.join(ROOT, "dtf_tpu", "**", "*.py"),
                             recursive=True))
    problems = check_source_names(paths)
    problems += check_runtime_guard()
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} telemetry naming violation(s)")
        return 1
    print(f"telemetry names OK ({len(paths)} files scanned + runtime "
          f"registration guard armed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
