#!/bin/bash
# Round-4 chip measurement blitz (r3 VERDICT #1): the moment the TPU relay
# is back, run these IN ORDER and append the results to BASELINE.md.
# Measurement before new code — the relay died mid-round-3 and took every
# unrecorded row with it.  The chip is SINGLE-TENANT: one process at a
# time, and do not kill anything mid-compile (it can wedge the relay).
#
# Usage: bash scripts/chip_blitz_r4.sh [outdir]   (default /tmp/r4_blitz)
# Each step logs to its own file; a step that fails must NOT stop the rest.
set -u
OUT=${1:-/tmp/r4_blitz}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

# Timeouts are sized >=3x the r3-measured compile+run time of each step
# (worst measured compile ~20 min for unroll+accum, which this script
# AVOIDS) — a timeout firing mid-compile is the known relay-wedging
# action, so the margins are deliberately generous.
. "$(dirname "$0")/blitz_lib.sh"

# 1a. Headline matmul bench -> the BENCH_r04 shape the driver captures.
run bench 1800 python bench.py

# 1b. BERT-base global-batch-256 with the round-3 MFU fixes recorded as a
#     ROW (not a projection).  NO grad_accum: unroll+accum compile was
#     pathological (>20 min, r3).  mb64 fits at attn policy (r3 deep dive).
run bert_attn_unroll 3600 python -m dtf_tpu.workloads.bert_pretrain \
  --preset base --bf16 --remat --remat_policy attn --layer_loop unroll \
  --per_device_batch 64 --steps 30

# 1c. GPT-2-small, same flags + chunked loss.
run gpt_attn_unroll 3600 python -m dtf_tpu.workloads.lm \
  --preset gpt2_small --bf16 --remat --remat_policy attn \
  --layer_loop unroll --loss_chunk 128 --per_device_batch 8 --steps 30
# Profiled REPEATS of 1b/1c in separate legs (start/stop_trace overhead
# and the window-end sync would perturb the headline step timings):
# prints the top device ops per step (--profile_summary).
run bert_attn_unroll_trace 3600 python -m dtf_tpu.workloads.bert_pretrain \
  --preset base --bf16 --remat --remat_policy attn --layer_loop unroll \
  --per_device_batch 64 --steps 15 \
  --profile_dir /tmp/r4_trace_bert --profile_start 8 --profile_steps 3 \
  --profile_summary
run gpt_attn_unroll_trace 3600 python -m dtf_tpu.workloads.lm \
  --preset gpt2_small --bf16 --remat --remat_policy attn \
  --layer_loop unroll --loss_chunk 128 --per_device_batch 8 --steps 15 \
  --profile_dir /tmp/r4_trace_gpt --profile_start 8 --profile_steps 3 \
  --profile_summary

# 1d. Re-confirm the fused-decode single-stream number (r3: 3,811 tok/s,
#     builder-measured only) with the reproducible ladder module.
run ladder_fused_1 2400 python -m dtf_tpu.bench.decode_ladder \
  --preset gpt2_small --mode fused --streams 1
run ladder_unfused_1 2400 python -m dtf_tpu.bench.decode_ladder \
  --preset gpt2_small --mode unfused --streams 1

# 2. MFU close-or-retire evidence: attention block-size sweep + Dh
#    shape ablation (bench/breakdown.py --attn_sweep).  If no tiling
#    beats 512/512 AND Dh=128 ~doubles TF/s at equal FLOPs, the kernel
#    is at its shape ceiling and the 45%% target retires with proof.
run attn_sweep_bert 3600 python -m dtf_tpu.bench.breakdown \
  --attn_sweep --family bert
run attn_sweep_gpt 3600 python -m dtf_tpu.bench.breakdown \
  --attn_sweep --family gpt

# 3. Mosaic-validate the batched fused kernel + in-kernel RoPE (r3 landed
#    interpret-only; the (B,T,.)->(B*T,.) major-dim reshapes are the
#    legality risk).  LLaMA-style preset exercises RoPE+GQA+SwiGLU.
for b in 2 4 8 16 32; do
  run fused_batched_$b 1800 python -m dtf_tpu.workloads.lm --preset llama \
    --bf16 --steps 2 --generate 256 --gen_batch "$b" --decode_fused
done
# aggregate-throughput ladder rows: tiled fused vs unfused at 16/32
# streams (r2 unfused-32: 3,571 aggregate tok/s — the tiled kernel
# should beat it substantially), plus int8-in-kernel at 32.
for s in 16 32; do
  run ladder_fused_$s 2400 python -m dtf_tpu.bench.decode_ladder \
    --preset gpt2_small --mode fused --streams "$s"
  run ladder_unfused_$s 2400 python -m dtf_tpu.bench.decode_ladder \
    --preset gpt2_small --mode unfused --streams "$s"
done
run ladder_fused_32_int8 2400 python -m dtf_tpu.bench.decode_ladder \
  --preset gpt2_small --mode fused --streams 32 --int8
# int8 KV cache: halves per-token cache DMA (dominant at batched
# long-context); quality contract = bench.int8_quality --kv
run ladder_fused_32_kvint8 2400 python -m dtf_tpu.bench.decode_ladder \
  --preset gpt2_small --mode fused --streams 32 --kv_int8
run int8_kv_quality 3600 python -m dtf_tpu.bench.int8_quality \
  --preset gpt2_small --kv
# long-context fused decode with the cache walked in chunks (explicit
# --cache_chunk: at llama dims a 3.8k cache still fits one block, so
# force the chunked online-softmax kernel for its first real-Mosaic
# run).  The ladder re-sizes the cache per point (T = ceil128(3584+k) =
# 3712/3712/3840), so the chunk must divide EVERY point's T:
# gcd(3712, 3840) = 128.
run ladder_longctx_8 2400 python -m dtf_tpu.bench.decode_ladder \
  --preset llama --mode fused --streams 8 --prompt_len 3584 \
  --ladder 64,128,256 --cache_chunk 128
run ladder_longctx_8_kvint8 2400 python -m dtf_tpu.bench.decode_ladder \
  --preset llama --mode fused --streams 8 --prompt_len 3584 \
  --ladder 64,128,256 --cache_chunk 128 --kv_int8

# 4. Fused beam search (new this round): width-4 on one stream.
run ladder_beam4_fused 2400 python -m dtf_tpu.bench.decode_ladder \
  --preset gpt2_small --mode fused --beam 4
run ladder_beam4_unfused 2400 python -m dtf_tpu.bench.decode_ladder \
  --preset gpt2_small --mode unfused --beam 4

# 5. T5 + BERT+MoE rows (first real-chip perf rows for these families).
# seq2seq has no --remat flag; T5-small bf16 at seq 512 fits without it.
run t5_small 3600 python -m dtf_tpu.workloads.seq2seq \
  --preset small --bf16 --seq_len 512 --per_device_batch 16 --steps 30
run bert_moe 3600 python -m dtf_tpu.workloads.bert_pretrain \
  --preset base --bf16 --remat --moe_experts 8 \
  --per_device_batch 32 --steps 30

# 6. int8 quality on TRAINED weights: train GPT-2-small a few thousand
#    steps on the Markov LM task, checkpoint, score.  Longest step last.
run train_gpt2s 14400 python -m dtf_tpu.workloads.lm --preset gpt2_small \
  --bf16 --remat --remat_policy attn --per_device_batch 8 --steps 3000 \
  --checkpoint_every 1000 --logdir /tmp/r4_gpt2s
run int8_trained 3600 python -m dtf_tpu.bench.int8_quality \
  --preset gpt2_small --ckpt /tmp/r4_gpt2s/checkpoints
run int8_random 3600 python -m dtf_tpu.bench.int8_quality \
  --preset gpt2_small

echo "=== blitz complete; logs in $OUT; failed steps: $FAILS ==="
[ "$FAILS" -eq 0 ]
