#!/bin/bash
# Round-5 relay poller: probe the TPU relay every POLL_S seconds; the
# moment a probe succeeds, run the chip blitz (scripts/chip_blitz_r5.sh
# — the full r4 queue plus the round-5 fused-block steps) exactly once
# and exit.  A dead relay HANGS rather than raising, so the
# probe runs under timeout.  The chip is single-tenant: only this poller
# may touch the axon platform while it runs.
set -u
cd "$(dirname "$0")/.." || exit 1
# Single-instance lock: two pollers -> two concurrent blitzes on the
# single-tenant chip the moment the relay revives.
exec 9>/tmp/relay_poller.lock
flock -n 9 || { echo "another relay_poller holds the lock; exiting" >&2; exit 1; }
OUT=${1:-/tmp/r5_blitz}
POLL_S=${POLL_S:-240}
PROBE_TO=${PROBE_TO:-150}
LOG=${LOG:-/tmp/relay_poller.log}

echo "$(date -u +%FT%TZ) poller start (probe timeout ${PROBE_TO}s, interval ${POLL_S}s)" >>"$LOG"
n=0
while true; do
  n=$((n + 1))
  if timeout "$PROBE_TO" python -c "import jax; d=jax.devices(); assert d and all(x.platform != 'cpu' for x in d), f'not a TPU: {d}'; print(d)" >>"$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) probe $n SUCCEEDED - relay alive, launching blitz" >>"$LOG"
    bash scripts/chip_blitz_r5.sh "$OUT" >>"$LOG" 2>&1 &
    blitz_pid=$!
    summarize() {   # partial results land IN THE REPO so the driver's
      {             # end-of-round commit captures them even mid-blitz
        echo "# Round-5 chip blitz results ($(date -u +%FT%TZ))"
        echo "# (auto-written by scripts/relay_poller.sh via"
        echo "#  scripts/blitz_rows.py; partial until the blitz ends)"
        echo
        python scripts/blitz_rows.py "$OUT"
      } > BLITZ_R5_RESULTS.md 2>&1
    }
    while kill -0 "$blitz_pid" 2>/dev/null; do
      sleep 600
      ls "$OUT"/*.log >/dev/null 2>&1 && summarize
    done
    wait "$blitz_pid"
    rc=$?
    summarize
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u +%FT%TZ) blitz finished rc=0 (logs in $OUT)" >>"$LOG"
    else
      echo "$(date -u +%FT%TZ) blitz FAILED rc=$rc (logs in $OUT) - check per-step logs" >>"$LOG"
    fi
    exit "$rc"
  fi
  echo "$(date -u +%FT%TZ) probe $n failed" >>"$LOG"
  sleep "$POLL_S"
done
