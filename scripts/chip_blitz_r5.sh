#!/bin/bash
# Round-5 chip blitz: the full round-4 queue (unchanged, highest priority
# after two dark rounds — see scripts/chip_blitz_r4.sh) followed by the
# round-5 additions: Mosaic validation + MFU rows for the fused
# transformer-block kernels (ops/block_kernel.py).
# Usage: bash scripts/chip_blitz_r5.sh [outdir]   (default /tmp/r5_blitz)
set -u
OUT=${1:-/tmp/r5_blitz}
mkdir -p "$OUT"
cd "$(dirname "$0")/.." || exit 1

bash scripts/chip_blitz_r4.sh "$OUT"
R4_RC=$?

. "$(dirname "$0")/blitz_lib.sh"

# 7. Fused-block kernels: cheap 2-step compile probes FIRST (a Mosaic
#    rejection must cost minutes, not a 3600s window), then the MFU rows
#    with the same flags as the r4 headline rows so the comparison is
#    one-variable.
run fused_block_bert_probe 1800 python -m dtf_tpu.workloads.bert_pretrain \
  --preset base --bf16 --per_device_batch 8 --steps 2 --fused_block
run fused_block_gpt_probe 1800 python -m dtf_tpu.workloads.lm \
  --preset gpt2_small --bf16 --per_device_batch 2 --steps 2 --fused_block
# llama probe exercises RoPE/GQA/SwiGLU lowering; t5 probe exercises
# rmsnorm + the (H,T,T) rel-bias input
run fused_block_llama_probe 1800 python -m dtf_tpu.workloads.lm \
  --preset llama --bf16 --per_device_batch 2 --steps 2 --fused_block
run fused_block_t5_probe 1800 python -m dtf_tpu.workloads.seq2seq \
  --preset small --bf16 --seq_len 512 --per_device_batch 2 --steps 2 \
  --fused_block
run bert_fused_block 3600 python -m dtf_tpu.workloads.bert_pretrain \
  --preset base --bf16 --remat --remat_policy attn --layer_loop unroll \
  --per_device_batch 64 --steps 30 --fused_block
run gpt_fused_block 3600 python -m dtf_tpu.workloads.lm \
  --preset gpt2_small --bf16 --remat --remat_policy attn \
  --layer_loop unroll --loss_chunk 128 --per_device_batch 8 --steps 30 \
  --fused_block
# component-level isolation: the layer breakdown now ends with fused-
# vs-unfused block rows (bench/breakdown.py) — the kernel win free of
# workload noise.
run breakdown_fused_bert 3600 python -m dtf_tpu.bench.breakdown --family bert
run breakdown_fused_gpt 3600 python -m dtf_tpu.bench.breakdown --family gpt
# llama wiring (RoPE in-kernel + GQA separate-gate SwiGLU)
run llama_fused_block 3600 python -m dtf_tpu.workloads.lm \
  --preset llama --bf16 --remat --remat_policy attn \
  --layer_loop unroll --loss_chunk 128 --per_device_batch 8 --steps 30 \
  --fused_block
# T5 wiring (RMSNorm + learned relpos bias in-kernel; XLA-vjp backward)
run t5_fused_block 3600 python -m dtf_tpu.workloads.seq2seq \
  --preset small --bf16 --seq_len 512 --per_device_batch 16 --steps 30 \
  --fused_block
# chunked-CE fallback/ablation: the r4 t5_small row runs the dense
# (B,T,V) head with no remat (never chip-run — sized on paper); this
# row both measures loss_chunk's cost and rescues the family's first
# perf row if the dense head OOMs.
run t5_small_chunked 3600 python -m dtf_tpu.workloads.seq2seq \
  --preset small --bf16 --seq_len 512 --per_device_batch 16 --steps 30 \
  --loss_chunk 128

echo "=== r5 blitz complete; logs in $OUT; r4 rc=$R4_RC, r5 failed steps: $FAILS ==="
[ "$R4_RC" -eq 0 ] && [ "$FAILS" -eq 0 ]
