"""Chaos harness + self-healing training (resilience/, DESIGN.md §5).

Every fault class the plan can inject is exercised against the REAL code
path it targets: NaN gradients against the compiled non-finite guard,
loader errors against the data-path retry, corruption against the manifest
checksums + restore_robust fallback, SIGTERM against the preemption save,
and whole-fit crashes against the restart supervisor — culminating in the
integration test: a faulted supervised run must converge to the fault-free
run's final loss."""

import os
import signal

import jax
import numpy as np
import pytest

from dtf_tpu import optim
from dtf_tpu.cluster import Cluster
from dtf_tpu.config import ClusterConfig, TrainConfig
from dtf_tpu.data.datasets import Dataset, DataSplits
from dtf_tpu.models.mlp import MnistMLP
from dtf_tpu.resilience.chaos import (
    ChaosLoaderError, FaultPlan, corrupt_tree,
)
from dtf_tpu.resilience.supervisor import SupervisorGaveUp, run_supervised
from dtf_tpu.train.checkpoint import CheckpointManager
from dtf_tpu.train.trainer import (
    Trainer, TrainingDiverged, init_state, make_train_step, put_global_batch,
)
from dtf_tpu.utils.retry import Backoff

pytestmark = pytest.mark.chaos


def make_cluster(mesh):
    return Cluster(config=ClusterConfig(), mesh=mesh)


def tiny_splits(n=512, seed=0):
    """Small, learnable classification data (the full synthetic MNIST is
    needlessly big for fault-path tests)."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n)
    protos = rng.normal(0, 1, (10, 784)).astype(np.float32)
    x = (protos[y] + rng.normal(0, 2.0, (n, 784))).astype(np.float32)
    return DataSplits(train=Dataset(x, np.eye(10, dtype=np.float32)[y],
                                    seed=1), test=None)


class TestFaultPlanParse:
    def test_full_grammar(self):
        plan = FaultPlan.parse("nan_grad@17, corrupt_ckpt@latest,"
                               "sigterm@40,stall@25:3s,loader_error@9,"
                               "corrupt_ckpt@30,seed=7")
        kinds = [(f.kind, f.step) for f in plan.faults]
        assert kinds == [("nan_grad", 17), ("corrupt_ckpt", None),
                         ("sigterm", 40), ("stall", 25),
                         ("loader_error", 9), ("corrupt_ckpt", 30)]
        assert plan.seed == 7
        assert [f for f in plan.faults if f.kind == "stall"][0].duration_s == 3.0

    def test_bad_specs_fail_loudly(self):
        for bad in ("frobnicate@3", "nan_grad@latest", "stall@5",
                    "nan_grad", "nan_grad@@3", "host_down@3",
                    "slow_host@3:1", "sigterm@every:5", "stall@every:0:1s",
                    "host_down@every:5:1", "partition@3:1:2",
                    "sigterm@40:1",
                    # serving kinds: delay required, spike width must be
                    # positive, kv_poison is one-shot, no extra args
                    "slow_decode@5", "slow_decode@5:10ms:0",
                    "slow_decode@every:3:10ms:5", "kv_poison@every:3",
                    "client_drop@3:1", "kv_poison@3:4",
                    # fleet kinds: replica_down is one-shot (a dead
                    # replica cannot die twice), wedge needs a duration,
                    # conn_flake needs its target replica
                    "replica_down@every:4", "replica_down@3:x",
                    "replica_wedge@5", "replica_wedge@5:80ms:1:2",
                    "conn_flake@3", "conn_flake@3:1:2"):
            with pytest.raises(ValueError):
                FaultPlan.parse(bad)

    def test_serving_fault_grammar(self):
        plan = FaultPlan.parse(
            "slow_decode@30:60ms,slow_decode@10:80ms:40,"
            "client_drop@7,kv_poison@9,client_drop@every:4")
        spec = [(f.kind, f.step, f.period) for f in plan.faults]
        assert spec == [("slow_decode", 30, None),
                        ("slow_decode", 10, None),
                        ("client_drop", 7, None), ("kv_poison", 9, None),
                        ("client_drop", None, 4)]
        assert plan.faults[0].duration_s == pytest.approx(0.06)
        assert plan.faults[0].count is None          # persistent
        assert plan.faults[1].count == 40            # bounded spike

    def test_slow_decode_window_semantics(self):
        """One-shot = persistent from S (optionally :N iterations);
        periodic = one hit per firing."""
        plan = FaultPlan.parse("slow_decode@3:50ms:2", process_index=0)
        assert [plan.maybe_slow_decode(i) for i in range(7)] == \
            [0, 0, 0, 0.05, 0.05, 0, 0]
        per = FaultPlan.parse("slow_decode@every:3:20ms", process_index=0)
        assert [per.maybe_slow_decode(i) for i in range(7)] == \
            [0, 0, 0, 0.02, 0, 0, 0.02]

    def test_host_fault_grammar(self):
        plan = FaultPlan.parse(
            "host_down@30:1,slow_host@10:2:250ms,partition@12,"
            "partition@15:0")
        spec = [(f.kind, f.step, f.process) for f in plan.faults]
        assert spec == [("host_down", 30, 1), ("slow_host", 10, 2),
                        ("partition", 12, None), ("partition", 15, 0)]
        assert plan.faults[1].duration_s == pytest.approx(0.25)

    def test_repeating_fault_grammar(self):
        plan = FaultPlan.parse("stall@every:50:1s,nan_grad@every:7")
        assert plan.faults[0].period == 50
        assert plan.faults[0].duration_s == 1.0
        assert plan.faults[1].period == 7
        assert plan.faults[1].step is None

    @pytest.mark.parametrize("spec", [
        "nan_grad@17,corrupt_ckpt@latest,sigterm@40,stall@25:3s,"
        "loader_error@9,corrupt_ckpt@30",
        "host_down@30:1,slow_host@10:1:250ms,partition@12,partition@15:0",
        "stall@every:50:1s,nan_grad@every:7,loader_error@every:3",
        # the scenario-matrix kinds: recurring preemption, one-shot and
        # persistent checkpoint-write stalls
        "preempt@every:12,ckpt_stall@10:200ms,ckpt_stall@every:5:150ms",
        "preempt@8",
        # a compound plan mixing every fault family in one spec
        "preempt@every:12,ckpt_stall@10:200ms,host_down@20:1,"
        "slow_host@5:0:50ms,nan_grad@every:7,corrupt_ckpt@latest",
        # the serving kinds (ISSUE 10): persistent + bounded decode
        # slowdowns, client drops, KV corruption
        "slow_decode@30:60ms,client_drop@10,kv_poison@20",
        "slow_decode@10:80ms:40,client_drop@every:4",
        # the fleet kinds (ISSUE 16): abrupt replica death, wedges
        # (one-shot GC pause + recurring flavor), flaky links
        "replica_down@8:1,replica_wedge@5:250ms:2,conn_flake@3:0",
        "replica_down@8,replica_wedge@every:4:100ms:1,"
        "conn_flake@every:6:2",
    ])
    def test_spec_round_trips(self, spec):
        """str(parse(spec)) == spec, and re-parsing the printed form is a
        fixed point — the replayability contract for every fault kind."""
        plan = FaultPlan.parse(spec)
        assert str(plan) == spec
        assert str(FaultPlan.parse(str(plan))) == spec

    def test_repeating_fault_fires_on_every_period(self):
        sleeps = []
        plan = FaultPlan.parse("stall@every:10:0.5s", sleep=sleeps.append,
                               process_index=0)
        for step in range(31):
            plan.maybe_step_faults(step)
        assert sleeps == [0.5, 0.5, 0.5]               # steps 10, 20, 30
        assert plan.pending() == []                    # standing schedule,
                                                       # never "pending"

    def test_repeating_loader_error_fires_once_per_step(self):
        """The data path RETRIES a failed fetch at the same step; a
        periodic fault must latch per step so the retry recovers (one
        raise per period, not one per attempt)."""
        plan = FaultPlan.parse("loader_error@every:5", process_index=0)
        with pytest.raises(ChaosLoaderError):
            plan.maybe_loader_error(5)
        plan.maybe_loader_error(5)                     # retry: recovers
        plan.maybe_loader_error(5)
        with pytest.raises(ChaosLoaderError):
            plan.maybe_loader_error(10)                # next period fires

    def test_preempt_fires_sigterm_on_every_period(self):
        """preempt@every:N delivers SIGTERM at N, 2N, ... — the recurring
        spot-reclamation schedule the scenario matrix cells use (each
        firing ends in a clean checkpoint; the shared plan keeps the
        schedule across supervisor attempts)."""
        kills = []
        plan = FaultPlan.parse("preempt@every:10", process_index=0,
                               kill=lambda pid, sig: kills.append(sig))
        for step in range(31):
            plan.maybe_step_faults(step)
        assert kills == [signal.SIGTERM] * 3           # steps 10, 20, 30
        assert plan.pending() == []                    # standing schedule
        # replaying the firing step (a resumed attempt) must not refire
        plan.maybe_step_faults(30)
        assert len(kills) == 3

    def test_one_shot_preempt_fires_once(self):
        kills = []
        plan = FaultPlan.parse("preempt@4", process_index=0,
                               kill=lambda pid, sig: kills.append(sig))
        for _ in range(2):
            plan.maybe_step_faults(4)
        assert kills == [signal.SIGTERM]

    def test_sigterm_every_is_rejected_with_preempt_hint(self):
        with pytest.raises(ValueError, match="preempt@every"):
            FaultPlan.parse("sigterm@every:10")

    def test_ckpt_stall_sleeps_at_checkpoint_hook(self):
        """ckpt_stall sleeps only via maybe_ckpt_stall (the trainer's
        checkpoint window), default-ms durations, one-shot and periodic."""
        sleeps = []
        plan = FaultPlan.parse("ckpt_stall@10:200ms", process_index=0,
                               sleep=sleeps.append)
        plan.maybe_step_faults(10)                     # not a step fault
        assert sleeps == []
        plan.maybe_ckpt_stall(5)
        assert sleeps == []                            # wrong step
        plan.maybe_ckpt_stall(10)
        plan.maybe_ckpt_stall(10)                      # one-shot
        assert sleeps == [0.2]
        periodic = FaultPlan.parse("ckpt_stall@every:5:150ms",
                                   process_index=0, sleep=sleeps.append)
        for step in (5, 10, 12):
            periodic.maybe_ckpt_stall(step)
        assert sleeps == [0.2, 0.15, 0.15]             # 5 and 10 fire

    def test_ckpt_stall_needs_duration(self):
        with pytest.raises(ValueError, match="ckpt_stall"):
            FaultPlan.parse("ckpt_stall@10")

    def test_host_targeted_faults_respect_process_index(self):
        kills = []
        here = FaultPlan.parse("host_down@5:1", process_index=1,
                               kill=lambda pid, sig: kills.append(sig))
        other = FaultPlan.parse("host_down@5:1", process_index=0,
                                kill=lambda pid, sig: kills.append(sig))
        other.maybe_step_faults(5)
        assert kills == []                             # not this host
        here.maybe_step_faults(5)
        assert kills == [signal.SIGKILL]               # abrupt, no goodbye

    def test_fleet_fault_hooks_and_process_filter_exemption(self):
        """Fleet kinds are keyed on the ACCEPTOR's dispatch sequence and
        their ``:P`` names the TARGET replica, not a host to fire on —
        the acceptor owns the plan, so the host-match filter must NOT
        apply (process_index=7 here matches none of the targets)."""
        plan = FaultPlan.parse(
            "replica_down@3:1,replica_wedge@5:80ms,conn_flake@2:1",
            process_index=7)
        assert plan.maybe_replica_down(2) is None
        assert plan.maybe_conn_flake(2) == 1
        assert plan.maybe_replica_down(3) == 1
        assert plan.maybe_replica_down(3) is None      # one-shot
        replica, dur = plan.maybe_replica_wedge(5)
        assert replica == 0 and dur == pytest.approx(0.08)
        assert plan.pending() == []

    def test_periodic_fleet_faults_refire(self):
        plan = FaultPlan.parse("conn_flake@every:3:0", process_index=0)
        hits = [plan.maybe_conn_flake(s) for s in range(1, 8)]
        assert hits == [None, None, 0, None, None, 0, None]

    def test_slow_host_delay_is_persistent(self):
        sleeps = []
        plan = FaultPlan.parse("slow_host@3:0:100ms", process_index=0,
                               sleep=sleeps.append)
        for step in range(6):
            plan.maybe_step_faults(step)
        assert sleeps == [0.1, 0.1, 0.1]               # steps 3, 4, 5

    def test_partition_calls_bound_monitor(self):
        fired = []
        plan = FaultPlan.parse("partition@4", process_index=0)
        plan.bind_partition(lambda: fired.append(True))
        plan.maybe_step_faults(3)
        assert fired == []
        plan.maybe_step_faults(4)
        assert fired == [True]

    def test_each_fault_fires_once(self):
        sleeps, kills = [], []
        plan = FaultPlan.parse("stall@3:0.5s,sigterm@3",
                               sleep=sleeps.append,
                               kill=lambda pid, sig: kills.append(sig))
        for _ in range(3):
            plan.maybe_step_faults(3)
        assert sleeps == [0.5] and kills == [signal.SIGTERM]
        assert plan.pending() == []

    def test_loader_error_is_oserror(self):
        plan = FaultPlan.parse("loader_error@2")
        plan.maybe_loader_error(1)                    # wrong step: no-op
        with pytest.raises(ChaosLoaderError):
            plan.maybe_loader_error(2)
        plan.maybe_loader_error(2)                    # fired once

    def test_poison_batch(self):
        plan = FaultPlan.parse("nan_grad@5")
        x = np.ones((4, 8), np.float32)
        y = np.ones((4, 10), np.int32)
        out = plan.maybe_poison_batch(4, (x, y))      # wrong step: untouched
        assert np.isfinite(out[0]).all()
        plan2 = FaultPlan.parse("nan_grad@5")
        px, py = plan2.maybe_poison_batch(5, (x, y))
        assert np.isnan(px).all()
        assert np.array_equal(py, y)                  # int leaves untouched

    def test_poison_int_only_batch_fails_loudly(self):
        plan = FaultPlan.parse("nan_grad@0")
        with pytest.raises(ValueError, match="no float leaf"):
            plan.maybe_poison_batch(0, {"tokens": np.ones((2, 4), np.int32)})


class TestNonFiniteGuard:
    @pytest.mark.parametrize("mode", ["implicit", "explicit"])
    def test_skip_semantics(self, mesh8, mode):
        """A non-finite step must leave params/opt state bitwise untouched,
        bump the counters, and keep the step counter advancing; the next
        clean step trains normally and resets the streak."""
        model = MnistMLP(init_scale="fan_in")
        opt = optim.momentum(0.1)
        state = init_state(model, opt, seed=1, mesh=mesh8, guard=True)
        step = make_train_step(model.loss, opt, mesh8, mode=mode,
                               donate=False, guard=True)
        rng = np.random.default_rng(0)
        x = rng.random((16, 784)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[np.arange(16) % 10]
        good = put_global_batch(mesh8, (x, y))
        bad = put_global_batch(mesh8, (np.full_like(x, np.nan), y))

        s1, m1 = step(state, good, jax.random.key(0))
        assert (int(m1["nonfinite"]), int(m1["bad_streak"])) == (0, 0)
        s2, m2 = step(s1, bad, jax.random.key(1))
        assert (int(m2["nonfinite"]), int(m2["skipped_total"]),
                int(m2["bad_streak"])) == (1, 1, 1)
        assert int(s2["step"]) == 2                   # step still counts
        for a, b in zip(jax.tree_util.tree_leaves(s1["params"]),
                        jax.tree_util.tree_leaves(s2["params"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(s1["opt_state"]),
                        jax.tree_util.tree_leaves(s2["opt_state"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        s3, m3 = step(s2, bad, jax.random.key(2))
        assert int(m3["bad_streak"]) == 2             # consecutive grows
        s4, m4 = step(s3, good, jax.random.key(3))
        assert (int(m4["nonfinite"]), int(m4["bad_streak"]),
                int(m4["skipped_total"])) == (0, 0, 2)
        assert np.isfinite(float(m4["loss"]))
        # the clean step actually updated
        assert not np.array_equal(
            np.asarray(s4["params"]["l1"]["w"]),
            np.asarray(s3["params"]["l1"]["w"]))

    def test_guarded_matches_unguarded_on_clean_data(self, mesh8):
        """The guard must be a no-op on finite steps: same params."""
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        rng = np.random.default_rng(0)
        x = rng.random((16, 784)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[np.arange(16) % 10]
        batch = put_global_batch(mesh8, (x, y))
        out = {}
        for guard in (False, True):
            state = init_state(model, opt, seed=1, mesh=mesh8, guard=guard)
            step = make_train_step(model.loss, opt, mesh8, donate=False,
                                   guard=guard)
            state, _ = step(state, batch, jax.random.key(0))
            out[guard] = state["params"]
        for a, b in zip(jax.tree_util.tree_leaves(out[False]),
                        jax.tree_util.tree_leaves(out[True])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestRestoreRobust:
    def _states(self, mesh8):
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        return (init_state(model, opt, seed=1, mesh=mesh8, guard=True),
                init_state(model, opt, seed=2, mesh=mesh8, guard=True),
                init_state(model, opt, seed=3, mesh=mesh8, guard=True))

    def test_falls_back_past_corrupt_latest(self, mesh8, tmp_path):
        s10, s20, tmpl = self._states(mesh8)
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(10, s10, force=True)
        mgr.save(20, s20, force=True)
        mgr.wait()
        ok, why = mgr.verify(20)
        assert ok and why == "manifest ok"
        corrupt_tree(mgr.step_dir(20), seed=3)
        ok, why = mgr.verify(20)
        assert not ok and "mismatch" in why
        restored, step = mgr.restore_robust(tmpl)
        assert step == 10
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["l1"]["w"]),
            np.asarray(s10["params"]["l1"]["w"]))
        mgr.close()

    def test_fallback_without_manifest_via_restore_failure(self, mesh8,
                                                           tmp_path):
        """No manifest (crash before flush): the orbax-restore try/except
        is the second line of defense."""
        s10, s20, tmpl = self._states(mesh8)
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(10, s10, force=True)
        mgr.save(20, s20, force=True)
        mgr.wait()
        os.remove(os.path.join(str(tmp_path), "manifests", "20.json"))
        corrupt_tree(mgr.step_dir(20), seed=3)
        ok, why = mgr.verify(20)
        assert ok and "unverified" in why             # can't prove corruption
        restored, step = mgr.restore_robust(tmpl)
        assert step == 10                             # ...but restore catches it
        mgr.close()

    def test_all_corrupt_returns_template(self, mesh8, tmp_path):
        s10, s20, tmpl = self._states(mesh8)
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(10, s10, force=True)
        mgr.wait()
        corrupt_tree(mgr.step_dir(10), seed=0)
        restored, step = mgr.restore_robust(tmpl)
        assert step is None and restored is tmpl
        mgr.close()

    def test_intact_but_mismatched_template_raises(self, mesh8, tmp_path):
        """A checkpoint whose checksums verify is NOT corrupt: failing to
        restore it means the caller's state template changed (model /
        optimizer / guard schema) — that must raise, never silently
        cold-start past a good trajectory."""
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        saved = init_state(model, opt, seed=1, mesh=mesh8, guard=False)
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(10, saved, force=True)
        mgr.wait()
        # template with guard counters the save doesn't have
        tmpl = init_state(model, opt, seed=2, mesh=mesh8, guard=True)
        with pytest.raises(RuntimeError, match="template/schema mismatch"):
            mgr.restore_robust(tmpl)
        mgr.close()


class TestTrainerSelfHealing:
    def _cfg(self, tmp_path, **kw):
        base = dict(batch_size=64, learning_rate=0.05, epochs=2,
                    log_frequency=1, seed=1, logdir=str(tmp_path))
        base.update(kw)
        return TrainConfig(**base)

    def test_nan_step_skipped_and_counted(self, mesh8, tmp_path):
        cfg = self._cfg(tmp_path, chaos="nan_grad@3")
        t = Trainer(make_cluster(mesh8), MnistMLP(init_scale="fan_in"),
                    optim.sgd(0.05), cfg)
        r = t.fit(tiny_splits(), epochs=2)            # 16 steps
        assert r["skipped_steps"] == 1 and r["rollbacks"] == 0
        assert np.isfinite(r["final_cost"])

    def test_loader_error_retried_transparently(self, mesh8, tmp_path):
        cfg = self._cfg(tmp_path, chaos="loader_error@2")
        t = Trainer(make_cluster(mesh8), MnistMLP(init_scale="fan_in"),
                    optim.sgd(0.05), cfg)
        r = t.fit(tiny_splits(), epochs=1)
        assert r["steps"] == 8 and np.isfinite(r["final_cost"])
        assert t._chaos.pending() == []               # it really fired

    def test_consecutive_bad_steps_roll_back(self, mesh8, tmp_path):
        cfg = self._cfg(tmp_path, chaos="nan_grad@4,nan_grad@5",
                        bad_step_limit=2, max_rollbacks=1,
                        checkpoint_every=2)
        t = Trainer(make_cluster(mesh8), MnistMLP(init_scale="fan_in"),
                    optim.sgd(0.05), cfg)
        r = t.fit(tiny_splits(n=256), epochs=3)       # 12 steps
        t.ckpt.close()
        assert r["skipped_steps"] == 2
        assert r["rollbacks"] == 1
        assert np.isfinite(r["final_cost"])

    def test_resume_backfills_pre_guard_checkpoint(self, mesh8, tmp_path):
        """A checkpoint saved with --no-nonfinite_guard (or before the
        guard existed) lacks the counter leaves; resuming with the guard
        on must backfill fresh zeros, not discard the trajectory."""
        cfg0 = self._cfg(tmp_path, nonfinite_guard=False,
                         checkpoint_every=4)
        t0 = Trainer(make_cluster(mesh8), MnistMLP(init_scale="fan_in"),
                     optim.sgd(0.05), cfg0)
        r0 = t0.fit(tiny_splits(n=256), epochs=2)     # 8 steps
        t0.ckpt.close()
        assert "skipped" not in t0.state

        cfg1 = self._cfg(tmp_path, checkpoint_every=4, resume=True)
        t1 = Trainer(make_cluster(mesh8), MnistMLP(init_scale="fan_in"),
                     optim.sgd(0.05), cfg1)
        assert int(t1.state["step"]) == r0["steps"]   # resumed
        assert int(t1.state["skipped"]) == 0          # backfilled zeros
        r1 = t1.fit(tiny_splits(n=256), epochs=3)     # one more epoch
        t1.ckpt.close()
        assert r1["steps"] == 12 and np.isfinite(r1["final_cost"])

    def test_persistent_nans_fail_fast_without_checkpoint(self, mesh8,
                                                          tmp_path):
        cfg = self._cfg(tmp_path, chaos="nan_grad@2,nan_grad@3",
                        bad_step_limit=2)             # no checkpointing
        t = Trainer(make_cluster(mesh8), MnistMLP(init_scale="fan_in"),
                    optim.sgd(0.05), cfg)
        with pytest.raises(TrainingDiverged, match="consecutive non-finite"):
            t.fit(tiny_splits(n=256), epochs=2)


class TestSupervisor:
    def test_restarts_after_crashes_then_completes(self):
        sleeps, calls = [], []

        def fit_once(attempt):
            calls.append(attempt)
            if attempt < 2:
                raise RuntimeError(f"boom {attempt}")
            return {"preempted": False, "steps": 7}

        out = run_supervised(fit_once, max_restarts=3,
                             backoff=Backoff(base_s=0.1, max_s=1.0,
                                             jitter=0.0),
                             sleep=sleeps.append)
        assert out["steps"] == 7 and calls == [0, 1, 2]
        assert sleeps == [0.1, 0.2]

    def test_preemption_consumes_a_restart(self):
        results = [{"preempted": True}, {"preempted": False, "steps": 3}]
        out = run_supervised(lambda a: results[a], max_restarts=1,
                             sleep=lambda s: None)
        assert out["steps"] == 3

    def test_gives_up_loudly(self):
        def fit_once(attempt):
            raise RuntimeError("persistent")

        with pytest.raises(SupervisorGaveUp, match="2 restart") as ei:
            run_supervised(fit_once, max_restarts=2, sleep=lambda s: None)
        assert isinstance(ei.value.__cause__, RuntimeError)
        assert len(ei.value.history) == 3              # initial + 2 restarts

    def test_keyboard_interrupt_is_never_swallowed(self):
        def fit_once(attempt):
            raise KeyboardInterrupt

        with pytest.raises(KeyboardInterrupt):
            run_supervised(fit_once, max_restarts=5, sleep=lambda s: None)

    def test_no_restart_errors_are_terminal(self):
        """Deterministic failures (checkpoint schema mismatch) replay
        identically — the supervisor must not burn restarts on them."""
        from dtf_tpu.train.checkpoint import CheckpointMismatchError
        calls = []

        def fit_once(attempt):
            calls.append(attempt)
            raise CheckpointMismatchError("template mismatch")

        with pytest.raises(CheckpointMismatchError):
            run_supervised(fit_once, max_restarts=5, sleep=lambda s: None)
        assert calls == [0]                            # no retries


class TestClusterInitRetry:
    def test_retries_slow_coordinator(self, monkeypatch):
        import dtf_tpu.cluster as cluster_mod
        calls = {"n": 0}

        def fake_init(**kw):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("coordination service not ready")

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setattr(cluster_mod, "_INITIALIZED", False)
        monkeypatch.setattr("dtf_tpu.utils.retry.time.sleep", lambda s: None)
        cluster = cluster_mod.bootstrap(ClusterConfig(
            num_processes=2, coordinator_address="127.0.0.1:9"))
        assert calls["n"] == 3 and cluster.mesh.size == 8

    def test_config_error_stays_terminal(self, monkeypatch):
        import dtf_tpu.cluster as cluster_mod

        def fake_init(**kw):
            raise ValueError("num_processes mismatch")

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setattr(cluster_mod, "_INITIALIZED", False)
        with pytest.raises(ValueError, match="mismatch"):
            cluster_mod.bootstrap(ClusterConfig(
                num_processes=2, coordinator_address="127.0.0.1:9"))


class TestChaosIntegration:
    def test_self_healing_run_matches_fault_free(self, mesh8, tmp_path):
        """THE acceptance scenario: nan_grad + sigterm + corrupt-latest-
        checkpoint, driven by the supervisor.  The run must self-heal —
        skipped step counted, restore falls back past the corrupt step,
        supervisor resumes after the kill — and land at the fault-free
        run's final loss within tolerance (trajectories differ only by
        the one skipped update)."""
        cluster = make_cluster(mesh8)

        def run(logdir, plan):
            cfg0 = TrainConfig(batch_size=64, learning_rate=0.05, epochs=2,
                               log_frequency=4, seed=1, logdir=logdir,
                               checkpoint_every=6)

            def fit_once(attempt):
                import dataclasses
                cfg = dataclasses.replace(cfg0, resume=attempt > 0)
                t = Trainer(cluster, MnistMLP(init_scale="fan_in"),
                            optim.sgd(0.05), cfg, chaos=plan)
                try:
                    return t.fit(tiny_splits(n=1024), epochs=2)  # 32 steps
                finally:
                    if t.ckpt is not None:
                        t.ckpt.close()

            return run_supervised(fit_once, max_restarts=2,
                                  backoff=Backoff(base_s=0.0, jitter=0.0),
                                  sleep=lambda s: None)

        plan = FaultPlan.parse("nan_grad@9,sigterm@20,corrupt_ckpt@latest")
        faulted = run(str(tmp_path / "faulted"), plan)
        baseline = run(str(tmp_path / "baseline"), None)

        assert plan.pending() == []                   # every fault fired
        assert baseline["preempted"] is False
        assert faulted["preempted"] is False          # healed, not killed
        assert faulted["steps"] == baseline["steps"] == 32
        assert faulted["skipped_steps"] == 1          # the nan_grad step
        assert baseline["skipped_steps"] == 0
        assert np.isfinite(faulted["final_cost"])
        # Same data/rng stream, one update skipped: final loss must agree
        # to a loose tolerance.
        assert faulted["final_cost"] == pytest.approx(
            baseline["final_cost"], rel=0.25, abs=0.15)
