"""Fused transformer-block kernels (ops/block_kernel.py): forward and
gradient parity with the models' XLA block paths, remat composition, and
the scope guards.  The kernels run in interpreter mode on CPU; real-Mosaic
legality is a chip-blitz step (scripts/chip_blitz_r5.sh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.ops.block_kernel import (MAX_FUSED_T, fused_attn_block,
                                      fused_mlp_block)


def _tree_close(a, b, atol, rtol):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b), strict=True):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=atol, rtol=rtol)


class TestAttnBlockParity:
    def _bert_layer(self, **kw):
        from dtf_tpu.models.bert import BertConfig, BertEncoderLayer
        cfg = BertConfig.tiny(num_heads=4, dim=32, mlp_dim=64,
                              use_flash=False, **kw)
        layer = BertEncoderLayer(cfg)
        return layer, layer.init(jax.random.key(0))

    @pytest.mark.slow
    def test_postnorm_fwd_and_grads_match_xla(self):
        layer, params = self._bert_layer()
        x = jax.random.normal(jax.random.key(1), (2, 16, 32), jnp.float32)

        def fused(p, x):
            x1 = fused_attn_block(x, p["attn"], p["ln1"], num_heads=4)
            return fused_mlp_block(x1, p["fc1"], p["fc2"], p["ln2"])

        ref, _ = layer.apply(params, x)
        np.testing.assert_allclose(np.asarray(fused(params, x)),
                                   np.asarray(ref), atol=2e-5, rtol=1e-5)
        g_ref = jax.grad(lambda p, x: jnp.sum(
            jnp.sin(layer.apply(p, x)[0])), argnums=(0, 1))(params, x)
        g_fused = jax.grad(lambda p, x: jnp.sum(
            jnp.sin(fused(p, x))), argnums=(0, 1))(params, x)
        _tree_close(g_ref, g_fused, 5e-4, 5e-4)

    def test_padding_mask_fwd_fast(self):
        """Fast-tier kv_mask coverage: forward parity only (the full
        fwd+grad mask test is slow-tier) — guards the has_rope/has_mask
        ref-ordering in the kernel."""
        layer, params = self._bert_layer()
        x = jax.random.normal(jax.random.key(2), (2, 16, 32), jnp.float32)
        kv = jnp.asarray(
            np.random.default_rng(0).random((2, 16)) > 0.4).at[:, 0].set(
                True)
        ref, _ = layer.apply(params, x, mask=kv[:, None, None, :])
        out = fused_attn_block(x, params["attn"], params["ln1"],
                               num_heads=4, kv_mask=kv)
        y = fused_mlp_block(out, params["fc1"], params["fc2"],
                            params["ln2"])
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=2e-5, rtol=1e-5)

    @pytest.mark.slow
    def test_padding_mask_matches_xla(self):
        layer, params = self._bert_layer()
        x = jax.random.normal(jax.random.key(2), (2, 16, 32), jnp.float32)
        kv = jnp.asarray(
            np.random.default_rng(0).random((2, 16)) > 0.4).at[:, 0].set(
                True)
        ref, _ = layer.apply(params, x, mask=kv[:, None, None, :])

        def fused(p, x):
            x1 = fused_attn_block(x, p["attn"], p["ln1"], num_heads=4,
                                  kv_mask=kv)
            return fused_mlp_block(x1, p["fc1"], p["fc2"], p["ln2"])

        np.testing.assert_allclose(np.asarray(fused(params, x)),
                                   np.asarray(ref), atol=2e-5, rtol=1e-5)
        g_ref = jax.grad(lambda p: jnp.sum(jnp.sin(
            layer.apply(p, x, mask=kv[:, None, None, :])[0])))(params)
        g_fused = jax.grad(lambda p: jnp.sum(jnp.sin(fused(p, x))))(params)
        _tree_close(g_ref, g_fused, 5e-4, 5e-4)

    def test_prenorm_causal_fwd_fast(self):
        """Fast-tier pre-LN/causal coverage: forward parity only (the
        fwd+grad version is slow-tier)."""
        from dtf_tpu.models.gpt import GPTBlock, GPTConfig
        cfg = GPTConfig.tiny(use_flash=False)
        blk = GPTBlock(cfg)
        params = blk.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(3), (2, 16, 32), jnp.float32)
        x1 = fused_attn_block(x, params["attn"], params["ln1"],
                              num_heads=cfg.num_heads, causal=True,
                              prenorm=True)
        y = fused_mlp_block(x1, params["fc1"], params["fc2"],
                            params["ln2"], prenorm=True)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(blk.apply(params, x)),
                                   atol=2e-5, rtol=1e-5)

    @pytest.mark.slow
    def test_prenorm_causal_matches_gpt_block(self):
        from dtf_tpu.models.gpt import GPTBlock, GPTConfig
        cfg = GPTConfig.tiny(use_flash=False)
        blk = GPTBlock(cfg)
        params = blk.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(3), (2, 16, 32), jnp.float32)

        def fused(p, x):
            x1 = fused_attn_block(x, p["attn"], p["ln1"],
                                  num_heads=cfg.num_heads, causal=True,
                                  prenorm=True)
            return fused_mlp_block(x1, p["fc1"], p["fc2"], p["ln2"],
                                   prenorm=True)

        np.testing.assert_allclose(np.asarray(fused(params, x)),
                                   np.asarray(blk.apply(params, x)),
                                   atol=2e-5, rtol=1e-5)
        g_ref = jax.grad(lambda p: jnp.sum(
            jnp.sin(blk.apply(p, x))))(params)
        g_fused = jax.grad(lambda p: jnp.sum(jnp.sin(fused(p, x))))(params)
        _tree_close(g_ref, g_fused, 5e-4, 5e-4)

    @pytest.mark.slow
    def test_llama_style_matches_gpt_block(self):
        """RoPE + GQA + SwiGLU (the llama preset's block wiring) through
        the fused kernels: fwd and grads match the XLA block."""
        from dtf_tpu.models.gpt import GPTBlock, GPTConfig
        cfg = GPTConfig.tiny(use_flash=False, rope=True, num_kv_heads=2,
                             mlp_act="swiglu")
        blk = GPTBlock(cfg)
        params = blk.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(9), (2, 16, 32), jnp.float32)

        def fused(p, x):
            x1 = fused_attn_block(x, p["attn"], p["ln1"], num_heads=4,
                                  num_kv_heads=2, causal=True,
                                  prenorm=True, rope=True)
            return fused_mlp_block(x1, p["fc1"], p["fc2"], p["ln2"],
                                   fc_gate_params=p["fc_gate"],
                                   prenorm=True)

        np.testing.assert_allclose(np.asarray(fused(params, x)),
                                   np.asarray(blk.apply(params, x)),
                                   atol=3e-5, rtol=1e-4)
        g_ref = jax.grad(lambda p: jnp.sum(
            jnp.sin(blk.apply(p, x))))(params)
        g_fused = jax.grad(lambda p: jnp.sum(jnp.sin(fused(p, x))))(params)
        _tree_close(g_ref, g_fused, 1e-3, 1e-3)

    @pytest.mark.slow
    @pytest.mark.parametrize("rope", [False, True])
    def test_multi_q_block_causal_matches_gpt_block(self, rope):
        """T > 256 engages the causal q-block loop (keys clamped to
        [0, q_end) per block); tokens and grads must still match the
        XLA block exactly.  rope=True additionally covers the per-block
        cos/sin table slices at q0 > 0."""
        from dtf_tpu.models.gpt import GPTBlock, GPTConfig
        cfg = GPTConfig.tiny(use_flash=False, max_len=512, rope=rope)
        blk = GPTBlock(cfg)
        params = blk.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(6), (1, 512, 32), jnp.float32)

        def fused(p, x):
            x1 = fused_attn_block(x, p["attn"], p["ln1"], num_heads=4,
                                  causal=True, prenorm=True, rope=rope)
            return fused_mlp_block(x1, p["fc1"], p["fc2"], p["ln2"],
                                   prenorm=True)

        np.testing.assert_allclose(np.asarray(fused(params, x)),
                                   np.asarray(blk.apply(params, x)),
                                   atol=5e-5, rtol=1e-4)
        g_ref = jax.grad(lambda p: jnp.sum(
            jnp.sin(blk.apply(p, x))))(params)
        g_fused = jax.grad(lambda p: jnp.sum(jnp.sin(fused(p, x))))(params)
        _tree_close(g_ref, g_fused, 1e-3, 1e-3)

    @pytest.mark.slow
    def test_causal_kv_mask_multi_block_matches_xla(self):
        """causal + kv_mask composed, at a T that engages the q-block
        loop — covers the bias[:k_end] truncation against an XLA
        reference built from the same modules."""
        from dtf_tpu.nn.attention import MultiHeadAttention, causal_mask
        from dtf_tpu.nn.layers import LayerNorm

        d, h, t = 32, 4, 512
        mha = MultiHeadAttention(d, h)
        ln = LayerNorm(d)
        k1, k2 = jax.random.split(jax.random.key(7))
        ap, lp = mha.init(k1), ln.init(k2)
        x = jax.random.normal(jax.random.key(8), (2, t, d), jnp.float32)
        kv = jnp.asarray(
            np.random.default_rng(1).random((2, t)) > 0.3).at[:, 0].set(
                True)
        mask = kv[:, None, None, :] & causal_mask(t)
        ref = ln.apply(lp, x + mha.apply(ap, x, mask=mask))
        out = fused_attn_block(x, ap, lp, num_heads=h, causal=True,
                               kv_mask=kv)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-5, rtol=1e-4)
        g_ref = jax.grad(lambda p: jnp.sum(jnp.sin(
            ln.apply(lp, x + mha.apply(p, x, mask=mask)))))(ap)
        g_fused = jax.grad(lambda p: jnp.sum(jnp.sin(
            fused_attn_block(x, p, lp, num_heads=h, causal=True,
                             kv_mask=kv))))(ap)
        _tree_close(g_ref, g_fused, 1e-3, 1e-3)

    @pytest.mark.slow
    def test_bf16_fwd_tracks_xla(self):
        layer, params = self._bert_layer(dtype=jnp.bfloat16)
        params = jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
        x = jax.random.normal(jax.random.key(4), (2, 16, 32), jnp.bfloat16)
        ref, _ = layer.apply(params, x)
        x1 = fused_attn_block(x, params["attn"], params["ln1"], num_heads=4)
        y = fused_mlp_block(x1, params["fc1"], params["fc2"], params["ln2"])
        assert y.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(y, np.float32),
                                   np.asarray(ref, np.float32),
                                   atol=5e-2, rtol=5e-2)

    @pytest.mark.slow
    def test_bf16_grads_track_xla(self):
        """bf16 grads: fused vs XLA block, relative L2 per leaf < 5%
        (bf16 rounding differs op-by-op; directional agreement is the
        contract)."""
        layer, params = self._bert_layer(dtype=jnp.bfloat16)
        params = jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
        x = jax.random.normal(jax.random.key(5), (2, 16, 32), jnp.bfloat16)

        def fused(p):
            x1 = fused_attn_block(x, p["attn"], p["ln1"], num_heads=4)
            return jnp.sum(jnp.sin(fused_mlp_block(
                x1, p["fc1"], p["fc2"], p["ln2"]).astype(jnp.float32)))

        g_ref = jax.grad(lambda p: jnp.sum(jnp.sin(
            layer.apply(p, x)[0].astype(jnp.float32))))(params)
        g_fused = jax.grad(fused)(params)
        ref_leaves = [np.asarray(a, np.float32).ravel()
                      for a in jax.tree.leaves(g_ref)]
        gmax = max(np.linalg.norm(a) for a in ref_leaves)
        for a, b in zip(ref_leaves, jax.tree.leaves(g_fused),
                        strict=True):
            b = np.asarray(b, np.float32).ravel()
            # scale-aware: leaves whose gradient is tiny relative to the
            # block's largest leaf are bf16-noise-dominated by both
            # paths; hold them to the global scale instead.
            denom = max(np.linalg.norm(a), 0.05 * gmax)
            assert np.linalg.norm(a - b) / denom < 0.05, (
                np.linalg.norm(a - b), denom, gmax)


class TestGuards:
    def test_bad_kv_heads_rejected(self):
        x = jnp.zeros((1, 16, 32))
        with pytest.raises(ValueError, match="divide"):
            fused_attn_block(x, {}, {}, num_heads=4, num_kv_heads=3)

    def test_bad_t_rejected(self):
        with pytest.raises(ValueError, match="T % 8"):
            fused_attn_block(jnp.zeros((1, 12, 32)), {}, {}, num_heads=4)
        with pytest.raises(ValueError, match=str(MAX_FUSED_T)):
            fused_attn_block(jnp.zeros((1, MAX_FUSED_T + 8, 32)), {}, {},
                             num_heads=4)

    def test_vmem_estimate_guard(self):
        """Dimensions whose working set exceeds the scoped-VMEM budget
        fail fast with an actionable error, not an opaque Mosaic
        allocation failure.  The guard reads only shapes/dtypes, so
        ShapeDtypeStructs suffice — no gigabyte zeros on the test rig."""
        x = jax.ShapeDtypeStruct((1, 1024, 8192), jnp.float32)
        with pytest.raises(ValueError, match="VMEM"):
            fused_attn_block(x, {}, {}, num_heads=64)
        w1 = jax.ShapeDtypeStruct((8192, 32768), jnp.float32)
        with pytest.raises(ValueError, match="VMEM"):
            fused_mlp_block(x, {"w": w1, "b": None}, {}, {})

    def test_odd_head_dim_rope_rejected(self):
        with pytest.raises(ValueError, match="even head dim"):
            fused_attn_block(jnp.zeros((1, 16, 36)), {}, {}, num_heads=4,
                             rope=True)

    def test_moe_and_attn_impl_rejected_at_model(self):
        from dtf_tpu.models.bert import BertConfig, BertMLM
        with pytest.raises(ValueError, match="dense"):
            BertMLM(BertConfig.tiny(fused_block=True, moe_experts=2))
        with pytest.raises(ValueError, match="attn_impl"):
            BertMLM(BertConfig.tiny(fused_block=True,
                                    attn_impl=lambda q, k, v, m: q))


class TestInt8Fused:
    """--matmul_dtype int8 composing with --fused_block: the fused
    kernels quantize the projection operands with nn/lowp.py's exact
    format (per-output-channel weight scales quantized OUTSIDE the
    pallas_call, per-token activation scales in-kernel, int8 x int8 ->
    i32), so fused-int8 must track unfused-int8 — the quantization is
    identical in both paths and integer accumulation is exact, leaving
    only fp reduction-order noise in the attention core."""

    @pytest.mark.parametrize("extra", [
        {},
        {"rope": True, "num_kv_heads": 2, "mlp_act": "swiglu"},
    ])
    def test_int8_loss_and_grads_match_unfused(self, extra):
        from dtf_tpu.models.gpt import GPT, GPTConfig
        m0 = GPT(GPTConfig.tiny(use_flash=False, matmul_dtype="int8",
                                **extra))
        m1 = GPT(GPTConfig.tiny(use_flash=False, matmul_dtype="int8",
                                fused_block=True, **extra))
        p = m0.init(jax.random.key(1))
        toks = jnp.asarray(
            np.random.default_rng(1).integers(0, 128, (4, 32)), jnp.int32)
        l0, g0 = jax.value_and_grad(lambda p: m0.loss(p, toks)[0])(p)
        l1, g1 = jax.value_and_grad(lambda p: m1.loss(p, toks)[0])(p)
        # forward: both paths quantize identically, int8 sums are exact
        assert abs(float(l0) - float(l1)) < 3e-5, (float(l0), float(l1))
        # backward: both are straight-through estimators, but the fused
        # path recomputes attention from f32-weight q/k/v while the
        # unfused STE saw the quantized activations — looser tolerance
        _tree_close(g0, g1, 1e-2, 1e-2)

    def test_int8_halfblocks_match_lowp_matmul(self):
        """The attn/mlp half-block wrappers with matmul_dtype='int8'
        reproduce a hand-built lowp reference: quantizing the packed
        (D, W) qkv matrix per column == quantizing q/k/v separately."""
        from dtf_tpu.models.gpt import GPT, GPTConfig
        m0 = GPT(GPTConfig.tiny(use_flash=False, matmul_dtype="int8"))
        p = m0.init(jax.random.key(2))
        lp = jax.tree.map(lambda a: a[0], p["layers"])
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((2, 16, 32)),
            jnp.float32)
        y_ref = m0.block.apply(lp, x)            # unfused int8 block
        x1 = fused_attn_block(x, lp["attn"], lp["ln1"], num_heads=4,
                              causal=True, prenorm=True,
                              matmul_dtype="int8")
        y = fused_mlp_block(x1, lp["fc1"], lp["fc2"], lp["ln2"],
                            prenorm=True, matmul_dtype="int8")
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-4, rtol=1e-4)

    def test_fused_rejects_bf16_fp8_still(self):
        from dtf_tpu.models.gpt import GPT, GPTConfig
        for md in ("bf16", "fp8"):
            with pytest.raises(ValueError, match="fused"):
                GPT(GPTConfig.tiny(fused_block=True, matmul_dtype=md))
        with pytest.raises(ValueError, match="int8"):
            fused_mlp_block(jnp.zeros((1, 8, 32)),
                            {"w": jnp.zeros((32, 64)),
                             "b": jnp.zeros((64,))},
                            {"w": jnp.zeros((64, 32)),
                             "b": jnp.zeros((32,))},
                            {"scale": jnp.ones((32,)),
                             "bias": jnp.zeros((32,))},
                            matmul_dtype="fp8")


@pytest.mark.slow
class TestModelIntegration:
    """fused_block=True must reproduce the unfused model's loss and grads
    (fp32) under every layer-loop/remat combination the trainer uses."""

    @pytest.mark.parametrize("extra", [
        {}, {"remat": True, "remat_policy": "attn"},
        {"remat": True, "remat_policy": "full"},
        {"layer_loop": "unroll"},
    ])
    def test_bert_loss_and_grads(self, extra):
        from dtf_tpu.models.bert import BertConfig, BertMLM
        m0 = BertMLM(BertConfig.tiny(use_flash=False, **extra))
        m1 = BertMLM(BertConfig.tiny(use_flash=False, fused_block=True,
                                     **extra))
        p = m0.init(jax.random.key(0))
        toks = jnp.asarray(
            np.random.default_rng(0).integers(4, 128, (4, 32)), jnp.int32)
        rng = jax.random.key(5)
        l0, g0 = jax.value_and_grad(
            lambda p: m0.loss(p, toks, rng=rng)[0])(p)
        l1, g1 = jax.value_and_grad(
            lambda p: m1.loss(p, toks, rng=rng)[0])(p)
        assert abs(float(l0) - float(l1)) < 2e-5
        _tree_close(g0, g1, 1e-3, 1e-3)

    @pytest.mark.parametrize("extra", [
        {},
        {"rope": True, "num_kv_heads": 2, "mlp_act": "swiglu"},
    ])
    def test_gpt_loss_and_grads(self, extra):
        from dtf_tpu.models.gpt import GPT, GPTConfig
        m0 = GPT(GPTConfig.tiny(use_flash=False, **extra))
        m1 = GPT(GPTConfig.tiny(use_flash=False, fused_block=True,
                                **extra))
        p = m0.init(jax.random.key(1))
        toks = jnp.asarray(
            np.random.default_rng(1).integers(0, 128, (4, 32)), jnp.int32)
        l0, g0 = jax.value_and_grad(lambda p: m0.loss(p, toks)[0])(p)
        l1, g1 = jax.value_and_grad(lambda p: m1.loss(p, toks)[0])(p)
        assert abs(float(l0) - float(l1)) < 3e-5
        _tree_close(g0, g1, 1e-3, 1e-3)

    @pytest.mark.parametrize("extra", [
        {},                             # rmsnorm + relative positions
        {"norm": "layernorm"},
        {"positions": "absolute"},      # no relpos bias -> flash bwd path
    ])
    def test_t5_loss_and_grads(self, extra):
        """T5 fused blocks (encoder self-attn+FFN, decoder self-attn+
        cross-attn+FFN — the ONLY CPU parity coverage for the cross
        kernel incl. its ctx_mask padding path): loss+grads match,
        INCLUDING the learned relpos table's cotangent through the
        in-kernel bias."""
        from dtf_tpu.models.t5 import T5, T5Config
        m0 = T5(T5Config.tiny(**extra))
        m1 = T5(T5Config.tiny(fused_block=True, **extra))
        p = m0.init(jax.random.key(0))
        r = np.random.default_rng(0)
        src = np.asarray(r.integers(2, 64, (4, 16)), np.int32)
        src[:, 12:] = 0                  # real padding -> pad_mask path
        batch = {"src": jnp.asarray(src),
                 "tgt": jnp.asarray(src[:, ::-1].copy())}
        l0, g0 = jax.value_and_grad(lambda p: m0.loss(p, batch)[0])(p)
        l1, g1 = jax.value_and_grad(lambda p: m1.loss(p, batch)[0])(p)
        assert abs(float(l0) - float(l1)) < 3e-5
        _tree_close(g0, g1, 1e-3, 1e-3)
        if "relpos_enc" in g1:
            assert float(jnp.abs(g1["relpos_enc"]["table"]).sum()) > 0

    @pytest.mark.parametrize("family", ["llama", "t5"])
    def test_bf16_families_track_unfused(self, family):
        """bf16 llama/T5 fused paths (the dtypes the blitz rows run):
        loss finite and within bf16 noise of the unfused model."""
        if family == "llama":
            from dtf_tpu.models.gpt import GPT, GPTConfig
            kw = dict(rope=True, num_kv_heads=2, mlp_act="swiglu",
                      dtype=jnp.bfloat16, use_flash=False)
            m0, m1 = GPT(GPTConfig.tiny(**kw)), GPT(
                GPTConfig.tiny(fused_block=True, **kw))
            p = m0.init(jax.random.key(0))
            batch = jnp.asarray(np.random.default_rng(0).integers(
                0, 128, (2, 32)), jnp.int32)
        else:
            from dtf_tpu.models.t5 import T5, T5Config
            kw = dict(dtype=jnp.bfloat16)
            m0, m1 = T5(T5Config.tiny(**kw)), T5(
                T5Config.tiny(fused_block=True, **kw))
            p = m0.init(jax.random.key(0))
            toks = jnp.asarray(np.random.default_rng(0).integers(
                2, 64, (2, 16)), jnp.int32)
            batch = {"src": toks, "tgt": toks[:, ::-1].copy()}
        l0, g0 = jax.value_and_grad(lambda p: m0.loss(p, batch)[0])(p)
        l1, g1 = jax.value_and_grad(lambda p: m1.loss(p, batch)[0])(p)
        assert np.isfinite(float(l1))
        assert abs(float(l0) - float(l1)) < 0.05, (float(l0), float(l1))
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1),
                        strict=True):
            assert np.isfinite(np.asarray(a, np.float32)).all()
            assert np.isfinite(np.asarray(b, np.float32)).all()

    def test_pipeline_parallel_composes(self):
        """fused_block inside GPipe pipeline stages (shard_map) must
        reproduce the unfused pipelined loss exactly."""
        from dtf_tpu import optim
        from dtf_tpu.models.bert import BertConfig, BertMLM
        from dtf_tpu.parallel import sharding as sh
        from dtf_tpu.parallel.mesh import make_mesh
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)
        mesh = make_mesh("data=4,pipe=2", devices=jax.devices()[:8])
        losses = {}
        for fused in (False, True):
            cfg = BertConfig.tiny(num_layers=2, pipeline_mesh=mesh,
                                  pipeline_microbatches=2,
                                  use_flash=False, fused_block=fused)
            model = BertMLM(cfg)
            opt = optim.adam(1e-3)
            state = init_state(model, opt, seed=0, mesh=mesh,
                               param_shardings=sh.apply_rules(
                                   model.axes(), mesh))
            step = make_train_step(model.loss, opt, mesh)
            toks = np.asarray(np.random.default_rng(1).integers(
                4, 128, (16, 32)), dtype=np.int32)
            _, metrics = step(state, put_global_batch(mesh, toks),
                              jax.random.key(1))
            losses[fused] = float(metrics["loss"])
        assert abs(losses[True] - losses[False]) < 1e-4, losses

    def test_train_step_under_mesh(self, mesh_2d):
        """One full DP/TP-sharded train step with fused blocks: finite
        loss, same value as the unfused step (GSPMD handles layout)."""
        from dtf_tpu import optim
        from dtf_tpu.models.bert import BertConfig, BertMLM
        from dtf_tpu.parallel import sharding as sh
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)
        losses = {}
        for fused in (False, True):
            model = BertMLM(BertConfig.tiny(use_flash=False,
                                            fused_block=fused))
            opt = optim.adam(1e-3)
            state = init_state(model, opt, seed=0, mesh=mesh_2d,
                               param_shardings=sh.apply_rules(
                                   model.axes(), mesh_2d))
            step = make_train_step(model.loss, opt, mesh_2d)
            toks = np.asarray(np.random.default_rng(2).integers(
                4, 128, (8, 32)), dtype=np.int32)
            _, metrics = step(state, put_global_batch(mesh_2d, toks),
                              jax.random.key(2))
            losses[fused] = float(metrics["loss"])
        assert np.isfinite(losses[True])
        assert abs(losses[True] - losses[False]) < 2e-5, losses
