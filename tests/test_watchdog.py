"""Fail-fast hang watchdog (SURVEY.md §5.3: the reference hung forever on a
dead peer; here no-progress is detected and the process dies loudly)."""

import time

import pytest

from dtf_tpu.utils.watchdog import HangWatchdog


def test_fires_on_no_progress():
    fired = []
    wd = HangWatchdog(0.2, what="test loop",
                      on_hang=lambda what, t: fired.append((what, t)),
                      poll_s=0.05)
    try:
        time.sleep(0.6)
        assert wd.fired
        assert fired == [("test loop", 0.2)]
    finally:
        wd.close()


def test_stays_quiet_while_ticking():
    fired = []
    with HangWatchdog(0.3, on_hang=lambda *a: fired.append(a),
                      poll_s=0.05) as wd:
        for _ in range(10):
            time.sleep(0.06)
            wd.tick()
        assert not wd.fired and fired == []


def test_close_disarms():
    fired = []
    wd = HangWatchdog(0.2, on_hang=lambda *a: fired.append(a), poll_s=0.05)
    wd.close()
    time.sleep(0.4)
    assert fired == []


def test_rejects_nonpositive_timeout():
    with pytest.raises(ValueError, match="timeout_s"):
        HangWatchdog(0.0)


def test_trainer_integration_ticks(tmp_path):
    """A short MNIST run with the watchdog armed completes without firing
    (ticks flow from the step loop), and the watchdog is disarmed at the
    end of fit()."""
    from dtf_tpu.cluster import Cluster
    from dtf_tpu.config import ClusterConfig, TrainConfig
    from dtf_tpu.data import load_mnist
    from dtf_tpu.models.mlp import MnistMLP
    from dtf_tpu.optim import sgd
    from dtf_tpu.parallel.mesh import make_mesh
    from dtf_tpu.train.trainer import Trainer

    cluster = Cluster(config=ClusterConfig(), mesh=make_mesh("data=8"))
    cfg = TrainConfig(batch_size=64, epochs=1, log_frequency=50,
                      logdir=str(tmp_path), hang_timeout_s=120.0)
    trainer = Trainer(cluster, MnistMLP(init_scale="fan_in"),
                      sgd(cfg.learning_rate), cfg)
    # Not armed until fit(): slow pre-fit host work must not trip it.
    assert trainer._watchdog is None
    trainer.fit(load_mnist(seed=1))
    assert trainer._watchdog is not None and not trainer._watchdog.fired
    # disarmed: the monitor thread has exited
    assert not trainer._watchdog._thread.is_alive()


def test_suspend_excludes_slow_host_calls():
    """A blocking call longer than the timeout doesn't fire while wrapped
    in suspend(), and the deadline restarts fresh afterwards."""
    fired = []
    with HangWatchdog(0.2, on_hang=lambda *a: fired.append(a),
                      poll_s=0.05) as wd:
        with wd.suspend():
            time.sleep(0.5)          # e.g. full-test-set eval
        assert not wd.fired
        time.sleep(0.1)              # under timeout again: still quiet
        assert fired == []


def test_dump_all_stacks_is_diagnosable(tmp_path):
    """A tripped watchdog must leave every thread's stack behind (the
    post-mortem that says WHERE the main thread wedged), and the dump
    helper must never raise — it runs on the kill path."""
    import threading

    from dtf_tpu.utils.watchdog import dump_all_stacks

    release = threading.Event()
    t = threading.Thread(target=release.wait, name="wedged-worker",
                         daemon=True)
    t.start()
    try:
        path = tmp_path / "stacks.txt"
        with open(path, "w") as f:
            dump_all_stacks(file=f)
        out = path.read_text()
        # faulthandler prints one "Thread 0x..." block per thread with
        # File/line frames; both this thread and the worker must appear.
        assert out.count("Thread 0x") + out.count("Current thread") >= 2
        assert "test_watchdog.py" in out
    finally:
        release.set()
        t.join(timeout=5)


def test_dump_all_stacks_swallows_bad_file():
    from dtf_tpu.utils.watchdog import dump_all_stacks
    dump_all_stacks(file=object())     # no fd: must not raise
