"""Scenario matrix (dtf_tpu/scenarios, DESIGN.md §8): spec grammar,
curated matrices, zoo builders, gate wiring, CLI — plus a slow
end-to-end supervised cell through the real child-process runner.

The fast tests are deliberately jax-free (spec/runner/CLI import no
backend); the zoo tests build models but never train; only the
``slow``-marked end-to-end tests spawn cells.
"""

import json
import os
import subprocess
import sys

import pytest

from dtf_tpu.scenarios.spec import (Gate, MATRICES, ScenarioSpec,
                                    TRAIN_WORKLOADS, WORKLOADS,
                                    default_matrix, load_matrix, mini_matrix)

pytestmark = pytest.mark.scenarios


def tiny_spec(**kw) -> ScenarioSpec:
    base = dict(name="t", workload="mnist",
                gate=Gate(max_final_cost=2.5, min_goodput=0.01,
                          min_examples_per_s=1.0))
    base.update(kw)
    return ScenarioSpec(**base)


class TestSpec:
    def test_json_round_trip(self):
        spec = tiny_spec(name="rt", workload="gpt", chaos="preempt@every:9",
                         steps=12, grad_sync="zero1",
                         extra=(("seq_len", 16),),
                         gate=Gate(max_final_cost=5.0, min_goodput=0.1,
                                   min_tokens_per_s=10.0, max_rollbacks=2))
        again = ScenarioSpec.from_json(spec.to_json())
        assert again == spec
        assert again.extra_dict == {"seq_len": 16}
        # the doc is plain JSON — what <out>/<name>.json embeds
        doc = json.loads(spec.to_json())
        assert doc["gate"]["max_final_cost"] == 5.0

    def test_unknown_workload_rejected(self):
        with pytest.raises(ValueError, match="unknown workload"):
            tiny_spec(workload="resnet152")

    def test_bad_chaos_rejected_at_load_time_with_cell_name(self):
        """A typo'd fault fails when the matrix loads — through the REAL
        FaultPlan grammar — with the cell named."""
        with pytest.raises(ValueError, match="'bad_cell'.*bad chaos"):
            tiny_spec(name="bad_cell", chaos="sigquit@7")

    def test_elastic_without_host_down_rejected(self):
        with pytest.raises(ValueError, match="host_down"):
            tiny_spec(hosts=2, chaos="nan_grad@3")

    def test_gate_thresholds_arm_only_set_floors(self):
        """Gate -> check_gates kwargs: convergence + goodput always armed,
        throughput/MFU/rollbacks only when set — the exact contract the
        runner feeds report.check_gates."""
        g = Gate(max_final_cost=1.0, min_goodput=0.2)
        assert g.thresholds() == {"max_final_cost": 1.0,
                                  "min_goodput": 0.2}
        g = Gate(max_final_cost=1.0, min_goodput=0.2, min_mfu_pct=30.0,
                 min_tokens_per_s=5.0, max_rollbacks=0)
        assert g.thresholds() == {"max_final_cost": 1.0,
                                  "min_goodput": 0.2, "min_mfu": 30.0,
                                  "min_tokens_per_s": 5.0,
                                  "max_rollbacks": 0}


class TestMatrices:
    def test_default_matrix_covers_the_contract(self):
        """ISSUE-8 shape: >= 6 cells, >= 4 workloads, chaos-off baselines
        AND host_down/straggler/recurring-preemption/nan+corrupt plans,
        at least one elastic (shrunken-mesh) cell, one zero1 cell."""
        cells = default_matrix()
        assert len(cells) >= 6
        assert len({c.workload for c in cells}) >= 4
        assert len({c.name for c in cells}) == len(cells)
        chaos = ",".join(c.chaos or "" for c in cells)
        assert any(c.chaos is None for c in cells)
        for kind in ("host_down", "slow_host", "preempt@every",
                     "nan_grad", "corrupt_ckpt", "ckpt_stall"):
            assert kind in chaos, f"no cell injects {kind}"
        elastic = [c for c in cells if c.hosts > 1]
        assert elastic and all(0 < c.shrink_devices < c.devices
                               for c in elastic)
        assert any(c.grad_sync == "zero1" for c in cells)
        # the serving cell (ISSUE 10): chaos'd load run gated on
        # goodput-QPS + p99 TTFT like training cells gate on loss
        serve = [c for c in cells if c.workload == "serve"]
        assert serve, "no serving cell in the default matrix"
        for kind in ("slow_decode", "client_drop", "kv_poison"):
            assert kind in (serve[0].chaos or ""), kind
        assert serve[0].gate.min_goodput_qps > 0
        assert serve[0].gate.max_ttft_p99_ms > 0
        assert serve[0].gate.max_final_cost is None

    def test_default_matrix_chaos_parses_for_every_host(self):
        """Host-targeted faults must parse under every process index the
        cell will spawn (the _host child parses with its own task id)."""
        from dtf_tpu.resilience.chaos import FaultPlan
        for c in default_matrix():
            if not c.chaos:
                continue
            for task in range(c.hosts):
                FaultPlan.parse(c.chaos, process_index=task)

    def test_int8_ring_cell_contract(self):
        """ISSUE 19: the pod-gradient cell plans itself (--plan auto),
        pins the EQuARX ring wire, arms the wire-bytes ceiling, and
        round-trips through JSON with the new spec fields."""
        cell = {c.name: c for c in
                default_matrix()}["mnist_zero1_int8_ring"]
        assert cell.plan == "auto"
        assert cell.grad_comm_dtype == "int8_ring"
        assert cell.devices == 8
        assert "preempt" in cell.chaos
        th = cell.gate.thresholds()
        assert (th["max_wire_bytes_per_step"]
                == cell.gate.max_wire_bytes_per_step > 0)
        assert ScenarioSpec.from_json(cell.to_json()) == cell
        # an unarmed gate stays out of the kwargs (old cells unchanged)
        assert "max_wire_bytes_per_step" not in Gate(
            max_final_cost=1.0, min_goodput=0.1).thresholds()

    def test_mini_matrix_is_the_lane_pair(self):
        names = [c.name for c in mini_matrix()]
        assert names == ["gpt_baseline", "mnist_host_down_elastic"]
        by_name = {c.name: c for c in default_matrix()}
        assert all(by_name[n] == c for n, c in
                   zip(names, mini_matrix()))

    def test_load_matrix_builtin_and_file(self, tmp_path):
        assert load_matrix("mini") == mini_matrix()
        path = tmp_path / "m.json"
        docs = [json.loads(c.to_json()) for c in mini_matrix()]
        path.write_text(json.dumps(docs))
        assert load_matrix(str(path)) == mini_matrix()

    def test_load_matrix_rejects_duplicates_and_non_lists(self, tmp_path):
        dup = tmp_path / "dup.json"
        doc = json.loads(tiny_spec().to_json())
        dup.write_text(json.dumps([doc, doc]))
        with pytest.raises(ValueError, match="duplicate"):
            load_matrix(str(dup))
        empty = tmp_path / "empty.json"
        empty.write_text("[]")
        with pytest.raises(ValueError, match="non-empty"):
            load_matrix(str(empty))

    def test_matrices_registry(self):
        assert set(MATRICES) >= {"default", "mini"}


class TestZoo:
    def test_builders_in_sync_with_spec_workloads(self):
        """spec.TRAIN_WORKLOADS (jax-free) mirrors zoo.BUILDERS
        (jax-heavy); this is the pinned sync the spec docstring
        promises.  The serve cell kind rides WORKLOADS but never goes
        through the zoo (scenarios/_host.py drives the engine)."""
        from dtf_tpu.scenarios import zoo
        assert tuple(sorted(zoo.BUILDERS)) == tuple(sorted(TRAIN_WORKLOADS))
        assert set(WORKLOADS) == set(TRAIN_WORKLOADS) | {"serve"}

    @pytest.mark.parametrize("workload", TRAIN_WORKLOADS)
    def test_kits_build_and_data_streams_rewind(self, workload):
        """Every builder yields a model + fresh optimizer per call + a
        splits_factory whose streams REWIND (restart attempts replay the
        same data — the convergence gate depends on it)."""
        import numpy as np

        from dtf_tpu.scenarios import zoo
        kit = zoo.build(tiny_spec(workload=workload, batch_size=8,
                                  steps=4))
        assert kit.make_optimizer() is not kit.make_optimizer()
        a = kit.splits_factory().train.next_batch(8)
        b = kit.splits_factory().train.next_batch(8)
        for la, lb in zip(*[list(x.values()) if isinstance(x, dict)
                            else list(x) for x in (a, b)]):
            np.testing.assert_array_equal(la, lb)


class TestRunnerPieces:
    def test_cell_result_doc_is_json(self):
        from dtf_tpu.scenarios.runner import CellResult
        res = CellResult(tiny_spec(), True,
                         ["gate min_goodput: OK — 0.5 >= 0.2"],
                         {"final_cost": 1.0}, 2.5, logdir="/tmp/x")
        doc = res.to_doc()
        assert json.loads(json.dumps(doc))["ok"] is True
        assert doc["spec"]["name"] == "t"

    def test_summary_table_renders_missing_measurements(self):
        from dtf_tpu.scenarios.__main__ import summary_table
        from dtf_tpu.scenarios.runner import CellResult
        table = summary_table([
            CellResult(tiny_spec(), False, [], {}, 1.0,
                       error="host exited 1")])
        assert "FAIL" in table and "0/1 cells passed" in table

    def test_child_env_strips_sitecustomize_and_forces_cpu(self, tmp_path):
        from dtf_tpu.scenarios.runner import child_env
        shim = tmp_path / "shim"
        shim.mkdir()
        (shim / "sitecustomize.py").write_text("")
        old = os.environ.get("PYTHONPATH")
        os.environ["PYTHONPATH"] = str(shim)
        try:
            env = child_env()
        finally:
            if old is None:
                os.environ.pop("PYTHONPATH", None)
            else:
                os.environ["PYTHONPATH"] = old
        assert env["JAX_PLATFORMS"] == "cpu"
        assert str(shim) not in env["PYTHONPATH"]


class TestCLI:
    def test_list_and_bad_inputs(self, capsys):
        from dtf_tpu.scenarios.__main__ import main
        assert main(["--matrix", "mini", "--list"]) == 0
        out = capsys.readouterr().out
        assert "gpt_baseline" in out and "mnist_host_down_elastic" in out
        assert main(["--matrix", "/nonexistent/m.json"]) == 2
        assert main(["--matrix", "mini", "--only", "nope"]) == 2


@pytest.mark.slow
class TestEndToEnd:
    """One real supervised cell through the child-process runner: the
    fault fires, the supervisor restarts, the triple gate reads the
    books the run left on disk.  (The elastic shape is covered by
    tests/test_multiprocess.py's zero1-transformer pair and the
    full-suite scenario lane.)"""

    def _cell(self):
        return tiny_spec(
            name="e2e_mnist_preempt", workload="mnist", devices=2,
            steps=16, batch_size=64, learning_rate=5e-2, optimizer="sgd",
            checkpoint_every=4, chaos="preempt@9", max_restarts=1,
            gate=Gate(max_final_cost=2.5, min_goodput=0.005,
                      min_examples_per_s=10.0, max_rollbacks=0))

    def test_run_cell_passes_triple_gate_despite_preemption(self, tmp_path):
        from dtf_tpu.scenarios.runner import run_cell
        res = run_cell(self._cell(), str(tmp_path))
        assert res.ok, (res.error, res.gates)
        assert res.measured["steps"] == 16
        assert res.measured["restarts"] == 1      # the preempt fired
        assert res.measured["faults_fired"] == 1
        # every armed gate produced a verdict line, all OK
        assert len(res.gates) == 5 and all("OK" in g for g in res.gates)
        # recovery is OBSERVABLE: books survived the restart
        assert os.path.isfile(os.path.join(res.logdir, "telemetry.json"))

    def test_cli_check_emits_json_and_summary(self, tmp_path):
        from dtf_tpu.scenarios.runner import REPO_ROOT, child_env
        matrix = tmp_path / "m.json"
        matrix.write_text(json.dumps(
            [json.loads(self._cell().to_json())]))
        out = tmp_path / "results"
        proc = subprocess.run(
            [sys.executable, "-m", "dtf_tpu.scenarios",
             "--matrix", str(matrix), "--out", str(out), "--check"],
            cwd=REPO_ROOT, env=child_env(), text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=420)
        assert proc.returncode == 0, proc.stdout[-3000:]
        assert "scenario check: OK" in proc.stdout
        doc = json.loads((out / "e2e_mnist_preempt.json").read_text())
        assert doc["ok"] and doc["spec"]["chaos"] == "preempt@9"
        assert (out / "summary.txt").read_text().strip()

    def test_failing_gate_fails_the_check(self, tmp_path):
        """An absurd convergence target must FAIL the cell and the CLI
        exit code — the gate is falsifiable, not decorative."""
        from dtf_tpu.scenarios.runner import run_cell
        spec = self._cell()
        bad = ScenarioSpec(**{**{f.name: getattr(spec, f.name)
                                 for f in spec.__dataclass_fields__.values()},
                              "name": "e2e_impossible",
                              "gate": Gate(max_final_cost=1e-9,
                                           min_goodput=0.005)})
        res = run_cell(bad, str(tmp_path))
        assert not res.ok
        assert any("max_final_cost" in g and "FAIL" in g
                   for g in res.gates)
