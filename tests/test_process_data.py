"""Per-process data sharding (put_process_batch + Dataset.shard): each
host feeds only its own slice — single-process equivalence, disjoint
partitioning, and a 2-process run whose loss matches the single-process
full-batch loss exactly."""

import os
import re
import sys

import jax
import numpy as np
import pytest

from dtf_tpu.data.datasets import Dataset
from dtf_tpu.train.trainer import put_global_batch, put_process_batch

from tests.test_multiprocess import REPO_ROOT, free_port, run_workers


class TestSingleProcess:
    def test_matches_put_global_batch(self, mesh8):
        x = np.random.default_rng(0).random((16, 12), np.float32)
        a = put_global_batch(mesh8, x)
        b = put_process_batch(mesh8, x)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert b.sharding.spec == a.sharding.spec

    def test_scalar_replicated(self, mesh8):
        out = put_process_batch(mesh8, np.float32(3.5))
        assert float(out) == 3.5


class TestDatasetShard:
    def test_disjoint_equal_cover(self):
        n = 103
        imgs = np.arange(n, dtype=np.float32)[:, None]
        labels = np.eye(2, dtype=np.float32)[np.arange(n) % 2]
        ds = Dataset(imgs, labels, seed=1)
        shards = [ds.shard(k, 4) for k in range(4)]
        sizes = [s.num_examples for s in shards]
        assert sizes == [25, 25, 25, 25]        # 103 -> 100, equal shards
        seen = np.concatenate([s.images[:, 0] for s in shards])
        assert len(set(seen.tolist())) == 100   # disjoint
        # different shuffle streams per shard
        a = shards[0].next_batch(8)[0][:, 0].tolist()
        b = shards[1].next_batch(8)[0][:, 0].tolist()
        assert a != b


class TestProcessShard:
    """process_shard: contiguous slices of the SAME shuffle stream, so the
    union of all hosts' slices at step i IS the global batch at step i
    (bitwise-identical trajectory to put_global_batch)."""

    def _mk(self, seed=3):
        n = 64
        imgs = np.arange(n, dtype=np.float32)[:, None]
        labels = np.eye(2, dtype=np.float32)[np.arange(n) % 2]
        return Dataset(imgs, labels, seed=seed)

    def test_slices_reassemble_global_batches(self):
        ds_global = self._mk()
        views = [self._mk().process_shard(k, 2) for k in range(2)]
        for _ in range(10):   # crosses an epoch reshuffle at 64/16
            gx, gy = ds_global.next_batch(16)
            parts = [v.next_batch(8) for v in views]
            np.testing.assert_array_equal(
                np.concatenate([p[0] for p in parts]), gx)
            np.testing.assert_array_equal(
                np.concatenate([p[1] for p in parts]), gy)

    def test_fast_forward_stays_aligned(self):
        ds_global = self._mk()
        view = self._mk().process_shard(1, 2)
        for _ in range(3):
            ds_global.next_batch(16)
        view.fast_forward(3, 8)
        gx, _ = ds_global.next_batch(16)
        vx, _ = view.next_batch(8)
        np.testing.assert_array_equal(vx, gx[8:])

    def test_token_dataset_shards_too(self):
        from dtf_tpu.data.datasets import TokenDataset
        toks = np.arange(32 * 4, dtype=np.int32).reshape(32, 4)
        g = TokenDataset(toks, seed=5)
        views = [TokenDataset(toks, seed=5).process_shard(k, 2)
                 for k in range(2)]
        gb = g.next_batch(8)["tokens"]
        parts = [v.next_batch(4)["tokens"] for v in views]
        np.testing.assert_array_equal(np.concatenate(parts), gb)


class TestValidation:
    def test_oversized_batch_rejected(self):
        """batch_size > num_examples raises up front instead of silently
        truncating into a later divisibility error (ADVICE r2)."""
        imgs = np.zeros((8, 3), np.float32)
        labels = np.eye(2, dtype=np.float32)[np.zeros(8, np.int64)]
        ds = Dataset(imgs, labels, seed=1)
        with pytest.raises(ValueError, match="exceeds"):
            ds.next_batch(16)
        with pytest.raises(ValueError, match="exceeds"):
            ds.fast_forward(2, 16)

    def test_process_shard_examples_is_train_only(self):
        imgs = np.zeros((8, 3), np.float32)
        labels = np.eye(2, dtype=np.float32)[np.zeros(8, np.int64)]
        view = Dataset(imgs, labels, seed=1).process_shard(0, 2)
        with pytest.raises(NotImplementedError):
            view.examples(0, 4)


@pytest.mark.slow
class TestTwoProcess:
    def test_loss_equals_full_batch(self, mesh8):
        """2 processes each feeding HALF the global batch must produce the
        same first-step loss as one process feeding all of it."""
        # single-process reference on the same deterministic global batch
        from dtf_tpu import optim
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import init_state, make_train_step

        rng = np.random.default_rng(42)
        gx = rng.random((32, 784), np.float32)
        gy = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 32)]
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        state = init_state(model, opt, seed=1, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                               donate=False)
        _, m = step(state, put_global_batch(mesh8, (gx, gy)),
                    jax.random.key(0))
        ref = float(m["loss"])

        port = free_port()
        script = os.path.join(REPO_ROOT, "tests", "_mp_process_data.py")
        outs = run_workers(
            [[sys.executable, script, str(task), f"localhost:{port}"]
             for task in range(2)],
            n_local_devices=4, timeout=300)
        losses = [float(re.findall(r"LOSS=([0-9.]+)", out)[0])
                  for out in outs]
        assert losses[0] == losses[1]                       # SPMD agree
        assert losses[0] == pytest.approx(ref, abs=1e-5)    # == full batch
